"""ReplicaNode: one server's membership in the replication mesh.

Composes the peer table (health), lease manager (ownership), and
anti-entropy loop (convergence) around a DocStore, and implements the
two protocols the HTTP tier delegates to it:

  * mutation routing — `route_mutation(doc_id)` names the host that
    should apply a write (current lease holder when known and healthy,
    rendezvous owner otherwise); `proxy()` forwards the raw request
    body there. When the target is unreachable the server falls back
    to accepting locally (availability over placement — the edit lands
    in the local oplog, anti-entropy reconciles it later, and the
    merge gate keeps device work off this host);

  * handoff — `handoff(doc_id, new_owner)` drives the sender side of
    the lease state machine (see ownership.py):
    grant → drain pending merges → final patch transfer → activate.

`maintain()` is the periodic control step (piggybacked on the probe
loop): renew held leases and hand off docs whose rendezvous owner moved
(peer recovered, health view changed).
"""

from __future__ import annotations

import threading
import time
import urllib.error
from typing import List, Optional, Set, Tuple

from ..causalgraph.summary import intersect_with_summary
from ..encoding.encode import ENCODE_PATCH, encode_oplog
from .antientropy import AntiEntropy
from .faults import FaultInjector
from .metrics import ReplicationMetrics
from .ownership import DRAINING, TRANSFER, LeaseManager, owner_of
from .peers import PeerTable

MUTATION_ACTIONS = ("push", "edit", "ops")


class ReplicaNode:
    def __init__(self, store, self_id: str, peer_addrs: List[str],
                 seed: int = 0, lease_ttl_s: float = 2.0,
                 probe_interval_s: float = 0.5,
                 antientropy_interval_s: float = 0.5,
                 timeout_s: float = 2.0, fail_threshold: int = 3,
                 backoff_base_s: float = 0.1,
                 backoff_cap_s: float = 5.0,
                 takeover_after_s: Optional[float] = None,
                 faults: Optional[FaultInjector] = None) -> None:
        self.store = store
        self.self_id = self_id
        self.started_at = time.monotonic()
        # how long a peer must stay continuously down before ownership
        # reassigns its docs; defaults to the lease TTL so a takeover
        # can only happen after the old holder's lease has expired
        self.takeover_after_s = (lease_ttl_s if takeover_after_s is None
                                 else takeover_after_s)
        self.metrics = ReplicationMetrics(self_id)
        self.faults = faults
        self.table = PeerTable(self_id, peer_addrs, timeout_s=timeout_s,
                               fail_threshold=fail_threshold, seed=seed,
                               backoff_base_s=backoff_base_s,
                               backoff_cap_s=backoff_cap_s,
                               faults=faults, metrics=self.metrics)
        self.leases = LeaseManager(self_id, ttl_s=lease_ttl_s,
                                   metrics=self.metrics)
        self.antientropy = AntiEntropy(
            self, interval_s=antientropy_interval_s)
        self.probe_interval_s = probe_interval_s
        # docs whose merges this host has admitted — the test surface
        # for the exactly-one-merger property
        self.merged_docs: Set[str] = set()
        self._maintain_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- ownership -------------------------------------------------------

    def ownership_ids(self) -> List[str]:
        """Hosts rendezvous ownership is computed over: self plus every
        peer that is healthy OR has been down for less than
        `takeover_after_s`. The delay means a short partition does not
        collapse each side's host set to itself — both sides keep
        computing the same owner, so exactly one host admits merges.
        Only an outage longer than a lease TTL (holder's lease provably
        expired) reassigns ownership."""
        now = time.monotonic()
        ids = [self.self_id]
        for p in self.table.peer_ids():
            d = self.table.down_duration(p, now)
            if d is None or d < self.takeover_after_s:
                ids.append(p)
        return sorted(ids)

    def desired_owner(self, doc_id: str) -> str:
        return owner_of(doc_id, self.ownership_ids())

    def owns(self, doc_id: str) -> bool:
        """The scheduler's merge-admission gate: True iff this host
        holds (or may now acquire) the doc's ACTIVE lease."""
        ok = self.leases.ensure_local(
            doc_id, self.desired_owner(doc_id) == self.self_id)
        self.metrics.bump("merge_gate", "admits" if ok else "denials")
        if ok:
            self.merged_docs.add(doc_id)
        return ok

    def route_mutation(self, doc_id: str) -> str:
        """The host a write for `doc_id` should land on."""
        holder = self.leases.holder_of(doc_id)
        if holder is not None and (holder == self.self_id
                                   or self.table.is_healthy(holder)):
            return holder
        return self.desired_owner(doc_id)

    # ---- proxy -----------------------------------------------------------

    def proxy(self, target: str, path: str,
              body: bytes) -> Optional[Tuple[int, bytes]]:
        """Forward a mutation to its owner. Returns (status, body) to
        relay, or None when the owner is unreachable — the caller then
        accepts locally (and anti-entropy reconciles)."""
        try:
            status, resp = self.table.call(
                target, path, data=body,
                headers={"X-DT-Proxied": "1"})
        except urllib.error.HTTPError as e:
            # owner answered with an application error: relay verbatim
            status, resp = e.code, e.read()
        except OSError:
            self.metrics.bump("proxy", "fallback_local")
            return None
        self.metrics.bump("proxy", "proxied")
        return status, resp

    # ---- handoff (sender) ------------------------------------------------

    def handoff(self, doc_id: str, new_owner: str) -> bool:
        """Move doc ownership to `new_owner` without ever having two
        active mergers: grant → drain → final patch → activate. Any
        failure aborts back to ACTIVE (the remote GRANTED lease simply
        expires)."""
        t0 = time.monotonic()
        new_epoch = self.leases.begin_handoff(doc_id)
        if new_epoch is None:
            return False
        self.metrics.bump("handoffs", "started")
        try:
            # grant: the receiver records a not-yet-active lease (its
            # TTL covers the whole handoff, so a crashed sender leaves
            # a lease that expires rather than a stuck doc)
            resp = self.table.call_json(
                new_owner, "/replicate/lease",
                {"action": "grant", "doc": doc_id, "epoch": new_epoch,
                 "ttl_s": self.leases.ttl_s * 4})
            if not resp.get("ok"):
                raise ValueError(f"grant refused: {resp!r}")
            # drain: flush our pending merge work for the doc so the
            # final patch includes every admitted op
            self.leases.advance_handoff(doc_id, DRAINING)
            sched = getattr(self.store, "scheduler", None)
            if sched is not None:
                sched.drain()
            # final patch transfer (from the receiver's common version)
            self.leases.advance_handoff(doc_id, TRANSFER)
            remote_summary = self.table.call_json(
                new_owner, f"/doc/{doc_id}/summary")
            ol = self.store.get(doc_id)
            with self.store.lock:
                common, _rem = intersect_with_summary(ol.cg,
                                                      remote_summary)
                patch = None
                if sorted(common) != sorted(ol.version):
                    patch = encode_oplog(ol, ENCODE_PATCH,
                                         from_version=common)
            if patch is not None:
                self.table.call(new_owner, f"/doc/{doc_id}/push",
                                data=patch)
            # activate: receiver flips GRANTED -> ACTIVE; we release
            resp = self.table.call_json(
                new_owner, "/replicate/lease",
                {"action": "activate", "doc": doc_id,
                 "epoch": new_epoch})
            if not resp.get("ok"):
                raise ValueError(f"activate refused: {resp!r}")
            self.leases.finish_handoff(doc_id, new_owner, new_epoch)
            self.metrics.bump("handoffs", "completed")
            self.metrics.observe_handoff_latency(time.monotonic() - t0)
            return True
        except (OSError, ValueError, KeyError,
                urllib.error.HTTPError):
            self.leases.abort_handoff(doc_id)
            self.metrics.bump("handoffs", "failed")
            return False

    # ---- lease wire handler (receiver) -----------------------------------

    def handle_lease_message(self, req: dict) -> dict:
        action = req.get("action")
        doc_id = req.get("doc")
        if not isinstance(doc_id, str) or not doc_id:
            return {"ok": False, "error": "bad doc"}
        epoch = int(req.get("epoch", 0))
        if action == "grant":
            ok = self.leases.accept_grant(
                doc_id, epoch, float(req.get("ttl_s", 0.0)))
            return {"ok": ok}
        if action == "activate":
            ok = self.leases.activate_grant(doc_id, epoch)
            return {"ok": ok}
        if action == "status":
            lease = self.leases.get(doc_id)
            return {"ok": True,
                    "lease": lease.as_json() if lease else None,
                    "desired": self.desired_owner(doc_id)}
        return {"ok": False, "error": f"bad action {action!r}"}

    # ---- periodic control ------------------------------------------------

    def maintain(self) -> dict:
        """Renew held leases; hand off docs whose rendezvous owner
        moved to a healthy peer. Serialized (probe loop + manual test
        calls must not race two handoffs for one doc)."""
        out = {"renewed": 0, "handoffs": 0}
        with self._maintain_lock:
            for doc_id in self.leases.held_ids():
                desired = self.desired_owner(doc_id)
                if desired == self.self_id:
                    self.leases.ensure_local(doc_id, True)
                    out["renewed"] += 1
                elif self.table.is_healthy(desired):
                    if self.handoff(doc_id, desired):
                        out["handoffs"] += 1
        return out

    # ---- docs listing (for anti-entropy peers) ---------------------------

    def docs_json(self) -> dict:
        now = time.monotonic()
        docs = {}
        with self.leases.lock:
            for doc_id in self.store.doc_ids():
                lease = self.leases.leases.get(doc_id)
                docs[doc_id] = {
                    "lease": lease.as_json(now) if lease is not None
                    and not lease.expired(now) else None}
        return {"docs": docs, "self": self.self_id}

    # ---- metrics ---------------------------------------------------------

    def metrics_json(self) -> dict:
        return self.metrics.snapshot(
            leases_held=self.leases.held_count(),
            per_peer=self.table.states(),
            faults=self.faults.snapshot()
            if self.faults is not None else None)

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Probe + maintain loop and the anti-entropy loop."""
        self.antientropy.start()
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.probe_interval_s):
                try:
                    self.table.probe_once()
                    self.maintain()
                except Exception:   # pragma: no cover - keep running
                    pass

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.antientropy.stop()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self._stop = threading.Event()
        self.table.stop_probe_loop()


def attach_replication(httpd, self_id: str, peer_addrs: List[str],
                       **opts) -> ReplicaNode:
    """Wire a ReplicaNode onto a running server (tools/server.serve):
    the store gains `.replica`, and the merge scheduler (when present)
    gets the ownership admit gate. Split from serve() because tests
    bind port 0 first and only then know their own `host:port`
    identity."""
    store = httpd.store
    node = ReplicaNode(store, self_id, peer_addrs, **opts)
    store.replica = node
    if getattr(store, "scheduler", None) is not None:
        store.scheduler.admit = node.owns
    return node
