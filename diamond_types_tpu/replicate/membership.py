"""Dynamic mesh membership: join/leave/suspect/dead with incarnations.

PR 2's mesh was a static `--peers host:port,...` list — a restarted or
added host silently fell out of the rendezvous universe. This module
replaces that with an explicit membership view driven by two evidence
sources:

  * local health — the PeerTable probe loop's `down_duration` maps to
    ALIVE (reachable), SUSPECT (down, but for less than the takeover
    delay) and DEAD (down past it). SUSPECT members stay in the
    rendezvous universe, so a short partition never collapses each
    side's host set to itself — exactly the semantics the old
    `ownership_ids()` delay encoded, now as named states;
  * gossip — ping responses piggyback the responder's member table.
    Entries with a HIGHER incarnation always win; at equal incarnation
    local probe evidence wins (a node I can reach is not dead no matter
    who says so). A node that hears itself called SUSPECT/DEAD at its
    own incarnation refutes by bumping its incarnation (SWIM's
    refutation rule), and the bumped number spreads the same way.

Incarnations are persisted (quorum.ReplicaJournal) and bumped on every
restart, so a recovered node's refutation is never mistaken for a stale
echo of its previous life.

Two derived sets drive everything else:

  * `universe()` — ALIVE + SUSPECT (+ always self): the host set
    `owner_of` rendezvous-hashes over. Deterministic lease migration on
    view changes falls out of rendezvous placement being a pure
    function of this set.
  * `voters()` — every member not LEFT (DEAD included): the quorum
    denominator. Counting DEAD members keeps the denominator from
    shrinking under partition — a minority side can never reach
    majority by declaring the other side dead. Shrinking the voter set
    requires an explicit, operator-driven `leave`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from .metrics import ReplicationMetrics

JOINING = "joining"   # announced via /replicate/join, not yet probed ok
ALIVE = "alive"
SUSPECT = "suspect"   # unreachable for < dead_after_s; still in universe
DEAD = "dead"         # unreachable past dead_after_s; out of universe
LEFT = "left"         # explicit leave; out of universe AND voters

_UNIVERSE_STATES = (JOINING, ALIVE, SUSPECT)


class Member:
    __slots__ = ("member_id", "state", "incarnation", "since")

    def __init__(self, member_id: str, state: str,
                 incarnation: int = 0) -> None:
        self.member_id = member_id
        self.state = state
        self.incarnation = incarnation
        self.since = time.monotonic()

    def as_json(self) -> dict:
        return {"state": self.state, "incarnation": self.incarnation,
                "since_s": round(time.monotonic() - self.since, 3)}


class MembershipView:
    """Thread-safe membership table. `view_version` bumps on every
    state transition so scrapers (and tests) can detect view churn."""

    def __init__(self, self_id: str, incarnation: int = 1,
                 metrics: Optional[ReplicationMetrics] = None) -> None:
        self.self_id = self_id
        self.metrics = metrics
        from ..analysis.witness import make_lock
        self._lock = make_lock("repl.membership", "repl.membership")
        self.members: Dict[str, Member] = {
            self_id: Member(self_id, ALIVE, incarnation)}
        self.view_version = 1

    def _bump(self, key: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.bump("membership", key, n)

    def _set_state(self, m: Member, state: str) -> bool:
        if m.state == state:
            return False
        m.state = state
        m.since = time.monotonic()
        self.view_version += 1
        return True

    # ---- views -----------------------------------------------------------

    @property
    def self_incarnation(self) -> int:
        with self._lock:
            return self.members[self.self_id].incarnation

    def state_of(self, member_id: str) -> Optional[str]:
        with self._lock:
            m = self.members.get(member_id)
            return m.state if m is not None else None

    def universe(self) -> List[str]:
        """Host ids rendezvous ownership is computed over. Self is
        always included (a node always owns the docs that hash to it,
        regardless of what gossip claims about it)."""
        with self._lock:
            ids = {m.member_id for m in self.members.values()
                   if m.state in _UNIVERSE_STATES}
            ids.add(self.self_id)
            return sorted(ids)

    def voters(self) -> List[str]:
        """The quorum denominator: every member that has not
        explicitly LEFT (DEAD members still count — see module doc)."""
        with self._lock:
            return sorted(m.member_id for m in self.members.values()
                          if m.state != LEFT)

    def quorum_size(self) -> int:
        return len(self.voters()) // 2 + 1

    # ---- explicit membership changes -------------------------------------

    def add(self, member_id: str, state: str = JOINING,
            incarnation: int = 0) -> bool:
        """Register a member (join announcement or bootstrap peer).
        Re-adding a LEFT/DEAD member with a newer incarnation revives
        it (a restarted host re-joins under a bumped incarnation)."""
        with self._lock:
            m = self.members.get(member_id)
            if m is None:
                self.members[member_id] = Member(member_id, state,
                                                 incarnation)
                self.view_version += 1
                self._bump("joins")
                return True
            if incarnation > m.incarnation:
                m.incarnation = incarnation
                changed = self._set_state(m, state)
                if changed:
                    self._bump("joins")
                return changed
            return False

    def leave(self, member_id: str) -> bool:
        """Explicit leave: out of the universe AND the voter set."""
        with self._lock:
            m = self.members.get(member_id)
            if m is None or m.state == LEFT:
                return False
            self._set_state(m, LEFT)
            self._bump("leaves")
            return True

    # ---- local health evidence -------------------------------------------

    def note_health(self, member_id: str, down_s: Optional[float],
                    dead_after_s: float) -> bool:
        """Fold one probe-loop observation: `down_s` is
        PeerTable.down_duration (None = reachable). Local evidence
        moves state without touching the incarnation — incarnations
        arbitrate GOSSIP, not direct observation."""
        with self._lock:
            m = self.members.get(member_id)
            if m is None or m.state == LEFT:
                return False
            if down_s is None:
                return self._set_state(m, ALIVE)
            if down_s >= dead_after_s:
                changed = self._set_state(m, DEAD)
                if changed:
                    self._bump("deaths")
                return changed
            changed = self._set_state(m, SUSPECT)
            if changed:
                self._bump("suspicions")
            return changed

    # ---- gossip ----------------------------------------------------------

    def merge_remote(self, entries: Dict[str, dict]) -> bool:
        """Fold a peer's member table (ping piggyback). Returns True
        when the view changed. Rules: higher incarnation wins; at equal
        incarnation local state stands (probe evidence beats hearsay);
        unknown ids are added (this is how a join spreads without a
        broadcast). Hearing ourselves called SUSPECT/DEAD at our own
        incarnation (or newer) is refuted by bumping our incarnation."""
        changed = False
        with self._lock:
            for mid, info in entries.items():
                try:
                    state = str(info["state"])
                    inc = int(info["incarnation"])
                except (KeyError, TypeError, ValueError):
                    continue
                if state not in (JOINING, ALIVE, SUSPECT, DEAD, LEFT):
                    continue
                if mid == self.self_id:
                    me = self.members[self.self_id]
                    if state in (SUSPECT, DEAD) \
                            and inc >= me.incarnation:
                        me.incarnation = inc + 1
                        self.view_version += 1
                        self._bump("refutations")
                        changed = True
                    continue
                m = self.members.get(mid)
                if m is None:
                    self.members[mid] = Member(mid, state, inc)
                    self.view_version += 1
                    self._bump("joins")
                    changed = True
                    continue
                if inc > m.incarnation:
                    m.incarnation = inc
                    changed |= self._set_state(m, state)
                elif inc == m.incarnation and state == LEFT \
                        and m.state != LEFT:
                    # LEFT is operator-driven and terminal at its
                    # incarnation: it must spread even without an
                    # incarnation bump
                    self._set_state(m, LEFT)
                    self._bump("leaves")
                    changed = True
        return changed

    # ---- export ----------------------------------------------------------

    def as_json(self) -> dict:
        with self._lock:
            return {"view_version": self.view_version,
                    "members": {mid: m.as_json()
                                for mid, m in
                                sorted(self.members.items())}}

    def gossip_payload(self) -> Dict[str, dict]:
        """The compact member table piggybacked on ping responses."""
        with self._lock:
            return {mid: {"state": m.state,
                          "incarnation": m.incarnation}
                    for mid, m in self.members.items()}
