"""In-process N-server replication soak (CLI: `replicate-soak`).

Boots N sync servers on ephemeral localhost ports, wires them into one
mesh sharing a single seeded FaultInjector, then drives rounds of
client edits at random servers while dropping, delaying and
partitioning the inter-server links. After the fault window every
partition heals and reconciliation rounds run until every server holds
byte-identical text for every doc (or the round budget runs out).

Stepping is inline and single-threaded on purpose — probes, lease
maintenance and anti-entropy advance once per round in a fixed order —
so a given seed replays the exact fault schedule (see faults.py's
determinism contract). The HTTP servers themselves still run real
threads; only the *replication control plane* is stepped.

Invariants checked:
  * convergence — all servers byte-identical on every doc;
  * owner-only merges — at any point in time one host admits a doc's
    merges; across the run a doc may legitimately appear in several
    hosts' merged sets (lease takeover after a partition), reported as
    `multi_merger_docs` and required to be 0 when no partition was
    configured.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.request
from typing import Dict, List

from .faults import FaultInjector
from .node import attach_replication

_WORDS = ("sync", "merge", "lease", "patch", "shard", "probe",
          "quorum", "epoch", "drain", "heal")


def run_replicate_soak(servers: int = 3, docs: int = 4, rounds: int = 20,
                       edits_per_round: int = 4, seed: int = 7,
                       drop_rate: float = 0.15, delay_rate: float = 0.0,
                       max_delay_s: float = 0.0, dup_rate: float = 0.05,
                       partition_rounds: int = 6,
                       reconcile_rounds: int = 12,
                       lease_ttl_s: float = 1.0,
                       serve_shards: int = 0,
                       progress: bool = False) -> dict:
    from ..tools.server import SyncClient, serve

    rng = random.Random(seed)
    faults = FaultInjector(seed=seed, drop_rate=drop_rate,
                           dup_rate=dup_rate, delay_rate=delay_rate,
                           max_delay_s=max_delay_s)
    httpds, nodes, addrs = [], [], []
    for _ in range(servers):
        httpd = serve(port=0, serve_shards=serve_shards)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    for i, httpd in enumerate(httpds):
        node = attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            seed=seed, lease_ttl_s=lease_ttl_s, faults=faults,
            timeout_s=2.0, backoff_base_s=0.02, backoff_cap_s=0.1)
        nodes.append(node)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

    doc_ids = [f"soak-{i}" for i in range(docs)]
    clients: Dict[tuple, SyncClient] = {}

    def client(server_i: int, doc_id: str) -> SyncClient:
        key = (server_i, doc_id)
        if key not in clients:
            clients[key] = SyncClient(
                f"http://{addrs[server_i]}", doc_id,
                f"agent-{server_i}-{doc_id}", retries=2)
        return clients[key]

    def step_control_plane() -> None:
        for node in nodes:
            node.table.probe_once()
            node.maintain()
        for node in nodes:
            node.antientropy.run_round()

    part_pair = (addrs[0], addrs[1]) if servers >= 2 \
        and partition_rounds > 0 else None
    t0 = time.monotonic()
    edits = 0
    for r in range(rounds):
        if part_pair and r == 1:
            faults.partition(*part_pair)
        if part_pair and r == 1 + partition_rounds:
            faults.heal(*part_pair)
        for _ in range(edits_per_round):
            si = rng.randrange(servers)
            doc = rng.choice(doc_ids)
            c = client(si, doc)
            try:
                c.pull()
            except OSError:
                pass    # client keeps editing its local replica
            pos = rng.randrange(len(c.text()) + 1)
            c.insert(pos, rng.choice(_WORDS) + " ")
            try:
                c.sync()
                edits += 1
            except OSError:
                pass    # retries exhausted mid-fault; next round
        step_control_plane()
        if progress:
            print(f"round {r + 1}/{rounds}: {edits} edits applied")

    # fault window over: heal everything and reconcile to convergence
    faults.heal()
    converged_after = None
    for r in range(reconcile_rounds):
        time.sleep(0.05)   # let breaker backoff windows lapse
        step_control_plane()
        if _converged(addrs, doc_ids):
            converged_after = r + 1
            break

    texts = _final_texts(addrs, doc_ids)
    converged = all(len(set(v.values())) == 1 for v in texts.values())
    mergers = {d: sorted(n.self_id for n in nodes
                         if d in n.merged_docs) for d in doc_ids}
    multi = sorted(d for d, who in mergers.items() if len(who) > 1)
    report = {
        "config": {"servers": servers, "docs": docs, "rounds": rounds,
                   "edits_per_round": edits_per_round, "seed": seed,
                   "drop_rate": drop_rate, "dup_rate": dup_rate,
                   "partition_rounds": partition_rounds,
                   "lease_ttl_s": lease_ttl_s,
                   "serve_shards": serve_shards},
        "edits_applied": edits,
        "converged": converged,
        "converged_after_reconcile_rounds": converged_after,
        "multi_merger_docs": multi,
        "mergers": mergers,
        "doc_lengths": {d: {a: len(t) for a, t in v.items()}
                        for d, v in texts.items()},
        "faults": faults.snapshot(),
        "wall_s": round(time.monotonic() - t0, 3),
        "metrics": {addrs[i]: nodes[i].metrics_json()
                    for i in range(servers)},
    }
    for httpd in httpds:
        httpd.shutdown()
        httpd.server_close()
    return report


def _get_text(addr: str, doc_id: str) -> str:
    with urllib.request.urlopen(f"http://{addr}/doc/{doc_id}",
                                timeout=5) as r:
        return r.read().decode("utf8")


def _final_texts(addrs: List[str],
                 doc_ids: List[str]) -> Dict[str, Dict[str, str]]:
    return {d: {a: _get_text(a, d) for a in addrs} for d in doc_ids}


def _converged(addrs: List[str], doc_ids: List[str]) -> bool:
    for d in doc_ids:
        if len({_get_text(a, d) for a in addrs}) > 1:
            return False
    return True


def main(argv=None) -> int:  # pragma: no cover - exercised via cli.py
    import argparse
    p = argparse.ArgumentParser(prog="replicate-soak")
    p.add_argument("--servers", type=int, default=3)
    p.add_argument("--docs", type=int, default=4)
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--drop-rate", type=float, default=0.15)
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    report = run_replicate_soak(servers=args.servers, docs=args.docs,
                                rounds=args.rounds, seed=args.seed,
                                drop_rate=args.drop_rate)
    print(json.dumps(report if args.json else {
        k: report[k] for k in ("converged", "edits_applied",
                               "multi_merger_docs", "wall_s")}))
    return 0 if report["converged"] else 1
