"""In-process N-server replication soak (CLI: `replicate-soak`).

Boots N sync servers on ephemeral localhost ports, wires them into one
mesh sharing a single seeded FaultInjector, then drives rounds of
client edits at random servers while dropping, delaying and
partitioning the inter-server links. After the fault window every
partition heals and reconciliation rounds run until every live server
holds byte-identical text for every doc (or the round budget runs out).

Chaos mode (the partition-safety PR's acceptance surface) layers on:

  * `asym`      — the partition window uses ONE-WAY cuts (a hears b,
                  b cannot reach a: the TTL-takeover killer), plus a
                  jittered slow link and clock-skew bookkeeping;
  * `crash`     — two nodes are crash-restarted mid-run: the process
                  is torn down WITHOUT closing its replica journal
                  (the WAL replays at reboot), restarted on the same
                  port + data dir, and must re-earn quorum through the
                  rejoining fence before merging again;
  * `churn`     — an extra node joins the mesh mid-run via
                  /replicate/join, then explicitly leaves.

Stepping is inline and single-threaded on purpose — probes, lease
maintenance and anti-entropy advance once per round in a fixed order —
so a given seed replays the exact fault schedule (see faults.py's
determinism contract). The HTTP servers themselves still run real
threads; only the *replication control plane* is stepped.

Invariants checked (report fields):
  * convergence — all live servers byte-identical on every doc;
  * zero split-brain — the detector scans EVERY node incarnation's
    activation history (live + crashed) for two ACTIVE holders sharing
    one (doc, epoch); `split_brain` must be empty. This is the quorum
    safety property, checked from the ground truth rather than
    asserted from the design;
  * owner-only merges — across the run a doc may legitimately appear
    in several hosts' merged sets (lease takeover after a partition /
    crash), reported as `multi_merger_docs` and required to be 0 when
    no partition, crash or churn was configured.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import urllib.request
from typing import Dict, List, Optional

from .faults import FaultInjector
from .node import attach_replication

_WORDS = ("sync", "merge", "lease", "patch", "shard", "probe",
          "quorum", "epoch", "drain", "heal")


def _split_brain(all_nodes) -> List[str]:
    """Scan every node incarnation's activation history for a
    (doc, epoch) that two DIFFERENT holders both activated — the
    at-most-one-ACTIVE-per-(doc, epoch) violation quorum forbids."""
    holders: Dict[tuple, set] = {}
    for n in all_nodes:
        for rec in n.leases.activation_history():
            holders.setdefault(
                (rec["doc"], rec["epoch"]), set()).add(rec["holder"])
    return sorted(f"{d}@e{e}" for (d, e), hs in holders.items()
                  if len(hs) > 1)


def run_replicate_soak(servers: int = 3, docs: int = 4, rounds: int = 20,
                       edits_per_round: int = 4, seed: int = 7,
                       drop_rate: float = 0.15, delay_rate: float = 0.0,
                       max_delay_s: float = 0.0, dup_rate: float = 0.05,
                       partition_rounds: int = 6,
                       reconcile_rounds: int = 12,
                       lease_ttl_s: float = 1.0,
                       serve_shards: int = 0,
                       crash: bool = False, asym: bool = False,
                       churn: bool = False,
                       witness: Optional[bool] = None,
                       data_dir: Optional[str] = None,
                       progress: bool = False) -> dict:
    from ..tools.server import SyncClient, serve

    # the lease machinery is exercised through the scheduler's admit
    # gate, so the chaos modes (whose whole point is quorum + fencing)
    # force at least one serve shard
    if (crash or asym or churn) and serve_shards == 0:
        serve_shards = 1
    # runtime lock witness: on by default for the chaos modes — those
    # are exactly the runs whose thread interleavings are worth mining
    # for lock-order edges (witness=False forces it off, True forces on)
    use_witness = witness if witness is not None else (crash or churn)
    if use_witness:
        from ..analysis import witness_enable, witness_reset
        witness_reset()
        witness_enable()
    rng = random.Random(seed)
    faults = FaultInjector(seed=seed, drop_rate=drop_rate,
                           dup_rate=dup_rate, delay_rate=delay_rate,
                           max_delay_s=max_delay_s)
    # crash-restart needs persistence (docs survive via .dt files, the
    # replica journal survives via the Wal); make dirs on demand
    if crash and data_dir is None:
        import tempfile
        data_dir = tempfile.mkdtemp(prefix="dt-soak-")
    dirs: List[Optional[str]] = []

    httpds: List = []
    nodes: List = []
    addrs: List[str] = []
    live: List[bool] = []
    dead_nodes: List = []    # crashed/left incarnations, kept for the
    #                          split-brain scan (their logs are evidence)
    node_opts = dict(seed=seed, lease_ttl_s=lease_ttl_s, faults=faults,
                     timeout_s=2.0, backoff_base_s=0.02,
                     backoff_cap_s=0.1)

    def _dir(i: int) -> Optional[str]:
        if data_dir is None:
            return None
        d = os.path.join(data_dir, f"n{i}")
        os.makedirs(d, exist_ok=True)
        return d

    def boot(i: int, port: int = 0, join_to: Optional[str] = None):
        """Boot (or reboot) server slot `i` and attach its replica."""
        # sample_rate=1.0: every soak edit gets a trace AND a journey.
        # follower_reads gives each owner a FollowerIndex, whose advert
        # hook closes journeys at advert_usable — without it the
        # verdict's convergence-lag column exists but never populates.
        httpd = serve(port=port, serve_shards=serve_shards,
                      data_dir=dirs[i], follower_reads=True,
                      obs_opts=dict(sample_rate=1.0))
        addr = f"127.0.0.1:{httpd.server_address[1]}"
        opts = dict(node_opts)
        if dirs[i] is not None:
            opts["journal_prefix"] = os.path.join(dirs[i], "_replica")
        peer_list = [a for j, a in enumerate(addrs) if j != i] \
            if join_to is None else []
        node = attach_replication(httpd, addr, peer_list, **opts)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        if join_to is not None:
            node.join_mesh(join_to)
        return httpd, node, addr

    for i in range(servers):
        dirs.append(_dir(i))
        httpd = serve(port=0, serve_shards=serve_shards,
                      data_dir=dirs[i], follower_reads=True,
                      obs_opts=dict(sample_rate=1.0))
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
        live.append(True)
    for i, httpd in enumerate(httpds):
        opts = dict(node_opts)
        if dirs[i] is not None:
            opts["journal_prefix"] = os.path.join(dirs[i], "_replica")
        node = attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            **opts)
        nodes.append(node)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

    def crash_node(i: int) -> None:
        """Tear slot `i` down WITHOUT closing its journal (the reboot
        replays the WAL, torn tail and all)."""
        node = nodes[i]
        node.journal = None          # crash: no graceful close/compact
        node.leases.journal = None
        httpds[i].shutdown()
        httpds[i].server_close()
        dead_nodes.append(node)
        live[i] = False

    def reboot_node(i: int) -> None:
        port = int(addrs[i].split(":")[1])
        httpd, node, _addr = boot(i, port=port)
        httpds[i] = httpd
        nodes[i] = node
        live[i] = True

    doc_ids = [f"soak-{i}" for i in range(docs)]
    clients: Dict[tuple, SyncClient] = {}

    def client(server_i: int, doc_id: str) -> SyncClient:
        key = (server_i, doc_id)
        if key not in clients:
            clients[key] = SyncClient(
                f"http://{addrs[server_i]}", doc_id,
                f"agent-{server_i}-{doc_id}", retries=2)
        return clients[key]

    def step_control_plane() -> None:
        for j, node in enumerate(nodes):
            if not live[j]:
                continue
            node.table.probe_once()
            node.maintain()
        for j, node in enumerate(nodes):
            if live[j]:
                node.antientropy.run_round()

    def live_addrs() -> List[str]:
        return [a for j, a in enumerate(addrs) if live[j]]

    part_pair = (addrs[0], addrs[1]) if servers >= 2 \
        and partition_rounds > 0 else None
    if asym and servers >= 3:
        # one slow, jittered link + a skewed clock: neither may break
        # safety, only latency
        faults.set_link_latency(addrs[0], addrs[2], 0.005,
                                jitter_s=0.005)
        faults.set_clock_skew(addrs[1], 0.5)
    # two crash-restart events, spread across the run, avoiding the
    # partition window's endpoints (those nodes are already stressed)
    crash_at = {}
    if crash and rounds >= 6:
        victims = [rng.randrange(servers) for _ in range(2)]
        crash_at = {max(2, rounds // 3): victims[0],
                    max(4, (2 * rounds) // 3): victims[1]}
    churn_join_at = rounds // 2 if churn else None
    churn_leave_at = (3 * rounds) // 4 if churn else None
    churn_idx: Optional[int] = None

    t0 = time.monotonic()
    edits = 0
    crashes = 0
    pending_reboot: Dict[int, int] = {}   # slot -> reboot round
    for r in range(rounds):
        if part_pair and r == 1:
            faults.partition(*part_pair, oneway=asym)
        if part_pair and r == 1 + partition_rounds:
            faults.heal(*part_pair)
        if r in crash_at and live[crash_at[r]]:
            i = crash_at[r]
            crash_node(i)
            crashes += 1
            pending_reboot[i] = r + 2     # two rounds of downtime
            if progress:
                print(f"round {r + 1}: crashed {addrs[i]}")
        for i, back_at in list(pending_reboot.items()):
            if r >= back_at:
                reboot_node(i)
                del pending_reboot[i]
                if progress:
                    print(f"round {r + 1}: rebooted {addrs[i]}")
        if churn_join_at is not None and r == churn_join_at:
            dirs.append(_dir(len(dirs)))
            churn_idx = len(addrs)
            addrs.append("")              # placeholder; boot fills it
            live.append(False)
            httpd, node, addr = boot(churn_idx,
                                     join_to=live_addrs()[0])
            httpds.append(httpd)
            nodes.append(node)
            addrs[churn_idx] = addr
            live[churn_idx] = True
            if progress:
                print(f"round {r + 1}: joined {addr}")
        if churn_leave_at is not None and r == churn_leave_at \
                and churn_idx is not None and live[churn_idx]:
            # explicit leave, announced to a surviving member so the
            # LEFT state gossips; then the node goes away for good
            target = [a for j, a in enumerate(addrs)
                      if live[j] and j != churn_idx][0]
            who = addrs[churn_idx]
            try:
                req = urllib.request.Request(
                    f"http://{target}/replicate/leave",
                    data=json.dumps({"id": who}).encode("utf8"))
                urllib.request.urlopen(req, timeout=2).read()
            except OSError:
                pass
            node = nodes[churn_idx]
            httpds[churn_idx].shutdown()
            httpds[churn_idx].server_close()
            dead_nodes.append(node)
            live[churn_idx] = False
            if progress:
                print(f"round {r + 1}: left {who}")
        for _ in range(edits_per_round):
            alive = [j for j in range(len(addrs)) if live[j]]
            si = rng.choice(alive)
            doc = rng.choice(doc_ids)
            c = client(si, doc)
            try:
                c.pull()
            except OSError:
                pass    # client keeps editing its local replica
            pos = rng.randrange(len(c.text()) + 1)
            c.insert(pos, rng.choice(_WORDS) + " ")
            try:
                c.sync()
                edits += 1
            except OSError:
                pass    # retries exhausted mid-fault; next round
        step_control_plane()
        if progress:
            print(f"round {r + 1}/{rounds}: {edits} edits applied")

    # fault window over: reboot stragglers, heal everything and
    # reconcile to convergence
    for i in list(pending_reboot):
        reboot_node(i)
        del pending_reboot[i]
    faults.heal()
    converged_after = None
    for r in range(reconcile_rounds):
        time.sleep(0.05)   # let breaker backoff windows lapse
        step_control_plane()
        if _converged(live_addrs(), doc_ids):
            converged_after = r + 1
            break

    texts = _final_texts(live_addrs(), doc_ids)
    converged = all(len(set(v.values())) == 1 for v in texts.values())
    all_nodes = nodes + dead_nodes
    split_brain = _split_brain(all_nodes)
    live_nodes = [n for j, n in enumerate(nodes) if live[j]]
    mergers = {d: sorted({n.self_id for n in all_nodes
                          if d in n.merged_docs}) for d in doc_ids}
    multi = sorted(d for d, who in mergers.items() if len(who) > 1)
    fencing_totals = {
        k: sum(n.metrics.get("fencing", k) for n in all_nodes)
        for k in ("rejected_writes", "stale_lease_revoked",
                  "rejoin_denials")}
    quorum_totals = {
        k: sum(n.metrics.get("quorum", k) for n in all_nodes)
        for k in ("rounds_won", "rounds_lost", "promise_conflicts",
                  "rejoins_completed")}
    report = {
        "config": {"servers": servers, "docs": docs, "rounds": rounds,
                   "edits_per_round": edits_per_round, "seed": seed,
                   "drop_rate": drop_rate, "dup_rate": dup_rate,
                   "partition_rounds": partition_rounds,
                   "lease_ttl_s": lease_ttl_s,
                   "serve_shards": serve_shards,
                   "crash": crash, "asym": asym, "churn": churn},
        "edits_applied": edits,
        "converged": converged,
        "converged_after_reconcile_rounds": converged_after,
        "split_brain": split_brain,
        "zero_split_brain": not split_brain,
        "crashes": crashes,
        "fencing": fencing_totals,
        "quorum": quorum_totals,
        "multi_merger_docs": multi,
        "mergers": mergers,
        "doc_lengths": {d: {a: len(t) for a, t in v.items()}
                        for d, v in texts.items()},
        "faults": faults.snapshot(),
        "wall_s": round(time.monotonic() - t0, 3),
        "metrics": {n.self_id: n.metrics_json() for n in live_nodes},
        # edit-to-visibility: per-peer convergence-lag rollup of every
        # journey each owner tracked (admitted -> advert_usable)
        "convergence_lag": {
            n.self_id: n.obs.journey.lag_summary()
            for n in live_nodes if getattr(n, "obs", None) is not None},
    }
    if use_witness:
        # the observed lock-order graph across every thread the soak
        # ran (flush workers, maintenance loops, HTTP handlers): a
        # cycle is a latent deadlock the run merely didn't lose the
        # race to, so acyclicity joins the verdict
        from ..analysis import witness_disable, witness_snapshot
        snap = witness_snapshot()
        witness_disable()
        report["lock_witness"] = {
            "acquires": snap["acquires"],
            "edge_count": snap["edge_count"],
            "edges": snap["edges"],
            "violation_count": snap["violation_count"],
            "cycles": snap["cycles"],
            "acyclic": snap["acyclic"]
            and not snap["violation_count"],
        }
    if not (converged and not split_brain
            and report.get("lock_witness", {}).get("acyclic", True)):
        # flight-recorder tail makes a failed soak diagnosable from the
        # JSON report alone: last 50 events across all live recorders
        events = []
        for n in live_nodes:
            obs = getattr(n, "obs", None)
            if obs is None:
                continue
            for ev in obs.recorder.tail(50):
                events.append(dict(ev, node=n.self_id))
        events.sort(key=lambda e: e.get("t", 0.0))
        report["events_tail"] = events[-50:]
    for j, httpd in enumerate(httpds):
        if live[j]:
            httpd.shutdown()
            httpd.server_close()
    return report


def _get_text(addr: str, doc_id: str) -> str:
    with urllib.request.urlopen(f"http://{addr}/doc/{doc_id}",
                                timeout=5) as r:
        return r.read().decode("utf8")


def _final_texts(addrs: List[str],
                 doc_ids: List[str]) -> Dict[str, Dict[str, str]]:
    return {d: {a: _get_text(a, d) for a in addrs} for d in doc_ids}


def _converged(addrs: List[str], doc_ids: List[str]) -> bool:
    for d in doc_ids:
        if len({_get_text(a, d) for a in addrs}) > 1:
            return False
    return True


def main(argv=None) -> int:  # pragma: no cover - exercised via cli.py
    import argparse
    p = argparse.ArgumentParser(prog="replicate-soak")
    p.add_argument("--servers", type=int, default=3)
    p.add_argument("--docs", type=int, default=4)
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--drop-rate", type=float, default=0.15)
    p.add_argument("--crash", action="store_true")
    p.add_argument("--asym", action="store_true")
    p.add_argument("--churn", action="store_true")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    report = run_replicate_soak(servers=args.servers, docs=args.docs,
                                rounds=args.rounds, seed=args.seed,
                                drop_rate=args.drop_rate,
                                crash=args.crash, asym=args.asym,
                                churn=args.churn)
    print(json.dumps(report if args.json else {
        k: report[k] for k in ("converged", "edits_applied",
                               "split_brain", "zero_split_brain",
                               "crashes", "fencing",
                               "multi_merger_docs", "wall_s")
        if k in report}))
    return 0 if (report["converged"] and report["zero_split_brain"]
                 and report.get("lock_witness",
                                {}).get("acyclic", True)) else 1
