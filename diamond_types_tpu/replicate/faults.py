"""Deterministic fault injection for the peer mesh.

Every inter-server HTTP call funnels through `PeerTable.call`, which
consults one shared `FaultInjector` before touching the network. Tests
and the `cli replicate-soak` driver inject drops, delays, duplicates
and partitions from a fixed seed, so a failing convergence run replays
byte-for-byte.

Determinism contract: outcomes are drawn from one `random.Random(seed)`
in call order. Drive the mesh single-threaded (tests call
`probe_once()` / `run_round()` inline) and the fault schedule is exact;
under the threaded soak driver it is still seed-stable per interleaving.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, FrozenSet, Set


class FaultDrop(ConnectionError):
    """An injected drop — indistinguishable from a connection failure to
    the caller, on purpose: the retry/circuit machinery must treat
    injected and real faults identically."""


class FaultInjector:
    def __init__(self, seed: int = 0, drop_rate: float = 0.0,
                 dup_rate: float = 0.0, delay_rate: float = 0.0,
                 max_delay_s: float = 0.0) -> None:
        self.rng = random.Random(seed)
        self.drop_rate = drop_rate
        self.dup_rate = dup_rate
        self.delay_rate = delay_rate
        self.max_delay_s = max_delay_s
        self._partitions: Set[FrozenSet[str]] = set()
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "drops": 0, "delays": 0, "dups": 0, "partition_blocks": 0}

    # ---- partitions ------------------------------------------------------

    def partition(self, a: str, b: str) -> None:
        """Cut the (bidirectional) link between peers `a` and `b`."""
        with self._lock:
            self._partitions.add(frozenset((a, b)))

    def heal(self, a: str = None, b: str = None) -> None:
        """Heal one link (both args) or every partition (no args)."""
        with self._lock:
            if a is None:
                self._partitions.clear()
            else:
                self._partitions.discard(frozenset((a, b)))

    def partitioned(self, a: str, b: str) -> bool:
        with self._lock:
            return frozenset((a, b)) in self._partitions

    # ---- call-site hook --------------------------------------------------

    def before_call(self, src: str, dst: str) -> bool:
        """Run the fault schedule for one outbound call. Raises
        `FaultDrop` for a drop/partition, sleeps for a delay, and
        returns True when the call should be DUPLICATED (sent twice;
        peer endpoints are idempotent, so dups must be harmless)."""
        if self.partitioned(src, dst):
            with self._lock:
                self.counters["partition_blocks"] += 1
            raise FaultDrop(f"partitioned: {src} <-> {dst}")
        with self._lock:
            # one rng draw per configured fault class, in fixed order,
            # so enabling delays does not shift the drop schedule
            drop = self.drop_rate and self.rng.random() < self.drop_rate
            delay = (self.delay_rate
                     and self.rng.random() < self.delay_rate)
            dup = self.dup_rate and self.rng.random() < self.dup_rate
            delay_s = (self.rng.random() * self.max_delay_s
                       if delay else 0.0)
            if drop:
                self.counters["drops"] += 1
            elif delay:
                self.counters["delays"] += 1
            if not drop and dup:
                self.counters["dups"] += 1
        if drop:
            raise FaultDrop(f"injected drop: {src} -> {dst}")
        if delay_s:
            time.sleep(delay_s)
        return bool(not drop and dup)

    def snapshot(self) -> dict:
        with self._lock:
            return {"partitions": sorted(
                        tuple(sorted(p)) for p in self._partitions),
                    **self.counters}
