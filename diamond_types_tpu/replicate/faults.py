"""Deterministic fault injection for the peer mesh.

Every inter-server HTTP call funnels through `PeerTable.call`, which
consults one shared `FaultInjector` before touching the network. Tests
and the `cli replicate-soak` driver inject drops, delays, duplicates
and partitions from a fixed seed, so a failing convergence run replays
byte-for-byte.

Partitions are DIRECTED internally: `partition(a, b)` cuts both
directions, `partition(a, b, oneway=True)` cuts only a→b — the
asymmetric case PR 2 documented as unsafe for TTL-delayed takeover (a
can't renew toward b, but b still hears a's claims). Per-link latency
(`set_link_latency`) adds a deterministic jittered sleep to one
direction, and per-host clock skew (`set_clock_skew`) is bookkept for
tests that reason about disagreeing lease-expiry clocks (`now(host)`).

Determinism contract: outcomes are drawn from one `random.Random(seed)`
in call order. Drive the mesh single-threaded (tests call
`probe_once()` / `run_round()` inline) and the fault schedule is exact;
under the threaded soak driver it is still seed-stable per
interleaving. Link-latency jitter draws happen only for links that
configured jitter, so enabling it on one link does not shift the
global drop/dup schedule of the others.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, Set, Tuple


class FaultDrop(ConnectionError):
    """An injected drop — indistinguishable from a connection failure to
    the caller, on purpose: the retry/circuit machinery must treat
    injected and real faults identically."""


class FaultInjector:
    def __init__(self, seed: int = 0, drop_rate: float = 0.0,
                 dup_rate: float = 0.0, delay_rate: float = 0.0,
                 max_delay_s: float = 0.0) -> None:
        self.rng = random.Random(seed)
        self.drop_rate = drop_rate
        self.dup_rate = dup_rate
        self.delay_rate = delay_rate
        self.max_delay_s = max_delay_s
        # directed edges: (src, dst) blocked
        self._partitions: Set[Tuple[str, str]] = set()
        # (src, dst) -> (latency_s, jitter_s)
        self._link_latency: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self._clock_skew: Dict[str, float] = {}
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "drops": 0, "delays": 0, "dups": 0, "partition_blocks": 0,
            "link_delays": 0}

    # ---- partitions ------------------------------------------------------

    def partition(self, a: str, b: str, oneway: bool = False) -> None:
        """Cut the link a→b; both directions unless `oneway` (the
        asymmetric-partition case quorum must survive)."""
        with self._lock:
            self._partitions.add((a, b))
            if not oneway:
                self._partitions.add((b, a))

    def heal(self, a: str = None, b: str = None) -> None:
        """Heal one link (both directions) or every partition (no
        args)."""
        with self._lock:
            if a is None:
                self._partitions.clear()
            else:
                self._partitions.discard((a, b))
                self._partitions.discard((b, a))

    def partitioned(self, a: str, b: str) -> bool:
        """Is the DIRECTED link a→b cut?"""
        with self._lock:
            return (a, b) in self._partitions

    # ---- per-link latency / clock skew -----------------------------------

    def set_link_latency(self, src: str, dst: str, latency_s: float,
                         jitter_s: float = 0.0) -> None:
        """Add `latency_s` (+ uniform jitter in [0, jitter_s)) of sleep
        to every src→dst call. Directed — model an asymmetric slow
        link by setting only one direction. Zero both to clear."""
        with self._lock:
            if latency_s <= 0.0 and jitter_s <= 0.0:
                self._link_latency.pop((src, dst), None)
            else:
                self._link_latency[(src, dst)] = (max(latency_s, 0.0),
                                                  max(jitter_s, 0.0))

    def set_clock_skew(self, host: str, skew_s: float) -> None:
        """Bookkeep a per-host clock skew. Nothing in the mesh reads
        wall clocks cross-host (lease TTLs are local monotonic), so
        skew does not alter the fault schedule — tests use `now(host)`
        to model hosts disagreeing about lease expiry."""
        with self._lock:
            if skew_s == 0.0:
                self._clock_skew.pop(host, None)
            else:
                self._clock_skew[host] = float(skew_s)

    def now(self, host: str) -> float:
        """This host's (skewed) view of the monotonic clock."""
        with self._lock:
            return time.monotonic() + self._clock_skew.get(host, 0.0)

    # ---- call-site hook --------------------------------------------------

    def before_call(self, src: str, dst: str) -> bool:
        """Run the fault schedule for one outbound call. Raises
        `FaultDrop` for a drop/partition, sleeps for a delay, and
        returns True when the call should be DUPLICATED (sent twice;
        peer endpoints are idempotent, so dups must be harmless)."""
        if self.partitioned(src, dst):
            with self._lock:
                self.counters["partition_blocks"] += 1
            raise FaultDrop(f"partitioned: {src} -> {dst}")
        with self._lock:
            # one rng draw per configured fault class, in fixed order,
            # so enabling delays does not shift the drop schedule
            drop = self.drop_rate and self.rng.random() < self.drop_rate
            delay = (self.delay_rate
                     and self.rng.random() < self.delay_rate)
            dup = self.dup_rate and self.rng.random() < self.dup_rate
            delay_s = (self.rng.random() * self.max_delay_s
                       if delay else 0.0)
            link = self._link_latency.get((src, dst))
            if link is not None and not drop:
                base, jitter = link
                delay_s += base + (self.rng.random() * jitter
                                   if jitter else 0.0)
                self.counters["link_delays"] += 1
            if drop:
                self.counters["drops"] += 1
            elif delay:
                self.counters["delays"] += 1
            if not drop and dup:
                self.counters["dups"] += 1
        if drop:
            raise FaultDrop(f"injected drop: {src} -> {dst}")
        if delay_s:
            time.sleep(delay_s)
        return bool(not drop and dup)

    def snapshot(self) -> dict:
        with self._lock:
            # a pair is "oneway" when its reverse edge is not also cut
            oneway = sorted(
                [src, dst] for (src, dst) in self._partitions
                if (dst, src) not in self._partitions)
            return {"partitions": sorted(
                        [src, dst] for (src, dst) in self._partitions),
                    "oneway_partitions": oneway,
                    "link_latency": {
                        f"{s}->{d}": {"latency_s": lat,
                                      "jitter_s": jit}
                        for (s, d), (lat, jit) in
                        sorted(self._link_latency.items())},
                    "clock_skew": dict(sorted(
                        self._clock_skew.items())),
                    **self.counters}
