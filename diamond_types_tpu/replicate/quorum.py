"""Quorum-backed lease acquisition + durable replica state.

PR 2's takeover was TTL-delayed: a host that believed the owner's lease
expired simply self-granted the next epoch. Under an asymmetric
partition two hosts can believe that simultaneously — the exact
split-brain the ROADMAP marked open. This module closes it with a
single-round promise protocol (the prepare half of Paxos, which is all
a lease needs):

  * Before a lease (grant, takeover, or handoff activation) becomes
    ACTIVE at epoch E, the would-be holder must collect promises for
    (doc, E) from a MAJORITY of the membership voter set
    (membership.MembershipView.voters — LEFT excluded, DEAD still
    counted so a minority partition can never vote the other side out).
  * A voter promises (doc, E) to AT MOST ONE holder — ever. A second
    proposer at the same epoch is denied (counted as a
    `promise_conflict`); retries by the SAME holder are idempotent
    acks. Any two majorities intersect, so at most one holder can
    collect a quorum for (doc, E): **at most one ACTIVE lease per
    (doc, epoch)**, under any combination of partitions, crashes and
    membership churn.
  * Promising (or observing) epoch E raises the voter's per-doc
    fencing floor `max_epoch[doc]`. A holder whose ACTIVE lease sits
    below the floor has been superseded: its scheduler admits are
    revoked and its proxied writes are rejected (HTTP 409), not merged.

The promise table and fencing floors live in ownership.LeaseManager
(one lock for all per-doc lease state); this module provides the
coordinator that runs the network round, and the journal that makes the
floors survive a crash.

`ReplicaJournal` reuses the storage/ primitives (the checksummed `Wal`
+ double-blit-header `PageStore`): JSON records appended to
`{data_dir}/_replica.state.wal`, periodically compacted into
`{data_dir}/_replica.state`. Restored state: per-doc max epoch (the
safety payload — a restarted node must never re-issue a stale epoch),
the held-lease table (as expired hints), and the membership
incarnation (bumped on every restart so post-crash refutations are
fresh). A node that restores prior state boots into a fenced
"rejoining" mode: `ReplicaNode.owns` denies every merge until the node
has confirmed a quorum of voters reachable (see node.maintain).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
from typing import Dict, Optional

from ..storage.store import PageStore, StorageError, Wal

# journal WAL records folded into one snapshot at compaction
_COMPACT_EVERY = 256


class ReplicaJournal:
    """Durable replica coordination state at `{prefix}.state[.wal]`.

    Record shapes (JSON, one per WAL frame):
      {"t": "incarnation", "n": int}
      {"t": "epoch", "doc": str, "n": int}          # per-doc max epoch
      {"t": "promise", "doc": str, "epoch": int, "holder": str}
      {"t": "lease", "doc": str, "holder": str, "epoch": int,
       "state": str}                                 # held-lease hint
      {"t": "drop_lease", "doc": str}
      {"t": "override", "doc": str, "target": str | null, "ver": int}
                                    # placement override (null = tombstone)
      {"t": "group", "doc": str, "epoch": int, "members": [str],
       "leader": str}               # writer-group registration
      {"t": "drop_group", "doc": str}

    Promises are persisted because they are the safety core: a voter
    that promised (doc, E) to A, crashed, and forgot could promise
    (doc, E) to B — and sit in the intersection of both majorities,
    breaking at-most-one-ACTIVE-per-(doc, epoch).

    Appends flush to the OS (process-crash durable) and fsync only when
    `sync=True` (incarnation bumps, compaction) — the soak kills
    processes, not power.
    """

    def __init__(self, prefix: str) -> None:
        os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
        self.prefix = prefix
        # journal writes nest under the lease lock and take nothing
        # further — a leaf in the canonical order
        from ..analysis.witness import make_lock
        self._lock = make_lock("repl.journal", "leaf")
        self.state: dict = {"incarnation": 0, "max_epoch": {},
                            "leases": {}, "promises": {}}
        try:
            self._store: Optional[PageStore] = PageStore(
                prefix + ".state")
            self._wal: Optional[Wal] = Wal(prefix + ".state.wal")
        except StorageError:
            # corrupt beyond the double-header's protection: start
            # fresh rather than refuse to boot (the lease table is
            # reconstructible from the mesh; losing max_epoch degrades
            # to PR 2's behavior for this node only)
            for suffix in (".state", ".state.wal"):
                try:
                    os.remove(prefix + suffix)
                except OSError:
                    pass
            self._store = PageStore(prefix + ".state")
            self._wal = Wal(prefix + ".state.wal")
        base = self._store.read()
        if base:
            try:
                self.state = json.loads(base)
            except ValueError:
                pass
        self._pending = 0
        for rec in self._wal.records():
            try:
                self._apply(json.loads(rec))
                self._pending += 1
            except ValueError:
                continue

    # ---- state fold ------------------------------------------------------

    def _apply(self, rec: dict) -> None:
        t = rec.get("t")
        if t == "incarnation":
            self.state["incarnation"] = max(
                int(rec["n"]), int(self.state.get("incarnation", 0)))
        elif t == "epoch":
            me = self.state.setdefault("max_epoch", {})
            doc = rec["doc"]
            me[doc] = max(int(rec["n"]), int(me.get(doc, 0)))
        elif t == "promise":
            self.state.setdefault("promises", {})[rec["doc"]] = {
                "epoch": int(rec["epoch"]), "holder": rec["holder"]}
        elif t == "lease":
            self.state.setdefault("leases", {})[rec["doc"]] = {
                "holder": rec["holder"], "epoch": int(rec["epoch"]),
                "state": rec.get("state", "active")}
        elif t == "drop_lease":
            self.state.setdefault("leases", {}).pop(rec["doc"], None)
        elif t == "group":
            self.state.setdefault("groups", {})[rec["doc"]] = {
                "epoch": int(rec["epoch"]),
                "members": list(rec.get("members", [])),
                "leader": rec.get("leader", "")}
        elif t == "drop_group":
            self.state.setdefault("groups", {}).pop(rec["doc"], None)
        elif t == "override":
            # last-writer-wins by version, matching
            # rebalance.PlacementOverrides.merge (tombstones kept — a
            # restored table must remember retractions too)
            ov = self.state.setdefault("overrides", {})
            cur = ov.get(rec["doc"])
            if cur is None or int(rec["ver"]) >= int(cur.get("ver", 0)):
                ov[rec["doc"]] = {"target": rec.get("target"),
                                  "ver": int(rec["ver"])}

    def record(self, rec: dict, sync: bool = False) -> None:
        with self._lock:
            if self._wal is None:
                return
            self._wal.append(json.dumps(rec).encode("utf8"), sync=sync)
            self._apply(rec)
            self._pending += 1
            if self._pending >= _COMPACT_EVERY:
                self._compact_locked()

    def _compact_locked(self) -> None:
        self._store.write(json.dumps(self.state).encode("utf8"))
        self._wal.reset()
        self._pending = 0

    def compact(self) -> None:
        with self._lock:
            self._compact_locked()

    # ---- typed appends ---------------------------------------------------

    def note_incarnation(self, n: int) -> None:
        self.record({"t": "incarnation", "n": int(n)}, sync=True)

    def note_epoch(self, doc: str, epoch: int) -> None:
        # dedup: only a raise of the floor is worth a frame
        with self._lock:
            if int(self.state.get("max_epoch", {}).get(doc, 0)) \
                    >= int(epoch):
                return
        self.record({"t": "epoch", "doc": doc, "n": int(epoch)})

    def note_promise(self, doc: str, epoch: int, holder: str) -> None:
        self.record({"t": "promise", "doc": doc, "epoch": int(epoch),
                     "holder": holder})

    def note_lease(self, doc: str, holder: str, epoch: int,
                   state: str) -> None:
        self.record({"t": "lease", "doc": doc, "holder": holder,
                     "epoch": int(epoch), "state": state})

    def drop_lease(self, doc: str) -> None:
        self.record({"t": "drop_lease", "doc": doc})

    def note_override(self, doc: str, target, ver: int) -> None:
        self.record({"t": "override", "doc": doc, "target": target,
                     "ver": int(ver)})

    def note_group(self, doc: str, epoch: int, members, leader: str) -> None:
        self.record({"t": "group", "doc": doc, "epoch": int(epoch),
                     "members": list(members), "leader": leader})

    def drop_group(self, doc: str) -> None:
        self.record({"t": "drop_group", "doc": doc})

    # ---- restored views --------------------------------------------------

    def restored_incarnation(self) -> int:
        return int(self.state.get("incarnation", 0))

    def restored_max_epochs(self) -> Dict[str, int]:
        return {d: int(n)
                for d, n in self.state.get("max_epoch", {}).items()}

    def restored_promises(self) -> Dict[str, dict]:
        return dict(self.state.get("promises", {}))

    def restored_leases(self) -> Dict[str, dict]:
        return dict(self.state.get("leases", {}))

    def restored_overrides(self) -> Dict[str, dict]:
        return dict(self.state.get("overrides", {}))

    def restored_groups(self) -> Dict[str, dict]:
        return dict(self.state.get("groups", {}))

    def has_prior_state(self) -> bool:
        return bool(self.state.get("incarnation", 0)
                    or self.state.get("max_epoch")
                    or self.state.get("leases")
                    or self.state.get("promises")
                    or self.state.get("overrides")
                    or self.state.get("groups"))

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._compact_locked()
                self._wal.close()
                self._store.close()
                self._wal = None
                self._store = None


class QuorumCoordinator:
    """Runs the proposer side of the promise round for one node.

    Stateless between rounds — the durable per-doc state (promises,
    fencing floors) lives in the LeaseManager on each voter; this class
    only fans the proposal out and counts acks. One instance per
    ReplicaNode, called with no locks held (the round does network I/O).
    """

    def __init__(self, node) -> None:
        self.node = node            # ReplicaNode (duck-typed)

    def acquire(self, doc_id: str, epoch: int,
                takeover: bool = False) -> bool:
        """Collect promises for (doc_id, epoch) from a majority of the
        voter set. Our own promise is taken first (and is binding: if
        we cannot promise to ourselves, someone beat us to the epoch).
        Best-effort short-circuit once the majority is reached."""
        from ..obs.trace import NOOP_SPAN, TRACE_HEADER
        node = self.node
        metrics = node.metrics
        obs = getattr(node, "obs", None)
        t0 = time.monotonic()
        span = NOOP_SPAN
        if obs is not None:
            span = obs.tracer.start(
                "repl.quorum", attrs={"doc": doc_id, "epoch": epoch,
                                      "takeover": bool(takeover)})
        hdrs = {TRACE_HEADER: span.header()} if span.sampled else None
        voters = node.membership.voters()
        need = len(voters) // 2 + 1
        metrics.bump("quorum", "proposals")
        ok, _reason = node.leases.promise(doc_id, epoch, node.self_id)
        if not ok:
            metrics.bump("quorum", "rounds_lost")
            metrics.observe_latency("quorum_round",
                                    time.monotonic() - t0)
            span.end(won=False, reason="self_promise_refused")
            return False
        acks = 1
        for v in voters:
            if v == node.self_id:
                continue
            if acks >= need:
                break
            try:
                resp = node.table.call_json(
                    v, "/replicate/lease",
                    {"action": "propose", "doc": doc_id,
                     "epoch": epoch, "holder": node.self_id,
                     "takeover": bool(takeover)},
                    headers=hdrs)
            except (OSError, KeyError, ValueError,
                    urllib.error.HTTPError):
                continue            # unreachable voter = no ack
            if resp.get("ok"):
                acks += 1
                metrics.bump("quorum", "acks")
            else:
                metrics.bump("quorum", "denials")
        won = acks >= need
        metrics.bump("quorum", "rounds_won" if won else "rounds_lost")
        metrics.observe_latency("quorum_round", time.monotonic() - t0)
        span.end(won=won, acks=acks, need=need)
        return won
