"""Elastic mesh: SLO-driven hot-doc rebalancing over the lease handoff.

Rendezvous hashing gives every doc a stable home, but a flash crowd on
one doc pins its owner host no matter how many peers sit idle — the
mesh can OBSERVE the overload (obs/slo.py burn rates, obs/attrib.py
hot-doc sketch) yet cannot act on it. This module closes the loop:

  * `PlacementOverrides` is a versioned doc -> host table LAYERED OVER
    rendezvous hashing. `ReplicaNode.desired_owner` consults it first,
    so the merge-admission gate, write proxying, the maintain loop and
    the follower read path all follow an override the moment it lands.
    Entries are last-writer-wins by (version, target) — every host
    folds remote entries with `merge`, newer version (tie: lexically
    smaller target) wins, removals are tombstones (target None) so they
    gossip the same way. The table rides SWIM ping bodies
    (`ReplicaNode.ping_json` / `_on_ping`) and is journaled through
    `ReplicaJournal.note_override` so placement survives crash-restart.

  * `Rebalancer` is the closed loop: each control tick it evaluates the
    SLO engine; when an objective is `warning`/`burning` it ranks this
    host's held docs by the hot-doc sketch, picks the least-loaded
    healthy peer (load = held-lease counts gossiped on pings), and
    live-migrates the offenders over the EXISTING epoch-fenced handoff
    (grant -> drain -> transfer -> activate, replicate/ownership.py).
    The override is written before the grant and shipped ON the grant
    message, so the target keeps the doc instead of rendezvous handing
    it straight back; a failed handoff aborts back to ACTIVE at the
    source with the fence intact and the override is tombstoned — a
    failed target never strands a doc. After a successful migration the
    source parks its warm copy back to the snapshot+WAL home
    (hydrator.evict_to_snapshot), completing the residency move.

A host joining mid-soak simply gossips a load of zero and becomes the
preferred target — scale-out under load needs no operator action.
Safety never depends on this module: overrides only steer placement;
every activation still runs the quorum round and every write is still
epoch-fenced.

Locking: `repl.rebalance` is a new rung between `repl.maintain` and
`repl.leases` (the tick plans under it; migrations run OUTSIDE it and
take the lease lock through `node.handoff`). See
analysis/rules/locks.py ORDER_LEVELS.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..analysis.witness import make_lock

# overrides gossiped per ping body (tables are tiny — one entry per
# actively-migrated doc — but the cap keeps a pathological table from
# bloating every probe)
_GOSSIP_CAP = 64


class PlacementOverrides:
    """Versioned placement-override table (doc -> target host).

    Merge rule: higher version wins; equal versions tie-break on the
    lexically smaller target string so every host converges to the
    same entry without coordination. A cleared override is a tombstone
    (target None) at a bumped version — it gossips and journals like
    any entry, which is what lets an abort roll BACK an override that
    other hosts may already have folded.
    """

    def __init__(self, journal=None, metrics=None) -> None:
        # consulted from desired_owner (no lock held) and from the
        # maintain loop (repl.maintain, rung 0) — repl.rebalance (1)
        # nests under maintain and outside repl.leases (2)
        self._rebalance_lock = make_lock("repl.rebalance.overrides",
                                         "repl.rebalance")
        # doc -> (target | None, version)
        self._entries: Dict[str, Tuple[Optional[str], int]] = {}
        self.journal = journal
        self.metrics = metrics
        if journal is not None:
            restore = getattr(journal, "restored_overrides", None)
            if restore is not None:
                for doc, ent in restore().items():
                    tgt = ent.get("target")
                    self._entries[doc] = (tgt, int(ent.get("ver", 0)))

    # ---- local writes ----------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.bump("rebalance", key, n)

    def _journal(self, doc: str, target: Optional[str],
                 ver: int) -> None:
        if self.journal is not None:
            note = getattr(self.journal, "note_override", None)
            if note is not None:
                note(doc, target, ver)

    def set(self, doc_id: str, target: str) -> int:
        """Pin `doc_id`'s placement to `target`; returns the version
        the entry was written at (for the grant-message rider)."""
        with self._rebalance_lock:
            _old, ver = self._entries.get(doc_id, (None, 0))
            ver += 1
            self._entries[doc_id] = (target, ver)
        self._journal(doc_id, target, ver)
        self._bump("overrides_set")
        return ver

    def clear(self, doc_id: str) -> int:
        """Tombstone the override (rollback / un-pin). No-op version
        bump when no entry exists — nothing to retract."""
        with self._rebalance_lock:
            _old, ver = self._entries.get(doc_id, (None, 0))
            ver += 1
            self._entries[doc_id] = (None, ver)
        self._journal(doc_id, None, ver)
        self._bump("overrides_cleared")
        return ver

    # ---- reads -----------------------------------------------------------

    def target_of(self, doc_id: str) -> Optional[str]:
        with self._rebalance_lock:
            ent = self._entries.get(doc_id)
            return ent[0] if ent is not None else None

    def version_of(self, doc_id: str) -> int:
        with self._rebalance_lock:
            ent = self._entries.get(doc_id)
            return ent[1] if ent is not None else 0

    def size(self) -> int:
        """Active (non-tombstone) entries — the prom gauge."""
        with self._rebalance_lock:
            return sum(1 for t, _v in self._entries.values()
                       if t is not None)

    def as_json(self) -> dict:
        with self._rebalance_lock:
            return {d: {"target": t, "ver": v}
                    for d, (t, v) in sorted(self._entries.items())}

    # ---- gossip ----------------------------------------------------------

    def gossip_payload(self, cap: int = _GOSSIP_CAP) -> list:
        """[[doc, target|null, version], ...] — tombstones included so
        clears propagate exactly like sets."""
        with self._rebalance_lock:
            items = sorted(self._entries.items())[:cap]
            return [[d, t, v] for d, (t, v) in items]

    def merge(self, payload, journal: bool = True) -> int:
        """Fold a peer's gossiped entries; returns how many local
        entries changed. Newly-learned entries are journaled too —
        placement must survive a crash on EVERY host, not just the one
        that initiated the migration."""
        if not isinstance(payload, list):
            return 0
        changed: List[Tuple[str, Optional[str], int]] = []
        with self._rebalance_lock:
            for row in payload:
                if not (isinstance(row, list) and len(row) == 3):
                    continue
                doc, target, ver = row
                if not isinstance(doc, str) \
                        or not isinstance(ver, int) \
                        or not (target is None
                                or isinstance(target, str)):
                    continue
                cur_t, cur_v = self._entries.get(doc, (None, 0))
                if ver < cur_v:
                    continue
                if ver == cur_v and (cur_t is None
                                     or (target is not None
                                         and target >= cur_t)):
                    continue        # equal version: smaller target wins
                self._entries[doc] = (target, ver)
                changed.append((doc, target, ver))
        if journal:
            for doc, target, ver in changed:
                self._journal(doc, target, ver)
        if changed:
            self._bump("override_merges", len(changed))
        return len(changed)


class Rebalancer:
    """The closed loop: SLO burn state -> offender docs -> live
    migration. One instance per ReplicaNode; `tick()` runs from the
    node's probe/maintain loop (and from the soaks' single-threaded
    control-plane step). Planning happens under the rebalance lock;
    migrations (network + lease lock) run strictly outside it."""

    def __init__(self, node, obs=None, *,
                 max_migrations_per_tick: int = 1,
                 cooldown_s: float = 3.0,
                 top_n: int = 4,
                 min_load_gap: int = 1,
                 act_on: Tuple[str, ...] = ("warning", "burning"),
                 enabled: bool = True,
                 split_hot_docs: bool = False,
                 group_size: int = 2,
                 promote_after_ticks: int = 2,
                 promote_min_share: float = 0.5,
                 demote_after_s: float = 6.0) -> None:
        self.node = node
        self.obs = obs if obs is not None else getattr(node, "obs",
                                                       None)
        self.max_migrations_per_tick = max_migrations_per_tick
        self.cooldown_s = cooldown_s
        self.top_n = top_n
        # only migrate when our held-lease count exceeds the target's
        # gossiped load by at least this much (ping-pong damper)
        self.min_load_gap = min_load_gap
        # SLO states that arm a migration; a conservative deployment
        # narrows this to ("burning",) so transient warnings never
        # move a doc
        self.act_on = tuple(act_on)
        self.enabled = enabled
        # hot-doc write splitting (replicate/writergroup.py): when a
        # held doc stays a top offender for `promote_after_ticks`
        # consecutive stressed ticks, promote it to a writer group of
        # `group_size` instead of migrating it (a flash crowd on ONE
        # doc cannot be migrated away — splitting the write path can).
        # Cooled groups demote after `demote_after_s` without burn.
        # OFF by default: the single-writer path stays byte-identical.
        self.split_hot_docs = split_hot_docs
        self.group_size = max(2, int(group_size))
        self.promote_after_ticks = max(1, int(promote_after_ticks))
        # splitting is for a DOMINANT doc: promotion also requires the
        # doc to carry at least this share of the attributed burn, so
        # merely ranking in the top-N (which migration is happy with)
        # never splits a cold doc
        self.promote_min_share = float(promote_min_share)
        self.demote_after_s = demote_after_s
        self._rebalance_lock = make_lock("repl.rebalance.plan",
                                         "repl.rebalance")
        self._last_attempt: Dict[str, float] = {}
        # doc -> consecutive stressed ticks it ranked as an offender
        self._hot_ticks: Dict[str, int] = {}
        # doc -> last time a group we lead saw hot-doc burn
        self._group_hot: Dict[str, float] = {}

    # ---- selection -------------------------------------------------------

    def _stressed(self) -> List[str]:
        """Objective names currently warning/burning (empty = healthy)."""
        if self.obs is None or getattr(self.obs, "slo", None) is None:
            return []
        try:
            rows = self.obs.slo.evaluate()
        except Exception:       # pragma: no cover - obs must never kill
            return []
        return [r["name"] for r in rows
                if r.get("state") in self.act_on]

    def _attrib_scores(self) -> Dict[str, float]:
        """Per-doc hot-doc attribution (ops + bytes sketches merged)."""
        scores: Dict[str, float] = {}
        attrib = getattr(self.obs, "attrib", None) \
            if self.obs is not None else None
        if attrib is not None:
            for kind in ("ops", "bytes"):
                for key, count, _err in attrib.top("doc", kind,
                                                   self.top_n * 4):
                    scores[key] = scores.get(key, 0.0) + count
        return scores

    def _offenders(self, scores: Optional[Dict[str, float]] = None
                   ) -> List[str]:
        """This host's held docs ranked by hot-doc attribution score
        (ops + bytes sketches merged); falls back to held order when
        the sketch is cold so a burning host can still shed load."""
        node = self.node
        held = list(node.leases.held_ids())
        if not held:
            return []
        if scores is None:
            scores = self._attrib_scores()
        held.sort(key=lambda d: (-scores.get(d, 0.0), d))
        return held[:self.top_n]

    def _pick_target(self) -> Optional[str]:
        """Least-loaded healthy peer by gossiped held-lease counts —
        a freshly joined host has load 0 and becomes the preferred
        target, which is exactly scale-out under load."""
        node = self.node
        self_load = node.leases.held_count()
        best: Optional[Tuple[int, str]] = None
        for m in node.membership.universe():
            if m == node.self_id or not node.table.is_healthy(m):
                continue
            load = int(node.peer_load.get(m, 0))
            if load + self.min_load_gap > self_load:
                continue
            if best is None or (load, m) < best:
                best = (load, m)
        return best[1] if best is not None else None

    def _pick_members(self, n: int) -> List[str]:
        """Up to `n` co-writer candidates, least-loaded first. Unlike
        `_pick_target` there is no load-gap damper: splitting does not
        move the doc, it only shares its write path, so any healthy
        peer helps."""
        node = self.node
        ranked = sorted(
            (int(node.peer_load.get(m, 0)), m)
            for m in node.membership.universe()
            if m != node.self_id and node.table.is_healthy(m))
        return [m for _load, m in ranked[:n]]

    # ---- migration -------------------------------------------------------

    def migrate(self, doc_id: str, target: str) -> bool:
        """One live migration: override first (shipped on the grant so
        the target keeps the doc), then the epoch-fenced handoff; on
        failure the handoff aborts back to ACTIVE at the source and the
        override is tombstoned. Returns True on a completed move."""
        node = self.node
        metrics = node.metrics
        metrics.bump("rebalance", "migrations_started")
        self._last_attempt[doc_id] = node.clock()
        ver = node.overrides.set(doc_id, target)
        ok = node.handoff(doc_id, target, override_version=ver)
        if ok:
            metrics.bump("rebalance", "migrations_completed")
            if node.obs is not None:
                node.obs.recorder.record("rebalance_migrated",
                                         doc=doc_id, to=target,
                                         override_version=ver)
            self._park_source_copy(doc_id)
            return True
        # rollback: lease already rolled back to ACTIVE (same epoch) by
        # abort_handoff inside node.handoff; retract the override so
        # routing stays at the source
        node.overrides.clear(doc_id)
        metrics.bump("rebalance", "migrations_aborted")
        if node.obs is not None:
            node.obs.recorder.record("rebalance_aborted", doc=doc_id,
                                     to=target)
        return False

    def _park_source_copy(self, doc_id: str) -> None:
        """Residency half of the move: the source's warm copy goes back
        to its snapshot+WAL home (the target hydrates its own). Best
        effort — the doc stays servable for follower reads either way."""
        sched = getattr(self.node.store, "scheduler", None)
        hydrator = getattr(sched, "hydrator", None) \
            if sched is not None else None
        if hydrator is None:
            return
        try:
            hydrator.evict_to_snapshot(doc_id)
        except Exception:       # pragma: no cover - eviction is advisory
            pass

    # ---- the loop --------------------------------------------------------

    def tick(self) -> dict:
        """One control-loop evaluation. Returns a small report dict
        (soaks fold it into their round logs). Planning happens under
        the rebalance lock; migrations AND group promotions/demotions
        (network + lease lock) run strictly outside it."""
        out = {"stressed": [], "migrated": [], "aborted": [],
               "promoted": [], "demoted": []}
        if not self.enabled or self.node.rejoining:
            return out
        plan: List[Tuple[str, str]] = []
        promote_plan: List[Tuple[str, List[str]]] = []
        demote_plan: List[str] = []
        node = self.node
        groups = getattr(node, "writergroups", None)
        with self._rebalance_lock:
            stressed = self._stressed()
            out["stressed"] = stressed
            now = node.clock()
            scores = self._attrib_scores() if stressed else {}
            offenders = self._offenders(scores) if stressed else []
            led = {d for d, g in groups.entries()
                   if g.leader == node.self_id} \
                if groups is not None else set()
            if self.split_hot_docs and groups is not None:
                total = sum(scores.values())
                hot = {d for d in offenders
                       if total > 0.0 and scores.get(d, 0.0)
                       >= self.promote_min_share * total}
                for d in list(self._hot_ticks):
                    if d not in hot:
                        self._hot_ticks.pop(d, None)
                for doc_id in sorted(hot):
                    if doc_id in led:
                        self._group_hot[doc_id] = now
                        continue
                    ticks = self._hot_ticks.get(doc_id, 0) + 1
                    self._hot_ticks[doc_id] = ticks
                    if ticks >= self.promote_after_ticks:
                        members = self._pick_members(
                            self.group_size - 1)
                        if members:
                            promote_plan.append((doc_id, members))
                for doc_id in sorted(led):
                    if doc_id in hot:
                        continue
                    last = self._group_hot.get(doc_id, 0.0)
                    if now - last >= self.demote_after_s:
                        demote_plan.append(doc_id)
            if stressed:
                target = self._pick_target()
                if target is not None:
                    # group-led docs are pinned to their leader, and a
                    # doc accumulating toward promotion splits rather
                    # than migrates — moving the burn is not fixing it
                    skip = led | {d for d, _m in promote_plan}
                    if self.split_hot_docs:
                        skip |= set(self._hot_ticks)
                    for doc_id in offenders:
                        if len(plan) >= self.max_migrations_per_tick:
                            break
                        if doc_id in skip:
                            continue    # group-led docs are pinned
                        last = self._last_attempt.get(doc_id, 0.0)
                        if now - last < self.cooldown_s:
                            continue
                        plan.append((doc_id, target))
        for doc_id, members in promote_plan:
            if node.promote_writer_group(doc_id, members):
                out["promoted"].append([doc_id, members])
                self._group_hot[doc_id] = node.clock()
                self._hot_ticks.pop(doc_id, None)
        for doc_id in demote_plan:
            if node.can_demote(doc_id) \
                    and node.demote_writer_group(doc_id):
                out["demoted"].append(doc_id)
                self._group_hot.pop(doc_id, None)
        for doc_id, target in plan:
            if self.migrate(doc_id, target):
                out["migrated"].append([doc_id, target])
            else:
                out["aborted"].append([doc_id, target])
        return out


def attach_rebalancer(node, obs=None, **opts) -> Rebalancer:
    """Hang a Rebalancer on a ReplicaNode (node.rebalancer); the node's
    probe/maintain loop ticks it. Mirrors attach_replication's shape."""
    rb = Rebalancer(node, obs=obs, **opts)
    node.rebalancer = rb
    return rb
