"""Cross-host replication: peer mesh, doc-ownership leases, anti-entropy.

The serve/ scheduler made one process own many documents across many
chips; this package makes N *processes* (sync-server instances) jointly
own the document space. The wire format is the one the single server
already speaks — version summaries (`causalgraph/summary.py`) plus v1
binary patches — reused verbatim for inter-server anti-entropy, so a
peer is just another sync client with a lease protocol on top.

Layers (each its own module, composed by `node.ReplicaNode`):

  peers.py        peer table (seeded + dynamic add/remove), health
                  probes, consecutive-failure circuit breaker,
                  jittered exponential `Backoff`, gossip piggyback on
                  ping, timeout on every HTTP call
  membership.py   dynamic membership view: join/leave/suspect/dead
                  states, incarnation refutation, the rendezvous
                  universe and the quorum voter set
  ownership.py    doc-ownership leases on top of rendezvous placement
                  extended to hosts (same blake2b scheme as
                  serve/router.py), epoch fencing floors, the voter
                  promise table, and an explicit handoff protocol
  quorum.py       majority promise rounds (at most one ACTIVE lease
                  per (doc, epoch)) + the crash-durable ReplicaJournal
                  on the storage/ Wal + PageStore primitives
  antientropy.py  background reconciliation: summary exchange + binary
                  patch pull/push for divergent docs
  faults.py       deterministic fault injection (drop / delay /
                  duplicate / asymmetric partition / link latency /
                  clock skew, by seed) for tests + soak
  metrics.py      replication counters merged into `GET /metrics`
  node.py         ReplicaNode — wires the above to a DocStore
  soak.py         in-process N-server soak driver (`cli replicate-soak`)
"""

from .faults import FaultDrop, FaultInjector
from .membership import MembershipView
from .metrics import ReplicationMetrics
from .node import ReplicaNode, attach_replication
from .ownership import LeaseManager, owner_of
from .peers import Backoff, CircuitOpen, PeerTable, call_with_retries
from .quorum import QuorumCoordinator, ReplicaJournal

__all__ = [
    "Backoff", "CircuitOpen", "FaultDrop", "FaultInjector",
    "LeaseManager", "MembershipView", "PeerTable", "QuorumCoordinator",
    "ReplicaJournal", "ReplicaNode", "ReplicationMetrics",
    "attach_replication", "call_with_retries", "owner_of",
]
