"""Anti-entropy: background convergence between peer servers.

Each round, for each healthy peer, exchange doc lists and version
summaries (`summarize_versions` / `intersect_with_summary` — the exact
handshake `SyncClient` already speaks) and move v1 binary patches for
divergent docs:

  * pull — the peer has ops we lack (`intersect_with_summary` returned
    a remainder): POST our summary to its `/doc/{id}/pull`, decode the
    patch into the local oplog;
  * push — we have ops past the common frontier: encode a patch from
    `common` and POST it to the peer's `/doc/{id}/push` (symmetric, so
    one round converges a pair instead of waiting for the peer's own
    pull pass).

Ownership is irrelevant here on purpose: NON-owners converge too, so a
dead owner's docs are recoverable — the rendezvous successor already
holds the bytes when it takes the lease over. Scheduler merge work
stays owner-only via the admit gate; a pulled patch on a non-owner just
lands in the oplog (host state), no device merge.

Doc-list responses piggyback lease claims, which keeps every host's
lease view fresh without a separate gossip channel. They also
piggyback per-doc frontiers, and an advertised frontier EQUAL to ours
short-circuits the whole per-doc handshake — a frontier uniquely
names its causal downset, so equal frontiers mean nothing to exchange.
Most docs are idle in any given round, which makes this the wire
tier's single biggest bandwidth lever.

Transport rides the wire tier when the peer negotiated it (binary
SUMMARY frames both ways, lz4 PATCH frames, and one SNAPSHOT frame
instead of a patch replay for a peer lagging past the snapshot
threshold); JSON + raw-patch fallback otherwise. Every request body
sent here lands in the `antientropy` wire channel accounting — framed
or not — so before/after scorecards stay comparable.
"""

from __future__ import annotations

import threading
import time
import urllib.error
from typing import Dict, List, Optional

from ..causalgraph.summary import intersect_with_summary, \
    summarize_versions
from ..encoding.decode import decode_into
from ..encoding.encode import ENCODE_PATCH, encode_oplog
from ..wire.frames import (FRAME_DOCS, FRAME_PATCH, FRAME_SUMMARY,
                           WIRE_HEADER, WireError, decode_docs,
                           decode_frame, decode_summary, encode_frame,
                           encode_summary, is_frame)
from ..wire.snapshot import build_snapshot, should_ship_snapshot


class AntiEntropy:
    def __init__(self, node, interval_s: float = 0.5, push: bool = True,
                 max_docs_per_round: Optional[int] = None) -> None:
        self.node = node                  # ReplicaNode (duck-typed)
        self.interval_s = interval_s
        self.push = push
        self.max_docs_per_round = max_docs_per_round
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- one round -------------------------------------------------------

    def run_round(self, peer_id: Optional[str] = None) -> dict:
        """Reconcile with one peer (or every currently-healthy peer).
        Never raises: per-doc failures are counted and the round moves
        on — a flaky link degrades convergence speed, not the loop."""
        node = self.node
        t0 = time.monotonic()
        peers = [peer_id] if peer_id is not None \
            else [p for p in node.table.peer_ids()
                  if node.table.is_healthy(p)]
        # writer-group co-members reconcile FIRST: a split hot doc's
        # in-group visibility lag is the one convergence path user
        # writes now depend on, so it gets the front of every round
        groups = getattr(node, "writergroups", None)
        co = groups.peer_set() if groups is not None else frozenset()
        if co:
            peers.sort(key=lambda p: (p not in co, p))
        report = {"peers": {}, "pulled": 0, "pushed": 0, "errors": 0}
        for p in peers:
            rep = self._round_with(p)
            report["peers"][p] = rep
            report["pulled"] += rep["pulled"]
            report["pushed"] += rep["pushed"]
            report["errors"] += rep["errors"]
        node.metrics.bump("antientropy", "rounds")
        node.metrics.observe_latency("antientropy_round",
                                     time.monotonic() - t0)
        return report

    def _round_with(self, peer_id: str) -> dict:
        node = self.node
        rep = {"docs": 0, "pulled": 0, "pushed": 0, "errors": 0}
        # advert timestamp: stamped BEFORE the request so it is a
        # conservative lower bound on "when the peer was in this state"
        t0 = time.monotonic()
        try:
            listing = self._fetch_listing(peer_id)
        except (OSError, ValueError, urllib.error.HTTPError):
            node.metrics.bump("antientropy", "errors")
            rep["errors"] += 1
            return rep
        remote_docs = listing.get("docs") or {}
        remote_frontiers = {}
        reads = getattr(node.store, "reads", None)
        # piggybacked lease claims keep the lease view fresh
        for doc_id, info in remote_docs.items():
            lease = (info or {}).get("lease")
            if lease:
                node.leases.observe_remote(
                    doc_id, lease["holder"], int(lease["epoch"]),
                    lease.get("state", "active"),
                    float(lease.get("ttl_s", 0.0)))
            # piggybacked frontier advertisement feeds the
            # follower-read staleness contract (read/follower.py);
            # only an advert from the doc's lease HOLDER proves
            # owner-side freshness, so record the peer's own frontier
            frontier = (info or {}).get("frontier")
            if frontier:
                remote_frontiers[doc_id] = frontier
            if reads is not None and frontier:
                reads.index.note_advert(doc_id, peer_id, frontier,
                                        as_of=t0)
                node.metrics.bump("antientropy", "frontier_adverts")
        doc_ids = sorted(set(remote_docs) | set(node.store.doc_ids()))
        if self.max_docs_per_round is not None:
            doc_ids = doc_ids[:self.max_docs_per_round]
        for doc_id in doc_ids:
            try:
                # frontier short-circuit: the peer advertised this
                # doc's frontier on the listing, and it equals ours —
                # equal frontiers imply identical causal downsets, so
                # the summary/pull/push round trip would move nothing.
                # Part of the wire tier: a node pinned to JSON
                # (DT_WIRE_DISABLED) reproduces the pre-wire protocol
                # exactly, which is what before/after baselines diff.
                adv = remote_frontiers.get(doc_id)
                if adv is not None and node.wire.enabled \
                        and self._frontier_matches(doc_id, adv):
                    node.metrics.bump("antientropy", "docs_skipped")
                    rep["docs"] += 1
                    if reads is not None:
                        reads.index.note_reconciled(doc_id, peer_id,
                                                    as_of=t0)
                    continue
                r = self._reconcile_doc(peer_id, doc_id)
                rep["docs"] += 1
                rep["pulled"] += r["pulled"]
                rep["pushed"] += r["pushed"]
            except (OSError, ValueError, KeyError,
                    urllib.error.HTTPError):
                node.metrics.bump("antientropy", "errors")
                rep["errors"] += 1
        return rep

    def _frontier_matches(self, doc_id: str, advert) -> bool:
        """Is the peer's advertised remote frontier identical to ours?
        Never materializes an absent doc (an advertised doc we lack
        must reconcile, not spring into existence here)."""
        store = self.node.store
        with store.lock:
            ol = store.docs.get(doc_id)
            if ol is None:
                return False
            local = ol.cg.local_to_remote_frontier(ol.version)
        return sorted(map(tuple, local)) == sorted(map(tuple, advert))

    def _fetch_listing(self, peer_id: str) -> dict:
        """GET the peer's doc listing — a DOCS frame when it honors the
        `X-DT-Wire` advert, JSON from old peers; the response magic
        decides, exactly like `_fetch_summary`."""
        node = self.node
        hdrs = None
        hv = node.wire.header_value()
        if hv is not None:
            hdrs = {WIRE_HEADER: hv}
        _st, body = node.table.call(peer_id, "/replicate/docs",
                                    headers=hdrs)
        if is_frame(body):
            ftype, payload = decode_frame(body)
            if ftype != FRAME_DOCS:
                raise WireError(f"expected docs frame, got {ftype}")
            return decode_docs(payload)
        import json
        return json.loads(body)

    def _fetch_summary(self, peer_id: str, doc_id: str) -> dict:
        """GET the peer's version summary — framed when it honors the
        `X-DT-Wire` advert, JSON from old peers; the response magic
        decides, so no capability cache is needed on the GET side."""
        node = self.node
        hdrs = None
        hv = node.wire.header_value()
        if hv is not None:
            hdrs = {WIRE_HEADER: hv}
        _st, body = node.table.call(
            peer_id, f"/doc/{doc_id}/summary", headers=hdrs)
        if is_frame(body):
            ftype, payload = decode_frame(body)
            if ftype != FRAME_SUMMARY:
                raise WireError(f"expected summary frame, got {ftype}")
            return decode_summary(payload)
        import json
        return json.loads(body)

    def _reconcile_doc(self, peer_id: str, doc_id: str) -> dict:
        """Summary handshake + patch/snapshot exchange for one doc."""
        import json
        node = self.node
        store = node.store
        node.metrics.bump("antientropy", "docs_checked")
        # reconcile timestamp: a COMPLETED handshake proves the local
        # oplog covers everything the peer had as of the round start
        t0 = time.monotonic()
        remote_summary = self._fetch_summary(peer_id, doc_id)
        ol = store.get(doc_id)
        wire_peer = node.wire.use_wire(peer_id)
        with store.lock:
            common, remainder = intersect_with_summary(
                ol.cg, remote_summary)
            local_summary = summarize_versions(ol.cg)
            # anything of ours past the common frontier, the peer
            # lacks. A peer lagging past the snapshot threshold gets
            # one compacted snapshot frame instead of a patch replay
            # (built outside the lock, frontier-keyed cache).
            push_patch = None
            ship_snapshot = False
            snap_key = ()
            if self.push and sorted(common) != sorted(ol.version):
                if wire_peer and should_ship_snapshot(
                        ol.cg, list(ol.version), common,
                        node.wire.snapshot_ops_threshold):
                    ship_snapshot = True
                    snap_key = tuple(sorted(map(
                        tuple,
                        ol.cg.local_to_remote_frontier(ol.version))))
                else:
                    push_patch = encode_oplog(ol, ENCODE_PATCH,
                                              from_version=common)
        if ship_snapshot:
            hyd = getattr(getattr(store, "scheduler", None),
                          "hydrator", None)
            tstore = getattr(hyd, "store", None)
            push_patch = node.wire.cached_snapshot(
                doc_id, snap_key,
                lambda: build_snapshot(ol, store=tstore, doc_id=doc_id,
                                       oplog_lock=store.lock))
        out = {"pulled": 0, "pushed": 0}
        if remainder:
            from ..obs.trace import NOOP_SPAN, TRACE_HEADER
            obs = getattr(node, "obs", None)
            span = NOOP_SPAN
            hdrs = None
            if obs is not None:
                span = obs.tracer.start(
                    "repl.ae_pull", attrs={"peer": peer_id,
                                           "doc": doc_id})
                if span.sampled:
                    hdrs = {TRACE_HEADER: span.header()}
            # pull request: our summary, framed for a v1 peer; the
            # X-DT-Wire advert asks for a framed (lz4) patch back
            pull_body = json.dumps(local_summary).encode("utf8")
            framed = False
            if wire_peer:
                f = encode_frame(FRAME_SUMMARY,
                                 encode_summary(local_summary),
                                 compress=True)
                if len(f) < len(pull_body):
                    pull_body, framed = f, True
            hv = node.wire.header_value()
            if hv is not None:
                hdrs = dict(hdrs or {})
                hdrs[WIRE_HEADER] = hv
            _st, patch = node.table.call(
                peer_id, f"/doc/{doc_id}/pull", data=pull_body,
                headers=hdrs)
            node.wire.account(
                "antientropy", sent_bytes=len(pull_body),
                json_bytes=len(json.dumps(local_summary)
                               .encode("utf8")) if framed else None,
                framed=framed)
            span.end(bytes=len(patch))
            recv_len = len(patch)
            if is_frame(patch):
                ftype, patch = decode_frame(patch)
                if ftype != FRAME_PATCH:
                    raise WireError(f"expected patch frame, {ftype}")
            with store.lock:
                pre_len = len(ol)
                decode_into(ol, patch)
                n_new = len(ol) - pre_len
            node.metrics.bump("antientropy", "docs_pulled")
            node.metrics.bump("antientropy", "bytes_pulled", recv_len)
            out["pulled"] = 1
            if n_new:
                store.mark_dirty(doc_id)
                store.notify(doc_id)
                # owner-gated: on a non-owner the admit gate denies and
                # the ops stay host-side until the lease moves here
                store.submit_merge(doc_id, n_new)
        reads = getattr(store, "reads", None)
        if reads is not None:
            if out["pulled"]:
                # the doc's tip moved under us: drop cached checkouts
                reads.on_antientropy_apply(doc_id)
            # pull (or no remainder at all) completed: local state now
            # dominates the peer's as of t0
            reads.index.note_reconciled(doc_id, peer_id, as_of=t0)
        if push_patch is not None:
            from ..obs.trace import NOOP_SPAN, TRACE_HEADER
            obs = getattr(node, "obs", None)
            span = NOOP_SPAN
            # X-DT-Replication marks the patch as host-targeted
            # anti-entropy traffic: the peer applies it locally instead
            # of routing it through the mutation proxy (which would
            # bounce an owner-pushed patch straight back to the owner,
            # a 200 no-op that converges nothing)
            hdrs = {"X-DT-Replication": "1"}
            # a raw v1 patch is already binary; the PATCH frame only
            # replaces it when lz4 actually wins. Snapshots are born
            # framed (build_snapshot) and count as one snapshot ship.
            send = push_patch
            framed = ship_snapshot
            if not ship_snapshot and wire_peer:
                f = encode_frame(FRAME_PATCH, push_patch,
                                 compress=True)
                if len(f) < len(push_patch):
                    send, framed = f, True
            if obs is not None:
                span = obs.tracer.start(
                    "repl.ae_push", attrs={"peer": peer_id,
                                           "doc": doc_id,
                                           "bytes": len(send),
                                           "snapshot": ship_snapshot})
                if span.sampled:
                    hdrs[TRACE_HEADER] = span.header()
            t_push = time.monotonic()
            st, _body = node.table.call(peer_id, f"/doc/{doc_id}/push",
                                        data=send, headers=hdrs)
            node.metrics.observe_latency("ae_ship",
                                         time.monotonic() - t_push)
            node.wire.account(
                "antientropy", sent_bytes=len(send),
                json_bytes=len(push_patch)
                if framed and not ship_snapshot else None,
                framed=framed, snapshot=ship_snapshot)
            span.end(status=st)
            node.metrics.bump("antientropy", "docs_pushed")
            node.metrics.bump("antientropy", "bytes_pushed", len(send))
            out["pushed"] = 1
            if obs is not None and st == 200:
                # journey (owner-side bookkeeping of peer facts): the
                # patch left this host AND the peer acknowledged
                # applying it — one round trip observes both stages
                obs.journey.stamp_doc(doc_id, "ae_shipped",
                                      peer=peer_id, t=t_push)
                obs.journey.stamp_doc(doc_id, "applied_at_peer",
                                      peer=peer_id)
        return out

    # ---- background loop -------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.run_round()
                except Exception:    # pragma: no cover - keep running
                    pass

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self._stop = threading.Event()
