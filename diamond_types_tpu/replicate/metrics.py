"""Replication counters, merged into the sync server's `GET /metrics`.

Same philosophy as serve/metrics.py: plain host-side ints behind one
small lock, recording never touches the network or the device. The
snapshot carries a `version` field so soak/bench scrapers can detect
counter-set changes across PRs.

Changelog:
  v8  writer groups: new `writergroup` group — hot-doc write splitting
      (`promotions`, `demotions`, `demote_aborts`, `member_grants`,
      `member_admits`, `renewals`, `renewal_denials`, `self_fenced`,
      `stale_installs_rejected`, plus `active_groups` /
      `member_entries` injected by the node at snapshot time).
      Exported as `dt_repl_writergroup_*` prom families like every
      other group.
  v7  wire tier: new `wire` group — per-channel transport accounting
      (`{channel}_{bytes_sent,bytes_saved,frames,snapshot_ships}` for
      the antientropy / proxy / hydrate / gossip channels, exported as
      dedicated `dt_wire_*` prom families). Counts every send, framed
      or JSON fallback, so before/after scorecards stay comparable.
      Also `antientropy.docs_skipped` — per-doc handshakes elided by
      the frontier short-circuit (equal advertised frontier).
  v6  `ae_ship` latency histogram — per-peer anti-entropy push round
      trip (encode→200), the owner-side half of the edit-to-visibility
      journey (obs/journey.py stamps ae_shipped/applied_at_peer off
      the same call).
  v5  elastic-mesh rebalancer: new `rebalance` group (overrides
      set/cleared/merged, migrations started/completed/aborted, and
      `override_table_size` injected by the node at snapshot time),
      `antientropy.adverts_relayed` (follower→follower frontier advert
      relay), and a seeded `rebalance_drain` latency histogram (the
      drain phase of a live migration).
  v4  `antientropy.frontier_adverts` — owner frontier advertisements
      folded into the follower-read tier's FollowerIndex (from ping
      gossip and `/replicate/docs` piggybacks; read/follower.py).
  v3  latency observations moved onto obs.hist log-bucketed
      histograms. `handoffs.latency_s_total/latency_s_max` are now
      DERIVED from the handoff histogram (kept so schema-v2 scrapers
      keep working); the new `latencies` group carries full histogram
      snapshots (count/sum/max/p50/p90/p99/buckets) for `handoff`,
      `quorum_round`, `probe`, and `antientropy_round`.
  v2  quorum / fencing / membership groups, `leases.tie_breaks`,
      `proxy.fenced_relays`, membership_view + quorum_view objects
      (the partition-safety PR).

Schema (snapshot()):

  {"version": 5, "self": "host:port",
   "leases": {"held", "acquires", "renewals", "takeovers", "releases",
              "tie_breaks",        # equal-epoch conflicts arbitrated
              "churn"},            # churn = acquires+takeovers+releases
   "handoffs": {"started", "completed", "failed",
                "latency_s_total", "latency_s_max"},
   "antientropy": {"rounds", "docs_checked", "docs_skipped",
                   "docs_pulled", "docs_pushed", "bytes_pulled",
                   "bytes_pushed", "errors", "frontier_adverts",
                   "adverts_relayed"},
   "rebalance": {"overrides_set", "overrides_cleared",
                 "override_merges", "migrations_started",
                 "migrations_completed", "migrations_aborted",
                 "override_table_size"},  # size injected at snapshot
   "proxy": {"proxied", "fallback_local", "loops_refused",
             "fenced_relays"},     # 409-fenced proxies retried locally
   "merge_gate": {"admits", "denials"},
   "probes": {"ok", "failed", "circuit_opens", "circuit_closes"},
   "quorum": {"proposals", "acks", "denials", "rounds_won",
              "rounds_lost", "promise_conflicts",
              "rejoins_completed"},
   "fencing": {"rejected_writes",       # proxied writes 409'd as stale
               "stale_lease_revoked",   # own ACTIVE lease below floor
               "rejoin_denials"},       # merges denied while rejoining
   "membership": {"joins", "leaves", "suspicions", "refutations",
                  "deaths"},
   "wire": {f"{channel}_{key}"      # channel x key, flat
            for channel in ("antientropy", "proxy", "hydrate", "gossip")
            for key in ("bytes_sent", "bytes_saved", "frames",
                        "snapshot_ships")},
   "latencies": {"handoff": hist, "quorum_round": hist,
                 "probe": hist, "antientropy_round": hist,
                 "rebalance_drain": hist, "ae_ship": hist},
   "per_peer": {peer_id: {"consecutive_failures", "circuit_open",
                          "backoff_s", "last_ok_age_s"}},
   "membership_view": {"view_version", "members": {...}} | null,
   "quorum_view": {"voters", "quorum", "rejoining"} | null,
   "faults": injector counters | null}
"""

from __future__ import annotations

import threading
from typing import Dict

from ..obs.hist import Histogram
from ..wire.frames import WIRE_CHANNELS, WIRE_KEYS

_LATENCY_NAMES = ("handoff", "quorum_round", "probe",
                  "antientropy_round", "rebalance_drain", "ae_ship")

_GROUPS = {
    "leases": ("acquires", "renewals", "takeovers", "releases",
               "tie_breaks"),
    "handoffs": ("started", "completed", "failed"),
    "antientropy": ("rounds", "docs_checked", "docs_skipped",
                    "docs_pulled", "docs_pushed", "bytes_pulled",
                    "bytes_pushed", "errors", "frontier_adverts",
                    "adverts_relayed"),
    "rebalance": ("overrides_set", "overrides_cleared",
                  "override_merges", "migrations_started",
                  "migrations_completed", "migrations_aborted"),
    "proxy": ("proxied", "fallback_local", "loops_refused",
              "fenced_relays"),
    "merge_gate": ("admits", "denials"),
    "probes": ("ok", "failed", "circuit_opens", "circuit_closes"),
    "quorum": ("proposals", "acks", "denials", "rounds_won",
               "rounds_lost", "promise_conflicts",
               "rejoins_completed"),
    "fencing": ("rejected_writes", "stale_lease_revoked",
                "rejoin_denials"),
    "membership": ("joins", "leaves", "suspicions", "refutations",
                   "deaths"),
    "wire": tuple(f"{c}_{k}" for c in WIRE_CHANNELS for k in WIRE_KEYS),
    "writergroup": ("promotions", "demotions", "demote_aborts",
                    "member_grants", "member_admits", "renewals",
                    "renewal_denials", "self_fenced",
                    "stale_installs_rejected"),
}


class ReplicationMetrics:
    # v7 -> v8: writer-group hot-doc split counters (see changelog)
    SCHEMA_VERSION = 8

    def __init__(self, self_id: str = "") -> None:
        self.self_id = self_id
        self._lock = threading.Lock()
        self._c: Dict[str, Dict[str, int]] = {
            g: {k: 0 for k in keys} for g, keys in _GROUPS.items()}
        self.hist: Dict[str, Histogram] = {
            n: Histogram() for n in _LATENCY_NAMES}
        # live-telemetry double-write target (obs TimeSeries), wired by
        # attach_replication when the server carries an obs bundle
        self.ts = None

    def bump(self, group: str, key: str, n: int = 1) -> None:
        with self._lock:
            self._c[group][key] += n
        if self.ts is not None:
            self.ts.inc(f"repl.{group}.{key}", n)

    def get(self, group: str, key: str) -> int:
        with self._lock:
            return self._c[group][key]

    def observe_latency(self, name: str, seconds: float) -> None:
        h = self.hist.get(name)
        if h is None:
            with self._lock:
                h = self.hist.setdefault(name, Histogram())
        h.record(seconds)
        if self.ts is not None:
            self.ts.observe(f"repl.{name}", seconds)

    def observe_handoff_latency(self, seconds: float) -> None:
        self.observe_latency("handoff", seconds)

    def bump_wire(self, channel: str, key: str, n: int = 1) -> None:
        """One wire-tier count: ``channel`` in WIRE_CHANNELS, ``key``
        in WIRE_KEYS — flattened into the ``wire`` group."""
        self.bump("wire", f"{channel}_{key}", n)

    def wire_counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._c["wire"])

    def snapshot(self, leases_held: int = 0, per_peer: dict = None,
                 faults: dict = None, membership_view: dict = None,
                 quorum_view: dict = None,
                 override_table_size: int = 0,
                 writergroup_sizes: dict = None) -> dict:
        # histograms carry their own locks; snapshot before taking ours
        latencies = {n: h.snapshot() for n, h in
                     sorted(self.hist.items())}
        handoff = latencies["handoff"]
        with self._lock:
            leases = dict(self._c["leases"])
            leases["held"] = leases_held
            leases["churn"] = (leases["acquires"] + leases["takeovers"]
                               + leases["releases"])
            handoffs = dict(self._c["handoffs"])
            # v2-compat keys, now derived from the histogram
            handoffs["latency_s_total"] = handoff["sum"]
            handoffs["latency_s_max"] = handoff["max"]
            rebalance = dict(self._c["rebalance"])
            rebalance["override_table_size"] = int(override_table_size)
            writergroup = dict(self._c["writergroup"])
            for k, v in (writergroup_sizes or {}).items():
                writergroup[k] = int(v)
            return {
                "version": self.SCHEMA_VERSION,
                "self": self.self_id,
                "leases": leases,
                "handoffs": handoffs,
                "antientropy": dict(self._c["antientropy"]),
                "rebalance": rebalance,
                "proxy": dict(self._c["proxy"]),
                "merge_gate": dict(self._c["merge_gate"]),
                "probes": dict(self._c["probes"]),
                "quorum": dict(self._c["quorum"]),
                "fencing": dict(self._c["fencing"]),
                "membership": dict(self._c["membership"]),
                "wire": dict(self._c["wire"]),
                "writergroup": writergroup,
                "latencies": latencies,
                "per_peer": per_peer or {},
                "membership_view": membership_view,
                "quorum_view": quorum_view,
                "faults": faults,
            }
