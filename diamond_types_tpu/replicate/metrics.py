"""Replication counters, merged into the sync server's `GET /metrics`.

Same philosophy as serve/metrics.py: plain host-side ints behind one
small lock, recording never touches the network or the device. The
snapshot carries a `version` field so soak/bench scrapers can detect
counter-set changes across PRs.

Schema (snapshot()) — v2 adds the quorum / fencing / membership groups
and `leases.tie_breaks` (the partition-safety PR):

  {"version": 2, "self": "host:port",
   "leases": {"held", "acquires", "renewals", "takeovers", "releases",
              "tie_breaks",        # equal-epoch conflicts arbitrated
              "churn"},            # churn = acquires+takeovers+releases
   "handoffs": {"started", "completed", "failed",
                "latency_s_total", "latency_s_max"},
   "antientropy": {"rounds", "docs_checked", "docs_pulled",
                   "docs_pushed", "bytes_pulled", "bytes_pushed",
                   "errors"},
   "proxy": {"proxied", "fallback_local", "loops_refused",
             "fenced_relays"},     # 409-fenced proxies retried locally
   "merge_gate": {"admits", "denials"},
   "probes": {"ok", "failed", "circuit_opens", "circuit_closes"},
   "quorum": {"proposals", "acks", "denials", "rounds_won",
              "rounds_lost", "promise_conflicts",
              "rejoins_completed"},
   "fencing": {"rejected_writes",       # proxied writes 409'd as stale
               "stale_lease_revoked",   # own ACTIVE lease below floor
               "rejoin_denials"},       # merges denied while rejoining
   "membership": {"joins", "leaves", "suspicions", "refutations",
                  "deaths"},
   "per_peer": {peer_id: {"consecutive_failures", "circuit_open",
                          "backoff_s", "last_ok_age_s"}},
   "membership_view": {"view_version", "members": {...}} | null,
   "quorum_view": {"voters", "quorum", "rejoining"} | null,
   "faults": injector counters | null}
"""

from __future__ import annotations

import threading
from typing import Dict

_GROUPS = {
    "leases": ("acquires", "renewals", "takeovers", "releases",
               "tie_breaks"),
    "handoffs": ("started", "completed", "failed"),
    "antientropy": ("rounds", "docs_checked", "docs_pulled",
                    "docs_pushed", "bytes_pulled", "bytes_pushed",
                    "errors"),
    "proxy": ("proxied", "fallback_local", "loops_refused",
              "fenced_relays"),
    "merge_gate": ("admits", "denials"),
    "probes": ("ok", "failed", "circuit_opens", "circuit_closes"),
    "quorum": ("proposals", "acks", "denials", "rounds_won",
               "rounds_lost", "promise_conflicts",
               "rejoins_completed"),
    "fencing": ("rejected_writes", "stale_lease_revoked",
                "rejoin_denials"),
    "membership": ("joins", "leaves", "suspicions", "refutations",
                   "deaths"),
}


class ReplicationMetrics:
    # v1 -> v2: quorum / fencing / membership groups, leases.tie_breaks,
    # proxy.fenced_relays, membership_view + quorum_view objects
    SCHEMA_VERSION = 2

    def __init__(self, self_id: str = "") -> None:
        self.self_id = self_id
        self._lock = threading.Lock()
        self._c: Dict[str, Dict[str, int]] = {
            g: {k: 0 for k in keys} for g, keys in _GROUPS.items()}
        self._handoff_latency_total = 0.0
        self._handoff_latency_max = 0.0

    def bump(self, group: str, key: str, n: int = 1) -> None:
        with self._lock:
            self._c[group][key] += n

    def get(self, group: str, key: str) -> int:
        with self._lock:
            return self._c[group][key]

    def observe_handoff_latency(self, seconds: float) -> None:
        with self._lock:
            self._handoff_latency_total += seconds
            if seconds > self._handoff_latency_max:
                self._handoff_latency_max = seconds

    def snapshot(self, leases_held: int = 0, per_peer: dict = None,
                 faults: dict = None, membership_view: dict = None,
                 quorum_view: dict = None) -> dict:
        with self._lock:
            leases = dict(self._c["leases"])
            leases["held"] = leases_held
            leases["churn"] = (leases["acquires"] + leases["takeovers"]
                               + leases["releases"])
            handoffs = dict(self._c["handoffs"])
            handoffs["latency_s_total"] = round(
                self._handoff_latency_total, 6)
            handoffs["latency_s_max"] = round(
                self._handoff_latency_max, 6)
            return {
                "version": self.SCHEMA_VERSION,
                "self": self.self_id,
                "leases": leases,
                "handoffs": handoffs,
                "antientropy": dict(self._c["antientropy"]),
                "proxy": dict(self._c["proxy"]),
                "merge_gate": dict(self._c["merge_gate"]),
                "probes": dict(self._c["probes"]),
                "quorum": dict(self._c["quorum"]),
                "fencing": dict(self._c["fencing"]),
                "membership": dict(self._c["membership"]),
                "per_peer": per_peer or {},
                "membership_view": membership_view,
                "quorum_view": quorum_view,
                "faults": faults,
            }
