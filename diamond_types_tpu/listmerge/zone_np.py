"""Host-reference executor for device origin extraction (NumPy).

This is the sequential-entry, batched-within-entry merge algorithm that
tpu/zone_kernel.py lowers to one lax.scan. Everything here is the exact
computation the device runs — kept in NumPy as (a) the correctness oracle
for the kernel and (b) the documentation of the algorithm.

The merge engine family it joins (all byte-identical on the corpora):
  M1 Python/C++ (tracker walk), fork/join dense (plan2 + state matrix),
  device tape (plan_kernels) — and now this: a per-CHAR engine where the
  host does only plan compilation + entry composition (compose.py) and the
  whole conflict zone resolves origins against state rows.

Per-char state (W = prefix chars + zone insert chars):
  state [n_idx, W] u8   0 NotInsertedYet / 1 Inserted / 2 Deleted lattice
  rank  [W]             current document-order rank; unplaced = sentinel
  ord   [m]             rank -> char slot (prefix chars pre-placed)
  ever  [W] u8          ever-deleted flag (final visibility = ever == 0)
  p_id/sd/ol_id/orr_id  fugue-tree metadata per placed char, used by the
                        YjsMod sibling window scan of later entries

Per entry (one plan APPLY): resolve the composed queries against the
entry's state row with two prefix sums (origin_left = c'th visible char,
origin_right = next non-NIY — reference: merge.rs:395-423), place each
block with the vectorized sibling stop-scan (reference: integrate,
merge.rs:154-278 — the stop conditions mirror the Fugue-tree sibling sort
of tpu/linearize.py, validated against it by fuzz), bump ranks, write
Inserted/Deleted states into the row.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..text.op import INS
from .compose import (K_LEFTJOIN, K_OWN, K_ROOT, ComposedEntry,
                      assemble_prefix, compose_plan)
from .plan2 import APPLY, BEGIN, DROP, FORK, MAX, MergePlan2, compile_plan2

BIG = np.int64(1) << 40


@dataclass
class ZonePrep:
    """Everything the host prepares for a zone execution (pure control
    flow + text-pool assembly; no merge engine anywhere)."""
    plan: MergePlan2
    composed: List[ComposedEntry]
    prefix: str
    plen: int
    W: int                    # total char slots
    ins_lv0: np.ndarray       # zone insert-run starts (sorted)
    ins_cum: np.ndarray       # cumulative insert chars before each run
    pool: np.ndarray          # int32 [W] char codes by slot
    agent_k: np.ndarray       # int64 [W] agent name rank (-1 prefix)
    seq_k: np.ndarray         # int64 [W] agent-local seq
    # native handle for the C++ tape packer (None = Python pack); set by
    # prepare_zone when the oplog has a native context
    native_ctx: object = None
    # compose-cache identity at prepare time (0 = no native compose);
    # the packer only reads the ctx cache when this still matches
    compose_serial: int = 0
    # back-reference for lazy composed-entry fetch (get_composed)
    oplog: object = None

    def get_composed(self):
        """The per-entry composition results, fetched lazily: the
        flagship device path (prepare -> native pack -> execute) never
        needs them Python-side, so prepare_zone(fetch_composed=False)
        skips the column round-trip; consumers that DO need them
        (ZoneExec, the Python packer, sessions) land here."""
        if self.composed is None:
            self.composed = compose_plan(self.oplog, self.plan)
        return self.composed


def _slot_of(prep: ZonePrep, lvs: np.ndarray) -> np.ndarray:
    """Map zone insert LVs to char slots (prefix chars are slots
    0..plen-1; insert chars follow in LV order)."""
    lvs = np.asarray(lvs, dtype=np.int64)
    j = np.searchsorted(prep.ins_lv0, lvs, side="right") - 1
    return prep.plen + prep.ins_cum[j] + (lvs - prep.ins_lv0[j])


def prepare_zone(oplog, from_frontier: Sequence[int] = (),
                 merge_frontier: Optional[Sequence[int]] = None,
                 prefix: Optional[str] = None,
                 pin_lvs: Sequence[int] = (),
                 fetch_composed: bool = True) -> ZonePrep:
    """Host pass: plan + composition + slot/pool/key tables.

    `prefix` overrides the doc at the zone's common ancestor (an
    incremental caller that already holds it skips the replay).
    `pin_lvs` threads through to compile_plan2 (state rows kept alive at
    those versions — device sessions resume from them)."""
    from ..tpu.merge_kernel import _agent_keys

    merge = list(oplog.version) if merge_frontier is None \
        else list(merge_frontier)
    plan = compile_plan2(oplog.cg.graph, list(from_frontier), merge,
                         pin_lvs=tuple(pin_lvs))

    if prefix is None:
        if not plan.entries:
            # pure linear fast-forward: the prefix IS the document
            prefix = assemble_prefix(oplog, plan.ff_spans)
        elif not plan.common:
            prefix = ""   # fully concurrent from the dawn of time
        else:
            # The zone's base is the doc at its common ancestor — NOT the
            # fast-forward end: when history forks below the ff tip, the
            # recomputed zone re-covers the ops between common and the tip
            # (compile_plan2 visit2), so the prefix must stop at common.
            # Computed with this same engine, recursively (the recursion
            # bottoms out in pure-ff or empty-common plans).
            prefix, _ = zone_checkout_np(oplog, (), list(plan.common))
    # compose LAST: the prefix recursion above may run compose_plan for
    # its own zone, and the native packer reads the ctx's compose cache —
    # composing here leaves THIS plan's entries as the cached set. With
    # fetch_composed=False only the native cache is populated (the
    # column round-trip to Python is deferred to get_composed).
    from ..native import native_ctx_or_none
    nctx = native_ctx_or_none(oplog)
    composed = None
    serial = 0
    if not fetch_composed and nctx is not None:
        spans = [en.span for en in plan.entries]
        if nctx.compose_cache_only(spans):
            serial = nctx.compose_serial()
    if serial == 0:
        composed = compose_plan(oplog, plan)
        if nctx is not None:
            serial = nctx.compose_serial()
    plen = len(prefix)

    # zone insert runs -> slot map + pool (C++ when available: this was
    # a ~50k-piece Python loop on node_nodecc)
    cols = nctx.zone_ins_runs([en.span for en in plan.entries]) \
        if nctx is not None and plan.entries else None
    if not plan.entries:
        cols = (np.zeros(0, np.int64), np.zeros(0, np.int64),
                np.zeros(0, np.int64))
    if cols is not None:
        ins_lv0, ins_len, ins_cp = cols
    else:
        lv0: List[int] = []
        lens: List[int] = []
        cps: List[int] = []
        for en in plan.entries:
            for piece in oplog.ops.iter_range(en.span):
                if piece.kind == INS:
                    assert piece.content_pos is not None, \
                        "zone insert without stored content"
                    lv0.append(piece.lv)
                    lens.append(len(piece))
                    cps.append(piece.content_pos[0])
        ins_lv0 = np.asarray(lv0, dtype=np.int64)
        ins_len = np.asarray(lens, dtype=np.int64)
        ins_cp = np.asarray(cps, dtype=np.int64)
    order = np.argsort(ins_lv0, kind="stable")
    ins_lv0, ins_len, ins_cp = ins_lv0[order], ins_len[order], ins_cp[order]
    ins_cum = np.concatenate([[0], np.cumsum(ins_len)])[:-1]
    n_ins = int(ins_len.sum())
    W = plen + n_ins

    prefix_arr = np.frombuffer(prefix.encode("utf-32-le"), dtype=np.int32)
    arena_str = oplog.ops._arenas[INS].get((0, oplog.ops.arena_len(INS)))
    arena = np.frombuffer(arena_str.encode("utf-32-le"), dtype=np.int32)
    pool = np.empty(W, dtype=np.int32)
    pool[:plen] = prefix_arr
    if n_ins:
        run_of = np.repeat(np.arange(len(ins_len)), ins_len)
        off_in_run = np.arange(n_ins) - ins_cum[run_of]
        pool[plen:] = arena[ins_cp[run_of] + off_in_run]

    agent_k = np.full(W, -1, dtype=np.int64)
    seq_k = np.zeros(W, dtype=np.int64)
    if n_ins:
        lvs = ins_lv0[run_of] + off_in_run
        a, s = _agent_keys(oplog, lvs)
        agent_k[plen:] = a
        seq_k[plen:] = s
    seq_k[:plen] = np.arange(plen)   # prefix spine order key (unused)

    return ZonePrep(plan=plan, composed=composed, prefix=prefix, plen=plen,
                    W=W, ins_lv0=ins_lv0, ins_cum=ins_cum, pool=pool,
                    agent_k=agent_k, seq_k=seq_k, native_ctx=nctx,
                    compose_serial=serial, oplog=oplog)


class ZoneExec:
    """Sequential NumPy execution of a prepared zone."""

    def __init__(self, prep: ZonePrep):
        self.prep = prep
        W, plen = prep.W, prep.plen
        n_idx = max(1, prep.plan.indexes_used)
        self.state = np.zeros((n_idx, W), dtype=np.uint8)
        self.base_row = np.zeros(W, dtype=np.uint8)
        self.base_row[:plen] = 1
        self.rank = np.full(W, BIG, dtype=np.int64)
        self.rank[:plen] = np.arange(plen)
        self.ord = np.arange(plen, dtype=np.int64)
        self.ever = np.zeros(W, dtype=np.uint8)
        # per-placed-char origins: everything the YjsMod comparisons need
        # (prefix chars never appear inside scan windows — they are
        # non-NIY in every row — so only zone chars' values are read)
        self.ol_id = np.full(W, -2, dtype=np.int64)
        self.ol_id[:plen] = np.arange(plen) - 1   # prefix spine chain
        self.orr_id = np.full(W, -1, dtype=np.int64)

    # ---- per-entry resolution -------------------------------------------

    def _resolve_queries(self, snap: np.ndarray, cursors: List[int]):
        """(a_rank, ol_char, b_rank, orr_char) per cursor coord."""
        ordv = self.ord
        m = len(ordv)
        s_r = snap[ordv]
        vis_r = s_r == 1
        cum = np.cumsum(vis_r)
        nonniy_pos = np.flatnonzero(s_r != 0)
        out = []
        for c in cursors:
            if c == 0:
                a_rank, ol_char = -1, -1
            else:
                j = int(np.searchsorted(cum, c, side="left"))
                assert j < m and vis_r[j] and cum[j] == c, \
                    "cursor beyond entry document"
                a_rank, ol_char = j, int(ordv[j])
            k = int(np.searchsorted(nonniy_pos, a_rank, side="right"))
            if k < len(nonniy_pos):
                b_rank = int(nonniy_pos[k])
                orr_char = int(ordv[b_rank])
            else:
                b_rank, orr_char = m, -1
            out.append((a_rank, ol_char, b_rank, orr_char))
        return out

    def _place_block(self, q: Tuple[int, int, int, int], root_slot: int
                     ) -> Tuple[int, int]:
        """YjsMod integrate in rank space (reference: merge.rs:154-278),
        vectorized. Every window char is NotInsertedYet in the entry's row
        (origin-right is the first non-NIY, so the window holds only
        concurrent items — the reference debug-asserts exactly this).
        Per other item o, comparing origin-left positions (= ranks):
          * rank(o.ol) < rank(our ol): break — insert here ("top row")
          * rank(o.ol) > rank(our ol): skip ("bottom row")
          * equal gap: same origin-right char -> order by agent name rank
            then seq (break if we sort first, else scanning=false);
            different -> scanning = rank(o.orr) < rank(our orr),
            remembering where the current scanning streak began.
        Final position: the break point, rolled back to the streak start
        if `scanning` was still set (merge.rs:258 `if scanning { cursor =
        scan_start }`). Document end (orr == -1) compares as +infinity on
        BOTH sides, so end-vs-end falls to the agent tie-break.
        Returns (target_rank, orr_char)."""
        a_rank, ol_char, b_rank, orr_char = q
        ordv, rank = self.ord, self.rank
        agent_c = self.prep.agent_k[root_slot]
        seq_c = self.prep.seq_k[root_slot]

        w = ordv[a_rank + 1:b_rank]
        n = len(w)
        if n == 0:
            return b_rank, orr_char

        olw = self.ol_id[w]
        olr = np.where(olw >= 0, rank[np.clip(olw, 0, None)], -1)
        orw = self.orr_id[w]
        orr_r = np.where(orw >= 0, rank[np.clip(orw, 0, None)], BIG)
        b_eff = BIG if orr_char < 0 else b_rank

        top_row = olr < a_rank
        eq = olr == a_rank
        same = eq & (orw == orr_char)
        ka, ks = self.prep.agent_k[w], self.prep.seq_k[w]
        ins_here = same & ((agent_c < ka) | ((agent_c == ka) & (seq_c < ks)))
        brk = top_row | ins_here
        hits = np.flatnonzero(brk)
        jstar = int(hits[0]) if len(hits) else n

        set_ev = eq & ~same & (orr_r < b_eff)
        reset_ev = (eq & ~same & (orr_r >= b_eff)) | (same & ~ins_here)
        set_ev[jstar:] = False
        reset_ev[jstar:] = False
        set_idx = np.flatnonzero(set_ev)
        reset_idx = np.flatnonzero(reset_ev)
        last_reset = int(reset_idx[-1]) if len(reset_idx) else -1
        streak = set_idx[set_idx > last_reset]
        if len(streak):
            t = a_rank + 1 + int(streak[0])   # scanning rollback
        else:
            t = a_rank + 1 + jstar            # break point (or window end)
        return t, orr_char

    def apply_entry(self, row: int, ce: ComposedEntry) -> None:
        prep = self.prep
        snap = self.state[row].copy()
        queries = self._resolve_queries(snap, ce.q_cursor)

        # resolve base-coord delete targets against the snapshot BEFORE
        # ranks move (results are char lists; states write at the end)
        del_chars: List[np.ndarray] = []
        if ce.del_base:
            ordv = self.ord
            s_r = snap[ordv]
            vis_r = s_r == 1
            cum = np.cumsum(vis_r)
            for (c0, c1) in ce.del_base:
                mask = vis_r & (cum > c0) & (cum <= c1)
                del_chars.append(ordv[mask])

        nc = ce.num_chars()
        if nc:
            slots = _slot_of(prep, ce.ch_lv)
            # block placement (windows are disjoint: see compose.py)
            nb = len(ce.blk_start)
            t_arr = np.empty(nb, dtype=np.int64)
            orr_b = np.empty(nb, dtype=np.int64)
            for b in range(nb):
                root_slot = int(_slot_of(
                    prep, np.asarray([ce.blk_root_lv[b]]))[0])
                t, orr = self._place_block(
                    queries[ce.blk_root_q[b]], root_slot)
                t_arr[b] = t
                orr_b[b] = orr

            # combined rank bump (block targets are distinct & disjoint)
            border = np.argsort(t_arr, kind="stable")
            t_sorted = t_arr[border]
            len_sorted = ce.blk_len.astype(np.int64)[border]
            cum_before = np.concatenate([[0], np.cumsum(len_sorted)])[:-1]
            # existing placed chars shift by total block chars at <= rank
            bump = np.searchsorted(t_sorted, self.rank[self.ord],
                                   side="right")
            add = np.concatenate([[0], np.cumsum(len_sorted)])[bump]
            new_rank_existing = self.rank[self.ord] + add
            # new chars: block b starts at t_b + chars of blocks before it
            blk_new_start = np.empty(nb, dtype=np.int64)
            blk_new_start[border] = t_sorted + cum_before
            intra = np.arange(nc, dtype=np.int64) - \
                ce.blk_start.astype(np.int64)[ce.ch_block]
            new_char_rank = blk_new_start[ce.ch_block] + intra

            self.rank[self.ord] = new_rank_existing
            self.rank[slots] = new_char_rank
            m_new = len(self.ord) + nc
            new_ord = np.empty(m_new, dtype=np.int64)
            new_ord[new_rank_existing] = self.ord
            new_ord[new_char_rank] = slots
            self.ord = new_ord

            # origin metadata for the new chars: interiors chain off their
            # predecessor; K_OWN heads anchor an own char; query-anchored
            # heads take the device-resolved origin-left. origin_right is
            # the own char the run saw on its right at insert time, else
            # the block's resolved B (merge.rs:407-424 via compose.py).
            q_ol = np.asarray([queries[q][1] if q >= 0 else -2
                               for q in ce.ch_q], dtype=np.int64)
            prev_slot = slots - 1
            anchor_slot = np.where(
                ce.ch_anchor >= 0,
                _slot_of(prep, np.maximum(ce.ch_anchor, 0)), -1)
            kind = ce.ch_kind
            ol_new = np.where(
                kind == 0, prev_slot,
                np.where(kind == K_OWN, anchor_slot, q_ol))
            orr_new = np.where(
                ce.ch_orrown >= 0,
                _slot_of(prep, np.maximum(ce.ch_orrown, 0)),
                orr_b[ce.ch_block])
            self.ol_id[slots] = ol_new
            self.orr_id[slots] = orr_new
            self.state[row, slots] = np.maximum(self.state[row, slots], 1)

        # deletes last (an entry's deletes follow its inserts in LV order
        # only when they do — but all targets were resolved against the
        # snapshot, and states are monotone, so write order is free)
        for chars in del_chars:
            self.state[row, chars] = 2
            self.ever[chars] = 1
        for (lv0, lv1) in ce.del_own:
            sl = _slot_of(prep, np.arange(lv0, lv1))
            self.state[row, sl] = 2
            self.ever[sl] = 1

    # ---- plan execution --------------------------------------------------

    def run(self) -> None:
        for act in self.prep.plan.actions:
            op = act[0]
            if op == BEGIN:
                self.state[act[1]] = self.base_row
            elif op == FORK:
                self.state[act[2]] = self.state[act[1]]
            elif op == MAX:
                np.maximum(self.state[act[1]], self.state[act[2]],
                           out=self.state[act[1]])
            elif op == DROP:
                pass
            elif op == APPLY:
                self.apply_entry(act[2], self.prep.get_composed()[act[1]])

    def text(self) -> str:
        vis = self.ever[self.ord] == 0
        chars = self.prep.pool[self.ord[vis]]
        return chars.tobytes().decode("utf-32-le")


def zone_checkout_np(oplog, from_frontier: Sequence[int] = (),
                     merge_frontier: Optional[Sequence[int]] = None,
                     prefix: Optional[str] = None,
                     return_exec: bool = False):
    """Full checkout/merge via the zone engine. Returns (text, frontier)
    — the document at version_union(from, merge), like merge_device."""
    prep = prepare_zone(oplog, from_frontier, merge_frontier, prefix=prefix)
    if not prep.plan.entries:
        out = prep.prefix
        ex = None
    else:
        ex = ZoneExec(prep)
        ex.run()
        out = ex.text()
    frontier = list(prep.plan.final_frontier)
    if return_exec:
        return out, frontier, prep, ex
    return out, frontier
