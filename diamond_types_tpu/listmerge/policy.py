"""Measured merge-engine selection policy.

`Branch.merge` keeps several interchangeable engines behind one seam
(reference: the listmerge/listmerge2 seam, src/list/merge.rs:63-96). The
tracker engine wins every single-doc host merge measured so far
(BASELINE.md); the zone engine wins when merges amortize over batched
replicas on a real accelerator. Rather than hard-coding that belief (or
hiding it behind env vars only), the policy CHOOSES from measured
throughput. Measurements are recorded at the ENGINES (zone rates inside
zone_checkout_device for FULL runs — whether started by a DT_TPU_ZONE
override, a bench, or the policy itself; precomputed-prep runs are not
recorded since they skip the dominant host cost — and tracker rates at
the Branch.merge seam), so the policy can bootstrap without env flips. Env overrides (DT_TPU_ZONE / DT_TPU_PLAN2 /
DT_TPU_DEVICE_MERGE / DT_TPU_NO_NATIVE) still force a specific engine —
they are development switches, not the policy.

The tracker stays the correctness oracle either way: the policy boundary
is differential-tested (tests/test_zone.py) so a selection flip can never
change merged text. A policy-selected zone merge reports
last_merge_collisions = None (the documented "engine doesn't report"
value — same as the plan2/device overrides); callers that need conflict
detection use OpLog.has_conflicts_when_merging.

Selection properties:
  * the TRACKER is chosen until BOTH engines have measurements — the
    zone engine is never started spontaneously, so a merge can never be
    the thing that first initializes an accelerator backend;
  * once both are measured, every PROBE_EVERY-th call runs the currently
    losing engine so both rates stay fresh and a flip self-corrects;
  * rates decay with WALL-CLOCK half-life HALF_LIFE_S, so a regression is
    not hidden under stale history;
  * a zone-engine failure demotes it on the spot (forget) and the merge
    falls back to the tracker.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

TRACKER = "tracker"
ZONE = "zone"


class EnginePolicy:
    PROBE_EVERY = 16
    HALF_LIFE_S = 300.0
    # After a failure-demotion (forget) the engine has no rate, and zone
    # rates are only recorded by zone runs — without a re-probe nothing
    # in-process could ever measure it again, so a transient accelerator
    # blip would disable the faster engine for the process lifetime
    # (ADVICE r4). One probe-sized retry is allowed per cooldown window.
    DEMOTION_COOLDOWN_S = 60.0

    def __init__(self) -> None:
        # engine -> [ops, seconds, last_record_wall_time]
        self._acc: Dict[str, list] = {}
        self._calls = 0
        self._last_probe = 0
        self._demoted_at: Dict[str, float] = {}
        # record()/choose() run concurrently in multi-threaded embedders
        # (tools/server.py merges from HTTP handler threads); unguarded,
        # _decayed's in-place rescale races with record() and can corrupt
        # rates or double-probe (ADVICE r4).
        self._lock = threading.Lock()

    def _decayed(self, engine: str):
        acc = self._acc.get(engine)
        if acc is None:
            return None
        dt = time.monotonic() - acc[2]
        if dt > 0:
            f = 0.5 ** (dt / self.HALF_LIFE_S)
            acc[0] *= f
            acc[1] *= f
            acc[2] = time.monotonic()
        return acc

    def record(self, engine: str, n_ops: int, seconds: float) -> None:
        if seconds <= 0 or n_ops <= 0:
            # 0-op timings (e.g. a fork merge whose frontier-top proxy
            # under-counts) would add pure denominator and corrupt the
            # rate; skip them
            return
        with self._lock:
            acc = self._decayed(engine)
            if acc is None:
                acc = self._acc[engine] = [0.0, 0.0, time.monotonic()]
            acc[0] += n_ops
            acc[1] += seconds
            # a successful measurement clears any standing demotion
            self._demoted_at.pop(engine, None)

    def forget(self, engine: str) -> None:
        """Drop an engine's measurements (e.g. it just failed): the
        policy stops choosing it until it is measured again — except the
        ZONE engine, which gets one probe-eligible re-try per
        DEMOTION_COOLDOWN_S (see choose(); the tracker is the default
        and never needs recovery, so cooldown bookkeeping is zone-only)."""
        with self._lock:
            self._acc.pop(engine, None)
            if engine == ZONE:
                self._demoted_at[engine] = time.monotonic()

    def _rate_locked(self, engine: str):
        """Decayed ops/sec for `engine`, or None unmeasured. Caller
        holds self._lock (the lock is not reentrant)."""
        acc = self._decayed(engine)
        if acc is None or acc[1] <= 0:
            return None
        return acc[0] / acc[1]

    def rate(self, engine: str):
        with self._lock:
            return self._rate_locked(engine)

    PROBE_MAX_OPS = 20_000

    def choose(self, n_ops_hint=None) -> str:
        """The engine with the best MEASURED rate; the tracker wherever
        evidence is missing (it is the oracle and the measured winner on
        every host workload to date). `n_ops_hint` bounds exploration:
        the loser-refresh probe only fires on merges KNOWN small (a
        fork merge's frontier-top delta can be tiny or negative while
        the merge is huge, so a non-positive hint counts as big), and a
        skipped probe stays due — it fires on the next small merge
        instead of being consumed, so big-merge-dominated workloads
        still refresh the loser."""
        # a missing hint counts as probe-eligible (same rule as the
        # loser-refresh probe below): hint-less embedder calls must not
        # be the one path where a demoted engine can never recover
        probe_eligible = n_ops_hint is None or \
            0 < n_ops_hint <= self.PROBE_MAX_OPS
        with self._lock:
            zr = self._rate_locked(ZONE)
            tr = self._rate_locked(TRACKER)
            if zr is None and tr is not None and probe_eligible:
                # demotion-cooldown re-probe: a forgotten (failed) zone
                # engine gets one probe-sized retry per cooldown window,
                # so a transient blip can't disable it for the process
                # lifetime. Re-arm the window now; a second failure just
                # waits out the next one, a success clears it (record()).
                demoted = self._demoted_at.get(ZONE)
                if demoted is not None and \
                        time.monotonic() - demoted >= self.DEMOTION_COOLDOWN_S:
                    self._demoted_at[ZONE] = time.monotonic()
                    return ZONE
            if zr is None or tr is None:
                return TRACKER
            self._calls += 1
            best = ZONE if zr > tr else TRACKER
            if self._calls - self._last_probe >= self.PROBE_EVERY \
                    and probe_eligible:
                self._last_probe = self._calls
                return TRACKER if best == ZONE else ZONE  # refresh loser
            return best

    def snapshot(self) -> dict:
        """Observability (reported in bench_report_full.json): measured
        ops/sec per engine."""
        with self._lock:
            return {e: round(a[0] / a[1])
                    for e, a in self._acc.items() if a[1] > 0}


GLOBAL = EnginePolicy()
