"""Measured merge-engine selection policy.

`Branch.merge` keeps several interchangeable engines behind one seam
(reference: the listmerge/listmerge2 seam, src/list/merge.rs:63-96). The
tracker engine wins every single-doc host merge measured so far
(BASELINE.md); the zone engine wins when merges amortize over batched
replicas on a real accelerator. Rather than hard-coding that belief (or
hiding it behind env vars only), the policy CHOOSES from measured
throughput: every engine run records (ops, seconds), and the zone engine
is selected only when its observed rate actually exceeds the tracker's
for the workload shape. Env overrides (DT_TPU_ZONE / DT_TPU_PLAN2 /
DT_TPU_DEVICE_MERGE / DT_TPU_NO_NATIVE) still force a specific engine —
they are development switches, not the policy.

The tracker stays the correctness oracle either way: the policy boundary
is differential-tested (tests/test_zone.py) so a selection flip can never
change merged text.
"""

from __future__ import annotations

from typing import Dict, Tuple

TRACKER = "tracker"
ZONE = "zone"


class EnginePolicy:
    """Rolling throughput record per engine; selection by measured rate.

    Rates are recorded per workload shape bucket ("single" for one-doc
    merges, "batched" for replica batches) because the zone engine's
    economics differ entirely between them (per-call latency vs aggregate
    throughput).

    Selection properties:
      * the TRACKER is chosen until BOTH engines have measurements — the
        zone engine is never started spontaneously (its first run comes
        from the bench's device phase, a session, or DT_TPU_ZONE), so a
        merge can never be the thing that first initializes an
        accelerator backend;
      * once both are measured, every PROBE_EVERY-th call runs the
        currently-losing engine so both rates stay fresh and a flip can
        self-correct (without this, the winner would starve the loser of
        measurements forever);
      * accumulators decay (halved past DECAY_SECONDS) so a regression
        is not hidden under hours of stale history.
    """

    PROBE_EVERY = 16
    DECAY_SECONDS = 60.0

    def __init__(self) -> None:
        # (engine, shape) -> [total_ops, total_seconds]
        self._acc: Dict[Tuple[str, str], list] = {}
        self._calls = 0

    def record(self, engine: str, shape: str, n_ops: int,
               seconds: float) -> None:
        if seconds <= 0 or n_ops <= 0:
            # 0-op timings (e.g. a fork merge whose frontier-top proxy
            # under-counts) would add pure denominator and corrupt the
            # rate; skip them
            return
        acc = self._acc.setdefault((engine, shape), [0.0, 0.0])
        acc[0] += n_ops
        acc[1] += seconds
        if acc[1] > self.DECAY_SECONDS:
            acc[0] *= 0.5
            acc[1] *= 0.5

    def rate(self, engine: str, shape: str):
        acc = self._acc.get((engine, shape))
        if acc is None or acc[1] <= 0:
            return None
        return acc[0] / acc[1]

    def choose(self, shape: str = "single") -> str:
        """The engine with the best MEASURED rate for this shape; the
        tracker wherever evidence is missing (it is the oracle and the
        measured winner on every host workload to date)."""
        zr = self.rate(ZONE, shape)
        tr = self.rate(TRACKER, shape)
        if zr is None or tr is None:
            return TRACKER
        self._calls += 1
        best = ZONE if zr > tr else TRACKER
        if self._calls % self.PROBE_EVERY == 0:
            return TRACKER if best == ZONE else ZONE   # refresh the loser
        return best

    def snapshot(self) -> dict:
        """Observability: measured rates per (engine, shape)."""
        return {f"{e}/{s}": round(a[0] / a[1])
                for (e, s), a in self._acc.items() if a[1] > 0}


GLOBAL = EnginePolicy()
