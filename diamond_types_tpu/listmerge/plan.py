"""Plan-then-execute merging: static merge schedules.

Capability mirror of the reference's experimental listmerge2 engine
(reference: src/listmerge2/ — ConflictSubgraph mod.rs:20-33, MergePlan
action_plan.rs:11-37): instead of interleaving DAG queries (diff,
find_conflicting, frontier movement) with tracker mutation the way the M1
engine does, *compile* the whole traversal into a linear `MergePlan` first —
a flat list of steps, each a (retreat spans, advance spans, consume span,
emit?) tuple — then execute it with zero graph queries.

Why this shape matters for the TPU tier: execution becomes pure data
movement over dense span tables with a statically known schedule — exactly
what a device kernel can consume (the compile step stays on host; the
execute step is the part that lowers to JAX/Pallas; the reference's
index_gap_buffer dense state matrix is the round-2 executor design).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Tuple

from ..causalgraph.agent import AgentAssignment
from ..causalgraph.graph import DiffFlag, Graph
from ..core.span import Span, push_reversed_rle
from ..text.op import OpStore
from .tracker import Tracker
from .walker import SpanningTreeWalker


@dataclass
class PlanStep:
    retreat: List[Span]        # descending order
    advance: List[Span]        # ascending order
    consume: Span
    emit: bool                 # False while building the tracker "hot"


@dataclass
class MergePlan:
    steps: List[PlanStep] = field(default_factory=list)
    ff_spans: List[Span] = field(default_factory=list)  # ascending, untransformed
    final_frontier: List[int] = field(default_factory=list)

    def num_ops(self) -> int:
        n = sum(b - a for (a, b) in self.ff_spans)
        n += sum(s.consume[1] - s.consume[0] for s in self.steps if s.emit)
        return n


def compile_plan(graph: Graph, from_frontier: List[int],
                 merge_frontier: List[int]) -> MergePlan:
    """All control flow happens here: conflict analysis, fast-forward
    extraction, spanning-tree traversal order, frontier diffs."""
    plan = MergePlan()
    new_ops: List[Span] = []
    conflict_ops: List[Span] = []

    def visit(span: Span, flag: DiffFlag) -> None:
        target = new_ops if flag == DiffFlag.ONLY_B else conflict_ops
        push_reversed_rle(target, span)

    common = graph.find_conflicting(from_frontier, merge_frontier, visit)
    next_frontier = list(from_frontier)

    # Fast-forward prefix.
    did_ff = False
    while new_ops:
        span = new_ops[-1]
        i = graph.find_idx(span[0])
        if list(graph.parents_at(span[0])) != next_frontier:
            break
        new_ops.pop()
        take_end = min(graph.ends[i], span[1])
        if take_end < span[1]:
            new_ops.append((take_end, span[1]))
        plan.ff_spans.append((span[0], take_end))
        next_frontier = [take_end - 1]
        did_ff = True

    if new_ops:
        if did_ff:
            conflict_ops = []

            def visit2(span: Span, flag: DiffFlag) -> None:
                if flag != DiffFlag.ONLY_B:
                    push_reversed_rle(conflict_ops, span)

            common = graph.find_conflicting(next_frontier, merge_frontier,
                                            visit2)

        walker = SpanningTreeWalker(graph, conflict_ops, list(common))
        for walk in walker:
            plan.steps.append(PlanStep(
                walk.retreat, list(reversed(walk.advance_rev)),
                walk.consume, emit=False))
        walker2 = SpanningTreeWalker(graph, new_ops, walker.frontier)
        for walk in walker2:
            graph.advance_frontier(next_frontier, walk.consume)
            plan.steps.append(PlanStep(
                walk.retreat, list(reversed(walk.advance_rev)),
                walk.consume, emit=True))

    plan.final_frontier = next_frontier
    return plan


def execute_plan(plan: MergePlan, aa: AgentAssignment, ops: OpStore
                 ) -> Iterator[Tuple[int, object, Optional[int]]]:
    """Pure data movement: no graph queries, no frontier logic — just the
    schedule. Yields the same (lv, op_piece, xf_pos|None) stream as
    TransformedOps."""
    for span in plan.ff_spans:
        for piece in ops.iter_range(span):
            yield (piece.lv, piece, piece.start)

    if not plan.steps:
        return

    tracker = Tracker()
    for step in plan.steps:
        for rng in step.retreat:
            tracker.retreat_by_range(rng)
        for rng in step.advance:
            tracker.advance_by_range(rng)
        for piece in ops.iter_range(step.consume):
            pair = piece
            while True:
                agent, _seq, alen = aa.local_span_to_agent_span(
                    pair.lv, len(pair))
                consumed, xf = tracker.apply(aa, agent, pair, alen)
                head = pair if consumed == len(pair) else \
                    ops._slice_run(pair, 0, consumed)
                if step.emit:
                    yield (head.lv, head, xf)
                if consumed == len(pair):
                    break
                pair = ops._slice_run(pair, consumed, len(pair))


def merge_via_plan(oplog, from_frontier, merge_frontier):
    """Convenience: compile + execute, returning (xf list, final frontier)."""
    plan = compile_plan(oplog.cg.graph, list(from_frontier),
                        list(merge_frontier))
    out = list(execute_plan(plan, oplog.cg.agent_assignment, oplog.ops))
    return out, plan.final_frontier
