"""Dense fork/join merge executor: flat span table + state matrix.

Capability mirror of the reference's listmerge2 dense executor (reference:
src/listmerge2/index_gap_buffer.rs:20-31 — a flat buffer of YjsSpans with a
2-D `[index * len + item] -> SpanState` state matrix), executing the
fork/join plans compiled by plan2.py.

Representation:
  * `slots`   — flat table of RLE item spans (id range, origins, ever-deleted
                flag), indexed by creation-order slot id; never moved.
  * `S`       — the dense state matrix, shape [n_slots, n_indexes] uint8,
                values from the 3-point lattice NIY(0) < Inserted(1) <
                Deleted(2). Fork/Max/Begin are whole-column numpy ops.
  * `order`   — slot ids in document (CRDT) order; the only structure that
                shifts on insert (the reference uses a gap buffer for the
                same purpose; a Python list's memmove plays that role here).

Per-index visibility is S[:, idx] == 1; the upstream (output-frame) metric
is `not ever_deleted`, exactly the dual metric of the M1 tracker
(reference: src/listmerge/metrics.rs:18-66). Because this engine never
retreats, delete counts are unnecessary — see plan2.py.

Integration of concurrent inserts is the same YjsMod scan as the M1 engine
(reference: merge.rs:154-278) — run over the flat order list with states
read from the active index's matrix column, so the differential tests can
demand byte-identical documents, not just equivalent ones.
"""

from __future__ import annotations

from bisect import bisect_right, insort
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..core.span import UNDERWATER_START
from ..text.op import INS, OpRun
from .plan2 import APPLY, BEGIN, DROP, FORK, MAX, MergePlan2, compile_plan2

ROOT = -1
NIY = 0
INSERTED = 1
DELETED = 2


class _Slot:
    __slots__ = ("ids", "ide", "ol", "orr", "ever")

    def __init__(self, ids: int, ide: int, ol: int, orr: int,
                 ever: bool) -> None:
        self.ids = ids
        self.ide = ide
        self.ol = ol
        self.orr = orr
        self.ever = ever

    def __len__(self) -> int:
        return self.ide - self.ids

    def origin_left_at(self, offset: int) -> int:
        return self.ol if offset == 0 else self.ids + offset - 1


@dataclass
class _Cur:
    """Cursor = gap before item `off` of slot order[oi]; (raw, cur, up) are
    the metric totals of FULL slots strictly before oi (partial offsets are
    added on demand — slot states are uniform so partials are linear)."""
    oi: int
    off: int
    raw: int
    cur: int
    up: int

    def copy(self) -> "_Cur":
        return _Cur(self.oi, self.off, self.raw, self.cur, self.up)


class DenseExecutor:
    def __init__(self, plan: MergePlan2, aa, ops,
                 journal: bool = False) -> None:
        self.plan = plan
        self.aa = aa
        self.ops = ops
        self.n_idx = max(1, plan.indexes_used)
        # Optional effect journal for the device tier: per entry, the list
        # of (id_lo, id_hi, state) writes its Apply performed, in item-id
        # space AT WRITE TIME — the data the TPU plan executor replays (see
        # tpu/plan_kernels.py). Ranges subsume split inheritance: a later
        # split only refines slots WITHIN an already-journaled range, and
        # states are monotone, so replaying ranges over the final slot
        # table reproduces every snapshot exactly.
        self.journal = [] if journal else None
        self._cur_writes = None
        cap = 64
        self.S = np.zeros((cap, self.n_idx), dtype=np.uint8)
        self.is_base = np.zeros(cap, dtype=bool)
        self.slots: List[_Slot] = []
        self.order: List[int] = []
        self.total_raw = 0
        # item-LV -> slot id lookup (mirrors the M1 tracker's SpaceIndex).
        self._ins_starts: List[int] = []
        self._ins_slots = {}
        self._row = -1            # active index during Apply
        self._cur: Optional[_Cur] = None

        under = self._new_slot(UNDERWATER_START, UNDERWATER_START * 2 - 1,
                               ROOT, ROOT, False, base=True)
        self.order.append(under)

    # ---- slot table ------------------------------------------------------

    def _new_slot(self, ids: int, ide: int, ol: int, orr: int, ever: bool,
                  base: bool = False) -> int:
        sid = len(self.slots)
        if sid == len(self.S):
            self.S = np.vstack([self.S, np.zeros_like(self.S)])
            self.is_base = np.concatenate(
                [self.is_base, np.zeros_like(self.is_base)])
        self.slots.append(_Slot(ids, ide, ol, orr, ever))
        self.is_base[sid] = base
        self.total_raw += ide - ids
        insort(self._ins_starts, ids)
        self._ins_slots[ids] = sid
        return sid

    def _split(self, sid: int, offset: int) -> int:
        """Split slot after `offset` items; returns the new right slot id.
        Does NOT touch `order` — callers place the new slot."""
        s = self.slots[sid]
        assert 0 < offset < len(s)
        mid = s.ids + offset
        rid = self._new_slot(mid, s.ide, mid - 1, s.orr, s.ever,
                             base=bool(self.is_base[sid]))
        self.total_raw -= s.ide - mid  # _new_slot double-counted the tail
        self.S[rid] = self.S[sid]
        s.ide = mid
        return rid

    def _ins_lookup(self, lv: int) -> int:
        i = bisect_right(self._ins_starts, lv) - 1
        sid = self._ins_slots[self._ins_starts[i]]
        s = self.slots[sid]
        assert s.ids <= lv < s.ide, f"item LV {lv} not tracked"
        return sid

    # ---- cursors ---------------------------------------------------------

    def _slot_metrics(self, sid: int, row: int) -> Tuple[int, int, int]:
        s = self.slots[sid]
        n = len(s)
        return (n, n if self.S[sid, row] == INSERTED else 0,
                0 if s.ever else n)

    def _step_fwd(self, c: _Cur, row: int) -> None:
        n, cu, up = self._slot_metrics(self.order[c.oi], row)
        c.raw += n
        c.cur += cu
        c.up += up
        c.oi += 1
        c.off = 0

    def _step_back(self, c: _Cur, row: int) -> None:
        assert c.oi > 0, "cursor walked past document start"
        c.oi -= 1
        n, cu, up = self._slot_metrics(self.order[c.oi], row)
        c.raw -= n
        c.cur -= cu
        c.up -= up
        c.off = 0

    def _roll(self, c: _Cur, row: int) -> Optional[_Cur]:
        """Normalize so off < len(slot); None at end of document."""
        while c.oi < len(self.order):
            sid = self.order[c.oi]
            n = len(self.slots[sid])
            if c.off < n:
                return c
            assert c.off == n
            self._step_fwd(c, row)
        return None

    def _raw_pos(self, c: Optional[_Cur]) -> int:
        if c is None:
            return self.total_raw
        return c.raw + c.off

    def _up_pos(self, c: Optional[_Cur]) -> int:
        if c is None:
            return sum(0 if s.ever else len(s) for s in self.slots)
        if c.oi >= len(self.order):
            return c.up
        s = self.slots[self.order[c.oi]]
        return c.up + (0 if s.ever else c.off)

    def _seek_cur(self, row: int, pos: int) -> _Cur:
        """Cursor at the `pos`-th item visible in `row` (inside the slot).
        Walks from the cached cursor when possible (gap-buffer locality)."""
        c = self._cur if self._cur is not None else _Cur(0, 0, 0, 0, 0)
        c = c.copy()
        c.off = 0
        while c.cur > pos:
            self._step_back(c, row)
        while True:
            assert c.oi < len(self.order), f"content pos {pos} out of range"
            sid = self.order[c.oi]
            n, cu, up = self._slot_metrics(sid, row)
            if pos < c.cur + cu:
                c.off = pos - c.cur
                return c
            c.raw += n
            c.cur += cu
            c.up += up
            c.oi += 1

    def _locate_slot(self, sid: int) -> _Cur:
        """Cursor at the start of slot `sid` (O(order) scan)."""
        c = _Cur(0, 0, 0, 0, 0)
        for oi, s in enumerate(self.order):
            if s == sid:
                c.oi = oi
                return c
            n, cu, up = self._slot_metrics(s, self._row)
            c.raw += n
            c.cur += cu
            c.up += up
        raise AssertionError(f"slot {sid} not in order")

    def _cursor_before_item(self, lv: int) -> Optional[_Cur]:
        if lv == ROOT:
            return None  # end-of-document sentinel
        sid = self._ins_lookup(lv)
        c = self._locate_slot(sid)
        c.off = lv - self.slots[sid].ids
        return c

    def _cursor_after_item(self, lv: int, stick_end: bool) -> _Cur:
        if lv == ROOT:
            return _Cur(0, 0, 0, 0, 0)  # start of document
        sid = self._ins_lookup(lv)
        c = self._locate_slot(sid)
        c.off = lv - self.slots[sid].ids + 1
        if not stick_end:
            rolled = self._roll(c, self._row)
            if rolled is not None:
                return rolled
        return c

    def _cmp(self, a: Optional[_Cur], b: Optional[_Cur]) -> int:
        pa, pb = self._raw_pos(a), self._raw_pos(b)
        return (pa > pb) - (pa < pb)

    # ---- integrate (YjsMod) ---------------------------------------------

    def _insert_at(self, c: Optional[_Cur], sid: int) -> Optional[_Cur]:
        """Place slot `sid` at cursor `c`; returns a cursor just after it
        (None when prefixes would need a rescan — callers drop the cache)."""
        if c is None:
            self.order.append(sid)
            return None
        out = c.copy()
        if c.oi >= len(self.order):
            self.order.append(sid)
        else:
            tgt = self.order[c.oi]
            n = len(self.slots[tgt])
            if c.off == 0:
                self.order.insert(c.oi, sid)
            elif c.off == n:
                self.order.insert(c.oi + 1, sid)
                self._step_fwd(out, self._row)
            else:
                rid = self._split(tgt, c.off)
                self.order.insert(c.oi + 1, rid)
                self.order.insert(c.oi + 1, sid)
                self._step_fwd(out, self._row)  # past the (now split) left
        # `out` sits just before the new slot at out.oi; advance past it.
        assert self.order[out.oi] == sid
        self._step_fwd(out, self._row)
        return out

    def _integrate(self, agent: int, sid: int,
                   cursor: Optional[_Cur]) -> Tuple[int, _Cur]:
        """YjsMod / FugueMax concurrent-insert resolution over the flat
        table (reference: merge.rs:154-278; mirrors tracker.integrate).
        Returns (upstream insert position, cursor after the new item)."""
        row = self._row
        item = self.slots[sid]
        cursor = self._roll(cursor, row) if cursor is not None else None
        left_cursor = cursor.copy() if cursor is not None else None
        scan_start = cursor.copy() if cursor is not None else None
        scanning = False

        while True:
            if cursor is None:
                break
            rolled = self._roll(cursor, row)
            if rolled is None:
                cursor = None
                break
            cursor = rolled
            other_sid = self.order[cursor.oi]
            other = self.slots[other_sid]
            other_lv = other.ids + cursor.off
            if other_lv == item.orr:
                break

            assert self.S[other_sid, row] == NIY, \
                "concurrent scan hit a non-NIY item"

            other_left_lv = other.origin_left_at(cursor.off)
            other_left_cursor = self._cursor_after_item(other_left_lv, False)

            c = self._cmp(other_left_cursor, left_cursor)
            if left_cursor is None:
                c = -1
            if c < 0:
                break
            elif c == 0:
                if item.orr == other.orr:
                    my_name = self.aa.get_agent_name(agent)
                    other_agent, other_seq = \
                        self.aa.local_to_agent_version(other_lv)
                    other_name = self.aa.get_agent_name(other_agent)
                    if my_name < other_name:
                        ins_here = True
                    elif my_name == other_name:
                        my_seq = self.aa.local_to_agent_version(item.ids)[1]
                        ins_here = my_seq < other_seq
                    else:
                        ins_here = False
                    if ins_here:
                        break
                    scanning = False
                else:
                    my_right = self._cursor_before_item(item.orr)
                    other_right = self._cursor_before_item(other.orr)
                    if self._cmp(other_right, my_right) < 0:
                        if not scanning:
                            scanning = True
                            scan_start = cursor.copy()
                    else:
                        scanning = False

            # Advance past `other` wholesale.
            cursor.off = len(other)
            nxt = self._roll(cursor, row)
            if nxt is None:
                break
            cursor = nxt

        if scanning:
            cursor = scan_start

        pos = self._up_pos(cursor)
        after = self._insert_at(cursor, sid)
        return pos, after

    # ---- op application --------------------------------------------------

    def _apply_one(self, agent: int, op: OpRun, max_len: int):
        """Advance the active row by (a prefix of) one op run; returns
        (len_consumed, xf_pos | None). Mirrors tracker.apply semantics."""
        row = self._row
        length = min(max_len, len(op))
        if op.kind == INS:
            if not op.fwd:
                raise NotImplementedError("reverse insert runs")
            if op.start == 0:
                origin_left = ROOT
                cursor: Optional[_Cur] = _Cur(0, 0, 0, 0, 0)
            else:
                c = self._seek_cur(row, op.start - 1)
                sid = self.order[c.oi]
                origin_left = self.slots[sid].ids + c.off
                cursor = c.copy()
                cursor.off += 1

            # origin_right: next item not in the NIY state in this row.
            c2 = self._roll(cursor.copy(), row)
            if c2 is None:
                origin_right = ROOT
            else:
                while True:
                    sid2 = self.order[c2.oi]
                    if self.S[sid2, row] == NIY:
                        c2.off = len(self.slots[sid2])
                        c2 = self._roll(c2, row)
                        if c2 is None:
                            origin_right = ROOT
                            break
                    else:
                        origin_right = self.slots[sid2].ids + c2.off
                        break

            new_sid = self._new_slot(op.lv, op.lv + length,
                                     origin_left, origin_right, False)
            self.S[new_sid, row] = INSERTED
            if self._cur_writes is not None:
                self._cur_writes.append((op.lv, op.lv + length, INSERTED))
            ins_pos, after = self._integrate(agent, new_sid, cursor)
            self._cur = after  # sequential typing lands right here next
            return length, ins_pos

        else:  # DEL
            fwd = op.fwd
            if fwd:
                c = self._seek_cur(row, op.start)
                take_req = length
            else:
                last_pos = op.end - 1
                c = self._seek_cur(row, last_pos)
                entry_start_pos = last_pos - c.off
                edit_start = max(entry_start_pos, op.end - length)
                take_req = op.end - edit_start
                c.off -= take_req - 1

            sid = self.order[c.oi]
            s = self.slots[sid]
            assert self.S[sid, row] == INSERTED
            ever_deleted = s.ever
            del_start_xf = self._up_pos(c)

            take = min(take_req, len(s) - c.off)
            if c.off > 0:
                rid = self._split(sid, c.off)
                self.order.insert(c.oi + 1, rid)
                self._step_fwd(c, row)  # move past the left remainder
                sid, s = rid, self.slots[rid]
            if take < len(s):
                rid = self._split(sid, take)
                self.order.insert(c.oi + 1, rid)
            self.S[sid, row] = DELETED
            if self._cur_writes is not None:
                self._cur_writes.append((s.ids, s.ide, DELETED))
            s.ever = True
            if not fwd:
                assert take == take_req
            self._cur = c.copy()
            self._cur.off = 0
            return take, (del_start_xf if not ever_deleted else None)

    # ---- plan execution --------------------------------------------------

    def run(self) -> Iterator[Tuple[int, OpRun, Optional[int]]]:
        plan, aa, ops = self.plan, self.aa, self.ops
        for act in plan.actions:
            kind = act[0]
            if kind == BEGIN:
                n = len(self.slots)
                self.S[:n, act[1]] = self.is_base[:n].astype(np.uint8)
                self._cur = None  # row states changed under the cache
            elif kind == FORK:
                self.S[:, act[2]] = self.S[:, act[1]]
                self._cur = None
            elif kind == MAX:
                np.maximum(self.S[:, act[1]], self.S[:, act[2]],
                           out=self.S[:, act[1]])
                self._cur = None
            elif kind == DROP:
                pass
            elif kind == APPLY:
                entry = plan.entries[act[1]]
                if self.journal is not None:
                    self._cur_writes = []
                    self.journal.append(self._cur_writes)
                if act[2] != self._row:
                    self._row = act[2]
                    self._cur = None  # cached prefixes are per-row
                for piece in ops.iter_range(entry.span):
                    pair = piece
                    while True:
                        agent, _seq, alen = aa.local_span_to_agent_span(
                            pair.lv, len(pair))
                        consumed, xf = self._apply_one(agent, pair, alen)
                        head = pair if consumed == len(pair) else \
                            ops._slice_run(pair, 0, consumed)
                        if entry.emit:
                            yield (head.lv, head, xf)
                        if consumed == len(pair):
                            break
                        pair = ops._slice_run(pair, consumed, len(pair))


def merge_via_plan2(oplog, from_frontier, merge_frontier,
                    validate: bool = False):
    """Compile + execute a fork/join plan; returns (xf rows, final frontier).
    The stream is a valid transform of the `from` document (positions are in
    the evolving output frame) but emission ORDER is the plan's topological
    order, not the M1 walker's — differential tests compare applied text."""
    plan = compile_plan2(oplog.cg.graph, list(from_frontier),
                         list(merge_frontier))
    if validate:
        from .plan2 import validate_plan2
        validate_plan2(plan)
    out = []
    for span in plan.ff_spans:
        for piece in oplog.ops.iter_range(span):
            out.append((piece.lv, piece, piece.start))
    if plan.entries:
        ex = DenseExecutor(plan, oplog.cg.agent_assignment, oplog.ops)
        out.extend(ex.run())
    return out, plan.final_frontier


def apply_xf_stream(oplog, content, rows) -> str:
    """Apply an xf stream to a str/Rope-like `content`; returns the new
    text (delegates to Branch's shared application loop)."""
    from ..text.branch import Branch
    from ..utils.rope import Rope
    b = Branch()
    b.content = Rope(str(content))
    b._apply_xf(oplog, rows)
    return b.snapshot()
