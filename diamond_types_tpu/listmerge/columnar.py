"""Columnar export of plan/tracker state for the device transform.

The host tracker walk (`get_xf_operations_full`) resolves one op at a
time; the device transform (`tpu/xform.py`) instead consumes the whole
conflict zone as dense columns at RLE-run granularity:

  * the tracker's item table (ids / lengths / origin-left / origin-right
    / ever-deleted), exactly as `dump_tracker(keep_underwater=True)`
    returns it — one native transform extracts the origins, nothing
    walks the zone in Python;
  * the delete-target rows (`dump_del_rows`): op LV range -> target item
    range, the column that lets old-vs-new delete visibility be decided
    by an LV threshold instead of a per-op walk;
  * the fast-forward prefix text at the zone's common ancestor (the
    underwater spine's real text), plus the merge's union frontier.

This module also owns the agent-rank and insert-arena offset columns
that `tpu/merge_kernel.py` historically carried (`_agent_keys` /
`_arena_offsets` remain as aliases there): they are plain oplog column
extractions, not device code, and the transform path shares them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..native.core import UNDERWATER
from ..text.op import INS


class UnsupportedTail(Exception):
    """The tail's shape is outside the device transform's contract; the
    caller falls back to the host tracker walk (`plan_tail`)."""


def agent_key_columns(oplog, lvs: np.ndarray):
    """(name-rank, seq) per LV, vectorized over the agent-assignment runs.

    Reference tie-break: agent NAME order then seq
    (causalgraph/agent_assignment/mod.rs:163)."""
    aa = oplog.cg.agent_assignment
    gr = aa.global_runs
    lv0 = np.asarray([r[0] for r in gr], dtype=np.int64)
    ag = np.asarray([r[2] for r in gr], dtype=np.int64)
    sq0 = np.asarray([r[3] for r in gr], dtype=np.int64)
    o = np.argsort(lv0)
    lv0, ag, sq0 = lv0[o], ag[o], sq0[o]
    name_rank = np.asarray(np.argsort(np.argsort(aa.agent_names)))
    j = np.clip(np.searchsorted(lv0, lvs, side="right") - 1, 0, len(lv0) - 1)
    agent = np.where(lvs >= UNDERWATER, 0, name_rank[ag[j]])
    seq = np.where(lvs >= UNDERWATER, 0, sq0[j] + (lvs - lv0[j]))
    return agent, seq


def arena_offset_columns(oplog, lvs: np.ndarray) -> np.ndarray:
    """Insert-arena char offset of each LV (must be insert LVs)."""
    runs = oplog.ops.runs
    lv0 = np.asarray([r.lv for r in runs], dtype=np.int64)
    cp0 = np.asarray(
        [r.content_pos[0] if (r.kind == INS and r.content_pos is not None)
         else -1 for r in runs], dtype=np.int64)
    j = np.clip(np.searchsorted(lv0, lvs, side="right") - 1, 0, len(lv0) - 1)
    return cp0[j] + (lvs - lv0[j])


@dataclass
class TailColumns:
    """One document's conflict zone as dense columns (host-extracted)."""
    ids: np.ndarray       # [r] int64 item-run first LVs (doc order as dumped)
    ln: np.ndarray        # [r] int64 run lengths
    ol: np.ndarray        # [r] int64 origin-left LVs (-1 = ROOT)
    orr: np.ndarray       # [r] int64 origin-right LVs (-1 = ROOT)
    ev: np.ndarray        # [r] int64 ever-deleted flags
    del_lv0: np.ndarray   # [d] int64 delete-op LV range starts
    del_lv1: np.ndarray   # [d] int64 delete-op LV range ends (exclusive)
    del_t0: np.ndarray    # [d] int64 target item range starts
    del_t1: np.ndarray    # [d] int64 target item range ends (exclusive)
    del_fwd: np.ndarray   # [d] int64 1 = op lv0+k targets t0+k, 0 = t1-1-k
    prefix: str           # doc text at the zone's common ancestor
    union: Tuple[int, ...]   # version_union(from, merge) — the plan frontier
    arena: np.ndarray     # int32 char codes of the whole insert arena


def export_tail_columns(oplog, from_frontier: Sequence[int],
                        merge_frontier: Optional[Sequence[int]] = None
                        ) -> TailColumns:
    """One native transform -> the tail's columnar DAG tables.

    Raises UnsupportedTail for shapes the device transform does not
    model: an empty conflict zone (pure fast-forward — the host plan is
    already O(tail) with no concurrency to resolve) and reversed insert
    runs (their arena content order is not affine in LV, so the run-
    granular char columns cannot describe them)."""
    from ..native.core import get_native_ctx

    ctx = get_native_ctx(oplog)
    frm = [int(x) for x in from_frontier]
    merge = ([int(x) for x in oplog.version] if merge_frontier is None
             else [int(x) for x in merge_frontier])
    lv, ln_ops, kind, fwd, _pos, union = ctx.transform(frm, merge)
    if (np.asarray(ln_ops) > 0).any() and \
            ((np.asarray(kind) == INS) & (np.asarray(fwd) == 0)).any():
        ctx.release_tracker()
        raise UnsupportedTail("reversed insert run in zone")
    ids, ln, ol, orr, _st, ev = ctx.dump_tracker(keep_underwater=True)
    if len(ids) == 0:
        ctx.release_tracker()
        raise UnsupportedTail("empty conflict zone (pure fast-forward)")
    dl0, dl1, dt0, dt1, dfw = ctx.dump_del_rows()
    common = ctx.zone_common()
    prefix = ctx.merge_to_string("", [], common)[0] if common else ""
    ctx.release_tracker()
    arena_str = oplog.ops._arenas[INS].get((0, oplog.ops.arena_len(INS)))
    arena = np.frombuffer(arena_str.encode("utf-32-le"), dtype=np.int32)
    return TailColumns(
        ids=ids, ln=ln, ol=ol, orr=orr, ev=ev,
        del_lv0=dl0, del_lv1=dl1, del_t0=dt0, del_t1=dt1, del_fwd=dfw,
        prefix=prefix, union=tuple(int(x) for x in union), arena=arena)


def old_delete_intervals(cols: TailColumns, synced_to: int
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Target item intervals deleted by zone ops with LV < synced_to.

    The zone covers BOTH branches past the common ancestor, so its
    delete rows mix ops the session already applied (LV < synced_to —
    the log-prefix-frontier threshold, see tpu/xform.py) with
    concurrent/new ones. A straddling row [lv0, lv1) contributes only
    its old portion, direction-resolved per `del_fwd`. Returns
    (starts, ends) — possibly overlapping (double deletes)."""
    lv0, lv1 = cols.del_lv0, cols.del_lv1
    t0, t1, fw = cols.del_t0, cols.del_t1, cols.del_fwd
    m = np.minimum(lv1, synced_to)
    old = m > lv0
    k = (m - lv0)[old]
    starts = np.where(fw[old] != 0, t0[old], t1[old] - k)
    ends = np.where(fw[old] != 0, t0[old] + k, t1[old])
    return starts.astype(np.int64), ends.astype(np.int64)


def visibility_cuts(cols: TailColumns, synced_to: int) -> np.ndarray:
    """Extra item-run cut points that make per-run visibility
    all-or-nothing: the old/new insert threshold (synced_to), every
    delete-target boundary, and the old/new split point inside each
    straddling delete row."""
    cuts: List[np.ndarray] = [
        np.asarray([synced_to], dtype=np.int64),
        cols.del_t0.astype(np.int64), cols.del_t1.astype(np.int64)]
    lv0, lv1 = cols.del_lv0, cols.del_lv1
    straddle = (lv0 < synced_to) & (synced_to < lv1)
    if straddle.any():
        k = synced_to - lv0[straddle]
        cuts.append(np.where(cols.del_fwd[straddle] != 0,
                             cols.del_t0[straddle] + k,
                             cols.del_t1[straddle] - k).astype(np.int64))
    return np.unique(np.concatenate(cuts))
