"""Transformed-operation stream: the top of the merge pipeline.

Capability mirror of the reference TransformedOpsIter (reference:
src/listmerge/merge.rs:585-941): given a causal graph, op table and two
frontiers (`from`, `merge`), yield every op in merge's history that `from`
hasn't seen, with positions transformed onto `from`'s document frame.

Pipeline (reference strategy, re-expressed):
  1. find_conflicting splits the zone into `new_ops` (only-B) and
     `conflict_ops` (shared / only-A).
  2. Fast-forward: while the next new span's parents == our frontier, ops
     stream through untransformed (linear history; reference merge.rs:792-859).
  3. Otherwise build a Tracker over the conflict set, then walk the new spans
     in causal order, advancing/retreating the tracker between spans and
     transforming each op run.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from ..causalgraph.agent import AgentAssignment
from ..causalgraph.graph import DiffFlag, Graph
from ..core.span import Span, push_reversed_rle
from ..text.op import DEL, INS, OpRun, OpStore
from .tracker import Tracker
from .walker import SpanningTreeWalker

# xf results: ("ok", pos) == BaseMoved; ("gone", None) == DeleteAlreadyHappened
XfOp = Tuple[int, OpRun, Optional[int]]


class TransformedOps:
    """Iterate (lv, op_piece, xf_pos | None) triples; after exhaustion,
    `final_frontier` holds the merged version."""

    def __init__(self, graph: Graph, aa: AgentAssignment, ops: OpStore,
                 from_frontier: List[int], merge_frontier: List[int]) -> None:
        self.graph = graph
        self.aa = aa
        self.ops = ops
        self.merge_frontier = list(merge_frontier)
        self.next_frontier = list(from_frontier)
        self.tracker: Optional[Tracker] = None

        self.new_ops: List[Span] = []
        self.conflict_ops: List[Span] = []

        def visit(span: Span, flag: DiffFlag) -> None:
            target = self.new_ops if flag == DiffFlag.ONLY_B else self.conflict_ops
            push_reversed_rle(target, span)

        self.common_ancestor = graph.find_conflicting(
            from_frontier, merge_frontier, visit)

    def __iter__(self) -> Iterator[XfOp]:
        return self._gen()

    @property
    def collisions(self) -> int:
        """Colliding concurrent inserts seen while transforming (valid
        after the iterator is exhausted; reference: merge_conflict_checks
        flag, listmerge/mod.rs:50-51)."""
        return self.tracker.collisions if self.tracker is not None else 0

    def _gen(self) -> Iterator[XfOp]:
        graph, aa, ops = self.graph, self.aa, self.ops

        # --- Phase 1: fast-forward over linear history -------------------
        did_ff = False
        while self.new_ops:
            span = self.new_ops[-1]
            i = graph.find_idx(span[0])
            parents = graph.parents_at(span[0])
            if list(parents) != self.next_frontier:
                break
            self.new_ops.pop()
            take_end = min(graph.ends[i], span[1])
            if take_end < span[1]:
                self.new_ops.append((take_end, span[1]))
            self.next_frontier = [take_end - 1]
            did_ff = True
            for piece in ops.iter_range((span[0], take_end)):
                yield (piece.lv, piece, piece.start)

        if not self.new_ops:
            return

        if did_ff:
            # Re-scan the (smaller) conflict zone from the new frontier.
            self.conflict_ops = []

            def visit(span: Span, flag: DiffFlag) -> None:
                if flag != DiffFlag.ONLY_B:
                    push_reversed_rle(self.conflict_ops, span)

            self.common_ancestor = graph.find_conflicting(
                self.next_frontier, self.merge_frontier, visit)

        # --- Phase 2: tracked merge --------------------------------------
        tracker = Tracker()
        self.tracker = tracker
        frontier = self._walk_populate(tracker)

        walker = SpanningTreeWalker(graph, self.new_ops, frontier)
        for walk in walker:
            for rng in walk.retreat:
                tracker.retreat_by_range(rng)
            for rng in reversed(walk.advance_rev):
                tracker.advance_by_range(rng)
            graph.advance_frontier(self.next_frontier, walk.consume)

            for piece in ops.iter_range(walk.consume):
                pair = piece
                while True:
                    _agent, _seq, agent_len = aa.local_span_to_agent_span(
                        pair.lv, len(pair))
                    consumed, xf = tracker.apply(aa, _agent, pair, agent_len)
                    if consumed == len(pair):
                        yield (pair.lv, pair, xf)
                        break
                    head = ops._slice_run(pair, 0, consumed)
                    pair = ops._slice_run(pair, consumed, len(pair))
                    yield (head.lv, head, xf)

    def _walk_populate(self, tracker: Tracker) -> List[int]:
        """Build the tracker over the conflict set ("hot"), returning the
        walker's final frontier (reference: merge.rs:560-581 M2Tracker::walk)."""
        walker = SpanningTreeWalker(self.graph, self.conflict_ops,
                                    list(self.common_ancestor))
        for walk in walker:
            for rng in walk.retreat:
                tracker.retreat_by_range(rng)
            for rng in reversed(walk.advance_rev):
                tracker.advance_by_range(rng)
            for piece in self.ops.iter_range(walk.consume):
                pair = piece
                while True:
                    agent, _seq, agent_len = self.aa.local_span_to_agent_span(
                        pair.lv, len(pair))
                    consumed, _xf = tracker.apply(self.aa, agent, pair, agent_len)
                    if consumed == len(pair):
                        break
                    pair = self.ops._slice_run(pair, consumed, len(pair))
        return walker.frontier
