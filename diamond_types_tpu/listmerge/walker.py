"""Spanning-tree traversal of the conflict DAG.

Capability mirror of the reference SpanningTreeWalker (reference:
src/listmerge/txn_trace.rs:75-332): visit every span of a set of (reverse
ordered) LV spans exactly once, in causal order, emitting for each visit the
frontier retreat/advance schedule that moves the tracker to the span's parent
version with minimal churn.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..causalgraph.graph import Graph
from ..core.span import Span


class _VisitEntry:
    __slots__ = ("span", "parents", "parent_idxs", "child_idxs", "visited")

    def __init__(self, span: Span, parents: Tuple[int, ...]) -> None:
        self.span = span
        self.parents = parents
        self.parent_idxs: List[int] = []
        self.child_idxs: List[int] = []
        self.visited = False


class WalkItem:
    __slots__ = ("retreat", "advance_rev", "parents", "consume")

    def __init__(self, retreat, advance_rev, parents, consume) -> None:
        self.retreat: List[Span] = retreat        # descending order
        self.advance_rev: List[Span] = advance_rev  # descending order
        self.parents = parents
        self.consume: Span = consume


class SpanningTreeWalker:
    def __init__(self, graph: Graph, rev_spans: Sequence[Span],
                 start_at: List[int], track_frontier: bool = True) -> None:
        """With track_frontier=False the walker yields the same traversal
        order and parents but skips the per-step frontier diff (the
        retreat/advance lists come back empty) — for consumers like the
        encoder that only need (consume, parents), this removes the
        dominant graph-query cost."""
        self.graph = graph
        self.track_frontier = track_frontier
        # NOTE: with track_frontier=False, `frontier` is intentionally NOT
        # maintained; reading it raises (see frontier property) so callers
        # that copy the plan.py chaining pattern fail loudly.
        self._frontier: List[int] = list(start_at)
        self.input: List[_VisitEntry] = []
        self.to_process: List[int] = []

        def find_entry_idx(t: int) -> Optional[int]:
            # binary search entries by span containment
            lo, hi = 0, len(self.input)
            while lo < hi:
                mid = (lo + hi) // 2
                s = self.input[mid].span
                if t < s[0]:
                    hi = mid
                elif t >= s[1]:
                    lo = mid + 1
                else:
                    return mid
            return None

        for span in reversed(rev_spans):  # ascending order
            start, end = span
            i = graph.find_idx(start)
            while start < end:
                t_end = min(graph.ends[i], end)
                parents = graph.parents_at(start)
                e = _VisitEntry((start, t_end), parents)
                e.parent_idxs = [pi for pi in
                                 (find_entry_idx(p) for p in parents)
                                 if pi is not None]
                if not e.parent_idxs:
                    self.to_process.append(len(self.input))
                self.input.append(e)
                start = t_end
                i += 1

        for i, e in enumerate(self.input):
            for p in e.parent_idxs:
                self.input[p].child_idxs.append(i)

        self.to_process.reverse()
        assert not rev_spans or self.to_process

    def __iter__(self):
        return self

    def __next__(self) -> WalkItem:
        # Preferentially expand non-merge entries (reference: txn_trace.rs:243-265).
        if not self.to_process:
            raise StopIteration
        idx = self.to_process[-1]
        if len(self.input[idx].parents) >= 2:
            found = None
            for ii in range(len(self.to_process) - 1, -1, -1):
                if len(self.input[self.to_process[ii]].parents) < 2:
                    found = ii
                    break
            if found is not None:
                idx = self.to_process[found]
                # swap_remove
                self.to_process[found] = self.to_process[-1]
                self.to_process.pop()
            else:
                self.to_process.pop()
        else:
            self.to_process.pop()

        e = self.input[idx]
        e.visited = True
        parents = e.parents
        span = e.span

        if self.track_frontier:
            only_branch, only_txn = self.graph.diff_rev(self._frontier,
                                                        list(parents))
            for rng in only_branch:
                self.graph.retreat_frontier(self._frontier, rng)
            for rng in reversed(only_txn):
                self.graph.advance_frontier(self._frontier, rng)
            self.graph._advance_known_run(self._frontier, parents, span)
        else:
            only_branch, only_txn = [], []

        for c in e.child_idxs:
            ce = self.input[c]
            if ce.visited:
                continue
            if all(self.input[p].visited for p in ce.parent_idxs):
                self.to_process.append(c)

        return WalkItem(only_branch, only_txn, parents, span)

    @property
    def frontier(self) -> List[int]:
        if not self.track_frontier:
            raise RuntimeError(
                "walker built with track_frontier=False does not maintain "
                "a frontier; construct with track_frontier=True to chain "
                "walks from walker.frontier")
        return self._frontier
