"""Per-entry op composition — the host half of device origin extraction.

A conflict-zone entry (plan2.SubgraphEntry) is a linear run of ops whose
positions are each relative to the document as the entry's own previous
ops left it. The M1 engine resolves those positions one op at a time with
a tracker cursor (reference: src/listmerge/merge.rs:395-423 — the per-op
origin scan). This module instead *composes* each entry's ops into
ENTRY-START coordinates with a piece table, so that:

  * every position the device must resolve is relative to one frozen
    snapshot (the doc at the entry's parent version) — resolvable for the
    whole entry with two prefix sums (tpu/zone_kernel.py);
  * the entry's own inserted chars are grouped into "blocks" (maximal
    runs of own chars between snapshot chars). Each block has exactly one
    snapshot-anchored ROOT run; every other run in the block chains off
    own chars and therefore never competes with concurrent siblings (a
    concurrent op cannot anchor onto chars it cannot causally see), so
    only the root needs the YjsMod sibling comparison.

Composition is pure control flow over the op table: no tracker, no text,
no M1 transform. It replaces the full `ctx.transform` call the round-2
device path still depended on (VERDICT r2 missing #1).

Piece-table semantics mirror the tracker cursor exactly:
  * the insert cursor lands immediately after the visible char at pos-1,
    BEFORE any adjacent tombstones (merge.rs cursor positioning);
  * deleted pieces stay in the table as tombstones — they are origin-
    right candidates (origin_right skips only NotInsertedYet items,
    merge.rs:407-424, and chars this entry deleted were alive in the
    snapshot, so the device resolves them identically);
  * delete targets are recorded against snapshot coords (for snapshot
    chars) or own char ids (for chars this entry inserted itself).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..text.op import INS

# Sentinel "infinite" snapshot length: the composer cannot know the
# entry-start visible length (it depends on the state row at runtime); ops
# never reference positions beyond the true length, so an infinite base
# piece yields identical splits.
BASE_INF = 1 << 40

# Run-head kinds (how the head char anchors).
K_OWN = 1        # right child of an own char (anchor_lv)
K_LEFTJOIN = 2   # left child of an own char (anchor_lv); ol via query q
K_ROOT = 3       # block root: anchors via query q, case decided on device


class _P:
    """Piece: `base >= 0` — snapshot chars [base, base+length) in
    entry-start coords; `base == -1` — own chars [lv, lv+length) whose
    governing run head is `head`."""

    __slots__ = ("base", "lv", "length", "alive", "head", "prio", "left",
                 "right", "up", "sub_alive")

    def __init__(self, base: int, lv: int, length: int, alive: bool,
                 prio: int, head: int = -1):
        self.base = base
        self.lv = lv
        self.length = length
        self.alive = alive
        self.head = head
        self.prio = prio
        self.left: Optional[_P] = None
        self.right: Optional[_P] = None
        self.up: Optional[_P] = None
        self.sub_alive = length if alive else 0

    @property
    def own_alive(self) -> int:
        return self.length if self.alive else 0


def _upd(n: _P) -> None:
    # hot path (called along the root path for every mutation): inline
    # the own-alive term rather than paying a property call
    s = n.length if n.alive else 0
    if n.left is not None:
        s += n.left.sub_alive
    if n.right is not None:
        s += n.right.sub_alive
    n.sub_alive = s


def _fix_up(n: Optional[_P]) -> None:
    while n is not None:
        _upd(n)
        n = n.up


@dataclass
class ComposedEntry:
    """One entry's composition result (see module docstring). All own-char
    references are LVs; the slot mapping is applied by the executor."""
    # queries: cursor coords in entry-start-visible space
    q_cursor: List[int] = field(default_factory=list)
    # per own char, grouped by block in final (piece-table) order
    ch_lv: np.ndarray = None          # int64 [nc]
    ch_block: np.ndarray = None       # int32 [nc]
    ch_head: np.ndarray = None        # int8  [nc] 1 = run head char
    ch_kind: np.ndarray = None        # int8  [nc] K_* for heads, 0 interior
    ch_anchor: np.ndarray = None      # int64 [nc] own anchor lv or -1
    ch_q: np.ndarray = None           # int32 [nc] query idx or -1
    ch_headlv: np.ndarray = None      # int64 [nc] governing run-head lv
    ch_orrown: np.ndarray = None      # int64 [nc] own-char orr lv or -1 (=B)
    # per block
    blk_root_q: np.ndarray = None     # int32 [nb] root query idx
    blk_root_lv: np.ndarray = None    # int64 [nb] root head char lv
    blk_start: np.ndarray = None      # int32 [nb] first char idx in ch_*
    blk_len: np.ndarray = None        # int32 [nb]
    # deletes
    del_base: List[Tuple[int, int]] = field(default_factory=list)  # coords
    del_own: List[Tuple[int, int]] = field(default_factory=list)   # lv range

    def num_chars(self) -> int:
        return 0 if self.ch_lv is None else len(self.ch_lv)


@dataclass
class _HeadMeta:
    kind: int
    anchor_lv: int   # own char lv (K_OWN parent / K_LEFTJOIN parent)
    q: int           # query idx (K_LEFTJOIN ol / K_ROOT), else -1
    block: int       # block id the run belongs to
    orr_own: int     # origin-right when it is an own char (next piece at
                     # insert time was own): its lv; -1 = the block's B
                     # (the snapshot-resolved origin-right — a run whose
                     # right neighbor at insert time was the snapshot is
                     # the block's current tail, so its origin-right IS
                     # the root's device-resolved B; merge.rs:407-424)


class EntryComposer:
    """Piece-table composer for one entry's sequential op stream."""

    def __init__(self) -> None:
        self._next_prio = 0x9E3779B97F4A7C15
        self.root: Optional[_P] = _P(0, -1, BASE_INF, True, self._prio())
        self.q_cursor: List[int] = []
        self.heads: Dict[int, _HeadMeta] = {}   # run-head lv -> meta
        self.n_blocks = 0
        self.blk_root_lv: List[int] = []        # block id -> root head lv
        self.del_base: List[Tuple[int, int]] = []
        self.del_own: List[Tuple[int, int]] = []

    def _prio(self) -> int:
        # splitmix64: deterministic, well-mixed treap priorities
        self._next_prio = (self._next_prio + 0x9E3779B97F4A7C15) & ((1 << 64) - 1)
        z = self._next_prio
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & ((1 << 64) - 1)
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & ((1 << 64) - 1)
        return z ^ (z >> 31)

    # ---- treap machinery -------------------------------------------------

    def _rot_up(self, x: _P) -> None:
        p = x.up
        g = p.up
        if p.left is x:
            p.left = x.right
            if p.left is not None:
                p.left.up = p
            x.right = p
        else:
            p.right = x.left
            if p.right is not None:
                p.right.up = p
            x.left = p
        p.up = x
        x.up = g
        if g is not None:
            if g.left is p:
                g.left = x
            else:
                g.right = x
        else:
            self.root = x
        _upd(p)
        _upd(x)

    def _bubble(self, x: _P) -> None:
        while x.up is not None and x.up.prio < x.prio:
            self._rot_up(x)
        if x.up is None:
            self.root = x
        else:
            _fix_up(x.up)

    def _insert_after(self, a: Optional[_P], x: _P) -> None:
        """Insert piece x immediately after piece a (a=None → first)."""
        if a is None:
            n = self.root
            if n is None:
                self.root = x
                return
            while n.left is not None:
                n = n.left
            n.left = x
            x.up = n
        elif a.right is None:
            a.right = x
            x.up = a
        else:
            n = a.right
            while n.left is not None:
                n = n.left
            n.left = x
            x.up = n
        _fix_up(x.up)
        self._bubble(x)

    @staticmethod
    def _succ(n: _P) -> Optional[_P]:
        if n.right is not None:
            n = n.right
            while n.left is not None:
                n = n.left
            return n
        while n.up is not None and n.up.right is n:
            n = n.up
        return n.up

    def _find_visible(self, pos: int) -> Tuple[_P, int]:
        """(piece, offset) of visible char `pos` (0-indexed)."""
        n = self.root
        while n is not None:
            la = n.left.sub_alive if n.left is not None else 0
            if pos < la:
                n = n.left
            elif n.alive and pos < la + n.length:
                return n, pos - la
            else:
                pos -= la + n.own_alive
                n = n.right
        raise IndexError("visible position out of range")

    def _split(self, n: _P, off: int) -> _P:
        """Split piece at char offset (0 < off < length); returns the
        right half (inserted immediately after n)."""
        assert 0 < off < n.length
        if n.base >= 0:
            right = _P(n.base + off, -1, n.length - off, n.alive,
                       self._prio())
        else:
            right = _P(-1, n.lv + off, n.length - off, n.alive,
                       self._prio(), head=n.head)
        n.length = off
        _fix_up(n)
        self._insert_after(n, right)
        return right

    # ---- ops -------------------------------------------------------------

    def insert(self, pos: int, lv: int, length: int) -> None:
        if pos == 0:
            prev = None
        else:
            node, off = self._find_visible(pos - 1)
            if off + 1 < node.length:
                self._split(node, off + 1)
            prev = node
        nxt = self._succ(prev) if prev is not None else self._leftmost()

        orr_own = nxt.lv if (nxt is not None and nxt.base < 0) else -1
        if prev is not None and prev.base < 0:
            # ol is an own char: right child of it (K_OWN)
            anchor = prev.lv + prev.length - 1
            meta = _HeadMeta(K_OWN, anchor, -1, self.heads[prev.head].block,
                             orr_own)
        elif nxt is not None and nxt.base < 0:
            # ol snapshot/doc-start, next piece own: left-join that block
            q = self._emit_query(prev)
            meta = _HeadMeta(K_LEFTJOIN, nxt.lv, q,
                             self.heads[nxt.head].block, orr_own)
        else:
            # new block root
            q = self._emit_query(prev)
            blk = self.n_blocks
            self.n_blocks += 1
            self.blk_root_lv.append(lv)
            meta = _HeadMeta(K_ROOT, -1, q, blk, -1)
        self.heads[lv] = meta
        new = _P(-1, lv, length, True, self._prio(), head=lv)
        self._insert_after(prev, new)

    def _emit_query(self, prev: Optional[_P]) -> int:
        """Query for the snapshot gap after `prev` (a snapshot piece or
        None = doc start). Cursor coord = snapshot chars before the gap."""
        assert prev is None or prev.base >= 0, "query gap must be snapshot"
        c = 0 if prev is None else prev.base + prev.length
        self.q_cursor.append(c)
        return len(self.q_cursor) - 1

    def _leftmost(self) -> Optional[_P]:
        n = self.root
        if n is None:
            return None
        while n.left is not None:
            n = n.left
        return n

    def delete(self, pos: int, length: int) -> None:
        node, off = self._find_visible(pos)
        if off > 0:
            node = self._split(node, off)
        remaining = length
        while remaining > 0:
            assert node is not None, "delete past end of document"
            if not node.alive:
                node = self._succ(node)
                continue
            take = min(remaining, node.length)
            if take < node.length:
                self._split(node, take)
            if node.base >= 0:
                self.del_base.append((node.base, node.base + take))
            else:
                self.del_own.append((node.lv, node.lv + take))
            node.alive = False
            _fix_up(node)
            remaining -= take
            node = self._succ(node)

    # ---- result ----------------------------------------------------------

    def _in_order(self) -> List[_P]:
        out: List[_P] = []
        st: List[_P] = []
        cur = self.root
        while st or cur is not None:
            while cur is not None:
                st.append(cur)
                cur = cur.left
            cur = st.pop()
            out.append(cur)
            cur = cur.right
        return out

    def finish(self) -> ComposedEntry:
        out = ComposedEntry()
        out.q_cursor = self.q_cursor
        out.del_base = self.del_base
        out.del_own = self.del_own

        # walk the table in order, collecting own PIECES grouped by their
        # block ids; intra-block order IS table order (char columns are
        # expanded vectorized below — per-char Python tuples were the
        # composition profile's second-hottest line)
        per_block: Dict[int, List[Tuple[int, int, int]]] = {}
        for p in self._in_order():
            if p.base >= 0:
                continue
            blk = self.heads[p.head].block
            per_block.setdefault(blk, []).append((p.lv, p.length, p.head))

        # per-piece rows, then one vectorized char expansion
        p_lv: List[int] = []
        p_len: List[int] = []
        p_blk: List[int] = []
        p_headlv: List[int] = []
        p_orrown: List[int] = []
        blk_start: List[int] = []
        blk_len: List[int] = []
        blk_root_q: List[int] = []
        blk_root_lv: List[int] = []
        total = 0
        for blk in sorted(per_block):
            pieces = per_block[blk]
            blk_start.append(total)
            blk_len.append(sum(ln for _, ln, _ in pieces))
            total += blk_len[-1]
            root_lv = self.blk_root_lv[blk]
            blk_root_q.append(self.heads[root_lv].q)
            blk_root_lv.append(root_lv)
            bi = len(blk_start) - 1
            for (lv, ln, head_lv) in pieces:
                p_lv.append(lv)
                p_len.append(ln)
                p_blk.append(bi)
                p_headlv.append(head_lv)
                p_orrown.append(self.heads[head_lv].orr_own)

        plv = np.asarray(p_lv, dtype=np.int64)
        plen = np.asarray(p_len, dtype=np.int64)
        rep = np.repeat(np.arange(len(plv)), plen)
        cum = np.concatenate([[0], np.cumsum(plen)])[:-1]
        off = np.arange(total, dtype=np.int64) - cum[rep]
        out.ch_lv = plv[rep] + off
        out.ch_block = np.asarray(p_blk, dtype=np.int32)[rep]
        out.ch_headlv = np.asarray(p_headlv, dtype=np.int64)[rep]
        out.ch_orrown = np.asarray(p_orrown, dtype=np.int64)[rep]
        # head flags/metadata: a char is a run head iff its lv IS the
        # piece's governing head lv (splits never create heads)
        is_head = out.ch_lv == out.ch_headlv
        out.ch_head = is_head.astype(np.int8)
        kind = np.zeros(total, dtype=np.int8)
        anchor = np.full(total, -1, dtype=np.int64)
        qq = np.full(total, -1, dtype=np.int32)
        for i in np.flatnonzero(is_head):
            meta = self.heads[int(out.ch_lv[i])]
            kind[i] = meta.kind
            anchor[i] = meta.anchor_lv
            qq[i] = meta.q
        out.ch_kind = kind
        out.ch_anchor = anchor
        out.ch_q = qq
        out.blk_root_q = np.asarray(blk_root_q, dtype=np.int32)
        out.blk_root_lv = np.asarray(blk_root_lv, dtype=np.int64)
        out.blk_start = np.asarray(blk_start, dtype=np.int32)
        out.blk_len = np.asarray(blk_len, dtype=np.int32)
        return out


def compose_entry(oplog, span: Tuple[int, int]) -> ComposedEntry:
    """Compose one entry's op stream into entry-start coordinates."""
    comp = EntryComposer()
    for piece in oplog.ops.iter_range(span):
        if piece.kind == INS:
            assert piece.fwd, "reverse insert runs are unimplemented " \
                "(matches reference merge.rs:384 unimplemented!)"
            comp.insert(piece.start, piece.lv, len(piece))
        else:
            comp.delete(piece.start, len(piece))
    return comp.finish()


def _native_composed(oplog, spans) -> Optional[List[ComposedEntry]]:
    """Run the C++ composer (native/dt_core.cpp Composer — same piece-
    table semantics, ~20x faster); None when unavailable/unsupported."""
    from ..native import native_ctx_or_none
    ctx = native_ctx_or_none(oplog)
    if ctx is None:
        return None
    rows = ctx.compose_plan(spans)
    if rows is None:
        return None
    return [ComposedEntry(**r) for r in rows]


def compose_plan(oplog, plan) -> List[ComposedEntry]:
    """Compose every entry of a fork/join plan (host control-flow pass)."""
    native = _native_composed(oplog, [en.span for en in plan.entries])
    if native is not None:
        return native
    return [compose_entry(oplog, en.span) for en in plan.entries]


def assemble_prefix(oplog, ff_spans) -> str:
    """Replay the linear fast-forward prefix WITHOUT any merge engine: the
    spans are causally linear (plan2's ff extraction), so one piece-table
    composition over an empty base reconstructs the text directly from the
    insert arena (reference equivalent: the FF-mode streaming of
    merge.rs:792-859, minus the tracker)."""
    from ..native import native_ctx_or_none
    spans = sorted(ff_spans)
    ctx = native_ctx_or_none(oplog)
    if ctx is not None:
        res = ctx.compose_linear(spans)
        if res is not None:
            lvs, lens = res
            parts = []
            for lv, ln in zip(lvs.tolist(), lens.tolist()):
                s = oplog.ops.content_slice(lv, ln)
                assert s is not None, "insert content missing from arena"
                parts.append(s)
            return "".join(parts)
    comp = EntryComposer()
    comp.root = None   # no snapshot: the prefix starts from nothing
    for (s, e) in spans:
        for piece in oplog.ops.iter_range((s, e)):
            if piece.kind == INS:
                comp.insert(piece.start, piece.lv, len(piece))
            else:
                comp.delete(piece.start, len(piece))
    parts: List[str] = []
    for p in comp._in_order():
        if p.base < 0 and p.alive:
            s = oplog.ops.content_slice(p.lv, p.length)
            assert s is not None, "insert content missing from arena"
            parts.append(s)
    return "".join(parts)
