"""Fork/join merge plans over numbered state indexes.

Capability mirror of the reference's listmerge2 action plans (reference:
src/listmerge2/action_plan.rs:11-37 `MergePlanAction` —
Apply/ForkIndex/DropIndex/MaxIndex over numbered indexes; conflict subgraph
in src/listmerge2/mod.rs:20-33, conflict_subgraph.rs): instead of moving ONE
tracker state back and forth along the conflict DAG with advance/retreat the
way the M1 engine does, keep SEVERAL numbered tracker states ("indexes")
alive at once:

  * every conflict-subgraph entry (a run of ops with one parents set) is
    applied exactly once, to exactly one index;
  * branches fork an index (copy its state row);
  * merge points join indexes with an elementwise state MAX — valid because
    listmerge2's span states are the 3-point lattice NotInsertedYet(0) <
    Inserted(1) < Deleted(2) (reference: listmerge2/yjsspan.rs SpanState)
    where delete *counts* are unnecessary: counts only exist in M1 so that
    retreat can undo one delete at a time, and this engine never retreats.

The compile step is pure control flow (host); execution is pure data
movement over a flat span table with a dense [n_spans, n_indexes] state
matrix (see dense.py) — the representation that lowers to the TPU tier
(reference: listmerge2/index_gap_buffer.rs:20-31 dense state matrix).

Unlike the reference's DFS planner (action_plan.rs plan_first_pass /
make_plan, which discovers fork/join structure by walking up and down the
subgraph), this compiler exploits a property the reference's own data
guarantees but its planner doesn't use: ascending-LV order over subgraph
entries IS a topological order (parents always have lower LVs). One linear
pass with refcounted index allocation emits the same action algebra with a
free-list bound on peak indexes.
"""

from __future__ import annotations

from bisect import bisect_right
from dataclasses import dataclass, field
from typing import List, Tuple

from ..causalgraph.graph import DiffFlag, Graph
from ..core.span import Span, push_reversed_rle

# Action opcodes (plan actions are plain tuples so the schedule can be
# packed into arrays for the device tier).
BEGIN = 0   # (BEGIN, idx)             row <- base state (fresh index)
FORK = 1    # (FORK, src, dest)        row[dest] <- row[src]
MAX = 2     # (MAX, dest, src)         row[dest] <- max(row[dest], row[src])
DROP = 3    # (DROP, idx)              free the index
APPLY = 4   # (APPLY, entry_i, idx)    apply entry's op span at row[idx]


@dataclass
class SubgraphEntry:
    """One run of ops in the conflict zone with a single parents set
    (reference: listmerge2/mod.rs ConflictGraphEntry)."""
    span: Span
    parents: Tuple[int, ...]   # indexes of in-zone parent ENTRIES (topo order)
    emit: bool                 # True for only-B ops (new to `from`)
    num_children: int = 0


@dataclass
class MergePlan2:
    entries: List[SubgraphEntry] = field(default_factory=list)
    actions: List[tuple] = field(default_factory=list)
    indexes_used: int = 0
    ff_spans: List[Span] = field(default_factory=list)
    final_frontier: List[int] = field(default_factory=list)
    common: List[int] = field(default_factory=list)  # zone common ancestor
    # pin_lvs support: lv -> index holding that version's state row at
    # plan end (the row is never dropped; device sessions resume from it)
    pinned_rows: dict = field(default_factory=dict)

    def num_ops(self) -> int:
        n = sum(b - a for (a, b) in self.ff_spans)
        n += sum(e.span[1] - e.span[0] for e in self.entries if e.emit)
        return n


def _build_subgraph(graph: Graph, zone_spans: List[Tuple[Span, bool]]
                    ) -> List[SubgraphEntry]:
    """Split zone spans into entries (one parents set each), resolving parent
    LVs to entry indexes. `zone_spans` is ascending and disjoint."""
    # Pass 1: split at graph-run boundaries so each piece lives in one run.
    pieces: List[Tuple[int, int, bool]] = []
    for (s, e), emit in zone_spans:
        v = s
        while v < e:
            i = graph.find_idx(v)
            take = min(e, graph.ends[i])
            pieces.append((v, take, emit))
            v = take

    # Pass 2: cut after every LV that some zone piece names as a parent, so
    # parent LVs always sit at the END of the entry containing them.
    in_zone_starts = [p[0] for p in pieces]

    def in_zone(lv: int) -> bool:
        j = bisect_right(in_zone_starts, lv) - 1
        return j >= 0 and lv < pieces[j][1]

    cuts = set()
    for (s, _e, _emit) in pieces:
        i = graph.find_idx(s)
        if s == graph.starts[i]:
            for p in graph.parents[i]:
                if in_zone(p):
                    cuts.add(p + 1)

    entries: List[SubgraphEntry] = []
    sorted_cuts = sorted(cuts)
    for (s, e, emit) in pieces:
        v = s
        while v < e:
            j = bisect_right(sorted_cuts, v)
            nxt = sorted_cuts[j] if j < len(sorted_cuts) and \
                sorted_cuts[j] < e else e
            entries.append(SubgraphEntry((v, nxt), (), emit))
            v = nxt

    # Pass 3: resolve parents to entry indexes (ascending order = topo order).
    starts = [en.span[0] for en in entries]

    def entry_of(lv: int) -> int:
        j = bisect_right(starts, lv) - 1
        assert j >= 0 and lv < entries[j].span[1], "parent not in zone"
        assert lv == entries[j].span[1] - 1, "parent must end its entry"
        return j

    for k, en in enumerate(entries):
        s = en.span[0]
        i = graph.find_idx(s)
        if s == graph.starts[i]:
            plist = [entry_of(p) for p in graph.parents[i] if in_zone(p)]
        else:
            # Implicit mid-run parent: the previous piece of the same run
            # (unless the zone boundary cuts through the run right here —
            # then the parent is part of the base state).
            plist = [entry_of(s - 1)] if in_zone(s - 1) else []
        en.parents = tuple(plist)
        for p in plist:
            entries[p].num_children += 1
    return entries


def _alloc_actions(entries: List[SubgraphEntry],
                   pinned: Tuple[int, ...] = ()
                   ) -> Tuple[List[tuple], int, dict]:
    """Refcounted index allocation over the topo order. `pinned` entries
    keep their row alive past plan end (an extra phantom use); the
    returned dict maps pinned entry index -> row."""
    actions: List[tuple] = []
    free: List[int] = []
    next_idx = 0
    peak = 0
    row = [-1] * len(entries)
    uses = [en.num_children for en in entries]
    for k in pinned:
        uses[k] += 1

    def alloc() -> int:
        nonlocal next_idx, peak
        if free:
            i = free.pop()
        else:
            i = next_idx
            next_idx += 1
        peak = max(peak, next_idx - len(free))
        return i

    for k, en in enumerate(entries):
        if not en.parents:
            idx = alloc()
            actions.append((BEGIN, idx))
        else:
            p0 = en.parents[0]
            if uses[p0] == 1:
                idx = row[p0]          # consume the parent's row in place
            else:
                idx = alloc()
                actions.append((FORK, row[p0], idx))
            uses[p0] -= 1
            for pk in en.parents[1:]:
                actions.append((MAX, idx, row[pk]))
                uses[pk] -= 1
                if uses[pk] == 0:
                    actions.append((DROP, row[pk]))
                    free.append(row[pk])
        actions.append((APPLY, k, idx))
        row[k] = idx
        if uses[k] == 0:
            actions.append((DROP, idx))
            free.append(idx)
    return actions, peak, {k: row[k] for k in pinned}


def compile_plan2(graph: Graph, from_frontier: List[int],
                  merge_frontier: List[int],
                  pin_lvs: Tuple[int, ...] = ()) -> MergePlan2:
    """Conflict analysis + fast-forward extraction + fork/join schedule.
    Mirrors the control-flow split of plan.compile_plan; the emitted schedule
    is the listmerge2 action algebra instead of a retreat/advance tape."""
    plan = MergePlan2()
    new_ops: List[Span] = []
    conflict_ops: List[Span] = []

    def visit(span: Span, flag: DiffFlag) -> None:
        target = new_ops if flag == DiffFlag.ONLY_B else conflict_ops
        push_reversed_rle(target, span)

    common = graph.find_conflicting(from_frontier, merge_frontier, visit)
    next_frontier = list(from_frontier)

    # Fast-forward prefix (linear history streams through untransformed).
    did_ff = False
    while new_ops:
        span = new_ops[-1]
        i = graph.find_idx(span[0])
        if list(graph.parents_at(span[0])) != next_frontier:
            break
        new_ops.pop()
        take_end = min(graph.ends[i], span[1])
        if take_end < span[1]:
            new_ops.append((take_end, span[1]))
        plan.ff_spans.append((span[0], take_end))
        next_frontier = [take_end - 1]
        did_ff = True

    if new_ops:
        if did_ff:
            conflict_ops = []

            def visit2(span: Span, flag: DiffFlag) -> None:
                if flag != DiffFlag.ONLY_B:
                    push_reversed_rle(conflict_ops, span)

            common = graph.find_conflicting(next_frontier, merge_frontier,
                                            visit2)

        plan.common = list(common)
        zone = sorted([(tuple(s), False) for s in conflict_ops] +
                      [(tuple(s), True) for s in new_ops])
        entries = _build_subgraph(graph, zone)
        # Apply the whole conflict set before the first emitted entry, the
        # way M1 builds the tracker "hot" first (merge.rs:869-887): emitted
        # upstream positions must see the full `from` document. This stays a
        # topological order because an only-B op is never an ancestor of an
        # only-A/shared op (ancestors of hist(from) lie in hist(from)).
        perm = [k for k, en in enumerate(entries) if not en.emit] + \
               [k for k, en in enumerate(entries) if en.emit]
        inv = [0] * len(perm)
        for new_k, old_k in enumerate(perm):
            inv[old_k] = new_k
        plan.entries = [entries[old_k] for old_k in perm]
        for en in plan.entries:
            en.parents = tuple(inv[p] for p in en.parents)
        # pin: entries whose LAST lv is a requested pin point keep their
        # state row alive for session resumption (zone_session.py)
        pins = []
        pin_entry = {}
        for lv in pin_lvs:
            for k, en in enumerate(plan.entries):
                if en.span[1] - 1 == lv:
                    pins.append(k)
                    pin_entry[k] = lv
                    break
        plan.actions, plan.indexes_used, rowmap = _alloc_actions(
            plan.entries, tuple(pins))
        plan.pinned_rows = {pin_entry[k]: r for k, r in rowmap.items()}
        for en in plan.entries:
            if en.emit:
                graph.advance_frontier(next_frontier, en.span)

    plan.final_frontier = next_frontier
    return plan


def validate_plan2(plan: MergePlan2) -> None:
    """Independent correctness check: simulate each index as the SET of
    entries whose effects its row contains; every Apply must see exactly its
    entry's in-zone ancestor set (the reference validates plans similarly by
    simulating index frontiers — action_plan.rs MergePlan::simulate_plan)."""
    anc: List[frozenset] = []
    for en in plan.entries:
        s = set()
        for p in en.parents:
            s |= anc[p] | {p}
        anc.append(frozenset(s))

    sim = {}
    applied = [False] * len(plan.entries)
    live_peak = 0
    for act in plan.actions:
        op = act[0]
        if op == BEGIN:
            assert act[1] not in sim, "BEGIN on live index"
            sim[act[1]] = frozenset()
        elif op == FORK:
            assert act[2] not in sim, "FORK onto live index"
            sim[act[2]] = sim[act[1]]
        elif op == MAX:
            sim[act[1]] = sim[act[1]] | sim[act[2]]
        elif op == DROP:
            del sim[act[1]]
        elif op == APPLY:
            k, idx = act[1], act[2]
            assert not applied[k], "entry applied twice"
            assert sim[idx] == anc[k], \
                f"apply {k}: row holds {sorted(sim[idx])}, " \
                f"needs {sorted(anc[k])}"
            applied[k] = True
            sim[idx] = sim[idx] | {k}
        live_peak = max(live_peak, len(sim))
    assert all(applied), "some entries never applied"
    assert set(sim.keys()) <= set(plan.pinned_rows.values()), \
        "indexes leaked at end of plan (beyond the pinned rows)"
    assert live_peak <= plan.indexes_used
