"""The merge tracker: a CRDT-order span store used to transform concurrent
positional edits onto a common document frame.

Capability mirror of the reference M2Tracker (reference: src/listmerge/mod.rs:40-55,
merge.rs:89-558, advance_retreat.rs) with a different data-structure design:
instead of an unsafe B-tree with leaf back-pointers (content-tree) plus a
second range tree for the LV index, this uses

  * an order-statistic **treap** over RLE item spans, each node carrying three
    subtree aggregates: raw length, current length (items in INSERTED state)
    and upstream length (items never deleted) — the dual metric of the
    reference's MarkerMetrics (reference: src/listmerge/metrics.rs:18-66);
  * bisect-indexed maps from LV -> tree node (inserts) and LV -> delete target
    (deletes), replacing the SpaceIndex (reference: src/listmerge/markers.rs).

Item states follow the reference YjsSpan state machine (yjsspan.rs:47-91):
0 = not-inserted-yet, 1 = inserted, n>=2 = deleted (n-1) times.
"""

from __future__ import annotations

import random
from bisect import bisect_right, insort
from typing import List, Optional, Tuple

from ..core.span import UNDERWATER_START
from ..text.op import DEL, INS, OpRun
from ..utils.stats import GLOBAL_COUNTERS as COUNTERS

ROOT = -1

NOT_INSERTED_YET = 0
INSERTED = 1

_rng = random.Random(0x5EED)


class _Node:
    __slots__ = ("ids", "ide", "ol", "orr", "state", "ever",
                 "prio", "l", "r", "p", "s_len", "s_cur", "s_up")

    def __init__(self, ids: int, ide: int, ol: int, orr: int,
                 state: int, ever: bool) -> None:
        self.ids = ids      # id span [ids, ide): LVs of the inserted items
        self.ide = ide
        self.ol = ol        # origin_left of the FIRST item (later items: id-1)
        self.orr = orr      # origin_right, shared by all items in the span
        self.state = state
        self.ever = ever    # ever deleted?
        self.prio = _rng.random()
        self.l: Optional[_Node] = None
        self.r: Optional[_Node] = None
        self.p: Optional[_Node] = None
        self.s_len = ide - ids
        self.s_cur = 0
        self.s_up = 0
        _update(self)

    # metric contributions of this node alone
    def n_len(self) -> int:
        return self.ide - self.ids

    def n_cur(self) -> int:
        return self.ide - self.ids if self.state == INSERTED else 0

    def n_up(self) -> int:
        return 0 if self.ever else self.ide - self.ids

    def origin_left_at(self, offset: int) -> int:
        return self.ol if offset == 0 else self.ids + offset - 1


def _update(n: _Node) -> None:
    ln, lc, lu = (n.l.s_len, n.l.s_cur, n.l.s_up) if n.l else (0, 0, 0)
    rn, rc, ru = (n.r.s_len, n.r.s_cur, n.r.s_up) if n.r else (0, 0, 0)
    n.s_len = ln + rn + n.n_len()
    n.s_cur = lc + rc + n.n_cur()
    n.s_up = lu + ru + n.n_up()


def _fix_path(n: Optional[_Node]) -> None:
    while n is not None:
        _update(n)
        n = n.p


def _leftmost(n: _Node) -> _Node:
    while n.l is not None:
        n = n.l
    return n


def _succ(n: _Node) -> Optional[_Node]:
    if n.r is not None:
        return _leftmost(n.r)
    while n.p is not None and n is n.p.r:
        n = n.p
    return n.p


def _pred(n: _Node) -> Optional[_Node]:
    if n.l is not None:
        x = n.l
        while x.r is not None:
            x = x.r
        return x
    while n.p is not None and n is n.p.l:
        n = n.p
    return n.p


# A cursor is a (node, offset) pair with 0 <= offset <= node.n_len(), meaning
# "the gap just before item `offset` of `node`". (None, 0) = empty tree.
Cursor = Tuple[Optional[_Node], int]


class Tracker:
    def __init__(self) -> None:
        under = _Node(UNDERWATER_START, UNDERWATER_START * 2 - 1,
                      ROOT, ROOT, INSERTED, False)
        self.root: _Node = under
        # LV -> node index for inserted items (covers underwater ids too).
        self._ins_starts: List[int] = [under.ids]
        self._ins_nodes = {under.ids: under}
        # Delete-op LV -> target items: rows (lv0, lv1, t0, t1, fwd), disjoint.
        self._del_rows: List[Tuple[int, int, int, int, bool]] = []
        # Genuinely colliding concurrent inserts seen by integrate
        # (reference: merge_conflict_checks, listmerge/mod.rs:50-51 —
        # set whenever the scan meets another item that is not simply our
        # origin-right).
        self.collisions = 0

    # ---- treap plumbing --------------------------------------------------

    def _rot_up(self, x: _Node) -> None:
        p = x.p
        g = p.p
        if x is p.l:
            p.l = x.r
            if x.r is not None:
                x.r.p = p
            x.r = p
        else:
            p.r = x.l
            if x.l is not None:
                x.l.p = p
            x.l = p
        p.p = x
        x.p = g
        if g is not None:
            if g.l is p:
                g.l = x
            else:
                g.r = x
        else:
            self.root = x
        _update(p)
        _update(x)

    def _insert_leaf(self, x: _Node) -> None:
        _fix_path(x.p)
        while x.p is not None and x.prio < x.p.prio:
            self._rot_up(x)

    def _insert_after(self, a: _Node, x: _Node) -> None:
        if a.r is None:
            a.r = x
            x.p = a
        else:
            b = _leftmost(a.r)
            b.l = x
            x.p = b
        self._insert_leaf(x)

    def _insert_first(self, x: _Node) -> None:
        b = _leftmost(self.root)
        b.l = x
        x.p = b
        self._insert_leaf(x)

    def _register(self, n: _Node) -> None:
        insort(self._ins_starts, n.ids)
        self._ins_nodes[n.ids] = n

    def _split(self, n: _Node, offset: int) -> _Node:
        """Split node after `offset` items; returns the new right node."""
        assert 0 < offset < n.n_len()
        rn = _Node(n.ids + offset, n.ide, n.ids + offset - 1, n.orr,
                   n.state, n.ever)
        n.ide = n.ids + offset
        _fix_path(n)
        self._insert_after(n, rn)
        self._register(rn)
        return rn

    def _ins_lookup(self, lv: int) -> _Node:
        i = bisect_right(self._ins_starts, lv) - 1
        n = self._ins_nodes[self._ins_starts[i]]
        assert n.ids <= lv < n.ide, f"item LV {lv} not tracked"
        return n

    # ---- cursors ---------------------------------------------------------

    def _prefix(self, n: _Node, which: int) -> int:
        """Sum of metric `which` (0=len,1=cur,2=up) strictly before node n."""
        def sub(x: Optional[_Node]) -> int:
            if x is None:
                return 0
            return (x.s_len, x.s_cur, x.s_up)[which]

        def own(x: _Node) -> int:
            return (x.n_len(), x.n_cur(), x.n_up())[which]

        acc = sub(n.l)
        x = n
        while x.p is not None:
            if x is x.p.r:
                acc += sub(x.p.l) + own(x.p)
            x = x.p
        return acc

    def _raw_pos(self, c: Cursor) -> int:
        n, off = c
        if n is None:
            return self.root.s_len
        return self._prefix(n, 0) + off

    def _upstream_pos(self, c: Cursor) -> int:
        n, off = c
        if n is None:
            return self.root.s_up
        return self._prefix(n, 2) + (0 if n.ever else off)

    def _find_by_cur(self, pos: int) -> Cursor:
        """Cursor at the `pos`-th currently-INSERTED item."""
        n = self.root
        assert pos < n.s_cur, f"content pos {pos} out of range"
        while True:
            lc = n.l.s_cur if n.l else 0
            if pos < lc:
                n = n.l
                continue
            pos -= lc
            here = n.n_cur()
            if pos < here:
                return (n, pos)
            pos -= here
            n = n.r

    def _roll(self, c: Cursor) -> Cursor | None:
        """Normalize cursor so offset < node len; None at end of document."""
        n, off = c
        if n is None:
            return None
        while off >= n.n_len():
            nxt = _succ(n)
            if nxt is None:
                return None
            n, off = nxt, 0
        return (n, off)

    def _cursor_before_item(self, lv: int) -> Cursor:
        if lv == ROOT:
            return (None, 0)  # end-of-document sentinel
        n = self._ins_lookup(lv)
        return (n, lv - n.ids)

    def _cursor_after_item(self, lv: int, stick_end: bool) -> Cursor:
        if lv == ROOT:
            n = _leftmost(self.root)
            return (n, 0)  # start of document
        n = self._ins_lookup(lv)
        c = (n, lv - n.ids + 1)
        if not stick_end:
            rolled = self._roll(c)
            if rolled is not None:
                return rolled
        return c

    def _cmp_cursors(self, a: Cursor, b: Cursor) -> int:
        pa, pb = self._raw_pos(a), self._raw_pos(b)
        return (pa > pb) - (pa < pb)

    # ---- insertion (integrate) ------------------------------------------

    def _insert_at(self, c: Cursor, node: _Node) -> None:
        n, off = c
        if n is None:
            # end of document
            x = self.root
            while x.r is not None:
                x = x.r
            self._insert_after(x, node)
        elif off == 0:
            prev = _pred(n)
            if prev is None:
                self._insert_first(node)
            else:
                self._insert_after(prev, node)
        elif off == n.n_len():
            self._insert_after(n, node)
        else:
            self._split(n, off)
            self._insert_after(n, node)
        self._register(node)

    def integrate(self, aa, agent: int, item: _Node, cursor: Cursor | None) -> int:
        """YjsMod / FugueMax concurrent-insert resolution (reference:
        merge.rs:154-278). Returns the item's transformed (upstream) insert
        position. `cursor` sits immediately after the item's origin_left.
        """
        COUNTERS.bump("integrate_calls")
        cursor = self._roll(cursor) if cursor is not None else None
        left_cursor = cursor
        scan_start = cursor
        scanning = False

        while True:
            if cursor is None:
                break  # end of document
            rolled = self._roll(cursor)
            if rolled is None:
                cursor = None
                break
            cursor = rolled
            other, off = cursor
            other_lv = other.ids + off
            if other_lv == item.orr:
                break
            self.collisions += 1   # a genuinely concurrent insert here

            # Only not-yet-inserted items can be concurrent with us here.
            assert other.state == NOT_INSERTED_YET

            other_left_lv = other.origin_left_at(off)
            other_left_cursor = self._cursor_after_item(other_left_lv, False)

            c = self._cmp_cursors(other_left_cursor,
                                  left_cursor if left_cursor is not None else (None, 0))
            if left_cursor is None:
                # our origin-left is end-of-doc sentinel: nothing sorts after it
                c = -1
            if c < 0:
                break
            elif c == 0:
                if item.orr == other.orr:
                    # Fully concurrent siblings: order by agent name, then seq
                    # (reference: merge.rs:193-241).
                    my_name = aa.get_agent_name(agent)
                    other_agent, other_seq = aa.local_to_agent_version(other_lv)
                    other_name = aa.get_agent_name(other_agent)
                    if my_name < other_name:
                        ins_here = True
                    elif my_name == other_name:
                        my_seq = aa.local_to_agent_version(item.ids)[1]
                        ins_here = my_seq < other_seq
                    else:
                        ins_here = False
                    if ins_here:
                        break
                    scanning = False
                else:
                    my_right = self._cursor_before_item(item.orr)
                    other_right = self._cursor_before_item(other.orr)
                    if self._cmp_cursors(other_right, my_right) < 0:
                        if not scanning:
                            scanning = True
                            scan_start = cursor
                    else:
                        scanning = False

            # Advance to the next entry wholesale.
            nxt = _succ(other)
            if nxt is None:
                cursor = (other, other.n_len())
                break
            cursor = (nxt, 0)

        if scanning:
            cursor = scan_start

        at = cursor if cursor is not None else (None, 0)
        pos = self._upstream_pos(at)
        self._insert_at(at, item)
        return pos

    # ---- op application --------------------------------------------------

    def apply(self, aa, agent: int, op: OpRun, max_len: int):
        """Advance the tracker by (a prefix of) one op run; returns
        (len_consumed, xf) where xf is the transformed position (int) or None
        when the delete already happened (reference: merge.rs:375-558).
        """
        length = min(max_len, len(op))
        COUNTERS.bump("apply_ins_runs" if op.kind == INS else "apply_del_runs")
        if op.kind == INS:
            if not op.fwd:
                raise NotImplementedError("reverse insert runs")
            if op.start == 0:
                origin_left = ROOT
                cursor: Cursor | None = (_leftmost(self.root), 0)
            else:
                n, off = self._find_by_cur(op.start - 1)
                origin_left = n.ids + off
                cursor = (n, off + 1)

            # origin_right: next item that is not in the NIY state.
            rolled = self._roll(cursor)
            if rolled is None:
                origin_right = ROOT
            else:
                c2 = rolled
                while True:
                    n2, off2 = c2
                    if n2.state == NOT_INSERTED_YET:
                        nxt = _succ(n2)
                        if nxt is None:
                            origin_right = ROOT
                            break
                        c2 = (nxt, 0)
                    else:
                        origin_right = n2.ids + off2
                        break

            item = _Node(op.lv, op.lv + length, origin_left, origin_right,
                         INSERTED, False)
            ins_pos = self.integrate(aa, agent, item, cursor)
            return length, ins_pos

        else:  # DEL
            fwd = op.fwd
            if fwd:
                cursor = self._find_by_cur(op.start)
                take_req = length
            else:
                last_pos = op.end - 1
                n, off = self._find_by_cur(last_pos)
                entry_start_pos = last_pos - off
                edit_start = max(entry_start_pos, op.end - length)
                take_req = op.end - edit_start
                cursor = (n, off - (take_req - 1))

            n, off = cursor
            assert n.state == INSERTED
            ever_deleted = n.ever
            del_start_xf = self._upstream_pos(cursor)

            # Delete as much as fits within this node.
            take = min(take_req, n.n_len() - off)
            if off > 0:
                n = self._split(n, off)
            if take < n.n_len():
                self._split(n, take)
            target = (n.ids, n.ide)
            n.state += 1
            n.ever = True
            _fix_path(n)
            if not fwd:
                assert take == take_req

            insort(self._del_rows, (op.lv, op.lv + take, target[0], target[1], fwd))

            if not ever_deleted:
                return take, del_start_xf
            else:
                return take, None

    # ---- time travel (advance / retreat) ---------------------------------

    def _index_query(self, lv: int):
        """(kind, target_rangerev, offset, total_len) for op LV `lv`
        (reference: advance_retreat.rs:28-56)."""
        i = bisect_right(self._del_rows, (lv, (1 << 63),)) - 1
        if i >= 0:
            lv0, lv1, t0, t1, fwd = self._del_rows[i]
            if lv0 <= lv < lv1:
                return DEL, (t0, t1, fwd), lv - lv0, lv1 - lv0
        n = self._ins_lookup(lv)
        return INS, (n.ids, n.ide, True), lv - n.ids, n.n_len()

    def _toggle_items(self, s: int, e: int, mode: str) -> None:
        """Apply a state transition to items with ids in [s, e)."""
        lv = s
        while lv < e:
            n = self._ins_lookup(lv)
            if lv > n.ids:
                n = self._split(n, lv - n.ids)
            if e < n.ide:
                self._split(n, e - n.ids)
            if mode == "ins":
                assert n.state == NOT_INSERTED_YET
                n.state = INSERTED
            elif mode == "unins":
                assert n.state == INSERTED
                n.state = NOT_INSERTED_YET
            elif mode == "del":
                assert n.state >= INSERTED
                n.state += 1
                n.ever = True
            elif mode == "undel":
                assert n.state >= 2
                n.state -= 1
            _fix_path(n)
            lv = n.ide

    def advance_by_range(self, rng: Tuple[int, int]) -> None:
        """Re-apply op effects for LVs in `rng` (reference: advance_retreat.rs:58-97)."""
        COUNTERS.bump("advance_calls")
        start, end = rng
        while start < end:
            kind, target, offset, total = self._index_query(start)
            take = min(total - offset, end - start)
            lo, hi = _rr_sub(target, offset, offset + take)
            self._toggle_items(lo, hi, "ins" if kind == INS else "del")
            start += take

    def retreat_by_range(self, rng: Tuple[int, int]) -> None:
        """Un-apply op effects for LVs in `rng`, back to front so un-deletes
        precede un-inserts of the same item (reference: advance_retreat.rs:100-153)."""
        COUNTERS.bump("retreat_calls")
        start, end = rng
        while start < end:
            req = end - 1
            kind, target, offset, total = self._index_query(req)
            chunk_start = req - offset
            s = max(start, chunk_start)
            e = min(end, chunk_start + total)
            o0 = s - chunk_start
            lo, hi = _rr_sub(target, o0, o0 + (e - s))
            self._toggle_items(lo, hi, "unins" if kind == INS else "undel")
            end -= e - s

    # ---- debug -----------------------------------------------------------

    def dbg_iter(self):
        out = []
        n = _leftmost(self.root)
        while n is not None:
            out.append((n.ids, n.ide, n.ol, n.orr, n.state, n.ever))
            n = _succ(n)
        return out

    def check_invariants(self) -> None:
        n = _leftmost(self.root)
        while n is not None:
            assert n.ide > n.ids
            if n.p is None:
                assert n is self.root
            n = _succ(n)

        def rec(x: Optional[_Node]):
            if x is None:
                return 0, 0, 0
            ll = rec(x.l)
            rr = rec(x.r)
            if x.l:
                assert x.l.p is x and x.l.prio >= x.prio
            if x.r:
                assert x.r.p is x and x.r.prio >= x.prio
            tot = (ll[0] + rr[0] + x.n_len(), ll[1] + rr[1] + x.n_cur(),
                   ll[2] + rr[2] + x.n_up())
            assert tot == (x.s_len, x.s_cur, x.s_up)
            return tot

        rec(self.root)


def _rr_sub(target: Tuple[int, int, bool], o0: int, o1: int) -> Tuple[int, int]:
    """Sub-range [o0, o1) of a reversible target range, in item-id space
    (reference: src/rev_range.rs range())."""
    t0, t1, fwd = target
    if fwd:
        return (t0 + o0, t0 + o1)
    return (t1 - o1, t1 - o0)
