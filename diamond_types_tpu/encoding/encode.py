"""v1 oplog file format writer ("DMNDTYPS").

Capability mirror of the reference encoder (reference:
src/list/encoding/encode_oplog.rs: `encode`, `encode_from`, EncodeOptions /
ENCODE_FULL / ENCODE_PATCH). Ops are walked in optimized spanning-tree order
between `from_version` and the oplog tip, renumbered densely into file order,
and written as per-column RLE chunks. Content fields are LZ4-compressed into
the shared compressed-fields chunk by default (compress_content=False writes
plain content chunks; decoders accept both).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ..core.span import Span
from ..listmerge.walker import SpanningTreeWalker
from ..text.op import DEL, INS, can_append_ops, OpRun
from ..text.oplog import OpLog
from .crc32c import crc32c
from .decode import (CHUNK_AGENTNAMES, CHUNK_COMPRESSED, CHUNK_CONTENT,
                     CHUNK_CONTENT_COMPRESSED, CHUNK_CONTENT_IS_KNOWN,
                     CHUNK_CRC, CHUNK_DOCID, CHUNK_FILEINFO,
                     CHUNK_OP_PARENTS, CHUNK_OP_TYPE_AND_POSITION,
                     CHUNK_OP_VERSIONS, CHUNK_PATCH_CONTENT, CHUNK_PATCHES,
                     CHUNK_STARTBRANCH, CHUNK_USERDATA, CHUNK_VERSION,
                     DATA_PLAIN_TEXT, MAGIC, PROTOCOL_VERSION)
from .lz4 import lz4_compress_block
from .varint import encode_leb, encode_zigzag_old, mix_bit


@dataclass
class EncodeOptions:
    user_data: Optional[bytes] = None
    store_start_branch_content: bool = True
    store_inserted_content: bool = True
    store_deleted_content: bool = False
    compress_content: bool = True


ENCODE_FULL = EncodeOptions()
ENCODE_PATCH = EncodeOptions(store_start_branch_content=False)


def _chunk(ctype: int, data: bytes) -> bytes:
    return encode_leb(ctype) + encode_leb(len(data)) + data


class _AgentMapping:
    """File-local agent numbering, 1-based (0 = ROOT), in order of first use
    (reference: encode_oplog.rs:193-239)."""

    def __init__(self, aa) -> None:
        self.aa = aa
        self.map = {}
        self.names_buf = bytearray()
        self.seq_cursor = {}

    def map_agent(self, agent: int) -> int:
        m = self.map.get(agent)
        if m is None:
            m = len(self.map) + 1
            self.map[agent] = m
            name = self.aa.get_agent_name(agent).encode("utf8")
            self.names_buf += encode_leb(len(name)) + name
            self.seq_cursor[agent] = 0
        return m

    def seq_delta(self, agent: int, seq_start: int, seq_end: int) -> int:
        old = self.seq_cursor[agent]
        self.seq_cursor[agent] = seq_end
        return seq_start - old


def _write_op(out: bytearray, kind: int, start: int, end: int, fwd: bool,
              cursor: List[int]) -> None:
    """One op run in the type/position column (reference: encode_oplog.rs:20-90)."""
    length = end - start
    fwd = fwd or length == 1
    op_start = end if (kind == DEL and not fwd) else start
    op_end = end if (kind == INS and fwd) else start
    diff = op_start - cursor[0]
    cursor[0] = op_end

    if length != 1:
        n = length
        if kind == DEL:
            n = mix_bit(n, fwd)
    elif diff != 0:
        n = encode_zigzag_old(diff)
    else:
        n = 0
    n = mix_bit(n, kind == DEL)
    n = mix_bit(n, diff != 0)
    n = mix_bit(n, length != 1)
    out += encode_leb(n)
    if length != 1 and diff != 0:
        out += encode_leb(encode_zigzag_old(diff))


class _ContentChunk:
    """Per-kind content column: chars + (len, known) runs
    (reference: encode_oplog.rs ContentChunk)."""

    def __init__(self, kind: int) -> None:
        self.kind = kind
        self.content: List[str] = []
        self.runs: List[List] = []  # [len, known]
        self.any = False

    def push(self, content: Optional[str], n: int) -> None:
        self.any = True
        known = content is not None
        if known:
            self.content.append(content)
        if self.runs and self.runs[-1][1] == known:
            self.runs[-1][0] += n
        else:
            self.runs.append([n, known])

    def bake(self, compress_parts: Optional[List[bytes]] = None) -> Optional[bytes]:
        if not self.any:
            return None
        body = bytearray()
        body += encode_leb(0 if self.kind == INS else 1)
        text = "".join(self.content).encode("utf8")
        if compress_parts is not None:
            compress_parts.append(text)
            body += _chunk(CHUNK_CONTENT_COMPRESSED,
                           encode_leb(DATA_PLAIN_TEXT) + encode_leb(len(text)))
        else:
            body += _chunk(CHUNK_CONTENT, encode_leb(DATA_PLAIN_TEXT) + text)
        runs = bytearray()
        for n, known in self.runs:
            runs += encode_leb(mix_bit(n, known))
        body += _chunk(CHUNK_CONTENT_IS_KNOWN, bytes(runs))
        return bytes(body)


def encode_oplog(oplog: OpLog, opts: EncodeOptions = ENCODE_FULL,
                 from_version: Optional[Sequence[int]] = None) -> bytes:
    from_version = sorted(from_version) if from_version else []
    if not opts.store_deleted_content and \
            (not from_version or not opts.store_start_branch_content):
        # Native fast paths (native/dt_core.cpp encode_impl): full
        # snapshots AND patch encodes (the sync-protocol hot path —
        # every /changes push pays this; VERDICT r4 #4). The native
        # walk mirrors SpanningTreeWalker's order, so output is
        # byte-identical to this writer — pinned by tests/test_encode.py.
        # Deleted-content storage and from_version-with-start-content
        # snapshots stay here.
        from ..native import native_ctx_or_none
        ctx = native_ctx_or_none(oplog)
        if ctx is not None:
            if from_version:
                blob = ctx.encode_patch(
                    oplog.doc_id, opts.user_data,
                    opts.store_inserted_content, opts.compress_content,
                    from_version)
            else:
                blob = ctx.encode_full(
                    oplog.doc_id, opts.user_data,
                    opts.store_inserted_content, opts.compress_content)
            if blob is not None:
                return blob
    graph = oplog.cg.graph
    aa = oplog.cg.agent_assignment

    mapping = _AgentMapping(aa)

    agent_chunk = bytearray()
    pending_aa: Optional[List] = None  # [mapped_agent, delta, len, agent, seq_end]

    def flush_aa() -> None:
        nonlocal pending_aa
        if pending_aa is None:
            return
        m, delta, n, _agent, _se = pending_aa
        has_jump = delta != 0
        agent_chunk.extend(encode_leb(mix_bit(m, has_jump)))
        agent_chunk.extend(encode_leb(n))
        if has_jump:
            agent_chunk.extend(encode_leb(encode_zigzag_old(delta)))
        pending_aa = None

    ops_chunk = bytearray()
    ops_cursor = [0]
    pending_op: Optional[OpRun] = None

    def flush_op() -> None:
        nonlocal pending_op
        if pending_op is None:
            return
        _write_op(ops_chunk, pending_op.kind, pending_op.start, pending_op.end,
                  pending_op.fwd, ops_cursor)
        pending_op = None

    ins_content = _ContentChunk(INS) if opts.store_inserted_content else None
    del_content = _ContentChunk(DEL) if opts.store_deleted_content else None

    txns_chunk = bytearray()
    # txn_map: local span start -> output start, ascending in output order.
    txn_map: List[Tuple[int, int, int]] = []  # (local_start, out_start, len)
    next_output_time = 0

    def map_local_to_output(p: int) -> Optional[int]:
        from bisect import bisect_right
        i = bisect_right(txn_map, p, key=lambda r: r[0]) - 1
        if i < 0:
            return None
        ls, os_, n = txn_map[i]
        if p >= ls + n:
            return None
        return os_ + (p - ls)

    def write_txn(span: Span, parents: Sequence[int]) -> None:
        nonlocal next_output_time
        from bisect import insort
        n = span[1] - span[0]
        out_start = next_output_time
        insort(txn_map, (span[0], out_start, n))
        next_output_time += n

        txns_chunk.extend(encode_leb(n))
        if not parents:
            txns_chunk.extend(encode_leb(1))  # foreign-ROOT marker
            return
        for i, p in enumerate(parents):
            has_more = i + 1 < len(parents)
            mapped = map_local_to_output(p)
            if mapped is not None:
                v = mix_bit(mix_bit(out_start - mapped, has_more), False)
                txns_chunk.extend(encode_leb(v))
            else:
                agent, seq = aa.local_to_agent_version(p)
                m = mapping.map_agent(agent)
                v = mix_bit(mix_bit(m, has_more), True)
                txns_chunk.extend(encode_leb(v))
                txns_chunk.extend(encode_leb(seq))

    # --- main walk (reference: encode_oplog.rs:545-600) ---------------------
    _only_a, only_b = graph.diff_rev(from_version, oplog.cg.version)
    assert not _only_a, "from_version must be an ancestor of the oplog version"
    walker = SpanningTreeWalker(graph, only_b, list(from_version),
                                track_frontier=False)
    for walk in walker:
        span = walk.consume
        # 1. agent assignment runs
        pos = span[0]
        while pos < span[1]:
            agent, seq, n = aa.local_span_to_agent_span(pos, span[1] - pos)
            m = mapping.map_agent(agent)
            if pending_aa is not None and pending_aa[0] == m \
                    and pending_aa[4] == seq:
                pending_aa[2] += n
                pending_aa[4] = seq + n
                mapping.seq_cursor[agent] = seq + n
            else:
                flush_aa()
                delta = mapping.seq_delta(agent, seq, seq + n)
                pending_aa = [m, delta, n, agent, seq + n]
            pos += n

        # 2. ops + content
        for piece in oplog.ops.iter_range(span):
            content = oplog.ops.get_run_content(piece)
            if piece.kind == INS and ins_content is not None:
                # content may be unknown (oplog decoded from a blob
                # written without inserted content): a known=false run,
                # same as the native writer and the reference format
                ins_content.push(content, len(piece))
            elif piece.kind == DEL and del_content is not None:
                del_content.push(content, len(piece))
            if pending_op is not None and pending_op.kind == piece.kind \
                    and can_append_ops(piece.kind, pending_op, piece):
                from ..text.op import append_ops
                clone = OpRun(piece.lv, piece.kind, piece.start, piece.end,
                              piece.fwd, None)
                append_ops(piece.kind, pending_op, clone)
            else:
                flush_op()
                pending_op = OpRun(piece.lv, piece.kind, piece.start,
                                   piece.end, piece.fwd, None)

        # 3. parents
        write_txn(span, walk.parents)

    flush_aa()
    flush_op()

    # --- start branch --------------------------------------------------------
    compress_parts: Optional[List[bytes]] = [] if opts.compress_content else None
    start_branch = bytearray()
    if from_version:
        vbuf = bytearray()
        for i, lv in enumerate(from_version):
            has_more = i + 1 < len(from_version)
            agent, seq = aa.local_to_agent_version(lv)
            m = mapping.map_agent(agent)
            vbuf += encode_leb(mix_bit(m, has_more))
            vbuf += encode_leb(seq)
        start_branch += _chunk(CHUNK_VERSION, bytes(vbuf))
        if opts.store_start_branch_content:
            content = oplog.checkout(from_version).snapshot().encode("utf8")
            if compress_parts is not None:
                compress_parts.append(content)
                start_branch += _chunk(
                    CHUNK_CONTENT_COMPRESSED,
                    encode_leb(DATA_PLAIN_TEXT) + encode_leb(len(content)))
            else:
                start_branch += _chunk(
                    CHUNK_CONTENT, encode_leb(DATA_PLAIN_TEXT) + content)

    # --- file info -----------------------------------------------------------
    fileinfo = bytearray()
    if oplog.doc_id is not None:
        fileinfo += _chunk(CHUNK_DOCID, encode_leb(DATA_PLAIN_TEXT)
                           + oplog.doc_id.encode("utf8"))
    fileinfo += _chunk(CHUNK_AGENTNAMES, bytes(mapping.names_buf))
    if opts.user_data is not None:
        fileinfo += _chunk(CHUNK_USERDATA, opts.user_data)

    # --- assemble ------------------------------------------------------------
    patches = bytearray()
    if ins_content is not None:
        baked = ins_content.bake(compress_parts)
        if baked is not None:
            patches += _chunk(CHUNK_PATCH_CONTENT, baked)
    if del_content is not None:
        baked = del_content.bake(compress_parts)
        if baked is not None:
            patches += _chunk(CHUNK_PATCH_CONTENT, baked)

    result = bytearray()
    result += MAGIC
    result += encode_leb(PROTOCOL_VERSION)
    if compress_parts:
        blob = b"".join(compress_parts)
        result += _chunk(CHUNK_COMPRESSED,
                         encode_leb(len(blob)) + lz4_compress_block(blob))
    result += _chunk(CHUNK_FILEINFO, bytes(fileinfo))
    result += _chunk(CHUNK_STARTBRANCH, bytes(start_branch))
    patches += _chunk(CHUNK_OP_VERSIONS, bytes(agent_chunk))
    patches += _chunk(CHUNK_OP_TYPE_AND_POSITION, bytes(ops_chunk))
    patches += _chunk(CHUNK_OP_PARENTS, bytes(txns_chunk))
    result += _chunk(CHUNK_PATCHES, bytes(patches))

    checksum = crc32c(bytes(result))
    result += _chunk(CHUNK_CRC, checksum.to_bytes(4, "little"))
    return bytes(result)
