"""LEB128 varints + bit mixing + zigzag, as used by the v1 wire format
(reference: src/list/encoding/leb.rs, src/encoding/varint.rs:416-530).
"""

from __future__ import annotations

from typing import Tuple


def decode_leb(buf: bytes, pos: int) -> Tuple[int, int]:
    """Returns (value, new_pos)."""
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if b < 0x80:
            return result, pos
        shift += 7


def encode_leb(value: int) -> bytes:
    assert value >= 0
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def strip_bit(value: int) -> Tuple[int, bool]:
    return value >> 1, (value & 1) != 0


def mix_bit(value: int, bit: bool) -> int:
    return (value << 1) | (1 if bit else 0)


def decode_zigzag_old(value: int) -> int:
    """The 'old' zigzag used by the v1 list format (reference:
    src/list/encoding/leb.rs:305-323): magnitude * sign; note -0 == 0."""
    return (value >> 1) * (-1 if value & 1 else 1)


def encode_zigzag_old(value: int) -> int:
    return mix_bit(abs(value), value < 0)
