"""Raw LZ4 block decompression (no frame header).

The reference compresses content/patch fields with lz4_flex's block format
(reference: src/list/encoding/decode_oplog.rs:621-633). This is a standard
LZ4 block stream: token byte (hi nibble = literal length, lo nibble = match
length - 4), optional 255-extension bytes, literals, little-endian 16-bit
match offset, overlapping match copy.
"""

from __future__ import annotations


def lz4_decompress_block(src: bytes, uncompressed_len: int) -> bytes:
    out = bytearray()
    i = 0
    n = len(src)
    while i < n:
        token = src[i]
        i += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                b = src[i]
                i += 1
                lit_len += b
                if b != 255:
                    break
        if lit_len:
            out += src[i:i + lit_len]
            i += lit_len
        if i >= n:
            break  # last sequence has literals only
        offset = src[i] | (src[i + 1] << 8)
        i += 2
        if offset == 0:
            raise ValueError("invalid LZ4 offset 0")
        match_len = (token & 0xF) + 4
        if (token & 0xF) == 15:
            while True:
                b = src[i]
                i += 1
                match_len += b
                if b != 255:
                    break
        start = len(out) - offset
        if start < 0:
            raise ValueError("LZ4 offset out of range")
        for k in range(match_len):  # overlapping copies must go byte-by-byte
            out.append(out[start + k])
    if len(out) != uncompressed_len:
        raise ValueError(f"LZ4 length mismatch: {len(out)} != {uncompressed_len}")
    return bytes(out)
