"""Raw LZ4 block decompression (no frame header).

The reference compresses content/patch fields with lz4_flex's block format
(reference: src/list/encoding/decode_oplog.rs:621-633). This is a standard
LZ4 block stream: token byte (hi nibble = literal length, lo nibble = match
length - 4), optional 255-extension bytes, literals, little-endian 16-bit
match offset, overlapping match copy.
"""

from __future__ import annotations


def lz4_compress_block(src: bytes) -> bytes:
    """Greedy LZ4 block compression (hash-table match finder).

    Produces standard LZ4 block streams decodable by lz4_decompress_block and
    by the reference's lz4_flex reader. Spec constraints honored: matches are
    >= 4 bytes, offsets <= 0xFFFF, and the final 5 bytes (plus the 12-byte
    end-of-block window) are emitted as literals.

    Delegates to the byte-identical native mirror when available (the two
    are differential-tested; output must not depend on which one ran).
    """
    try:
        from ..native.core import lz4_compress_native
        out = lz4_compress_native(src)
        if out is not None:
            return out
    except Exception:  # noqa: BLE001 - degrade to pure python on any failure
        pass
    n = len(src)
    out = bytearray()
    table: dict = {}
    anchor = 0
    i = 0
    limit = n - 12  # don't start matches in the end window

    def emit(lit_start: int, lit_end: int, match_off: int, match_len: int) -> None:
        lit_len = lit_end - lit_start
        token_lit = 15 if lit_len >= 15 else lit_len
        if match_len >= 0:
            ml = match_len - 4
            token_match = 15 if ml >= 15 else ml
        else:
            token_match = 0
        out.append((token_lit << 4) | token_match)
        if lit_len >= 15:
            rem = lit_len - 15
            while rem >= 255:
                out.append(255)
                rem -= 255
            out.append(rem)
        out.extend(src[lit_start:lit_end])
        if match_len >= 0:
            out.append(match_off & 0xFF)
            out.append(match_off >> 8)
            if match_len - 4 >= 15:
                rem = match_len - 4 - 15
                while rem >= 255:
                    out.append(255)
                    rem -= 255
                out.append(rem)

    while i < limit:
        key = src[i:i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand <= 0xFFFF and src[cand:cand + 4] == key:
            # extend the match
            m = 4
            max_m = n - 5 - i  # keep last 5 bytes literal
            while m < max_m and src[cand + m] == src[i + m]:
                m += 1
            if m >= 4:
                emit(anchor, i, i - cand, m)
                i += m
                anchor = i
                continue
        i += 1
    emit(anchor, n, 0, -1)  # trailing literals, no match
    return bytes(out)


def lz4_decompress_block(src: bytes, uncompressed_len: int) -> bytes:
    out = bytearray()
    i = 0
    n = len(src)
    while i < n:
        token = src[i]
        i += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                b = src[i]
                i += 1
                lit_len += b
                if b != 255:
                    break
        if lit_len:
            out += src[i:i + lit_len]
            i += lit_len
        if i >= n:
            break  # last sequence has literals only
        offset = src[i] | (src[i + 1] << 8)
        i += 2
        if offset == 0:
            raise ValueError("invalid LZ4 offset 0")
        match_len = (token & 0xF) + 4
        if (token & 0xF) == 15:
            while True:
                b = src[i]
                i += 1
                match_len += b
                if b != 255:
                    break
        start = len(out) - offset
        if start < 0:
            raise ValueError("LZ4 offset out of range")
        for k in range(match_len):  # overlapping copies must go byte-by-byte
            out.append(out[start + k])
    if len(out) != uncompressed_len:
        raise ValueError(f"LZ4 length mismatch: {len(out)} != {uncompressed_len}")
    return bytes(out)
