"""CRC-32C (Castagnoli, reflected poly 0x82F63B78) — the file checksum used
by the wire format (reference: src/encoding/tools.rs:111-115, CRC_32_ISCSI).
"""

from __future__ import annotations

_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _TABLE.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    try:
        from ..native.core import crc32c_native
        out = crc32c_native(data, crc)
        if out is not None:
            return out
    except Exception:  # noqa: BLE001 - degrade to pure python on any failure
        pass
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF
