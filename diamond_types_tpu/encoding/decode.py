"""v1 oplog file format reader ("DMNDTYPS").

Capability mirror of the reference decoder (reference:
src/list/encoding/decode_oplog.rs, format spec BINARY.md:55-141): chunked
binary format, LEB128 varints, per-column RLE, optional LZ4-compressed field
data, CRC32. Supports both load-into-empty and decode_and_add (merging a
patch file into an existing oplog, deduping already-known ops).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..text.op import DEL, INS
from ..text.oplog import OpLog
from .crc32c import crc32c
from .lz4 import lz4_decompress_block
from .varint import decode_leb, decode_zigzag_old, strip_bit

# Chunk ids (reference: src/list/encoding/mod.rs:29-60)
CHUNK_COMPRESSED = 5
CHUNK_FILEINFO = 1
CHUNK_DOCID = 2
CHUNK_AGENTNAMES = 3
CHUNK_USERDATA = 4
CHUNK_STARTBRANCH = 10
CHUNK_END_BRANCH = 11
CHUNK_VERSION = 12
CHUNK_CONTENT = 13
CHUNK_CONTENT_COMPRESSED = 14
CHUNK_PATCHES = 20
CHUNK_OP_VERSIONS = 21
CHUNK_OP_TYPE_AND_POSITION = 22
CHUNK_OP_PARENTS = 23
CHUNK_PATCH_CONTENT = 24
CHUNK_CONTENT_IS_KNOWN = 25
CHUNK_TRANSFORMED_POSITIONS = 27
CHUNK_CRC = 100

DATA_PLAIN_TEXT = 4

MAGIC = b"DMNDTYPS"
PROTOCOL_VERSION = 0

UNDERWATER = 1 << 62


class ParseError(Exception):
    pass


class Buf:
    __slots__ = ("data", "pos", "end")

    def __init__(self, data: bytes, pos: int = 0, end: Optional[int] = None):
        self.data = data
        self.pos = pos
        self.end = len(data) if end is None else end

    def is_empty(self) -> bool:
        return self.pos >= self.end

    def next_usize(self) -> int:
        if self.pos >= self.end:
            raise ParseError("unexpected EOF")
        v, self.pos = decode_leb(self.data, self.pos)
        if self.pos > self.end:
            raise ParseError("varint overruns chunk")
        return v

    next_u32 = next_usize

    def next_zigzag(self) -> int:
        return decode_zigzag_old(self.next_usize())

    def next_n_bytes(self, n: int) -> bytes:
        if self.pos + n > self.end:
            raise ParseError("unexpected EOF")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def next_str(self) -> str:
        n = self.next_usize()
        return self.next_n_bytes(n).decode("utf8")

    def rest(self) -> bytes:
        return self.data[self.pos:self.end]

    def next_chunk(self) -> Tuple[int, "Buf"]:
        ctype = self.next_usize()
        clen = self.next_usize()
        if self.pos + clen > self.end:
            raise ParseError("chunk overruns buffer")
        c = Buf(self.data, self.pos, self.pos + clen)
        self.pos += clen
        return ctype, c

    def peek_chunk_type(self) -> Optional[int]:
        if self.is_empty():
            return None
        v, _ = decode_leb(self.data, self.pos)
        return v

    def read_chunk_if_eq(self, ctype: int) -> Optional["Buf"]:
        if self.peek_chunk_type() != ctype:
            return None
        return self.next_chunk()[1]

    def expect_chunk(self, ctype: int) -> "Buf":
        t, c = self.next_chunk()
        if t != ctype:
            raise ParseError(f"expected chunk {ctype}, got {t}")
        return c


def _content_str(parent: Buf, compressed: Optional[Buf]) -> str:
    t, r = parent.next_chunk()
    if t == CHUNK_CONTENT:
        if r.next_u32() != DATA_PLAIN_TEXT:
            raise ParseError("unknown content data type")
        return r.rest().decode("utf8")
    elif t == CHUNK_CONTENT_COMPRESSED:
        if r.next_u32() != DATA_PLAIN_TEXT:
            raise ParseError("unknown content data type")
        n = r.next_usize()
        if compressed is None:
            raise ParseError("compressed chunk missing")
        return compressed.next_n_bytes(n).decode("utf8")
    raise ParseError(f"expected content chunk, got {t}")


class _PatchesIter:
    """Op type/position column (reference: decode_oplog.rs:279-346).
    Yields [kind, start, end, fwd] rows; supports pushback."""

    def __init__(self, buf: Buf) -> None:
        self.buf = buf
        self.cursor = 0
        self.pushed: List[list] = []

    def next(self) -> Optional[list]:
        if self.pushed:
            return self.pushed.pop()
        if self.buf.is_empty():
            return None
        n = self.buf.next_usize()
        n, has_length = strip_bit(n)
        n, diff_not_zero = strip_bit(n)
        n, is_del = strip_bit(n)
        kind = DEL if is_del else INS
        if has_length:
            fwd = True
            if is_del:
                n, fwd = strip_bit(n)
            length = n
            diff = self.buf.next_zigzag() if diff_not_zero else 0
        else:
            length = 1
            fwd = True
            diff = decode_zigzag_old(n)

        raw_start = self.cursor + diff
        if kind == INS and fwd:
            start, raw_end = raw_start, raw_start + length
        elif kind == DEL and not fwd:
            start, raw_end = raw_start - length, raw_start - length
        else:  # (Ins, rev) | (Del, fwd)
            start, raw_end = raw_start, raw_start
        self.cursor = raw_end
        return [kind, start, start + length, fwd]

    def push_back(self, row: list) -> None:
        self.pushed.append(row)


class _ContentIter:
    """Per-kind content stream: runs of (len, known) + char data
    (reference: decode_oplog.rs:348-425). Yields [len, str|None]."""

    def __init__(self, chunk: Buf, compressed: Optional[Buf]) -> None:
        kind = chunk.next_u32()
        if kind not in (0, 1):
            raise ParseError("invalid content kind")
        self.kind = INS if kind == 0 else DEL
        self.content = _content_str(chunk, compressed)
        self.cpos = 0
        self.runs = chunk.expect_chunk(CHUNK_CONTENT_IS_KNOWN)
        self.pushed: List[list] = []

    def next(self) -> Optional[list]:
        if self.pushed:
            return self.pushed.pop()
        if self.runs.is_empty():
            if self.cpos < len(self.content):
                raise ParseError("trailing content")
            return None
        n = self.runs.next_usize()
        length, known = strip_bit(n)
        if known:
            s = self.content[self.cpos:self.cpos + length]
            if len(s) != length:
                raise ParseError("content underrun")
            self.cpos += length
            return [length, s]
        return [length, None]

    def push_back(self, row: list) -> None:
        self.pushed.append(row)


class _VersionMap:
    """RLE map file-time -> local LV (reference: decode_oplog.rs:728)."""

    def __init__(self) -> None:
        self.rows: List[list] = []  # [file_start, local_start, len]

    def push(self, file_start: int, local_start: int, n: int) -> None:
        if self.rows:
            r = self.rows[-1]
            if r[0] + r[2] == file_start and r[1] + r[2] == local_start:
                r[2] += n
                return
        self.rows.append([file_start, local_start, n])

    def map_with_len(self, file_t: int) -> Tuple[int, int]:
        """Returns (local_t, run_len_remaining)."""
        from bisect import bisect_right
        i = bisect_right(self.rows, file_t, key=lambda r: r[0]) - 1
        r = self.rows[i]
        off = file_t - r[0]
        assert 0 <= off < r[2], f"file time {file_t} unmapped"
        return r[1] + off, r[2] - off


def _rebuild_from_native(oplog: OpLog, cols: dict) -> List[int]:
    """Fill an empty OpLog from the C++ decoder's columns (native/core.py
    decode_file_native). The op rows arrive pre-merged with push_op's RLE
    rule, so the resulting tables are identical to the Python decoder's."""
    from ..text.op import OpRun

    if cols["doc_id"] is not None:
        oplog.doc_id = cols["doc_id"]
    local_agents = [oplog.get_or_create_agent_id(n)
                    for n in cols["agent_names"]]
    aa = oplog.cg.agent_assignment
    ar_agent, ar_seq0, ar_n = cols["agent_runs"]
    lv = 0
    for i in range(len(ar_agent)):
        n = int(ar_n[i])
        aa.assign_span(local_agents[int(ar_agent[i])], int(ar_seq0[i]),
                       lv, n)
        lv += n

    ins_base = oplog.ops._arenas[INS].push(cols["ins_blob"])[0]
    del_base = oplog.ops._arenas[DEL].push(cols["del_blob"])[0]
    assert ins_base == 0 and del_base == 0, "native decode needs fresh arenas"
    (olv, okind, ostart, oend, ofwd, oknown, oclen) = cols["ops"]
    runs = oplog.ops.runs
    # vectorized arena-cursor math + bulk row conversion: the per-row
    # Python loop was the decode hot spot on big corpora (~53k rows on
    # node_nodecc)
    import numpy as _np
    known = _np.asarray(oknown, dtype=bool)
    kind_arr = _np.asarray(okind, dtype=_np.int64)
    clen = _np.asarray(oclen, dtype=_np.int64)
    c0 = _np.zeros(len(olv), dtype=_np.int64)
    for k in (INS, DEL):
        sel = known & (kind_arr == k)
        take = _np.where(sel, clen, 0)
        c0 += _np.where(sel, _np.cumsum(take) - take, 0)
    rows = zip(_np.asarray(olv).tolist(), kind_arr.tolist(),
               _np.asarray(ostart).tolist(), _np.asarray(oend).tolist(),
               _np.asarray(ofwd, dtype=bool).tolist(), known.tolist(),
               c0.tolist(), clen.tolist())
    for (lv_i, kind, st, en, fwd, kn, cc, cl) in rows:
        runs.append(OpRun(lv_i, kind, st, en, fwd,
                          (cc, cc + cl) if kn else None))

    g_start, g_end, g_off, g_par = cols["graph"]
    graph = oplog.cg.graph
    from ..native.core import graph_rebuild_native
    built = graph_rebuild_native(g_start, g_end, g_off, g_par)
    if built is not None:
        # batch path (same push/advance semantics, computed in C++ —
        # pinned equal to the per-row path by tests/test_decode.py)
        (ms, me, msh, pind, pflat, cind, cflat, croot, ver) = built
        graph.starts = ms.tolist()
        graph.ends = me.tolist()
        graph.shadows = msh.tolist()
        pf = pflat.tolist()
        pi = pind.tolist()
        graph.parents = [tuple(pf[pi[i]:pi[i + 1]])
                         for i in range(len(ms))]
        cf = cflat.tolist()
        ci = cind.tolist()
        graph.child_idxs = [cf[ci[i]:ci[i + 1]] for i in range(len(ms))]
        graph.root_child_idxs = croot.tolist()
        oplog.cg.version[:] = ver.tolist()
        return list(oplog.cg.version)
    for i in range(len(g_start)):
        parents = [int(p) for p in g_par[g_off[i]:g_off[i + 1]]]
        span = (int(g_start[i]), int(g_end[i]))
        graph.push(parents, span[0], span[1])
        graph._advance_known_run(oplog.cg.version, parents, span)
    return list(oplog.cg.version)


_native_decode_ok = True  # negative cache: set False on any native failure


def _try_decode_native(data: bytes):
    """Native fresh-load probe with the same broad exception guard +
    negative caching the codec paths use (native/core.py::_codec_load):
    ANY native failure — missing .so, CDLL OSError, stale ABI missing
    dt_decode_new — degrades to the Python decoder instead of breaking
    load_oplog. Genuine corruption (NativeParseError) still raises: the
    Python decoder would reject the same bytes."""
    global _native_decode_ok
    if not _native_decode_ok:
        return None
    try:
        from ..native.core import NativeParseError, decode_file_native
    except ImportError:  # pragma: no cover - e.g. numpy-less install
        _native_decode_ok = False
        return None
    try:
        return decode_file_native(data)
    except NativeParseError as e:
        raise ParseError(str(e)) from None
    except Exception:  # noqa: BLE001 - any failure means "no native"
        _native_decode_ok = False
        return None


def decode_into(oplog: OpLog, data: bytes, ignore_crc: bool = False) -> List[int]:
    """Decode a .dt file, merging its ops into `oplog` (dedup-safe).
    Returns the file's frontier mapped to local LVs
    (reference: decode_oplog.rs:590-960 decode_internal).

    Fresh loads (empty oplog) go through the native C++ parser when it is
    available (native/dt_decode.cpp — same format, column for column);
    patch files and decode-and-add merges use this Python path."""
    import os
    if len(oplog) == 0 and not ignore_crc \
            and not os.environ.get("DT_TPU_NO_NATIVE"):
        cols = _try_decode_native(data)
        if cols is not None:
            return _rebuild_from_native(oplog, cols)

    if data[:8] != MAGIC:
        raise ParseError("bad magic")
    top = Buf(data, 8)
    if top.next_usize() != PROTOCOL_VERSION:
        raise ParseError("unsupported protocol version")

    # CRC first so we fail before mutating (reference checks last; we can
    # afford the extra pass).
    crc_scan = Buf(data, top.pos)
    crc_expected = None
    crc_end = None
    while not crc_scan.is_empty():
        mark = crc_scan.pos
        t, c = crc_scan.next_chunk()
        if t == CHUNK_CRC:
            crc_expected = int.from_bytes(c.next_n_bytes(4), "little")
            crc_end = mark
            break
    if crc_expected is not None and not ignore_crc:
        if crc32c(data[:crc_end]) != crc_expected:
            raise ParseError("checksum failed")

    compressed: Optional[Buf] = None
    c5 = top.read_chunk_if_eq(CHUNK_COMPRESSED)
    if c5 is not None:
        un_len = c5.next_usize()
        raw = lz4_decompress_block(c5.rest(), un_len)
        compressed = Buf(raw)

    # --- FileInfo ---
    fileinfo = top.expect_chunk(CHUNK_FILEINFO)
    doc_id_chunk = fileinfo.read_chunk_if_eq(CHUNK_DOCID)
    agent_names = fileinfo.expect_chunk(CHUNK_AGENTNAMES)
    _userdata = fileinfo.read_chunk_if_eq(CHUNK_USERDATA)

    if doc_id_chunk is not None:
        if doc_id_chunk.next_u32() != DATA_PLAIN_TEXT:
            raise ParseError("bad docid type")
        file_doc_id = doc_id_chunk.rest().decode("utf8")
        if oplog.doc_id is not None and len(oplog) > 0 \
                and oplog.doc_id != file_doc_id:
            raise ParseError("doc id mismatch")
        oplog.doc_id = file_doc_id

    # agent_map: file agent idx -> [local agent id, seq cursor]
    agent_map: List[list] = []
    while not agent_names.is_empty():
        name = agent_names.next_str()
        agent_map.append([oplog.get_or_create_agent_id(name), 0])

    aa = oplog.cg.agent_assignment

    def read_version_chunk(parent: Buf) -> List[int]:
        chunk = parent.read_chunk_if_eq(CHUNK_VERSION)
        if chunk is None:
            return []
        out = []
        while True:
            n = chunk.next_usize()
            mapped_agent, has_more = strip_bit(n)
            seq = chunk.next_usize()
            if mapped_agent == 0:
                break
            agent = agent_map[mapped_agent - 1][0]
            lv = aa.try_agent_version_to_lv(agent, seq)
            if lv is None:
                raise ParseError("base version unknown (data from the future)")
            out.append(lv)
            if not has_more:
                break
        return sorted(out)

    # --- StartBranch ---
    start_branch = top.expect_chunk(CHUNK_STARTBRANCH)
    start_version = read_version_chunk(start_branch)
    if not start_branch.is_empty():
        _start_content = _content_str(start_branch, compressed)

    patches_overlap = start_version != list(oplog.cg.version)

    # --- Patches ---
    patch_chunk = top.expect_chunk(CHUNK_PATCHES)

    ins_content: Optional[_ContentIter] = None
    del_content: Optional[_ContentIter] = None
    while patch_chunk.peek_chunk_type() == CHUNK_PATCH_CONTENT:
        it = _ContentIter(patch_chunk.next_chunk()[1], compressed)
        if it.kind == INS:
            ins_content = it
        else:
            del_content = it

    agent_assignment_chunk = patch_chunk.expect_chunk(CHUNK_OP_VERSIONS)
    pos_patches_chunk = patch_chunk.expect_chunk(CHUNK_OP_TYPE_AND_POSITION)
    history_chunk = patch_chunk.expect_chunk(CHUNK_OP_PARENTS)

    patches_iter = _PatchesIter(pos_patches_chunk)

    first_new_time = len(oplog)
    next_patch_time = first_new_time
    next_assignment_time = first_new_time
    new_op_start = UNDERWATER if patches_overlap else first_new_time
    next_file_time = new_op_start

    version_map = _VersionMap()

    def parse_next_patches(n: int, keep: bool) -> None:
        nonlocal next_patch_time
        while n > 0:
            row = patches_iter.next()
            if row is None:
                raise ParseError("patch column underrun")
            kind, start, end, fwd = row
            max_len = min(n, end - start)
            content_iter = ins_content if kind == INS else del_content
            content_here = None
            if content_iter is not None:
                crow = content_iter.next()
                if crow is None:
                    raise ParseError("content column underrun")
                clen, cstr = crow
                max_len = min(max_len, clen)
                if clen > max_len:
                    if cstr is not None:
                        content_iter.push_back([clen - max_len, cstr[max_len:]])
                        cstr = cstr[:max_len]
                    else:
                        content_iter.push_back([clen - max_len, None])
                content_here = cstr
            assert max_len > 0
            n -= max_len
            # Split the op row: first max_len items, remainder back.
            from ..text.op import split_op_loc
            if max_len < end - start:
                (s0, e0), (s1, e1) = split_op_loc(kind, start, end, fwd, max_len)
                patches_iter.push_back([kind, s1, e1, fwd])
                start, end = s0, e0
            if keep:
                oplog.ops.push_op(next_patch_time, kind, start, end, fwd,
                                  content_here)
                next_patch_time += max_len

    def find_sparse(agent: int, seq: int):
        """(overlap_lv_start | None, span_end): is `seq` already known, and
        till where does that (known or unknown) state extend?"""
        from bisect import bisect_right
        runs = aa.client_runs[agent]
        i = bisect_right(runs, seq, key=lambda r: r[0]) - 1
        if i >= 0 and seq < runs[i][1]:
            s0, s1, lv0 = runs[i]
            return lv0 + (seq - s0), s1
        nxt = runs[i + 1][0] if i + 1 < len(runs) else 1 << 62
        return None, nxt

    # --- agent assignment + patches ---
    while not agent_assignment_chunk.is_empty():
        n = agent_assignment_chunk.next_usize()
        n, has_jump = strip_bit(n)
        length = agent_assignment_chunk.next_usize()
        jump = agent_assignment_chunk.next_zigzag() if has_jump else 0
        if n == 0:
            raise ParseError("op assigned to ROOT agent")
        if n - 1 >= len(agent_map):
            raise ParseError("invalid agent index")
        entry = agent_map[n - 1]
        agent = entry[0]
        seq_start = entry[1] + jump
        seq_end = seq_start + length
        entry[1] = seq_end

        if patches_overlap:
            seq = seq_start
            while seq < seq_end:
                overlap_lv, span_end = find_sparse(agent, seq)
                end = min(seq_end, span_end)
                chunk_len = end - seq
                if overlap_lv is not None:
                    version_map.push(next_file_time, overlap_lv, chunk_len)
                    keep = False
                else:
                    aa.assign_span(agent, seq, next_assignment_time, chunk_len)
                    version_map.push(next_file_time, next_assignment_time,
                                     chunk_len)
                    next_assignment_time += chunk_len
                    keep = True
                next_file_time += chunk_len
                parse_next_patches(chunk_len, keep)
                seq = end
        else:
            aa.assign_span(agent, seq_start, next_assignment_time, length)
            version_map.push(next_file_time, next_assignment_time, length)
            parse_next_patches(length, True)
            next_assignment_time += length
            next_file_time += length

    # --- history (parents) ---
    next_file_time = new_op_start
    next_history_time = first_new_time
    file_frontier = list(start_version)
    graph = oplog.cg.graph

    def read_parents(chunk: Buf, next_time: int) -> List[int]:
        parents = []
        while True:
            n = chunk.next_usize()
            n, is_foreign = strip_bit(n)
            n, has_more = strip_bit(n)
            if is_foreign:
                if n == 0:
                    break  # ROOT
                agent = agent_map[n - 1][0]
                seq = chunk.next_usize()
                lv = aa.try_agent_version_to_lv(agent, seq)
                if lv is None:
                    raise ParseError("unknown foreign parent")
                parents.append(lv)
            else:
                parents.append(next_time - n)
            if not has_more:
                break
        return sorted(parents)

    while not history_chunk.is_empty():
        length = history_chunk.next_usize()
        parents = read_parents(history_chunk, next_file_time)
        span = (next_file_time, next_file_time + length)
        next_file_time += length

        # Map through version_map piecewise (reference: decode_oplog.rs:241-269).
        while True:
            local_start, run_len = version_map.map_with_len(span[0])
            n_here = min(span[1] - span[0], run_len)
            mapped_span = (local_start, local_start + n_here)
            mapped_parents = []
            for p in parents:
                if p >= UNDERWATER:
                    mp, _ = version_map.map_with_len(p)
                    mapped_parents.append(mp)
                else:
                    mapped_parents.append(p)
            mapped_parents.sort()

            graph._advance_known_run(file_frontier, mapped_parents, mapped_span)

            if mapped_span[1] > next_history_time:
                ms, me = mapped_span
                mp = mapped_parents
                if ms < next_history_time:
                    skip = next_history_time - ms
                    ms += skip
                    mp = [ms - 1]
                graph.push(mp, ms, me)
                graph._advance_known_run(oplog.cg.version, mp, (ms, me))
                next_history_time = me

            if span[0] + n_here < span[1]:
                span = (span[0] + n_here, span[1])
                parents = [span[0] - 1]
            else:
                break

    if next_patch_time != next_assignment_time or \
            next_patch_time != next_history_time:
        raise ParseError("column length mismatch")

    return file_frontier


def load_oplog(data: bytes) -> OpLog:
    """reference: ListOpLog::load_from (decode_oplog.rs:447)."""
    ol = OpLog()
    decode_into(ol, data)
    return ol
