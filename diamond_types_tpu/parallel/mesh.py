"""Device-mesh parallelism for multi-document and multi-replica workloads.

SURVEY.md §2.9: the reference has no process-level parallelism — its
"distributed system" is the logical peer-sync protocol. The TPU rebuild adds
real data parallelism as a first-class axis:

  * `docs` axis — independent documents sharded across devices (pure data
    parallel; no collectives on the hot path).
  * `graph` axis — one huge causal DAG sharded by run index across devices;
    reachability fixed-point sweeps run locally per shard and exchange
    frontier coverage with `psum`/all-reduce over ICI each round
    (BASELINE.json config 5: 10k-replica fan-in graph).

Everything uses jax.sharding + shard_map so XLA inserts the collectives.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
try:                       # jax >= 0.4.38 exports it at top level
    from jax import shard_map
except ImportError:        # pragma: no cover - version-dependent path
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tpu.batch import replay_batch


def make_mesh(n_devices: int | None = None, axis: str = "docs") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def serve_mesh(n_shards: int | None = None, axis: str = "docs") -> Mesh:
    """1-D `docs` mesh over the device slice the serve tier's shards
    occupy (`serve_shard_devices` wraps shards onto devices; the mesh
    covers the distinct devices actually used, capped at the shard
    count). This is the mesh the flush-window coordinator issues its
    single program over."""
    devs = jax.devices()
    n = len(devs) if n_shards is None else min(max(n_shards, 1),
                                               len(devs))
    return Mesh(np.array(devs[:n]), (axis,))


def serve_shard_devices(n_shards: int):
    """Device placement for the serve/ scheduler's shard banks: shard i
    lives on devices[i % n_devices]. With fewer devices than shards the
    assignment wraps (several logical shards share a chip — the CPU
    simulation path, where conftest/driver force a virtual host device
    count). Each SessionBank then builds and steps its sessions under
    `jax.default_device(...)` of its own device, so per-shard work is
    genuinely placed, not just labeled."""
    devs = jax.devices()
    return [devs[i % len(devs)] for i in range(n_shards)]


def sharded_replay(mesh: Mesh, pos, dlen, ilen, chars, cap: int):
    """Shard the batch axis of replay_batch over the mesh's `docs` axis."""
    sh = NamedSharding(mesh, P("docs"))
    pos, dlen, ilen = (jax.device_put(x, sh) for x in (pos, dlen, ilen))
    chars = jax.device_put(chars, sh)
    fn = jax.jit(partial(replay_batch, cap=cap),
                 in_shardings=(sh, sh, sh, sh),
                 out_shardings=(sh, sh))
    return fn(pos, dlen, ilen, chars)


def pad_edges(packed: dict, n_devices: int):
    """Pad a pack_graph CSR edge list to a multiple of n_devices.

    Padding edges scatter to the drop slot (prun == n) with a -1 LV, so
    they are inert regardless of activity. Returns (src, plv, prun) numpy
    arrays ready to shard."""
    n, m = packed["n"], packed["m"]
    pad_to = max(n_devices, ((m + n_devices - 1) // n_devices) * n_devices)
    src = np.zeros(pad_to, dtype=np.int32)
    plv = np.full(pad_to, -1, dtype=np.int32)
    prun = np.full(pad_to, n, dtype=np.int32)
    src[:m] = np.asarray(packed["edge_src"])
    plv[:m] = np.asarray(packed["edge_plv"])
    prun[:m] = np.asarray(packed["edge_prun"])
    return src, plv, prun


def pad_batch_count(b: int, n_devices: int) -> int:
    """Smallest super-batch size >= b that (a) divides the mesh and
    (b) is n_devices times a power of two — divisibility is what
    `shard_map` needs, the pow2 rounding is what keeps the mesh jit
    cache O(log) in window size (mirroring `_pow2` batch rounding on
    the per-shard path)."""
    from ..tpu.merge_kernel import _pow2
    per_dev = max(-(-max(int(b), 1) // n_devices), 1)
    # _pow2 floors at 2; one row per device is a legal class of its own
    # (same convention as _fused_fn's `bp = 1` for a single doc)
    return n_devices * (1 if per_dev == 1 else _pow2(per_dev))


def pad_batch_to_mesh(pos, dlen, ilen, chars, n_devices: int):
    """Pad a packed super-batch's row axis to `pad_batch_count` rows
    (mirroring `pad_edges`): padding rows carry all-zero ops — no-ops
    through the replay kernel — and the caller pairs them with
    `lens = -1` sentinel rows, so they stay identifiably inert end to
    end regardless of what the window carries. Returns
    (pos, dlen, ilen, chars, bp)."""
    b = pos.shape[0]
    bp = pad_batch_count(b, n_devices)
    if bp == b:
        return pos, dlen, ilen, chars, bp

    def _pad(a):
        out = np.zeros((bp,) + a.shape[1:], dtype=a.dtype)
        out[:b] = a
        return out

    return _pad(pos), _pad(dlen), _pad(ilen), _pad(chars), bp


_mesh_jit_cache = {}
from ..analysis.witness import make_lock as _make_lock
_mesh_jit_lock = _make_lock("mesh_jit", "leaf")


def mesh_flush_fn(mesh: Mesh, b: int, n: int, mi: int, cap: int):
    """The mesh flush-window program: the fused replay body wrapped in
    ONE `shard_map` over the mesh's `docs` axis, jitted with donated
    state buffers. The body is pure data parallel (every doc's scan is
    independent), so each device runs its `b / n_devices` row slice
    locally and XLA inserts zero collectives — N shards' buckets flush
    in a single dispatch. Cache keyed on (mesh, shapes), same O(log^2)
    discipline as the per-shard `_fused_fn` cache; lookups surface as
    devprof jit_cache "mesh" rows."""
    key = (mesh, b, n, mi, cap)
    with _mesh_jit_lock:
        fn = _mesh_jit_cache.get(key)
        from ..obs.devprof import note_jit_lookup
        note_jit_lookup("mesh", fn is not None)
        if fn is None:
            from ..tpu.flush_fuse import make_replay_body
            axis = mesh.axis_names[0]
            body = shard_map(make_replay_body(mi), mesh=mesh,
                             in_specs=(P(axis),) * 6,
                             out_specs=(P(axis), P(axis)))
            fn = jax.jit(body, donate_argnums=(0, 1))
            _mesh_jit_cache[key] = fn
    from ..tpu.steer import STEER
    STEER.note_warm("mesh", mi, cap, b, n)
    return fn


def mesh_fused_replay(mesh: Mesh, sessions, plans):
    """Replay MANY shards' pending tails in ONE mesh-sharded program.

    `sessions`/`plans` are the fusable rows of a whole flush window —
    every shard's bucket concatenated — all sharing (cap, max_ins).
    The padded shape `(bp, n)` is STEERED onto a warm mesh jit class
    (`tpu/steer.py`) from the `pad_batch_count` / pow2 floors, and
    state assembly is device-resident by default (`parallel/arena.py`):

      * arena fast path — the previous window's donated output arrays
        are reused verbatim when the same session list recurs in the
        same shape class (zero staging, zero allocation);
      * device-side gather — otherwise sessions' resident rows are
        `jnp.stack`-ed and placed with `NamedSharding` without a host
        round trip; only the host-built op PLAN arrays cross the
        boundary (accounted as purpose="plan").

    With `DEVICE_STAGE` disabled (the `--no-device-stage` control
    arm) the legacy host-numpy staging runs instead and every state
    byte is accounted as purpose="stage" — the A/B that makes the
    staging saving measurable.

    Returns (ok-per-session, device_wait_s, padded_b, staged_bytes);
    `staged_bytes` is the host->device bytes this window's staging
    paid. Per-doc poison and the returned-length fence are
    byte-identical to `fused_replay` (`adopt_results` is shared), so
    the bank's fallback ladder catches violating rows exactly as
    before — and a violating doc in one shard cannot corrupt another
    shard's rows. Padding rows enter with the `lens = -1` sentinel and
    zero ops on EVERY staging path, so they stay identifiably inert.

    Device-planned tails (serve banks built with `device_plan=True`)
    need no special handling here: by the time a row reaches this rung
    its transform has already resolved into a plain doc-order
    `TailPlan` (tpu/xform.py resolve_positions), indistinguishable
    from a host tracker-walk plan — the mesh rung consumes either
    unchanged, and a transform fallback upstream simply arrives as a
    host plan."""
    import time

    import jax.numpy as jnp

    from ..obs.devprof import note_transfer
    from ..tpu.flush_fuse import adopt_results, pack_plans
    from ..tpu.merge_kernel import _pow2
    from ..tpu.steer import STEER
    from . import arena as _arena

    b = len(sessions)
    assert b == len(plans) and b >= 1
    cap = sessions[0].cap
    mi = sessions[0].max_ins
    ndev = int(mesh.devices.size)
    n0 = _pow2(max(max(p.n_ops for p in plans), 1))
    bp0 = pad_batch_count(b, ndev)
    # warm mesh classes are mesh-legal by construction; multiple=ndev
    # keeps a hypothetical second mesh in-process from cross-matching
    bp, n = STEER.snap("mesh", bp0, n0, mi, cap, multiple=ndev)
    pos, dlen, ilen, chars = pack_plans(plans, n, mi, bp)
    plan_bytes = (pos.nbytes + dlen.nbytes + ilen.nbytes + chars.nbytes)
    note_transfer(plan_bytes, rung="mesh", purpose="plan")
    staged_bytes = plan_bytes
    sh = NamedSharding(mesh, P(mesh.axis_names[0]))
    fn = mesh_flush_fn(mesh, bp, n, mi, cap)
    reuse = _arena.acquire(mesh, cap, mi, sessions, bp) \
        if _arena.DEVICE_STAGE.enabled else None
    if reuse is not None:
        # donated-buffer fast path: window k's outputs are window
        # k+1's inputs, already sharded over this mesh — no staging
        docs_d, lens_d = reuse
    elif _arena.DEVICE_STAGE.enabled:
        # device-side gather: resident rows never visit host numpy
        pad = bp - b
        docs_d = jnp.stack([s.docs for s in sessions])
        lens_d = jnp.stack([jnp.asarray(s.lens, jnp.int32)
                            for s in sessions])
        if pad:
            docs_d = jnp.concatenate(
                [docs_d, jnp.zeros((pad, cap), jnp.int32)])
            lens_d = jnp.concatenate(
                [lens_d, jnp.full((pad,), -1, jnp.int32)])
        docs_d = jax.device_put(docs_d, sh)
        lens_d = jax.device_put(lens_d, sh)
    else:
        # control arm: legacy host staging — every resident byte
        # round-trips through numpy and is accounted as staged
        docs_h = np.zeros((bp, cap), np.int32)
        lens_h = np.full((bp,), -1, np.int32)   # padding sentinels
        for i, s in enumerate(sessions):
            docs_h[i] = np.asarray(s.docs)
            lens_h[i] = int(np.asarray(s.lens))
        note_transfer(docs_h.nbytes + lens_h.nbytes,
                      rung="mesh", purpose="stage")
        staged_bytes += docs_h.nbytes + lens_h.nbytes
        docs_d = jax.device_put(jnp.asarray(docs_h), sh)
        lens_d = jax.device_put(jnp.asarray(lens_h), sh)
    out_docs, out_lens = fn(docs_d, lens_d,
                            *(jax.device_put(jnp.asarray(x), sh)
                              for x in (pos, dlen, ilen, chars)))
    # the length fetch is the completion fence + parity cross-check
    t_fence = time.perf_counter()
    got = np.asarray(out_lens)
    device_s = time.perf_counter() - t_fence
    ok = adopt_results(sessions, plans, out_docs, out_lens, got)
    if _arena.DEVICE_STAGE.enabled:
        _arena.adopt(mesh, cap, mi, out_docs, out_lens, sessions,
                     ok, bp)
    return ok, device_s, bp, staged_bytes


def sharded_reach_fixed_point(mesh: Mesh, starts, edge_src, edge_plv,
                              edge_prun, reach0):
    """Causal-graph reachability with the EDGE list sharded across devices.

    Each device owns a contiguous slice of (run, parent) edges; the reach
    vector is replicated. One round = local scatter-max relaxation +
    all-reduce(max) over ICI. Rounds iterate to a fixed point (the
    cross-shard frontier propagation of SURVEY.md §2.9). Edge sharding —
    not run sharding — keeps a 10k-way fan-in merge balanced: its 10k
    edges spread evenly over the mesh instead of landing on one run's
    device.

    starts: int32 [n]; edge_*: int32 [m] (m divisible by the mesh size,
    see pad_edges); reach0: int32 [n].
    """
    n = starts.shape[0]
    axis = mesh.axis_names[0]

    @partial(shard_map, mesh=mesh,
             in_specs=(P(None), P(axis), P(axis), P(axis), P(None)),
             out_specs=P(None))
    def one_round(starts_r, src_l, plv_l, prun_l, reach):
        active = (reach >= starts_r)[src_l]
        contrib = jnp.where(active, plv_l, -1)
        tgt = jnp.where(active, prun_l, jnp.int32(n))
        upd = jnp.full((n,), -1, dtype=reach.dtype).at[tgt].max(
            contrib, mode="drop")
        # Exchange shard contributions over ICI.
        upd = jax.lax.pmax(upd, axis)
        return jnp.maximum(reach, upd)

    def cond(state):
        return state[1]

    def body(state):
        reach, _ = state
        new = one_round(starts, edge_src, edge_plv, edge_prun, reach)
        return new, jnp.any(new != reach)

    reach, _ = jax.lax.while_loop(cond, body, (reach0, jnp.array(True)))
    return reach


def multichip_merge_step(mesh: Mesh, pos, dlen, ilen, chars, cap: int,
                         starts, edge_src, edge_plv, edge_prun, reach0):
    """One full sharded "step": sharded multi-doc replay (data parallel) +
    sharded causal-graph propagation (graph parallel with collectives).
    This is the step that `__graft_entry__.dryrun_multichip` jits over an
    n-device mesh."""
    docs, lens = sharded_replay(mesh, pos, dlen, ilen, chars, cap)
    reach = sharded_reach_fixed_point(mesh, starts, edge_src, edge_plv,
                                      edge_prun, reach0)
    return docs, lens, reach
