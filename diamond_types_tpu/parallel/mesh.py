"""Device-mesh parallelism for multi-document and multi-replica workloads.

SURVEY.md §2.9: the reference has no process-level parallelism — its
"distributed system" is the logical peer-sync protocol. The TPU rebuild adds
real data parallelism as a first-class axis:

  * `docs` axis — independent documents sharded across devices (pure data
    parallel; no collectives on the hot path).
  * `graph` axis — one huge causal DAG sharded by run index across devices;
    reachability fixed-point sweeps run locally per shard and exchange
    frontier coverage with `psum`/all-reduce over ICI each round
    (BASELINE.json config 5: 10k-replica fan-in graph).

Everything uses jax.sharding + shard_map so XLA inserts the collectives.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
try:                       # jax >= 0.4.38 exports it at top level
    from jax import shard_map
except ImportError:        # pragma: no cover - version-dependent path
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tpu.batch import replay_batch


def make_mesh(n_devices: int | None = None, axis: str = "docs") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def serve_shard_devices(n_shards: int):
    """Device placement for the serve/ scheduler's shard banks: shard i
    lives on devices[i % n_devices]. With fewer devices than shards the
    assignment wraps (several logical shards share a chip — the CPU
    simulation path, where conftest/driver force a virtual host device
    count). Each SessionBank then builds and steps its sessions under
    `jax.default_device(...)` of its own device, so per-shard work is
    genuinely placed, not just labeled."""
    devs = jax.devices()
    return [devs[i % len(devs)] for i in range(n_shards)]


def sharded_replay(mesh: Mesh, pos, dlen, ilen, chars, cap: int):
    """Shard the batch axis of replay_batch over the mesh's `docs` axis."""
    sh = NamedSharding(mesh, P("docs"))
    pos, dlen, ilen = (jax.device_put(x, sh) for x in (pos, dlen, ilen))
    chars = jax.device_put(chars, sh)
    fn = jax.jit(partial(replay_batch, cap=cap),
                 in_shardings=(sh, sh, sh, sh),
                 out_shardings=(sh, sh))
    return fn(pos, dlen, ilen, chars)


def pad_edges(packed: dict, n_devices: int):
    """Pad a pack_graph CSR edge list to a multiple of n_devices.

    Padding edges scatter to the drop slot (prun == n) with a -1 LV, so
    they are inert regardless of activity. Returns (src, plv, prun) numpy
    arrays ready to shard."""
    n, m = packed["n"], packed["m"]
    pad_to = max(n_devices, ((m + n_devices - 1) // n_devices) * n_devices)
    src = np.zeros(pad_to, dtype=np.int32)
    plv = np.full(pad_to, -1, dtype=np.int32)
    prun = np.full(pad_to, n, dtype=np.int32)
    src[:m] = np.asarray(packed["edge_src"])
    plv[:m] = np.asarray(packed["edge_plv"])
    prun[:m] = np.asarray(packed["edge_prun"])
    return src, plv, prun


def sharded_reach_fixed_point(mesh: Mesh, starts, edge_src, edge_plv,
                              edge_prun, reach0):
    """Causal-graph reachability with the EDGE list sharded across devices.

    Each device owns a contiguous slice of (run, parent) edges; the reach
    vector is replicated. One round = local scatter-max relaxation +
    all-reduce(max) over ICI. Rounds iterate to a fixed point (the
    cross-shard frontier propagation of SURVEY.md §2.9). Edge sharding —
    not run sharding — keeps a 10k-way fan-in merge balanced: its 10k
    edges spread evenly over the mesh instead of landing on one run's
    device.

    starts: int32 [n]; edge_*: int32 [m] (m divisible by the mesh size,
    see pad_edges); reach0: int32 [n].
    """
    n = starts.shape[0]
    axis = mesh.axis_names[0]

    @partial(shard_map, mesh=mesh,
             in_specs=(P(None), P(axis), P(axis), P(axis), P(None)),
             out_specs=P(None))
    def one_round(starts_r, src_l, plv_l, prun_l, reach):
        active = (reach >= starts_r)[src_l]
        contrib = jnp.where(active, plv_l, -1)
        tgt = jnp.where(active, prun_l, jnp.int32(n))
        upd = jnp.full((n,), -1, dtype=reach.dtype).at[tgt].max(
            contrib, mode="drop")
        # Exchange shard contributions over ICI.
        upd = jax.lax.pmax(upd, axis)
        return jnp.maximum(reach, upd)

    def cond(state):
        return state[1]

    def body(state):
        reach, _ = state
        new = one_round(starts, edge_src, edge_plv, edge_prun, reach)
        return new, jnp.any(new != reach)

    reach, _ = jax.lax.while_loop(cond, body, (reach0, jnp.array(True)))
    return reach


def multichip_merge_step(mesh: Mesh, pos, dlen, ilen, chars, cap: int,
                         starts, edge_src, edge_plv, edge_prun, reach0):
    """One full sharded "step": sharded multi-doc replay (data parallel) +
    sharded causal-graph propagation (graph parallel with collectives).
    This is the step that `__graft_entry__.dryrun_multichip` jits over an
    n-device mesh."""
    docs, lens = sharded_replay(mesh, pos, dlen, ilen, chars, cap)
    reach = sharded_reach_fixed_point(mesh, starts, edge_src, edge_plv,
                                      edge_prun, reach0)
    return docs, lens, reach
