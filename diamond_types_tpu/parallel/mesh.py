"""Device-mesh parallelism for multi-document and multi-replica workloads.

SURVEY.md §2.9: the reference has no process-level parallelism — its
"distributed system" is the logical peer-sync protocol. The TPU rebuild adds
real data parallelism as a first-class axis:

  * `docs` axis — independent documents sharded across devices (pure data
    parallel; no collectives on the hot path).
  * `graph` axis — one huge causal DAG sharded by run index across devices;
    reachability fixed-point sweeps run locally per shard and exchange
    frontier coverage with `psum`/all-reduce over ICI each round
    (BASELINE.json config 5: 10k-replica fan-in graph).

Everything uses jax.sharding + shard_map so XLA inserts the collectives.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tpu.batch import replay_batch


def make_mesh(n_devices: int | None = None, axis: str = "docs") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def sharded_replay(mesh: Mesh, pos, dlen, ilen, chars, cap: int):
    """Shard the batch axis of replay_batch over the mesh's `docs` axis."""
    sh = NamedSharding(mesh, P("docs"))
    pos, dlen, ilen = (jax.device_put(x, sh) for x in (pos, dlen, ilen))
    chars = jax.device_put(chars, sh)
    fn = jax.jit(partial(replay_batch, cap=cap),
                 in_shardings=(sh, sh, sh, sh),
                 out_shardings=(sh, sh))
    return fn(pos, dlen, ilen, chars)


def sharded_reach_fixed_point(mesh: Mesh, starts, parent_lv, parent_run,
                              reach0):
    """Causal-graph reachability with the run table sharded across devices.

    Each device owns a contiguous slice of runs. One round = local scatter-max
    relaxation + all-reduce(max) of the global reach vector over ICI. Rounds
    iterate to a fixed point (device analogue of the cross-shard frontier
    propagation described in SURVEY.md §2.9).

    starts: int64 [n]; parent_lv: int64 [n, k]; parent_run: int32 [n, k]
    (global run indices, n = pad); reach0: int64 [n].
    """
    n = starts.shape[0]
    axis = mesh.axis_names[0]

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis), P(axis, None), P(axis, None), P(None)),
             out_specs=P(None))
    def one_round(starts_l, plv_l, prun_l, reach):
        # Local slice: which of my runs are active?
        shard_i = jax.lax.axis_index(axis)
        per = starts_l.shape[0]
        offset = shard_i * per
        my_reach = jax.lax.dynamic_slice(reach, (offset,), (per,))
        active = my_reach >= starts_l
        contrib = jnp.where(active[:, None], plv_l, -1).reshape(-1)
        tgt = jnp.where(active[:, None], prun_l, jnp.int32(n)).reshape(-1)
        upd = jnp.full((n,), -1, dtype=reach.dtype).at[tgt].max(
            contrib, mode="drop")
        # Exchange shard contributions over ICI.
        upd = jax.lax.pmax(upd, axis)
        return jnp.maximum(reach, upd)

    def cond(state):
        return state[1]

    def body(state):
        reach, _ = state
        new = one_round(starts, parent_lv, parent_run, reach)
        return new, jnp.any(new != reach)

    reach, _ = jax.lax.while_loop(cond, body, (reach0, jnp.array(True)))
    return reach


def multichip_merge_step(mesh: Mesh, pos, dlen, ilen, chars, cap: int,
                         starts, parent_lv, parent_run, reach0):
    """One full sharded "step": sharded multi-doc replay (data parallel) +
    sharded causal-graph propagation (graph parallel with collectives).
    This is the step that `__graft_entry__.dryrun_multichip` jits over an
    n-device mesh."""
    docs, lens = sharded_replay(mesh, pos, dlen, ilen, chars, cap)
    reach = sharded_reach_fixed_point(mesh, starts, parent_lv, parent_run,
                                      reach0)
    return docs, lens, reach
