"""Per-mesh window arenas: device-resident staging + donated-buffer
reuse for the mesh flush rung.

Pre-arena, `mesh_fused_replay` re-staged every session's resident
state through host numpy each window (`np.asarray(s.docs)` into a
fresh `[B, cap]` buffer, then `device_put`) — a full host round trip
for rows that already lived on-chip, and the donated `[B, cap]`
output buffers of window k were simply dropped. This module keeps
both on the device:

  * **Device-side gather** (the `DEVICE_STAGE` default): sessions'
    `docs`/`lens` rows are stacked with `jnp.stack` and placed with
    `NamedSharding` directly — no host copy of resident state; only
    the window's op PLAN arrays (host-built by construction) still
    cross the host boundary.
  * **Arena fast path** (donated-buffer reuse): after a window
    commits, its `[B, cap]` output arrays are parked as the arena of
    the `(mesh, cap, max_ins)` class and every committed session row
    is tagged `(arena, generation, row)`. When the NEXT window
    presents the same session list in the same shape class, the arena
    arrays are handed straight back to the donated kernel — zero
    staging, zero allocation. Donation is safe because sessions hold
    independent per-row buffers (`out_docs[i]` is an eager gather),
    never the stacked array itself.

Poison/fallback discipline: a row that fails the `adopt_results`
length fence is NOT committed, so its session keeps a stale-generation
tag (or none) — the next window's tag check misses, the gather path
rebuilds from the sessions' own rows, and the poisoned slot can never
leak stale bytes. Any session mutation outside the mesh commit
(`FusedDocSession.commit` / `_materialize`) clears the tag for the
same reason.

Lock order: `_arena_lock` is a DEVICE-class witness lock (rank=None —
it guards a process-wide table, not a chip), taken briefly around
table reads/swaps while the scheduler already holds the ranked
per-device locks; dispatches and `device_put` run strictly OUTSIDE
it. It never acquires anything itself.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..analysis.witness import make_lock as _make_lock

_arena_lock = _make_lock("window_arena", "device", rank=None)


class _StageFlag:
    """Process-global device-staging switch (`--no-device-stage`
    flips it for the A/B control arm: host-numpy staging, full
    transfer accounting — the pre-arena behavior)."""

    def __init__(self) -> None:
        self.enabled = True


DEVICE_STAGE = _StageFlag()


class WindowArena:
    """Parked output buffers of the last committed window of one
    `(mesh, cap, max_ins)` class. `gen` increments per adoption so a
    stale tag can never match; `docs`/`lens` are cleared on handoff
    (donation consumes them) and on any failed dispatch they simply
    stay cleared until the next adoption."""

    __slots__ = ("bp", "gen", "live", "docs", "lens")

    def __init__(self) -> None:
        self.bp = 0
        self.gen = 0
        self.live = 0
        self.docs = None
        self.lens = None


_arenas: Dict[Tuple, WindowArena] = {}


def reset_arenas() -> None:
    with _arena_lock:
        _arenas.clear()


def arena_stats() -> dict:
    with _arena_lock:
        return {"arenas": len(_arenas),
                "generations": sum(a.gen for a in _arenas.values())}


def acquire(mesh, cap: int, mi: int, sessions, bp: int):
    """Try the fast path: if the previous window of this shape class
    committed EXACTLY these sessions in this order at this padded
    batch, hand its parked `[bp, cap]` arrays back for donation.
    Returns `(docs, lens)` or None (caller gathers instead)."""
    key = (mesh, int(cap), int(mi))
    with _arena_lock:
        a = _arenas.get(key)
        if a is None or a.docs is None or a.bp != bp \
                or a.live != len(sessions):
            return None
        for i, s in enumerate(sessions):
            if getattr(s, "_arena_tag", None) != (a, a.gen, i):
                return None
        docs, lens = a.docs, a.lens
        a.docs = a.lens = None      # the donated call consumes them
        for s in sessions:
            s._arena_tag = None     # re-tagged on adopt, or not at all
        return docs, lens


def adopt(mesh, cap: int, mi: int, out_docs, out_lens, sessions,
          ok: List[bool], bp: int) -> None:
    """Park a committed window's output arrays as the next window's
    arena and tag every COMMITTED session row. Rows that failed the
    length fence are left untagged — their slot exists in the parked
    array but can never be matched, so the fast path degrades to the
    gather path instead of replaying stale bytes."""
    key = (mesh, int(cap), int(mi))
    with _arena_lock:
        a = _arenas.setdefault(key, WindowArena())
        a.gen += 1
        a.bp = bp
        a.live = len(sessions)
        a.docs = out_docs
        a.lens = out_lens
        for i, s in enumerate(sessions):
            if ok[i]:
                s._arena_tag = (a, a.gen, i)
