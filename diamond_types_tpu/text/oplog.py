"""The document operation log.

Capability mirror of the reference ListOpLog (reference: src/list/mod.rs:104-126,
src/list/oplog.rs): an append-only columnar op table + causal graph + content
arenas. Every public entry point of the reference's stable list API is here:
local/remote append paths, checkout, transformed-op iteration, stats.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

from ..causalgraph.causal_graph import CausalGraph
from ..core.span import Span
from ..listmerge.transform import TransformedOps
from .op import DEL, INS, OpRun, OpStore


class OpLog:
    __slots__ = ("cg", "ops", "doc_id", "_native_ctx")

    def __init__(self) -> None:
        self.cg = CausalGraph()
        self.ops = OpStore()
        self.doc_id: Optional[str] = None
        self._native_ctx = None

    def __len__(self) -> int:
        return len(self.cg)

    def get_or_create_agent_id(self, name: str) -> int:
        return self.cg.get_or_create_agent(name)

    @property
    def version(self) -> List[int]:
        return list(self.cg.version)

    # --- local append path (reference: src/list/oplog.rs:203-296) ---------

    def add_insert_at(self, agent: int, parents: Sequence[int], pos: int,
                      content: str) -> int:
        """Append an insert op; returns the last new LV."""
        lv = len(self)
        self.ops.push_op(lv, INS, pos, pos + len(content), True, content)
        self.cg.assign_local_op_with_parents(parents, agent, len(content))
        return lv + len(content) - 1

    def add_delete_at(self, agent: int, parents: Sequence[int], start: int,
                      end: int, content: Optional[str] = None) -> int:
        lv = len(self)
        n = end - start
        assert n > 0
        self.ops.push_op(lv, DEL, start, end, True, content)
        self.cg.assign_local_op_with_parents(parents, agent, n)
        return lv + n - 1

    def add_insert(self, agent: int, pos: int, content: str) -> int:
        return self.add_insert_at(agent, self.version, pos, content)

    def add_delete_without_content(self, agent: int, start: int, end: int) -> int:
        return self.add_delete_at(agent, self.version, start, end)

    # --- remote append path ------------------------------------------------

    def add_remote_op(self, agent: int, seq_start: int, parents: Sequence[int],
                      kind: int, start: int, end: int, fwd: bool,
                      content: Optional[str]) -> Span:
        """Merge a remote op run; dedups already-known spans via the causal
        graph (reference: decode path, causalgraph.rs:132)."""
        n = end - start
        span = self.cg.merge_and_assign(parents, agent, seq_start, n)
        new_len = span[1] - span[0]
        if new_len > 0:
            skip = n - new_len
            if skip and content is not None:
                content = content[skip:]
            if skip:
                from .op import sub_op_loc
                start, end = sub_op_loc(kind, start, end, fwd, skip, n)
            self.ops.push_op(span[0], kind, start, end, fwd, content)
        return span

    # --- transformed ops ---------------------------------------------------

    def get_xf_operations_full(self, from_frontier: Sequence[int],
                               merge_frontier: Sequence[int]) -> TransformedOps:
        return TransformedOps(self.cg.graph, self.cg.agent_assignment, self.ops,
                              list(from_frontier), list(merge_frontier))

    def iter_xf_operations_from(self, from_frontier: Sequence[int],
                                merge_frontier: Sequence[int]
                                ) -> Iterator[Tuple[Span, Optional[OpRun], Optional[str]]]:
        """Yield (lv_span, transformed_op | None, content | None)."""
        xf = self.get_xf_operations_full(from_frontier, merge_frontier)
        for lv, op, pos in xf:
            n = len(op)
            if pos is None:
                yield ((lv, lv + n), None, None)
            else:
                moved = OpRun(op.lv, op.kind, pos, pos + n, op.fwd, op.content_pos)
                yield ((lv, lv + n), moved, self.ops.get_run_content(op))

    def iter_xf_operations(self):
        return self.iter_xf_operations_from([], self.version)

    # --- checkout ----------------------------------------------------------

    def checkout(self, frontier: Sequence[int]):
        from .branch import Branch
        b = Branch()
        b.merge(self, frontier)
        return b

    def checkout_tip(self):
        return self.checkout(self.version)

    # --- misc ---------------------------------------------------------------

    def print_stats(self) -> None:
        print(f"oplog: {len(self)} LVs in {len(self.ops.runs)} op runs, "
              f"{len(self.cg.graph)} graph runs, "
              f"{len(self.cg.agent_assignment.agent_names)} agents, "
              f"ins arena {self.ops.arena_len(INS)} chars, "
              f"del arena {self.ops.arena_len(DEL)} chars")
