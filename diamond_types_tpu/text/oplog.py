"""The document operation log.

Capability mirror of the reference ListOpLog (reference: src/list/mod.rs:104-126,
src/list/oplog.rs): an append-only columnar op table + causal graph + content
arenas. Every public entry point of the reference's stable list API is here:
local/remote append paths, checkout, transformed-op iteration, stats.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

from ..causalgraph.causal_graph import CausalGraph
from ..core.span import Span
from ..listmerge.transform import TransformedOps
from .op import DEL, INS, OpRun, OpStore


class OpLog:
    __slots__ = ("cg", "ops", "doc_id", "_native_ctx")

    def __init__(self) -> None:
        self.cg = CausalGraph()
        self.ops = OpStore()
        self.doc_id: Optional[str] = None
        self._native_ctx = None

    def __len__(self) -> int:
        return len(self.cg)

    def get_or_create_agent_id(self, name: str) -> int:
        return self.cg.get_or_create_agent(name)

    @property
    def version(self) -> List[int]:
        return list(self.cg.version)

    # --- local append path (reference: src/list/oplog.rs:203-296) ---------

    def add_insert_at(self, agent: int, parents: Sequence[int], pos: int,
                      content: str) -> int:
        """Append an insert op; returns the last new LV."""
        lv = len(self)
        self.ops.push_op(lv, INS, pos, pos + len(content), True, content)
        self.cg.assign_local_op_with_parents(parents, agent, len(content))
        return lv + len(content) - 1

    def add_delete_at(self, agent: int, parents: Sequence[int], start: int,
                      end: int, content: Optional[str] = None) -> int:
        lv = len(self)
        n = end - start
        assert n > 0
        self.ops.push_op(lv, DEL, start, end, True, content)
        self.cg.assign_local_op_with_parents(parents, agent, n)
        return lv + n - 1

    def add_insert(self, agent: int, pos: int, content: str) -> int:
        return self.add_insert_at(agent, self.version, pos, content)

    def local_session(self, agent: int):
        """Native batched ingest for linear tip edits by one agent — the
        editor-typing hot path at C speed (reference: the native local
        apply path, src/list/oplog.rs:203-296; ~30x the per-op Python
        path on automerge-paper). Pending edits land at flush()/context
        exit; see native/ingest.py for scope and parity guarantees."""
        from ..native.ingest import LocalSession
        return LocalSession(self, agent)

    def add_delete_without_content(self, agent: int, start: int, end: int) -> int:
        return self.add_delete_at(agent, self.version, start, end)

    def apply_local_patches(self, agent: int,
                            patches: Sequence[Tuple[int, int, str]]) -> int:
        """Bulk local ingest: apply `[(pos, num_deleted, ins_text), ...]`
        patches (delete first, then insert — the editing-trace convention)
        as one linear chain on top of the current version. Semantically
        identical to calling add_delete_without_content/add_insert per
        patch, but the RLE grouping and bookkeeping are vectorized so
        ingest runs at array speed instead of Python-call speed
        (reference: the grouped-RLE apply path, crates/bench/src/main.rs
        local/apply_grouped_rle:56-72). Returns the last new LV.

        The positional RLE merge rules mirror OpStore.push_op /
        can_append_ops (op_metrics.rs:235-256): forward insert runs chain
        end-to-start, delete-key runs repeat one position, backspace runs
        chain start-to-end. A chain's direction is fixed by its first
        link; a direction flip starts a new run (at worst slightly less
        compact than the sequential merger, never wrong).
        """
        import numpy as np

        if len(patches) == 0:
            return len(self) - 1
        pos_l, nd_l, txt_l = zip(*patches)
        return self.apply_local_patch_columns(
            agent,
            np.array(pos_l, dtype=np.int64),
            np.array(nd_l, dtype=np.int64),
            np.array(list(map(len, txt_l)), dtype=np.int64),
            "".join(txt_l))

    def apply_local_patch_columns(self, agent: int, pos, nd, ni,
                                  ins_text: str) -> int:
        """Columnar core of apply_local_patches: `pos`/`nd`/`ni` are int64
        arrays (patch position, deleted count, inserted count) and
        `ins_text` is every patch's inserted text concatenated. Pure
        array math end-to-end — the shape the trace loader (and any
        network ingest path) can produce directly."""
        import numpy as np

        has_d = nd > 0
        has_i = ni > 0
        cnt = has_d.astype(np.int64) + has_i.astype(np.int64)
        m = int(cnt.sum())
        if m == 0:
            return len(self) - 1
        # interleave per-patch (delete, insert) ops into one dense stream
        slot = np.cumsum(cnt) - cnt
        kind = np.empty(m, np.int64)
        s = np.empty(m, np.int64)
        e = np.empty(m, np.int64)
        ds = slot[has_d]
        kind[ds] = DEL
        s[ds] = pos[has_d]
        e[ds] = pos[has_d] + nd[has_d]
        is_ = (slot + has_d)[has_i]
        kind[is_] = INS
        s[is_] = pos[has_i]
        e[is_] = pos[has_i] + ni[has_i]
        ln = e - s

        # pairwise link types between op i and i+1:
        #   1 = forward chain (ins end-to-start / delete-key same-start)
        #   2 = backspace chain, 0 = no merge
        pk, ck = kind[:-1], kind[1:]
        link_fwd = ((pk == ck)
                    & (((ck == INS) & (s[1:] == e[:-1]))
                       | ((ck == DEL) & (s[1:] == s[:-1]))))
        link_back = (pk == DEL) & (ck == DEL) & (e[1:] == s[:-1])
        ltype = np.where(link_fwd, 1, np.where(link_back, 2, 0))
        brk = np.empty(m, dtype=bool)
        brk[0] = True
        brk[1:] = ltype == 0
        if m > 2:
            # direction flip inside a live chain starts a new run
            brk[2:] |= (ltype[:-1] != 0) & (ltype[1:] != ltype[:-1])

        firsts = np.flatnonzero(brk)
        counts = np.diff(np.append(firsts, m))
        lasts = firsts + counts - 1
        g_len = np.add.reduceat(ln, firsts)
        tip = len(self)
        g_lv = tip + np.cumsum(g_len) - g_len
        g_kind = kind[firsts]
        g_back = np.zeros(len(firsts), dtype=bool)
        multi = counts > 1
        g_back[multi] = ltype[firsts[multi]] == 2
        g_start = np.where(g_back, s[lasts], s[firsts])
        g_end = np.where(g_back, e[firsts], g_start + g_len)

        # insert contents: one arena append, cumulative char offsets
        base, _ = self.ops._arenas[INS].push(ins_text) if ins_text \
            else (0, 0)
        ins_ln = np.where(kind == INS, ln, 0)
        coff = np.cumsum(ins_ln) - ins_ln

        # one tolist() per column (C-speed int conversion) — per-element
        # numpy scalar indexing made this loop the whole ingest cost
        runs = self.ops.runs
        cp0 = (base + coff[firsts]).tolist()
        for lv, k, st, en, back, c0, gl in zip(
                g_lv.tolist(), g_kind.tolist(), g_start.tolist(),
                g_end.tolist(), g_back.tolist(), cp0, g_len.tolist()):
            runs.append(OpRun(lv, k, st, en, not back,
                              (c0, c0 + gl) if k == INS else None))

        total = int(g_len.sum())
        self.cg.assign_local_op_with_parents(self.version, agent, total)
        return tip + total - 1

    # --- remote append path ------------------------------------------------

    def add_remote_op(self, agent: int, seq_start: int, parents: Sequence[int],
                      kind: int, start: int, end: int, fwd: bool,
                      content: Optional[str]) -> Span:
        """Merge a remote op run; dedups already-known spans via the causal
        graph (reference: decode path, causalgraph.rs:132)."""
        n = end - start
        span = self.cg.merge_and_assign(parents, agent, seq_start, n)
        new_len = span[1] - span[0]
        if new_len > 0:
            skip = n - new_len
            if skip and content is not None:
                content = content[skip:]
            if skip:
                from .op import sub_op_loc
                start, end = sub_op_loc(kind, start, end, fwd, skip, n)
            self.ops.push_op(span[0], kind, start, end, fwd, content)
        return span

    # --- transformed ops ---------------------------------------------------

    def get_xf_operations_full(self, from_frontier: Sequence[int],
                               merge_frontier: Sequence[int]) -> TransformedOps:
        return TransformedOps(self.cg.graph, self.cg.agent_assignment, self.ops,
                              list(from_frontier), list(merge_frontier))

    def iter_xf_operations_from(self, from_frontier: Sequence[int],
                                merge_frontier: Sequence[int]
                                ) -> Iterator[Tuple[Span, Optional[OpRun], Optional[str]]]:
        """Yield (lv_span, transformed_op | None, content | None)."""
        xf = self.get_xf_operations_full(from_frontier, merge_frontier)
        for lv, op, pos in xf:
            n = len(op)
            if pos is None:
                yield ((lv, lv + n), None, None)
            else:
                moved = OpRun(op.lv, op.kind, pos, pos + n, op.fwd, op.content_pos)
                yield ((lv, lv + n), moved, self.ops.get_run_content(op))

    def iter_xf_operations(self):
        return self.iter_xf_operations_from([], self.version)

    # --- conflict detection --------------------------------------------------

    def count_conflicts_when_merging(
            self, from_frontier: Sequence[int],
            merge_frontier: Optional[Sequence[int]] = None) -> int:
        """How many genuinely colliding concurrent inserts the merge from
        `from_frontier` to `merge_frontier` (default: tip) resolves —
        concurrent inserts landing in the same document gap, the YjsMod
        tie-break actually firing. 0 means the merge is trivial: positions
        transform cleanly with no insert-order ambiguity. The exact count
        is engine-granularity-specific (RLE runs, not chars); only
        zero-vs-nonzero is engine-independent — the reference likewise
        keeps only a boolean flag.

        Reference: `has_conflicts_when_merging` (src/list/merge.rs:51) and
        the merge_conflict_checks collision flag (listmerge/mod.rs:50-51,
        merge.rs:176-179)."""
        merge = list(self.version) if merge_frontier is None \
            else list(merge_frontier)
        frm = [int(x) for x in from_frontier]
        from ..native import native_ctx_or_none
        ctx = native_ctx_or_none(self)
        if ctx is not None:
            ctx.transform(frm, merge)
            ctx.release_tracker()
            return ctx.last_collisions()
        xf = self.get_xf_operations_full(frm, merge)
        for _ in xf:
            pass
        return xf.collisions

    def has_conflicts_when_merging(
            self, from_frontier: Sequence[int],
            merge_frontier: Optional[Sequence[int]] = None) -> bool:
        return self.count_conflicts_when_merging(
            from_frontier, merge_frontier) > 0

    # --- checkout ----------------------------------------------------------

    def checkout(self, frontier: Sequence[int]):
        from .branch import Branch
        b = Branch()
        b.merge(self, frontier)
        return b

    def checkout_tip(self):
        return self.checkout(self.version)

    # --- misc ---------------------------------------------------------------

    def print_stats(self) -> None:
        print(f"oplog: {len(self)} LVs in {len(self.ops.runs)} op runs, "
              f"{len(self.cg.graph)} graph runs, "
              f"{len(self.cg.agent_assignment.agent_names)} agents, "
              f"ins arena {self.ops.arena_len(INS)} chars, "
              f"del arena {self.ops.arena_len(DEL)} chars")
