"""Columnar operation storage (struct-of-arrays).

Capability mirror of the reference's op table (reference:
src/list/op_metrics.rs:24-78): each run is `(loc_start, loc_end, fwd, kind,
content span)`, contents live in shared per-kind character arenas. Runs are
keyed by their starting LV; the key column is ascending and dense.

Positions are unicode-char indexes. Contents are stored in append-only arenas
with lazily-consolidated string views (content_pos indexes are in *chars*,
unlike the reference's byte offsets — chars keep all device math uniform,
SURVEY.md §7 hard-part 5).
"""

from __future__ import annotations

from bisect import bisect_right
from dataclasses import dataclass
from typing import List, Optional, Tuple

INS = 0
DEL = 1


@dataclass(slots=True)
class OpRun:
    lv: int              # starting LV of this run
    kind: int            # INS / DEL
    start: int           # loc span start (doc position, chars)
    end: int             # loc span end
    fwd: bool
    content_pos: Optional[Tuple[int, int]]  # char span into the arena, or None

    def __len__(self) -> int:
        return self.end - self.start


class _Arena:
    """Append-only char arena with a lazily consolidated string view."""

    __slots__ = ("_parts", "_str", "_len")

    def __init__(self) -> None:
        self._parts: List[str] = []
        self._str = ""
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def push(self, s: str) -> Tuple[int, int]:
        start = self._len
        self._parts.append(s)
        self._len += len(s)
        return (start, self._len)

    def get(self, span: Tuple[int, int]) -> str:
        if len(self._str) != self._len:
            self._str = self._str + "".join(self._parts)
            self._parts.clear()
        return self._str[span[0]:span[1]]


class OpStore:
    """Append-mostly RLE vector of op runs + content arenas."""

    __slots__ = ("runs", "_arenas")

    def __init__(self) -> None:
        self.runs: List[OpRun] = []
        self._arenas = (_Arena(), _Arena())  # INS, DEL

    def arena_len(self, kind: int) -> int:
        return len(self._arenas[kind])

    def push_content(self, kind: int, s: str) -> Tuple[int, int]:
        return self._arenas[kind].push(s)

    def get_content(self, kind: int, span: Tuple[int, int]) -> str:
        return self._arenas[kind].get(span)

    def get_run_content(self, run: OpRun) -> Optional[str]:
        if run.content_pos is None:
            return None
        return self._arenas[run.kind].get(run.content_pos)

    def find_idx(self, lv: int) -> int:
        i = bisect_right(self.runs, lv, key=lambda r: r.lv) - 1
        if i < 0:
            raise KeyError(lv)
        return i

    def content_slice(self, lv: int, n: int) -> Optional[str]:
        """Content chars for items [lv, lv+n) of the run containing lv."""
        run = self.runs[self.find_idx(lv)]
        if run.content_pos is None:
            return None
        off = lv - run.lv
        assert off + n <= len(run)
        base = run.content_pos[0]
        return self._arenas[run.kind].get((base + off, base + off + n))

    def end_lv(self) -> int:
        if not self.runs:
            return 0
        last = self.runs[-1]
        return last.lv + len(last)

    def push_op(self, lv: int, kind: int, start: int, end: int, fwd: bool,
                content: Optional[str]) -> None:
        """Append one op run, RLE-merging with the previous run when possible
        (reference: src/list/oplog.rs:159-175 + RleVec append)."""
        content_pos = self.push_content(kind, content) if content is not None else None
        run = OpRun(lv, kind, start, end, fwd, content_pos)
        if self.runs:
            prev = self.runs[-1]
            if (prev.lv + len(prev) == lv and prev.kind == kind
                    and (prev.content_pos is None) == (content_pos is None)
                    and can_append_ops(kind, prev, run)):
                append_ops(kind, prev, run)
                return
        self.runs.append(run)

    def iter_range(self, span: Tuple[int, int]):
        """Yield (lv, kind, loc_start, loc_end, fwd, content_pos) sub-runs
        covering LV span `span` (reference: src/list/op_iter.rs)."""
        lo, hi = span
        if hi <= lo:
            return
        i = self.find_idx(lo)
        pos = lo
        while pos < hi:
            run = self.runs[i]
            run_end_lv = run.lv + len(run)
            off0 = pos - run.lv
            off1 = min(hi, run_end_lv) - run.lv
            yield self._slice_run(run, off0, off1)
            pos = run.lv + off1
            i += 1

    @staticmethod
    def _slice_run(run: OpRun, off0: int, off1: int) -> OpRun:
        """Sub-run covering item offsets [off0, off1) of `run`."""
        n = len(run)
        assert 0 <= off0 < off1 <= n
        if off0 == 0 and off1 == n:
            return run
        loc = sub_op_loc(run.kind, run.start, run.end, run.fwd, off0, off1)
        cp = None
        if run.content_pos is not None:
            cp = (run.content_pos[0] + off0, run.content_pos[0] + off1)
        return OpRun(run.lv + off0, run.kind, loc[0], loc[1], run.fwd, cp)


def can_append_ops(kind: int, a: OpRun, b: OpRun) -> bool:
    """RLE append rule for positional runs (reference: op_metrics.rs:235-256).

    Ins forward: b continues at a's end position. Del forward: b deletes at
    a's *start* (delete-key runs). Del reverse: b ends at a's start
    (backspace runs).
    """
    a_len, b_len = len(a), len(b)
    if (a_len == 1 or a.fwd) and (b_len == 1 or b.fwd):
        if kind == INS and b.start == a.end:
            return True
        if kind == DEL and b.start == a.start:
            return True
    if kind == DEL and (a_len == 1 or not a.fwd) and (b_len == 1 or not b.fwd):
        if b.end == a.start:
            return True
    return False


def append_ops(kind: int, a: OpRun, b: OpRun) -> None:
    """Merge run `b` into `a` in place (reference: op_metrics.rs:258-271)."""
    fwd = b.start >= a.start and (b.start != a.start or kind == DEL)
    a.fwd = fwd
    if kind == DEL and not fwd:
        a.start = b.start
    else:
        a.end += len(b)
    if a.content_pos is not None and b.content_pos is not None:
        assert a.content_pos[1] == b.content_pos[0]
        a.content_pos = (a.content_pos[0], b.content_pos[1])


def split_op_loc(kind: int, start: int, end: int, fwd: bool, at: int):
    """Split a run's loc after `at` items -> (first_loc, rest_loc).

    Del-fwd remainders re-target `start`; Del-rev runs consume from the tail
    first (reference: op_metrics.rs truncate_tagged_span).
    """
    length = end - start
    assert 0 < at < length
    if kind == INS:
        if fwd:
            return (start, start + at), (start + at, end)
        raise NotImplementedError("reverse inserts")
    else:
        if fwd:
            return (start, start + at), (start, start + (length - at))
        else:
            return (end - at, end), (start, end - at)


def sub_op_loc(kind: int, start: int, end: int, fwd: bool,
               off0: int, off1: int) -> Tuple[int, int]:
    """Loc of the sub-run covering item offsets [off0, off1)."""
    loc = (start, end)
    if off0 > 0:
        _, loc = split_op_loc(kind, loc[0], loc[1], fwd, off0)
    n = loc[1] - loc[0]
    take = off1 - off0
    if take < n:
        loc, _ = split_op_loc(kind, loc[0], loc[1], fwd, take)
    return loc
