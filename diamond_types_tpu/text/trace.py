"""Editing-trace loader + replay.

Loads the concurrent-editing-trace JSON format used by the reference's bench
corpus (reference: crates/crdt-testdata/src/lib.rs:14-54): gzipped JSON with
`startContent`, `endContent` and `txns: [{patches: [[pos, del, ins], ...]}]`.
"""

from __future__ import annotations

import gzip
import json
from dataclasses import dataclass
from typing import List, Tuple

from .oplog import OpLog


@dataclass
class TestData:
    start_content: str
    end_content: str
    txns: List[List[Tuple[int, int, str]]]  # per txn: [(pos, num_deleted, ins)]

    def num_ops(self) -> int:
        return sum(len(t) for t in self.txns)

    def patch_columns(self):
        """Columnar view of the flattened patches: (pos, num_del, ins_len)
        int64 arrays + concatenated insert text — the zero-Python-loop
        input shape of OpLog.apply_local_patch_columns. Cached."""
        cols = getattr(self, "_cols", None)
        if cols is None:
            import numpy as np
            flat = [p for t in self.txns for p in t]
            pos_l, nd_l, txt_l = zip(*flat) if flat else ((), (), ())
            cols = (np.array(pos_l, dtype=np.int64),
                    np.array(nd_l, dtype=np.int64),
                    np.array(list(map(len, txt_l)), dtype=np.int64),
                    "".join(txt_l))
            self._cols = cols
        return cols


def load_trace(path: str) -> TestData:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf8") as f:
        d = json.load(f)
    return TestData(
        start_content=d["startContent"],
        end_content=d["endContent"],
        txns=[[(p[0], p[1], p[2]) for p in t["patches"]] for t in d["txns"]],
    )


def replay_into_oplog(data: TestData, agent_name: str = "trace") -> OpLog:
    """Linear replay of a trace into an oplog (reference:
    crates/bench/src/main.rs local/apply_* benches)."""
    ol = OpLog()
    agent = ol.get_or_create_agent_id(agent_name)
    assert not data.start_content, "traces in the corpus start empty"
    for txn in data.txns:
        for (pos, num_del, ins) in txn:
            if num_del:
                ol.add_delete_without_content(agent, pos, pos + num_del)
            if ins:
                ol.add_insert(agent, pos, ins)
    return ol


def replay_into_oplog_native(data: TestData,
                             agent_name: str = "trace") -> OpLog:
    """Per-op replay through the native local-ingest session (reference:
    local/apply_direct over the native push path, src/list/oplog.rs:
    203-296 + crates/bench/src/main.rs:17-40). Same per-op call shape as
    replay_into_oplog; the RLE/graph/arena state lands bit-identical
    (tests/test_native_ingest.py proves encode parity)."""
    ol = OpLog()
    agent = ol.get_or_create_agent_id(agent_name)
    assert not data.start_content, "traces in the corpus start empty"
    session = ol.local_session(agent)
    sess, ins, dele = session.hot()
    for txn in data.txns:
        for (pos, num_del, ins_text) in txn:
            if num_del:
                dele(sess, pos, pos + num_del)
            if ins_text:
                ins(sess, pos, ins_text)
    session.flush()
    return ol


def replay_into_oplog_grouped(data: TestData,
                              agent_name: str = "trace") -> OpLog:
    """Bulk-ingest replay via OpLog.apply_local_patches (reference:
    crates/bench/src/main.rs local/apply_grouped_rle)."""
    ol = OpLog()
    agent = ol.get_or_create_agent_id(agent_name)
    assert not data.start_content, "traces in the corpus start empty"
    ol.apply_local_patch_columns(agent, *data.patch_columns())
    return ol


def replay_direct(data: TestData) -> str:
    """Oracle replay straight into a rope (no CRDT)."""
    from ..utils.rope import Rope
    r = Rope(data.start_content)
    for txn in data.txns:
        for (pos, num_del, ins) in txn:
            if num_del:
                r.delete(pos, num_del)
            if ins:
                r.insert(pos, ins)
    return str(r)
