"""ListCRDT: the convenience (oplog, branch) pair kept in lockstep.

Capability mirror of the reference ListCRDT (reference: src/list/mod.rs:142-145,
src/list/list.rs:144-222).
"""

from __future__ import annotations

from typing import Sequence

from .branch import Branch
from .oplog import OpLog


class ListCRDT:
    __slots__ = ("oplog", "branch")

    def __init__(self) -> None:
        self.oplog = OpLog()
        self.branch = Branch()

    def __len__(self) -> int:
        return len(self.branch)

    def get_or_create_agent_id(self, name: str) -> int:
        return self.oplog.get_or_create_agent_id(name)

    def insert(self, agent: int, pos: int, content: str) -> int:
        return self.branch.insert(self.oplog, agent, pos, content)

    def delete(self, agent: int, start: int, end: int) -> int:
        return self.branch.delete(self.oplog, agent, start, end)

    def snapshot(self) -> str:
        return self.branch.snapshot()

    def merge_data_and_ff(self, other: "ListCRDT") -> None:
        """Pull every op from `other` then fast-forward our branch."""
        merge_oplogs(self.oplog, other.oplog)
        self.branch.merge_tip(self.oplog)


def merge_oplogs(dst: OpLog, src: OpLog) -> None:
    """Merge all ops of `src` into `dst` (cross-oplog version mapping;
    capability mirror of reference src/list/oplog_merge.rs:10-30)."""
    # Map src agents into dst agent ids lazily.
    agent_map = {}

    def map_agent(a: int) -> int:
        if a not in agent_map:
            name = src.cg.agent_assignment.get_agent_name(a)
            agent_map[a] = dst.get_or_create_agent_id(name)
        return agent_map[a]

    for (lv0, lv1, parents, agent, seq) in src.cg.iter_entries():
        # Convert parents to dst LVs via (agent, seq) naming.
        dst_parents = []
        for p in parents:
            pa, pseq = src.cg.agent_assignment.local_to_agent_version(p)
            dlv = dst.cg.agent_assignment.try_agent_version_to_lv(map_agent(pa), pseq)
            assert dlv is not None, "src parents must be merged before children"
            dst_parents.append(dlv)
        dst_parents.sort()

        # Ops covering [lv0, lv1) in src, re-keyed into dst LV space.
        for piece in src.ops.iter_range((lv0, lv1)):
            off = piece.lv - lv0
            content = src.ops.get_run_content(piece)
            dst.add_remote_op(map_agent(agent), seq + off, dst_parents if off == 0
                              else [dst.cg.agent_assignment.agent_version_to_lv(
                                    map_agent(agent), seq + off - 1)],
                              piece.kind, piece.start, piece.end, piece.fwd,
                              content)
