"""Operational-transform bridge.

Capability mirror of the reference's OT layer (reference:
crates/diamond-types-old/src/list/ot/ot.rs — `transform`, `compose`, apply —
and positionmap.rs which maps CRDT ops onto positional traversal ops;
README.md:31-33: "interoperable with positional updates ... via operational
transform"). This lets plain centralized clients interoperate with CRDT
peers: a traversal op is a list of components over unicode chars:

    int n     -> retain n
    "text"    -> insert text
    {"d": n}  -> delete n

Validated against the reference's golden conformance vectors
(test_data/ot/{apply,compose,transform}.json).
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Union

Component = Union[int, str, dict]
TraversalOp = List[Component]


def _is_retain(c: Component) -> bool:
    return isinstance(c, int)


def _is_insert(c: Component) -> bool:
    return isinstance(c, str)


def _is_delete(c: Component) -> bool:
    return isinstance(c, dict)


def _clen(c: Component) -> int:
    if isinstance(c, int):
        return c
    if isinstance(c, str):
        return len(c)
    return c["d"]


class _Appender:
    """Append components, merging adjacent same-kind ones."""

    def __init__(self) -> None:
        self.out: TraversalOp = []

    def append(self, c: Component) -> None:
        if c == 0 or c == "" or (isinstance(c, dict) and c["d"] == 0):
            return
        out = self.out
        if out:
            last = out[-1]
            if _is_retain(last) and _is_retain(c):
                out[-1] = last + c
                return
            if _is_insert(last) and _is_insert(c):
                out[-1] = last + c
                return
            if _is_delete(last) and _is_delete(c):
                out[-1] = {"d": last["d"] + c["d"]}
                return
        out.append(c)

    def result(self) -> TraversalOp:
        # Trim a trailing retain.
        if self.out and _is_retain(self.out[-1]):
            self.out.pop()
        return self.out


class _Taker:
    """Consume an op component-stream in arbitrary-size chunks."""

    def __init__(self, op: TraversalOp) -> None:
        self.op = op
        self.idx = 0
        self.offset = 0

    def take(self, n: int, indivisible: str = "") -> Component | None:
        """Take up to n of the current component (-1 = the whole thing).
        When the current component's kind matches `indivisible` ("i" insert /
        "d" delete), take it whole regardless of n."""
        if self.idx == len(self.op):
            return None if n == -1 else (n if n > 0 else None)
        c = self.op[self.idx]
        if _is_retain(c):
            if n == -1 or c - self.offset <= n:
                part: Component = c - self.offset
                self.idx += 1
                self.offset = 0
            else:
                part = n
                self.offset += n
        elif _is_insert(c):
            if n == -1 or indivisible == "i" or len(c) - self.offset <= n:
                part = c[self.offset:]
                self.idx += 1
                self.offset = 0
            else:
                part = c[self.offset:self.offset + n]
                self.offset += n
        else:
            if n == -1 or indivisible == "d" or c["d"] - self.offset <= n:
                part = {"d": c["d"] - self.offset}
                self.idx += 1
                self.offset = 0
            else:
                part = {"d": n}
                self.offset += n
        return part

    def peek(self) -> Component | None:
        return self.op[self.idx] if self.idx < len(self.op) else None


def normalize(op: TraversalOp) -> TraversalOp:
    a = _Appender()
    for c in op:
        a.append(c)
    return a.result()


def apply(doc: str, op: TraversalOp) -> str:
    """Apply a traversal op to a string (reference: ot.rs apply)."""
    out: List[str] = []
    pos = 0
    for c in op:
        if _is_retain(c):
            assert pos + c <= len(doc), "retain past end"
            out.append(doc[pos:pos + c])
            pos += c
        elif _is_insert(c):
            out.append(c)
        else:
            assert pos + c["d"] <= len(doc), "delete past end"
            pos += c["d"]
    out.append(doc[pos:])
    return "".join(out)


def compose(op1: TraversalOp, op2: TraversalOp) -> TraversalOp:
    """Compose two sequential ops into one (reference: ot.rs compose)."""
    t = _Taker(op1)
    a = _Appender()
    for c in op2:
        if _is_retain(c):
            n = c
            while n > 0:
                chunk = t.take(n, "d")
                if chunk is None:
                    a.append(n)
                    n = 0
                    break
                a.append(chunk)
                if not _is_delete(chunk):
                    n -= _clen(chunk)
        elif _is_insert(c):
            a.append(c)
        else:
            n = c["d"]
            while n > 0:
                chunk = t.take(n, "d")
                if chunk is None:
                    a.append({"d": n})
                    n = 0
                    break
                if _is_retain(chunk):
                    a.append({"d": chunk})
                    n -= chunk
                elif _is_insert(chunk):
                    n -= len(chunk)  # inserted then deleted: cancels out
                else:
                    a.append(chunk)  # op1's delete happens first
    while True:
        chunk = t.take(-1)
        if chunk is None:
            break
        a.append(chunk)
    return a.result()


def transform(op: TraversalOp, other: TraversalOp, side: str) -> TraversalOp:
    """Transform `op` so it applies after `other` (reference: ot.rs transform).
    `side` breaks insert ties: "left" inserts before the other's inserts."""
    assert side in ("left", "right")
    t = _Taker(op)
    a = _Appender()
    for c in other:
        if _is_retain(c):
            n = c
            while n > 0:
                chunk = t.take(n, "i")
                if chunk is None:
                    a.append(n)
                    n = 0
                    break
                a.append(chunk)
                if not _is_insert(chunk):
                    n -= _clen(chunk)
        elif _is_insert(c):
            if side == "left" and _is_insert(t.peek()):
                a.append(t.take(-1))
            a.append(len(c))  # retain over the other's insert
        else:
            n = c["d"]
            while n > 0:
                chunk = t.take(n, "i")
                if chunk is None:
                    n = 0
                    break
                if _is_retain(chunk):
                    n -= chunk
                elif _is_insert(chunk):
                    a.append(chunk)
                else:
                    n -= chunk["d"]  # deleted by both: drop
    while True:
        chunk = t.take(-1)
        if chunk is None:
            break
        a.append(chunk)
    return a.result()


def xf_stream_to_traversal(xf_iter, final_len_hint: int | None = None
                           ) -> TraversalOp:
    """Convert a transformed-op stream (lv_span, OpRun|None, content) from
    OpLog.iter_xf_operations_from into a single traversal op by composition
    (capability mirror of reference positionmap.rs: CRDT ops -> positional
    OT ops)."""
    from .op import INS
    result: TraversalOp = []
    for (_span, op, content) in xf_iter:
        if op is None:
            continue
        if op.kind == INS:
            assert content is not None
            if not op.fwd:
                content = content[::-1]
            step: TraversalOp = [op.start, content]
        else:
            step = [op.start, {"d": len(op)}]
        result = compose(result, normalize(step))
    return result
