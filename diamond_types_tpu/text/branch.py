"""A branch: (version frontier, document content) — a live checkpoint.

Capability mirror of the reference ListBranch (reference: src/list/mod.rs:66-76,
src/list/branch.rs, src/list/merge.rs:63-96).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from ..utils.rope import Rope
from .op import DEL, INS
from .oplog import OpLog


class Branch:
    __slots__ = ("version", "content", "last_merge_collisions",
                 "last_merge_engine")

    def __init__(self) -> None:
        self.version: List[int] = []
        self.content = Rope()
        # collisions reported by the last merge() — genuinely concurrent
        # inserts at the same gap (reference: has_conflicts_when_merging,
        # src/list/merge.rs:51). None = the selected engine doesn't report
        # (zone/plan2/device tiers); 0 = merged cleanly. A fully-default
        # merge() can return None once the measured policy has zone
        # measurements: check last_merge_engine to detect which engine
        # ran, and use OpLog.has_conflicts_when_merging (before merging)
        # when a collision count is required regardless of engine.
        self.last_merge_collisions: Optional[int] = None
        # which engine the policy picked for the last merge() — the
        # supported way to interpret last_merge_collisions above
        self.last_merge_engine: Optional[str] = None

    def __len__(self) -> int:
        return len(self.content)

    def snapshot(self) -> str:
        return str(self.content)

    # --- local edits (append to oplog, then apply here) --------------------

    def insert(self, oplog: OpLog, agent: int, pos: int, content: str) -> int:
        lv = oplog.add_insert_at(agent, self.version, pos, content)
        self.content.insert(pos, content)
        self.version = [lv]
        return lv

    def delete(self, oplog: OpLog, agent: int, start: int, end: int) -> int:
        deleted = self.content.slice(start, end)
        lv = oplog.add_delete_at(agent, self.version, start, end, deleted)
        self.content.delete(start, end - start)
        self.version = [lv]
        return lv

    def delete_without_content(self, oplog: OpLog, agent: int, start: int,
                               end: int) -> int:
        lv = oplog.add_delete_at(agent, self.version, start, end, None)
        self.content.delete(start, end - start)
        self.version = [lv]
        return lv

    # UTF-16 entry points for JS/Swift-style clients (reference:
    # branch.rs insert_at_wchar / delete_at_wchar, wchar_conversion feature).

    def insert_at_wchar(self, oplog: OpLog, agent: int, wchar_pos: int,
                        content: str) -> int:
        from ..core.unicount import wchars_to_chars
        return self.insert(oplog, agent,
                           wchars_to_chars(self.snapshot(), wchar_pos), content)

    def delete_at_wchar(self, oplog: OpLog, agent: int, wchar_start: int,
                        wchar_end: int) -> int:
        from ..core.unicount import wchars_to_chars
        snap = self.snapshot()
        return self.delete(oplog, agent, wchars_to_chars(snap, wchar_start),
                           wchars_to_chars(snap, wchar_end))

    # --- merge -------------------------------------------------------------

    def merge(self, oplog: OpLog, merge_frontier: Sequence[int]) -> None:
        """Bring everything in `merge_frontier`'s history into this branch
        (reference: src/list/merge.rs:63-96).

        Backend selection behind this one boundary (the reference keeps
        listmerge/listmerge2 behind the same seam):
          * DT_TPU_DEVICE_MERGE=1 — device merge kernel (Fugue-tree
            linearization of the conflict zone, batched-friendly),
          * default — C++ host core when built (same algorithm as the
            Python engine, ~2 orders of magnitude faster),
          * DT_TPU_ZONE=1 — zone engine (host composes entries, every
            origin resolves against state rows on the device tier —
            tpu/zone_kernel.py; the round-3 flagship),
          * DT_TPU_PLAN2=1 — fork/join plan engine (compile the conflict
            zone into a Begin/Fork/Max/Apply schedule over numbered state
            indexes, execute against the dense state matrix — the
            listmerge2 design; listmerge/plan2.py + dense.py),
          * DT_TPU_NO_NATIVE=1 — pure-Python engine (the oracle).

        Without an env override, the ZONE engine is auto-selected when
        the measured policy (listmerge/policy.py) says its observed
        throughput beats the tracker's for single-doc merges — engine
        selection is measured, not belief; the tracker remains the
        default and the oracle.
        """
        import time as _time

        self.last_merge_collisions = None
        self.last_merge_engine = None
        if os.environ.get("DT_TPU_PLAN2"):
            from ..listmerge.dense import merge_via_plan2
            rows, final = merge_via_plan2(oplog, self.version,
                                          merge_frontier)
            self._apply_xf(oplog, rows)
            self.version = list(final)
            self.last_merge_engine = "plan2"
            return
        if os.environ.get("DT_TPU_DEVICE_MERGE"):
            from ..tpu.merge_kernel import merge_device
            text, frontier = merge_device(oplog, self.version,
                                          merge_frontier)
            self.content = Rope(text)
            self.version = frontier
            self.last_merge_engine = "device"
            return

        def _top(v):
            return max((int(x) for x in v), default=-1) + 1

        from ..listmerge import policy as _policy

        def _zone_merge():
            # the round-3 zone engine: host composes, device (or the
            # NumPy oracle under JAX_PLATFORMS=cpu) resolves every origin
            # against state rows — no tracker anywhere. Its throughput is
            # recorded by zone_checkout_device itself. A policy-selected
            # zone merge reports last_merge_collisions = None (the
            # documented "engine doesn't report" value).
            from ..tpu.zone_kernel import zone_checkout_device
            text, frontier = zone_checkout_device(oplog, self.version,
                                                  merge_frontier)
            self.content = Rope(text)
            self.version = list(frontier)
            self.last_merge_engine = _policy.ZONE

        def _tracker_merge(ctx):
            from ..native import merge_native
            n_before = _top(self.version)
            t0 = _time.perf_counter()
            doc, frontier = merge_native(oplog, self.snapshot(),
                                         self.version, merge_frontier)
            self.content = Rope(doc)
            self.version = frontier
            self.last_merge_collisions = ctx.last_collisions()
            self.last_merge_engine = _policy.TRACKER
            _policy.GLOBAL.record(_policy.TRACKER,
                                  _top(self.version) - n_before,
                                  _time.perf_counter() - t0)

        if os.environ.get("DT_TPU_ZONE"):   # explicit dev override
            _zone_merge()
            return
        from ..native import native_ctx_or_none
        ctx = native_ctx_or_none(oplog)
        if ctx is not None:
            # fully-default path: measured policy decides (zone is never
            # chosen before it has measurements, with one exception: a
            # cooldown re-probe after a failure-demotion, which implies
            # zone already ran in this process — see policy.py)
            n_hint = _top(merge_frontier) - _top(self.version)
            if _policy.GLOBAL.choose(n_hint) == _policy.ZONE:
                try:
                    _zone_merge()
                    return
                except Exception as e:
                    # demote the zone engine and fall back: a failed
                    # accelerator path must never fail a merge the
                    # tracker can do in milliseconds. Leave a trail —
                    # otherwise a transient blip and a persistent zone
                    # bug both look like an unexplained slowdown.
                    import warnings
                    warnings.warn(
                        f"zone engine failed ({e.__class__.__name__}: "
                        f"{e}); demoted, falling back to the tracker",
                        RuntimeWarning)
                    _policy.GLOBAL.forget(_policy.ZONE)
            _tracker_merge(ctx)
            return

        # DT_TPU_NO_NATIVE / no library: the pure-Python oracle, always
        xf = oplog.get_xf_operations_full(self.version, merge_frontier)
        self._apply_xf(oplog, xf)
        self.version = list(xf.next_frontier)
        self.last_merge_collisions = xf.collisions
        self.last_merge_engine = "python"

    def _apply_xf(self, oplog: OpLog, rows) -> None:
        """Apply an (lv, op, xf_pos|None) stream to this branch's content —
        the one shared application loop for every host engine."""
        for _lv, op, pos in rows:
            if pos is None:
                continue  # delete already happened
            if op.kind == INS:
                content = oplog.ops.get_run_content(op)
                assert content is not None
                if not op.fwd:
                    content = content[::-1]
                self.content.insert(pos, content)
            else:
                self.content.delete(pos, len(op))

    def merge_tip(self, oplog: OpLog) -> None:
        self.merge(oplog, oplog.version)
