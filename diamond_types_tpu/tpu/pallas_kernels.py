"""Pallas TPU kernels for the batched replay hot loop.

The XLA path (tpu/batch.py) expresses one op-application as a select over
static rolls plus unrolled insert lanes (it deliberately avoids dynamic
gathers — the TPU slow path); this module provides the same step as a
hand-written Pallas kernel that keeps the whole document block resident in
VMEM and fuses the shift / insert-select arithmetic into one pass per
(doc-block, op), without materializing the 2*max_ins+1 rolled copies the
XLA formulation selects among.

Kernels run natively on TPU; tests exercise them with `interpret=True` on
the CPU mesh (pallas_guide.md debugging convention).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces only exist on TPU-enabled builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _apply_op_kernel(pos_ref, dlen_ref, ilen_ref, chars_ref, doc_ref,
                     len_ref, out_doc_ref, out_len_ref):
    """One op applied to a [block, cap] slab of documents (all in VMEM).

    out[i] = chars[i - pos]          for pos <= i < pos+ilen   (insert lane)
           = doc[i]                  for i < pos
           = doc[i - ilen + dlen]    for i >= pos+ilen         (tail shift)
    """
    doc = doc_ref[...]                      # [b, cap] int32
    pos = pos_ref[...][:, None]             # [b, 1]
    dlen = dlen_ref[...][:, None]
    ilen = ilen_ref[...][:, None]
    chars = chars_ref[...]                  # [b, max_ins]
    cap = doc.shape[1]
    idx = jax.lax.broadcasted_iota(jnp.int32, doc.shape, 1)

    shift = ilen - dlen
    src = jnp.where(idx < pos, idx, idx - shift)
    gathered = jnp.take_along_axis(doc, jnp.clip(src, 0, cap - 1), axis=1)
    ins_idx = jnp.clip(idx - pos, 0, chars.shape[1] - 1)
    ins_vals = jnp.take_along_axis(chars, ins_idx, axis=1)
    in_insert = (idx >= pos) & (idx < pos + ilen)
    new_doc = jnp.where(in_insert, ins_vals, gathered)

    noop = (ilen == 0) & (dlen == 0)
    out_doc_ref[...] = jnp.where(noop, doc, new_doc)
    out_len_ref[...] = len_ref[...] + jnp.where(noop[:, 0], 0,
                                                (ilen - dlen)[:, 0])


def apply_op_block(pos, dlen, ilen, chars, doc, doc_len, *,
                   interpret: bool = False):
    """Apply one positional op per document to a [b, cap] batch (Pallas)."""
    b, cap = doc.shape
    kwargs = {}
    if not interpret and _VMEM is not None:
        spec = pl.BlockSpec(memory_space=_VMEM)
        kwargs = {"in_specs": [spec] * 6, "out_specs": (spec, spec)}
    return pl.pallas_call(
        _apply_op_kernel,
        out_shape=(jax.ShapeDtypeStruct((b, cap), jnp.int32),
                   jax.ShapeDtypeStruct((b,), jnp.int32)),
        interpret=interpret,
        **kwargs,
    )(pos, dlen, ilen, chars, doc, doc_len)


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def replay_batch_pallas(pos, dlen, ilen, chars, cap: int,
                        interpret: bool = False):
    """Full batched replay with the Pallas step kernel inside lax.scan
    (drop-in for tpu.batch.replay_batch)."""
    b = pos.shape[0]
    docs0 = jnp.zeros((b, cap), dtype=jnp.int32)
    lens0 = jnp.zeros((b,), dtype=jnp.int32)

    def step(carry, op):
        docs, lens = carry
        p, d, i, c = op
        docs, lens = apply_op_block(p, d, i, c, docs, lens,
                                    interpret=interpret)
        return (docs, lens), None

    ops = (jnp.swapaxes(pos, 0, 1), jnp.swapaxes(dlen, 0, 1),
           jnp.swapaxes(ilen, 0, 1), jnp.swapaxes(chars, 0, 1))
    (docs, lens), _ = jax.lax.scan(step, (docs0, lens0), ops)
    return docs, lens
