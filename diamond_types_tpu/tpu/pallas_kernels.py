"""Pallas TPU kernels for the batched replay hot loop.

The XLA path (tpu/batch.py) expresses one op-application as a select over
static rolls plus unrolled insert lanes (it deliberately avoids dynamic
gathers — the TPU slow path); this module provides the same step as a
hand-written Pallas kernel that keeps the whole document block resident in
VMEM and fuses the shift / insert-select arithmetic into one pass per
(doc-block, op), without materializing the 2*max_ins+1 rolled copies the
XLA formulation selects among.

Kernels run natively on TPU; tests exercise them with `interpret=True` on
the CPU mesh (pallas_guide.md debugging convention).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces only exist on TPU-enabled builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _apply_op_kernel(pos_ref, dlen_ref, ilen_ref, chars_ref, doc_ref,
                     len_ref, out_doc_ref, out_len_ref):
    """One op applied to a [block, cap] slab of documents (all in VMEM).

    out[i] = chars[i - pos]          for pos <= i < pos+ilen   (insert lane)
           = doc[i]                  for i < pos
           = doc[i - ilen + dlen]    for i >= pos+ilen         (tail shift)
    """
    doc = doc_ref[...]                      # [b, cap] int32
    pos = pos_ref[...][:, None]             # [b, 1]
    dlen = dlen_ref[...][:, None]
    ilen = ilen_ref[...][:, None]
    chars = chars_ref[...]                  # [b, max_ins]
    cap = doc.shape[1]
    idx = jax.lax.broadcasted_iota(jnp.int32, doc.shape, 1)

    shift = ilen - dlen
    src = jnp.where(idx < pos, idx, idx - shift)
    gathered = jnp.take_along_axis(doc, jnp.clip(src, 0, cap - 1), axis=1)
    ins_idx = jnp.clip(idx - pos, 0, chars.shape[1] - 1)
    ins_vals = jnp.take_along_axis(chars, ins_idx, axis=1)
    in_insert = (idx >= pos) & (idx < pos + ilen)
    new_doc = jnp.where(in_insert, ins_vals, gathered)

    noop = (ilen == 0) & (dlen == 0)
    out_doc_ref[...] = jnp.where(noop, doc, new_doc)
    out_len_ref[...] = len_ref[...] + jnp.where(noop[:, 0], 0,
                                                (ilen - dlen)[:, 0])


def apply_op_block(pos, dlen, ilen, chars, doc, doc_len, *,
                   interpret: bool = False):
    """Apply one positional op per document to a [b, cap] batch (Pallas)."""
    b, cap = doc.shape
    kwargs = {}
    if not interpret and _VMEM is not None:
        spec = pl.BlockSpec(memory_space=_VMEM)
        kwargs = {"in_specs": [spec] * 6, "out_specs": (spec, spec)}
    return pl.pallas_call(
        _apply_op_kernel,
        out_shape=(jax.ShapeDtypeStruct((b, cap), jnp.int32),
                   jax.ShapeDtypeStruct((b,), jnp.int32)),
        interpret=interpret,
        **kwargs,
    )(pos, dlen, ilen, chars, doc, doc_len)


# ---------------------------------------------------------------------------
# materialize: run-expansion as a Pallas kernel (VERDICT r2 next-step #5)
# ---------------------------------------------------------------------------


def _materialize_kernel(starts_ref, base_ref, arena_ref, total_ref,
                        out_ref, *, n_pow: int):
    """Expand visible runs into text for one [block] of output positions.

    Gather-only formulation (TPU Pallas has fast gathers, no fast
    scatter): each output position j binary-searches the compacted live
    runs' start table (log2(n) vectorized steps), then reads its char
    through the run's affine base. Replaces materialize_jax's
    scatter+cummax run expansion for the device merge path."""
    j = jax.lax.broadcasted_iota(jnp.int32, (1, out_ref.shape[1]), 1) + \
        pl.program_id(0) * out_ref.shape[1]
    starts = starts_ref[...]               # [1, n] (+inf padded, sorted)
    base = base_ref[...]                   # [1, n]
    arena = arena_ref[...]                 # [1, A]
    total = total_ref[0]

    # binary search: largest r with starts[r] <= j
    lo = jnp.zeros_like(j)
    for _ in range(n_pow):
        step = jnp.full_like(j, 1 << (n_pow - 1)) if _ == 0 else step // 2
        probe = lo + step
        pv = jnp.take_along_axis(
            starts, jnp.clip(probe, 0, starts.shape[1] - 1), axis=1)
        lo = jnp.where((probe < starts.shape[1]) & (pv <= j), probe, lo)
    b = jnp.take_along_axis(base, lo, axis=1)
    src = jnp.clip(b + j, 0, arena.shape[1] - 1)
    text = jnp.take_along_axis(arena, src, axis=1)
    out_ref[...] = jnp.where(j < total, text, 0)


def materialize_pallas(perm, vis_len, arena_off, arena, cap: int,
                       interpret: bool = False):
    """Drop-in for linearize.materialize_jax with the run expansion in a
    Pallas kernel. The XLA pre-pass compacts live runs (sorted starts +
    affine bases — one cumsum and one scatter over [n]); the [cap]-wide
    expansion (the hot part) runs in VMEM."""
    if not interpret and jax.default_backend() != "tpu":
        interpret = True   # CPU/GPU backends run the kernel interpreted
    n = perm.shape[0]
    vl = vis_len[perm]
    cum = jnp.cumsum(vl)
    total = (cum[-1] if n else jnp.int32(0)).astype(jnp.int32)
    starts = cum - vl
    base = arena_off[perm] - starts
    live = vl > 0
    # compact live runs to a sorted prefix; pad tail with +inf starts
    k = jnp.cumsum(live.astype(jnp.int32)) - 1
    n_pad = max(1, _next_pow2(n))
    INF = jnp.int32(2 ** 30)
    starts_c = jnp.full((n_pad,), INF, jnp.int32).at[
        jnp.where(live, k, n_pad - 1)].set(
        jnp.where(live, starts, INF).astype(jnp.int32), mode="drop")
    base_c = jnp.zeros((n_pad,), jnp.int32).at[
        jnp.where(live, k, n_pad - 1)].set(
        jnp.where(live, base, 0).astype(jnp.int32), mode="drop")
    # guard slot 0: with no live runs at position 0 the search floor must
    # still be a valid run for padded positions (masked by `total` anyway)
    arena_i = arena.astype(jnp.int32)
    A = arena_i.shape[0]

    block = min(cap, 64 * 1024)
    grid = (cap + block - 1) // block
    kwargs = {}
    if not interpret and _VMEM is not None:
        kwargs = {
            "in_specs": [
                pl.BlockSpec((1, n_pad), lambda i: (0, 0),
                             memory_space=_VMEM),
                pl.BlockSpec((1, n_pad), lambda i: (0, 0),
                             memory_space=_VMEM),
                pl.BlockSpec((1, A), lambda i: (0, 0),
                             memory_space=_VMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            "out_specs": pl.BlockSpec((1, block), lambda i: (0, i),
                                      memory_space=_VMEM),
        }
    else:
        kwargs = {
            "in_specs": [pl.BlockSpec((1, n_pad), lambda i: (0, 0)),
                         pl.BlockSpec((1, n_pad), lambda i: (0, 0)),
                         pl.BlockSpec((1, A), lambda i: (0, 0)),
                         pl.BlockSpec((1,), lambda i: (0,))],
            "out_specs": pl.BlockSpec((1, block), lambda i: (0, i)),
        }
    out = pl.pallas_call(
        functools.partial(_materialize_kernel,
                          n_pow=max(1, (n_pad - 1).bit_length())),
        grid=(grid,),
        out_shape=jax.ShapeDtypeStruct((1, grid * block), jnp.int32),
        interpret=interpret,
        **kwargs,
    )(starts_c[None, :], base_c[None, :], arena_i[None, :],
      total[None])
    return out[0, :cap], total


def _next_pow2(x: int) -> int:
    return 1 << max(1, int(x) - 1).bit_length()


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def replay_batch_pallas(pos, dlen, ilen, chars, cap: int,
                        interpret: bool = False):
    """Full batched replay with the Pallas step kernel inside lax.scan
    (drop-in for tpu.batch.replay_batch)."""
    b = pos.shape[0]
    docs0 = jnp.zeros((b, cap), dtype=jnp.int32)
    lens0 = jnp.zeros((b,), dtype=jnp.int32)

    def step(carry, op):
        docs, lens = carry
        p, d, i, c = op
        docs, lens = apply_op_block(p, d, i, c, docs, lens,
                                    interpret=interpret)
        return (docs, lens), None

    ops = (jnp.swapaxes(pos, 0, 1), jnp.swapaxes(dlen, 0, 1),
           jnp.swapaxes(ilen, 0, 1), jnp.swapaxes(chars, 0, 1))
    (docs, lens), _ = jax.lax.scan(step, (docs0, lens0), ops)
    return docs, lens
