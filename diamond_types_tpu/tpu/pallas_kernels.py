"""Pallas TPU kernels for the batched replay hot loop.

The XLA path (tpu/batch.py) expresses one op-application as a select over
static rolls plus unrolled insert lanes (it deliberately avoids dynamic
gathers — the TPU slow path); this module provides the same step as a
hand-written Pallas kernel that keeps the whole document block resident in
VMEM and fuses the shift / insert-select arithmetic into one pass per
(doc-block, op), without materializing the 2*max_ins+1 rolled copies the
XLA formulation selects among.

Kernels run natively on TPU; tests exercise them with `interpret=True` on
the CPU mesh (pallas_guide.md debugging convention).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces only exist on TPU-enabled builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _gather_lanes(tab, idx):
    """take_along_axis(tab, idx, axis=1) in the one gather form Mosaic
    lowers (`tpu.dynamic_gather`): same-shape [b, n] operand/indices/out
    with operand_batching_dims=(0,). jnp.take_along_axis itself emits
    offset_dims=(0,) when b == 1 (a size-1 batch dim is folded into the
    slice), which Mosaic rejects — so build the batched form explicitly.
    Indices must already be in [0, n)."""
    return jax.lax.gather(
        tab, idx[..., None],
        dimension_numbers=jax.lax.GatherDimensionNumbers(
            offset_dims=(), collapsed_slice_dims=(1,), start_index_map=(1,),
            operand_batching_dims=(0,), start_indices_batching_dims=(0,)),
        slice_sizes=(1, 1),
        mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS)


def _apply_op_kernel(pos_ref, dlen_ref, ilen_ref, chars_ref, doc_ref,
                     len_ref, out_doc_ref, out_len_ref):
    """One op applied to a [block, cap] slab of documents (all in VMEM).

    out[i] = chars[i - pos]          for pos <= i < pos+ilen   (insert lane)
           = doc[i]                  for i < pos
           = doc[i - ilen + dlen]    for i >= pos+ilen         (tail shift)

    Mosaic's gather (`tpu.dynamic_gather`) only lowers take_along_axis
    when operand, indices and output shapes all match, so `chars` arrives
    pre-padded to [b, cap] by the wrapper and every gather here is
    same-shape [b, cap].
    """
    doc = doc_ref[...]                      # [b, cap] int32
    pos = pos_ref[...][:, None]             # [b, 1]
    dlen = dlen_ref[...][:, None]
    ilen = ilen_ref[...][:, None]
    chars = chars_ref[...]                  # [b, cap] (zero-padded tail)
    cap = doc.shape[1]
    idx = jax.lax.broadcasted_iota(jnp.int32, doc.shape, 1)

    shift = ilen - dlen
    src = jnp.where(idx < pos, idx, idx - shift)
    gathered = _gather_lanes(doc, jnp.clip(src, 0, cap - 1))
    ins_idx = jnp.clip(idx - pos, 0, cap - 1)
    ins_vals = _gather_lanes(chars, ins_idx)
    in_insert = (idx >= pos) & (idx < pos + ilen)
    new_doc = jnp.where(in_insert, ins_vals, gathered)

    noop = (ilen == 0) & (dlen == 0)
    out_doc_ref[...] = jnp.where(noop, doc, new_doc)
    out_len_ref[...] = len_ref[...] + jnp.where(noop, 0, ilen - dlen)


def apply_op_block(pos, dlen, ilen, chars, doc, doc_len, *,
                   interpret: bool = False):
    """Apply one positional op per document to a [b, cap] batch (Pallas)."""
    b, cap = doc.shape
    if chars.shape[1] < cap:      # same-shape gather table (see kernel doc)
        chars = jnp.pad(chars, ((0, 0), (0, cap - chars.shape[1])))
    kwargs = {}
    if not interpret and _VMEM is not None:
        spec = pl.BlockSpec(memory_space=_VMEM)
        kwargs = {"in_specs": [spec] * 6, "out_specs": (spec, spec)}
    doc_out, len2d = pl.pallas_call(
        _apply_op_kernel,
        out_shape=(jax.ShapeDtypeStruct((b, cap), jnp.int32),
                   jax.ShapeDtypeStruct((b, 1), jnp.int32)),
        interpret=interpret,
        **kwargs,
    )(pos, dlen, ilen, chars, doc, doc_len[:, None])
    return doc_out, len2d[:, 0]


# ---------------------------------------------------------------------------
# materialize: run-expansion as a Pallas kernel (VERDICT r2 next-step #5)
# ---------------------------------------------------------------------------


def _materialize_kernel(starts_ref, ends_ref, base_ref, arena_ref,
                        out_ref, *, n_pow: int, tiles: int):
    """Expand visible runs into text for one [block] of output positions.

    Gather-only formulation (TPU Pallas has fast gathers, no fast
    scatter): each output position j binary-searches the compacted live
    runs' start table (log2(block) vectorized steps), then reads its char
    through the run's affine base. Replaces materialize_jax's
    scatter+cummax run expansion for the device merge path.

    Mosaic's gather only lowers same-shape take_along_axis, so the run
    tables arrive padded to [1, block] and the arena lookup walks
    `tiles` static [1, block] slices of the arena, selecting the tile
    that covers each position's source index.
    """
    block = out_ref.shape[1]
    j = jax.lax.broadcasted_iota(jnp.int32, (1, block), 1) + \
        pl.program_id(0) * block
    starts = starts_ref[...]               # [1, block] (+inf pad, sorted)
    ends = ends_ref[...]                   # [1, block] run end positions
    base = base_ref[...]                   # [1, block]

    # binary search: largest r with starts[r] <= j  (same-shape gathers)
    lo = jnp.zeros_like(j)
    step = jnp.full_like(j, 1 << (n_pow - 1))
    for _ in range(n_pow):
        probe = lo + step
        pv = _gather_lanes(starts, jnp.clip(probe, 0, block - 1))
        lo = jnp.where((probe < block) & (pv <= j), probe, lo)
        step = step // 2
    b = _gather_lanes(base, lo)
    src = b + j                            # arena index per position
    # in-range ⟺ j lands inside its run's [start, end): beyond-total
    # positions bind to the last live run and fail j < end (no SMEM
    # scalar needed — a scalar block spec does not survive vmap)
    valid = j < _gather_lanes(ends, lo)
    text = jnp.zeros_like(j)
    for t in range(tiles):                 # tiled same-shape arena gather
        tile = arena_ref[:, t * block:(t + 1) * block]
        local = src - t * block
        hit = (local >= 0) & (local < block)
        g = _gather_lanes(tile, jnp.clip(local, 0, block - 1))
        text = jnp.where(hit, g, text)
    out_ref[...] = jnp.where(valid, text, 0)


def materialize_pallas(perm, vis_len, arena_off, arena, cap: int,
                       interpret: bool = False):
    """Drop-in for linearize.materialize_jax with the run expansion in a
    Pallas kernel. The XLA pre-pass compacts live runs (sorted starts +
    affine bases — one cumsum and one scatter over [n]); the [cap]-wide
    expansion (the hot part) runs in VMEM. Falls back to materialize_jax
    when the run table cannot fit one output block (the same-shape gather
    bound; >64Ki live runs)."""
    if not interpret and jax.default_backend() != "tpu":
        interpret = True   # CPU/GPU backends run the kernel interpreted
    n = perm.shape[0]

    # Lane-aligned block: multiple of 128, covers the run table.
    block = max(128, min(_next_pow2(max(cap, 1)), 64 * 1024))
    n_pad = max(1, _next_pow2(n))
    if n_pad > block:
        from .linearize import materialize_jax
        return materialize_jax(perm, vis_len, arena_off, arena, cap)

    vl = vis_len[perm]
    cum = jnp.cumsum(vl)
    total = (cum[-1] if n else jnp.int32(0)).astype(jnp.int32)
    starts = cum - vl
    base = arena_off[perm] - starts
    live = vl > 0
    # compact live runs to a sorted prefix; pad tail with +inf starts
    k = jnp.cumsum(live.astype(jnp.int32)) - 1
    INF = jnp.int32(2 ** 30)
    starts_c = jnp.full((block,), INF, jnp.int32).at[
        jnp.where(live, k, block - 1)].set(
        jnp.where(live, starts, INF).astype(jnp.int32), mode="drop")
    ends_c = jnp.zeros((block,), jnp.int32).at[
        jnp.where(live, k, block - 1)].set(
        jnp.where(live, cum, 0).astype(jnp.int32), mode="drop")
    base_c = jnp.zeros((block,), jnp.int32).at[
        jnp.where(live, k, block - 1)].set(
        jnp.where(live, base, 0).astype(jnp.int32), mode="drop")
    arena_i = arena.astype(jnp.int32)
    A = arena_i.shape[0]
    tiles = max(1, (A + block - 1) // block)
    A_pad = tiles * block
    if A_pad > A:
        arena_i = jnp.pad(arena_i, (0, A_pad - A))

    grid = (cap + block - 1) // block
    if not interpret and _VMEM is not None:
        table_spec = pl.BlockSpec((1, block), lambda i: (0, 0),
                                  memory_space=_VMEM)
        arena_spec = pl.BlockSpec((1, A_pad), lambda i: (0, 0),
                                  memory_space=_VMEM)
        out_spec = pl.BlockSpec((1, block), lambda i: (0, i),
                                memory_space=_VMEM)
    else:
        table_spec = pl.BlockSpec((1, block), lambda i: (0, 0))
        arena_spec = pl.BlockSpec((1, A_pad), lambda i: (0, 0))
        out_spec = pl.BlockSpec((1, block), lambda i: (0, i))
    out = pl.pallas_call(
        functools.partial(_materialize_kernel,
                          n_pow=max(1, (block - 1).bit_length()),
                          tiles=tiles),
        grid=(grid,),
        in_specs=[table_spec, table_spec, table_spec, arena_spec],
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((1, grid * block), jnp.int32),
        interpret=interpret,
    )(starts_c[None, :], ends_c[None, :], base_c[None, :],
      arena_i[None, :])
    return out[0, :cap], total


def _next_pow2(x: int) -> int:
    return 1 << max(1, int(x) - 1).bit_length()


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def replay_batch_pallas(pos, dlen, ilen, chars, cap: int,
                        interpret: bool = False):
    """Full batched replay with the Pallas step kernel inside lax.scan
    (drop-in for tpu.batch.replay_batch)."""
    b = pos.shape[0]
    docs0 = jnp.zeros((b, cap), dtype=jnp.int32)
    lens0 = jnp.zeros((b,), dtype=jnp.int32)

    def step(carry, op):
        docs, lens = carry
        p, d, i, c = op
        docs, lens = apply_op_block(p, d, i, c, docs, lens,
                                    interpret=interpret)
        return (docs, lens), None

    ops = (jnp.swapaxes(pos, 0, 1), jnp.swapaxes(dlen, 0, 1),
           jnp.swapaxes(ilen, 0, 1), jnp.swapaxes(chars, 0, 1))
    (docs, lens), _ = jax.lax.scan(step, (docs0, lens0), ops)
    return docs, lens
