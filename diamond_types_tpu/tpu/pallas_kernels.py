"""Pallas TPU kernels for the merge/replay hot loops.

Design constraint learned on real hardware (2026-07-31, first live
tunnel window in three rounds): this backend's Mosaic compiler rejects
`tpu.dynamic_gather` whose gather dimension spans more than one vector
register ("Not implemented: Multiple source vregs along gather
dimension"), so per-lane table lookups are limited to ~128 lanes — far
below any real document or run table. Gather-formulated kernels lower
fine locally (`.lower(lowering_platforms=('tpu',))` passes) and only
fail at the server-side Mosaic compile, which is why the first,
gather-based revision of this module survived CI for three rounds while
dying on every on-chip attempt.

Both kernels here are therefore gather-free:

* `materialize_pallas` exploits that a merge-ordered run's source text
  is CONTIGUOUS in the arena (affine, slope 1): the kernel walks runs as
  a Pallas grid and block-copies each run's chars with dynamic-offset
  vector loads/stores + masked read-modify-write at the edges — pure
  DMA-shaped work, which is what the hardware is good at.
* `apply_op_block` routes each document row's tail shift and insert lane
  through `pltpu.roll` (scalar-controlled lane rotation, natively
  supported) under a row-per-grid-step layout, replacing the per-lane
  gathers of the XLA formulation in tpu/batch.py.

Tests exercise the kernels with `interpret=True` on the CPU mesh
(pallas_guide.md debugging convention) AND assert TPU lowering offline;
the on-chip compile is covered by the device bench.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces only exist on TPU-enabled builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
    _SMEM = pltpu.SMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None
    _SMEM = None


def _roll_lanes(x, shift):
    """jnp.roll(x, shift, axis=1) with a traced shift, in the form Mosaic
    lowers natively (pltpu.roll -> tpu.dynamic_rotate). Falls back to
    jnp.roll under interpret mode / non-TPU pallas."""
    if pltpu is not None and hasattr(pltpu, "roll"):
        return pltpu.roll(x, shift, 1)
    return jnp.roll(x, shift, axis=1)  # pragma: no cover


_ROWS = 8           # VMEM sublane granularity: rows are processed in 8s


def _apply_op_rows_kernel(pos_ref, dlen_ref, ilen_ref, chars_ref, doc_ref,
                          out_doc_ref):
    """One op applied to an [8, cap] row group (grid = row groups).

    out[i] = chars[i - pos]          for pos <= i < pos+ilen   (insert lane)
           = doc[i]                  for i < pos
           = doc[i - ilen + dlen]    for i >= pos+ilen         (tail shift)

    The tail shift and the insert lane are lane rotations by per-row
    SCALARS (from SMEM), so no per-lane gather is needed (Mosaic's
    dynamic_gather cannot span vregs — module doc); rotation wrap-around
    lanes are dead by the same masks the gather formulation clipped
    with. Rows ride in sublane groups of 8 (a single-row VMEM block is
    not a legal Pallas TPU block shape); each row's rotation amount
    differs, so rows are unrolled statically inside the group.
    """
    g = pl.program_id(0)
    cap = doc_ref.shape[1]
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, cap), 1)
    for r in range(_ROWS):      # static unroll within the sublane group
        row = g * _ROWS + r
        pos = pos_ref[0, row]
        dlen = dlen_ref[0, row]
        ilen = ilen_ref[0, row]
        doc = doc_ref[r:r + 1, :]           # [1, cap] static row slice
        chars = chars_ref[r:r + 1, :]       # [1, cap] (zero-padded tail)

        shift = ilen - dlen
        shifted = _roll_lanes(doc, shift)   # doc[i - shift]
        gathered = jnp.where(idx < pos, doc, shifted)
        ins_vals = _roll_lanes(chars, pos)  # chars[i - pos]
        in_insert = (idx >= pos) & (idx < pos + ilen)
        new_doc = jnp.where(in_insert, ins_vals, gathered)

        noop = (ilen == 0) & (dlen == 0)
        out_doc_ref[r:r + 1, :] = jnp.where(noop, doc, new_doc)


def apply_op_block(pos, dlen, ilen, chars, doc, doc_len, *,
                   interpret: bool = False):
    """Apply one positional op per document to a [b, cap] batch (Pallas).

    Returns (new_docs [b, cap], new_lens [b]). Lengths are pure
    elementwise arithmetic and stay outside the kernel."""
    b, cap = doc.shape
    if chars.shape[1] < cap:      # rotation source plane, full width
        chars = jnp.pad(chars, ((0, 0), (0, cap - chars.shape[1])))
    bp = _round_up(b, _ROWS)
    if bp > b:
        pad = ((0, bp - b), (0, 0))
        doc_p = jnp.pad(doc, pad)
        chars_p = jnp.pad(chars, pad)
        scal_pad = (0, bp - b)
        pos_p = jnp.pad(pos, scal_pad)
        dlen_p = jnp.pad(dlen, scal_pad)
        ilen_p = jnp.pad(ilen, scal_pad)
    else:
        doc_p, chars_p, pos_p, dlen_p, ilen_p = doc, chars, pos, dlen, ilen
    rows = pl.BlockSpec((_ROWS, cap), lambda g: (g, 0))
    scal = pl.BlockSpec((1, bp), lambda g: (0, 0))
    if not interpret and _SMEM is not None:
        rows = pl.BlockSpec((_ROWS, cap), lambda g: (g, 0),
                            memory_space=_VMEM)
        scal = pl.BlockSpec((1, bp), lambda g: (0, 0), memory_space=_SMEM)
    out = pl.pallas_call(
        _apply_op_rows_kernel,
        grid=(bp // _ROWS,),
        in_specs=[scal, scal, scal, rows, rows],
        out_specs=rows,
        out_shape=jax.ShapeDtypeStruct((bp, cap), jnp.int32),
        interpret=interpret,
    )(pos_p[None, :], dlen_p[None, :], ilen_p[None, :], chars_p, doc_p)
    noop = (ilen == 0) & (dlen == 0)
    return out[:b], doc_len + jnp.where(noop, 0, ilen - dlen)


# ---------------------------------------------------------------------------
# materialize: run expansion as contiguous block copies (VERDICT r2 #5)
# ---------------------------------------------------------------------------

_CB = 512           # copy-chunk lanes (4 int32 vregs)


def _materialize_runs_kernel(starts_ref, lens_ref, abase_ref, arena_ref,
                             out_ref, *, cb: int, cap: int):
    """Copy one run's visible chars into the output (grid = runs).

    Every run's source is a contiguous arena span, so the expansion is
    chunked vector copies with a masked read-modify-write (grid steps
    are sequential on TPU, so the window RMW is race-free). Runs
    at/after `cap` are clipped; chunk-tail junk past `cap` lands in the
    output slack and is sliced off by the wrapper.

    Alignment (on-chip Mosaic evidence, 2026-07-31): a dynamic
    lane-dimension `pl.ds` offset must be statically provable as a
    multiple of 128 ("cannot statically prove that index in dimension 1
    is a multiple of 128") — arbitrary `a + off` offsets are rejected.
    All loads/stores therefore use 128-aligned windows (`(idx//128)*128`
    carries the proof) one vreg wider than the copy chunk, with the
    sub-tile offsets folded into a single lane rotation of the source
    window: placed[i] = win[i - (dst%128) + (src%128)]."""
    i = pl.program_id(0)
    w = cb + 128        # aligned window lanes

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    s = starts_ref[0, i]
    n = lens_ref[0, i]
    a = abase_ref[0, i]
    wlane = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)

    n_eff = jnp.minimum(n, jnp.maximum(cap - s, 0))   # clip at cap
    n_chunks = (n_eff + cb - 1) // cb

    def body(k, _):
        off = k * cb
        src_idx = a + off
        dst_idx = s + off
        ra = jax.lax.rem(src_idx, 128)
        rd = jax.lax.rem(dst_idx, 128)
        # aligned bases written as q*128 — the literal multiply is the
        # form Mosaic's affine analysis accepts as provably aligned
        qa128 = jax.lax.div(src_idx, 128) * 128
        qd128 = jax.lax.div(dst_idx, 128) * 128
        win = arena_ref[:, pl.ds(qa128, w)]
        old = out_ref[:, pl.ds(qd128, w)]
        placed = _roll_lanes(win, jnp.mod(rd - ra, w))
        j = wlane - rd                # window lane → chunk lane
        mask = (j >= 0) & (j < cb) & ((j + off) < n)
        out_ref[:, pl.ds(qd128, w)] = jnp.where(mask, placed, old)
        return 0

    jax.lax.fori_loop(0, n_chunks, body, 0)


def _round_up(x: int, m: int) -> int:
    return -(-int(x) // m) * m


import os as _os

# Run tables live in SMEM (per-grid-step scalars); bound their size to
# stay inside scalar memory. 8192 runs = 96 KiB of tables — deliberately
# conservative until an on-chip compile probes the real ceiling
# (friendsforever: 3.3k runs fits; git-makefile: 21.5k needs the raise).
_SMEM_RUNS_DEFAULT = 8192


def materialize_pallas(perm, vis_len, arena_off, arena, cap: int,
                       interpret: bool = False):
    """Drop-in for linearize.materialize_jax with the run expansion in a
    Pallas kernel: gather-free contiguous run copies (see module doc).
    Returns (text [cap] int32, total_len).

    Dead (vis_len == 0) runs cost one near-empty sequential grid step
    each — a static Pallas grid cannot contract to the dynamic live
    count, so compaction would only reorder, not reduce, the steps.

    Run tables beyond DT_PALLAS_SMEM_RUNS fall back to materialize_jax
    (SMEM is scalar memory and small); DT_TPU_PALLAS_STRICT=1 turns the
    fallback into an error so a Pallas BENCH can never silently report
    XLA numbers as kernel numbers."""
    if not interpret and jax.default_backend() != "tpu":
        interpret = True   # CPU/GPU backends run the kernel interpreted
    n = perm.shape[0]
    smem_max = int(_os.environ.get("DT_PALLAS_SMEM_RUNS",
                                   _SMEM_RUNS_DEFAULT))
    if not interpret and n > smem_max:
        if _os.environ.get("DT_TPU_PALLAS_STRICT"):
            raise ValueError(
                f"materialize_pallas: {n} runs exceeds the SMEM table "
                f"bound ({smem_max}); refusing the XLA fallback under "
                "DT_TPU_PALLAS_STRICT (raise DT_PALLAS_SMEM_RUNS if the "
                "chip's SMEM allows it)")
        from .linearize import materialize_jax
        return materialize_jax(perm, vis_len, arena_off, arena, cap)
    vl = vis_len[perm].astype(jnp.int32)
    cum = jnp.cumsum(vl)
    total = (cum[-1] if n else jnp.int32(0)).astype(jnp.int32)
    if n == 0:
        return jnp.zeros((cap,), jnp.int32), total
    starts = (cum - vl).astype(jnp.int32)
    abase = arena_off[perm].astype(jnp.int32)

    arena_i = arena.astype(jnp.int32)
    # window slack: aligned-window copies reach one vreg past the chunk
    A_pad = _round_up(arena_i.shape[0] + _CB + 128, 128)
    arena_i = jnp.pad(arena_i, (0, A_pad - arena_i.shape[0]))
    OUTD = _round_up(cap + _CB + 128, 128)

    tab = pl.BlockSpec((1, n), lambda i: (0, 0))
    arena_spec = pl.BlockSpec((1, A_pad), lambda i: (0, 0))
    out_spec = pl.BlockSpec((1, OUTD), lambda i: (0, 0))
    if not interpret and _SMEM is not None:
        tab = pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=_SMEM)
        arena_spec = pl.BlockSpec((1, A_pad), lambda i: (0, 0),
                                  memory_space=_VMEM)
        out_spec = pl.BlockSpec((1, OUTD), lambda i: (0, 0),
                                memory_space=_VMEM)
    out = pl.pallas_call(
        functools.partial(_materialize_runs_kernel, cb=_CB, cap=cap),
        grid=(n,),
        in_specs=[tab, tab, tab, arena_spec],
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((1, OUTD), jnp.int32),
        interpret=interpret,
    )(starts[None, :], vl[None, :], abase[None, :], arena_i[None, :])
    return out[0, :cap], total


# ---------------------------------------------------------------------------
# transform position resolution: prefix scans with a carried chunk state
# ---------------------------------------------------------------------------

_XCB = 512          # scan-chunk lanes (4 int32 vregs)


def _xform_pos_kernel(nv_ref, ov_ref, pos_ref, stats_ref, *, cb: int):
    """One chunk of the transform's position-resolution scan (grid =
    chunks, sequential on TPU so the stats row carries across steps).

    Given DOC-ORDERED visible-length columns (nv = chars after the
    merge, ov = chars at the session frontier), each run's edit position
    is the exclusive prefix sum of nv, the projected length is Σnv, and
    the replay's peak length offset is the running max of Σ(nv-ov).

    Gather-free by construction (the Mosaic ≤128-lane gather limit —
    module doc): the caller applies the device-computed Fugue order
    BEFORE this kernel, so everything here is chunked cumsums + a
    carried scalar row — no per-lane table lookups at all.

    stats row: [0] chars emitted so far, [1] running Σ(nv-ov),
    [2] running peak of Σ(nv-ov)."""
    k = pl.program_id(0)

    @pl.when(k == 0)
    def _init():
        stats_ref[...] = jnp.zeros_like(stats_ref)

    base = stats_ref[0, 0]
    cdelta = stats_ref[0, 1]
    peak = stats_ref[0, 2]
    nv = nv_ref[...]                    # [1, cb]
    ov = ov_ref[...]
    c = jnp.cumsum(nv, axis=1)
    pos_ref[...] = base + c - nv
    d = jnp.cumsum(nv - ov, axis=1)
    stats_ref[0, 0] = base + c[0, cb - 1]
    stats_ref[0, 1] = cdelta + d[0, cb - 1]
    stats_ref[0, 2] = jnp.maximum(peak, cdelta + jnp.max(d))


def xform_positions_pallas(nv, ov, *, interpret: bool = False):
    """Gather-free Pallas run of the transform position-resolution hot
    loop (drop-in for the jnp scans in tpu/xform._xform_single; inputs
    are the doc-order-permuted visibility columns). Returns
    (pos [n] int32, new_len, peak_delta >= 0)."""
    if not interpret and jax.default_backend() != "tpu":
        interpret = True   # CPU/GPU backends run the kernel interpreted
    n = nv.shape[0]
    cb = min(_XCB, _round_up(max(n, 1), 128))
    npad = _round_up(max(n, 1), cb)
    nv_p = jnp.zeros((1, npad), jnp.int32).at[0, :n].set(
        nv.astype(jnp.int32))
    ov_p = jnp.zeros((1, npad), jnp.int32).at[0, :n].set(
        ov.astype(jnp.int32))
    tab = pl.BlockSpec((1, cb), lambda k: (0, k))
    stat = pl.BlockSpec((1, 4), lambda k: (0, 0))
    if not interpret and _SMEM is not None:
        tab = pl.BlockSpec((1, cb), lambda k: (0, k), memory_space=_VMEM)
        stat = pl.BlockSpec((1, 4), lambda k: (0, 0), memory_space=_SMEM)
    pos, stats = pl.pallas_call(
        functools.partial(_xform_pos_kernel, cb=cb),
        grid=(npad // cb,),
        in_specs=[tab, tab],
        out_specs=[tab, stat],
        out_shape=[jax.ShapeDtypeStruct((1, npad), jnp.int32),
                   jax.ShapeDtypeStruct((1, 4), jnp.int32)],
        interpret=interpret,
    )(nv_p, ov_p)
    return (pos[0, :n], stats[0, 0],
            jnp.maximum(stats[0, 2], jnp.int32(0)))


def _next_pow2(x: int) -> int:
    return 1 << max(1, int(x) - 1).bit_length()


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def replay_batch_pallas(pos, dlen, ilen, chars, cap: int,
                        interpret: bool = False):
    """Full batched replay with the Pallas step kernel inside lax.scan
    (drop-in for tpu.batch.replay_batch)."""
    b = pos.shape[0]
    docs0 = jnp.zeros((b, cap), dtype=jnp.int32)
    lens0 = jnp.zeros((b,), dtype=jnp.int32)

    def step(carry, op):
        docs, lens = carry
        p, d, i, c = op
        docs, lens = apply_op_block(p, d, i, c, docs, lens,
                                    interpret=interpret)
        return (docs, lens), None

    ops = (jnp.swapaxes(pos, 0, 1), jnp.swapaxes(dlen, 0, 1),
           jnp.swapaxes(ilen, 0, 1), jnp.swapaxes(chars, 0, 1))
    (docs, lens), _ = jax.lax.scan(step, (docs0, lens0), ops)
    return docs, lens
