"""Device zone execution — origin extraction ON the accelerator.

Lowers listmerge/zone_np.py's per-entry merge algorithm to ONE `lax.scan`
over a packed step tape. This is the round-3 flagship (VERDICT r2 missing
#1): the host's only jobs are plan compilation (plan2), entry composition
(compose.py — a piece-table pass over the op table) and text-pool
assembly; the device resolves every origin, places every concurrent
block with the YjsMod integrate rule, evolves the per-index state matrix,
and assembles the final document order. No M1/tracker transform runs
anywhere in this path (reference being replaced: the per-op origin scan +
integrate of src/listmerge/merge.rs:154-423).

Tape steps (all shapes static; scan body compiled once per size bucket):
  OP_BEGIN row        state[row] <- base visibility (prefix chars)
  OP_FORK  src dst    state[dst] <- state[src]
  OP_MAX   dst src    state[dst] <- max(state[dst], state[src])
  OP_APPLY row        one SUB-STEP of an entry: up to MB blocks, MC chars,
                      MD delete atoms. The first sub-step of each entry
                      snapshots the row (resolution must not see the
                      entry's own writes; compose coords are entry-start).

Per APPLY sub-step, fully vectorized over the W char slots:
  * visibility prefix-sum over the current order (one cumsum)
  * per block: cursor coord -> (a = rank of origin-left, b = rank of
    origin-right = first non-NotInsertedYet after a)
  * per block: the rank-space YjsMod integrate (top-row break / bottom-row
    skip / same-gap right-origin comparison with the scanning-rollback
    rule, merge.rs:154-278) as masked reductions — no data-dependent
    control flow
  * combined rank bump + order rescatter + state/metadata writes

Blocks larger than MC chars continue in later sub-steps as CONTINUATION
blocks (cursor == -2): their target is directly after the previous
chunk's last char, and their origin-right re-resolves to the same B (the
first snapshot-non-NIY after the gap — own chars are NIY in the snapshot).
"""

from __future__ import annotations

import os

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..listmerge.compose import K_OWN
from ..listmerge.plan2 import APPLY, BEGIN, DROP, FORK, MAX
from ..listmerge.zone_np import ZonePrep, _slot_of, prepare_zone
from .merge_kernel import _pow2

OP_BEGIN, OP_FORK, OP_MAX, OP_APPLY = 0, 1, 2, 3

BIG32 = np.int32(1 << 30)


@dataclass
class ZoneTape:
    """Packed device tape + host-prepared pools for one document."""
    # per step
    op: np.ndarray         # [T] i32
    arg_a: np.ndarray      # [T] i32 (row / src)
    arg_b: np.ndarray      # [T] i32 (dst)
    snap_flag: np.ndarray  # [T] i32 1 = copy row -> snapshot first
    # per step x block
    blk_cursor: np.ndarray  # [T,MB] i32 coord; -1 pad; -2 continuation
    blk_prev: np.ndarray    # [T,MB] i32 continuation: append after slot
    blk_root: np.ndarray    # [T,MB] i32 root char slot (keys)
    blk_start: np.ndarray   # [T,MB] i32 first char index in this step
    blk_len: np.ndarray     # [T,MB] i32 char count (0 pad)
    # per step x char
    ch_slot: np.ndarray     # [T,MC] i32 (-1 pad)
    ch_ol_static: np.ndarray   # [T,MC] i32 slot; -1 doc start; -2 coord
    ch_ol_coord: np.ndarray    # [T,MC] i32 entry-start coord
    ch_orr_own: np.ndarray     # [T,MC] i32 slot or -1 (block B)
    ch_blk: np.ndarray         # [T,MC] i32 block index in step
    ch_agent: np.ndarray       # [T,MC] i32 agent name rank
    ch_seq: np.ndarray         # [T,MC] i32 agent-local seq
    # per step x delete atom
    del_kind: np.ndarray    # [T,MD] i32 -1 pad / 0 coords / 1 slot range
    del_a: np.ndarray       # [T,MD] i32
    del_b: np.ndarray       # [T,MD] i32
    # doc-level
    W: int
    plen: int
    n_idx: int
    pool: np.ndarray        # [W] i32 char codes by slot
    total_steps: int


def _origin_encoding(ch_kind, slots, anchor, c_of):
    """The per-char origin-left encoding — the ONE statement of the rule
    shared by the per-entry and whole-corpus batched column builders:
    interior chars chain to their predecessor slot, K_OWN heads anchor on
    an own slot, query heads (K_LEFTJOIN / K_ROOT) carry a cursor coord
    (-1 = doc start, -2 = resolve the coord at runtime)."""
    is_q = ch_kind >= 2
    ol_static = np.where(
        ch_kind == 0, slots - 1,
        np.where(ch_kind == K_OWN, anchor,
                 np.where(c_of == 0, -1, -2)))
    ol_coord = np.where(is_q & (c_of > 0), c_of, 0)
    return ol_static, ol_coord


def entry_columns(ce, slot_fn, agent_k, seq_k):
    """Per-char tape columns for one composed entry: (slots, ol_static,
    ol_coord, orr_own, ag, sq, root_slots)."""
    slots = slot_fn(ce.ch_lv).astype(np.int64)
    anchor = np.where(ce.ch_anchor >= 0,
                      slot_fn(np.maximum(ce.ch_anchor, 0)), -1)
    orr_own = np.where(ce.ch_orrown >= 0,
                       slot_fn(np.maximum(ce.ch_orrown, 0)), -1)
    root_slots = slot_fn(ce.blk_root_lv)
    qc = np.asarray(ce.q_cursor, dtype=np.int64) \
        if ce.q_cursor else np.zeros(1, np.int64)
    c_of = qc[np.clip(ce.ch_q, 0, None)]
    ol_static, ol_coord = _origin_encoding(np.asarray(ce.ch_kind), slots,
                                           anchor, c_of)
    if callable(agent_k):   # one call yields both key planes
        ag, sq = agent_k(ce.ch_lv)
    else:
        ag = np.asarray(agent_k)[slots]
        sq = np.asarray(seq_k)[slots]
    return slots, ol_static, ol_coord, orr_own, ag, sq, root_slots


def entry_steps(ce, slot_fn, agent_k, seq_k, MB, MC, MD, cur, next_sub,
                cols=None):
    """Append one composed entry's APPLY sub-step contents (blocks, char
    slices, delete atoms) under the shared budgets. `slot_fn` maps insert
    LVs to char slots; `cur` is the current step dict; `next_sub()`
    returns a fresh sub-step. Shared by the whole-document packer below
    and the incremental session packer (zone_session.py). `cols` are
    precomputed entry_columns (the whole-document packer batches them
    across all entries — per-entry numpy-call overhead dominated the
    pack on many-entry corpora)."""
    nc = ce.num_chars()
    if nc:
        if cols is None:
            cols = entry_columns(ce, slot_fn, agent_k, seq_k)
        slots, ol_static, ol_coord, orr_own, ag, sq, root_slots = cols
    for b in range(len(ce.blk_start) if nc else 0):
        lo = int(ce.blk_start[b])
        hi = lo + int(ce.blk_len[b])
        first = True
        pos = lo
        while pos < hi:
            if len(cur["blocks"]) >= MB or cur["n_chars"] >= MC:
                cur = next_sub()
            take = min(hi - pos, MC - cur["n_chars"])
            assert take > 0
            cursor = int(ce.q_cursor[int(ce.blk_root_q[b])]) \
                if first else -2
            cur["blocks"].append((
                cursor, -1 if first else int(slots[pos - 1]),
                int(root_slots[b]), cur["n_chars"], take))
            cur["chars"].append((len(cur["blocks"]) - 1, pos, pos + take,
                                 slots, ol_static, ol_coord, orr_own,
                                 ag, sq))
            cur["n_chars"] += take
            pos += take
            first = False
    for (c0, c1) in ce.del_base:
        if len(cur["dels"]) >= MD:
            cur = next_sub()
        cur["dels"].append((0, int(c0), int(c1)))
    for (lv0, lv1) in ce.del_own:
        if len(cur["dels"]) >= MD:
            cur = next_sub()
        s0 = int(slot_fn(np.asarray([lv0]))[0])
        cur["dels"].append((1, s0, s0 + (lv1 - lv0)))


def _batched_columns(prep):
    """entry_columns for EVERY composed entry in a few whole-corpus numpy
    passes, returned as per-entry views. Equivalent to calling
    entry_columns per entry (pinned by test_zone_kernel's corpora parity)
    but ~an order of magnitude cheaper on many-entry plans."""
    ces = prep.get_composed()
    # Batching trades per-entry numpy-call overhead for whole-corpus
    # concatenation copies: a win on many-small-entry plans (git-style
    # DAGs), a loss on few-huge-entry plans (node_nodecc's 100 entries
    # of ~4k chars) where the copies dominate and the per-entry overhead
    # was negligible. 200 entries is comfortably past the crossover.
    if len(ces) < 200:
        return {}
    cat = np.concatenate
    ch_lv = cat([np.asarray(ce.ch_lv, dtype=np.int64) if ce.num_chars()
                 else np.zeros(0, np.int64) for ce in ces])
    if not len(ch_lv):
        return {}
    as_i64 = lambda a: np.asarray(a, dtype=np.int64)  # noqa: E731
    nchars = [ce.num_chars() for ce in ces]
    z = np.zeros(0, np.int64)
    ch_kind = cat([as_i64(ce.ch_kind) if n else z
                   for ce, n in zip(ces, nchars)])
    ch_anchor = cat([as_i64(ce.ch_anchor) if n else z
                     for ce, n in zip(ces, nchars)])
    ch_orrown = cat([as_i64(ce.ch_orrown) if n else z
                     for ce, n in zip(ces, nchars)])
    # entry-local query ids -> one flat query table via per-entry offsets
    q_lens = [len(ce.q_cursor) for ce in ces]
    q_off = np.cumsum([0] + q_lens[:-1])
    flat_q = cat([as_i64(ce.q_cursor) if q else z
                  for ce, q in zip(ces, q_lens)]) if sum(q_lens) \
        else np.zeros(1, np.int64)
    ch_q = cat([np.where(as_i64(ce.ch_q) >= 0, as_i64(ce.ch_q) + off, -1)
                if n else z
                for ce, n, off in zip(ces, nchars, q_off)])
    from ..listmerge.zone_np import _slot_of
    slots = _slot_of(prep, ch_lv).astype(np.int64)
    anchor = np.where(ch_anchor >= 0,
                      _slot_of(prep, np.maximum(ch_anchor, 0)), -1)
    orr_own = np.where(ch_orrown >= 0,
                       _slot_of(prep, np.maximum(ch_orrown, 0)), -1)
    c_of = flat_q[np.clip(ch_q, 0, None)]
    ol_static, ol_coord = _origin_encoding(ch_kind, slots, anchor, c_of)
    ag = np.asarray(prep.agent_k)[slots]
    sq = np.asarray(prep.seq_k)[slots]
    nb = [len(ce.blk_root_lv) if ce.num_chars() else 0 for ce in ces]
    root_slots = _slot_of(prep, cat(
        [as_i64(ce.blk_root_lv) if n else z for ce, n in zip(ces, nb)])) \
        if sum(nb) else z
    out = {}
    c0 = b0 = 0
    for i, (ce, n, bn) in enumerate(zip(ces, nchars, nb)):
        if n:
            sl = slice(c0, c0 + n)
            out[i] = (slots[sl], ol_static[sl], ol_coord[sl],
                      orr_own[sl], ag[sl], sq[sl],
                      root_slots[b0:b0 + bn])
        c0 += n
        b0 += bn
    return out


def _pack_native(prep: ZonePrep, MB: int, MC: int, MD: int):
    """The C++ tape packer (native/dt_core.cpp dt_zone_pack; VERDICT r4
    #6 — the pure-Python pack was ~280 ms of git-makefile zone prep).
    Array-identical to the Python packer below (pinned by
    tests/test_zone_kernel.py); None when the native library is absent."""
    ctx = prep.native_ctx
    if ctx is None:
        return None
    lib = ctx._lib
    if not hasattr(lib, "dt_zone_pack"):
        return None
    n = len(prep.plan.entries)

    acts = prep.plan.actions
    ak = np.zeros(len(acts), np.int64)
    aa = np.zeros(len(acts), np.int64)
    ab = np.zeros(len(acts), np.int64)
    for i, act in enumerate(acts):
        ak[i] = act[0]
        aa[i] = act[1]
        ab[i] = act[2] if len(act) > 2 else 0
    ins_lv0 = np.ascontiguousarray(prep.ins_lv0, dtype=np.int64)
    ins_cum = np.ascontiguousarray(prep.ins_cum, dtype=np.int64)
    agent_k = np.ascontiguousarray(prep.agent_k, dtype=np.int64)
    seq_k = np.ascontiguousarray(prep.seq_k, dtype=np.int64)

    # fast path: the composer's output is still cached on the ctx from
    # prepare_zone's compose_plan call — pack straight from it, no
    # column round-trip. -2 = cache stale/absent -> marshal below.
    if prep.compose_serial:
        d64 = np.zeros(1, np.int64)
        d32 = np.zeros(1, np.int32)
        du8 = np.zeros(1, np.uint8)
        T = lib.dt_zone_pack(
            ctx._ptr, len(acts), ak, aa, ab, n, d64, d64, d64, du8, d64,
            d32, d64, d32, d64, d32, d32, d64, d64, d64, d64,
            len(ins_lv0), ins_lv0, ins_cum, prep.plen, agent_k, seq_k,
            MB, MC, MD, prep.compose_serial)
        if T >= 0:
            return _pack_fetch(prep, lib, ctx, int(T), MB, MC, MD)
    ces = prep.get_composed()
    as_i64 = lambda a: np.ascontiguousarray(a, dtype=np.int64)  # noqa: E731
    counts = np.zeros(n * 5, dtype=np.int64)
    for k, ce in enumerate(ces):
        counts[k * 5 + 0] = len(ce.q_cursor)
        counts[k * 5 + 1] = ce.num_chars()
        counts[k * 5 + 2] = 0 if ce.blk_start is None else len(ce.blk_start)
        counts[k * 5 + 3] = len(ce.del_base)
        counts[k * 5 + 4] = len(ce.del_own)
    z64 = np.zeros(0, np.int64)
    z32 = np.zeros(0, np.int32)
    zu8 = np.zeros(0, np.uint8)

    def cat(parts, dtype):
        parts = [p for p in parts if len(p)]
        if not parts:
            return np.zeros(1, dtype)
        return np.ascontiguousarray(np.concatenate(parts), dtype=dtype)

    flat_q = cat([as_i64(ce.q_cursor) if ce.q_cursor else z64
                  for ce in ces], np.int64)
    nc = [ce.num_chars() for ce in ces]
    ch_lv = cat([as_i64(ce.ch_lv) if m else z64
                 for ce, m in zip(ces, nc)], np.int64)
    ch_kind = cat([np.asarray(ce.ch_kind, np.uint8) if m else zu8
                   for ce, m in zip(ces, nc)], np.uint8)
    ch_anchor = cat([as_i64(ce.ch_anchor) if m else z64
                     for ce, m in zip(ces, nc)], np.int64)
    ch_q = cat([np.asarray(ce.ch_q, np.int32) if m else z32
                for ce, m in zip(ces, nc)], np.int32)
    ch_orrown = cat([as_i64(ce.ch_orrown) if m else z64
                     for ce, m in zip(ces, nc)], np.int64)
    nb = [int(counts[k * 5 + 2]) for k in range(n)]
    blk_root_q = cat([np.asarray(ce.blk_root_q, np.int32) if m else z32
                      for ce, m in zip(ces, nb)], np.int32)
    blk_root_lv = cat([as_i64(ce.blk_root_lv) if m else z64
                       for ce, m in zip(ces, nb)], np.int64)
    blk_start = cat([np.asarray(ce.blk_start, np.int32) if m else z32
                     for ce, m in zip(ces, nb)], np.int32)
    blk_len = cat([np.asarray(ce.blk_len, np.int32) if m else z32
                   for ce, m in zip(ces, nb)], np.int32)
    db0 = cat([as_i64([a for a, _ in ce.del_base]) for ce in ces], np.int64)
    db1 = cat([as_i64([b for _, b in ce.del_base]) for ce in ces], np.int64)
    do0 = cat([as_i64([a for a, _ in ce.del_own]) for ce in ces], np.int64)
    do1 = cat([as_i64([b for _, b in ce.del_own]) for ce in ces], np.int64)

    T = lib.dt_zone_pack(
        ctx._ptr, len(acts), ak, aa, ab, n, counts, flat_q, ch_lv, ch_kind,
        ch_anchor, ch_q, ch_orrown, blk_root_q, blk_root_lv, blk_start,
        blk_len, db0, db1, do0, do1, len(ins_lv0), ins_lv0, ins_cum,
        prep.plen, agent_k, seq_k, MB, MC, MD, 0)
    if T < 0:
        return None
    return _pack_fetch(prep, lib, ctx, int(T), MB, MC, MD)


def _pack_fetch(prep, lib, ctx, T: int, MB: int, MC: int, MD: int):
    Tp = max(1, int(T))
    # np.empty everywhere: dt_zone_pack_fetch writes every cell, pads
    # included (pad-initializing the ~100 MB tape in numpy was a
    # measurable share of the whole pack)
    out = ZoneTape(
        op=np.empty(Tp, np.int32), arg_a=np.empty(Tp, np.int32),
        arg_b=np.empty(Tp, np.int32), snap_flag=np.empty(Tp, np.int32),
        blk_cursor=np.empty((Tp, MB), np.int32),
        blk_prev=np.empty((Tp, MB), np.int32),
        blk_root=np.empty((Tp, MB), np.int32),
        blk_start=np.empty((Tp, MB), np.int32),
        blk_len=np.empty((Tp, MB), np.int32),
        ch_slot=np.empty((Tp, MC), np.int32),
        ch_ol_static=np.empty((Tp, MC), np.int32),
        ch_ol_coord=np.empty((Tp, MC), np.int32),
        ch_orr_own=np.empty((Tp, MC), np.int32),
        ch_blk=np.empty((Tp, MC), np.int32),
        ch_agent=np.empty((Tp, MC), np.int32),
        ch_seq=np.empty((Tp, MC), np.int32),
        del_kind=np.empty((Tp, MD), np.int32),
        del_a=np.empty((Tp, MD), np.int32),
        del_b=np.empty((Tp, MD), np.int32),
        W=prep.W, plen=prep.plen,
        n_idx=max(1, prep.plan.indexes_used),
        pool=prep.pool.astype(np.int32), total_steps=int(T))
    lib.dt_zone_pack_fetch(
        ctx._ptr, out.op, out.arg_a, out.arg_b, out.snap_flag,
        out.blk_cursor, out.blk_prev, out.blk_root, out.blk_start,
        out.blk_len, out.ch_slot, out.ch_ol_static, out.ch_ol_coord,
        out.ch_orr_own, out.ch_blk, out.ch_agent, out.ch_seq,
        out.del_kind, out.del_a, out.del_b, MB, MC, MD)
    return out


def pack_zone_tape(prep: ZonePrep, max_blocks: int = 8,
                   max_chars: int = 512, max_dels: int = 16) -> ZoneTape:
    """Flatten a prepared zone (plan + composed entries) into the tape."""
    MB, MC, MD = max_blocks, max_chars, max_dels
    if not os.environ.get("DT_TPU_NO_NATIVE"):
        native = _pack_native(prep, MB, MC, MD)
        if native is not None:
            return native
    steps: List[dict] = []
    all_cols = _batched_columns(prep)

    def new_step(op, a=0, b=0, snap=0):
        s = dict(op=op, a=a, b=b, snap=snap,
                 blocks=[], chars=[], dels=[], n_chars=0)
        steps.append(s)
        return s

    composed = prep.get_composed()
    for act in prep.plan.actions:
        kind = act[0]
        if kind == BEGIN:
            new_step(OP_BEGIN, act[1])
        elif kind == FORK:
            new_step(OP_FORK, act[1], act[2])
        elif kind == MAX:
            new_step(OP_MAX, act[2], act[1])   # a=src, b=dst
        elif kind == DROP:
            continue
        elif kind == APPLY:
            ce = composed[act[1]]
            row = act[2]
            cur = new_step(OP_APPLY, row, snap=1)

            def next_sub():
                return new_step(OP_APPLY, row, snap=0)

            def slot_fn(lvs):
                return _slot_of(prep, lvs)

            entry_steps(ce, slot_fn, prep.agent_k, prep.seq_k,
                        MB, MC, MD, cur, next_sub,
                        cols=all_cols.get(act[1]))

    return _fill_tape(steps, prep.W, prep.plen,
                      max(1, prep.plan.indexes_used),
                      prep.pool.astype(np.int32), MB, MC, MD)


def _fill_tape(steps: List[dict], W: int, plen: int, n_idx: int,
               pool: np.ndarray, MB: int, MC: int, MD: int) -> ZoneTape:
    """Materialize packed micro-step dicts into tape arrays (shared by
    the whole-document packer above and zone_session's incremental
    packer)."""
    T = max(1, len(steps))
    out = ZoneTape(
        op=np.zeros(T, np.int32), arg_a=np.zeros(T, np.int32),
        arg_b=np.zeros(T, np.int32), snap_flag=np.zeros(T, np.int32),
        blk_cursor=np.full((T, MB), -1, np.int32),
        blk_prev=np.full((T, MB), -1, np.int32),
        blk_root=np.zeros((T, MB), np.int32),
        blk_start=np.zeros((T, MB), np.int32),
        blk_len=np.zeros((T, MB), np.int32),
        ch_slot=np.full((T, MC), -1, np.int32),
        ch_ol_static=np.full((T, MC), -1, np.int32),
        ch_ol_coord=np.zeros((T, MC), np.int32),
        ch_orr_own=np.full((T, MC), -1, np.int32),
        ch_blk=np.zeros((T, MC), np.int32),
        ch_agent=np.zeros((T, MC), np.int32),
        ch_seq=np.zeros((T, MC), np.int32),
        del_kind=np.full((T, MD), -1, np.int32),
        del_a=np.zeros((T, MD), np.int32),
        del_b=np.zeros((T, MD), np.int32),
        W=W, plen=plen, n_idx=n_idx,
        pool=pool, total_steps=len(steps))
    for t, s in enumerate(steps):
        out.op[t] = s["op"]
        out.arg_a[t] = s["a"]
        out.arg_b[t] = s["b"]
        out.snap_flag[t] = s["snap"]
        for i, (cursor, prev, root, start, length) in \
                enumerate(s["blocks"]):
            out.blk_cursor[t, i] = cursor
            out.blk_prev[t, i] = prev
            out.blk_root[t, i] = root
            out.blk_start[t, i] = start
            out.blk_len[t, i] = length
        w = 0
        for (blk_i, lo, hi, slots, ol_static, ol_coord, orr_own,
             ag, sq) in s["chars"]:
            n = hi - lo
            out.ch_slot[t, w:w + n] = slots[lo:hi]
            out.ch_ol_static[t, w:w + n] = ol_static[lo:hi]
            out.ch_ol_coord[t, w:w + n] = ol_coord[lo:hi]
            out.ch_orr_own[t, w:w + n] = orr_own[lo:hi]
            out.ch_blk[t, w:w + n] = blk_i
            out.ch_agent[t, w:w + n] = ag[lo:hi]
            out.ch_seq[t, w:w + n] = sq[lo:hi]
            w += n
        for i, (k, a, b) in enumerate(s["dels"]):
            out.del_kind[t, i] = k
            out.del_a[t, i] = a
            out.del_b[t, i] = b
    return out


# ---------------------------------------------------------------------------
# device execution
# ---------------------------------------------------------------------------


def make_zone_step(W: int, plen: int, n_idx: int, MB: int, MC: int,
                  MD: int):
    """Build the scan-step function over the zone carry. The carry is
    (state, snap, rank, ord, ol_id, orr_id, ever, m, agent_k, seq_k) —
    agent/seq key planes ride in the carry and are updated from the tape,
    so an incremental caller (zone_session.py) ships only per-char deltas
    per step instead of re-uploading whole key arrays."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    idx_w = jnp.arange(W, dtype=jnp.int32)
    base_row = (idx_w < plen).astype(jnp.uint8)

    def gather_i32(arr, ix, fill):
        return jnp.where(ix >= 0, arr[jnp.clip(ix, 0, W - 1)], fill)

    def apply_step(carry, x):
        (state, snap, rank, ordv, ol_id, orr_id, ever, m,
         agent_k, seq_k) = carry
        # key planes first: the chars placed THIS step are roots/anchors
        # whose keys the integrate scan reads
        ch_ok = x["ch_slot"] >= 0
        key_ix = jnp.where(ch_ok, x["ch_slot"], W)
        agent_k = agent_k.at[key_ix].set(x["ch_agent"], mode="drop")
        seq_k = seq_k.at[key_ix].set(x["ch_seq"], mode="drop")
        row = jnp.clip(x["a"], 0, n_idx - 1)
        st_row = lax.dynamic_index_in_dim(state, row, 0, keepdims=False)
        snap = jnp.where(x["snap"] == 1, st_row, snap)

        placed_r = idx_w < m                      # rank-space mask
        ch_at = ordv                              # [W]: char slot by rank
        s_r = jnp.where(placed_r, snap[jnp.clip(ch_at, 0, W - 1)], 0)
        vis_r = (s_r == 1) & placed_r
        cum = jnp.cumsum(vis_r.astype(jnp.int32))
        nonniy_r = (s_r != 0) & placed_r

        # ---- block anchor resolution (reference: merge.rs:395-423) ----
        def resolve_block(cursor, prev_slot):
            is_cont = cursor == -2
            j = jnp.searchsorted(cum, jnp.maximum(cursor, 1),
                                 side="left").astype(jnp.int32)
            a_from_coord = jnp.where(cursor <= 0, -1, j)
            a_rank = jnp.where(
                is_cont, gather_i32(rank, prev_slot, BIG32), a_from_coord)
            ol_char = jnp.where(
                is_cont | (cursor <= 0), -1,
                ch_at[jnp.clip(a_from_coord, 0, W - 1)])
            cand = jnp.where(nonniy_r & (idx_w > a_rank), idx_w, W)
            b0 = jnp.min(cand)
            orr_char = jnp.where(b0 < m, ch_at[jnp.clip(b0, 0, W - 1)], -1)
            b_rank = jnp.minimum(b0, m)
            return a_rank, ol_char, b_rank, orr_char

        a_b, ol_b, b_b, orr_b = jax.vmap(resolve_block)(
            x["blk_cursor"], x["blk_prev"])

        # ---- YjsMod integrate (reference: merge.rs:154-278) ----
        olw = gather_i32(ol_id, ch_at, -3)
        olr_w = jnp.where(olw == -1, -1, gather_i32(rank, olw, BIG32))
        orw = gather_i32(orr_id, ch_at, -3)
        orr_r_w = jnp.where(orw == -1, BIG32,
                            gather_i32(rank, orw, BIG32))
        agent_w = gather_i32(agent_k, ch_at, 0)
        seq_w = gather_i32(seq_k, ch_at, 0)

        def integrate(a_rank, ol_char, b_rank, orr_char, cursor, root):
            is_cont = cursor == -2
            in_win = (idx_w > a_rank) & (idx_w < b_rank) & placed_r
            agent_c = gather_i32(agent_k, root, 0)
            seq_c = gather_i32(seq_k, root, 0)
            b_eff = jnp.where(orr_char < 0, BIG32, b_rank)

            top_row = in_win & (olr_w < a_rank)
            eq = in_win & (olr_w == a_rank)
            same = eq & (orw == orr_char)
            ins_here = same & ((agent_c < agent_w) |
                               ((agent_c == agent_w) & (seq_c < seq_w)))
            brk = top_row | ins_here
            jstar = jnp.min(jnp.where(brk, idx_w, b_rank))
            before = idx_w < jstar
            set_ev = eq & ~same & (orr_r_w < b_eff) & before
            reset_ev = ((eq & ~same & (orr_r_w >= b_eff)) |
                        (same & ~ins_here)) & before
            last_reset = jnp.max(jnp.where(reset_ev, idx_w, -1))
            streak = jnp.min(jnp.where(set_ev & (idx_w > last_reset),
                                       idx_w, W))
            t = jnp.where(streak < W, streak, jstar)
            return jnp.where(is_cont, a_rank + 1, t)

        t_b = jax.vmap(integrate)(a_b, ol_b, b_b, orr_b,
                                  x["blk_cursor"], x["blk_root"])
        blk_valid = x["blk_len"] > 0
        t_b = jnp.where(blk_valid, t_b, BIG32)
        L_b = jnp.where(blk_valid, x["blk_len"], 0)

        # ---- delete resolution against the snapshot, in rank space ----
        def del_mask(kind, a, b):
            return vis_r & (cum > a) & (cum <= b) & (kind == 0)

        dmask_r = jnp.any(jax.vmap(del_mask)(
            x["del_kind"], x["del_a"], x["del_b"]), axis=0)

        # ---- rank bump + placement (disjoint windows commute) ----
        bump = jnp.sum(
            jnp.where((t_b[:, None] <= rank[None, :]), L_b[:, None], 0),
            axis=0).astype(jnp.int32)
        live = rank < BIG32
        rank = jnp.where(live, rank + bump, rank)
        off_b = jnp.sum(
            jnp.where(t_b[None, :] < t_b[:, None], L_b[None, :], 0),
            axis=1).astype(jnp.int32)
        start_b = t_b + off_b
        ch_valid = x["ch_slot"] >= 0
        intra = jnp.arange(MC, dtype=jnp.int32) - \
            x["blk_start"][x["ch_blk"]]
        new_rank_ch = start_b[x["ch_blk"]] + intra
        # scatter targets: pad chars aim out of bounds and are dropped
        slot_ix = jnp.where(ch_valid, x["ch_slot"], W)
        rank = rank.at[slot_ix].set(new_rank_ch, mode="drop")
        m = m + jnp.sum(ch_valid.astype(jnp.int32))
        live = rank < BIG32
        ordv = jnp.zeros(W, jnp.int32).at[
            jnp.where(live, rank, W)].set(idx_w, mode="drop")

        # ---- origin metadata for the new chars ----
        coordq = jnp.maximum(x["ch_ol_coord"], 1)
        jq = jnp.searchsorted(cum, coordq, side="left").astype(jnp.int32)
        ol_from_coord = jnp.where(
            x["ch_ol_coord"] <= 0, -1, ch_at[jnp.clip(jq, 0, W - 1)])
        ol_ch = jnp.where(x["ch_ol_static"] == -2, ol_from_coord,
                          x["ch_ol_static"])
        orr_ch = jnp.where(x["ch_orr_own"] >= 0, x["ch_orr_own"],
                           orr_b[x["ch_blk"]])
        ol_id = ol_id.at[slot_ix].set(ol_ch, mode="drop")
        orr_id = orr_id.at[slot_ix].set(orr_ch, mode="drop")

        # ---- state writes: inserts + deletes (monotone lattice) ----
        ins_w = jnp.zeros(W, jnp.uint8).at[slot_ix].set(
            jnp.ones(MC, jnp.uint8), mode="drop")
        del_slot_ix = jnp.where(dmask_r, ch_at, W)
        del_w = jnp.zeros(W, jnp.uint8).at[del_slot_ix].set(
            jnp.full(W, 2, jnp.uint8), mode="drop")

        def slot_del(kind, a, b):
            return (kind == 1) & (idx_w >= a) & (idx_w < b)

        own_del = jnp.any(jax.vmap(slot_del)(
            x["del_kind"], x["del_a"], x["del_b"]), axis=0)
        del_w = jnp.maximum(del_w,
                            jnp.where(own_del, 2, 0).astype(jnp.uint8))
        new_row = jnp.maximum(jnp.maximum(st_row, ins_w), del_w)
        state = lax.dynamic_update_index_in_dim(state, new_row, row, 0)
        ever = jnp.maximum(ever, (del_w >= 2).astype(jnp.uint8))
        return (state, snap, rank, ordv, ol_id, orr_id, ever, m,
                agent_k, seq_k), None

    def row_step(carry, x):
        state = carry[0]
        op = x["op"]
        src = lax.dynamic_index_in_dim(
            state, jnp.clip(x["a"], 0, n_idx - 1), 0, keepdims=False)
        dst = lax.dynamic_index_in_dim(
            state, jnp.clip(x["b"], 0, n_idx - 1), 0, keepdims=False)
        new = jnp.where(op == OP_BEGIN, base_row,
                        jnp.where(op == OP_FORK, src,
                                  jnp.maximum(dst, src)))
        target = jnp.where(op == OP_BEGIN, x["a"], x["b"])
        state = lax.dynamic_update_index_in_dim(
            state, new, jnp.clip(target, 0, n_idx - 1), 0)
        return (state,) + tuple(carry[1:]), None

    def step(carry, x):
        return lax.cond(x["op"] == OP_APPLY, apply_step, row_step,
                        carry, x)

    return step


def init_zone_carry(W: int, plen: int, n_idx: int, agent_k, seq_k):
    """Fresh carry for a zone execution (prefix chars pre-placed)."""
    import jax.numpy as jnp
    idx_w = jnp.arange(W, dtype=jnp.int32)
    return (jnp.zeros((n_idx, W), jnp.uint8),          # state matrix
            jnp.zeros(W, jnp.uint8),                   # entry snapshot
            jnp.where(idx_w < plen, idx_w, BIG32),     # rank
            idx_w,                                     # ord
            jnp.where(idx_w < plen, idx_w - 1, -2),    # ol_id
            jnp.full(W, -1, jnp.int32),                # orr_id
            jnp.zeros(W, jnp.uint8),                   # ever
            jnp.int32(plen),                           # m
            jnp.asarray(agent_k, jnp.int32),
            jnp.asarray(seq_k, jnp.int32))


def _run_zone(xs, agent_k, seq_k, W: int, plen: int, n_idx: int, MB: int,
              MC: int, MD: int):
    """Jitted whole-tape execution: one lax.scan, returns (rank, ever)."""
    from jax import lax

    step = make_zone_step(W, plen, n_idx, MB, MC, MD)
    carry = init_zone_carry(W, plen, n_idx, agent_k, seq_k)
    final, _ = lax.scan(step, carry, xs)
    return final[2], final[6]


_zone_jit_cache = {}


def execute_zone_jax(tape: ZoneTape, agent_k: np.ndarray,
                     seq_k: np.ndarray):
    """Run the tape; returns (rank, ever) as numpy [W] arrays."""
    import jax
    import jax.numpy as jnp

    W, plen, n_idx = tape.W, tape.plen, tape.n_idx
    T = tape.op.shape[0]
    MB, MC, MD = (tape.blk_cursor.shape[1], tape.ch_slot.shape[1],
                  tape.del_kind.shape[1])
    key = (W, plen, n_idx, _pow2(T), MB, MC, MD)
    fn = _zone_jit_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(_run_zone, W=W, plen=plen, n_idx=n_idx,
                             MB=MB, MC=MC, MD=MD))
        _zone_jit_cache[key] = fn

    xs = {k: jnp.asarray(v) for k, v in _pad_tape_xs(tape).items()}
    rank, ever = fn(xs, jnp.asarray(agent_k.astype(np.int32)),
                    jnp.asarray(seq_k.astype(np.int32)))
    return np.asarray(rank), np.asarray(ever)


_zone_batch_jit_cache = {}


def execute_zone_batch_jax(tape: ZoneTape, agent_k: np.ndarray,
                           seq_k: np.ndarray, batch: int,
                           replica_sharding=None, xs=None):
    """Batched replica execution: ONE shared tape, `batch` independent
    state evolutions (the many-docs-per-chip deployment shape — BASELINE
    config 4). seq keys are materialized per replica so every row is a
    real computation, not a broadcast the compiler can collapse.
    `replica_sharding` (a jax.sharding.NamedSharding over the replica
    axis) spreads the batch over a device mesh; jit partitions the whole
    evolution from the input placement.
    Returns (rank [B, W], ever [B, W]) as numpy arrays."""
    import jax
    import jax.numpy as jnp

    W, plen, n_idx = tape.W, tape.plen, tape.n_idx
    T = tape.op.shape[0]
    MB, MC, MD = (tape.blk_cursor.shape[1], tape.ch_slot.shape[1],
                  tape.del_kind.shape[1])
    key = (W, plen, n_idx, _pow2(T), MB, MC, MD, batch)
    fn = _zone_batch_jit_cache.get(key)
    if fn is None:
        inner = partial(_run_zone, W=W, plen=plen, n_idx=n_idx,
                        MB=MB, MC=MC, MD=MD)
        fn = jax.jit(jax.vmap(inner, in_axes=(None, None, 0)))
        _zone_batch_jit_cache[key] = fn
    if xs is None:
        xs = _pad_tape_xs(tape)
        xs = {k: jnp.asarray(v) for k, v in xs.items()}
    seq_b = jnp.asarray(
        np.broadcast_to(seq_k.astype(np.int32), (batch, W)).copy())
    if replica_sharding is not None:
        seq_b = jax.device_put(seq_b, replica_sharding)
    rank, ever = fn(xs, jnp.asarray(agent_k.astype(np.int32)), seq_b)
    return rank, ever   # DEVICE arrays: callers np.asarray (or slice) them


def _run_zone_slice(carry, xs, W: int, plen: int, n_idx: int, MB: int,
                    MC: int, MD: int):
    """One bounded-length scan segment: carry in, carry out."""
    from jax import lax

    step = make_zone_step(W, plen, n_idx, MB, MC, MD)
    final, _ = lax.scan(step, carry, xs)
    return final


def slice_tape_xs(tape: ZoneTape, slice_steps: int):
    """Cut the padded tape into device-resident scan segments of length
    `slice_steps` (pad steps are self-FORK no-ops, so over-padding the
    last segment is safe). Returns (S, [xs dicts on device])."""
    import jax.numpy as jnp

    if int(slice_steps) <= 0:
        raise ValueError(f"slice_steps must be positive, got {slice_steps}"
                         " (use the whole-tape executor to disable slicing)")
    T = tape.op.shape[0]
    S = min(int(slice_steps), _pow2(T))
    n_sl = max(1, -(-T // S))
    xs_np = _pad_tape_xs(tape, target=n_sl * S)
    return S, [{k: jnp.asarray(v[i * S:(i + 1) * S])
                for k, v in xs_np.items()} for i in range(n_sl)]


# Per-dispatch device-time budget for the sliced executor, in
# step-replica-width units (scan_steps x batch x W). Calibrated on the
# tunneled v5e runtime (2026-07-31): the runtime kills any single
# program past a ~60 s device-time bound ("TPU worker process crashed
# or restarted"); friendsforever at batch 8 (W 23,719) measured ~33M
# units/s, so 3.3e8 units ~= 10 s/dispatch — a 6x margin under the kill
# bound that also keeps liveness probes responsive between dispatches.
_SLICE_BUDGET_UNITS = 3.3e8


def auto_slice_steps(tape: "ZoneTape", batch: int) -> int:
    """Slice length that bounds one dispatch's device time on the
    tunneled runtime: scan steps per dispatch shrink as the replica
    batch or the zone width W grow (per-step cost is ~linear in both —
    every step does W-wide vector updates per replica)."""
    units_per_step = max(1, int(batch)) * max(1, int(tape.W))
    steps = int(_SLICE_BUDGET_UNITS // units_per_step)
    # the budget takes precedence over the floor: a floor-clamped
    # dispatch at flagship width (git-makefile W ~560k, batch 8) was
    # measured at ~35 s with a 256 floor — inside 2x of the runtime's
    # kill bound. 64 steps keeps the worst honored shape near the
    # budget; dispatch-count growth is cheap (async enqueue, one
    # compile for all slices).
    return max(64, min(32768, steps))


_zone_slice_jit_cache = {}


def execute_zone_batch_sliced_jax(tape: ZoneTape, agent_k: np.ndarray,
                                  seq_k: np.ndarray, batch: int,
                                  slice_steps: int = 32768,
                                  xs_slices=None):
    """execute_zone_batch_jax semantics with the whole-tape scan split
    into bounded-length dispatches (carry stays device-resident between
    calls, so the only extra cost is per-slice dispatch).

    Motivation (2026-07-31, first live tunnel window in three rounds):
    the single whole-tape scan — 524k scan steps on git-makefile —
    reproducibly killed the TPU worker on the tunneled v5e runtime
    (\"TPU worker process crashed or restarted ... kernel fault\") on
    every corpus, while short-program benches on the same chip ran
    clean. Bounding device time per dispatch keeps each program inside
    whatever execution budget that runtime enforces, and is the right
    shape for a tunneled deployment anyway: liveness probes and other
    work interleave at slice boundaries instead of queueing behind a
    minutes-long program. Returns (rank [B, W], ever [B, W]) as DEVICE
    arrays, like the whole-tape batch executor."""
    import jax
    import jax.numpy as jnp

    W, plen, n_idx = tape.W, tape.plen, tape.n_idx
    MB, MC, MD = (tape.blk_cursor.shape[1], tape.ch_slot.shape[1],
                  tape.del_kind.shape[1])
    if xs_slices is None:
        S, xs_slices = slice_tape_xs(tape, slice_steps)
    else:
        S = int(xs_slices[0]["op"].shape[0])
    key = (W, plen, n_idx, S, MB, MC, MD, batch)
    fns = _zone_slice_jit_cache.get(key)
    if fns is None:
        inner = partial(_run_zone_slice, W=W, plen=plen, n_idx=n_idx,
                        MB=MB, MC=MC, MD=MD)
        # donate the dead previous carry (zone_session._micro_fn
        # pattern): each slice updates the batched state in place
        # instead of doubling peak device memory per dispatch
        fn = jax.jit(jax.vmap(inner, in_axes=(0, None)),
                     donate_argnums=0)
        init = jax.jit(jax.vmap(
            partial(init_zone_carry, W, plen, n_idx), in_axes=(None, 0)))
        fns = (fn, init)
        _zone_slice_jit_cache[key] = fns
    fn, init = fns
    agent_j = jnp.asarray(agent_k.astype(np.int32))
    seq_b = jnp.asarray(
        np.broadcast_to(seq_k.astype(np.int32), (batch, W)).copy())
    carry = init(agent_j, seq_b)
    for xs in xs_slices:
        carry = fn(carry, xs)
    return carry[2], carry[6]


def _pad_tape_xs(tape: ZoneTape, target: Optional[int] = None) -> dict:
    T = tape.op.shape[0]
    Tp = _pow2(T) if target is None else int(target)
    assert Tp >= T

    def pad_t(a, fill=0):
        out = np.full((Tp,) + a.shape[1:], fill, a.dtype)
        out[:T] = a
        return out

    return dict(
        # pad steps are self-FORKs (state[0] <- state[0]): a padded
        # OP_BEGIN would reset row 0 to the base prefix and clobber any
        # pinned session row held there
        op=pad_t(tape.op, OP_FORK), a=pad_t(tape.arg_a),
        b=pad_t(tape.arg_b), snap=pad_t(tape.snap_flag),
        blk_cursor=pad_t(tape.blk_cursor, -1),
        blk_prev=pad_t(tape.blk_prev, -1), blk_root=pad_t(tape.blk_root),
        blk_start=pad_t(tape.blk_start), blk_len=pad_t(tape.blk_len),
        ch_slot=pad_t(tape.ch_slot, -1),
        ch_ol_static=pad_t(tape.ch_ol_static, -1),
        ch_ol_coord=pad_t(tape.ch_ol_coord),
        ch_orr_own=pad_t(tape.ch_orr_own, -1), ch_blk=pad_t(tape.ch_blk),
        ch_agent=pad_t(tape.ch_agent), ch_seq=pad_t(tape.ch_seq),
        del_kind=pad_t(tape.del_kind, -1), del_a=pad_t(tape.del_a),
        del_b=pad_t(tape.del_b))


def zone_checkout_device(oplog, from_frontier: Sequence[int] = (),
                         merge_frontier: Optional[Sequence[int]] = None,
                         prep: Optional[ZonePrep] = None,
                         tape: Optional[ZoneTape] = None):
    """Full device checkout/merge via the zone kernel. Returns
    (text, frontier). FULL runs (prep and tape computed here) record
    their throughput into the engine policy (listmerge/policy.py) — this
    is how the policy's zone rate bootstraps; callers passing precomputed
    prep/tape are NOT recorded (an execute-only rate would flatter the
    engine by the dominant compose/pack cost it skipped)."""
    import time as _time
    t0 = _time.perf_counter()
    # Record throughput into the engine policy only for FULL runs (prep
    # and tape computed here): a caller passing precomputed prep/tape
    # would otherwise feed an execute-only rate — minus the dominant
    # compose/pack cost — into merge-engine selection.
    full_run = prep is None and tape is None
    if prep is None:
        # fetch_composed=False: the native pack reads the composer's
        # output in the ctx cache; the Python-side entry columns are
        # only materialized if a fallback needs them (get_composed)
        prep = prepare_zone(oplog, from_frontier, merge_frontier,
                            fetch_composed=False)
    if not prep.plan.entries:
        txt = prep.prefix
    else:
        if tape is None:
            tape = pack_zone_tape(prep)
        rank, ever = execute_zone_jax(tape, prep.agent_k, prep.seq_k)
        order = np.argsort(rank, kind="stable")[:_count_live(rank)]
        vis = ever[order] == 0
        txt = prep.pool[order[vis]].astype(np.int32).tobytes() \
            .decode("utf-32-le")
    if full_run:
        from ..listmerge import policy as _policy
        n_before = max((int(x) for x in from_frontier), default=-1) + 1
        n_after = max((int(x) for x in prep.plan.final_frontier),
                      default=-1) + 1
        _policy.GLOBAL.record(_policy.ZONE, n_after - n_before,
                              _time.perf_counter() - t0)
    return txt, list(prep.plan.final_frontier)


def _count_live(rank: np.ndarray) -> int:
    return int((rank < int(BIG32)).sum())
