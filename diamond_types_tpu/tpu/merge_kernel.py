"""listmerge_tpu — the device-resident merge backend.

End-to-end document checkout with the concurrent-order resolution running
on the accelerator (reference equivalent: the whole `src/listmerge` stack).
Division of labor (BASELINE.json north star): the host extracts per-item
origins (its order-statistic tree is the right tool for positional
lookups); the device computes the global document order — the Fugue-tree
linearization that replaces YjsMod `integrate` (see tpu/linearize.py) —
plus visibility filtering and text assembly, batched over documents.

Pipeline:

  host   prepare_doc(oplog):
           native transform (origin extraction) -> tracker item table
           -> anchor-split runs -> tree arrays (parent/side/keys)
           -> char pool (fast-forward prefix text + insert arena slices)
  device checkout_device / checkout_batch_device:
           fugue_linearize_jax (sorts + pointer-jumping Euler tour)
           -> visible-length prefix sums -> gather from the char pool

Batching: documents are padded to a common run count and char capacity and
vmapped; padding runs carry parent=root, huge sort keys, and zero visible
length, so they sort to the end and contribute no text.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence

import numpy as np

from .linearize import (ROOT, UNDERWATER, build_tree_np,
                        fugue_linearize_jax, materialize_jax,
                        resolve_pos_keys, split_runs_at_anchors)


@dataclass
class DeviceDoc:
    """Host-prepared dense tables for one document's device checkout."""
    parent: np.ndarray      # [n] int32, parent == n -> virtual root
    side: np.ndarray        # [n] int8, 0 left / 1 right child
    key_pos: np.ndarray     # [n] int32 sibling sort key (orr position desc)
    key_agent: np.ndarray   # [n] int32 sibling sort key (agent name rank)
    key_seq: np.ndarray     # [n] int32 sibling sort key (seq)
    vis_len: np.ndarray     # [n] int32 visible chars contributed by run
    char_off: np.ndarray    # [n] int32 first char of run in `chars`
    chars: np.ndarray       # [pool] int32 char codes (prefix + ins arena)
    total_len: int          # expected document length
    frontier: Optional[List[int]] = None  # version the checkout lands on


# The agent-rank and insert-arena columns moved to listmerge/columnar.py
# (shared with the device transform, tpu/xform.py); the historical names
# stay importable — plan_kernels and the bench harnesses use them.
from ..listmerge.columnar import (agent_key_columns as _agent_keys,
                                  arena_offset_columns as _arena_offsets)


def prepare_doc(oplog, from_frontier: Sequence[int] = (),
                merge_frontier: Optional[Sequence[int]] = None) -> DeviceDoc:
    """Host pass: origins + char pool for a device checkout.

    Generalizes to INCREMENTAL merge (reference: TransformedOpsIter::new
    takes any `from` frontier, merge.rs:618): the tracker covers the
    conflict zone of (from, merge), the underwater spine tiles the
    document at the zone's common ancestor, and the produced checkout is
    the document at version_union(from, merge) — which is exactly what a
    branch at `from` merging `merge` must converge to."""
    from ..native.core import get_native_ctx

    ctx = get_native_ctx(oplog)
    frm = [int(x) for x in from_frontier]
    merge = ([int(x) for x in oplog.version] if merge_frontier is None
             else [int(x) for x in merge_frontier])
    *_rest, union = ctx.transform(frm, merge)
    ids, ln, ol, orr, st, ev = ctx.dump_tracker(keep_underwater=True)
    common = ctx.zone_common()

    # The underwater id space tiles the document at the conflict zone's
    # COMMON ANCESTOR (the version the tracker's walk starts from) — NOT
    # at [min insert id - 1]: zone ops that are pure deletes toggle
    # underwater text without creating tracker items.
    if len(ids) == 0:
        # no conflict zone at all (purely linear history): the document is
        # the fast-forward result; model it as one visible pseudo-run
        prefix, _ = ctx.merge_to_string("", [], union)
        ctx.release_tracker()
        arr = np.frombuffer(prefix.encode("utf-32-le"), dtype=np.int32)
        n = 1
        return DeviceDoc(
            parent=np.array([n], dtype=np.int32),
            side=np.ones(n, dtype=np.int8),
            key_pos=np.zeros(n, dtype=np.int32),
            key_agent=np.zeros(n, dtype=np.int32),
            key_seq=np.zeros(n, dtype=np.int32),
            vis_len=np.array([len(arr)], dtype=np.int32),
            char_off=np.zeros(n, dtype=np.int32),
            chars=arr if len(arr) else np.zeros(1, np.int32),
            total_len=len(arr), frontier=union)
    if common:
        prefix, _ = ctx.merge_to_string("", [], common)
    else:
        prefix = ""
    ctx.release_tracker()  # the dump above is all we needed
    prefix_arr = np.frombuffer(prefix.encode("utf-32-le"), dtype=np.int32)
    plen = len(prefix_arr)

    s_ids, s_len, s_ol, s_orr, s_ev = split_runs_at_anchors(
        ids, ln, ol, orr, (ev,))
    agent, seq = _agent_keys(oplog, s_ids)
    parent, side, ka, ks, orr_run = build_tree_np(s_ids, s_len, s_ol, s_orr,
                                                  agent, seq)
    kp = resolve_pos_keys(parent, side, ka, ks, orr_run)

    uw = s_ids >= UNDERWATER
    # Final visibility: a full checkout merges EVERY op, so an item is
    # visible iff no delete op ever targeted it — the tracker's monotone
    # `ever` flag. (The post-walk `state` reflects only the LAST walked
    # piece's version: concurrent branches sit retreated, deletes from
    # other branches sit undone — wrong for the merged frontier.)
    # Underwater runs are structural anchors; only their overlap with the
    # real prefix text [UNDERWATER, UNDERWATER+plen) is document text (the
    # tracker seeds one giant placeholder span whose tail is not text).
    uw_text = np.maximum(
        0, np.minimum(s_ids + s_len, UNDERWATER + plen) - s_ids)
    vis = np.where(s_ev != 0, 0, np.where(uw, uw_text, s_len))

    from ..text.op import INS
    arena_str = oplog.ops._arenas[INS].get((0, oplog.ops.arena_len(INS)))
    arena = np.frombuffer(arena_str.encode("utf-32-le"), dtype=np.int32)
    chars = np.concatenate([prefix_arr, arena]) if plen else arena
    off = np.where(uw, s_ids - UNDERWATER,
                   plen + _arena_offsets(oplog, np.where(uw, 0, s_ids)))

    return DeviceDoc(
        parent=parent.astype(np.int32), side=side.astype(np.int8),
        key_pos=kp.astype(np.int32),
        key_agent=ka.astype(np.int32), key_seq=ks.astype(np.int32),
        vis_len=vis.astype(np.int32), char_off=off.astype(np.int32),
        chars=chars.astype(np.int32), total_len=int(vis.sum()),
        frontier=union)


def _checkout_kernel(parent, side, key_pos, key_agent, key_seq, vis_len,
                     char_off, chars, cap: int, pallas: bool = False):
    perm = fugue_linearize_jax(parent, side, key_pos, key_agent, key_seq)
    if pallas:
        from .pallas_kernels import materialize_pallas
        return materialize_pallas(perm, vis_len, char_off, chars, cap)
    return materialize_jax(perm, vis_len, char_off, chars, cap)


_kernel_cache = {}


def _pow2(x: int) -> int:
    return 1 << max(1, (int(x) - 1)).bit_length()


def _jitted_kernel(cap: int):
    """Compiled batched kernels keyed by the (power-of-two) capacity so
    growing documents reuse O(log max_len) compiled executables instead of
    recompiling per exact length. DT_TPU_PALLAS=1 selects the Pallas
    materialize stage (pallas_kernels.materialize_pallas); that path
    unrolls the batch instead of vmapping — the run-copy kernel's grid
    spans runs, and vmap-of-pallas_call would stack a batch grid dim
    whose auto-extended block specs violate Pallas TPU block rules."""
    pallas = bool(os.environ.get("DT_TPU_PALLAS"))
    key = (cap, pallas)
    fn = _kernel_cache.get(key)
    if fn is None:
        import jax
        if pallas:
            import jax.numpy as jnp
            single = partial(_checkout_kernel, cap=cap, pallas=True)

            def run_all(*cols):
                outs = [single(*(c[i] for c in cols))
                        for i in range(cols[0].shape[0])]
                return (jnp.stack([t for t, _ in outs]),
                        jnp.stack([n for _, n in outs]))

            fn = jax.jit(run_all)
        else:
            fn = jax.jit(jax.vmap(partial(_checkout_kernel, cap=cap,
                                          pallas=pallas)))
        _kernel_cache[key] = fn
    return fn


def checkout_device(oplog, doc: Optional[DeviceDoc] = None) -> str:
    """Full checkout with device-side order resolution. Returns the text."""
    if doc is None:
        doc = prepare_doc(oplog)
    return checkout_batch_device([doc])[0]


def merge_device(oplog, from_frontier: Sequence[int],
                 merge_frontier: Optional[Sequence[int]] = None):
    """Incremental device merge: the document + frontier a branch at
    `from_frontier` reaches after merging `merge_frontier` (defaults to
    the oplog tip). Returns (text, frontier) at version_union(from,
    merge) — the convergence target of Branch.merge (reference:
    src/list/merge.rs:63-96 via TransformedOpsIter::new(from, ...))."""
    doc = prepare_doc(oplog, from_frontier, merge_frontier)
    return checkout_batch_device([doc])[0], doc.frontier


def pad_docs(docs: List[DeviceDoc]):
    """Stack documents into batch arrays. Shapes are padded to the next
    power of two so repeated checkouts of growing documents hit the jit
    trace cache instead of recompiling per exact size."""
    n = _pow2(max(d.parent.shape[0] for d in docs))
    pool = _pow2(max(d.chars.shape[0] for d in docs))
    b = len(docs)
    parent = np.full((b, n), 0, dtype=np.int32)
    side = np.ones((b, n), dtype=np.int32)
    kp = np.full((b, n), np.iinfo(np.int32).max, dtype=np.int32)
    ka = np.full((b, n), np.iinfo(np.int32).max, dtype=np.int32)
    ks = np.full((b, n), np.iinfo(np.int32).max, dtype=np.int32)
    vis = np.zeros((b, n), dtype=np.int32)
    off = np.zeros((b, n), dtype=np.int32)
    chars = np.zeros((b, pool), dtype=np.int32)
    for i, d in enumerate(docs):
        k = d.parent.shape[0]
        # the kernel's virtual root is index n (padded size); remap each
        # doc's own root (k) and hang padding rows off the root with huge
        # keys so they linearize to the very end (zero visible text)
        parent[i, :] = n
        parent[i, :k] = np.where(d.parent == k, n, d.parent)
        side[i, :k] = d.side
        kp[i, :k] = d.key_pos
        ka[i, :k] = d.key_agent
        ks[i, :k] = d.key_seq
        vis[i, :k] = d.vis_len
        off[i, :k] = d.char_off
        chars[i, :d.chars.shape[0]] = d.chars
    return parent, side, kp, ka, ks, vis, off, chars


def checkout_batch_device(docs: List[DeviceDoc], cap: Optional[int] = None
                          ) -> List[str]:
    """Batched device checkout: one vmapped kernel call for all docs."""
    import jax.numpy as jnp

    parent, side, kp, ka, ks, vis, off, chars = pad_docs(docs)
    if cap is None:
        cap = _pow2(max(max(d.total_len for d in docs), 1))
    fn = _jitted_kernel(cap)
    texts, totals = fn(*(jnp.asarray(x) for x in
                         (parent, side, kp, ka, ks, vis, off, chars)))
    texts = np.asarray(texts)
    totals = np.asarray(totals)
    return [texts[i, :totals[i]].astype(np.int32).tobytes()
            .decode("utf-32-le") for i in range(len(docs))]
