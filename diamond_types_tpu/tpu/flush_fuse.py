"""Fused vmapped bucket flush: many documents, ONE device call.

The serve tier batches work (shape-bucketed admission queues) but the
pre-fusion flush threw the batch away at the device boundary: each doc
in a taken bucket was synced back-to-back, so batch occupancy bought
compile reuse but zero arithmetic intensity (ROADMAP item (c)). This
module closes that gap with the `tpu/batch.py replay_batch` shape —
`lax.scan` over op index, batched over documents — continued from
RESIDENT device state instead of replayed from scratch:

  * `FusedDocSession` — a document resident on the device as a dense
    `[cap]` char-code buffer + length (the replay-kernel state). The
    pending op tail since the last sync is extracted HOST-side through
    the oplog's transformed-op stream (`get_xf_operations_full`, the
    same oracle every host engine applies), so concurrent/merged
    histories arrive as plain positional ops — the device only ever
    sees the bounded-shift linear form.
  * `plan_tail()` packs that tail into dense `(pos, dlen, ilen, chars)`
    rows, splitting long ops to `max_ins` exactly like
    `encode_trace_ops` (the bounded-shift contract that keeps the tail
    shift a static-roll select, see batch.py).
  * `fused_replay(sessions, plans)` stacks every doc in the bucket into
    `[b, n, max_ins]` arrays — `n` padded to the bucket's power-of-two
    shape class, `b` rounded to a power of two so the jit cache stays
    O(log^2) — and runs ONE jitted scan for the whole bucket.

Contract violations (an op longer than `max_ins` reaching the kernel)
poison that DOCUMENT's length to -1 — per-doc, not per-batch, so one
bad doc falls back to the host engine without discarding its bucket
neighbors' work. `fused_replay` additionally cross-checks each
returned length against the host-side projection; any drift evicts the
session and the bank serves the doc from `oplog.checkout_tip()`.

Everything device-touching imports jax lazily: the serve tier's host
engine (the HTTP server default) must never pull in a backend.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

from .merge_kernel import _pow2

DEFAULT_CAP = 1 << 10
DEFAULT_MAX_INS = 16
# shape classes the background warmer compiles ahead of the first real
# flush (ops-per-doc axis); batch classes derive from flush_docs
WARMUP_SHAPE_CLASSES = (1, 2, 4, 8)

_fused_jit_cache = {}
from ..analysis.witness import make_lock as _make_lock
_fused_jit_lock = _make_lock("fused_jit", "leaf")


def make_replay_body(mi: int):
    """The fused-tail replay body, shared by the per-shard jit
    (`_fused_fn`) and the mesh flush program
    (`parallel.mesh.mesh_flush_fn`, which wraps it in `shard_map` over
    the `docs` axis — the body is pure data parallel, so partitioning
    the batch axis needs no collectives). Per-doc poison: a
    bounded-shift violation is zeroed to a no-op and only ITS doc's
    length comes back -1, so one bad doc never corrupts batch (or, on
    the mesh path, other shards') neighbors. Rows whose incoming length
    is the -1 padding sentinel and whose ops are all zero stay at -1 —
    inert mesh padding rows survive the kernel identifiably."""
    import jax
    import jax.numpy as jnp

    from .batch import _apply_ops_batched

    def run(docs, lens, pos, dlen, ilen, chars):
        bad = (dlen > mi) | (ilen > mi)
        dlen = jnp.where(bad, 0, dlen)
        ilen = jnp.where(bad, 0, ilen)
        bad_doc = jnp.any(bad, axis=1)

        def step(carry, op):
            d, l, p, dl, il, c = carry + op
            d, l = _apply_ops_batched(d, l, p, dl, il, c)
            return (d, l), None

        ops = (jnp.swapaxes(pos, 0, 1), jnp.swapaxes(dlen, 0, 1),
               jnp.swapaxes(ilen, 0, 1), jnp.swapaxes(chars, 0, 1))
        (docs, lens), _ = jax.lax.scan(step, (docs, lens), ops)
        return docs, jnp.where(bad_doc, -1, lens)

    return run


def _fused_fn(b: int, n: int, mi: int, cap: int):
    """Jitted fused-tail replay for batch `b`, `n` ops/doc, `max_ins`
    `mi`, capacity `cap` — all static, all powers of two, so the cache
    holds O(log^2) entries no matter how buckets drift."""
    import jax

    key = (b, n, mi, cap)
    with _fused_jit_lock:
        fn = _fused_jit_cache.get(key)
        from ..obs.devprof import note_jit_lookup
        note_jit_lookup("fused", fn is not None)
        if fn is None:
            fn = jax.jit(make_replay_body(mi), donate_argnums=(0, 1))
            _fused_jit_cache[key] = fn
    # hit or miss, the class is warm from here on — tell the steer
    # table (outside the cache guard; note_warm takes its own leaf)
    from .steer import STEER
    STEER.note_warm("fused", mi, cap, b, n)
    return fn


_pallas_jit_cache = {}
_pallas_jit_lock = _make_lock("pallas_jit", "leaf")


def make_pallas_replay_body(mi: int, interpret: bool):
    """The fused replay body with the op-step kernel in Pallas
    (pallas_kernels.apply_op_block — scalar-controlled lane rotations
    instead of the XLA formulation's per-lane gathers, which Mosaic caps
    at ~128 lanes). Poison masking and the -1 length sentinel are
    byte-identical to make_replay_body, so `adopt_results` fences this
    rung exactly like the fused and mesh rungs."""
    import jax
    import jax.numpy as jnp

    from .pallas_kernels import apply_op_block

    def run(docs, lens, pos, dlen, ilen, chars):
        bad = (dlen > mi) | (ilen > mi)
        dlen = jnp.where(bad, 0, dlen)
        ilen = jnp.where(bad, 0, ilen)
        bad_doc = jnp.any(bad, axis=1)

        def step(carry, op):
            d, l = carry
            p, dl, il, c = op
            d, l = apply_op_block(p, dl, il, c, d, l, interpret=interpret)
            return (d, l), None

        ops = (jnp.swapaxes(pos, 0, 1), jnp.swapaxes(dlen, 0, 1),
               jnp.swapaxes(ilen, 0, 1), jnp.swapaxes(chars, 0, 1))
        (docs, lens), _ = jax.lax.scan(step, (docs, lens), ops)
        return docs, jnp.where(bad_doc, -1, lens)

    return run


def _pallas_fn(b: int, n: int, mi: int, cap: int):
    """Jitted Pallas-rung replay, cache "pallas" — same pow2 shape-class
    discipline as `_fused_fn`. Off-TPU backends run the kernel
    interpreted (the pallas_guide.md debugging convention), so the rung
    stays exercisable on the CPU-simulated mesh."""
    import jax

    interpret = jax.default_backend() != "tpu"
    key = (b, n, mi, cap, interpret)
    with _pallas_jit_lock:
        fn = _pallas_jit_cache.get(key)
        from ..obs.devprof import note_jit_lookup
        note_jit_lookup("pallas", fn is not None)
        if fn is None:
            fn = jax.jit(make_pallas_replay_body(mi, interpret),
                         donate_argnums=(0, 1))
            _pallas_jit_cache[key] = fn
    from .steer import STEER
    STEER.note_warm("pallas", mi, cap, b, n)
    return fn


def pallas_fused_replay(sessions: List["FusedDocSession"],
                        plans: List["TailPlan"]
                        ) -> Tuple[List[bool], float]:
    """The ladder's TOP rung: fused bucket replay through the Pallas
    step kernel. Same packing, fences, and commit protocol as
    `fused_replay`; the scheduler falls back to the mesh/fused rungs on
    any failure here."""
    import jax.numpy as jnp

    b = len(sessions)
    assert b == len(plans) and b >= 1
    cap = sessions[0].cap
    mi = sessions[0].max_ins
    from .steer import STEER
    n0 = _pow2(max(max(p.n_ops for p in plans), 1))
    bp0 = _pow2(b) if b > 1 else 1
    bp, n = STEER.snap("pallas", bp0, n0, mi, cap)
    pos, dlen, ilen, chars = pack_plans(plans, n, mi, bp)
    from ..obs.devprof import note_transfer
    note_transfer(pos.nbytes + dlen.nbytes + ilen.nbytes + chars.nbytes,
                  rung="pallas", purpose="plan")
    docs = jnp.stack([s.docs for s in sessions]
                     + [sessions[0].docs] * (bp - b))
    lens = jnp.stack([s.lens for s in sessions]
                     + [sessions[0].lens] * (bp - b))
    fn = _pallas_fn(bp, n, mi, cap)
    out_docs, out_lens = fn(docs, lens, jnp.asarray(pos),
                            jnp.asarray(dlen), jnp.asarray(ilen),
                            jnp.asarray(chars))
    t_fence = time.perf_counter()
    got = np.asarray(out_lens)
    device_s = time.perf_counter() - t_fence
    return adopt_results(sessions, plans, out_docs, out_lens, got), \
        device_s


def warmup_fused_cache(flush_docs: int = 8, cap: int = DEFAULT_CAP,
                       max_ins: int = DEFAULT_MAX_INS,
                       shape_classes: Sequence[int] = WARMUP_SHAPE_CLASSES,
                       mesh_shards: int = 0,
                       xform_classes: Sequence[int] = (),
                       pallas: bool = False) -> int:
    """Compile the fused kernel for every (batch, ops) shape class a
    bank configured with `flush_docs` can emit, so the first REAL flush
    hits a warm jit cache instead of eating a compile on the request
    path. Returns the number of kernels compiled. Hits/misses surface
    through the existing `devprof.jit_cache` fields (cache "fused").

    `mesh_shards > 0` additionally pre-compiles the MESH flush program
    (`parallel.mesh.mesh_flush_fn`) for every super-batch shape class a
    `mesh_shards`-shard window can assemble — B padded to the mesh per
    `pad_batch_to_mesh` — so the first mesh window doesn't eat a cold
    compile either (cache "mesh").

    `xform_classes` pre-compiles the device-transform dispatch
    (tpu/xform.py, cache "xform") for those run-count classes, and
    `pallas=True` pre-compiles the Pallas replay rung (cache "pallas")
    for the same shape classes as the fused rung."""
    import jax
    import jax.numpy as jnp

    from .steer import cap_class, warmup_batches

    # sessions materialize at steer.cap_class(len * headroom) — warm
    # the floor class a fresh session actually lands on, not the raw
    # configured cap (which may name a class no session ever uses).
    # Both the floor and the batch enumeration come from tpu/steer.py,
    # the SAME table the flush path's snap() consults, so warmup and
    # steering can never disagree on what counts as a warm class.
    cap = cap_class(cap)
    compiled = 0
    batches = warmup_batches(flush_docs)
    for b in batches:
        for ncls in shape_classes:
            n = _pow2(ncls)
            fn = _fused_fn(b, n, max_ins, cap)
            docs = jnp.zeros((b, cap), jnp.int32)
            lens = jnp.zeros((b,), jnp.int32)
            z = jnp.zeros((b, n), jnp.int32)
            ch = jnp.zeros((b, n, max_ins), jnp.int32)
            out_docs, out_lens = fn(docs, lens, z, z, z, ch)
            jax.block_until_ready(out_lens)
            compiled += 1
    if mesh_shards > 0:
        from ..parallel.mesh import (mesh_flush_fn, pad_batch_count,
                                     serve_mesh)
        mesh = serve_mesh(mesh_shards)
        ndev = mesh.devices.size
        sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(mesh.axis_names[0]))
        # a window can fold up to mesh_shards * flush_docs docs; the
        # padded-B classes below are exactly what pad_batch_count can
        # emit for any b in that range (O(log) classes)
        bps = sorted({pad_batch_count(b, ndev)
                      for b in range(1, mesh_shards * flush_docs + 1)})
        from ..obs.devprof import note_transfer
        for bp in bps:
            for ncls in shape_classes:
                n = _pow2(ncls)
                fn = mesh_flush_fn(mesh, bp, n, max_ins, cap)
                docs = jax.device_put(
                    jnp.zeros((bp, cap), jnp.int32), sh)
                lens = jax.device_put(
                    jnp.full((bp,), -1, jnp.int32), sh)
                z = jax.device_put(jnp.zeros((bp, n), jnp.int32), sh)
                ch = jax.device_put(
                    jnp.zeros((bp, n, max_ins), jnp.int32), sh)
                note_transfer(docs.nbytes + lens.nbytes + 3 * z.nbytes
                              + ch.nbytes, rung="mesh",
                              purpose="warmup")
                _out, out_lens = fn(docs, lens, z, z, z, ch)
                jax.block_until_ready(out_lens)
                compiled += 1
    if xform_classes:
        # all-padding tables (parent=root, huge keys, zero visibility)
        # exercise exactly the (bp, n) signature resolve_positions emits
        from .xform import INT32_MAX, _xform_fn
        for b in batches:
            for ncls in xform_classes:
                n = _pow2(ncls)
                fn = _xform_fn(b, n)
                parent = jnp.full((b, n), n, jnp.int32)
                side = jnp.ones((b, n), jnp.int32)
                keys = jnp.full((b, n), INT32_MAX, jnp.int32)
                z = jnp.zeros((b, n), jnp.int32)
                out = fn(parent, side, keys, keys, keys, z, z)
                jax.block_until_ready(out[2])
                compiled += 1
    if pallas:
        for b in batches:
            for ncls in shape_classes:
                n = _pow2(ncls)
                fn = _pallas_fn(b, n, max_ins, cap)
                docs = jnp.zeros((b, cap), jnp.int32)
                lens = jnp.zeros((b,), jnp.int32)
                z = jnp.zeros((b, n), jnp.int32)
                ch = jnp.zeros((b, n, max_ins), jnp.int32)
                _d, out_lens = fn(docs, lens, z, z, z, ch)
                jax.block_until_ready(out_lens)
                compiled += 1
    return compiled


@dataclass
class TailPlan:
    """Host-side packing of one doc's pending op tail (see
    FusedDocSession.plan_tail). `max_len` past the session cap means
    the plan does not fit — the caller resyncs at a larger capacity."""
    pos: np.ndarray
    dlen: np.ndarray
    ilen: np.ndarray
    chars: np.ndarray          # [n_ops, max_ins] int32
    n_ops: int
    new_len: int               # projected doc length after the tail
    max_len: int               # peak length the tail passes through
    frontier: Tuple[int, ...]  # oplog frontier after the tail
    synced_to: int             # oplog length the plan covers

    def fits(self, cap: int) -> bool:
        return self.max_len <= cap


def _empty_plan(frontier, synced_to, doc_len, mi) -> TailPlan:
    z = np.zeros(0, np.int32)
    return TailPlan(z, z, z, np.zeros((0, mi), np.int32), 0, doc_len,
                    doc_len, frontier, synced_to)


class FusedDocSession:
    """A live document resident on the device as the replay-kernel
    state: `[cap]` char codes + length. Drop-in for the bank's session
    surface (sync / text / footprint_slots / resyncs / synced_to)."""

    def __init__(self, oplog, cap: int = DEFAULT_CAP,
                 max_ins: int = DEFAULT_MAX_INS,
                 headroom: float = 2.0) -> None:
        self.oplog = oplog
        self.max_ins = int(max_ins)
        self.headroom = float(headroom)
        self.resyncs = -1          # the first build counts up to 0
        self.merges = 0
        self._materialize(min_cap=cap)

    # ---- full (re)build --------------------------------------------------

    def _materialize(self, min_cap: int = 0) -> None:
        """Host checkout -> device buffer. Always correct (the host
        tracker is the oracle); costs one full upload, so it only runs
        at build time and on capacity growth."""
        import jax.numpy as jnp

        text = self.oplog.checkout_tip().snapshot()
        # capacity class via steer.cap_class — the SAME floor warmup
        # enumerates, so every materialized session lands on a class
        # the warm table knows about (the cap-floor agreement fix)
        from .steer import cap_class
        cap = cap_class(max(int(len(text) * self.headroom), min_cap))
        buf = np.zeros(cap, np.int32)
        if text:
            buf[:len(text)] = np.frombuffer(
                text.encode("utf-32-le"), dtype=np.int32)
        self.cap = cap
        self.docs = jnp.asarray(buf)
        self.lens = jnp.asarray(np.int32(len(text)))
        self.doc_len = len(text)
        self.frontier = tuple(int(x) for x in self.oplog.version)
        self.synced_to = len(self.oplog)
        self.resyncs += 1
        self._arena_tag = None     # full rebuild invalidates any slot
        from ..obs.devprof import note_transfer
        note_transfer(buf.nbytes, rung="session", purpose="stage")

    # ---- host-side planning ----------------------------------------------

    def plan_tail(self) -> TailPlan:
        """Pack every op appended since the last sync into dense
        positional rows. Pure read — commit() applies the bookkeeping,
        so a plan can be dropped (fallback, eviction) at zero cost.
        Concurrent/merged histories come back pre-transformed by the
        host oracle; `pos is None` rows (deletes that already
        happened) are no-ops and are skipped."""
        ol = self.oplog
        if self.synced_to >= len(ol):
            return _empty_plan(self.frontier, self.synced_to,
                               self.doc_len, self.max_ins)
        mi = self.max_ins
        xf = ol.get_xf_operations_full(list(self.frontier), ol.version)
        rows: List[Tuple[int, int, int, str]] = []
        cur = self.doc_len
        peak = cur
        from ..text.op import INS
        for _lv, op, pos in xf:
            if pos is None:
                continue
            if op.kind == INS:
                content = ol.ops.get_run_content(op)
                if not op.fwd:
                    content = content[::-1]
                off = 0
                while off < len(content):
                    chunk = content[off:off + mi]
                    rows.append((pos + off, 0, len(chunk), chunk))
                    off += len(chunk)
                cur += len(content)
                peak = max(peak, cur)
            else:
                d = len(op)
                while d:
                    k = min(d, mi)
                    rows.append((pos, k, 0, ""))
                    d -= k
                cur -= len(op)
        k = len(rows)
        frontier = tuple(int(x) for x in xf.next_frontier)
        if k == 0:
            plan = _empty_plan(frontier, len(ol), self.doc_len, mi)
            plan.max_len = peak
            return plan
        pos_a = np.zeros(k, np.int32)
        dl_a = np.zeros(k, np.int32)
        il_a = np.zeros(k, np.int32)
        ch_a = np.zeros((k, mi), np.int32)
        for i, (p, d, il, s) in enumerate(rows):
            pos_a[i] = p
            dl_a[i] = d
            il_a[i] = il
            if s:
                ch_a[i, :il] = np.frombuffer(
                    s.encode("utf-32-le"), dtype=np.int32)
        return TailPlan(pos_a, dl_a, il_a, ch_a, k, cur, peak, frontier,
                        len(ol))

    def commit(self, docs, lens, plan: TailPlan) -> None:
        """Adopt one fused-replay result row + the plan's bookkeeping.
        Clears the window-arena tag: the session's state rows are no
        longer the arena's rows (the mesh rung re-tags committed rows
        right after `adopt_results`, see parallel/arena.py)."""
        self._arena_tag = None
        self.docs = docs
        self.lens = lens
        self.doc_len = plan.new_len
        self.frontier = plan.frontier
        self.synced_to = plan.synced_to
        if plan.n_ops:
            self.merges += 1

    def commit_host(self, plan: TailPlan) -> None:
        """Adopt an EMPTY plan (frontier advanced, no visible ops —
        e.g. deletes of already-deleted spans): no device work."""
        assert plan.n_ops == 0
        self.frontier = plan.frontier
        self.synced_to = plan.synced_to

    # ---- merge path ------------------------------------------------------

    def sync(self) -> int:
        """Per-doc path (the fused fallback ladder's last device rung):
        plan, then replay this doc alone at batch size 1. Resyncs on
        capacity overflow. Raises on a poisoned result (the bank's
        sync_doc catches, evicts and serves from the host engine)."""
        plan = self.plan_tail()
        if not plan.fits(self.cap):
            self._materialize(
                min_cap=_pow2(int(plan.max_len * self.headroom)))
            return 0
        if plan.n_ops == 0:
            self.commit_host(plan)
            return 0
        ok, _device_s = fused_replay([self], [plan])
        if not ok[0]:
            raise RuntimeError(
                "fused replay poisoned/mismatched length "
                f"(doc_len {self.doc_len}, plan {plan.new_len})")
        return plan.n_ops

    # ---- reads -----------------------------------------------------------

    def text(self) -> str:
        """Fetch and decode the merged document (device parity
        surface: the answer comes from the replay kernel's state, not
        the host tracker)."""
        n = self.doc_len
        return np.asarray(self.docs[:n]).astype(np.int32).tobytes() \
            .decode("utf-32-le")

    def touch(self):
        return np.asarray(self.lens)

    def footprint_slots(self) -> int:
        """Device residency in int32 slots: the doc buffer dominates."""
        return int(self.cap)


def pack_plans(plans: Sequence[TailPlan], n: int, mi: int,
               bp: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray]:
    """Stack `plans` into dense host-side op arrays
    (pos/dlen/ilen [bp, n], chars [bp, n, mi]). Rows past len(plans)
    are all-zero no-ops — the inert padding the batch pow2 rounding
    (and the mesh super-batch divisibility padding) relies on. Shared
    by `fused_replay` and the mesh window's super-batch assembly."""
    pos = np.zeros((bp, n), np.int32)
    dlen = np.zeros((bp, n), np.int32)
    ilen = np.zeros((bp, n), np.int32)
    chars = np.zeros((bp, n, mi), np.int32)
    for i, p in enumerate(plans):
        k = p.n_ops
        pos[i, :k] = p.pos
        dlen[i, :k] = p.dlen
        ilen[i, :k] = p.ilen
        chars[i, :k] = p.chars
    return pos, dlen, ilen, chars


def adopt_results(sessions: Sequence[FusedDocSession],
                  plans: Sequence[TailPlan],
                  out_docs, out_lens,
                  got: np.ndarray) -> List[bool]:
    """The returned-length fence: commit each session whose device
    length matches the host-side projection; a poisoned (-1) or
    drifting row is NOT committed (the caller evicts it and serves the
    doc from the host engine). Shared by the per-shard and mesh paths
    so the fallback ladder fences identically in both."""
    ok: List[bool] = []
    for i, (sess, plan) in enumerate(zip(sessions, plans)):
        good = int(got[i]) == plan.new_len and int(got[i]) >= 0
        if good:
            sess.commit(out_docs[i], out_lens[i], plan)
        ok.append(good)
    return ok


def fused_replay(sessions: List[FusedDocSession],
                 plans: List[TailPlan]
                 ) -> Tuple[List[bool], float]:
    """Replay every session's pending tail in ONE jitted device call.

    All sessions must share (cap, max_ins) — the bank groups by
    capacity before calling. Ops pad to the max power-of-two op count
    in the batch (the bucket's shape class) and the batch rounds up to
    a power of two with no-op lanes, so the jit cache stays small.

    Returns (ok-per-session, device_wait_s). The device wait is the
    time spent blocked fetching the output lengths — the completion
    fence — which is the `block_until_ready`-equivalent attribution
    devprof wants. A session whose returned length is poisoned (-1) or
    disagrees with the host-side projection is NOT committed — the
    caller evicts it and serves the doc from the host engine.
    Successful sessions have their result rows committed."""
    import jax.numpy as jnp

    b = len(sessions)
    assert b == len(plans) and b >= 1
    cap = sessions[0].cap
    mi = sessions[0].max_ins
    from .steer import STEER
    n0 = _pow2(max(max(p.n_ops for p in plans), 1))
    bp0 = _pow2(b) if b > 1 else 1
    bp, n = STEER.snap("fused", bp0, n0, mi, cap)
    pos, dlen, ilen, chars = pack_plans(plans, n, mi, bp)
    from ..obs.devprof import note_transfer
    note_transfer(pos.nbytes + dlen.nbytes + ilen.nbytes + chars.nbytes,
                  rung="fused", purpose="plan")
    docs = jnp.stack([s.docs for s in sessions]
                     + [sessions[0].docs] * (bp - b))
    lens = jnp.stack([s.lens for s in sessions]
                     + [sessions[0].lens] * (bp - b))
    fn = _fused_fn(bp, n, mi, cap)
    out_docs, out_lens = fn(docs, lens, jnp.asarray(pos),
                            jnp.asarray(dlen), jnp.asarray(ilen),
                            jnp.asarray(chars))
    # the length fetch is the completion fence AND the parity
    # cross-check: poison (-1) or host-projection drift fails the doc
    t_fence = time.perf_counter()
    got = np.asarray(out_lens)
    device_s = time.perf_counter() - t_fence
    return adopt_results(sessions, plans, out_docs, out_lens, got), \
        device_s
