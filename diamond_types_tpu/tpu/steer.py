"""Batch-shape steering: snap flush windows onto WARM jit shape classes.

The dispatch rungs (pallas / mesh / fused) key their jit caches on the
padded `(b, n, max_ins, cap)` shape class, and pow2 rounding keeps the
class count O(log^2) — but pow2 rounding alone still lets a drifting
workload thrash the cache: a flash crowd whose per-window op counts
wander across pow2 buckets recompiles mid-flush even though a slightly
LARGER warmed class could have absorbed the window with bounded padding
waste. This module closes that gap with a tiny process-global policy:

  * `ShapeSteer` tracks the WARM set per jit cache ("fused" / "pallas"
    / "mesh") — populated by `note_warm` from the cache-lookup sites
    themselves (warmup compiles and observed flush compiles alike), so
    the table can never drift from the real jit caches.
  * `snap()` maps a window's pow2-floored `(bp0, n0)` to the shape
    class actually dispatched: an exact warm class is used as-is; a
    cold shape pads UP to the cheapest warm class whose cell waste
    `(bw*nw)/(bp0*n0)` stays under `max_waste`; a cold shape with no
    affordable warm neighbor pads anyway on FIRST sight (padding waste
    is microseconds, a compile is seconds) and only compiles its exact
    class once the shape RECURS (`recur_threshold`), at which point it
    joins the warm set and subsequent windows hit it exactly.

Padding `b`/`n` further up is parity-safe by construction: batch pad
rows replicate row 0 (per-shard rungs) or carry the `lens = -1` inert
sentinel (mesh rung), and op-axis padding rows are all-zero no-ops —
exactly the invariants `pack_plans` and the replay body already
maintain for pow2 rounding. The `adopt_results` length fence and the
five-rung fallback ladder sit BELOW this policy untouched.

`cap_class()` / `warmup_batches()` are the single source of truth for
capacity flooring and warmup batch enumeration — `warmup_fused_cache`
and `FusedDocSession._materialize` both consult them, so warmup can no
longer warm classes sessions never land on (the cap-floor drift fix).

Everything here is host-side dict bookkeeping — no jax imports; the
lookup cost is noise next to a single device dispatch, so the counters
run unconditionally and serve-bench / scorecards read them for free.
"""

from __future__ import annotations

from typing import Dict, Optional, Set, Tuple

from ..analysis.witness import make_lock as _make_lock
from .merge_kernel import _pow2

# pad up to a warm class while the padded cell count stays under this
# multiple of the floored cell count; beyond it a recurring shape earns
# its own compile instead of paying the waste every window
DEFAULT_MAX_WASTE = 4.0
# a cold shape seen this many times compiles its exact class (first
# sight never compiles: one-off shapes borrow a warm neighbor)
DEFAULT_RECUR_THRESHOLD = 2

_steer_lock = _make_lock("steer", "leaf")


def cap_class(cap: int) -> int:
    """The capacity shape class a session/warmup actually lands on:
    pow2, floored at 256 (`FusedDocSession._materialize`'s floor).
    Shared by warmup and the flush path so both agree byte-for-byte."""
    return _pow2(max(int(cap), 256))


def warmup_batches(flush_docs: int):
    """Batch shape classes a bank configured with `flush_docs` can emit
    on the per-shard rungs: 1 plus every pow2 up to flush_docs."""
    return sorted({1} | {_pow2(k) for k in range(2, max(int(flush_docs),
                                                        1) + 1)})


class ShapeSteer:
    """Process-global warm-class table + snap policy (see module doc).

    Keys are `(max_ins, cap, b, n)` per cache name, matching the jit
    cache keys modulo ordering. All state lives behind `_steer_lock`
    (leaf — safe under any rung's locks, including the jit-cache leaf
    guards, because it never acquires anything itself)."""

    def __init__(self, max_waste: float = DEFAULT_MAX_WASTE,
                 recur_threshold: int = DEFAULT_RECUR_THRESHOLD,
                 enabled: bool = True) -> None:
        self.enabled = enabled
        self.max_waste = float(max_waste)
        self.recur_threshold = int(recur_threshold)
        self._warm: Dict[str, Set[Tuple[int, int, int, int]]] = {}
        self._cold_seen: Dict[Tuple, int] = {}
        self._counts = {"lookups": 0, "hits": 0, "padded": 0,
                        "forced_pads": 0, "compiles": 0}

    def reset(self, table: bool = False) -> None:
        with _steer_lock:
            self._counts = {"lookups": 0, "hits": 0, "padded": 0,
                            "forced_pads": 0, "compiles": 0}
            if table:
                self._warm = {}
                self._cold_seen = {}

    def note_warm(self, cache: str, mi: int, cap: int, b: int,
                  n: int) -> None:
        """Record a shape class as warm in `cache`. Called from the jit
        cache lookup sites on hit AND miss — a hit proves the class
        warm, a miss is about to compile it — so the table tracks the
        real caches without a separate registration path."""
        with _steer_lock:
            self._warm.setdefault(cache, set()).add(
                (int(mi), int(cap), int(b), int(n)))

    def snap(self, cache: str, bp0: int, n0: int, mi: int, cap: int,
             multiple: int = 1) -> Tuple[int, int]:
        """Steer a window's pow2-floored shape `(bp0, n0)` onto the
        class to dispatch. `multiple` constrains the batch axis of any
        padded-to class (the mesh rung needs `bw % n_devices == 0`;
        warm mesh classes already satisfy it, this keeps a multi-mesh
        process honest). Returns `(bp, n)` with `bp >= bp0, n >= n0`;
        the caller pads exactly as it already does for pow2 rounding."""
        if not self.enabled:
            return bp0, n0
        with _steer_lock:
            self._counts["lookups"] += 1
            warm = self._warm.get(cache, ())
            if (mi, cap, bp0, n0) in warm:
                self._counts["hits"] += 1
                return bp0, n0
            floor_cells = bp0 * n0
            best: Optional[Tuple[int, int]] = None
            best_cells = 0
            for (wmi, wcap, bw, nw) in warm:
                if wmi != mi or wcap != cap or bw < bp0 or nw < n0:
                    continue
                if multiple > 1 and bw % multiple:
                    continue
                cells = bw * nw
                if best is None or cells < best_cells:
                    best, best_cells = (bw, nw), cells
            if best is not None \
                    and best_cells <= self.max_waste * floor_cells:
                self._counts["padded"] += 1
                return best
            ckey = (cache, mi, cap, bp0, n0)
            seen = self._cold_seen.get(ckey, 0) + 1
            self._cold_seen[ckey] = seen
            if best is not None and seen < self.recur_threshold:
                # one-off out-of-bound shape: borrow the warm neighbor
                # anyway — padding waste beats a request-path compile
                self._counts["forced_pads"] += 1
                return best
            self._counts["compiles"] += 1
            self._cold_seen.pop(ckey, None)
            return bp0, n0

    def snapshot(self) -> dict:
        with _steer_lock:
            c = dict(self._counts)
            looks = c["lookups"]
            pads = c["padded"] + c["forced_pads"]
            return {"enabled": self.enabled,
                    "max_waste": self.max_waste,
                    "lookups": looks,
                    "hits": c["hits"],
                    "padded": pads,
                    "forced_pads": c["forced_pads"],
                    "compiles": c["compiles"],
                    "hit_rate": round((c["hits"] + pads) / looks, 4)
                    if looks else 0.0,
                    "warm_classes": {k: len(v) for k, v
                                     in sorted(self._warm.items())}}


STEER = ShapeSteer()
