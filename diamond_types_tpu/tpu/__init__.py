"""Device tier: JAX/XLA kernels.

LVs are int64 (documents can exceed 2^31 ops; underwater sentinels live at
2^62), so x64 must be on before any tracing happens.
"""

import jax

jax.config.update("jax_enable_x64", True)
