"""Device tier: JAX/XLA kernels.

Device arrays use int32 LVs (a single document's op count is far below 2^31;
the host tier keeps full int64 LV space, and sentinel ids like UNDERWATER
never ship to device).
"""
