"""Batched multi-document op application (JAX/XLA).

The TPU-parallel analogue of the reference's single-doc linear replay bench
(reference: crates/bench/src/main.rs local/apply_*): instead of one document
applying ops one at a time, N replicas apply their op streams simultaneously —
`lax.scan` over op index, `vmap` over documents. This is the "batch/data
parallelism = vmap over many documents per chip" axis from SURVEY.md §2.9.

Document state is a fixed-capacity char-code buffer + length. One op step
(pos, del_len, ins_len, ins_chars) rebuilds the buffer:

    out(i) = doc(i)                for i <  pos
           = ins_chars(i - pos)    for pos <= i < pos + ins
           = doc(i - ins + del)    for i >= pos + ins     (tail shift)

The tail shift is deliberately NOT a dynamic gather: per-element gathers
with per-document indices hit the TPU's slow scatter/gather path (measured
~36x the cost of the whole scan step on this chip). Instead op lengths are
bounded by `max_ins` (encode_trace_ops splits longer inserts AND deletes),
so the shifted read is a select over the 2*max_ins+1 STATIC rolls of the
buffer and the insert writes unroll over max_ins static lanes — pure
elementwise ops the VPU streams at memory speed. Ops per document are
padded to a common count; zero-length ops are no-ops (shift 0 selects the
unrolled buffer).
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def encode_trace_ops(txns, max_ins: int):
    """Flatten a TestData-style patch list into dense op arrays, splitting
    long inserts into <= max_ins chunks. Returns (pos, dlen, ilen, chars)."""
    pos, dl, il, chars = [], [], [], []
    for txn in txns:
        for (p, d, ins) in txn:
            while d:  # split deletes to <= max_ins (bounded-shift contract)
                k = min(d, max_ins)
                pos.append(p)
                dl.append(k)
                il.append(0)
                chars.append([0] * max_ins)
                d -= k
            off = 0
            while off < len(ins):
                chunk = ins[off:off + max_ins]
                pos.append(p + off)
                dl.append(0)
                il.append(len(chunk))
                chars.append([ord(c) for c in chunk] + [0] * (max_ins - len(chunk)))
                off += len(chunk)
    return (np.asarray(pos, np.int32), np.asarray(dl, np.int32),
            np.asarray(il, np.int32),
            np.asarray(chars, np.int32).reshape(-1, max_ins))


def _apply_ops_batched(docs: jnp.ndarray, lens: jnp.ndarray,
                       pos: jnp.ndarray, dlen: jnp.ndarray,
                       ilen: jnp.ndarray, ins_chars: jnp.ndarray
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One op per document, whole batch at once: docs [b, cap], pos/dlen/
    ilen [b], ins_chars [b, max_ins]. Requires dlen <= max_ins and
    ilen <= max_ins (see module docstring — this is what keeps the tail
    shift a static-roll select instead of a slow dynamic gather)."""
    cap = docs.shape[1]
    mi = ins_chars.shape[1]
    idx = jnp.arange(cap, dtype=jnp.int32)
    shift = ilen - dlen
    out = docs  # shift == 0 case
    for s in range(-mi, mi + 1):
        if s == 0:
            continue
        out = jnp.where((shift == s)[:, None], jnp.roll(docs, s, axis=1),
                        out)
    for j in range(mi):  # insert lanes, static unroll
        lane = (idx[None, :] == pos[:, None] + j) & (j < ilen)[:, None]
        out = jnp.where(lane, ins_chars[:, j:j + 1], out)
    out = jnp.where(idx[None, :] < pos[:, None], docs, out)
    return out, lens + shift


def apply_op_step(doc: jnp.ndarray, doc_len: jnp.ndarray,
                  pos: jnp.ndarray, dlen: jnp.ndarray,
                  ilen: jnp.ndarray, ins_chars: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Single-document variant of _apply_ops_batched (same contract)."""
    docs, lens = _apply_ops_batched(
        doc[None], doc_len[None], pos[None], dlen[None], ilen[None],
        ins_chars[None])
    return docs[0], lens[0]


@partial(jax.jit, static_argnames=("cap",))
def replay_batch(pos: jnp.ndarray, dlen: jnp.ndarray, ilen: jnp.ndarray,
                 chars: jnp.ndarray, cap: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Replay [b, n] op streams into [b, cap] documents.

    pos/dlen/ilen: int32 [b, n]; chars: int32 [b, n, max_ins].
    CONTRACT: dlen and ilen must be <= max_ins (= chars.shape[-1]); split
    longer ops the way encode_trace_ops does. The kernel's tail shift is a
    select over the 2*max_ins+1 static rolls — an out-of-range shift would
    silently leave the buffer unshifted, so violations raise at trace time
    via the debug check below when jax debug checks are on, and corrupt
    deterministically otherwise (use encode_trace_ops and this cannot
    happen). Returns (docs [b, cap], lens [b]).
    """
    b = pos.shape[0]
    mi = chars.shape[-1]
    # Bounded-shift contract check: out-of-range ops are zeroed to no-ops
    # WITH a poisoned length (-1) so violations surface as an impossible
    # doc length instead of silently-wrong text.
    bad = (dlen > mi) | (ilen > mi)
    dlen = jnp.where(bad, 0, dlen)
    ilen = jnp.where(bad, 0, ilen)
    any_bad = jnp.any(bad)
    docs0 = jnp.zeros((b, cap), dtype=jnp.int32)
    lens0 = jnp.zeros((b,), dtype=jnp.int32)

    def step(carry, op):
        docs, lens = carry
        p, d, i, c = op
        docs, lens = _apply_ops_batched(docs, lens, p, d, i, c)
        return (docs, lens), None

    ops = (jnp.swapaxes(pos, 0, 1), jnp.swapaxes(dlen, 0, 1),
           jnp.swapaxes(ilen, 0, 1), jnp.swapaxes(chars, 0, 1))
    (docs, lens), _ = jax.lax.scan(step, (docs0, lens0), ops)
    return docs, jnp.where(any_bad, -1, lens)


def docs_to_strings(docs: np.ndarray, lens: np.ndarray) -> List[str]:
    return ["".join(chr(c) for c in row[:n]) for row, n in
            zip(np.asarray(docs), np.asarray(lens))]
