"""Batched multi-document op application (JAX/XLA).

The TPU-parallel analogue of the reference's single-doc linear replay bench
(reference: crates/bench/src/main.rs local/apply_*): instead of one document
applying ops one at a time, N replicas apply their op streams simultaneously —
`lax.scan` over op index, `vmap` over documents. This is the "batch/data
parallelism = vmap over many documents per chip" axis from SURVEY.md §2.9.

Document state is a fixed-capacity char-code buffer + length. One op step
(pos, del_len, ins_len, ins_chars) rebuilds the buffer with vectorized index
arithmetic (a gather), which XLA fuses into a single pass per step:

    src_idx(i) = i                 for i <  pos
               = i - ins + del     for i >= pos + ins   (tail shift)
    insert lane writes ins_chars at [pos, pos+ins)

Ops per document are padded to a common count; zero-length ops are no-ops.
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def encode_trace_ops(txns, max_ins: int):
    """Flatten a TestData-style patch list into dense op arrays, splitting
    long inserts into <= max_ins chunks. Returns (pos, dlen, ilen, chars)."""
    pos, dl, il, chars = [], [], [], []
    for txn in txns:
        for (p, d, ins) in txn:
            if d:
                pos.append(p)
                dl.append(d)
                il.append(0)
                chars.append([0] * max_ins)
            off = 0
            while off < len(ins):
                chunk = ins[off:off + max_ins]
                pos.append(p + off)
                dl.append(0)
                il.append(len(chunk))
                chars.append([ord(c) for c in chunk] + [0] * (max_ins - len(chunk)))
                off += len(chunk)
    return (np.asarray(pos, np.int32), np.asarray(dl, np.int32),
            np.asarray(il, np.int32),
            np.asarray(chars, np.int32).reshape(-1, max_ins))


def apply_op_step(doc: jnp.ndarray, doc_len: jnp.ndarray,
                  pos: jnp.ndarray, dlen: jnp.ndarray,
                  ilen: jnp.ndarray, ins_chars: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Apply one positional op to one document buffer. All args are traced
    scalars/vectors; `doc` is int32 [cap], `ins_chars` int32 [max_ins]."""
    cap = doc.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    shift = ilen - dlen
    # Where does each output slot read from?
    src = jnp.where(idx < pos, idx, idx - shift)
    in_insert = (idx >= pos) & (idx < pos + ilen)
    gathered = doc[jnp.clip(src, 0, cap - 1)]
    ins_vals = ins_chars[jnp.clip(idx - pos, 0, ins_chars.shape[0] - 1)]
    new_doc = jnp.where(in_insert, ins_vals, gathered)
    new_len = doc_len + shift
    # Zero-length op => no-op
    noop = (ilen == 0) & (dlen == 0)
    return (jnp.where(noop, doc, new_doc),
            jnp.where(noop, doc_len, new_len))


@partial(jax.jit, static_argnames=("cap",))
def replay_batch(pos: jnp.ndarray, dlen: jnp.ndarray, ilen: jnp.ndarray,
                 chars: jnp.ndarray, cap: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Replay [b, n] op streams into [b, cap] documents.

    pos/dlen/ilen: int32 [b, n]; chars: int32 [b, n, max_ins].
    Returns (docs [b, cap], lens [b]).
    """
    b = pos.shape[0]
    docs0 = jnp.zeros((b, cap), dtype=jnp.int32)
    lens0 = jnp.zeros((b,), dtype=jnp.int32)

    def step(carry, op):
        docs, lens = carry
        p, d, i, c = op
        docs, lens = jax.vmap(apply_op_step)(docs, lens, p, d, i, c)
        return (docs, lens), None

    ops = (jnp.swapaxes(pos, 0, 1), jnp.swapaxes(dlen, 0, 1),
           jnp.swapaxes(ilen, 0, 1), jnp.swapaxes(chars, 0, 1))
    (docs, lens), _ = jax.lax.scan(step, (docs0, lens0), ops)
    return docs, lens


def docs_to_strings(docs: np.ndarray, lens: np.ndarray) -> List[str]:
    return ["".join(chr(c) for c in row[:n]) for row, n in
            zip(np.asarray(docs), np.asarray(lens))]
