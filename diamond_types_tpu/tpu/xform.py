"""Device-resident tail transform — `plan_tail_device` for the serve path.

`FusedDocSession.plan_tail()` resolves every pending op's merge position
with the host tracker walk (`get_xf_operations_full`): one Python step
per op, serialized under the oplog guard — the stage ROADMAP item 2
calls the cap on every occupancy win. This module is the `listmerge_tpu`
replacement: the flush bucket's op tails become columnar DAG arrays
(listmerge/columnar.py) and the concurrent-order resolution runs on
device, batched over the bucket.

Division of labor (the merge_kernel prepare/checkout split):

  host   extract_tail(sess)        [under the oplog guard]
           one native transform -> tracker item runs + delete-target
           rows -> visibility-granular splits -> Fugue tree arrays
           (parent/side/keys) + old/new visible-length columns
  device resolve_positions(...)    [outside the oplog guard]
           fugue_linearize_jax order + position/peak/length prefix
           scans, vmapped over the bucket, pow2-padded shape classes
           with a locked jit cache (devprof family "xform")

Old-visibility is a pure LV THRESHOLD: a fused session's frontier is
always the oplog version at log length `synced_to` (set together under
the oplog guard), so `lv < synced_to  <=>  op causally <= frontier` —
no per-op reachability walk needed. `validate_prefix_frontier` proves
exactly that equivalence with the scatter-max DAG reachability kernel
(tpu/graph_kernels.py); the randomized parity tests run it, and
DT_XFORM_VALIDATE=1 turns it on per extract.

The edit script is emitted in DOCUMENT order (delete old-only runs,
insert new-only runs, positions = exclusive prefix sum of new visible
lengths), which reaches the same final text as the host's causal-order
script; `plan.new_len`/`max_len` describe THIS script, so the fused
replay fences (`adopt_results` length check) apply unchanged. Every
guard — empty conflict zone, reversed insert runs, missing arena
content, the Σold_vis == doc_len fence — falls back to the host
`plan_tail()` per document, never skipping a parity fence.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from ..listmerge.columnar import (TailColumns, UnsupportedTail,
                                  agent_key_columns, arena_offset_columns,
                                  export_tail_columns, old_delete_intervals,
                                  visibility_cuts)
from .flush_fuse import TailPlan, _empty_plan
from .linearize import (UNDERWATER, build_tree_np, fugue_linearize_jax,
                        resolve_pos_keys, split_runs_at_anchors)
from .merge_kernel import _pow2

INT32_MAX = np.iinfo(np.int32).max


@dataclass
class TailExtract:
    """Host half of one doc's device plan: Fugue tree arrays + visibility
    columns, self-contained (no oplog access needed after extraction, so
    the device half runs outside the oplog guard)."""
    parent: np.ndarray     # [k] int64, parent == k -> virtual root
    side: np.ndarray       # [k] int8
    key_pos: np.ndarray    # [k] int64
    key_agent: np.ndarray  # [k] int64
    key_seq: np.ndarray    # [k] int64
    old_vis: np.ndarray    # [k] int32 chars visible at the session frontier
    new_vis: np.ndarray    # [k] int32 chars visible after the merge
    aoff: np.ndarray       # [k] int64 insert-arena char offsets
    arena: np.ndarray      # int32 char codes (whole insert arena)
    doc_len: int
    max_ins: int
    frontier: Tuple[int, ...]
    synced_to: int

    @property
    def n(self) -> int:
        return len(self.parent)


def extract_tail(sess) -> Union[TailExtract, TailPlan]:
    """Host half of plan_tail_device for one FusedDocSession. Must be
    called under the oplog guard (native transform + column reads).

    Returns a TailExtract for the device resolver, or — when the tail is
    outside the device contract — the host `plan_tail()` result directly
    (the per-doc host fallback rung of the transform ladder)."""
    ol = sess.oplog
    if sess.synced_to >= len(ol):
        return sess.plan_tail()          # empty tail: host fast path
    try:
        cols = export_tail_columns(ol, sess.frontier)
    except UnsupportedTail:
        return sess.plan_tail()
    synced_to = len(ol)
    plen = len(cols.prefix)

    cuts = visibility_cuts(cols, sess.synced_to)
    s_ids, s_len, s_ol, s_orr, s_ev = split_runs_at_anchors(
        cols.ids, cols.ln, cols.ol, cols.orr, (cols.ev,), extra_cuts=cuts)
    agent, seq = agent_key_columns(ol, s_ids)
    parent, side, ka, ks, orr_run = build_tree_np(s_ids, s_len, s_ol, s_orr,
                                                  agent, seq)
    kp = resolve_pos_keys(parent, side, ka, ks, orr_run)

    uw = s_ids >= UNDERWATER
    uw_text = np.maximum(
        0, np.minimum(s_ids + s_len, UNDERWATER + plen) - s_ids)
    text_len = np.where(uw, uw_text, s_len)
    # new visibility: merged-to-union rule, identical to prepare_doc
    new_vis = np.where(s_ev != 0, 0, text_len)
    # old visibility: inserted at-or-before the session frontier (uw
    # spine, or lv under the threshold) and not deleted by an op under
    # the threshold. Runs are cut at every delete-target boundary and at
    # each straddling row's old/new split point, so coverage at the run
    # START decides the whole run.
    d0, d1 = old_delete_intervals(cols, sess.synced_to)
    cov = (np.searchsorted(np.sort(d0), s_ids, side="right")
           - np.searchsorted(np.sort(d1), s_ids, side="right"))
    old_ins = uw | (s_ids < sess.synced_to)
    old_vis = np.where(old_ins & (cov == 0), text_len, 0)

    if int(old_vis.sum(dtype=np.int64)) != sess.doc_len:
        # the transform's parity fence: our model of the resident text
        # disagrees with the session — never guess, host-plan instead
        return sess.plan_tail()
    aoff = arena_offset_columns(ol, np.where(uw, 0, s_ids))
    ins_run = (new_vis > 0) & (old_vis == 0)
    if (aoff[ins_run] < 0).any():
        return sess.plan_tail()          # insert without stored content
    if os.environ.get("DT_XFORM_VALIDATE"):
        assert validate_prefix_frontier(ol, sess.frontier, sess.synced_to), \
            "log-prefix-frontier contract violated (device reachability)"
    return TailExtract(
        parent=parent, side=side, key_pos=kp, key_agent=ka, key_seq=ks,
        old_vis=old_vis.astype(np.int32), new_vis=new_vis.astype(np.int32),
        aoff=aoff, arena=cols.arena, doc_len=sess.doc_len,
        max_ins=sess.max_ins, frontier=cols.union, synced_to=synced_to)


# ---------------------------------------------------------------------------
# device half: batched order + position resolution
# ---------------------------------------------------------------------------

_xform_jit_cache = {}
from ..analysis.witness import make_lock as _make_lock
_xform_jit_lock = _make_lock("xform_jit", "device")


def _xform_single(parent, side, kp, ka, ks, ov, nv, pallas: bool):
    import jax.numpy as jnp

    perm = fugue_linearize_jax(parent, side, kp, ka, ks)
    nvp = nv[perm]
    ovp = ov[perm]
    if pallas:
        from .pallas_kernels import xform_positions_pallas
        pos, new_len, peak = xform_positions_pallas(nvp, ovp)
    else:
        cum = jnp.cumsum(nvp)
        pos = (cum - nvp).astype(jnp.int32)
        delta = jnp.cumsum(nvp - ovp)
        new_len = cum[-1].astype(jnp.int32)
        peak = jnp.maximum(jnp.int32(0), jnp.max(delta)).astype(jnp.int32)
    return perm.astype(jnp.int32), pos, new_len, peak


def _xform_fn(b: int, n: int):
    """Jitted batched transform for `b` docs x `n` run slots — static
    pow2 shape classes, same O(log^2) cache discipline as `_fused_fn`.
    DT_TPU_PALLAS=1 routes the position-resolution scans through the
    gather-free Pallas kernel (batch unrolled: vmap-of-pallas_call would
    stack an illegal batch grid dim — see merge_kernel._jitted_kernel)."""
    import jax

    pallas = bool(os.environ.get("DT_TPU_PALLAS"))
    key = (b, n, pallas)
    with _xform_jit_lock:
        fn = _xform_jit_cache.get(key)
        from ..obs.devprof import note_jit_lookup
        note_jit_lookup("xform", fn is not None)
        if fn is not None:
            return fn
        if pallas:
            import jax.numpy as jnp
            single = partial(_xform_single, pallas=True)

            def run_all(*cols):
                outs = [single(*(c[i] for c in cols))
                        for i in range(cols[0].shape[0])]
                return tuple(jnp.stack([o[j] for o in outs])
                             for j in range(4))

            fn = jax.jit(run_all)
        else:
            fn = jax.jit(jax.vmap(partial(_xform_single, pallas=False)))
        _xform_jit_cache[key] = fn
        return fn


def xform_shape_class(extracts: Sequence[TailExtract]) -> Tuple[int, int]:
    """(b, n) jit-cache class a bucket of extracts compiles to."""
    b = len(extracts)
    return (_pow2(b) if b > 1 else 1,
            _pow2(max(max(ex.n for ex in extracts), 1)))


def resolve_positions(extracts: Sequence[TailExtract]
                      ) -> List[Optional[TailPlan]]:
    """Device half: resolve every extract's document order + positions in
    ONE batched dispatch, then assemble TailPlans host-side. Runs outside
    the oplog guard — extracts are self-contained.

    A doc whose device result fails the cross-check (device new_len vs
    the host visibility sum) comes back as None; the caller host-plans it
    under the oplog guard. Padding rows carry parent=root + INT32_MAX
    keys + zero visibility, so they linearize last and contribute no
    positions (the pad_docs convention)."""
    import jax.numpy as jnp

    if not extracts:
        return []
    bp, n = xform_shape_class(extracts)
    b = len(extracts)
    parent = np.full((bp, n), n, np.int32)
    side = np.ones((bp, n), np.int32)
    kp = np.full((bp, n), INT32_MAX, np.int32)
    ka = np.full((bp, n), INT32_MAX, np.int32)
    ks = np.full((bp, n), INT32_MAX, np.int32)
    ov = np.zeros((bp, n), np.int32)
    nv = np.zeros((bp, n), np.int32)
    for i, ex in enumerate(extracts):
        k = ex.n
        parent[i, :k] = np.where(ex.parent == k, n, ex.parent)
        side[i, :k] = ex.side
        kp[i, :k] = ex.key_pos
        ka[i, :k] = ex.key_agent
        ks[i, :k] = ex.key_seq
        ov[i, :k] = ex.old_vis
        nv[i, :k] = ex.new_vis
    from ..obs.devprof import note_transfer
    note_transfer(parent.nbytes * 5 + ov.nbytes + nv.nbytes)
    fn = _xform_fn(bp, n)
    perm_d, pos_d, len_d, peak_d = fn(*(jnp.asarray(x) for x in
                                        (parent, side, kp, ka, ks, ov, nv)))
    perm_d = np.asarray(perm_d)
    pos_d = np.asarray(pos_d)
    len_d = np.asarray(len_d)
    peak_d = np.asarray(peak_d)

    plans: List[Optional[TailPlan]] = []
    for i, ex in enumerate(extracts):
        try:
            plans.append(_assemble_plan(ex, perm_d[i], pos_d[i],
                                        int(len_d[i]), int(peak_d[i])))
        except Exception:
            plans.append(None)
    return plans


def _assemble_plan(ex: TailExtract, perm: np.ndarray, pos: np.ndarray,
                   new_len: int, peak: int) -> TailPlan:
    """Pack one doc's device-resolved order into TailPlan rows (doc-order
    edit script, ops chunked to max_ins like the host packer)."""
    if new_len != int(ex.new_vis.sum(dtype=np.int64)):
        raise AssertionError("device/host new-length disagreement")
    mi = ex.max_ins
    k = ex.n
    rows: List[Tuple[int, int, int, Optional[np.ndarray]]] = []
    for j in range(k):
        r = int(perm[j])
        ov_r = int(ex.old_vis[r])
        nv_r = int(ex.new_vis[r])
        if ov_r == nv_r:
            continue
        p = int(pos[j])
        if nv_r == 0:                      # delete the old-only run
            d = ov_r
            while d:
                step = min(d, mi)
                rows.append((p, step, 0, None))
                d -= step
        else:                              # insert the new-only run
            a = int(ex.aoff[r])
            off = 0
            while off < nv_r:
                step = min(nv_r - off, mi)
                rows.append((p + off, 0, step,
                             ex.arena[a + off:a + off + step]))
                off += step
    n_rows = len(rows)
    if n_rows == 0:
        return _empty_plan(ex.frontier, ex.synced_to, ex.doc_len, mi)
    pos_a = np.zeros(n_rows, np.int32)
    dl_a = np.zeros(n_rows, np.int32)
    il_a = np.zeros(n_rows, np.int32)
    ch_a = np.zeros((n_rows, mi), np.int32)
    for i, (p, d, il, ch) in enumerate(rows):
        pos_a[i] = p
        dl_a[i] = d
        il_a[i] = il
        if il:
            ch_a[i, :il] = ch
    return TailPlan(pos_a, dl_a, il_a, ch_a, n_rows, new_len,
                    ex.doc_len + peak, ex.frontier, ex.synced_to)


def plan_tails_device(sessions: Sequence, oplog_lock=None) -> Tuple[
        List[TailPlan], dict]:
    """plan_tail_device over a bucket: host extracts under the oplog
    guard, device resolves outside it, per-doc host fallback for guard
    trips. Returns (plans — one per session, never None — and a stats
    dict with the ServeMetrics transform-block counters)."""
    import contextlib
    guard = oplog_lock if oplog_lock is not None else contextlib.nullcontext()
    with guard:
        halves = [extract_tail(s) for s in sessions]
    extracts = [(i, h) for i, h in enumerate(halves)
                if isinstance(h, TailExtract)]
    stats = {"device_docs": 0, "host_docs": len(halves) - len(extracts),
             "fallbacks": 0, "batches": 1 if extracts else 0}
    plans: List[Optional[TailPlan]] = [
        h if isinstance(h, TailPlan) else None for h in halves]
    if extracts:
        resolved = resolve_positions([h for _, h in extracts])
        for (i, _), plan in zip(extracts, resolved):
            plans[i] = plan
    for i, plan in enumerate(plans):
        if plan is None:
            stats["fallbacks"] += 1
            with guard:
                plans[i] = sessions[i].plan_tail()
        elif isinstance(halves[i], TailExtract):
            stats["device_docs"] += 1
    return plans, stats


def validate_prefix_frontier(oplog, frontier: Sequence[int],
                             synced_to: int,
                             targets: Optional[np.ndarray] = None) -> bool:
    """Prove the log-prefix-frontier threshold with the device DAG
    reachability kernel: `lv < synced_to  <=>  frontier contains lv`,
    for every LV (or a caller-chosen sample). This is the property the
    transform's old-visibility column rests on."""
    import jax.numpy as jnp

    from .graph_kernels import frontier_contains_lv, pack_graph

    n = len(oplog)
    if n == 0:
        return int(synced_to) == 0
    packed = pack_graph(oplog.cg.graph)
    if targets is None:
        targets = np.arange(n, dtype=np.int32)
    fr = sorted(int(x) for x in frontier)
    fr_a = np.asarray(fr if fr else [-1], dtype=np.int32)
    got = np.asarray(frontier_contains_lv(packed, jnp.asarray(fr_a),
                                          jnp.asarray(targets)))
    want = np.asarray(targets) < int(synced_to)
    return bool((got == want).all())
