"""Device-resident incremental merge sessions.

The realtime pattern — a live document receiving a stream of small edits
from several peers, each merged immediately (reference hot path:
src/list/merge.rs:63-96) — must not pay a full document re-upload per
merge (VERDICT r2 next-step #4). A `DeviceZoneSession` keeps the zone
kernel's ENTIRE carry (state matrix, rank order, origin metadata, key
planes) resident on the device and treats each incremental merge as a
few more tape steps continued from that carry: the host ships only the
delta (the new entries' composed micro-tape, a handful of KB), and the
jitted step donates its input buffers so the state updates in place.

Row tracking: the session holds one state row per live branch head
(each peer's last version). A new run whose parents match tracked rows
applies directly (fork/max exactly like the plan compiler would); a run
anchored at an untracked version triggers `resync()` — a full rebuild
whose plan PINS a state row at each agent's head (plan2 pin_lvs), so
after one rebuild every active branch is tracked again. Slot capacity is
pre-allocated with headroom; growth also resyncs.

Everything reuses the zone kernel verbatim: the same step function, the
same tape schema, the same YjsMod semantics — a session is just a scan
whose xs arrive over time.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..listmerge.compose import compose_entry

from ..listmerge.zone_np import ZonePrep, prepare_zone
from .merge_kernel import _pow2
from .zone_kernel import (BIG32, OP_APPLY, OP_FORK, OP_MAX, ZoneTape,
                          _pad_tape_xs, auto_slice_steps, init_zone_carry,
                          make_zone_step, pack_zone_tape, slice_tape_xs)

_sess_jit_cache = {}


def _micro_fn(W: int, plen: int, n_rows: int, MB: int, MC: int, MD: int,
              T: int):
    """Jitted micro-tape continuation with donated carry buffers."""
    import jax

    key = (W, plen, n_rows, MB, MC, MD, T)
    fn = _sess_jit_cache.get(key)
    from ..obs.devprof import note_jit_lookup
    note_jit_lookup("micro", fn is not None)
    if fn is None:
        from jax import lax

        step = make_zone_step(W, plen, n_rows, MB, MC, MD)

        def run(carry, xs):
            final, _ = lax.scan(step, carry, xs)
            return final

        fn = jax.jit(run, donate_argnums=0)
        _sess_jit_cache[key] = fn
    return fn


_tip_jit_cache = {}


def _tip_row_fn(W: int, n_rows: int):
    """fn(carry, r): state[r] <- merged-tip visibility (1 = placed and
    never deleted, 2 = placed and deleted, 0 = unplaced)."""
    import jax

    # The 2-tuple key is deliberate: the tip-row builder has no tape
    # dims (no op batch to shape-specialise), and jit retraces per
    # carry shape anyway — the key only scopes the lookup for devprof
    # hit accounting.
    key = (W, n_rows)
    fn = _tip_jit_cache.get(key)  # dt-lint: ignore[jit-cache-key]
    from ..obs.devprof import note_jit_lookup
    note_jit_lookup("tip", fn is not None)
    if fn is None:
        import jax.numpy as jnp
        from jax import lax

        def build(carry, r):
            state, snap, rank, ordv, ol_id, orr_id, ever, m, ak, sk = carry
            row = jnp.where(rank < BIG32,
                            jnp.where(ever == 0, 1, 2), 0).astype(jnp.uint8)
            state = lax.dynamic_update_index_in_dim(
                state, row, jnp.clip(r, 0, n_rows - 1), 0)
            return (state, snap, rank, ordv, ol_id, orr_id, ever, m, ak, sk)

        fn = jax.jit(build, donate_argnums=0)
        _tip_jit_cache[key] = fn  # dt-lint: ignore[jit-cache-key]
    return fn


class DeviceZoneSession:
    """A live document resident on the device (see module docstring)."""

    def __init__(self, oplog, n_rows: int = 8, headroom: float = 2.0,
                 max_blocks: int = 4, max_chars: int = 256,
                 max_dels: int = 8, row_sharding=None):
        self.oplog = oplog
        self.n_rows = n_rows
        self.headroom = headroom
        self.MB, self.MC, self.MD = max_blocks, max_chars, max_dels
        # Multi-chip: a jax.sharding.NamedSharding for the version-row
        # axis of the session state — rows (tracked branches) spread over
        # the mesh; per-slot arrays are replicated. jit propagates the
        # placement through every micro-tape continuation, and donation
        # keeps it across syncs.
        self.row_sharding = row_sharding
        self.resyncs = -1          # first build counts up to 0
        self.merges = 0
        self._lru: Dict[Tuple[int, ...], int] = {}
        self._clock = 0
        self.resync()

    # ---- full (re)build --------------------------------------------------

    def resync(self) -> None:
        """Rebuild device state from scratch, pinning one state row per
        agent head so every active branch is immediately tracked."""
        import jax.numpy as jnp

        self.resyncs += 1
        ol = self.oplog
        # pin each agent's last version (if it lands in the zone)
        aa = ol.cg.agent_assignment
        heads: List[int] = []
        for agent in range(len(aa.agent_names)):
            last = aa.last_lv_of(agent) if hasattr(aa, "last_lv_of") else \
                self._agent_last_lv(agent)
            if last is not None:
                heads.append(last)
        prep = prepare_zone(ol, pin_lvs=tuple(heads))
        self.prep = prep
        W_cap = _pow2(max(int(prep.W * self.headroom), prep.W + 1024))
        n_rows = max(self.n_rows, prep.plan.indexes_used)
        if self.row_sharding is not None:
            # the sharded row axis must divide evenly over the mesh axes
            # named in its spec (a real corpus's plan can need any
            # number of index rows — e.g. friendsforever needs 12)
            m = 1
            spec0 = self.row_sharding.spec[0] \
                if len(self.row_sharding.spec) else None
            names = (spec0,) if isinstance(spec0, str) else (spec0 or ())
            for name in names:
                m *= int(self.row_sharding.mesh.shape[name])
            n_rows = ((n_rows + m - 1) // m) * m
        self.W_cap = W_cap
        self.plen = prep.plen

        self._agent_epoch = tuple(ol.cg.agent_assignment.agent_names)
        # growable host-side tables (slot map, pool, key arrays). The
        # run lists grow as PYTHON lists; the searchsorted arrays
        # regenerate lazily once per sync, not O(n) per appended run
        self._lv0_list = list(prep.ins_lv0)
        self._cum_list = list(prep.ins_cum)
        self._slot_arrays_dirty = True
        self.W_used = prep.W
        self.pool = np.zeros(W_cap, dtype=np.int32)
        self.pool[:prep.W] = prep.pool
        agent_k = np.zeros(W_cap, dtype=np.int32)
        seq_k = np.zeros(W_cap, dtype=np.int32)
        agent_k[:prep.W] = prep.agent_k
        seq_k[:prep.W] = prep.seq_k

        tape = pack_zone_tape(prep, self.MB, self.MC, self.MD)
        tape = self._retarget(tape, W_cap)
        carry = init_zone_carry(W_cap, prep.plen, n_rows, agent_k, seq_k)
        if self.row_sharding is not None:
            import jax
            carry = (jax.device_put(carry[0], self.row_sharding),) \
                + tuple(carry[1:])
        self.carry = self._run_tape(carry, tape, n_rows)

        # row registry: pinned agent-head rows + their frontiers
        self.row_of: Dict[Tuple[int, ...], int] = {}
        self.free_rows = set(range(n_rows))
        for lv, row in prep.plan.pinned_rows.items():
            self.row_of[(lv,)] = row
            self.free_rows.discard(row)
        self.n_rows_eff = n_rows
        self.synced_to = len(ol)
        self._lru.clear()          # stale frontiers died with the old rows
        self._keys_cache = None
        # always track the merged TIP as a row (derivable from rank/ever:
        # visible = placed and never deleted): linear histories have no
        # zone entries to pin, and most realtime ops parent on the tip
        tipkey = tuple(sorted(int(x) for x in ol.version))
        if tipkey and tipkey not in self.row_of and self.free_rows:
            r = min(self.free_rows)
            self.free_rows.discard(r)
            self.carry = _tip_row_fn(self.W_cap, self.n_rows_eff)(
                self.carry, r)
            self.row_of[tipkey] = r

    def _run_tape(self, carry, tape: ZoneTape, n_rows: int):
        """Execute `tape` on top of `carry`, with per-dispatch device
        time bounded on tpu (auto_slice_steps — per-step cost is
        ~linear in W x n_rows): the tunneled runtime kills any single
        program past ~60 s, which a grown session's resync tape — or a
        large sync() backlog (e.g. a bulk import appended onto a
        tracked head) — would cross as one whole-tape program. Pad
        steps are self-FORK no-ops, so the sliced and whole-tape paths
        are bit-identical (pinned by tests via DT_SESSION_SLICE: a
        positive value forces that slice length on any backend, 0
        forces whole-tape; empty/unset picks the backend default)."""
        import jax
        import jax.numpy as jnp

        sl_env = os.environ.get("DT_SESSION_SLICE")
        if sl_env:
            slice_steps = max(0, int(sl_env))
        else:
            slice_steps = (auto_slice_steps(tape, n_rows)
                           if jax.default_backend() == "tpu" else 0)
        T = tape.op.shape[0]
        if slice_steps and slice_steps < _pow2(T):
            S, xs_slices = slice_tape_xs(tape, slice_steps)
            fn = _micro_fn(tape.W, tape.plen, n_rows, self.MB, self.MC,
                           self.MD, S)
            for xs in xs_slices:
                carry = fn(carry, xs)
            return carry
        fn = _micro_fn(tape.W, tape.plen, n_rows, self.MB, self.MC,
                       self.MD, _pow2(T))
        padded = _pad_tape_xs(tape)
        from ..obs.devprof import PROFILER
        if PROFILER.enabled:   # host->device tape upload, one flush
            PROFILER.note_transfer(sum(int(np.asarray(v).nbytes)
                                       for v in padded.values()))
        xs = {k: jnp.asarray(v) for k, v in padded.items()}
        return fn(carry, xs)

    def _take_row(self, exclude) -> Optional[int]:
        """A free state row, evicting the least-recently-used tracked
        frontier when the pool is dry (an evicted frontier referenced
        later costs one resync — graceful degradation)."""
        if self.free_rows:
            r = min(self.free_rows)
            self.free_rows.discard(r)
            return r
        victims = [(self._lru.get(k, 0), k) for k, v in self.row_of.items()
                   if v not in exclude]
        if not victims:
            return None
        _, k = min(victims)
        r = self.row_of.pop(k)
        self._lru.pop(k, None)
        return r

    def _touch_key(self, key) -> None:
        self._clock += 1
        self._lru[key] = self._clock

    def _keys(self, lvs: np.ndarray):
        """(agent name rank, seq) per LV with the run tables cached per
        sync epoch — _agent_keys rebuilds them from scratch on every call,
        which is O(total history) per entry on the hot path."""
        aa = self.oplog.cg.agent_assignment
        gr = aa.global_runs
        cache = self._keys_cache
        if cache is None or cache[0] != len(gr):
            lv0 = np.asarray([r[0] for r in gr], dtype=np.int64)
            ag = np.asarray([r[2] for r in gr], dtype=np.int64)
            sq0 = np.asarray([r[3] for r in gr], dtype=np.int64)
            o = np.argsort(lv0)
            name_rank = np.asarray(np.argsort(np.argsort(aa.agent_names)))
            cache = (len(gr), lv0[o], ag[o], sq0[o], name_rank)
            self._keys_cache = cache
        _, lv0, ag, sq0, name_rank = cache
        lvs = np.asarray(lvs, dtype=np.int64)
        j = np.clip(np.searchsorted(lv0, lvs, side="right") - 1, 0,
                    len(lv0) - 1)
        return name_rank[ag[j]], sq0[j] + (lvs - lv0[j])

    def _agent_last_lv(self, agent: int) -> Optional[int]:
        aa = self.oplog.cg.agent_assignment
        best = None
        for (_lv0, lv_end, ag, _sq) in aa.global_runs:
            if ag == agent:
                end = lv_end - 1
                best = end if best is None or end > best else best
        return best

    def _retarget(self, tape: ZoneTape, W_cap: int) -> ZoneTape:
        """A tape packed for W slots runs unchanged at W_cap capacity
        (slot ids are absolute; only the padded width differs)."""
        tape.W = W_cap
        return tape

    # ---- incremental path ------------------------------------------------

    def _slot_of_lv(self, lvs: np.ndarray) -> np.ndarray:
        if self._slot_arrays_dirty:
            self.ins_lv0 = np.asarray(self._lv0_list, dtype=np.int64)
            self.ins_cum = np.asarray(self._cum_list, dtype=np.int64)
            self._slot_arrays_dirty = False
        j = np.searchsorted(self.ins_lv0, lvs, side="right") - 1
        return self.plen + self.ins_cum[j] + (lvs - self.ins_lv0[j])

    def _alloc_slots(self, entry_span) -> bool:
        """Extend the slot map/pool/keys with the entry's insert runs.
        Returns False when capacity would overflow (caller resyncs)."""
        from ..text.op import INS
        new = []
        for piece in self.oplog.ops.iter_range(entry_span):
            if piece.kind == INS:
                new.append((piece.lv, len(piece),
                            self.oplog.ops.content_slice(piece.lv,
                                                         len(piece))))
        total = sum(n for _, n, _ in new)
        if self.W_used + total > self.W_cap:
            return False
        for (lv, n, content) in new:
            slot0 = self.W_used
            self._lv0_list.append(lv)
            self._cum_list.append(slot0 - self.plen)
            self._slot_arrays_dirty = True
            arr = np.frombuffer(content.encode("utf-32-le"),
                                dtype=np.int32)
            self.pool[slot0:slot0 + n] = arr
            self.W_used += n
        return True

    def sync(self) -> int:
        """Fold every op appended to the oplog since the last sync into
        the device state. Returns the number of micro-steps executed
        (0 = nothing new). Resyncs transparently when needed."""
        import jax.numpy as jnp

        ol = self.oplog
        if self.synced_to >= len(ol):
            return 0
        # agent NAME RANKS are relative to the registered-name set; a new
        # agent shifts existing ranks, and the carry's key planes hold the
        # old epoch's ranks — rebuild before they can disagree
        if tuple(ol.cg.agent_assignment.agent_names) != self._agent_epoch:
            self.resync()
            return self.sync()
        g = ol.cg.graph
        # split the new span into entries (same-parents runs)
        steps: List[dict] = []
        lo = self.synced_to
        end = len(ol)
        spans: List[Tuple[int, int, Tuple[int, ...]]] = []
        v = lo
        while v < end:
            i = g.find_idx(v)
            take = min(end, g.ends[i])
            parents = tuple(g.parents_at(v)) if v == g.starts[i] \
                else (v - 1,)
            spans.append((v, take, parents))
            v = take

        for (s, e, parents) in spans:
            key = tuple(sorted(parents))
            # source rows: the exact frontier if tracked, else the
            # per-tip rows of a multi-parent frontier
            if key in self.row_of:
                srcs = [self.row_of[key]]
            else:
                srcs = [self.row_of.get((p,)) for p in sorted(parents)]
                if not srcs or any(r is None for r in srcs):
                    # untracked frontier — including parents == [] (a
                    # concurrent root-anchored op): rebuild
                    self.resync()
                    return self.sync()
            # apply on a FRESH row (fork + max joins): source rows stay
            # tracked — two branches forking the same frontier is the
            # normal realtime shape and must not force a rebuild
            row = self._take_row(exclude=set(srcs))
            if row is None or not self._alloc_slots((s, e)):
                self.resync()
                return self.sync()
            pre_ops = [(OP_FORK, srcs[0], row)] + \
                [(OP_MAX, r, row) for r in srcs[1:]]
            ce = compose_entry(ol, (s, e))
            steps.extend(self._pack_entry(ce, row, pre_ops))
            self.row_of[(e - 1,)] = row
            self._touch_key((e - 1,))

        if steps:
            tape = self._steps_to_tape(steps)
            self.carry = self._run_tape(self.carry, tape,
                                        self.n_rows_eff)
            self.merges += 1
        self.synced_to = end
        return len(steps)

    def _pack_entry(self, ce, row: int, pre_ops: List[tuple]
                    ) -> List[dict]:
        """Entry -> micro-steps via the SAME packer as whole documents
        (zone_kernel.entry_steps), against the session's growable slot
        map and live agent-key resolution."""
        from .zone_kernel import entry_steps
        steps: List[dict] = []
        for (op, a, b) in pre_ops:
            steps.append(dict(op=op, a=a, b=b, snap=0, blocks=[],
                              chars=[], dels=[], n_chars=0))
        cur = dict(op=OP_APPLY, a=row, b=0, snap=1, blocks=[], chars=[],
                   dels=[], n_chars=0)
        steps.append(cur)

        def next_sub():
            s = dict(op=OP_APPLY, a=row, b=0, snap=0, blocks=[],
                     chars=[], dels=[], n_chars=0)
            steps.append(s)
            return s

        entry_steps(ce, self._slot_of_lv, self._keys, None,
                    self.MB, self.MC, self.MD, cur, next_sub)
        return steps

    def _steps_to_tape(self, steps: List[dict]) -> ZoneTape:
        from .zone_kernel import _fill_tape
        return _fill_tape(steps, self.W_cap, self.plen, self.n_rows_eff,
                          self.pool[:self.W_used], self.MB, self.MC,
                          self.MD)

    # ---- reads -----------------------------------------------------------

    def text(self) -> str:
        """Fetch and assemble the merged document."""
        rank = np.asarray(self.carry[2])
        ever = np.asarray(self.carry[6])
        live = int((rank < int(BIG32)).sum())
        order = np.argsort(rank, kind="stable")[:live]
        vis = ever[order] == 0
        return self.pool[order[vis]].astype(np.int32).tobytes() \
            .decode("utf-32-le")

    def touch(self):
        """Force completion of pending device work with a tiny transfer
        (per-merge latency benches time sync()+touch())."""
        return np.asarray(self.carry[7])   # m: a scalar

    def footprint_slots(self) -> int:
        """Device-residency cost of this session in int32 slots, for the
        serve/ bank's capacity accounting: the state matrix dominates
        (n_rows x W_cap), plus the per-slot planes (rank, order, origin
        ids x2, ever, agent key, seq key — 7 more W_cap vectors). Host
        pool/key tables are not counted; the budget models the chip."""
        return int(self.W_cap) * (int(self.n_rows_eff) + 7)
