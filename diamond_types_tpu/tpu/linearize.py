"""Fugue-tree linearization — the parallel formulation of YjsMod integrate.

The reference resolves concurrent-insert order with a sequential scan per
insert (reference: src/listmerge/merge.rs:154-278 `integrate`, the YjsMod /
FugueMax algorithm). That scan is the part of the merge engine a TPU cannot
express directly: it is data-dependent, early-exiting control flow.

This module re-expresses the SAME total order as a static tree computation
(the Fugue construction: every item becomes a left child of its right
origin or a right child of its left origin; the document is the DFS of
that tree). Tree construction, sibling ordering, and the DFS linearization
are all sorts + segment scans — exactly the shapes XLA runs well — so the
whole-history merge order for thousands of concurrent items is computed in
a handful of parallel primitives instead of one scan per item.

Inputs are RLE runs (id-consecutive items sharing origins/state, the
tracker's native granularity):

    ids[i]   first LV of run i  (underwater ids >= 1<<62 are pre-zone text)
    length[i] run length (items)
    ol[i]    origin-left:  LV of the item immediately left at insert time,
             or -1 (document start)
    orr[i]   origin-right: LV of the next item at-or-right at insert time,
             or -1 (document end)
    agent[i] tie-break rank of the inserting agent — rank of the agent's
             NAME in sorted order (reference tie-breaks by name:
             agent_assignment/mod.rs:163 tie_break_agent_versions)
    seq[i]   agent-local sequence number of the run's first item

The host supplies origins (extracted by the tracker during its walk — the
"CPU-side position index stays host-side" split from BASELINE.json); this
module owns everything after that point.

Validation: `tests/test_linearize.py` checks the produced order is
IDENTICAL to the native tracker's document order (dt_dump_tracker) on the
shipped corpora and on randomized concurrent fuzz documents.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ..native.core import UNDERWATER

ROOT = -1

# ---------------------------------------------------------------------------
# host-side preparation: split runs so every anchor is a run endpoint
# ---------------------------------------------------------------------------


def split_runs_at_anchors(ids: np.ndarray, length: np.ndarray,
                          ol: np.ndarray, orr: np.ndarray,
                          extra: Tuple[np.ndarray, ...] = (),
                          extra_cuts: np.ndarray | None = None
                          ) -> Tuple[np.ndarray, ...]:
    """Split RLE runs so that every origin-left lands on a run's LAST item
    and every origin-right on a run's FIRST item. After this pass the tree
    is a pure run-level structure (no intra-run anchors).

    `extra` arrays (e.g. state) are split alongside; items inside a run are
    id-consecutive so a split at offset k gives (ids, k) + (ids+k, len-k)
    with the right half chained: ol = ids+k-1, orr = original orr... the
    right half keeps the SAME orr only if it was the run's trailing part;
    mid-run items' effective right origin within a run is the next item of
    the run itself, which stays adjacent — the chain ol encodes that.

    `extra_cuts` adds caller-chosen item-id cut points (the device
    transform cuts at the old/new LV threshold and at delete-target
    boundaries so per-run visibility is all-or-nothing). Extra cuts
    produce chained pieces exactly like anchor cuts, so they refine the
    run granularity without changing the linearization.
    """
    ends = ids + length
    # cut points: after every referenced ol (ol+1), and at every orr
    cuts = np.concatenate(
        [ol[ol != ROOT] + 1, orr[orr != ROOT]]
        + ([np.asarray(extra_cuts, dtype=ids.dtype)]
           if extra_cuts is not None and len(extra_cuts) else []))
    cuts = np.unique(cuts)
    # map each cut to the run containing it strictly inside (start < cut < end)
    order = np.argsort(ids, kind="stable")
    sids = ids[order]
    run_of = np.searchsorted(sids, cuts, side="right") - 1
    valid = (run_of >= 0)
    run_of = np.clip(run_of, 0, len(sids) - 1)
    inside = valid & (cuts > sids[run_of]) & (cuts < (sids + length[order])[run_of])
    cuts = cuts[inside]
    run_idx = order[run_of[inside]]  # original index of run to split

    # vectorized piece emission, grouped by run (ascending), cuts
    # ascending within each run
    n = len(ids)
    counts = np.bincount(run_idx, minlength=n) + 1
    out_n = int(counts.sum())
    offs = np.cumsum(counts) - counts          # first piece of each run
    last = offs + counts - 1                   # last piece of each run
    run_of_piece = np.repeat(np.arange(n), counts)

    cut_order = np.lexsort((cuts, run_idx))
    cuts_sorted = cuts[cut_order]

    is_first = np.zeros(out_n, dtype=bool)
    is_first[offs] = True
    new_ids = np.empty(out_n, dtype=np.int64)
    new_ids[offs] = ids
    new_ids[~is_first] = cuts_sorted           # (run, cut) order matches
    new_end = np.empty(out_n, dtype=np.int64)
    if out_n > 1:
        new_end[:-1] = new_ids[1:]             # next piece's start...
    new_end[last] = ends                       # ...except at run ends
    new_len = new_end - new_ids
    new_ol = np.where(is_first, ol[run_of_piece], new_ids - 1)
    new_orr = orr[run_of_piece]
    new_extra = tuple(e[run_of_piece] for e in extra)
    return (new_ids, new_len, new_ol, new_orr) + new_extra


# ---------------------------------------------------------------------------
# numpy reference linearizer
# ---------------------------------------------------------------------------


def _doc_order_np(parent: np.ndarray, side: np.ndarray, key_pos: np.ndarray,
                  key_agent: np.ndarray, key_seq: np.ndarray) -> np.ndarray:
    """DFS of the Fugue tree (parent == n is the virtual root) under the
    sibling sort (key_pos, key_agent, key_seq). Host-side mirror of
    fugue_linearize_jax."""
    n = len(parent)
    order = np.lexsort((key_seq, key_agent, key_pos, side, parent))

    from collections import defaultdict
    kids_left = defaultdict(list)
    kids_right = defaultdict(list)
    for i in order:
        (kids_left if side[i] == 0 else kids_right)[int(parent[i])].append(i)

    out = np.empty(n, dtype=np.int64)
    w = 0
    # iterative DFS: (node, phase) — phase 0 = emit left kids, 1 = self+right
    stack = [(n, 0)]
    while stack:
        node, phase = stack.pop()
        if phase == 0:
            stack.append((node, 1))
            for c in reversed(kids_left.get(node, ())):
                stack.append((c, 0))
        else:
            if node < n:
                out[w] = node
                w += 1
            for c in reversed(kids_right.get(node, ())):
                stack.append((c, 0))
    assert w == n
    return out


def resolve_pos_keys(parent: np.ndarray, side: np.ndarray,
                     key_agent: np.ndarray, key_seq: np.ndarray,
                     orr_run: np.ndarray, max_rounds: int = 64) -> np.ndarray:
    """Right-origin position sort key per run (the YjsMod `scanning` rule,
    reference merge.rs:230-242: same-left-origin concurrent siblings order
    by right-origin DOCUMENT POSITION, descending, before the agent
    tie-break).

    Returned key is ascending-sorts-first: `n - rank(orr)` so a farther
    right origin gives a smaller key; ROOT (document end — the farthest
    possible right origin) and underwater runs get 0.

    The key depends on the document order, which depends on the key — but
    the recursion is well-founded: the order of a sibling pair (u, v)
    depends only on the order of their right-origin targets, both of which
    have strictly smaller LVs (origins causally precede their items), so
    iterating order → keys → order converges stratum by stratum. Almost
    every document converges in 0 rounds (no same-(parent, side) sibling
    group has heterogeneous right origins) or 2 (compute + verify)."""
    n = len(parent)
    key_pos = np.zeros(n, dtype=np.int64)
    if n == 0:
        return key_pos
    # fast path: if every (parent, side) sibling group shares one orr_run,
    # the key ties inside every group and cannot affect the order
    grp = parent.astype(np.int64) * 2 + side
    o = np.lexsort((orr_run, grp))
    gs, rs = grp[o], orr_run[o]
    if not ((gs[1:] == gs[:-1]) & (rs[1:] != rs[:-1])).any():
        return key_pos
    for _ in range(max_rounds):
        out = _doc_order_np(parent, side, key_pos, key_agent, key_seq)
        rank = np.empty(n, dtype=np.int64)
        rank[out] = np.arange(n)
        new = np.where(orr_run >= 0, n - rank[np.clip(orr_run, 0, n - 1)], 0)
        if (new == key_pos).all():
            return key_pos
        key_pos = new
    raise AssertionError("right-origin position keys did not converge")


def fugue_order_np(ids: np.ndarray, length: np.ndarray, ol: np.ndarray,
                   orr: np.ndarray, agent: np.ndarray, seq: np.ndarray
                   ) -> np.ndarray:
    """Return the permutation of run indices giving document order.

    Precondition: runs are anchor-split (split_runs_at_anchors) — every ol
    is some run's last item, every orr some run's first item.

    Tree rules (== YjsMod; validated vs the native tracker on corpora +
    cross-sync fuzz):
      * parent/side: run x is a LEFT child of the run starting at orr(x)
        when that run shares x's left origin (same insertion gap — the
        "b.leftOrigin == a" Fugue condition); otherwise x is a RIGHT child
        of the run whose last item is ol(x) (ol == ROOT → right child of
        the virtual root).
      * Same-(parent, side) siblings sort by the YjsMod order: right-origin
        document position DESCENDING (reference merge.rs:230-242, the
        `scanning` branch), then (agent rank, seq) ascending. The position
        rank is well-defined before the full order is known because the
        relative order of two existing items never changes as later items
        are inserted between them; `resolve_pos_keys` computes it by a
        (rarely needed) fixed point.
    Soundness of the flat sibling ordering: a sibling's right origin can
    never point strictly inside another sibling's subtree. origin_right is
    the immediate tracker successor skipping only NOT_INSERTED_YET items
    (reference merge.rs:407-424) — any item between the insertion gap and
    a deeper target would have to be NIY (concurrent), yet it causally
    precedes the target (origins precede items), which causally precedes
    the new item: contradiction. The only reachable interior targets are
    the left spine of the next subtree, whose members share the new item's
    origin-left, so the LEFT-child rule routes those exactly.
    """
    parent, side, key_agent, key_seq, orr_run = build_tree_np(
        ids, length, ol, orr, agent, seq)
    key_pos = resolve_pos_keys(parent, side, key_agent, key_seq, orr_run)
    return _doc_order_np(parent, side, key_pos, key_agent, key_seq)


# ---------------------------------------------------------------------------
# host-side tree construction (vectorized; feeds the device kernel)
# ---------------------------------------------------------------------------


def build_tree_np(ids: np.ndarray, length: np.ndarray, ol: np.ndarray,
                  orr: np.ndarray, agent: np.ndarray, seq: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray,
                             np.ndarray]:
    """Vectorized parent/side/key computation for anchor-split runs.

    Returns (parent, side, key_agent, key_seq, orr_run); parent == n means
    the virtual root (index n). orr_run maps each run's origin-right LV to
    the index of the run starting at that LV, or -1 for ROOT (document
    end) and for underwater runs (the fixed pre-zone spine takes no part
    in right-origin ordering) — the input resolve_pos_keys needs."""
    n = len(ids)
    ends = ids + length
    order_s = np.argsort(ids, kind="stable")
    sorted_starts = ids[order_s]
    order_e = np.argsort(ends, kind="stable")
    sorted_ends = ends[order_e]

    def run_starting(lv):
        j = np.searchsorted(sorted_starts, lv)
        jj = np.clip(j, 0, n - 1)
        hit = (j < n) & (sorted_starts[jj] == lv)
        return np.where(hit, order_s[jj], -2)

    def run_ending(lv):
        j = np.searchsorted(sorted_ends, lv + 1)
        jj = np.clip(j, 0, n - 1)
        hit = (j < n) & (sorted_ends[jj] == lv + 1)
        return np.where(hit, order_e[jj], -2)

    uw = ids >= UNDERWATER
    r = np.where(orr != ROOT, run_starting(orr), -2)
    assert ((r >= 0) | (orr == ROOT)).all(), "unsplit orr anchor"
    orr_run = np.where(uw | (r < 0), -1, r).astype(np.int64)
    r_ok = (r >= 0) & (ol[np.clip(r, 0, n - 1)] == ol) & ~uw
    p_right = np.where(ol == ROOT, n, run_ending(ol))
    parent = np.where(uw, n, np.where(r_ok, r, p_right)).astype(np.int64)
    side = np.where(uw, 1, np.where(r_ok, 0, 1)).astype(np.int8)
    key_agent = np.where(uw, -1, agent).astype(np.int64)
    # underwater sort key: RANK among underwater ids (their absolute ids
    # exceed int32; only the relative order matters — ids ascend with
    # document position)
    uw_sorted = np.sort(ids[uw])
    uw_rank = np.searchsorted(uw_sorted, ids)
    key_seq = np.where(uw, uw_rank, seq).astype(np.int64)
    # the device kernel runs in int32 and pad_docs marks padding rows with
    # INT32_MAX: real keys must stay strictly below it (fail loudly rather
    # than silently mis-sorting)
    assert (key_seq.max(initial=0) < 2**31 - 1
            and key_agent.max(initial=0) < 2**31 - 1)
    assert (parent >= 0).all(), "unsplit anchor"
    return parent, side, key_agent, key_seq, orr_run


# ---------------------------------------------------------------------------
# device linearizer (JAX): sibling sort + threaded tour + list ranking
# ---------------------------------------------------------------------------


def fugue_linearize_jax(parent, side, key_pos, key_agent, key_seq):
    """Document-order permutation of n tree nodes on device.

    All inputs are int arrays of length n (parent == n denotes the virtual
    root). key_pos is the right-origin position key from resolve_pos_keys
    (YjsMod orders same-gap siblings by right-origin position before the
    agent tie-break). Returns perm [n]: node indices in document order.
    Padding nodes should carry parent == n, side == 1, and INT_MAX-ish
    key_pos/key_agent so they sort to the end of the document.

    Pure sorts/gathers/scans — no data-dependent control flow. The DFS is
    computed via a threaded Euler tour (3 cells per node: pre, visit,
    post) ranked by pointer jumping in ceil(log2(3n+3)) rounds.
    """
    import jax.numpy as jnp
    from jax import lax

    n = parent.shape[0]
    root = n

    # sibling order: (parent, side, key_pos, key_agent, key_seq)
    sort_idx = jnp.lexsort((key_seq, key_agent, key_pos,
                            side.astype(jnp.int32), parent))
    p_s = parent[sort_idx]
    s_s = side[sort_idx].astype(jnp.int32)
    grp = p_s * 2 + s_s
    # next sibling within the group; -1 at group end
    nxt = jnp.where(
        (jnp.arange(n) < n - 1) & (grp == jnp.roll(grp, -1)),
        jnp.roll(sort_idx, -1), -1)
    next_sib = jnp.zeros(n, dtype=jnp.int32).at[sort_idx].set(nxt)
    # first child per (node, side) via group-head scatter; non-heads are
    # routed to a dedicated overflow slot so no real slot gets clobbered
    is_head = jnp.concatenate([jnp.array([True]),
                               grp[1:] != grp[:-1]]) if n else jnp.zeros(0, bool)
    first = jnp.full(((n + 1) * 2 + 1,), -1, dtype=jnp.int32)
    first = first.at[jnp.where(is_head, grp, (n + 1) * 2)].set(
        jnp.where(is_head, sort_idx, -1), mode="drop")
    first_left = first[jnp.arange(n + 1) * 2]
    first_right = first[jnp.arange(n + 1) * 2 + 1]

    # cells: pre(x)=x, visit(x)=N+x, post(x)=2N+x for x in 0..n (incl root)
    N = n + 1
    idx = jnp.arange(N)
    succ_pre = jnp.where(first_left >= 0, first_left, N + idx)
    succ_visit = jnp.where(first_right >= 0, first_right, 2 * N + idx)
    # post(c): next sibling's pre, else visit(parent) [left] / post(parent)
    parent_full = jnp.concatenate(
        [parent, jnp.array([root], dtype=parent.dtype)])
    side_full = jnp.concatenate(
        [side.astype(jnp.int32), jnp.array([1], dtype=jnp.int32)])
    next_sib_full = jnp.concatenate(
        [next_sib, jnp.array([-1], dtype=jnp.int32)])
    up = jnp.where(side_full == 0, N + parent_full, 2 * N + parent_full)
    succ_post = jnp.where(next_sib_full >= 0, next_sib_full, up)
    succ_post = succ_post.at[root].set(-1)  # end of tour
    succ = jnp.concatenate([succ_pre, succ_visit, succ_post])

    # list ranking by pointer jumping: dist = #cells strictly after me
    dist = jnp.where(succ >= 0, 1, 0)
    n_rounds = max(1, int(np.ceil(np.log2(3 * N))) + 1)

    def body(_, carry):
        dist, succ = carry
        sc = jnp.clip(succ, 0, 3 * N - 1)
        dist2 = dist + jnp.where(succ >= 0, dist[sc], 0)
        succ2 = jnp.where(succ >= 0, succ[sc], -1)
        return dist2, succ2

    dist, _ = lax.fori_loop(0, n_rounds, body, (dist, succ))
    # visit-cell position from head = total - 1 - dist
    visit_rank = (3 * N - 1) - dist[N:N + n]  # item nodes only (root excl.)
    return jnp.argsort(visit_rank)


def materialize_jax(perm, vis_len, arena_off, arena, cap: int):
    """Assemble the visible document text on device.

    perm [n]: document-order permutation; vis_len [n]: visible char count
    of each run (0 for deleted/NIY/padding); arena_off [n]: first char of
    the run's content in `arena` (int32 char codes); cap: static output
    size. Returns (text [cap] int32, total_len).

    Run expansion avoids per-output-char searchsorted + double gathers
    (the TPU gather slow path): each live run parks its start position and
    its affine src base (`arena start - doc start`) AT its start slot; a
    plain cummax fills the monotone starts forward, leaving one gather for
    the base and one for the actual text."""
    import jax.numpy as jnp
    from jax import lax

    vl = vis_len[perm]
    cum = jnp.cumsum(vl)
    total = cum[-1] if vl.shape[0] else jnp.int64(0)
    starts = cum - vl
    base = arena_off[perm] - starts          # src[j] = base[run(j)] + j
    cs = jnp.clip(starts, 0, cap - 1)
    # Runs starting at/after cap can never contribute an output char; keep
    # them out of the scatter or they'd collide into slot cap-1 and corrupt
    # a truncated (cap < total) materialization.
    live = (vl > 0) & (starts < cap)
    S = jnp.zeros(cap, jnp.int32).at[cs].max(
        jnp.where(live, starts, 0).astype(jnp.int32))
    S = lax.associative_scan(jnp.maximum, S)
    BIAS = jnp.int32(1) << 30                # keeps parked bases >= 0
    parked = jnp.zeros(cap, jnp.int32).at[cs].max(
        jnp.where(live, base + BIAS, 0).astype(jnp.int32))
    j = jnp.arange(cap, dtype=jnp.int32)
    src = parked[S] - BIAS + j
    text = arena[jnp.clip(src, 0, arena.shape[0] - 1)]
    return jnp.where(j < total, text, 0), total
