"""Dense causal-graph kernels (JAX/XLA).

The host causal graph (diamond_types_tpu.causalgraph.graph) exports its RLE
time-DAG as columnar arrays. These kernels re-express the reference's
heap-walk DAG queries (reference: src/causalgraph/graph/tools.rs —
frontier_contains_version, diff) as *scatter-max fixed-point propagation*
over the dense entry table.

Key observation: within an RLE run, ancestry is linear — if LV x of a run is
an ancestor of a frontier, so is every earlier LV of the run. So per-run
reachability is a single integer `reach[e]` = highest LV of run `e` known to
be an ancestor (-1 = none). One sweep relaxes every run in parallel:

    active runs (reach >= start) push their first-LV parents p as
    reach[run(p)] = max(reach[run(p)], p)

`lax.while_loop` iterates to a fixed point; sweeps = DAG depth in run-hops,
with every run relaxed in parallel per sweep (the MXU-friendly formulation of
the reference's one-pop-at-a-time BinaryHeap walk). All shapes static;
vmappable over query batches; shardable over a device mesh
(diamond_types_tpu.parallel.mesh).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp


def pack_graph(graph) -> dict:
    """Export a host Graph into CSR edge arrays (fully vectorized).

    Edge-parallel layout: one row per (run, parent) edge. A 10k-way
    fan-in merge is 10k edge rows — NOT a [n, 10k] padded parent matrix
    (the round-1 dense layout could not scale to BASELINE config 5).
    Device math is int32; LV bounds are validated here, loudly."""
    starts, ends, shadows, indptr, flat = graph.as_arrays()
    n = len(starts)
    assert ends.max(initial=0) < 2**31 - 1, \
        "graph LVs exceed int32 device math — widen the kernels first"
    counts = np.diff(indptr)
    m = int(flat.shape[0])
    src = np.repeat(np.arange(n, dtype=np.int32), counts)
    plv = flat.astype(np.int32)
    prun = (np.searchsorted(starts, flat, side="right") - 1).astype(np.int32)
    return {
        "starts": jnp.asarray(starts.astype(np.int32)),
        "ends": jnp.asarray(ends.astype(np.int32)),
        "edge_src": jnp.asarray(src),    # [m] run owning the edge
        "edge_plv": jnp.asarray(plv),    # [m] parent LV
        "edge_prun": jnp.asarray(prun),  # [m] run containing the parent
        "n": n,
        "m": m,
    }


def _entry_of(starts: jnp.ndarray, lv: jnp.ndarray) -> jnp.ndarray:
    return jnp.searchsorted(starts, lv, side="right") - 1


def reach_fixed_point(packed: dict, reach0: jnp.ndarray) -> jnp.ndarray:
    """Propagate per-run coverage to a fixed point.

    reach0: int64 [n], highest directly-named LV per run (-1 none).
    Returns reach: highest LV of each run that is an ancestor of the seed set.
    """
    starts = packed["starts"]
    src = packed["edge_src"]        # [m]
    plv = packed["edge_plv"]        # [m]
    prun = packed["edge_prun"]      # [m]
    n = packed["n"]

    def body(state):
        reach, _ = state
        active = (reach >= starts)[src]                # [m]
        contrib = jnp.where(active, plv, -1)
        tgt = jnp.where(active, prun, jnp.int32(n))
        new_reach = reach.at[tgt].max(contrib, mode="drop")
        return new_reach, jnp.any(new_reach != reach)

    reach, _ = jax.lax.while_loop(
        lambda s: s[1], body, (reach0, jnp.array(True)))
    return reach


def seed_from_frontier(packed: dict, frontier_lvs: jnp.ndarray) -> jnp.ndarray:
    """Build reach0 from a padded (-1) frontier LV vector."""
    starts = packed["starts"]
    n = packed["n"]
    valid = frontier_lvs >= 0
    ent = jnp.where(valid, _entry_of(starts, jnp.maximum(frontier_lvs, 0)),
                    jnp.int32(n))
    reach0 = jnp.full((n,), -1, dtype=jnp.int32)
    return reach0.at[ent].max(jnp.where(valid, frontier_lvs, -1), mode="drop")


def frontier_contains_lv(packed: dict, frontier_lvs: jnp.ndarray,
                         target_lv: jnp.ndarray) -> jnp.ndarray:
    """Device analogue of frontier_contains_version (graph/tools.rs:88-146)."""
    reach = reach_fixed_point(packed, seed_from_frontier(packed, frontier_lvs))
    te = _entry_of(packed["starts"], jnp.maximum(target_lv, 0))
    return (target_lv < 0) | (reach[te] >= target_lv)


def diff_masks(packed: dict, a_lvs: jnp.ndarray, b_lvs: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-run coverage for a's and b's histories. The host converts the two
    reach vectors into (only_a, only_b) span lists by comparing coverage
    (device analogue of graph/tools.rs diff)."""
    ra = reach_fixed_point(packed, seed_from_frontier(packed, a_lvs))
    rb = reach_fixed_point(packed, seed_from_frontier(packed, b_lvs))
    return ra, rb


def make_contains_fn(graph):
    """Pack once; return a jitted batched containment query."""
    packed = pack_graph(graph)

    @jax.jit
    def contains(frontiers: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
        return jax.vmap(
            lambda f, t: frontier_contains_lv(packed, f, t))(frontiers, targets)

    return contains


def make_diff_fn(graph):
    packed = pack_graph(graph)

    @jax.jit
    def diff(a: jnp.ndarray, b: jnp.ndarray):
        return diff_masks(packed, a, b)

    return diff


def reach_to_spans(graph, reach: np.ndarray):
    """Host-side: convert a reach vector into ascending covered spans."""
    out = []
    for i in range(len(graph.starts)):
        r = int(reach[i])
        if r >= graph.starts[i]:
            s = (graph.starts[i], r + 1)
            if out and out[-1][1] == s[0]:
                out[-1] = (out[-1][0], s[1])
            else:
                out.append(s)
    return out
