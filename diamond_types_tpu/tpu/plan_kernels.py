"""Device execution of fork/join merge plans (listmerge2 on TPU).

Lowers the dense state-matrix executor (listmerge/dense.py) to JAX: the
whole fork/join schedule — Begin/Fork/Max column ops plus every Apply's
journaled state writes — runs as ONE `lax.scan` over a flat step tape,
evolving the dense [n_slots, n_indexes] state matrix on device and
snapshotting requested version rows along the way.

Two device capabilities fall out of the state rows:

  * **Batched time travel** — `texts_at_versions` materializes the document
    at MANY historical versions in one vmapped device call (the reference
    can only `checkout(version)` one at a time, rebuilding a tracker per
    call — src/list/oplog.rs:32). A version's document is just
    "final order, filtered to row==1" — the CRDT convergence property
    makes every historical doc a mask over one shared linearization.
  * **Batched origin resolution** — `origin_query_jax` answers the
    position->-(origin_left, origin_right) queries of YjsMod integrate
    (reference: merge.rs:395-423) for whole batches of concurrent inserts
    with two prefix-sums and a suffix scan, replacing the M1 engine's
    per-op tree walks for wide fan-in zones (the 10k-replica north star),
    where every branch's first run queries its parent-version row.

The step tape is int32-only: slots are addressed by their rank in id-sorted
order (underwater ids are >= 1<<62 and stay host-side). Journal writes are
item-id RANGES captured at write time, which makes split inheritance
disappear: a later split only refines slots inside an already-written
range, and states are monotone (this engine never retreats), so range-max
replay over the FINAL slot table reproduces every intermediate row exactly.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.span import UNDERWATER_START
from ..listmerge.dense import DenseExecutor
from ..listmerge.plan2 import (APPLY, BEGIN, DROP, FORK, MAX, MergePlan2,
                               compile_plan2)
from .merge_kernel import _pow2

# Tape opcodes.
T_WRITE = 0   # a=slot_lo, b=slot_hi (id-sorted ranks), c=state, d=row
T_BEGIN = 1   # a=idx
T_FORK = 2    # a=src, b=dest
T_MAX = 3     # a=dest, b=src
T_SNAP = 4    # a=row, b=snapshot slot in the output buffer


@dataclass
class PackedTape:
    op: np.ndarray        # [T] int32
    a: np.ndarray         # [T] int32
    b: np.ndarray         # [T] int32
    c: np.ndarray         # [T] int32
    d: np.ndarray         # [T] int32
    n_slots: int
    n_idx: int
    n_snaps: int
    is_base: np.ndarray   # [n_slots] uint8, id-sorted
    sorted_ids: np.ndarray    # [n_slots] int64 slot id-range starts
    sorted_lens: np.ndarray   # [n_slots] int64 slot lengths
    perm: np.ndarray      # [n_slots] int32: document order -> sorted rank
    snap_entries: List[int]   # entry index per snapshot slot


@dataclass
class TapeSource:
    """Slot table + write journal a tape can be packed from. Two builders:
    `source_from_executor` (the Python dense executor's own tables) and
    `source_native` (C++ tracker dump + delete-target rows — no Python
    execution of the zone at all)."""
    ids: np.ndarray       # [n_slots] int64 item-id range starts
    lens: np.ndarray      # [n_slots] int64
    is_base: np.ndarray   # [n_slots] uint8 (pre-zone / underwater slots)
    order: np.ndarray     # [n_slots] doc-order permutation into the above
    n_idx: int
    journal: list         # per-APPLY list of (id_lo, id_hi, state) writes


def source_from_executor(ex: DenseExecutor) -> TapeSource:
    assert ex.journal is not None, "executor must be run with journal=True"
    n = len(ex.slots)
    return TapeSource(
        ids=np.array([s.ids for s in ex.slots], dtype=np.int64),
        lens=np.array([len(s) for s in ex.slots], dtype=np.int64),
        is_base=np.asarray(ex.is_base[:n], dtype=np.uint8),
        order=np.asarray(ex.order, dtype=np.int64),
        n_idx=ex.n_idx, journal=ex.journal)


def source_native(oplog, plan: MergePlan2, from_frontier,
                  merge_frontier) -> TapeSource:
    """Build the tape source from the C++ engine: one native transform
    gives the final item table (document order) and the delete-target rows;
    the journal is derived from the op table (inserts) and those rows
    (deletes) — delete targets are intrinsic to each op, so the M1-walk-
    recorded rows are valid for the fork/join schedule too. The native
    items are RLE-merged, so they are split at every journal-write
    boundary to restore the alignment pack_plan_tape asserts."""
    from ..listmerge.dense import DELETED, INSERTED
    from ..native.core import get_native_ctx
    from ..text.op import INS

    ctx = get_native_ctx(oplog)
    ctx.transform([int(x) for x in from_frontier],
                  [int(x) for x in merge_frontier])
    common = ctx.zone_common()
    assert sorted(common) == sorted(plan.common), \
        "native transform and plan disagree on the conflict zone"
    ids, lens, *_rest = ctx.dump_tracker(keep_underwater=True)
    lv0, lv1, t0, t1, fwd = ctx.dump_del_rows()
    ctx.release_tracker()

    journal = []
    bounds = set()
    for en in plan.entries:
        writes = []
        for piece in oplog.ops.iter_range(en.span):
            if piece.kind == INS:
                writes.append((piece.lv, piece.lv + len(piece), INSERTED))
            else:
                a, b = piece.lv, piece.lv + len(piece)
                j = int(np.searchsorted(lv0, a, side="right")) - 1
                while a < b:
                    assert 0 <= j < len(lv0) and lv0[j] <= a < lv1[j], \
                        "delete op not covered by native del rows"
                    e = min(b, int(lv1[j]))
                    if fwd[j]:
                        tr = (int(t0[j]) + (a - int(lv0[j])),
                              int(t0[j]) + (e - int(lv0[j])))
                    else:
                        tr = (int(t1[j]) - (e - int(lv0[j])),
                              int(t1[j]) - (a - int(lv0[j])))
                    writes.append((tr[0], tr[1], DELETED))
                    a = e
                    j += 1
        for (lo, hi, _s) in writes:
            bounds.add(lo)
            bounds.add(hi)
        journal.append(writes)

    # Split the RLE-merged native items at write boundaries (doc order is
    # preserved: splits are adjacent).
    bs = np.array(sorted(bounds), dtype=np.int64)
    out_ids, out_lens = [], []
    for i in range(len(ids)):
        s, e = int(ids[i]), int(ids[i] + lens[i])
        lo = int(np.searchsorted(bs, s, side="right"))
        hi = int(np.searchsorted(bs, e, side="left"))
        prev = s
        for cut in bs[lo:hi]:
            out_ids.append(prev)
            out_lens.append(int(cut) - prev)
            prev = int(cut)
        out_ids.append(prev)
        out_lens.append(e - prev)
    oids = np.array(out_ids, dtype=np.int64)
    olens = np.array(out_lens, dtype=np.int64)
    return TapeSource(
        ids=oids, lens=olens,
        is_base=(oids >= UNDERWATER_START).astype(np.uint8),
        order=np.arange(len(oids), dtype=np.int64),
        n_idx=max(1, plan.indexes_used), journal=journal)


def pack_plan_tape(plan: MergePlan2, src, snapshot_entries: Sequence[int]
                   ) -> PackedTape:
    """Flatten a fork/join plan + a write journal into a device step tape.
    `src` is a TapeSource or a journal=True DenseExecutor."""
    if isinstance(src, DenseExecutor):
        src = source_from_executor(src)
    for e in snapshot_entries:
        if not 0 <= int(e) < len(plan.entries):
            raise IndexError(
                f"snapshot entry {e} out of range: plan has "
                f"{len(plan.entries)} conflict entries (a pure fast-forward "
                f"history has none — use oplog.checkout for those versions)")
    n_slots = len(src.ids)
    ids = src.ids
    lens = src.lens
    rank_order = np.argsort(ids, kind="stable")
    sorted_ids = ids[rank_order]
    sorted_lens = lens[rank_order]
    rank_of = np.empty(n_slots, dtype=np.int64)
    rank_of[rank_order] = np.arange(n_slots)
    ends = sorted_ids + sorted_lens

    def rank_range(lo: int, hi: int) -> Tuple[int, int]:
        a = int(np.searchsorted(sorted_ids, lo))
        b = int(np.searchsorted(sorted_ids, hi))
        assert a < b and sorted_ids[a] == lo and ends[b - 1] == hi, \
            "journal range not aligned to final slot boundaries"
        return a, b

    want = {int(e): i for i, e in enumerate(snapshot_entries)}
    op, aa, bb, cc, dd = [], [], [], [], []

    def emit(o, a=0, b=0, c=0, d=0):
        op.append(o); aa.append(a); bb.append(b); cc.append(c); dd.append(d)

    apply_i = 0
    for act in plan.actions:
        kind = act[0]
        if kind == BEGIN:
            emit(T_BEGIN, act[1])
        elif kind == FORK:
            emit(T_FORK, act[1], act[2])
        elif kind == MAX:
            emit(T_MAX, act[1], act[2])
        elif kind == DROP:
            pass
        elif kind == APPLY:
            for (lo, hi, state) in src.journal[apply_i]:
                ra, rb = rank_range(lo, hi)
                emit(T_WRITE, ra, rb, state, act[2])
            if act[1] in want:
                emit(T_SNAP, act[2], want[act[1]])
            apply_i += 1

    is_base = np.asarray(src.is_base, dtype=np.uint8)[rank_order]
    perm = rank_of[np.asarray(src.order, dtype=np.int64)].astype(np.int32)
    return PackedTape(
        op=np.array(op, dtype=np.int32), a=np.array(aa, dtype=np.int32),
        b=np.array(bb, dtype=np.int32), c=np.array(cc, dtype=np.int32),
        d=np.array(dd, dtype=np.int32), n_slots=n_slots, n_idx=src.n_idx,
        n_snaps=len(snapshot_entries), is_base=is_base,
        sorted_ids=sorted_ids, sorted_lens=sorted_lens, perm=perm,
        snap_entries=[int(e) for e in snapshot_entries])


_tape_jit_cache = {}
_materialize_jit_cache = {}


def execute_tape_jax(op, a, b, c, d, is_base, n_slots: int, n_idx: int,
                     n_snaps: int):
    """Run the packed schedule on device: one lax.scan over tape steps.
    Returns the snapshot rows [n_snaps, n_slots] uint8.

    All shapes are padded to powers of two so the compiled-executable cache
    stays O(log max_size) with real reuse across merges (same bucketing
    pattern as merge_kernel._jitted_kernel). Padding tape steps are WRITEs
    with an empty slot range; padding slots are never written and padding
    snapshot rows are sliced off before returning."""
    import jax

    ns, ni = _pow2(n_slots), _pow2(n_idx)
    nq = _pow2(max(n_snaps, 1))
    T = _pow2(max(len(op), 1))
    key = (ns, ni, nq, T)
    fn = _tape_jit_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(_execute_tape, n_slots=ns, n_idx=ni,
                             n_snaps=nq))
        _tape_jit_cache[key] = fn

    def pad(x, n, fill=0):
        x = np.asarray(x)
        out = np.full(n, fill, dtype=x.dtype)
        out[:len(x)] = x
        return out

    rows = fn(pad(op, T, T_WRITE), pad(a, T), pad(b, T), pad(c, T),
              pad(d, T), pad(is_base, ns))
    return rows[:n_snaps, :n_slots]


def _execute_tape(op, a, b, c, d, is_base, n_slots: int, n_idx: int,
                  n_snaps: int):
    import jax.numpy as jnp
    from jax import lax

    S0 = jnp.zeros((n_idx, n_slots), dtype=jnp.uint8)
    rows0 = jnp.zeros((max(n_snaps, 1), n_slots), dtype=jnp.uint8)
    base_row = jnp.asarray(is_base, dtype=jnp.uint8)
    slot_ix = jnp.arange(n_slots, dtype=jnp.int32)

    def write(S, rows, t):
        _o, lo, hi, state, row = t
        mask = (slot_ix >= lo) & (slot_ix < hi)
        col = lax.dynamic_index_in_dim(S, row, 0, keepdims=False)
        col = jnp.maximum(col, jnp.where(mask, state, 0).astype(jnp.uint8))
        return lax.dynamic_update_index_in_dim(S, col, row, 0), rows

    def begin(S, rows, t):
        return lax.dynamic_update_index_in_dim(S, base_row, t[1], 0), rows

    def fork(S, rows, t):
        col = lax.dynamic_index_in_dim(S, t[1], 0, keepdims=False)
        return lax.dynamic_update_index_in_dim(S, col, t[2], 0), rows

    def fmax(S, rows, t):
        dst = lax.dynamic_index_in_dim(S, t[1], 0, keepdims=False)
        src = lax.dynamic_index_in_dim(S, t[2], 0, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            S, jnp.maximum(dst, src), t[1], 0), rows

    def snap(S, rows, t):
        col = lax.dynamic_index_in_dim(S, t[1], 0, keepdims=False)
        return S, lax.dynamic_update_index_in_dim(rows, col, t[2], 0)

    def step(carry, t):
        S, rows = carry
        S, rows = lax.switch(t[0], [
            lambda args: write(*args),
            lambda args: begin(*args),
            lambda args: fork(*args),
            lambda args: fmax(*args),
            lambda args: snap(*args),
        ], (S, rows, t))
        return (S, rows), None

    tape = jnp.stack([jnp.asarray(x, dtype=jnp.int32)
                      for x in (op, a, b, c, d)], axis=1)
    (_S, rows), _ = lax.scan(step, (S0, rows0), tape)
    return rows


def snapshot_rows(oplog, from_frontier: Sequence[int],
                  merge_frontier: Optional[Sequence[int]] = None,
                  entries: Optional[Sequence[int]] = None,
                  source: str = "python"):
    """Compile + journal (host) + device-replay a merge, returning
    (plan, source, tape, rows) where rows[i] is the device-computed state
    row at snapshot entry i's version.

    source="python" runs the dense executor for the journal (also yields
    slot origins — the origin-query tests use them); source="native" gets
    the journal from one C++ transform + the delete-target rows — no
    Python zone execution, fast enough for the shipped corpora."""
    merge = list(oplog.version) if merge_frontier is None \
        else list(merge_frontier)
    plan = compile_plan2(oplog.cg.graph, list(from_frontier), merge)
    if source == "native":
        ex = source_native(oplog, plan, list(from_frontier), merge)
    elif source == "python":
        ex = DenseExecutor(plan, oplog.cg.agent_assignment, oplog.ops,
                           journal=True)
        for _ in ex.run():
            pass
    else:
        raise ValueError(f"unknown source {source!r}: use 'python' or "
                         f"'native'")
    if entries is None:
        entries = range(len(plan.entries))
    tape = pack_plan_tape(plan, ex, list(entries))
    rows = np.asarray(execute_tape_jax(
        tape.op, tape.a, tape.b, tape.c, tape.d, tape.is_base,
        n_slots=tape.n_slots, n_idx=tape.n_idx, n_snaps=tape.n_snaps))
    return plan, ex, tape, rows


def entry_frontier(graph, plan: MergePlan2, k: int) -> List[int]:
    """The version frontier reached by entry k: zone common ancestor plus
    every in-zone ancestor entry plus k itself."""
    tips = list(plan.common)
    seen = set()
    stack = [k]
    while stack:
        e = stack.pop()
        if e in seen:
            continue
        seen.add(e)
        tips.append(plan.entries[e].span[1] - 1)
        stack.extend(plan.entries[e].parents)
    return list(graph.find_dominators(tips))


# ---- batched time travel -------------------------------------------------

def texts_at_versions(oplog, entries: Sequence[int],
                      from_frontier: Sequence[int] = (),
                      source: str = "python",
                      merge_frontier: Optional[Sequence[int]] = None,
                      version_sharding=None) -> List[str]:
    """Materialize the document at many historical versions (one per
    snapshot entry) in a single vmapped device call.

    Reference equivalent: N separate `oplog.checkout(version)` calls, each
    a full tracker replay (src/list/oplog.rs:32). Here one device tape
    replay yields every version's state row, and one batched materialize
    gathers each document as a visibility mask over the shared final-order
    linearization. `version_sharding` (a jax.sharding.NamedSharding over
    the snapshot axis) spreads the materialize batch over a device mesh
    (the version axis is padded up to the mesh when needed)."""
    import jax
    import jax.numpy as jnp

    from ..text.op import INS
    from .linearize import materialize_jax
    from .merge_kernel import _arena_offsets

    plan, ex, tape, rows = snapshot_rows(oplog, from_frontier,
                                         merge_frontier=merge_frontier,
                                         entries=entries, source=source)
    base_text = oplog.checkout(plan.common).snapshot()
    plen = len(base_text)

    sid, slen = tape.sorted_ids, tape.sorted_lens
    uw = sid >= UNDERWATER_START
    uw_off = np.where(uw, sid - UNDERWATER_START, 0)
    text_len = np.where(
        uw, np.maximum(0, np.minimum(uw_off + slen, plen) - uw_off),
        slen).astype(np.int32)
    arena_str = oplog.ops._arenas[INS].get((0, oplog.ops.arena_len(INS)))
    arena = np.frombuffer((base_text + arena_str).encode("utf-32-le"),
                          dtype=np.int32)
    char_off = np.where(uw, uw_off,
                        plen + _arena_offsets(
                            oplog, np.where(uw, 0, sid))).astype(np.int32)

    vis = np.where(rows == 1, text_len[None, :], 0).astype(np.int32)
    n_real = vis.shape[0]
    cap = _pow2(max(1, int(vis.sum(axis=1).max())))
    fn = _materialize_jit_cache.get(cap)
    if fn is None:
        fn = jax.jit(jax.vmap(partial(materialize_jax, cap=cap),
                              in_axes=(None, 0, None, None)))
        _materialize_jit_cache[cap] = fn
    vis_dev = jnp.asarray(vis)
    if version_sharding is not None:
        n_mesh = int(np.prod(list(version_sharding.mesh.shape.values())))
        pad = (-n_real) % n_mesh
        if pad:
            vis_dev = jnp.concatenate(
                [vis_dev, jnp.zeros((pad, vis.shape[1]), jnp.int32)])
        vis_dev = jax.device_put(vis_dev, version_sharding)
    texts, totals = fn(jnp.asarray(tape.perm), vis_dev,
                       jnp.asarray(char_off),
                       jnp.asarray(arena if len(arena) else
                                   np.zeros(1, np.int32)))
    texts, totals = np.asarray(texts), np.asarray(totals)
    return [texts[i, :totals[i]].astype(np.int32).tobytes()
            .decode("utf-32-le") for i in range(len(tape.snap_entries))]


# ---- batched origin resolution ------------------------------------------

def origin_query_jax(row_ord, len_ord, positions):
    """Batched YjsMod origin queries against one version row.

    row_ord [n]: the version's slot states in DOCUMENT order (0/1/2).
    len_ord [n]: slot char lengths in document order (underwater clipped
                 to real text so int32 prefix sums cannot overflow).
    positions [q]: insert positions (chars) in the version's visible doc.

    Returns (ol_j, ol_off, orr_j, orr_off): document-order slot index and
    in-slot offset of origin_left (the pos-1'th visible char; ol_j == -1
    for pos == 0 / ROOT) and origin_right (the next char at or after the
    cursor whose slot is NOT NotInsertedYet; orr_j == -1 for end-of-doc) —
    the exact neighbor pair the M1 tracker extracts per insert with a tree
    descent + rightward scan (reference: merge.rs:395-423)."""
    import jax.numpy as jnp

    n = row_ord.shape[0]
    vis_len = jnp.where(row_ord == 1, len_ord, 0)
    cvis = jnp.cumsum(vis_len)

    # origin_left: slot containing visible char pos-1.
    p = positions - 1
    j = jnp.searchsorted(cvis, p, side="right").astype(jnp.int32)
    jc = jnp.clip(j, 0, n - 1)
    ol_off = (p - (cvis[jc] - vis_len[jc])).astype(jnp.int32)
    ol_j = jnp.where(positions == 0, -1, jc)

    # origin_right: cursor sits after origin_left; the next non-NIY char.
    # Within a visible slot the next char is right there; otherwise scan
    # forward to the next slot with state != NIY (suffix min over indexes).
    non_niy = row_ord != 0
    idx = jnp.arange(n, dtype=jnp.int32)
    nxt = jnp.flip(jax_lazy_cummin(jnp.flip(
        jnp.where(non_niy, idx, n), axis=0)), axis=0)
    # cursor slot/off: (jc, ol_off+1) unless past slot end or pos==0.
    in_slot = (positions != 0) & (ol_off + 1 < len_ord[jc])
    scan_from = jnp.clip(jnp.where(positions == 0, 0, jc + 1), 0, n)
    nxt_pad = jnp.concatenate([nxt, jnp.full((1,), n, dtype=nxt.dtype)])
    far_j = nxt_pad[scan_from]
    orr_j = jnp.where(in_slot, jc, far_j).astype(jnp.int32)
    orr_off = jnp.where(in_slot, ol_off + 1, 0).astype(jnp.int32)
    orr_j = jnp.where(orr_j >= n, -1, orr_j)
    return ol_j, ol_off, orr_j, orr_off


def jax_lazy_cummin(x):
    import jax.numpy as jnp
    from jax import lax
    return lax.associative_scan(jnp.minimum, x)
