"""Observability: structured counters + stats dumps.

Capability mirror of the reference's tracing facilities (SURVEY.md §5):
print_stats RLE-compaction dumps (reference: src/list/oplog.rs:353-405),
the thread-local op counters sketched in the merge hot loops (reference:
src/listmerge/merge.rs:311-314, advance_retreat.rs:73-76), and the counting
allocator used for peak-memory probes (reference: crates/trace-alloc).
"""

from __future__ import annotations

import time
from collections import Counter
from contextlib import contextmanager
from typing import Dict


class MergeCounters:
    """Structured counters around the merge kernel."""

    def __init__(self) -> None:
        self.counts: Counter = Counter()
        self.timings: Dict[str, float] = {}

    def bump(self, name: str, n: int = 1) -> None:
        self.counts[name] += n

    @contextmanager
    def timed(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timings[name] = self.timings.get(name, 0.0) + \
                (time.perf_counter() - t0)

    def snapshot(self) -> Dict:
        return {"counts": dict(self.counts), "timings": dict(self.timings)}


GLOBAL_COUNTERS = MergeCounters()


def oplog_stats(oplog, include_encoded_sizes: bool = False) -> Dict:
    """RLE compaction ratios & per-structure byte breakdown (reference:
    src/list/oplog.rs:353-405 print_stats — entry counts, packed bytes,
    and the ratio vs one record per op).

    Byte figures are the packed columnar widths: op runs are 6 i64
    columns, graph runs 3 i64 columns + one i64 per parent edge, agent
    runs 4 i64 columns; arenas are UTF-32 chars x 4 (the device-uniform
    char space). `include_encoded_sizes` adds the actual wire sizes
    (full snapshot + patch header cost), which is what the reference's
    281 KB / 23 KB automerge figures measure."""
    from ..text.op import DEL, INS
    n_lv = len(oplog)
    runs = len(oplog.ops.runs)
    graph = oplog.cg.graph
    n_parents = sum(len(p) for p in graph.parents)
    n_agent_runs = len(oplog.cg.agent_assignment.global_runs)
    rec_op = 6 * 8
    out = {
        "num_ops": n_lv,
        "op_runs": runs,
        "ops_per_run": round(n_lv / runs, 2) if runs else 0.0,
        "op_runs_bytes": runs * rec_op,
        "op_uncompacted_bytes": n_lv * rec_op,
        "op_compaction_ratio": round(n_lv / runs, 2) if runs else 0.0,
        "graph_runs": len(graph),
        "graph_runs_bytes": len(graph) * 3 * 8 + n_parents * 8,
        "graph_parent_edges": n_parents,
        "agent_runs": n_agent_runs,
        "agent_runs_bytes": n_agent_runs * 4 * 8,
        "agents": len(oplog.cg.agent_assignment.agent_names),
        "ins_arena_chars": oplog.ops.arena_len(INS),
        "ins_arena_bytes": oplog.ops.arena_len(INS) * 4,
        "del_arena_chars": oplog.ops.arena_len(DEL),
        "del_arena_bytes": oplog.ops.arena_len(DEL) * 4,
        "frontier_len": len(oplog.cg.version),
    }
    if include_encoded_sizes:
        from ..encoding.encode import (ENCODE_FULL, ENCODE_PATCH,
                                       encode_oplog)
        out["encoded_full_bytes"] = len(encode_oplog(oplog, ENCODE_FULL))
        out["encoded_patch_from_tip_bytes"] = len(
            encode_oplog(oplog, ENCODE_PATCH, from_version=oplog.version))
    return out


def print_stats(oplog) -> None:
    for k, v in oplog_stats(oplog).items():
        print(f"{k}: {v}")


def peak_memory_probe(fn, *args, **kwargs):
    """Run fn while tracking peak Python allocation (reference: trace-alloc
    counting allocator behind the memusage feature)."""
    import tracemalloc
    tracemalloc.start()
    try:
        result = fn(*args, **kwargs)
        _current, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    return result, peak
