"""Observability: structured counters + stats dumps.

Capability mirror of the reference's tracing facilities (SURVEY.md §5):
print_stats RLE-compaction dumps (reference: src/list/oplog.rs:353-405),
the thread-local op counters sketched in the merge hot loops (reference:
src/listmerge/merge.rs:311-314, advance_retreat.rs:73-76), and the counting
allocator used for peak-memory probes (reference: crates/trace-alloc).
"""

from __future__ import annotations

import time
from collections import Counter
from contextlib import contextmanager
from typing import Dict


class MergeCounters:
    """Structured counters around the merge kernel."""

    def __init__(self) -> None:
        self.counts: Counter = Counter()
        self.timings: Dict[str, float] = {}

    def bump(self, name: str, n: int = 1) -> None:
        self.counts[name] += n

    @contextmanager
    def timed(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timings[name] = self.timings.get(name, 0.0) + \
                (time.perf_counter() - t0)

    def snapshot(self) -> Dict:
        return {"counts": dict(self.counts), "timings": dict(self.timings)}


GLOBAL_COUNTERS = MergeCounters()


def oplog_stats(oplog) -> Dict:
    """RLE compaction ratios & size breakdown (reference: print_stats)."""
    from ..text.op import DEL, INS
    n_lv = len(oplog)
    runs = len(oplog.ops.runs)
    return {
        "num_ops": n_lv,
        "op_runs": runs,
        "ops_per_run": round(n_lv / runs, 2) if runs else 0.0,
        "graph_runs": len(oplog.cg.graph),
        "agent_runs": len(oplog.cg.agent_assignment.global_runs),
        "agents": len(oplog.cg.agent_assignment.agent_names),
        "ins_arena_chars": oplog.ops.arena_len(INS),
        "del_arena_chars": oplog.ops.arena_len(DEL),
        "frontier_len": len(oplog.cg.version),
    }


def print_stats(oplog) -> None:
    for k, v in oplog_stats(oplog).items():
        print(f"{k}: {v}")


def peak_memory_probe(fn, *args, **kwargs):
    """Run fn while tracking peak Python allocation (reference: trace-alloc
    counting allocator behind the memusage feature)."""
    import tracemalloc
    tracemalloc.start()
    try:
        result = fn(*args, **kwargs)
        _current, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    return result, peak
