"""A chunked rope for branch content.

Fills the role of the reference's external `jumprope` crate (a skip-list rope;
used at reference: src/list/mod.rs:75). This design is a flat list of string
chunks indexed by a Fenwick tree over chunk lengths: O(log n) position lookup,
O(chunk) splice. All positions are in unicode characters (the reference keeps
all CRDT math in char space too — src/unicount.rs).
"""

from __future__ import annotations

from typing import List

_TARGET = 1024  # target chunk size (chars)
_MAX = 2048


class Rope:
    __slots__ = ("_chunks", "_fen", "_len")

    def __init__(self, s: str = "") -> None:
        self._chunks: List[str] = [s[i:i + _TARGET] for i in range(0, len(s), _TARGET)] or [""]
        self._len = len(s)
        self._rebuild()

    # --- Fenwick over chunk lengths --------------------------------------

    def _rebuild(self) -> None:
        n = len(self._chunks)
        fen = [0] * (n + 1)
        for i, c in enumerate(self._chunks, start=1):
            fen[i] += len(c)
            j = i + (i & -i)
            if j <= n:
                fen[j] += fen[i]
        self._fen = fen

    def _fen_add(self, i: int, delta: int) -> None:
        i += 1
        n = len(self._fen) - 1
        while i <= n:
            self._fen[i] += delta
            i += i & -i

    def _find_chunk(self, pos: int):
        """Largest prefix <= pos; returns (chunk_idx, offset_in_chunk)."""
        idx = 0
        rem = pos
        bit = 1 << (len(self._fen).bit_length() - 1)
        n = len(self._fen) - 1
        while bit:
            nxt = idx + bit
            if nxt <= n and self._fen[nxt] <= rem:
                rem -= self._fen[nxt]
                idx = nxt
            bit >>= 1
        # idx = number of whole chunks before pos
        if idx >= len(self._chunks):
            idx = len(self._chunks) - 1
            rem = len(self._chunks[idx])
        return idx, rem

    # --- edits -----------------------------------------------------------

    def __len__(self) -> int:
        return self._len

    def insert(self, pos: int, s: str) -> None:
        if not s:
            return
        assert 0 <= pos <= self._len, (pos, self._len)
        ci, off = self._find_chunk(pos)
        chunk = self._chunks[ci]
        merged = chunk[:off] + s + chunk[off:]
        self._len += len(s)
        if len(merged) <= _MAX:
            self._chunks[ci] = merged
            self._fen_add(ci, len(s))
        else:
            parts = [merged[i:i + _TARGET] for i in range(0, len(merged), _TARGET)]
            self._chunks[ci:ci + 1] = parts
            self._rebuild()

    def delete(self, pos: int, n: int) -> None:
        if n <= 0:
            return
        assert pos + n <= self._len, (pos, n, self._len)
        self._len -= n
        ci, off = self._find_chunk(pos)
        remaining = n
        structural = False
        while remaining > 0:
            chunk = self._chunks[ci]
            take = min(len(chunk) - off, remaining)
            new_chunk = chunk[:off] + chunk[off + take:]
            remaining -= take
            if new_chunk or len(self._chunks) == 1:
                self._chunks[ci] = new_chunk
                if structural:
                    pass  # fenwick rebuilt at the end anyway
                else:
                    self._fen_add(ci, -take)
                ci += 1
            else:
                del self._chunks[ci]
                structural = True
            off = 0
        if structural:
            self._rebuild()

    def char_at(self, pos: int) -> str:
        ci, off = self._find_chunk(pos)
        return self._chunks[ci][off]

    def slice(self, start: int, end: int) -> str:
        return str(self)[start:end] if end - start > self._len // 2 else self._slice_small(start, end)

    def _slice_small(self, start: int, end: int) -> str:
        if end <= start:
            return ""
        ci, off = self._find_chunk(start)
        out: List[str] = []
        need = end - start
        while need > 0 and ci < len(self._chunks):
            chunk = self._chunks[ci]
            take = min(len(chunk) - off, need)
            out.append(chunk[off:off + take])
            need -= take
            ci += 1
            off = 0
        return "".join(out)

    def __str__(self) -> str:
        return "".join(self._chunks)
