"""Deep invariant checkers.

Capability mirror of the reference's dbg_check family (reference:
src/causalgraph/check.rs, src/causalgraph/graph/check.rs, src/check.rs;
SURVEY.md §4.5): structural validation compiled into tests and callable on
demand when debugging.
"""

from __future__ import annotations

from ..causalgraph.causal_graph import CausalGraph
from ..causalgraph.graph import Graph
from ..text.oplog import OpLog


def check_graph(g: Graph, deep: bool = False) -> None:
    n = len(g)
    prev_end = 0
    for i in range(n):
        assert g.starts[i] == prev_end, "graph runs must be dense"
        assert g.ends[i] > g.starts[i]
        prev_end = g.ends[i]
        ps = g.parents[i]
        assert list(ps) == sorted(set(ps)), "parents sorted and unique"
        for p in ps:
            assert 0 <= p < g.starts[i], "parents strictly earlier"
        # Shadow: every LV in [shadow, start) must be an ancestor of start.
        assert g.shadows[i] <= g.starts[i]
        if deep and g.starts[i] > 0:
            for v in range(g.shadows[i], g.starts[i]):
                assert g.frontier_contains_version([g.starts[i]], v), \
                    f"shadow {g.shadows[i]} of run {i} is wrong at {v}"
        # child indexes consistent
        for c in g.child_idxs[i]:
            assert g.starts[i] in [p if p >= 0 else -1
                                   for p in g.parents[c]] or \
                any(g.starts[i] <= p < g.ends[i] for p in g.parents[c])
    for r in g.root_child_idxs:
        assert g.parents[r] == ()


def check_cg(cg: CausalGraph, deep: bool = False) -> None:
    check_graph(cg.graph, deep)
    aa = cg.agent_assignment
    # Global runs dense over the LV space.
    prev = 0
    for (lv0, lv1, agent, seq0) in aa.global_runs:
        assert lv0 == prev and lv1 > lv0
        assert 0 <= agent < len(aa.agent_names)
        prev = lv1
    assert prev == cg.graph.next_lv(), "assignment and graph lengths differ"
    # Per-client runs sorted, disjoint, and consistent with the global map.
    for agent, runs in enumerate(aa.client_runs):
        prev_seq = -1
        for (s0, s1, lv0) in runs:
            assert s0 > prev_seq and s1 > s0
            prev_seq = s1 - 1
            if deep:
                for off in (0, s1 - s0 - 1):
                    a2, seq2 = aa.local_to_agent_version(lv0 + off)
                    assert (a2, seq2) == (agent, s0 + off)
    # The version must be a valid dominator set.
    f = list(cg.version)
    assert f == sorted(set(f))
    if deep and len(f) > 1:
        assert cg.graph.find_dominators(f) == f, "version isn't a frontier"


def check_oplog(ol: OpLog, deep: bool = False) -> None:
    check_cg(ol.cg, deep)
    assert ol.ops.end_lv() == len(ol), "op table and causal graph differ"
    prev_end = 0
    for run in ol.ops.runs:
        assert run.lv == prev_end
        assert run.end > run.start
        prev_end = run.lv + len(run)
        if run.content_pos is not None:
            assert run.content_pos[1] - run.content_pos[0] == len(run)
