"""Cold tier: one durable `PagedDocFile` home per document.

The bottom rung of the cold -> warm -> device residency ladder
(serve/README.md "Tiered residency"). Each doc's home is a single
page-store file — stream 0 holds baseline snapshots, stream 1 a WAL of
v1 patches — and `TieredStore` adds the per-doc policy the serving
tier needs on top of it:

  * `save(doc_id, oplog)` appends the oplog's unsaved suffix as one
    patch record and folds the patch chain into a fresh baseline when
    it grows past `compact_patch_records` (per-doc compaction policy);
  * `load(doc_id)` decodes the home into a FRESH OpLog the warm tier
    owns — the home file is opened per operation, so millions of docs
    never pin millions of file descriptors;
  * failure is per-doc: an unreadable home quarantines THAT doc with a
    typed `DocQuarantined` (best effort first: a rotten baseline is
    re-served from WAL replay when the patch chain still decodes), a
    slow read overrunning its hydration budget raises
    `HydrationTimeout` — neither ever poisons another doc's path.

Locking: `tier.table` (io rung) guards the lock table / quarantine map
and is never held across disk IO; `tier.doc[...]` (io rung) serializes
one doc's file operations. The serve tier's oplog guard is taken
INSIDE the doc lock around encode — the documented io -> oplog order
(analysis/rules/locks.py) — so a snapshot never races a handler
appending ops.

`StorageFaults` is the seeded fault injector the storage soak drives:
slow-disk delays on load, deterministic per seed.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from typing import Callable, Dict, Optional

from ..analysis.witness import make_lock
from ..encoding.decode import decode_into
from ..text.oplog import OpLog
from .pages import PAGE_SIZE, PagedDocFile, PagedStore
from .store import StorageError


# the tier's full counter surface, module-level so the dt-lint
# metrics-schema-drift rule (analysis/rules/metrics_schema.py) can
# cross-reference producer bumps against it without importing a class
TIER_KEYS = ("saves", "loads", "fresh_docs", "compactions",
             "salvaged_wal", "quarantines", "slow_loads")


class DocQuarantined(StorageError):
    """Typed per-doc rejection: the doc's durable home is unreadable
    (or its hydration budget is exhausted). Only THIS doc is affected
    — the rest of its bucket flushes on time."""

    def __init__(self, doc_id: str, reason: str) -> None:
        super().__init__(f"doc {doc_id!r} quarantined: {reason}")
        self.doc_id = doc_id
        self.reason = reason


class HydrationTimeout(StorageError):
    """One hydration attempt overran its per-attempt budget; the
    caller retries with backoff (transient), it does not quarantine."""

    def __init__(self, doc_id: str, budget_s: float) -> None:
        super().__init__(
            f"hydrating {doc_id!r} exceeded its {budget_s}s budget")
        self.doc_id = doc_id
        self.budget_s = budget_s


class StorageFaults:
    """Seeded fault injector for the cold tier: slow-disk delays on
    load, deterministic for a given seed so soak failures replay."""

    def __init__(self, seed: int = 0, slow_rate: float = 0.0,
                 slow_s: float = 0.05) -> None:
        self.slow_rate = float(slow_rate)
        self.slow_s = float(slow_s)
        self._rng = random.Random(f"faults:{seed}")
        self._lock = threading.Lock()
        self.injected_slow = 0

    def load_delay(self, doc_id: str) -> float:
        with self._lock:
            if self.slow_rate and self._rng.random() < self.slow_rate:
                self.injected_slow += 1
                return self.slow_s * (0.5 + self._rng.random())
            return 0.0


class TieredStore:
    """Per-doc durable homes under one root directory (see module
    docstring). Thread-safe; every public method is whole-operation
    atomic with respect to the doc it touches."""

    def __init__(self, root: str, compact_patch_records: int = 64,
                 faults: Optional[StorageFaults] = None,
                 on_persist: Optional[Callable[[str, OpLog], None]]
                 = None) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.compact_patch_records = max(int(compact_patch_records), 1)
        self.faults = faults
        # on_persist(doc_id, home_oplog) fires under the oplog guard
        # right after a save lands — the soak uses it to track each
        # doc's durable frontier for crash-recovery parity checks
        self.on_persist = on_persist
        self._tier_lock = make_lock("tier.table", "io")
        self._doc_locks: Dict[str, object] = {}
        self.quarantined: Dict[str, str] = {}
        self._counters = {k: 0 for k in TIER_KEYS}

    # ---- bookkeeping -----------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        with self._tier_lock:
            self._counters[key] += n

    def counters(self) -> dict:
        with self._tier_lock:
            out = dict(self._counters)
            out["quarantined_docs"] = len(self.quarantined)
            return out

    def path(self, doc_id: str) -> str:
        return os.path.join(self.root, doc_id + ".pages")

    def _doc_lock(self, doc_id: str):
        with self._tier_lock:
            lk = self._doc_locks.get(doc_id)
            if lk is None:
                lk = self._doc_locks[doc_id] = make_lock(
                    f"tier.doc[{doc_id}]", "io")
            return lk

    # ---- quarantine ------------------------------------------------------

    def quarantine(self, doc_id: str, reason: str) -> None:
        with self._tier_lock:
            if doc_id not in self.quarantined:
                self.quarantined[doc_id] = reason
                self._counters["quarantines"] += 1

    def is_quarantined(self, doc_id: str) -> Optional[str]:
        with self._tier_lock:
            return self.quarantined.get(doc_id)

    def _reject(self, doc_id: str, reason: str) -> None:
        self.quarantine(doc_id, reason)
        raise DocQuarantined(doc_id, reason)

    # ---- save / load -----------------------------------------------------

    def save(self, doc_id: str, oplog: OpLog, oplog_lock=None) -> int:
        """Append `oplog`'s unsaved suffix to the doc's home; compact
        when the per-doc patch chain grows past the policy threshold.
        `oplog_lock` (the serve tier's oplog guard) is taken INSIDE
        the per-doc io lock — the documented io -> oplog order.
        Returns the persisted op count (len(oplog) at encode time,
        under the guard) so eviction can detect a suffix that raced
        in after the snapshot and abort instead of dropping it."""
        reason = self.is_quarantined(doc_id)
        if reason is not None:
            raise DocQuarantined(doc_id, reason)
        olock = oplog_lock if oplog_lock is not None \
            else contextlib.nullcontext()
        with self._doc_lock(doc_id):
            f = PagedDocFile(self.path(doc_id))
            try:
                with olock:
                    f.append_from(oplog)
                    persisted = len(oplog)
                    if self.on_persist is not None:
                        self.on_persist(doc_id, f.oplog)
                patches = sum(1 for _ in f.store.records(f.PATCHES))
                if patches >= self.compact_patch_records:
                    f.compact()
                    self._bump("compactions")
            finally:
                f.close()
        self._bump("saves")
        return persisted

    def load(self, doc_id: str,
             timeout_s: Optional[float] = None) -> OpLog:
        """Hydrate the doc's home into a FRESH OpLog the caller owns.
        A missing file is a brand-new doc (empty oplog), not an error.
        Raises DocQuarantined for unreadable homes (quarantining
        them), HydrationTimeout when an injected slow read overruns
        `timeout_s` (transient — the hydrator retries)."""
        reason = self.is_quarantined(doc_id)
        if reason is not None:
            raise DocQuarantined(doc_id, reason)
        if self.faults is not None:
            delay = self.faults.load_delay(doc_id)
            if delay:
                self._bump("slow_loads")
                if timeout_s is not None and delay > timeout_s:
                    time.sleep(timeout_s)
                    raise HydrationTimeout(doc_id, timeout_s)
                time.sleep(delay)
        path = self.path(doc_id)
        with self._doc_lock(doc_id):
            if not os.path.exists(path):
                self._bump("fresh_docs")
                return OpLog()
            size = os.path.getsize(path)
            try:
                st = PagedStore(path)
            except Exception as e:
                self._reject(doc_id,
                             f"unreadable: {e.__class__.__name__}")
            try:
                base = list(st.records(PagedDocFile.BASELINE))
                patches = list(st.records(PagedDocFile.PATCHES))
            finally:
                st.close()
        if not base and not patches and size >= PAGE_SIZE:
            # a non-empty home with NO decodable chain at all is
            # wipe-level corruption, not a legitimately empty doc
            self._reject(doc_id, "no_valid_pages")
        ol = OpLog()
        try:
            for rec in base:
                decode_into(ol, rec)
            for rec in patches:
                decode_into(ol, rec)
        except Exception:
            # baseline poisoned: WAL replay — the patch stream alone.
            # The first patch after a (re)created home is a full
            # encode (diff from the empty intersection), so a doc
            # whose baseline rots before its first compact replays
            # byte-identical from patches; anything less salvages the
            # longest decodable prefix or rejects typed.
            ol = OpLog()
            try:
                for rec in patches:
                    decode_into(ol, rec)
            except Exception as e:
                self._reject(doc_id,
                             f"undecodable: {e.__class__.__name__}")
            self._bump("salvaged_wal")
        self._bump("loads")
        return ol

    def compact_doc(self, doc_id: str, _crash=None) -> None:
        """Explicit compaction (the soak's crash-mid-compaction
        injection rides on `_crash` — see PagedDocFile.compact)."""
        with self._doc_lock(doc_id):
            f = PagedDocFile(self.path(doc_id))
            try:
                f.compact(_crash=_crash)
                self._bump("compactions")
            finally:
                f.close()
