"""Page-granular incremental storage engine.

Capability mirror of the reference's page store (reference:
src/storage/README.md; src/storage/mod.rs:103-505 — fixed 4 KiB pages,
whole-page atomic writes, per-page checksums, blit pages so updating the
tail of a chain never overwrites its only valid copy; and
src/causalgraph/storage.rs:1-40 — incremental append format). The design
here is NOT a translation: instead of a header page + per-chunk next-page
pointers, every page is fully self-describing

    u32 crc | u8 stream | u8 is_blit | u16 used | u32 chain_idx |
    u32 chain_gen | u32 write_seq | payload

and recovery is one linear scan grouping pages by (stream, chain_gen,
chain_idx), picking the highest write_seq among a page's main and blit
images. No pointers to maintain means a tail update is exactly ONE page
write + fsync, and a torn write at any byte leaves the previous image
intact: the writer alternates between the tail's main slot and the
stream's blit slot, so the only valid copy is never the one being
overwritten.

Streams: small integers naming independent record chains in one file.
`PagedDocFile` uses stream 0 for baseline snapshots (a fresh chain_gen
per compact) and stream 1 for incremental binary patches — the roles the
reference splits across its oplog file, CG file and WAL. Records are
length-framed and may span pages; a record torn by a crash is rolled
back on recovery (the chain truncates to the last complete record).

Write amplification: appending a record rewrites the tail page and
allocates follow-on pages only for bytes that spill, so a 1-char edit to
a megabyte document persists O(1) pages — pinned down by
tests/test_storage.py::test_paged_write_amplification.
"""

from __future__ import annotations

import os
import struct
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..encoding.crc32c import crc32c


def _fsync_dir(dirpath: str) -> None:
    """fsync a directory so a just-completed rename survives power
    loss (an os.replace is atomic but not durable until the directory
    entry itself is flushed)."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:     # pragma: no cover - platform without dir fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)

PAGE_SIZE = 4096
_HDR = struct.Struct("<IBBHIII")        # crc, stream, blit, used, idx, gen, seq
PAYLOAD = PAGE_SIZE - _HDR.size
_REC = struct.Struct("<I")               # record length frame
_DEAD = 255      # reserved stream id: page invalidated by a rollback


class PageStoreError(Exception):
    pass


class _Chain:
    __slots__ = ("gen", "pages", "tail_main", "tail_seq", "tail_data",
                 "blit_slot")

    def __init__(self, gen: int):
        self.gen = gen
        self.pages: List[int] = []          # slots of FINALIZED (full) pages
        self.tail_main: Optional[int] = None   # tail's main slot (lazy)
        self.tail_seq = 0
        self.tail_data = b""
        self.blit_slot: Optional[int] = None


class PagedStore:
    """Multi-stream page-chain store in one file (see module docstring)."""

    def __init__(self, path: str) -> None:
        self.path = path
        new = not os.path.exists(path) or os.path.getsize(path) == 0
        self._f = open(path, "w+b" if new else "r+b")
        self.bytes_written = 0           # observability: write amplification
        self.page_writes = 0
        self._chains: Dict[int, _Chain] = {}
        self._full: Dict[Tuple[int, int], bytes] = {}  # (stream, idx) -> data
        self._max_gen: Dict[int, int] = {}
        self._next_free = 0
        if not new:
            self._recover()

    # ---- low-level page IO ----------------------------------------------

    def _write_page(self, slot: int, stream: int, is_blit: int, used: int,
                    idx: int, gen: int, seq: int, payload: bytes,
                    sync: bool = True) -> None:
        assert len(payload) <= PAYLOAD and slot is not None
        body = _HDR.pack(0, stream, is_blit, used, idx, gen, seq) + \
            payload.ljust(PAYLOAD, b"\0")
        page = struct.pack("<I", crc32c(body[4:])) + body[4:]
        self._f.seek(slot * PAGE_SIZE)
        self._f.write(page)
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())
        self.bytes_written += PAGE_SIZE
        self.page_writes += 1

    def _read_page(self, slot: int):
        self._f.seek(slot * PAGE_SIZE)
        raw = self._f.read(PAGE_SIZE)
        if len(raw) < PAGE_SIZE:
            return None
        crc, stream, is_blit, used, idx, gen, seq = _HDR.unpack(
            raw[:_HDR.size])
        if crc32c(raw[4:]) != crc or used > PAYLOAD:
            return None
        return stream, is_blit, used, idx, gen, seq, raw[_HDR.size:
                                                         _HDR.size + used]

    # ---- recovery --------------------------------------------------------

    def _recover(self) -> None:
        n_pages = (os.path.getsize(self.path) + PAGE_SIZE - 1) // PAGE_SIZE
        self._next_free = n_pages
        # (stream, idx) -> best image (gen, seq, payload, is_blit) and best
        # MAIN slot per key; blit slot per stream (the newest blit wins)
        best: Dict[Tuple[int, int], Tuple[int, int, bytes, int]] = {}
        main_slot: Dict[Tuple[int, int, int], int] = {}  # (stream,gen,idx)
        blit: Dict[int, Tuple[int, int, int, int]] = {}  # s->(seq,slot,idx,gen)
        max_seq: Dict[Tuple[int, int], int] = {}         # (stream,gen)
        for slot in range(n_pages):
            p = self._read_page(slot)
            if p is None:
                continue
            stream, is_blit, used, idx, gen, seq, payload = p
            if stream == _DEAD:
                continue   # invalidated by an earlier rollback
            k = (stream, gen)
            max_seq[k] = max(max_seq.get(k, 0), seq)
            if is_blit:
                cur = blit.get(stream)
                if cur is None or seq >= cur[0]:
                    blit[stream] = (seq, slot, idx, gen)
            else:
                key = (stream, gen, idx)
                cur = main_slot.get(key)
                if cur is None or seq > cur[0]:
                    main_slot[key] = (seq, slot)
            key2 = (stream, idx)
            cur2 = best.get(key2)
            if cur2 is None or (gen, seq) > (cur2[0], cur2[1]):
                best[key2] = (gen, seq, payload, is_blit)
        # live chain per stream = highest gen seen at idx 0; ALSO track
        # the max gen seen anywhere so a stream recreated after losing
        # its idx-0 page can never splice stale same-gen pages back in
        live_gen: Dict[int, int] = {}
        for (stream, idx), (gen, _s, _p, _b) in best.items():
            if idx == 0:
                live_gen[stream] = max(live_gen.get(stream, -1), gen)
            self._max_gen[stream] = max(self._max_gen.get(stream, 0), gen)
        for stream, gen in live_gen.items():
            ch = _Chain(gen)
            ch.blit_slot = blit.get(stream, (0, None, 0, 0))[1]
            payloads: List[bytes] = []
            idx = 0
            while True:
                cur = best.get((stream, idx))
                if cur is None or cur[0] != gen:
                    break
                payloads.append(cur[2])
                ch.tail_seq = cur[1]
                idx += 1
            if not payloads:
                continue
            # roll back any torn trailing record: keep only bytes up to
            # the end of the last COMPLETE record
            buf = b"".join(payloads)
            good = 0
            off = 0
            while off + _REC.size <= len(buf):
                (ln,) = _REC.unpack_from(buf, off)
                nxt = off + _REC.size + ln
                if nxt > len(buf):
                    break
                off = nxt
                good = off
            buf = buf[:good]
            n_full = len(buf) // PAYLOAD
            ch.pages = []
            seal_seq = max_seq.get((stream, gen), 0) + 1
            for i in range(n_full):
                entry = main_slot.get((stream, gen, i))
                content = buf[i * PAYLOAD:(i + 1) * PAYLOAD]
                bseq = best[(stream, i)][1]
                if entry is None or entry[0] < bseq:
                    # the newest image of this finalized page lives on the
                    # blit slot (tail filled on an odd write) — re-seal it
                    # at a main slot NOW, or the next blit reuse would
                    # leave only the stale main image to a later recovery
                    slot = entry[1] if entry is not None else self._alloc()
                    self._write_page(slot, stream, 0, PAYLOAD, i, gen,
                                     seal_seq, content)
                else:
                    slot = entry[1]
                ch.pages.append(slot)
                self._full[(stream, i)] = content
            ch.tail_data = buf[n_full * PAYLOAD:]
            tm = main_slot.get((stream, gen, n_full))
            ch.tail_main = None if tm is None else tm[1]
            # Invalidate the rolled-back suffix: a torn-record rollback can
            # shrink the chain, leaving VALID same-gen pages past the new
            # tail on disk. Without killing them, a later recovery's chain
            # walk splices their bytes back into the record stream (after a
            # clean intervening close), yielding phantom/garbage records.
            # Deferred-fsync batch: losing these writes to a crash is safe
            # (the next recovery deterministically redoes the identical
            # rollback), so one trailing fsync covers the whole suffix
            # instead of one per page.
            killed = False
            for key in [k for k in main_slot
                        if k[0] == stream and k[1] == gen and k[2] > n_full]:
                self._write_page(main_slot[key][1], _DEAD, 0, 0, 0, 0, 0,
                                 b"", sync=False)
                del main_slot[key]
                killed = True
            bl = blit.get(stream)
            if bl is not None and bl[3] == gen and bl[2] > n_full:
                # Stale high-idx tail image on the blit slot: overwrite it
                # with a valid EMPTY blit image at the new tail idx (not a
                # _DEAD page — the next recovery must still recognize the
                # slot as this stream's blit, or it would be leaked and a
                # fresh slot allocated per rollback+reopen). seq 0 loses to
                # any real tail image at this idx.
                self._write_page(bl[1], stream, 1, 0, n_full, gen, 0, b"",
                                 sync=False)
                killed = True
            if killed:
                os.fsync(self._f.fileno())
            # New tail writes must outrank ANY stale image of this chain
            # (rollback can re-point the tail at a page whose on-disk image
            # carries a higher seq; ditto re-sealed pages above). Parity
            # matters too: tail writes alternate main/blit by seq, and the
            # FIRST post-recovery write must target the slot NOT holding
            # the newest tail image, or a torn write there could destroy
            # the only valid copy of committed records.
            tb = best.get((stream, n_full))
            tail_on_blit = bool(tb is not None and tb[0] == gen and tb[3])
            want = 1 if tail_on_blit else 0   # next write flips parity
            ch.tail_seq = seal_seq if seal_seq % 2 == want else seal_seq + 1
            self._chains[stream] = ch

    # ---- write path ------------------------------------------------------

    def _alloc(self) -> int:
        slot = self._next_free
        self._next_free += 1
        return slot

    def _chain(self, stream: int) -> _Chain:
        ch = self._chains.get(stream)
        if ch is None:
            # a stream being (re)created starts ABOVE any gen ever seen on
            # disk — stale pages of a dropped chain must never win
            gen = self._max_gen.get(stream, -1) + 1
            self._max_gen[stream] = gen
            ch = _Chain(gen)
            ch.blit_slot = self._alloc()
            self._chains[stream] = ch
        return ch

    def _write_tail(self, stream: int, ch: _Chain) -> None:
        """Atomic tail update: alternate between the tail's main slot and
        the stream's blit slot; the image not being written always holds
        the previous state (reference: the blit protocol)."""
        ch.tail_seq += 1
        idx = len(ch.pages)
        if ch.tail_seq % 2 == 1:
            if ch.blit_slot is None:    # blit page lost to corruption
                ch.blit_slot = self._alloc()
            self._write_page(ch.blit_slot, stream, 1, len(ch.tail_data),
                             idx, ch.gen, ch.tail_seq, ch.tail_data)
        else:
            if ch.tail_main is None:
                ch.tail_main = self._alloc()
            self._write_page(ch.tail_main, stream, 0, len(ch.tail_data),
                             idx, ch.gen, ch.tail_seq, ch.tail_data)

    def _finalize_tail(self, stream: int, ch: _Chain) -> None:
        """Seal a full tail page at its main slot and start a new tail."""
        assert len(ch.tail_data) == PAYLOAD
        idx = len(ch.pages)
        if ch.tail_main is None:
            ch.tail_main = self._alloc()
        self._write_page(ch.tail_main, stream, 0, PAYLOAD, idx, ch.gen,
                         ch.tail_seq + 1, ch.tail_data)
        self._full[(stream, idx)] = ch.tail_data
        ch.pages.append(ch.tail_main)
        ch.tail_main = None
        ch.tail_data = b""
        ch.tail_seq = 0

    def append(self, stream: int, record: bytes) -> None:
        """Append one length-framed record (may span pages). Each touched
        page costs exactly one page write + fsync."""
        if stream == _DEAD:   # recovery would skip its pages as garbage
            raise PageStoreError("stream id 255 is reserved")
        ch = self._chain(stream)
        data = _REC.pack(len(record)) + record
        while True:
            space = PAYLOAD - len(ch.tail_data)
            take, data = data[:space], data[space:]
            ch.tail_data += take
            if not data:
                break
            self._finalize_tail(stream, ch)
        self._write_tail(stream, ch)

    def reset_stream(self, stream: int) -> None:
        """Start a fresh (empty) chain generation for the stream; prior
        pages become garbage until the file is compacted."""
        if stream == _DEAD:
            raise PageStoreError("stream id 255 is reserved")
        old = self._chains.get(stream)
        gen = self._max_gen.get(stream, -1) + 1
        self._max_gen[stream] = gen
        ch = _Chain(gen)
        ch.blit_slot = old.blit_slot if old and \
            old.blit_slot is not None else self._alloc()
        for key in [k for k in self._full if k[0] == stream]:
            del self._full[key]
        self._chains[stream] = ch
        self._write_tail(stream, ch)

    def records(self, stream: int) -> Iterator[bytes]:
        """Iterate the stream's complete records."""
        ch = self._chains.get(stream)
        if ch is None:
            return
        buf = b"".join(self._full[(stream, i)]
                       for i in range(len(ch.pages))) + ch.tail_data
        off = 0
        while off + _REC.size <= len(buf):
            (ln,) = _REC.unpack_from(buf, off)
            if off + _REC.size + ln > len(buf):
                return   # torn tail record (rolled back on next open)
            yield buf[off + _REC.size: off + _REC.size + ln]
            off += _REC.size + ln

    def close(self) -> None:
        self._f.close()


class PagedDocFile:
    """A persistent OpLog on the page engine: stream 0 = baseline
    snapshot, stream 1 = incremental patches. A 1-char edit persists one
    patch record (O(1) page writes); compact() folds the patch chain into
    a fresh baseline and rewrites the file packed (dt-cli repack role)."""

    BASELINE, PATCHES = 0, 1

    def __init__(self, path: str) -> None:
        from ..encoding.decode import decode_into
        from ..text.oplog import OpLog
        self.path = path
        stale = path + ".compact"
        if os.path.exists(stale):
            # a crash mid-compaction left a half-built rewrite behind;
            # `path` is authoritative either way (the swap is atomic),
            # and compact() must never append onto a stale rewrite
            os.remove(stale)
        self.store = PagedStore(path)
        self.oplog = OpLog()
        for rec in self.store.records(self.BASELINE):
            decode_into(self.oplog, rec)
        for rec in self.store.records(self.PATCHES):
            decode_into(self.oplog, rec)   # idempotent causal dedup

    def append_from(self, src_oplog) -> None:
        """Persist everything `src_oplog` has that this file hasn't."""
        from ..causalgraph.summary import (intersect_with_summary,
                                           summarize_versions)
        from ..encoding.decode import decode_into
        from ..encoding.encode import ENCODE_PATCH, encode_oplog
        common, _ = intersect_with_summary(
            src_oplog.cg, summarize_versions(self.oplog.cg))
        patch = encode_oplog(src_oplog, ENCODE_PATCH, from_version=common)
        self.store.append(self.PATCHES, patch)
        decode_into(self.oplog, patch)

    def compact(self,
                _crash: Optional[Callable[[str], None]] = None) -> None:
        """Fold both streams into a fresh single-baseline file.

        Crash protocol — each step is individually durable, so a kill
        at any point recovers to either the old or the new snapshot,
        never a torn mix:

          1. the full snapshot is built at `<path>.compact` (every
             page fsynced as written)           crash -> old file wins
          2. `os.replace` swaps it in atomically crash -> old OR new
          3. the directory entry is fsynced so the rename itself
             survives power loss                crash -> new file wins

        A stale `.compact` from an earlier crash is removed before
        rebuilding (and on open), so step 1 never appends onto a
        half-built rewrite. `_crash(point)` is a fault-injection hook
        fired after each step ("snapshot_written", "replaced",
        "dir_synced"); whatever it raises propagates only AFTER the
        store has been reopened on whichever image the crash left, so
        the object stays usable and matches what a real restart would
        recover."""
        from ..encoding.encode import ENCODE_FULL, encode_oplog
        crash = _crash if _crash is not None else (lambda point: None)
        blob = encode_oplog(self.oplog, ENCODE_FULL)
        tmp = self.path + ".compact"
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
            fresh = PagedStore(tmp)
            fresh.append(self.BASELINE, blob)
            fresh.close()
            crash("snapshot_written")
            self.store.close()
            os.replace(tmp, self.path)
            crash("replaced")
            _fsync_dir(os.path.dirname(os.path.abspath(self.path)))
            crash("dir_synced")
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
            # reopen even when a step (or the hook) raised: recovery
            # picks up whichever complete image is at `path`
            self.store.close()
            self.store = PagedStore(self.path)

    def close(self) -> None:
        self.store.close()
