"""Storage soak: churn docs through an undersized residency tier with
seeded fault injection, gating on byte-identical re-hydration.

`cli storage-soak` drives this. One process hosts the whole residency
ladder — `TieredStore` homes on real disk, a `Hydrator` warm tier
deliberately smaller than the doc population, a host-engine
`MergeScheduler` flushing through the hydration gate — and a seeded
rng injects the failure modes the tier exists to survive:

  * **crash-restart** — the hydrator is stopped WITHOUT checkpoint and
    the whole serving stack is rebuilt over the same directory; the
    expected state resets to the durable frontier (exactly what a real
    restart recovers);
  * **crash-mid-compaction** — `compact_doc` dies at a seeded fsync
    point (`snapshot_written` / `replaced` / `dir_synced`); recovery
    must read old-or-new snapshot, never a torn mix;
  * **torn tail** — the last page of a cold doc's home is garbled
    (a write the power cut mid-page); recovery must roll back to one
    of the doc's last two persisted states;
  * **corruption** — a whole home is overwritten; that doc (and ONLY
    that doc) must land in quarantine while everything else flushes;
  * **slow disk** (--slow) — seeded load delays exercise the
    per-attempt timeout / retry ladder without tripping quarantine.

The verdict JSON asserts: every surviving doc re-hydrates
byte-identical to its expected content, the quarantine set is EXACTLY
the corrupted docs, zero quarantined docs leaked into flush batches,
cold-start p99 is under budget, and the runtime lock witness stayed
acyclic. `ok` is the AND of all gates — the CLI exits nonzero
otherwise.
"""

from __future__ import annotations

import os
import random
import shutil
import tempfile
import time
from typing import Dict, List, Optional

from ..analysis.witness import (make_lock, witness_enable,
                                witness_snapshot)
from ..obs.hist import Histogram
from ..serve.hydrate import Hydrator
from ..serve.scheduler import MergeScheduler
from ..storage.pages import PAGE_SIZE
from ..storage.tier import DocQuarantined, StorageFaults, TieredStore
from ..text.oplog import OpLog

_CRASH_POINTS = ("snapshot_written", "replaced", "dir_synced")


class _InjectedCrash(Exception):
    pass


def run_storage_soak(docs: int = 120, warm: int = 12, rounds: int = 8,
                     edits_per_round: int = 48, shards: int = 2,
                     seed: int = 0, compact_every: int = 16,
                     churn: bool = False, crash: bool = False,
                     slow: bool = False,
                     data_dir: Optional[str] = None,
                     p99_budget_s: float = 0.5,
                     progress: bool = False) -> dict:
    rng = random.Random(f"storage-soak:{seed}")
    witness_enable()
    root = data_dir or tempfile.mkdtemp(prefix="dt-storage-soak-")
    own_root = data_dir is None
    t_start = time.monotonic()

    faults = StorageFaults(seed=seed, slow_rate=0.15 if slow else 0.0,
                           slow_s=0.02)
    # last two persisted texts per doc — the torn-tail oracle (a torn
    # final record must recover to one of these, never a mix)
    persist_history: Dict[str, List[str]] = {}

    def on_persist(doc_id: str, home_oplog) -> None:
        hist = persist_history.setdefault(doc_id, [])
        hist.append(home_oplog.checkout_tip().snapshot())
        del hist[:-2]

    cold_hist = Histogram()         # shared across crash lifetimes
    hyd_totals: Dict[str, int] = {}
    oplog_guard = make_lock("soak.oplog", "oplog")

    def build():
        store = TieredStore(root, compact_patch_records=compact_every,
                            faults=faults, on_persist=on_persist)
        hyd = Hydrator(store, workers=2, warm_max=warm,
                       attempt_timeout_s=0.25, max_attempts=4,
                       sync_wait_s=5.0, evict_grace_s=0.01,
                       oplog_lock=oplog_guard, seed=seed)
        hyd.cold_start = cold_hist      # aggregate across lifetimes
        sched = MergeScheduler(shards, hyd.resolve, engine="host",
                               max_sessions_per_shard=max(warm // 2, 2),
                               max_pending=4 * edits_per_round + 16,
                               flush_docs=8, flush_deadline_s=0.02,
                               sync_lock=oplog_guard)
        sched.attach_hydrator(hyd)
        return store, hyd, sched

    def teardown(hyd, sched, checkpoint: bool):
        if checkpoint:
            sched.drain()
        sched.stop_pump(drain=checkpoint)
        hyd.stop(checkpoint=checkpoint)
        for k, v in hyd.counters_snapshot().items():
            hyd_totals[k] = hyd_totals.get(k, 0) + v

    # ---- seed the population --------------------------------------------
    control: Dict[str, str] = {}
    store, hyd, sched = build()
    for i in range(docs):
        d = f"doc{i:05d}"
        ol = OpLog()
        a = ol.get_or_create_agent_id("seed")
        ol.add_insert(a, 0, f"[{d}] genesis. ")
        store.save(d, ol, oplog_lock=oplog_guard)
        control[d] = ol.checkout_tip().snapshot()

    expected_quarantined: set = set()
    edits = crashes = compaction_kills = torn_tails = 0
    quarantine_rejects = 0
    doc_ids = sorted(control)

    def apply_edits(d: str, n: int) -> None:
        nonlocal edits
        ol = hyd.resolve(d)
        a = ol.get_or_create_agent_id(f"ed{seed}")
        with oplog_guard:
            text = control[d]
            for _ in range(n):
                if text and rng.random() < 0.25:
                    start = rng.randrange(len(text))
                    end = min(start + rng.randint(1, 4), len(text))
                    ol.add_delete_at(a, ol.version, start, end,
                                     content=text[start:end])
                    text = text[:start] + text[end:]
                else:
                    pos = rng.randint(0, len(text))
                    tok = f"<{edits}>"
                    ol.add_insert(a, pos, tok)
                    text = text[:pos] + tok + text[pos:]
                edits += 1
            control[d] = text

    def live_docs() -> List[str]:
        return [d for d in doc_ids if d not in expected_quarantined]

    # ---- churn rounds ----------------------------------------------------
    for rnd in range(rounds):
        for _ in range(edits_per_round):
            d = rng.choice(live_docs())
            apply_edits(d, rng.randint(1, 3))
            r = sched.submit(d)
            if not r["accepted"] and r.get("reason") == "quarantined":
                quarantine_rejects += 1
            if rng.random() < 0.2:
                sched.pump(force=True)
        sched.drain()

        if churn:
            # eviction-under-pressure: force extra snapshot evictions
            # beyond what warm_max already causes
            for d in rng.sample(live_docs(),
                                k=min(warm, len(live_docs()))):
                hyd.evict_to_snapshot(d, why="soak-churn")

        if crash and rnd == rounds // 3:
            # ---- crash-mid-compaction (every fsync point) --------------
            for point in _CRASH_POINTS:
                d = rng.choice(live_docs())
                hyd.evict_to_snapshot(d, why="pre-compact")
                want = persist_history[d][-1]

                def _boom(p, point=point):
                    if p == point:
                        raise _InjectedCrash(point)

                try:
                    store.compact_doc(d, _crash=_boom)
                except _InjectedCrash:
                    pass
                compaction_kills += 1
                got = store.load(d).checkout_tip().snapshot()
                if got != want:
                    return _verdict(locals(), ok=False,
                                    error=f"compaction kill at {point}: "
                                          f"torn recovery for {d}")

        if crash and rnd == rounds // 2:
            # ---- torn tail + full corruption ---------------------------
            for _ in range(2):
                d = rng.choice(live_docs())
                hyd.evict_to_snapshot(d, why="pre-torn")
                path = store.path(d)
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.seek(max(size - PAGE_SIZE, 0))
                    f.write(os.urandom(min(PAGE_SIZE, size)))
                torn_tails += 1
                try:
                    got = store.load(d).checkout_tip().snapshot()
                except DocQuarantined:
                    # the garbled page ate the only decodable chain —
                    # acceptable only if recovery itself is clean
                    expected_quarantined.add(d)
                    continue
                ok_states = persist_history.get(d, [])[-2:]
                if got not in ok_states:
                    return _verdict(locals(), ok=False,
                                    error=f"torn tail: {d} recovered to "
                                          "a state outside its last two "
                                          "persists")
                control[d] = got
                # disk rolled back; the warm copy (if any) is AHEAD of
                # the home now — drop it so the doc re-hydrates from
                # the recovered state we just asserted
                with hyd._hydrate_lock:
                    hyd._warm.pop(d, None)
                    hyd._touched.pop(d, None)

            corrupt = rng.sample(live_docs(), k=2)
            for d in corrupt:
                hyd.evict_to_snapshot(d, why="pre-corrupt")
                path = store.path(d)
                with open(path, "r+b") as f:
                    f.write(b"\xff" * os.path.getsize(path))
                expected_quarantined.add(d)
            # quarantine is discovered at hydration time: touch them
            for d in corrupt:
                r = sched.submit(d)
                if not r["accepted"]:
                    quarantine_rejects += 1
            sched.drain()

        if crash and rnd == (2 * rounds) // 3:
            # ---- crash-restart -----------------------------------------
            teardown(hyd, sched, checkpoint=False)
            crashes += 1
            store, hyd, sched = build()
            for d in doc_ids:
                if d in expected_quarantined:
                    continue
                try:
                    control[d] = store.load(d) \
                        .checkout_tip().snapshot()
                except DocQuarantined:
                    expected_quarantined.add(d)

        if progress:     # pragma: no cover - human pacing output
            print(f"  round {rnd + 1}/{rounds}: {edits} edits, "
                  f"{len(expected_quarantined)} quarantined, "
                  f"warm={hyd.warm_count()}")

    # ---- final parity ----------------------------------------------------
    teardown(hyd, sched, checkpoint=True)
    verify = TieredStore(root, compact_patch_records=compact_every)
    byte_mismatches = 0
    observed_quarantined = set()
    for d in doc_ids:
        try:
            got = verify.load(d).checkout_tip().snapshot()
        except DocQuarantined:
            observed_quarantined.add(d)
            continue
        if d in expected_quarantined:
            # quarantine is per-STORE state; a fresh store may decode a
            # wiped file's salvageable WAL — only full equality to the
            # expected text counts as survival
            continue
        if got != control[d]:
            byte_mismatches += 1
    rehydrations = verify.counters()["loads"]
    return _verdict(locals(), ok=None)


def _verdict(ns: dict, ok, error: Optional[str] = None) -> dict:
    """Assemble the JSON verdict from run_storage_soak's locals (also
    the early-exit path for mid-run gate failures)."""
    wit = witness_snapshot()
    cold = ns["cold_hist"].snapshot()
    expected = ns["expected_quarantined"]
    observed = ns.get("observed_quarantined", set())
    p99_ok = cold["p99"] <= ns["p99_budget_s"]
    quarantine_match = (observed == expected) if ok is None else False
    leaks = ns["hyd_totals"].get("flush_leaks", 0) \
        + ns["hyd"].counters_snapshot().get("flush_leaks", 0)
    byte_mismatches = ns.get("byte_mismatches", -1)
    if ok is None:
        ok = (byte_mismatches == 0 and quarantine_match and leaks == 0
              and p99_ok and wit["acyclic"]
              and wit["violation_count"] == 0)
    report = {
        "config": {"docs": ns["docs"], "warm": ns["warm"],
                   "rounds": ns["rounds"],
                   "edits_per_round": ns["edits_per_round"],
                   "shards": ns["shards"], "seed": ns["seed"],
                   "compact_every": ns["compact_every"],
                   "churn": ns["churn"], "crash": ns["crash"],
                   "slow": ns["slow"],
                   "p99_budget_s": ns["p99_budget_s"]},
        "edits": ns["edits"],
        "rehydrations": ns.get("rehydrations", 0),
        "byte_mismatches": byte_mismatches,
        "quarantined": sorted(observed),
        "expected_quarantined": sorted(expected),
        "quarantine_match": quarantine_match,
        "quarantine_rejects": ns["quarantine_rejects"],
        "quarantine_leaks": leaks,
        "cold_start": {k: cold.get(k) for k in
                       ("count", "p50", "p90", "p99", "max")},
        "p99_ok": p99_ok,
        "crashes": ns["crashes"],
        "compaction_kills": ns["compaction_kills"],
        "torn_tails": ns["torn_tails"],
        "injected_slow": ns["faults"].injected_slow,
        "hydration": dict(ns["hyd_totals"]),
        "lock_witness": {"acyclic": wit["acyclic"],
                         "violation_count": wit["violation_count"],
                         "edge_count": wit["edge_count"],
                         "acquires": wit["acquires"],
                         "cycles": wit["cycles"]},
        "wall_s": round(time.monotonic() - ns["t_start"], 3),
        "ok": bool(ok),
    }
    if error:
        report["error"] = error
    if ns["own_root"]:
        shutil.rmtree(ns["root"], ignore_errors=True)
    else:
        report["data_dir"] = ns["root"]
    return report
