"""Crash-safe incremental persistence.

Capability mirror of the reference's L6 storage stack:
  * write-ahead log with per-record checksums and corrupt-tail recovery
    (reference: src/wal.rs:40-90 — "each chunk has a checksum, so
    inopportune crashes don't corrupt any data"; WAL records here are
    self-contained v1 patches: option 1 of the reference's design note)
  * page-based incremental store: fixed 4 KiB blocks, atomic whole-block
    writes, double "blit" header slots with monotonic generation counters so
    a torn header write never destroys the previous good header
    (reference: src/storage/README.md, src/storage/mod.rs:103-137,
    src/causalgraph/storage.rs:1-16 blitting buffers)

`DocFile` ties it together: a persistent OpLog = baseline snapshot +
incremental WAL of binary patches; reopening replays the WAL (idempotent —
decode dedups already-known ops) and `compact()` folds the WAL back into the
baseline.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional

from ..encoding.crc32c import crc32c
from ..encoding.decode import decode_into, load_oplog
from ..encoding.encode import ENCODE_FULL, ENCODE_PATCH, encode_oplog
from ..text.oplog import OpLog

PAGE_SIZE = 4096
WAL_MAGIC = b"DTTPUWAL"
STORE_MAGIC = b"DTTPUSTR"


class StorageError(Exception):
    pass


# --------------------------------------------------------------------- WAL

class Wal:
    """Append-only record log. Record frame: u32 len | u32 crc32c | bytes.
    A torn tail (partial frame or bad CRC) is truncated on open."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = None
        self._open()

    def _open(self) -> None:
        exists = os.path.exists(self.path)
        self._f = open(self.path, "a+b")
        if not exists or os.path.getsize(self.path) == 0:
            self._f.write(WAL_MAGIC)
            self._f.flush()
            os.fsync(self._f.fileno())
            return
        # Validate + find the end of good data.
        self._f.seek(0)
        head = self._f.read(len(WAL_MAGIC))
        if head != WAL_MAGIC:
            raise StorageError("bad WAL magic")
        good_end = self._scan_good_end()
        if good_end < os.path.getsize(self.path):
            self._f.truncate(good_end)
            self._f.flush()
            os.fsync(self._f.fileno())

    def _scan_good_end(self) -> int:
        self._f.seek(len(WAL_MAGIC))
        pos = len(WAL_MAGIC)
        while True:
            hdr = self._f.read(8)
            if len(hdr) < 8:
                return pos
            n, crc = struct.unpack("<II", hdr)
            data = self._f.read(n)
            if len(data) < n or crc32c(data) != crc:
                return pos
            pos += 8 + n

    def append(self, record: bytes, sync: bool = True) -> None:
        self._f.seek(0, os.SEEK_END)
        self._f.write(struct.pack("<II", len(record), crc32c(record)))
        self._f.write(record)
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())

    def records(self) -> Iterator[bytes]:
        self._f.seek(len(WAL_MAGIC))
        while True:
            hdr = self._f.read(8)
            if len(hdr) < 8:
                return
            n, crc = struct.unpack("<II", hdr)
            data = self._f.read(n)
            if len(data) < n or crc32c(data) != crc:
                return
            yield data

    def reset(self) -> None:
        self._f.truncate(len(WAL_MAGIC))
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None


# -------------------------------------------------------------- page store

class PageStore:
    """Fixed-size-block store with double-blit header.

    Layout: page 0 and page 1 are alternating header slots
      (magic | u64 generation | u64 data_offset | u64 data_len |
       u32 crc-of-header | u32 crc-of-data). Data blobs live at page-aligned
    extents; a new generation is written to a FRESH extent (past every live
    extent), fsynced, and only then does the *older* header slot get
    rewritten with generation+1 — so a crash at any point leaves at least
    one valid (header, data) pair. `compact()` (via DocFile) keeps growth
    bounded.
    """

    _HDR = struct.Struct("<8sQQQII")

    def __init__(self, path: str) -> None:
        self.path = path
        new = not os.path.exists(path) or os.path.getsize(path) == 0
        self._f = open(path, "r+b" if not new else "w+b")
        if new:
            self._gen = 0
            self._data = b""
            self._off = 2 * PAGE_SIZE
            self._extents = []
            self._write_header(slot=0)
        else:
            self._recover()

    def _read_header(self, slot: int):
        self._f.seek(slot * PAGE_SIZE)
        raw = self._f.read(self._HDR.size)
        if len(raw) < self._HDR.size:
            return None
        magic, gen, doff, dlen, hcrc, dcrc = self._HDR.unpack(raw)
        if magic != STORE_MAGIC:
            return None
        if crc32c(raw[:self._HDR.size - 8]) != hcrc:
            return None
        return (gen, doff, dlen, dcrc)

    def _recover(self) -> None:
        best = None
        self._extents = []
        for slot in (0, 1):
            h = self._read_header(slot)
            if h is None:
                continue
            gen, doff, dlen, dcrc = h
            self._f.seek(doff)
            data = self._f.read(dlen)
            if len(data) < dlen or crc32c(data) != dcrc:
                continue  # data for this header torn; try the other slot
            self._extents.append((doff, dlen))
            if best is None or gen > best[0]:
                best = (gen, data, doff)
        if best is None:
            raise StorageError("no valid header slot")
        self._gen, self._data, self._off = best[0], best[1], best[2]

    def _write_header(self, slot: int) -> None:
        body = self._HDR.pack(STORE_MAGIC, self._gen, self._off,
                              len(self._data), 0, crc32c(self._data))
        hcrc = crc32c(body[:self._HDR.size - 8])
        body = self._HDR.pack(STORE_MAGIC, self._gen, self._off,
                              len(self._data), hcrc, crc32c(self._data))
        self._f.seek(slot * PAGE_SIZE)
        self._f.write(body.ljust(PAGE_SIZE, b"\0"))
        self._f.flush()
        os.fsync(self._f.fileno())

    def write(self, data: bytes) -> None:
        # Fresh page-aligned extent past every live extent.
        end = 2 * PAGE_SIZE
        for (doff, dlen) in getattr(self, "_extents", []):
            end = max(end, doff + dlen)
        off = end + (-end % PAGE_SIZE)
        self._f.seek(off)
        self._f.write(data)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._gen += 1
        self._data = data
        self._off = off
        # Keep only the two most recent extents alive.
        self._extents = (getattr(self, "_extents", [])[-1:]) + [(off, len(data))]
        self._write_header(slot=self._gen % 2)

    def read(self) -> bytes:
        return self._data

    def close(self) -> None:
        self._f.close()


# ------------------------------------------------------------------ DocFile

class DocFile:
    """A persistent OpLog: PageStore baseline + WAL of incremental patches
    (the reference's oplog file + WAL + CG-storage roles combined)."""

    def __init__(self, path: str) -> None:
        self.base = PageStore(path)
        self.wal = Wal(path + ".wal")
        self.oplog = OpLog()
        baseline = self.base.read()
        if baseline:
            decode_into(self.oplog, baseline)
        for rec in self.wal.records():
            decode_into(self.oplog, rec)  # idempotent: dedup via causal graph
        self._saved_version = self.oplog.version

    def append_from(self, src_oplog: OpLog) -> None:
        """Persist everything `src_oplog` has that we haven't saved."""
        patch = encode_oplog(src_oplog, ENCODE_PATCH,
                             from_version=self._intersect(src_oplog))
        self.wal.append(patch)
        decode_into(self.oplog, patch)
        self._saved_version = self.oplog.version

    def _intersect(self, src: OpLog) -> List[int]:
        from ..causalgraph.summary import (intersect_with_summary,
                                           summarize_versions)
        common, _ = intersect_with_summary(src.cg,
                                           summarize_versions(self.oplog.cg))
        return common

    def compact(self, _crash=None) -> None:
        """Fold the WAL into the baseline (reference: dt-cli repack
        role). fsync ordering: PageStore.write makes the new baseline
        extent + header durable BEFORE the WAL truncates — a crash
        between the two steps replays the stale WAL onto the new
        baseline, which the idempotent decode dedups to the same
        oplog. `_crash(point)` is a fault-injection hook fired after
        each durable step ("baseline_written", "wal_reset")."""
        self.base.write(encode_oplog(self.oplog, ENCODE_FULL))
        if _crash is not None:
            _crash("baseline_written")
        self.wal.reset()
        if _crash is not None:
            _crash("wal_reset")

    def close(self) -> None:
        self.base.close()
        self.wal.close()
