"""Position-space conversions: unicode chars <-> UTF-16 code units (wchars).

Capability mirror of the reference's wchar conversion feature (reference:
src/unicount.rs + the wchar_conversion cargo feature; branch.rs
insert_at_wchar/delete_at_wchar): JS and Swift clients address text in UTF-16
code units, while all CRDT math here is in unicode chars. Characters outside
the BMP (>= U+10000) occupy two UTF-16 units.
"""

from __future__ import annotations


def count_utf16(s: str) -> int:
    """Number of UTF-16 code units in s."""
    return len(s) + sum(1 for c in s if ord(c) >= 0x10000)


def chars_to_wchars(s: str, char_pos: int) -> int:
    """Char offset -> UTF-16 offset."""
    assert 0 <= char_pos <= len(s)
    return char_pos + sum(1 for c in s[:char_pos] if ord(c) >= 0x10000)


def wchars_to_chars(s: str, wchar_pos: int) -> int:
    """UTF-16 offset -> char offset. Must not land inside a surrogate pair."""
    w = 0
    for i, c in enumerate(s):
        if w == wchar_pos:
            return i
        w += 2 if ord(c) >= 0x10000 else 1
        if w > wchar_pos:
            raise ValueError("wchar position splits a surrogate pair")
    if w == wchar_pos:
        return len(s)
    raise ValueError("wchar position out of range")


def chars_to_bytes(s: str, char_pos: int) -> int:
    """Char offset -> UTF-8 byte offset (reference: unicount.rs:8-30)."""
    return len(s[:char_pos].encode("utf8"))


def bytes_to_chars(s: str, byte_pos: int) -> int:
    b = s.encode("utf8")
    return len(b[:byte_pos].decode("utf8"))
