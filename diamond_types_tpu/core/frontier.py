"""Frontiers: a sorted list of LVs naming a version (the heads of the DAG).

The reference wraps this in a smallvec newtype with advance/retreat methods
(reference: src/frontier.rs:23). Here a frontier is a plain sorted `list[int]`
(always deduplicated, never containing ROOT). Graph-dependent movement
(advance/retreat) lives in `causalgraph.graph` to keep this module pure.
"""

from __future__ import annotations

from bisect import insort
from typing import Iterable, List, Sequence

Frontier = List[int]


def frontier_root() -> Frontier:
    return []


def frontier_from(vals: Iterable[int]) -> Frontier:
    return sorted(set(vals))


def frontier_eq(a: Sequence[int], b: Sequence[int]) -> bool:
    return list(a) == list(b)


def frontier_is_sorted(f: Sequence[int]) -> bool:
    return all(f[i] < f[i + 1] for i in range(len(f) - 1))


def insert_nonoverlapping(f: Frontier, v: int) -> None:
    """Insert `v` keeping the frontier sorted (reference: src/frontier.rs:343)."""
    assert v not in f
    insort(f, v)


def replace_with_1(f: Frontier, v: int) -> None:
    f.clear()
    f.append(v)
