"""Half-open integer spans — the universal currency of local versions (LVs).

The reference models these as `DTRange` (reference: src/dtrange.rs:19) and
reversible ranges as `RangeRev` (reference: src/rev_range.rs:20). Here spans
are plain `(start, end)` tuples so they vectorize directly into numpy / JAX
arrays; helpers are free functions instead of trait impls.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

Span = Tuple[int, int]  # half-open [start, end)

#: Sentinel id base for tracker placeholder ("underwater") items: content that
#: existed before the conflict zone being merged. Mirrors UNDERWATER_START
#: (reference: src/dtrange.rs:199) but any value far above real LVs works.
UNDERWATER_START = 1 << 62


def span_len(s: Span) -> int:
    return s[1] - s[0]


def span_is_empty(s: Span) -> bool:
    return s[1] <= s[0]


def span_contains(s: Span, v: int) -> bool:
    return s[0] <= v < s[1]


def span_last(s: Span) -> int:
    return s[1] - 1


def spans_overlap(a: Span, b: Span) -> bool:
    return a[0] < b[1] and b[0] < a[1]


def span_intersect(a: Span, b: Span) -> Span | None:
    lo, hi = max(a[0], b[0]), min(a[1], b[1])
    return (lo, hi) if lo < hi else None


def push_rle(out: List[Span], s: Span) -> None:
    """Append `s`, merging with the trailing span when contiguous (ascending)."""
    if out and out[-1][1] == s[0]:
        out[-1] = (out[-1][0], s[1])
    else:
        out.append(s)


def push_reversed_rle(out: List[Span], s: Span) -> None:
    """Append `s` to a descending-ordered list, merging when contiguous.

    Mirrors AppendRle::push_reversed_rle (reference: crates/rle/src/append_rle.rs):
    the list holds spans from highest to lowest; a new span glues onto the
    *front* of the last pushed span.
    """
    if out and s[1] == out[-1][0]:
        out[-1] = (s[0], out[-1][1])
    else:
        out.append(s)


def merge_spans(spans: Iterable[Span]) -> List[Span]:
    """Normalize: sort ascending and coalesce overlapping/adjacent spans."""
    out: List[Span] = []
    for s in sorted(spans):
        if out and s[0] <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], s[1]))
        else:
            out.append(s)
    return out


# --- Reversible ranges -------------------------------------------------------
# A RangeRev is (start, end, fwd). `fwd=False` encodes runs produced by e.g.
# backspacing, where successive LVs target successively *earlier* positions.

RangeRev = Tuple[int, int, bool]


def rr_len(r: RangeRev) -> int:
    return r[1] - r[0]


def rr_sub(r: RangeRev, offset: int, end_offset: int) -> Span:
    """Sub-span [offset, end_offset) of a RangeRev, in target-id space.

    For a forward run, offsets count from `start` upward; for a reversed run
    they count from the *end* downward (reference: src/rev_range.rs `range()`).
    """
    start, end, fwd = r
    if fwd:
        return (start + offset, start + end_offset)
    else:
        return (end - end_offset, end - offset)
