"""Invariants checked after every scheduler action.

Two kinds:

  * STATE invariants read the current world directly
    (floor-coverage, the per-scan half of single-active);
  * HISTORY invariants are phrased over auxiliary variables the
    checker accumulates across actions — promise grants, activation
    sets, floor watermarks. The history lives in the CHECKER, not in
    any node, so it survives simulated crashes; that is what makes
    "a recovered voter must not re-promise a taken epoch" checkable
    at all (the node's own table is exactly what the crash lost).

Deliberately NOT an invariant: "at most one host passes the merge
admit gate per doc" *across epochs*. An expired-lease holder renews
locally after a partition heals, and CRDT merges commute, so stale
merges reconcile — the protocol's actual safety claims are the
per-(doc, epoch) ones below plus convergence. See CHECKING.md.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ...replicate.ownership import (ACTIVE, DRAINING, GRANTED,
                                    GRANTING, TRANSFER)
from .world import SimWorld

_HELD = (ACTIVE, GRANTING, DRAINING, TRANSFER, GRANTED)

ALL_INVARIANTS = (
    "single-active",        # per (doc, epoch): at most one self-ACTIVE
    "promise-exclusivity",  # a voter promises (doc, epoch) to one holder
    "floor-monotonic",      # fencing floor never regresses (incl. restart)
    "floor-coverage",       # floor >= every promised / self-held epoch
    "own-lease-stability",  # peer echo never shortens our ACTIVE lease
    "tie-break-direction",  # equal-epoch arbitration keeps the smaller id
    "convergence",          # byte-identical state after quiesce (leaves)
    "no-acked-loss",        # every acked (queued) op survives to quiesce
    "group-epoch-exclusivity",  # no writer-group registration below
                                # its own host's fencing floor
)


class Violation(Exception):
    def __init__(self, invariant: str, message: str) -> None:
        self.invariant = invariant
        self.message = message
        super().__init__(f"{invariant}: {message}")


class InvariantChecker:
    def __init__(self, world: SimWorld,
                 names: Tuple[str, ...]) -> None:
        self.world = world
        self.names = tuple(names)
        # ghost state (survives node crashes by construction)
        self.active_holders: Dict[Tuple[str, int], set] = {}
        self.promise_hist: Dict[Tuple[str, str, int], str] = {}
        self.floor_hist: Dict[Tuple[str, str], int] = {}
        self.event_idx = 0
        self.pre: Dict[Tuple[str, str], Tuple[int, float]] = {}

    # ---- per-action protocol ----
    def snapshot_pre(self) -> None:
        """Capture every self-held ACTIVE lease before the action, for
        the own-lease-stability delta check."""
        pre = {}
        w = self.world
        for n in w.alive():
            mgr = w.nodes[n].leases
            with mgr.lock:
                for doc, l in mgr.leases.items():
                    if l.holder == n and l.state == ACTIVE:
                        pre[(n, doc)] = (l.epoch, l.expires_at)
        self.pre = pre

    def check_after(self, action_op: str) -> Optional[Violation]:
        """Fold the post-action world into the histories and evaluate
        every enabled invariant. Histories are ALWAYS folded (even for
        disabled invariants) so fingerprints and later checks see a
        consistent ledger. Returns the first violation found."""
        w = self.world
        failures: List[Violation] = []
        for n in w.alive():
            mgr = w.nodes[n].leases
            with mgr.lock:
                leases = {d: (l.holder, l.epoch, l.state, l.expires_at)
                          for d, l in mgr.leases.items()}
                promised = dict(mgr.promised)
                floors = dict(mgr.max_epoch)
                activations = [(e["doc"], e["epoch"])
                               for e in mgr.activation_log]
            for doc, ep in activations:
                self.active_holders.setdefault((doc, ep), set()).add(n)
            for d, (h, ep, st, _x) in leases.items():
                if h == n and st == ACTIVE:
                    self.active_holders.setdefault((d, ep),
                                                   set()).add(n)
            for d, (ep, h) in promised.items():
                key = (n, d, ep)
                prev = self.promise_hist.get(key)
                if prev is None:
                    self.promise_hist[key] = h
                elif prev != h and "promise-exclusivity" in self.names:
                    failures.append(Violation(
                        "promise-exclusivity",
                        f"voter {n} promised (doc {d}, epoch {ep}) to "
                        f"both {prev} and {h}"))
            for d in set(floors) | set(promised) | set(leases):
                f = floors.get(d, 0)
                key2 = (n, d)
                prev_f = self.floor_hist.get(key2, 0)
                if f < prev_f and "floor-monotonic" in self.names:
                    failures.append(Violation(
                        "floor-monotonic",
                        f"node {n} doc {d} fencing floor regressed "
                        f"{prev_f} -> {f}"))
                self.floor_hist[key2] = max(prev_f, f)
                if "floor-coverage" in self.names:
                    p = promised.get(d)
                    if p is not None and f < p[0]:
                        failures.append(Violation(
                            "floor-coverage",
                            f"node {n} doc {d} floor {f} below its own "
                            f"promise for epoch {p[0]} — the fencing "
                            f"token was not raised"))
                    ld = leases.get(d)
                    if ld is not None and ld[0] == n \
                            and ld[2] in _HELD and f < ld[1]:
                        failures.append(Violation(
                            "floor-coverage",
                            f"node {n} doc {d} floor {f} below held "
                            f"lease epoch {ld[1]}"))
            if "group-epoch-exclusivity" in self.names:
                groups = getattr(w.nodes[n], "writergroups", None)
                if groups is not None:
                    for d, g in groups.entries():
                        f = floors.get(d, 0)
                        if g.epoch < f:
                            failures.append(Violation(
                                "group-epoch-exclusivity",
                                f"node {n} doc {d} holds a writer-"
                                f"group registration at epoch "
                                f"{g.epoch} below its own fencing "
                                f"floor {f} — a member of the "
                                f"superseded group could still admit "
                                f"writes"))
        if "single-active" in self.names:
            for (d, ep), holders in self.active_holders.items():
                if len(holders) > 1:
                    failures.append(Violation(
                        "single-active",
                        f"doc {d} epoch {ep} was ACTIVE on "
                        f"{sorted(holders)} — two majorities for one "
                        f"epoch"))
        if "own-lease-stability" in self.names \
                and action_op in ("ae", "dup"):
            for (n, d), (ep, exp) in self.pre.items():
                if n in w.crashed:
                    continue
                l = w.nodes[n].leases.get(d)
                if l is not None and l.holder == n and l.epoch == ep \
                        and l.state == ACTIVE \
                        and l.expires_at < exp - 1e-9:
                    failures.append(Violation(
                        "own-lease-stability",
                        f"node {n} doc {d} epoch {ep}: own ACTIVE "
                        f"lease shortened by a peer echo "
                        f"({exp:.3f} -> {l.expires_at:.3f})"))
        new_events = w.events[self.event_idx:]
        self.event_idx = len(w.events)
        if "tie-break-direction" in self.names:
            for ev in new_events:
                if ev.get("kind") != "lease_tie_break":
                    continue
                n = ev["node"]
                if n in w.crashed:
                    continue
                want = min(ev["incumbent"], ev["claimant"])
                l = w.nodes[n].leases.get(ev["doc"])
                if l is not None and l.epoch == ev["epoch"] \
                        and l.holder != want:
                    failures.append(Violation(
                        "tie-break-direction",
                        f"node {n} doc {ev['doc']} epoch "
                        f"{ev['epoch']}: arbitration kept {l.holder}, "
                        f"deterministic rule requires {want}"))
        return failures[0] if failures else None

    # ---- leaf-only quiescence check (mutates the world) ----
    def check_convergence(self, max_rounds: int = 6) \
            -> Optional[Violation]:
        """Heal every link, restart every crashed node, flush every
        surviving admission queue, run bounded anti-entropy to
        fixpoint: all replicas must reach byte-identical text and
        identical frontiers, and every op still on the acked ledger
        must appear in the converged state (no-acked-loss — queued
        work a completed migration evicted without draining is exactly
        what this catches). Run only at leaf states — it consumes the
        world."""
        if "convergence" not in self.names \
                and "no-acked-loss" not in self.names:
            return None
        w = self.world
        for pair in list(w.cut_links):
            a, b = tuple(pair)
            w.heal(a, b)
        for n in list(w.crashed):
            w.restart(n)
        # surviving queues eventually flush; only ops DROPPED earlier
        # (not merely still queued) can violate no-acked-loss
        for n in w.node_ids:
            w.stores[n].scheduler.drain()
        docs = set()
        for n in w.node_ids:
            docs |= set(w.stores[n].docs)
        if not docs:
            return None
        for _ in range(max_rounds):
            if self._frontiers_equal(docs):
                break
            for n in w.node_ids:
                w.nodes[n].antientropy.run_round()
        if "convergence" in self.names:
            if not self._frontiers_equal(docs):
                return Violation(
                    "convergence",
                    f"frontiers still differ after {max_rounds} "
                    f"quiesce rounds")
            for d in sorted(docs):
                texts = {n: w.text_of(n, d) for n in w.node_ids}
                if len(set(texts.values())) > 1:
                    return Violation(
                        "convergence",
                        f"doc {d} texts diverge after quiesce: "
                        f"{ {n: t[:24] for n, t in texts.items()} }")
        if "no-acked-loss" in self.names:
            for d, chars in sorted(w.acked.items()):
                for n in w.node_ids:
                    text = w.text_of(n, d)
                    missing = [c for c in chars if c not in text]
                    if missing:
                        return Violation(
                            "no-acked-loss",
                            f"doc {d}: acked ops {missing} absent "
                            f"from {n}'s converged text {text[:24]!r}")
        return None

    def _frontiers_equal(self, docs) -> bool:
        w = self.world
        for d in docs:
            frontiers = {self._canon(w.frontier_of(n, d))
                         for n in w.node_ids}
            if len(frontiers) > 1:
                return False
        return True

    @staticmethod
    def _canon(frontier) -> str:
        if isinstance(frontier, (list, tuple)):
            return json.dumps(sorted(
                (json.dumps(x, sort_keys=True, default=str)
                 for x in frontier)))
        return json.dumps(frontier, sort_keys=True, default=str)
