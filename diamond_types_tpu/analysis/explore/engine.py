"""Replay-based exhaustive exploration with sleep-set reduction.

The protocol objects hold locks and thread primitives, so worlds are
not snapshot/restore-able. Instead each DFS edge rebuilds a FRESH
world and deterministically replays the choice prefix — O(depth) work
per visited transition, bought back by:

  * fingerprint dedup — a canonical hash of all protocol-visible
    state (leases, floors, promises, membership, frontiers, journals,
    link/crash/clock state, action budgets). Revisiting a fingerprint
    skips the subtree, with the standard sleep-set soundness rule: a
    cached state only covers a new visit when it was explored with a
    sleep set that is a SUBSET of the current one;
  * sleep sets — after exploring sibling `a`, later siblings need not
    re-explore orders that merely commute with `a`; the child of `b`
    inherits {x in sleep : independent(b, x)}.

On violation, the witness trace is minimized by greedy
choice-deletion to fixpoint and re-validated by replay from a fresh
world — the emitted trace is replayable verbatim (`replay_trace`),
which is how pytest pins it.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Dict, List, Optional, Tuple

from .invariants import ALL_INVARIANTS, InvariantChecker, Violation
from .model import SCENARIOS, Action, Scenario, independent
from .world import SimWorld


class _Budget(Exception):
    """Raised to unwind the DFS when max_states is hit."""


def _fingerprint(world: SimWorld, counts: Dict[str, int]) -> str:
    """Canonical hash of everything that can influence future
    transitions. Floats rounded so equal virtual-time states compare
    equal."""
    doc: dict = {"now": round(world.now, 3),
                 "crashed": sorted(world.crashed),
                 "cut": sorted(sorted(p) for p in world.cut_links),
                 "counts": dict(sorted(counts.items())),
                 "edit_seq": world.edit_seq,
                 "acked": {d: list(v)
                           for d, v in sorted(world.acked.items())},
                 "last_msg": {k: v for k, v in
                              sorted(world.last_lease_msg.items())},
                 "nodes": {}}
    for n in world.node_ids:
        journal = world.journals[n].fingerprint()
        pending = {d: list(v) for d, v in
                   sorted(world.stores[n].pending.items())}
        if n in world.crashed:
            doc["nodes"][n] = {"crashed": True, "journal": journal,
                               "pending": pending}
            continue
        node = world.nodes[n]
        mgr = node.leases
        with mgr.lock:
            leases = {d: [l.holder, l.epoch, l.state,
                          round(l.expires_at, 3)]
                      for d, l in sorted(mgr.leases.items())}
            promised = {d: list(p)
                        for d, p in sorted(mgr.promised.items())}
            floors = dict(sorted(mgr.max_epoch.items()))
        frontiers = {d: world.frontier_of(n, d)
                     for d in world.stores[n].doc_ids()}
        doc["nodes"][n] = {
            "leases": leases, "promised": promised, "floors": floors,
            "rejoining": node.rejoining,
            "incarnation": node.membership.self_incarnation,
            "members": node.membership.gossip_payload(),
            "merged": sorted(node.merged_docs),
            "frontiers": frontiers,
            "journal": journal,
            "pending": pending,
            "overrides": node.overrides.as_json(),
            "groups": node.writergroups.fingerprint(),
        }
    blob = json.dumps(doc, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode("utf8")).hexdigest()


def _run_trace(scenario: Scenario, actions: List[Action],
               invariants: Tuple[str, ...], mutation=None,
               converge: bool = False):
    """Fresh world + deterministic replay. Returns
    (world, checker, violation | None, step_index)."""
    world = scenario.build(mutation)
    checker = InvariantChecker(world, invariants)
    checker.check_after("init")
    for i, a in enumerate(actions):
        if not a.enabled(world):
            # can happen only for hand-edited or shrunk candidate
            # traces (e.g. restart with its crash deleted): reject the
            # trace rather than apply an impossible action
            return world, checker, None, i
        checker.snapshot_pre()
        a.apply(world)
        v = checker.check_after(a.op)
        if v is not None:
            return world, checker, v, i
    if converge:
        v = checker.check_convergence()
        if v is not None:
            return world, checker, v, len(actions) - 1
    return world, checker, None, len(actions)


def _shrink(scenario: Scenario, actions: List[Action],
            invariants: Tuple[str, ...], invariant: str,
            mutation=None) -> List[Action]:
    """Greedy choice-deletion to fixpoint: drop any single action whose
    removal preserves a violation of the SAME invariant, truncate past
    the violation point, repeat until no deletion survives."""
    conv = invariant == "convergence"
    changed = True
    while changed:
        changed = False
        for i in range(len(actions)):
            cand = actions[:i] + actions[i + 1:]
            _w, _c, v, step = _run_trace(scenario, cand, invariants,
                                         mutation, converge=conv)
            if v is not None and v.invariant == invariant:
                actions = cand if conv else cand[:step + 1]
                changed = True
                break
    return actions


def explore(scenario_name: str, depth: int = 4, seed: int = 0,
            max_states: Optional[int] = None,
            invariants: Optional[Tuple[str, ...]] = None,
            mutation=None, shrink: bool = True) -> dict:
    """Exhaustively enumerate interleavings of `scenario_name` to
    `depth`, checking invariants at every state. Stops at the first
    violation (minimized + replayable); otherwise reports the explored
    envelope honestly (complete vs truncated-by-budget)."""
    scenario = SCENARIOS[scenario_name]
    inv = tuple(invariants) if invariants else scenario.invariants
    for name in inv:
        if name not in ALL_INVARIANTS:
            raise ValueError(f"unknown invariant {name!r}")
    t0 = time.monotonic()
    stats = {"states": 1, "transitions": 0, "dedup_hits": 0,
             "sleep_skips": 0, "truncated": False}
    seen: Dict[str, List[frozenset]] = {}
    found: List[dict] = []

    def order(acts: List[Action]) -> List[Action]:
        acts = sorted(acts, key=lambda a: a.label)
        if seed:
            import random
            random.Random((seed, len(acts))).shuffle(acts)
        return acts

    def covered(fp: str, sleep: frozenset) -> bool:
        prior = seen.get(fp)
        if prior is not None:
            for ss in prior:
                if ss <= sleep:
                    stats["dedup_hits"] += 1
                    return True
            prior.append(sleep)
        else:
            seen[fp] = [sleep]
        return False

    def dfs(world: SimWorld, trace: List[Action],
            counts: Dict[str, int], sleep: frozenset,
            d: int) -> None:
        if found:
            return
        enabled = order(scenario.enabled_actions(world, counts))
        cur_sleep = set(sleep)
        for a in enabled:
            if found:
                return
            if a.label in cur_sleep:
                stats["sleep_skips"] += 1
                continue
            if max_states is not None \
                    and stats["states"] >= max_states:
                stats["truncated"] = True
                raise _Budget()
            child = trace + [a]
            is_leaf = d + 1 >= depth
            w2, c2, v, step = _run_trace(scenario, child, inv,
                                         mutation, converge=is_leaf)
            stats["transitions"] += 1
            stats["states"] += 1
            if v is not None:
                witness = child[:step + 1] if v.invariant != \
                    "convergence" else child
                minimized = _shrink(scenario, list(witness), inv,
                                    v.invariant, mutation) \
                    if shrink else list(witness)
                found.append({
                    "invariant": v.invariant, "message": v.message,
                    "trace": [x.as_json() for x in witness],
                    "minimized_trace": [x.as_json()
                                        for x in minimized]})
                return
            if not is_leaf:
                counts2 = dict(counts)
                counts2[a.op] = counts2.get(a.op, 0) + 1
                child_sleep = frozenset(
                    x for x in cur_sleep
                    if independent(a, _label_map[x]))
                fp = _fingerprint(w2, counts2)
                if not covered(fp, child_sleep):
                    dfs(w2, child, counts2, child_sleep, d + 1)
            cur_sleep.add(a.label)

    _label_map = {a.label: a for a in scenario.actions}
    root = scenario.build(mutation)
    root_checker = InvariantChecker(root, inv)
    v0 = root_checker.check_after("init")
    if v0 is not None:
        found.append({"invariant": v0.invariant, "message": v0.message,
                      "trace": [], "minimized_trace": []})
    try:
        if not found:
            dfs(root, [], {}, frozenset(), 0)
    except _Budget:
        pass
    wall = max(time.monotonic() - t0, 1e-9)
    report = {
        "scenario": scenario_name, "depth": depth, "seed": seed,
        "invariants": list(inv),
        "mutation": getattr(mutation, "name", None),
        "bounds": dict(scenario.bounds),
        "states": stats["states"],
        "transitions": stats["transitions"],
        "dedup_hits": stats["dedup_hits"],
        "sleep_skips": stats["sleep_skips"],
        "max_states": max_states,
        "truncated": stats["truncated"],
        "complete": not stats["truncated"] and not found,
        "wall_s": round(wall, 3),
        "states_per_s": round(stats["states"] / wall, 1),
        "violations": found,
        "ok": not found,
    }
    return report


def replay_trace(trace_doc: dict, mutation=None) -> dict:
    """Re-execute an emitted (minimized) trace from a fresh world.
    `trace_doc` is one entry of report['violations'] plus the
    scenario/invariants context, i.e. the JSON `dt-explore` writes.
    Returns {ok, violation, invariant, message}: ok=True means the
    replay REPRODUCED the recorded invariant violation."""
    scenario = SCENARIOS[trace_doc["scenario"]]
    inv = tuple(trace_doc.get("invariants") or scenario.invariants)
    actions = [Action.from_json(a)
               for a in trace_doc["minimized_trace"]]
    conv = trace_doc.get("invariant") == "convergence"
    _w, _c, v, _step = _run_trace(scenario, actions, inv, mutation,
                                  converge=conv)
    return {
        "ok": v is not None
        and v.invariant == trace_doc.get("invariant"),
        "violation": v is not None,
        "invariant": v.invariant if v is not None else None,
        "message": v.message if v is not None else None,
    }


# ---- obs publication (same pattern as analysis.lint) ----
_last_report: Optional[dict] = None


def publish_report(report: dict) -> None:
    global _last_report
    _last_report = {
        "scenario": report["scenario"], "depth": report["depth"],
        "states": report["states"],
        "states_per_s": report["states_per_s"],
        "violations": len(report["violations"]),
        "complete": report["complete"], "ok": report["ok"],
    }


def last_report() -> Optional[dict]:
    return _last_report
