"""Protocol model checker: exhaustive interleaving exploration of the
real lease/quorum/fencing code under a virtual scheduler.

Entry points:
  explore(scenario, depth=...)  -> report dict (violations minimized,
                                   replayable)
  replay_trace(trace_doc)       -> re-execute an emitted trace
  SCENARIOS / MUTATIONS         -> the bounded models and the seeded
                                   bugs that prove detection power
See CHECKING.md for the state model and the soundness boundary.
"""

from .engine import explore, last_report, publish_report, replay_trace
from .invariants import ALL_INVARIANTS, InvariantChecker, Violation
from .model import SCENARIOS, Action, Scenario, independent
from .mutations import MUTATIONS, Mutation
from .world import SimWorld

__all__ = [
    "explore", "replay_trace", "publish_report", "last_report",
    "SCENARIOS", "Scenario", "Action", "independent",
    "MUTATIONS", "Mutation",
    "ALL_INVARIANTS", "InvariantChecker", "Violation",
    "SimWorld",
]
