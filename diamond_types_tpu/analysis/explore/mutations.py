"""Seeded protocol mutations: the checker's own test of power.

Each mutation re-introduces a specific protocol bug (patched onto the
real objects at world-build time) together with the scenario in which
the explorer must find it and the invariant(s) expected to fire.
`dt-explore --mutate` runs all of them and fails unless EVERY mutation
is detected with a minimized, replayable trace — an analyzer that
cannot catch known-bad variants proves nothing about the real tree.

Node-level patches are re-applied on simulated restart (the world
rebuilds nodes through the same hook), so a mutation cannot be
"cured" by crashing.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from ...replicate.ownership import Lease


class Mutation:
    def __init__(self, name: str, scenario: str,
                 expect: Tuple[str, ...], description: str,
                 apply_node: Optional[Callable] = None,
                 apply_world: Optional[Callable] = None,
                 depth: int = 5) -> None:
        self.name = name
        self.scenario = scenario
        self.expect = expect            # acceptable firing invariants
        self.description = description
        self.apply_node = apply_node    # fn(ReplicaNode) -> None
        self.apply_world = apply_world  # fn(SimWorld) -> None
        self.depth = depth              # search depth that suffices


def _observe_remote_variant(mgr, own_guard: bool,
                            smaller_wins: bool) -> Callable:
    """Re-implementation of LeaseManager.observe_remote with the two
    guards the mutations remove made explicit. With both flags True
    this is behavior-identical to the real method."""

    def observe_remote(doc_id: str, holder: str, epoch: int,
                       state: str, ttl_s: float) -> None:
        now = mgr.clock()
        with mgr.lock:
            cur = mgr.leases.get(doc_id)
            if cur is not None:
                if cur.epoch > epoch:
                    return
                if cur.epoch == epoch:
                    if cur.holder == holder:
                        if own_guard and cur.holder == mgr.self_id:
                            return
                        cur.state = state
                        cur.expires_at = now + max(ttl_s, 0.0)
                        return
                    mgr._bump("tie_breaks")
                    mgr._event("lease_tie_break", doc_id, epoch,
                               incumbent=cur.holder, claimant=holder)
                    keep = (cur.holder < holder) if smaller_wins \
                        else (cur.holder > holder)
                    if keep:
                        return
            mgr.leases[doc_id] = Lease(
                doc_id, holder, epoch, state, now + max(ttl_s, 0.0),
                now=now)
            mgr._note_epoch_locked(doc_id, epoch)

    return observe_remote


def _mut_floor_drop(node) -> None:
    # promises/observations no longer raise the fencing floor
    node.leases._note_epoch_locked = lambda doc_id, epoch: None


def _mut_promise_skip(world) -> None:
    # voter promises are granted in memory but never persisted: a
    # crashed voter forgets and can re-promise a taken epoch
    for j in world.journals.values():
        j.note_promise = lambda doc_id, epoch, holder: None


def _mut_own_echo(node) -> None:
    node.leases.observe_remote = _observe_remote_variant(
        node.leases, own_guard=False, smaller_wins=True)


def _mut_tiebreak_invert(node) -> None:
    node.leases.observe_remote = _observe_remote_variant(
        node.leases, own_guard=True, smaller_wins=False)


def _mut_group_drain_skip(node) -> None:
    # the member-side demotion fence evicts the admission queue
    # WITHOUT the drain barrier: acked member writes die at demote
    node._group_demote_drains = False


def _mut_promote_unratified(node) -> None:
    # promotion commits without a majority round and without raising
    # the leader's own fencing floor — one coherent bug ("the group
    # grant is self-issued"): the leader's re-keyed lease epoch is not
    # covered by its floor, so nothing fences the superseded epoch
    real = node.promote_writer_group

    def promote(doc_id, members):
        rq = node._run_quorum
        note = node.leases._note_epoch_locked
        node._run_quorum = lambda d, e, t: True
        node.leases._note_epoch_locked = lambda d, e: None
        try:
            return real(doc_id, members)
        finally:
            node._run_quorum = rq
            node.leases._note_epoch_locked = note

    node.promote_writer_group = promote


def _mut_drain_skip(world) -> None:
    # the handoff's drain barrier no-ops: the final transfer patch is
    # cut while acked writes still sit in the admission queue, and the
    # source's post-migration eviction then drops them on the floor.
    # Applied to the STORES (which survive simulated crash/restart),
    # so a restart cannot cure it.
    for store in world.stores.values():
        store.scheduler.drain = lambda: None


MUTATIONS: Dict[str, Mutation] = {m.name: m for m in (
    Mutation(
        "floor-drop", scenario="renewal",
        expect=("floor-coverage",),
        description="_note_epoch_locked no-ops: promising or observing "
                    "an epoch no longer raises the fencing floor, so "
                    "stale holders are never fenced off",
        apply_node=_mut_floor_drop, depth=2),
    Mutation(
        "promise-persist-skip", scenario="crash-recovery",
        expect=("promise-exclusivity", "single-active"),
        description="journal.note_promise no-ops: a voter's promise "
                    "table does not survive a crash, so a recovered "
                    "voter can promise one epoch to two holders — two "
                    "majorities for one (doc, epoch)",
        apply_world=_mut_promise_skip, depth=5),
    Mutation(
        "own-echo-ttl", scenario="renewal",
        expect=("own-lease-stability",),
        description="observe_remote loses the own-lease guard: a "
                    "peer's stale echo of our lease overwrites the "
                    "locally-renewed TTL, shortening our own ACTIVE "
                    "lease",
        apply_node=_mut_own_echo, depth=6),
    Mutation(
        "tie-break-invert", scenario="tiebreak",
        expect=("tie-break-direction",),
        description="equal-epoch arbitration keeps the lexically "
                    "LARGER holder: hosts that see the two claims in "
                    "different orders resolve to different winners",
        apply_node=_mut_tiebreak_invert, depth=3),
    Mutation(
        "drain-skip", scenario="migration",
        expect=("no-acked-loss",),
        description="the migration handoff skips the drain barrier: "
                    "the transfer patch misses still-queued acked "
                    "writes and the source's post-migration eviction "
                    "loses them — an acknowledged op vanishes from "
                    "the converged state",
        apply_world=_mut_drain_skip, depth=2),
    Mutation(
        "demote-without-drain", scenario="writer-group",
        expect=("no-acked-loss",),
        description="the member-side demotion fence skips its drain "
                    "barrier: a fenced member evicts its admission "
                    "queue with acked group writes still in it — an "
                    "acknowledged member write vanishes from the "
                    "converged state",
        apply_node=_mut_group_drain_skip, depth=3),
    Mutation(
        "promote-floor-drop", scenario="writer-group",
        expect=("floor-coverage", "single-active"),
        description="promotion commits without quorum ratification or "
                    "the leader's floor raise: the re-keyed lease "
                    "epoch is uncovered by the fencing floor, so the "
                    "superseded single-writer epoch is never fenced "
                    "off",
        apply_node=_mut_promote_unratified, depth=2),
)}
