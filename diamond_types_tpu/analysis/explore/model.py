"""Actions and scenarios: the explored state machine's alphabet.

An action is one ATOMIC step of the virtual scheduler — a protocol
step on one node (acquire/renew, probe+maintain, anti-entropy round),
an environment event (clock tick, link cut/heal, crash/restart), or an
adversarial delivery (duplicate of the last lease message). Atomicity
is the model's core approximation: the real system interleaves at
instruction granularity under locks, the model at action granularity
(CHECKING.md discusses what that excludes and why the lock witness +
dt-lint carry the intra-action burden).

`acquire` calls `LeaseManager.ensure_local(doc, True)` directly — the
node acts as if placement selected it. That models divergent
rendezvous views (the adversarial case) without enumerating membership
states; with the quorum hook attached, safety must hold anyway.

Scenarios bound each action's occurrence count per trace. The bounds
are part of the model (the state space is finite because of them) and
are reported with every verdict — a clean verdict means "no violation
within these bounds", nothing stronger.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ...replicate.ownership import ACTIVE
from .world import SimWorld

# footprint token meaning "conflicts with everything"
ALL = "*"


class Action:
    __slots__ = ("op", "node", "peer", "doc")

    def __init__(self, op: str, node: Optional[str] = None,
                 peer: Optional[str] = None,
                 doc: Optional[str] = None) -> None:
        self.op = op
        self.node = node
        self.peer = peer
        self.doc = doc

    @property
    def label(self) -> str:
        if self.op == "tick":
            return "tick"
        if self.op in ("cut", "heal"):
            return f"{self.op}({self.node},{self.peer})"
        if self.op in ("edit", "qedit", "gedit", "acquire", "demote"):
            return f"{self.op}({self.node},{self.doc})"
        if self.op in ("migrate", "promote"):
            return f"{self.op}({self.node},{self.peer},{self.doc})"
        return f"{self.op}({self.node})"

    def __repr__(self) -> str:
        return self.label

    def as_json(self) -> dict:
        out = {"op": self.op}
        for k in ("node", "peer", "doc"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out

    @classmethod
    def from_json(cls, doc: dict) -> "Action":
        return cls(doc["op"], node=doc.get("node"),
                   peer=doc.get("peer"), doc=doc.get("doc"))

    # ---- scheduler interface ----
    def enabled(self, world: SimWorld) -> bool:
        op = self.op
        if op == "tick":
            return True
        if op == "cut":
            return not world.is_cut(self.node, self.peer)
        if op == "heal":
            return world.is_cut(self.node, self.peer)
        if op == "crash":
            return self.node not in world.crashed
        if op == "restart":
            return self.node in world.crashed
        if self.node in world.crashed:
            return False
        if op == "dup":
            return self.node in world.last_lease_msg
        if op in ("qedit", "migrate"):
            # only the current ACTIVE holder acks queued writes or
            # initiates a migration (the rebalancer runs on the owner);
            # the TARGET may be crashed/cut — that is the abort path
            l = world.nodes[self.node].leases.get(self.doc)
            return l is not None and l.holder == self.node \
                and l.state == ACTIVE
        if op == "promote":
            # the ACTIVE holder splits its own doc — once per trace is
            # enough; re-promotion of a live group is refused anyway
            node = world.nodes[self.node]
            l = node.leases.get(self.doc)
            return l is not None and l.holder == self.node \
                and l.state == ACTIVE \
                and node.writergroups.get(self.doc) is None
        if op == "demote":
            return world.nodes[self.node].can_demote(self.doc)
        if op == "gedit":
            # a member write is only offered where the member-side
            # admission gate (incl. the self-fence) would admit it
            return world.nodes[self.node].group_accepts(self.doc)
        if op == "flush":
            return bool(world.stores[self.node].pending)
        return True

    def apply(self, world: SimWorld) -> None:
        op = self.op
        if op == "edit":
            world.edit(self.node, self.doc)
        elif op == "qedit":
            world.qedit(self.node, self.doc)
        elif op == "flush":
            world.stores[self.node].scheduler.drain()
        elif op == "gedit":
            world.qedit(self.node, self.doc)
        elif op == "migrate":
            world.migrate(self.node, self.peer, self.doc)
        elif op == "promote":
            world.nodes[self.node].promote_writer_group(
                self.doc, [self.peer])
        elif op == "demote":
            world.nodes[self.node].demote_writer_group(self.doc)
        elif op == "acquire":
            world.nodes[self.node].leases.ensure_local(self.doc, True)
        elif op == "step":
            node = world.nodes[self.node]
            node.table.probe_once()
            node.maintain()
        elif op == "ae":
            world.nodes[self.node].antientropy.run_round()
        elif op == "tick":
            world.now += world.tick_s
        elif op == "cut":
            world.cut(self.node, self.peer)
        elif op == "heal":
            world.heal(self.node, self.peer)
        elif op == "crash":
            world.crash(self.node)
        elif op == "restart":
            world.restart(self.node)
        elif op == "dup":
            world.redeliver_last_lease_msg(self.node)
        else:
            raise ValueError(f"unknown action op {op!r}")

    def footprint(self) -> frozenset:
        """Aspects this action reads or writes, for the independence
        relation (disjoint footprints commute). Environment actions and
        anything that can touch every node are ALL — conservative is
        sound; it only costs reduction."""
        if self.op in ("edit", "qedit", "gedit", "flush"):
            return frozenset({f"{self.node}:oplog"})
        return frozenset({ALL})


def independent(a: Action, b: Action) -> bool:
    fa, fb = a.footprint(), b.footprint()
    if ALL in fa or ALL in fb:
        return False
    return not (fa & fb)


class Scenario:
    """A bounded model: node set, doc set, action pool with per-label
    occurrence bounds, and the invariant names checked over it."""

    def __init__(self, name: str, node_ids: Tuple[str, ...],
                 docs: Tuple[str, ...], quorum: bool,
                 actions: Tuple[Action, ...], bounds: Dict[str, int],
                 invariants: Tuple[str, ...], ttl_s: float = 2.0,
                 tick_s: float = 1.1,
                 setup: Tuple[Action, ...] = (),
                 description: str = "") -> None:
        self.name = name
        self.node_ids = node_ids
        self.docs = docs
        self.quorum = quorum
        self.actions = actions
        self.bounds = bounds
        self.invariants = invariants
        self.ttl_s = ttl_s
        self.tick_s = tick_s
        # deterministic pre-state applied at build time (seeded edits,
        # typically) — part of the model, not of the explored choices
        self.setup = setup
        self.description = description

    def build(self, mutation=None) -> SimWorld:
        world = SimWorld(self.node_ids, docs=self.docs,
                         ttl_s=self.ttl_s, quorum=self.quorum,
                         mutation=mutation)
        world.tick_s = self.tick_s
        for a in self.setup:
            a.apply(world)
        return world

    def enabled_actions(self, world: SimWorld,
                        counts: Dict[str, int]):
        out = []
        for a in self.actions:
            if counts.get(a.op, 0) >= self.bounds.get(a.op, 2):
                continue
            if a.enabled(world):
                out.append(a)
        return out


def _acts(*specs) -> Tuple[Action, ...]:
    return tuple(Action(*s) for s in specs)


# Node/doc ids are chosen so rendezvous placement makes the model
# interesting: owner_of("d0", [n1,n2,n3]) == n1, and n2 succeeds n1
# when n1 leaves the universe — so takeover and handoff-back paths are
# reachable within the bounds.
SCENARIOS: Dict[str, Scenario] = {}


def _register(s: Scenario) -> None:
    SCENARIOS[s.name] = s


_register(Scenario(
    "handoff", ("n1", "n2", "n3"), ("d0",), quorum=True,
    setup=_acts(("edit", "n1", None, "d0")),
    actions=_acts(
        ("acquire", "n1", None, "d0"), ("acquire", "n2", None, "d0"),
        ("step", "n1"), ("step", "n2"),
        ("ae", "n1"), ("ae", "n2"),
        ("edit", "n1", None, "d0"), ("edit", "n2", None, "d0"),
        ("tick",),
        ("cut", "n1", "n2"), ("heal", "n1", "n2"),
        ("crash", "n2"), ("restart", "n2"),
        ("dup", "n2"),
    ),
    bounds={"acquire": 3, "step": 2, "ae": 2, "edit": 2, "tick": 3,
            "cut": 1, "heal": 1, "crash": 1, "restart": 1, "dup": 1},
    invariants=("single-active", "promise-exclusivity",
                "floor-monotonic", "floor-coverage",
                "own-lease-stability", "tie-break-direction",
                "convergence"),
    description="3-voter mesh, one doc: competing acquires, partition, "
                "crash/restart, duplicate delivery, anti-entropy."))

_register(Scenario(
    "crash-recovery", ("n1", "n2", "n3"), ("d0",), quorum=True,
    actions=_acts(
        ("acquire", "n1", None, "d0"), ("acquire", "n3", None, "d0"),
        ("crash", "n2"), ("restart", "n2"),
        ("step", "n2"), ("tick",),
    ),
    bounds={"acquire": 2, "crash": 1, "restart": 1, "step": 2,
            "tick": 2},
    invariants=("single-active", "promise-exclusivity",
                "floor-monotonic", "floor-coverage"),
    description="voter crash between two competing acquisitions: the "
                "promise table must survive the restart."))

_register(Scenario(
    "renewal", ("n1", "n2"), ("d0",), quorum=True,
    setup=_acts(("edit", "n1", None, "d0")),
    actions=_acts(
        ("acquire", "n1", None, "d0"),
        ("ae", "n1"), ("ae", "n2"), ("tick",),
    ),
    bounds={"acquire": 2, "ae": 2, "tick": 2},
    invariants=("single-active", "promise-exclusivity",
                "floor-monotonic", "floor-coverage",
                "own-lease-stability", "convergence"),
    description="renewals under anti-entropy echo: a peer's stale "
                "view of our own lease must never shorten it."))

_register(Scenario(
    "tiebreak", ("n1", "n2"), ("d0",), quorum=False,
    setup=_acts(("edit", "n1", None, "d0"),
                ("edit", "n2", None, "d0")),
    actions=_acts(
        ("acquire", "n1", None, "d0"), ("acquire", "n2", None, "d0"),
        ("ae", "n1"), ("ae", "n2"), ("tick",),
    ),
    bounds={"acquire": 2, "ae": 2, "tick": 2},
    invariants=("floor-monotonic", "floor-coverage",
                "own-lease-stability", "tie-break-direction",
                "convergence"),
    description="PR 2 no-quorum mode, where equal-epoch conflicts ARE "
                "reachable: arbitration must be deterministic "
                "(lexically smaller holder wins) on every host. "
                "single-active is deliberately not checked here."))

_register(Scenario(
    "migration", ("n1", "n2", "n3"), ("d0",), quorum=True,
    # pre-state: n1 owns d0 with one acked-but-queued write sitting in
    # its admission queue — the op the drain barrier must not lose
    setup=_acts(("acquire", "n1", None, "d0"),
                ("qedit", "n1", None, "d0")),
    actions=_acts(
        ("qedit", "n1", None, "d0"),
        ("flush", "n1"),
        ("migrate", "n1", "n2", "d0"),
        ("step", "n1"), ("step", "n2"),
        ("ae", "n1"), ("ae", "n2"),
        ("tick",),
        ("cut", "n1", "n2"), ("heal", "n1", "n2"),
        ("crash", "n2"), ("restart", "n2"),
        ("dup", "n2"),
    ),
    bounds={"qedit": 1, "flush": 1, "migrate": 2, "step": 1, "ae": 1,
            "tick": 2, "cut": 1, "heal": 1, "crash": 1, "restart": 1,
            "dup": 1},
    invariants=("single-active", "promise-exclusivity",
                "floor-monotonic", "floor-coverage",
                "no-acked-loss", "convergence"),
    description="elastic-mesh live migration (override + grant -> "
                "drain -> transfer -> activate) under crash, "
                "partition and duplicate delivery: no interleaving "
                "may lose an acknowledged op or activate two owners; "
                "aborts must leave the doc owned at the source."))

_register(Scenario(
    "writer-group", ("n1", "n2", "n3"), ("d0",), quorum=True,
    # tick_s > ttl_s: one tick expires leases, two expire the group
    # registration TTL (2 * ttl_s) — so every TTL-gated path (member
    # self-fence on expiry, demotion past a silent member) is
    # reachable within the tick bound
    ttl_s=2.0, tick_s=2.2,
    # pre-state: n1 owns d0 with one acked-but-queued write — the op
    # neither promotion nor demotion may lose
    setup=_acts(("acquire", "n1", None, "d0"),
                ("qedit", "n1", None, "d0")),
    actions=_acts(
        ("promote", "n1", "n2", "d0"),
        ("demote", "n1", None, "d0"),
        ("qedit", "n1", None, "d0"),
        ("gedit", "n2", None, "d0"),
        ("flush", "n2"),
        ("step", "n1"), ("step", "n2"),
        ("ae", "n1"), ("ae", "n2"),
        ("tick",),
        ("cut", "n1", "n2"), ("heal", "n1", "n2"),
        ("crash", "n2"), ("restart", "n2"),
        ("dup", "n2"),
    ),
    bounds={"promote": 1, "demote": 2, "qedit": 1, "gedit": 2,
            "flush": 1, "step": 1, "ae": 1, "tick": 2, "cut": 1,
            "heal": 1, "crash": 1, "restart": 1, "dup": 1},
    invariants=("single-active", "promise-exclusivity",
                "floor-monotonic", "floor-coverage",
                "group-epoch-exclusivity", "no-acked-loss",
                "convergence"),
    description="hot-doc write splitting: promote n1's lease to a "
                "{n1,n2} writer group, member writes on n2, fenced "
                "demotion back to one writer — under member crash, "
                "partition, duplicate grant/demote delivery and TTL "
                "expiry. No interleaving may admit a write under a "
                "superseded group epoch or lose an acked member "
                "write across the demotion drain."))
