"""Simulated world for the protocol model checker.

The explorer drives the REAL protocol objects — `ReplicaNode`,
`LeaseManager`, `QuorumCoordinator`, `MembershipView`, `AntiEntropy` —
through three dependency seams the production constructors expose:

  * a virtual clock (`SimWorld.now`, advanced only by the scheduler's
    `tick` action, so timeouts fire as explicit choices);
  * a synchronous in-process transport (`SimTransport`, duck-typing
    `peers.PeerTable`) whose link state — partitions, crashes — is
    part of the explored state, not the physical network;
  * an in-memory journal (`MemJournal`, duck-typing
    `quorum.ReplicaJournal`) that survives a simulated crash, so
    restart re-runs the real restore path.

A crash discards the node OBJECT (all in-memory state) but keeps its
journal and oplog store: the journal is the real durability contract;
the oplog is treated as durable too (storage-tier crash safety is PR
8's separately-tested property, out of this model's scope — see
CHECKING.md).
"""

from __future__ import annotations

import json
import urllib.error
from typing import Callable, Dict, List, Optional, Set, Tuple

from ...causalgraph.summary import intersect_with_summary, \
    summarize_versions
from ...encoding.decode import decode_into
from ...encoding.encode import ENCODE_PATCH, encode_oplog
from ...replicate.node import ReplicaNode
from ...text.oplog import OpLog


class MemJournal:
    """Duck-type of quorum.ReplicaJournal backed by plain dicts.
    Lives in the WORLD (not the node), so a crash/restart cycle keeps
    it — exactly what the file-backed journal guarantees."""

    def __init__(self) -> None:
        self.incarnation = 0
        self.max_epochs: Dict[str, int] = {}
        self.promises: Dict[str, dict] = {}
        self.leases: Dict[str, dict] = {}
        self.overrides: Dict[str, dict] = {}
        self.groups: Dict[str, dict] = {}
        self._dirty = False

    # -- writes (mirror ReplicaJournal's semantics) --
    def note_incarnation(self, n: int) -> None:
        self.incarnation = int(n)
        self._dirty = True

    def note_epoch(self, doc_id: str, epoch: int) -> None:
        if epoch > self.max_epochs.get(doc_id, 0):
            self.max_epochs[doc_id] = int(epoch)
        self._dirty = True

    def note_promise(self, doc_id: str, epoch: int,
                     holder: str) -> None:
        self.promises[doc_id] = {"epoch": int(epoch),
                                 "holder": str(holder)}
        self._dirty = True

    def note_lease(self, doc_id: str, holder: str, epoch: int,
                   state: str) -> None:
        self.leases[doc_id] = {"holder": str(holder),
                               "epoch": int(epoch), "state": str(state)}
        self._dirty = True

    def drop_lease(self, doc_id: str) -> None:
        self.leases.pop(doc_id, None)
        self._dirty = True

    def note_group(self, doc_id: str, epoch: int, members,
                   leader: str) -> None:
        self.groups[doc_id] = {"epoch": int(epoch),
                               "members": [str(m) for m in members],
                               "leader": str(leader)}
        self._dirty = True

    def drop_group(self, doc_id: str) -> None:
        self.groups.pop(doc_id, None)
        self._dirty = True

    def note_override(self, doc_id: str, target, ver: int) -> None:
        # same LWW-by-version fold as ReplicaJournal._apply
        cur = self.overrides.get(doc_id)
        if cur is None or int(ver) >= int(cur.get("ver", 0)):
            self.overrides[doc_id] = {"target": target, "ver": int(ver)}
        self._dirty = True

    def record(self, *a, **k) -> None:
        self._dirty = True

    def compact(self) -> None:
        pass

    # -- restore views --
    def restored_incarnation(self) -> int:
        return self.incarnation

    def restored_max_epochs(self) -> Dict[str, int]:
        return dict(self.max_epochs)

    def restored_promises(self) -> Dict[str, dict]:
        return {d: dict(p) for d, p in self.promises.items()}

    def restored_leases(self) -> Dict[str, dict]:
        return {d: dict(l) for d, l in self.leases.items()}

    def restored_overrides(self) -> Dict[str, dict]:
        return {d: dict(o) for d, o in self.overrides.items()}

    def restored_groups(self) -> Dict[str, dict]:
        return {d: dict(g) for d, g in self.groups.items()}

    def has_prior_state(self) -> bool:
        return self._dirty

    def close(self) -> None:
        pass

    def fingerprint(self) -> dict:
        return {"inc": self.incarnation, "floors": self.max_epochs,
                "promises": self.promises, "leases": self.leases,
                "overrides": self.overrides, "groups": self.groups}


class _SimScheduler:
    """MergeScheduler duck-type exposing the one seam `node.handoff`'s
    drain phase uses: `drain()` flushes every queued (acknowledged)
    write into the oplog — the admission queue the real drain barrier
    empties before the final transfer patch is cut."""

    def __init__(self, store: "MemStore") -> None:
        self.store = store

    def drain(self) -> None:
        for doc_id in sorted(self.store.pending):
            self.store.flush_pending(doc_id)


class MemStore:
    """Minimal DocStore duck-type: real OpLogs, no scheduler/device
    tier beyond the `_SimScheduler` drain seam. Auto-creates docs on
    first touch (the anti-entropy union walk relies on that).

    `pending` models the admission queue: `qedit` actions ACK a write
    to the client but only queue it here; `flush`/drain moves it into
    the oplog. The queue is volatile — a crash loses it (and the model
    retracts those acks: client and server died together; queue
    durability is the storage soak's separately-tested property)."""

    def __init__(self, owner_id: str) -> None:
        from ..witness import make_lock
        self.owner_id = owner_id
        self.docs: Dict[str, OpLog] = {}
        self.lock = make_lock(f"sim.store.{owner_id}", "oplog",
                              reentrant=True)
        self.replica = None
        self.reads = None
        self.merge_submissions: List[Tuple[str, int]] = []
        self.pending: Dict[str, List[str]] = {}
        self.scheduler = _SimScheduler(self)

    def queue_edit(self, doc_id: str, ch: str) -> None:
        self.pending.setdefault(doc_id, []).append(ch)

    def flush_pending(self, doc_id: str) -> None:
        chars = self.pending.pop(doc_id, [])
        if not chars:
            return
        ol = self.get(doc_id)
        with self.lock:
            agent = ol.get_or_create_agent_id(
                f"agent-{self.owner_id}")
            for ch in chars:
                ol.add_insert(agent, 0, ch)

    def get(self, doc_id: str) -> OpLog:
        ol = self.docs.get(doc_id)
        if ol is None:
            ol = OpLog()
            ol.doc_id = doc_id
            self.docs[doc_id] = ol
        return ol

    def doc_ids(self) -> List[str]:
        return sorted(self.docs)

    def mark_dirty(self, doc_id: str) -> None:
        pass

    def notify(self, doc_id: str) -> None:
        pass

    def submit_merge(self, doc_id: str, n: int) -> None:
        self.merge_submissions.append((doc_id, n))


class SimRecorder:
    """FlightRecorder duck-type: every lease-manager event lands in the
    world's event log tagged with the emitting node (the
    tie-break-direction invariant reads these)."""

    def __init__(self, world: "SimWorld", node_id: str) -> None:
        self.world = world
        self.node_id = node_id

    def record(self, kind: str, **fields) -> None:
        ev = {"node": self.node_id, "kind": kind}
        ev.update(fields)
        self.world.events.append(ev)


class _SimPeerState:
    """PeerTable._PeerState duck-type: the two fields ReplicaNode's
    rejoin check reads. The sim has no circuit breaker — reachability
    is explicit link/crash state — so open_until stays 0.0."""

    __slots__ = ("addr", "last_ok", "open_until", "failures")

    def __init__(self, addr: str) -> None:
        self.addr = addr
        self.last_ok: Optional[float] = None
        self.open_until = 0.0
        self.failures = 0


class SimTransport:
    """peers.PeerTable duck-type: synchronous in-process dispatch.
    Reachability is a pure function of the world's cut-link set and
    crashed set; an unreachable call raises OSError exactly where the
    real transport would. Message loss/partition therefore happens at
    CALL time as a consequence of scheduler-chosen link state — there
    is no in-flight queue (see CHECKING.md for what that excludes)."""

    def __init__(self, world: "SimWorld", self_id: str) -> None:
        self.world = world
        self.self_id = self_id
        self.on_ping: Optional[Callable[[str, dict], None]] = None
        self.recorder = None
        self.metrics = None
        self.peers: Dict[str, _SimPeerState] = {
            p: _SimPeerState(p) for p in world.node_ids
            if p != self_id}

    # ---- membership / health views ----
    def add_peer(self, addr: str) -> bool:
        if not addr or addr == self.self_id or addr in self.peers:
            return False
        self.peers[addr] = _SimPeerState(addr)
        return True

    def remove_peer(self, addr: str) -> bool:
        return self.peers.pop(addr, None) is not None

    def peer_ids(self) -> List[str]:
        return sorted(self.peers)

    def all_ids(self) -> List[str]:
        return sorted(list(self.peers) + [self.self_id])

    def is_healthy(self, peer_id: str,
                   now: Optional[float] = None) -> bool:
        if peer_id == self.self_id:
            return True
        return peer_id in self.peers \
            and self.world.reachable(self.self_id, peer_id)

    def healthy_ids(self, now: Optional[float] = None) -> List[str]:
        return sorted([self.self_id] +
                      [p for p in self.peers if self.is_healthy(p)])

    def down_duration(self, peer_id: str,
                      now: Optional[float] = None) -> Optional[float]:
        if peer_id == self.self_id:
            return None
        if peer_id not in self.peers:
            return float("inf")
        t0 = self.world.down_since.get((self.self_id, peer_id))
        if t0 is None:
            return None
        return (self.world.now if now is None else now) - t0

    def state(self, peer_id: str) -> dict:
        st = self.peers[peer_id]
        return {"consecutive_failures": st.failures,
                "circuit_open": False, "backoff_s": 0.0,
                "last_ok_age_s": (round(self.world.now - st.last_ok, 3)
                                  if st.last_ok is not None else None)}

    def states(self) -> dict:
        return {p: self.state(p) for p in self.peer_ids()}

    # ---- calls ----
    def call(self, peer_id: str, path: str,
             data: Optional[bytes] = None,
             timeout: Optional[float] = None, probe: bool = False,
             headers: Optional[dict] = None) -> Tuple[int, bytes]:
        if peer_id not in self.peers:
            raise KeyError(f"unknown peer {peer_id!r}")
        st = self.peers[peer_id]
        if not self.world.reachable(self.self_id, peer_id):
            st.failures += 1
            raise OSError(f"sim: {self.self_id}->{peer_id} unreachable")
        status, body = self.world.dispatch(self.self_id, peer_id,
                                           path, data, headers)
        st.failures = 0
        st.last_ok = self.world.now
        return status, body

    def call_json(self, peer_id: str, path: str,
                  obj: Optional[dict] = None,
                  timeout: Optional[float] = None,
                  headers: Optional[dict] = None) -> dict:
        data = (json.dumps(obj).encode("utf8")
                if obj is not None else None)
        _status, body = self.call(peer_id, path, data=data,
                                  timeout=timeout, headers=headers)
        return json.loads(body or b"{}")

    # ---- probe loop (invoked by the `step` action, never a thread) ----
    def probe(self, peer_id: str) -> bool:
        try:
            status, body = self.call(peer_id, "/replicate/ping",
                                     probe=True)
        except (OSError, KeyError):
            return False
        if status == 200 and self.on_ping is not None:
            self.on_ping(peer_id, json.loads(body or b"{}"))
        return status == 200

    def probe_once(self) -> Dict[str, bool]:
        return {p: self.probe(p) for p in self.peer_ids()}

    def start_probe_loop(self, interval_s: float = 0.5) -> None:
        raise RuntimeError("sim transport never starts threads")

    def stop_probe_loop(self) -> None:
        pass


class SimWorld:
    """One configuration of the model: N real ReplicaNodes over the
    simulated transport/clock/journal, plus the explorer's auxiliary
    history (promise grants, floor watermarks, activations) that
    survives node crashes — the model-level ghost state several
    invariants are phrased over."""

    def __init__(self, node_ids: Tuple[str, ...],
                 docs: Tuple[str, ...] = ("d0",),
                 ttl_s: float = 2.0, quorum: bool = True,
                 mutation=None) -> None:
        self.node_ids = tuple(node_ids)
        self.docs = tuple(docs)
        self.ttl_s = ttl_s
        self.quorum = quorum
        self.mutation = mutation        # mutations.Mutation or None
        self.now = 0.0
        self.tick_s = 1.1               # Scenario.build overrides
        self.cut_links: Set[frozenset] = set()
        self.crashed: Set[str] = set()
        # (observer, peer) -> virtual time the peer became unreachable
        # from the observer's side (cut or crash event time)
        self.down_since: Dict[Tuple[str, str], float] = {}
        self.events: List[dict] = []
        self.edit_seq = 0
        # ghost ledger for the no-acked-loss invariant: every char the
        # model has acknowledged to a client, per doc (crash retracts
        # the crashed node's still-queued chars — see MemStore.pending)
        self.acked: Dict[str, List[str]] = {}
        # last lease message delivered to each node, for the `dup`
        # (duplicate delivery) action
        self.last_lease_msg: Dict[str, dict] = {}
        self.journals: Dict[str, MemJournal] = {
            n: MemJournal() for n in self.node_ids}
        self.stores: Dict[str, MemStore] = {
            n: MemStore(n) for n in self.node_ids}
        if mutation is not None and mutation.apply_world is not None:
            mutation.apply_world(self)
        self.nodes: Dict[str, ReplicaNode] = {}
        for n in self.node_ids:
            self.nodes[n] = self._build_node(n)

    # ---- construction / crash-restart ----
    def clock(self) -> float:
        return self.now

    def _build_node(self, node_id: str) -> ReplicaNode:
        table = SimTransport(self, node_id)
        node = ReplicaNode(
            self.stores[node_id], node_id, peer_addrs=[],
            lease_ttl_s=self.ttl_s, timeout_s=1.0,
            clock=self.clock, table=table,
            journal=self.journals[node_id])
        node.leases.recorder = SimRecorder(self, node_id)
        if not self.quorum:
            node.leases.quorum = None   # PR 2 standalone/TTL mode
        if self.mutation is not None \
                and self.mutation.apply_node is not None:
            self.mutation.apply_node(node)
        return node

    def crash(self, node_id: str) -> None:
        """Lose the node's in-memory state; keep journal + oplog. The
        admission queue is in-memory too: queued chars are gone, and
        their acks are retracted from the ghost ledger (the clients
        died with the server; queue durability is out of scope)."""
        store = self.stores[node_id]
        for doc_id, chars in list(store.pending.items()):
            acked = self.acked.get(doc_id)
            if acked:
                for ch in chars:
                    try:
                        acked.remove(ch)
                    except ValueError:
                        pass
        store.pending.clear()
        self.crashed.add(node_id)
        self.nodes.pop(node_id, None)
        for other in self.node_ids:
            if other != node_id:
                self.down_since.setdefault((other, node_id), self.now)

    def restart(self, node_id: str) -> None:
        """Rebuild the node from its journal — runs the real restore
        path, so it boots `rejoining` with restored floors/promises."""
        self.crashed.discard(node_id)
        for other in self.node_ids:
            if other != node_id \
                    and not self.is_cut(other, node_id):
                self.down_since.pop((other, node_id), None)
        self.nodes[node_id] = self._build_node(node_id)

    # ---- link state ----
    def is_cut(self, a: str, b: str) -> bool:
        return frozenset((a, b)) in self.cut_links

    def cut(self, a: str, b: str) -> None:
        self.cut_links.add(frozenset((a, b)))
        self.down_since.setdefault((a, b), self.now)
        self.down_since.setdefault((b, a), self.now)

    def heal(self, a: str, b: str) -> None:
        self.cut_links.discard(frozenset((a, b)))
        if b not in self.crashed:
            self.down_since.pop((a, b), None)
        if a not in self.crashed:
            self.down_since.pop((b, a), None)

    def reachable(self, a: str, b: str) -> bool:
        return b not in self.crashed and a not in self.crashed \
            and not self.is_cut(a, b)

    def alive(self) -> List[str]:
        return [n for n in self.node_ids if n not in self.crashed]

    # ---- wire dispatch (the simulated server side) ----
    def dispatch(self, src: str, dst: str, path: str,
                 data: Optional[bytes],
                 headers: Optional[dict]) -> Tuple[int, bytes]:
        node = self.nodes[dst]
        if path == "/replicate/ping":
            return 200, json.dumps(node.ping_json()).encode("utf8")
        if path == "/replicate/docs":
            return 200, json.dumps(node.docs_json()).encode("utf8")
        if path == "/replicate/lease":
            req = json.loads(data or b"{}")
            self.last_lease_msg[dst] = dict(req)
            resp = node.handle_lease_message(req)
            return 200, json.dumps(resp).encode("utf8")
        if path == "/replicate/join":
            resp = node.handle_join(json.loads(data or b"{}"))
            return 200, json.dumps(resp).encode("utf8")
        if path.startswith("/doc/"):
            _, _, rest = path.partition("/doc/")
            doc_id, _, action = rest.partition("/")
            store = node.store
            ol = store.get(doc_id)
            if action == "summary":
                with store.lock:
                    summary = summarize_versions(ol.cg)
                return 200, json.dumps(summary).encode("utf8")
            if action == "pull":
                # body = caller's summary; respond with a patch from
                # the common frontier (tools/server.py's pull handler)
                summary = json.loads(data or b"{}")
                with store.lock:
                    common, _rem = intersect_with_summary(ol.cg,
                                                          summary)
                    patch = encode_oplog(ol, ENCODE_PATCH,
                                         from_version=common)
                return 200, patch
            if action == "push":
                epoch_hdr = (headers or {}).get("X-DT-Lease-Epoch")
                if epoch_hdr is not None and not node.check_write_fence(
                        doc_id, int(epoch_hdr)):
                    raise urllib.error.HTTPError(
                        path, 409, "fenced", {}, None)
                with store.lock:
                    pre = len(ol)
                    decode_into(ol, data or b"")
                    n_new = len(ol) - pre
                if n_new:
                    store.submit_merge(doc_id, n_new)
                return 200, json.dumps({"ok": True,
                                        "new_ops": n_new}).encode()
        raise KeyError(f"sim: no handler for {path!r}")

    # ---- convenience used by actions/invariants ----
    def edit(self, node_id: str, doc_id: str) -> None:
        store = self.stores[node_id]
        ol = store.get(doc_id)
        with store.lock:
            agent = ol.get_or_create_agent_id(f"agent-{node_id}")
            ol.add_insert(agent, 0,
                          chr(ord("a") + self.edit_seq % 26))
        self.edit_seq += 1

    def qedit(self, node_id: str, doc_id: str) -> None:
        """Acknowledged-but-queued write: the char lands in the node's
        admission queue and in the ghost acked ledger — only a flush
        (or the handoff drain barrier) moves it into the oplog."""
        ch = chr(ord("a") + self.edit_seq % 26)
        self.edit_seq += 1
        self.stores[node_id].queue_edit(doc_id, ch)
        self.acked.setdefault(doc_id, []).append(ch)

    def migrate(self, node_id: str, peer: str, doc_id: str) -> bool:
        """The rebalancer's migration step: override first (rides the
        grant), epoch-fenced handoff, tombstone on abort. A completed
        move evicts the source's warm copy — with the drain barrier
        intact the queue is empty by then; without it (the seeded
        mutation) this is exactly where acked ops die."""
        node = self.nodes[node_id]
        ver = node.overrides.set(doc_id, peer)
        ok = node.handoff(doc_id, peer, override_version=ver)
        if ok:
            self.stores[node_id].pending.pop(doc_id, None)
        else:
            node.overrides.clear(doc_id)
        return ok

    def redeliver_last_lease_msg(self, node_id: str) -> None:
        req = self.last_lease_msg.get(node_id)
        if req is not None and node_id not in self.crashed:
            self.nodes[node_id].handle_lease_message(dict(req))

    def text_of(self, node_id: str, doc_id: str) -> str:
        store = self.stores[node_id]
        with store.lock:
            return store.get(doc_id).checkout_tip().snapshot()

    def frontier_of(self, node_id: str, doc_id: str):
        store = self.stores[node_id]
        with store.lock:
            ol = store.get(doc_id)
            return ol.cg.local_to_remote_frontier(ol.version)
