"""Runtime lock witness: lockdep-style held-while-acquiring edges.

`make_lock(name, order_class, rank)` is a drop-in replacement for
`threading.Lock()` / `threading.RLock()` at the repo's named lock
construction sites (scheduler global/shard/device locks, the DocStore
oplog guard, the replicate maintenance/lease locks). The wrapper costs
one attribute check per acquire while the witness is DISABLED (the
default); `witness_enable()` turns on recording:

  * every successful acquire records an edge (held_class -> new_class)
    for each DISTINCT lock currently held by the thread — the observed
    lock-order graph;
  * acquiring two locks of the SAME order class out of rank order
    (shard/device locks carry their index as `rank`) is recorded as a
    violation — the runtime form of the unsorted-multi-lock lint;
  * `witness_assert_acyclic()` DFS-checks the observed class graph —
    a cycle means two code paths disagree about lock order, i.e. a
    latent deadlock the soak merely didn't lose the race to.

Reentrant re-acquisition of the SAME lock object (RLocks) records
nothing. The witness is process-global on purpose: deadlocks are a
process-level property, and the soaks boot many nodes in one process.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

# module-level switch: read unlocked on the acquire fast path (a stale
# read merely delays the first recorded edge by one acquisition)
_enabled = False
_graph_lock = threading.Lock()
_edges: Dict[Tuple[str, str], int] = {}      # (from_cls, to_cls) -> n
_violations: List[dict] = []
_acquires = 0
_MAX_VIOLATIONS = 256

_tls = threading.local()


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class WitnessLock:
    """Instrumented lock: `threading.Lock`/`RLock` surface (acquire/
    release/context manager) plus witness recording when enabled."""

    __slots__ = ("_inner", "name", "order_class", "rank", "_reentrant")

    def __init__(self, name: str, order_class: str,
                 rank: Optional[int] = None,
                 reentrant: bool = False) -> None:
        self._inner = threading.RLock() if reentrant \
            else threading.Lock()
        self.name = name
        self.order_class = order_class
        self.rank = rank
        self._reentrant = reentrant

    # ---- lock surface ----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got and _enabled:
            self._record_acquire()
        elif got:
            # keep the held stack balanced even while disabled so an
            # enable() mid-run doesn't see releases without acquires
            _held().append(self)
        return got

    def release(self) -> None:
        stack = _held()
        # pop by identity from the top (condition-variable release order
        # is LIFO in practice; search defensively anyway)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._inner.release()

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner = self._inner
        if hasattr(inner, "locked"):
            return inner.locked()
        # RLock has no locked(); probe without recording
        if inner.acquire(blocking=False):
            inner.release()
            return False
        return True

    # ---- recording -------------------------------------------------------

    def _record_acquire(self) -> None:
        global _acquires
        stack = _held()
        if any(h is self for h in stack):
            # reentrant re-acquire of the same RLock: no new edge
            stack.append(self)
            return
        seen_cls = set()
        with _graph_lock:
            _acquires += 1
            for h in stack:
                if h.order_class == self.order_class:
                    if (h.rank is not None and self.rank is not None
                            and self.rank <= h.rank
                            and len(_violations) < _MAX_VIOLATIONS):
                        _violations.append({
                            "kind": "unsorted-same-class",
                            "class": self.order_class,
                            "held": h.name, "held_rank": h.rank,
                            "acquiring": self.name,
                            "rank": self.rank})
                    continue
                key = (h.order_class, self.order_class)
                if key[0] not in seen_cls:
                    seen_cls.add(key[0])
                    _edges[key] = _edges.get(key, 0) + 1
        stack.append(self)


def make_lock(name: str, order_class: str, rank: Optional[int] = None,
              reentrant: bool = False) -> WitnessLock:
    """Construct a witness-instrumented lock. Always returns the
    wrapper (near-zero cost disabled) so `witness_enable()` works on
    locks constructed before the switch flipped."""
    return WitnessLock(name, order_class, rank=rank,
                       reentrant=reentrant)


# ---- control / reporting ------------------------------------------------

def witness_enable() -> None:
    global _enabled
    _enabled = True


def witness_disable() -> None:
    global _enabled
    _enabled = False


def witness_reset() -> None:
    global _acquires
    with _graph_lock:
        _edges.clear()
        _violations.clear()
        _acquires = 0


def find_cycles() -> List[List[str]]:
    """Cycles in the observed class graph (each as a closed node list,
    e.g. ["oplog", "device", "oplog"]). Empty list == acyclic."""
    with _graph_lock:
        adj: Dict[str, List[str]] = {}
        for (a, b) in _edges:
            adj.setdefault(a, []).append(b)
    cycles: List[List[str]] = []
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    path: List[str] = []

    def dfs(n: str) -> None:
        color[n] = GRAY
        path.append(n)
        for m in adj.get(n, ()):
            c = color.get(m, WHITE)
            if c == GRAY:
                cycles.append(path[path.index(m):] + [m])
            elif c == WHITE:
                dfs(m)
        path.pop()
        color[n] = BLACK

    for n in list(adj):
        if color.get(n, WHITE) == WHITE:
            dfs(n)
    return cycles


def witness_snapshot() -> dict:
    """JSON-able state for /metrics (`obs` block) and soak reports."""
    with _graph_lock:
        edges = {f"{a}->{b}": n for (a, b), n in sorted(_edges.items())}
        violations = list(_violations)
        acquires = _acquires
    cycles = find_cycles()
    return {"enabled": _enabled,
            "acquires": acquires,
            "edges": edges,
            "edge_count": len(edges),
            "violations": violations,
            "violation_count": len(violations),
            "cycles": ["->".join(c) for c in cycles],
            "acyclic": not cycles}


def witness_assert_acyclic() -> None:
    """Raise AssertionError when the observed lock-order graph has a
    cycle (or an unsorted same-class acquisition was recorded)."""
    snap = witness_snapshot()
    if snap["cycles"]:
        raise AssertionError(
            f"lock-order cycle observed: {snap['cycles']} "
            f"(edges: {snap['edges']})")
    if snap["violations"]:
        raise AssertionError(
            f"unsorted same-class lock acquisition: "
            f"{snap['violations'][:4]}")
