"""Concurrency invariant analysis: static lint + runtime lock witness.

Two halves of one correctness story the chaos soaks only sample:

  lint.py / rules/   AST-based linter over serve/, replicate/, tpu/,
                     parallel/ and tools/ — lock-order violations,
                     unsorted multi-lock acquisition, device dispatch
                     under the global/oplog lock, unfenced doc-state
                     mutation on write paths, impurity inside
                     jitted/shard_map bodies. CLI: `dt-lint`.
  witness.py         lockdep-style instrumented Lock wrapper, off by
                     default; records actual held-while-acquiring
                     edges during tests/soaks and asserts the global
                     lock-order graph stays acyclic.

The canonical lock order both halves enforce (serve/README.md
"Concurrency invariants"): replicate maintenance → leases →
membership/peers/quorum → scheduler global → sorted shard locks →
oplog guard → sorted per-device locks → leaf (jit caches, first-touch
init, io).
"""

from __future__ import annotations

from .lint import (Violation, last_report, publish_report, render_human,
                   run_lint)
from .witness import (WitnessLock, make_lock, witness_assert_acyclic,
                      witness_disable, witness_enable, witness_reset,
                      witness_snapshot)

__all__ = [
    "Violation", "run_lint", "render_human", "publish_report",
    "last_report",
    "WitnessLock", "make_lock", "witness_enable", "witness_disable",
    "witness_reset", "witness_snapshot", "witness_assert_acyclic",
]
