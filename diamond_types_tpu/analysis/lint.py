"""AST-based concurrency invariant linter (CLI: `dt-lint`).

Walks the concurrency-bearing packages (serve/, replicate/, tpu/,
parallel/, tools/, storage/, read/) and enforces the invariants
serve/README.md documents under "Concurrency invariants":

  lock-order          acquiring a lock whose order class sits EARLIER
                      in the canonical order than a lock already held
  unsorted-locks      acquiring multiple same-class locks (shard /
                      device) in a loop whose iteration source is not
                      lexically sorted
  device-under-lock   device dispatch (jit call, block_until_ready,
                      device_put, fused/mesh replay, per-doc sync)
                      while holding the global or oplog lock
  unfenced-mutation   doc-state mutation on a scheduler/server write
                      path with no fencing check (`_fence`, `admit`,
                      `check_write_fence`, `X-DT-Lease-Epoch`)
  jit-impurity        host impurity (time.*, random, io, global state)
                      inside a jitted / shard_map body
  jit-cache-key       a *_jit_cache key tuple too small to carry the
                      kernel's shape dims

The engine is two-pass: pass 1 builds a cross-file call summary (which
function names transitively dispatch to the device, which contain a
fencing check) so one-hop indirection like `bank.text -> sync_doc`
is visible; pass 2 runs the rules per file.

Suppressions (documented in serve/README.md):

  x = thing()   # dt-lint: ignore[rule-name]     one line, named rules
  x = thing()   # dt-lint: ignore                one line, all rules
  # dt-lint: skip-file                           whole file

Violations carry severity "error" (deadlock/corruption class:
lock-order, device-under-lock, unfenced-mutation, unsorted-locks) or
"warn" (jit-impurity, jit-cache-key). `run_lint` returns a JSON-able
report; `publish_report` parks the latest report where
`obs.Observability.snapshot()` (and thus /metrics + prom.py's
`dt_lint_violations_total{rule}`) can see it.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional, Set

DEFAULT_PACKAGES = ("serve", "replicate", "tpu", "parallel", "tools",
                    "storage", "read", "obs", "workload", "wire",
                    "qos")

SEVERITY = {
    "lock-order": "error",
    "unsorted-locks": "error",
    "device-under-lock": "error",
    "unfenced-mutation": "error",
    "unguarded-acquire": "error",
    "metrics-schema-drift": "error",
    "jit-impurity": "warn",
    "jit-cache-key": "warn",
    "blocking-call-under-lock": "warn",
    "stale-suppression": "warn",
}

_SUPPRESS_RE = re.compile(
    r"#\s*dt-lint:\s*(skip-file|ignore(?:\[([\w\-, ]+)\])?)")


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    message: str
    severity: str = "warn"


class FileContext:
    """One parsed source file + its suppression table."""

    def __init__(self, path: str, src: str,
                 rel: Optional[str] = None) -> None:
        self.path = path
        self.rel = rel or path
        self.src = src
        self.tree = ast.parse(src, filename=path)
        self.skip_file = False
        self.suppressions: Dict[int, Set[str]] = {}
        for lineno, line in enumerate(src.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            if m.group(1) == "skip-file":
                self.skip_file = True
            elif m.group(2):
                self.suppressions[lineno] = {
                    r.strip() for r in m.group(2).split(",") if r.strip()}
            else:
                self.suppressions[lineno] = {"*"}

    def suppressed(self, v: Violation) -> bool:
        if self.skip_file:
            return True
        rules = self.suppressions.get(v.line)
        return bool(rules) and ("*" in rules or v.rule in rules)


class CallSummary:
    """Cross-file, name-level call summary (pass 1).

    `dispatchers` — bare function/method names whose body contains a
    direct device-dispatch call (one-hop transitive closure is taken
    by seeding with the jax API names).
    `self_fenced` — names whose body contains a fencing token, so a
    call to them IS a fenced mutation (e.g. `_flush_items`).
    `mutators` — names whose body directly calls a doc-state mutator.
    `blockers` — names whose body directly makes a blocking call
    (sleep/fsync/network), for the one-hop blocking-call-under-lock
    widening.
    `metric_literals` — string literals appearing in
    inc/observe/observe_latency calls anywhere in the linted tree,
    the producer side of the metrics-schema exemplar join.
    """

    def __init__(self) -> None:
        self.dispatchers: Set[str] = set()
        self.self_fenced: Set[str] = set()
        self.mutators: Set[str] = set()
        self.blockers: Set[str] = set()
        self.metric_literals: Set[str] = set()


def repo_root() -> str:
    """The diamond_types_tpu package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_source_files(paths: Optional[List[str]] = None) -> List[str]:
    """Default walk: the concurrency-bearing packages under the repo
    package dir. Explicit `paths` (files or dirs) override."""
    out: List[str] = []
    if paths:
        roots = list(paths)
    else:
        pkg = repo_root()
        roots = [os.path.join(pkg, p) for p in DEFAULT_PACKAGES]
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, _dirs, files in os.walk(root):
            for name in sorted(files):
                if name.endswith(".py"):
                    out.append(os.path.join(dirpath, name))
    return sorted(set(out))


def _load(path: str) -> Optional[FileContext]:
    try:
        with open(path, "r", encoding="utf8") as f:
            src = f.read()
        pkg_parent = os.path.dirname(repo_root())
        rel = os.path.relpath(path, pkg_parent)
        return FileContext(path, src, rel=rel)
    except (OSError, SyntaxError):
        return None


def build_summary(ctxs: List[FileContext]) -> CallSummary:
    from .rules.locks import DISPATCH_BASE
    from .rules.fencing import FENCE_TOKENS, MUTATOR_BASE
    from .rules.dataflow import BLOCKING_BASE
    summary = CallSummary()
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("inc", "observe",
                                           "observe_latency"):
                for arg in node.args:
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        summary.metric_literals.add(arg.value)
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            calls: Set[str] = set()
            tokens: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    fn = sub.func
                    if isinstance(fn, ast.Name):
                        calls.add(fn.id)
                    elif isinstance(fn, ast.Attribute):
                        calls.add(fn.attr)
                if isinstance(sub, ast.Attribute):
                    tokens.add(sub.attr)
                if isinstance(sub, ast.Name):
                    tokens.add(sub.id)
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    tokens.add(sub.value)
            if calls & DISPATCH_BASE:
                summary.dispatchers.add(node.name)
            if calls & MUTATOR_BASE:
                summary.mutators.add(node.name)
            if calls & BLOCKING_BASE:
                summary.blockers.add(node.name)
            if tokens & FENCE_TOKENS:
                summary.self_fenced.add(node.name)
    return summary


def run_lint(paths: Optional[List[str]] = None,
             disable: Optional[List[str]] = None) -> dict:
    """Lint `paths` (default: the repo's concurrency packages).
    Returns {"files", "violations", "by_rule", "errors", "warnings",
    "ok"}."""
    from .rules import RULES
    disabled = set(disable or ())
    ctxs = [c for c in (_load(p) for p in iter_source_files(paths))
            if c is not None]
    summary = build_summary(ctxs)
    violations: List[Violation] = []
    for ctx in ctxs:
        # which suppression comments actually absorbed a finding, by
        # line — the complement is the stale-suppression report
        fired: Dict[int, Set[str]] = {}
        for rule_fn in RULES:
            for v in rule_fn(ctx, summary):
                v.severity = SEVERITY.get(v.rule, v.severity)
                # suppression check BEFORE the disable check: a
                # comment shielding a --disable'd rule still shields
                # something and must not be reported stale
                if ctx.suppressed(v):
                    fired.setdefault(v.line, set()).add(v.rule)
                    continue
                if v.rule in disabled:
                    continue
                violations.append(v)
        if "stale-suppression" in disabled or ctx.skip_file:
            continue
        for line, rules in sorted(ctx.suppressions.items()):
            hit = fired.get(line, set())
            if "*" in rules:
                if not hit:
                    violations.append(Violation(
                        rule="stale-suppression", path=ctx.rel,
                        line=line, severity="warn",
                        message="`# dt-lint: ignore` suppresses "
                                "nothing on this line — delete it, "
                                "or it will hide the next real "
                                "finding here"))
                continue
            unused = sorted(r for r in rules if r not in hit)
            if unused:
                violations.append(Violation(
                    rule="stale-suppression", path=ctx.rel,
                    line=line, severity="warn",
                    message=(f"`# dt-lint: ignore[{', '.join(unused)}]`"
                             f" no longer suppresses anything — the "
                             f"finding it silenced is gone; delete "
                             f"the comment (stale suppressions hide "
                             f"the next real finding)")))
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    # zero-filled so dt_lint_violations_total{rule} exports one sample
    # per rule even on a clean tree
    by_rule: Dict[str, int] = {r: 0 for r in SEVERITY}
    for v in violations:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    errors = sum(1 for v in violations if v.severity == "error")
    report = {
        "files": len(ctxs),
        "violations": [asdict(v) for v in violations],
        "by_rule": by_rule,
        "errors": errors,
        "warnings": len(violations) - errors,
        "ok": not violations,
    }
    return report


# ---- report rendering / publication -------------------------------------

def render_human(report: dict) -> str:
    lines: List[str] = []
    for v in report["violations"]:
        lines.append(f"{v['path']}:{v['line']}: "
                     f"[{v['severity']}] {v['rule']}: {v['message']}")
    hit = {k: n for k, n in report["by_rule"].items() if n}
    lines.append(f"dt-lint: {report['files']} files, "
                 f"{report['errors']} errors, "
                 f"{report['warnings']} warnings"
                 + ("" if not hit else
                    " (" + ", ".join(f"{k}={n}" for k, n in
                                     sorted(hit.items())) + ")"))
    return "\n".join(lines)


def render_json(report: dict) -> str:
    return json.dumps(report, indent=1)


_LAST_REPORT: Optional[dict] = None


def publish_report(report: dict) -> None:
    """Park the latest lint report for obs: Observability.snapshot()
    includes a `lint` block when one has been published, and prom.py
    renders it as dt_lint_violations_total{rule}."""
    global _LAST_REPORT
    _LAST_REPORT = {"files": report["files"],
                    "by_rule": dict(report["by_rule"]),
                    "errors": report["errors"],
                    "warnings": report["warnings"],
                    "ok": report["ok"]}


def last_report() -> Optional[dict]:
    return _LAST_REPORT
