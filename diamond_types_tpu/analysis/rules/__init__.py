"""Rule registry for the dt-lint engine.

Each rule is `fn(ctx: FileContext, summary: CallSummary) ->
Iterable[Violation]`. Rule names, severities and the canonical lock
order live in lint.py / rules/locks.py; the human-facing contract is
serve/README.md "Concurrency invariants".
"""

from __future__ import annotations

from .dataflow import check_dataflow
from .fencing import check_fencing
from .jit_purity import check_jit_purity
from .locks import check_locks
from .metrics_schema import check_metrics_schema

RULES = (check_locks, check_fencing, check_jit_purity,
         check_dataflow, check_metrics_schema)

__all__ = ["RULES", "check_locks", "check_fencing", "check_jit_purity",
           "check_dataflow", "check_metrics_schema"]
