"""Dataflow rules: blocking-call-under-lock, unguarded-acquire.

blocking-call-under-lock (warn)
    A blocking call — sleep/fsync/network IO, or one hop through a
    function whose own body blocks (`summary.blockers`) — while
    lexically holding a HOT-PATH lock. The hot set is the scheduler's
    global lock, the shard locks, and the lease/quorum/peer protocol
    locks: one sleeping holder stalls every submit (or every lease
    operation) behind it. The io/oplog/device/leaf rungs are NOT in
    the hot set — io is the *designated* blocking serializer (fsync
    under the store guard inside an io-serialized flush pass is the
    documented design, see rules/locks.py), and warning on it would
    train people to ignore the rule.

unguarded-acquire (error)
    A bare `.acquire()` on a classifiable lock with no try/finally
    releasing the same lock expression — an exception between acquire
    and release leaves the lock held forever. `with lock:` is the
    expected form; bare acquire is tolerated only in the
    acquire(); try: ... finally: release() idiom. Unclassifiable
    lock expressions are ignored, same contract as every lock rule.
"""

from __future__ import annotations

import ast
from typing import List

from ..lint import FileContext, Violation
from .locks import _FnWalker, _call_name

# direct blocking surface: stdlib sleep/fsync plus the network and
# subprocess entry points the repo actually uses. Pass 1
# (lint.build_summary) widens this one hop into summary.blockers.
BLOCKING_BASE = {
    "sleep", "fsync", "urlopen", "create_connection", "getaddrinfo",
    "check_call", "check_output",
}

# lock classes where a blocking call stalls the serving / protocol hot
# path. io(25)/oplog(30)/device(40)/leaf(50) are deliberately absent —
# the io rung IS the blocking tier — and repl.maintain is absent
# because maintain() is the documented coarse single-flight guard
# around an entire (blocking) maintenance pass.
HOT_CLASSES = {"global", "shard", "repl.leases", "repl.quorum",
               "repl.peers", "repl.membership"}

# generic names the one-hop widening would otherwise poison: the
# page store's fsync'ing `append`/`write`/`load` (and soak/bench
# entry points like `run`/`main`/`once`/`reset`) share names with
# list.append, dict.get and friends, so a name-level summary cannot
# tell them apart. A genuinely blocking call through one of these
# names goes unflagged — the cost of name-level (not object-level)
# analysis, documented in CHECKING.md.
_BLOCKING_NAME_BLOCKLIST = {
    "append", "add", "get", "put", "read", "write", "load", "save",
    "open", "close", "run", "main", "once", "reset", "record",
    "_get", "_open",
}


class _DataflowWalker(_FnWalker):
    """Held-set simulation reusing the lock-order walker's
    classification/alias machinery, but emitting only the dataflow
    rules (check_locks owns the order rules)."""

    def _violate(self, rule: str, line: int, msg: str) -> None:
        if rule in ("blocking-call-under-lock", "unguarded-acquire"):
            super()._violate(rule, line, msg)
        # parent rules silenced: check_locks reports them

    def _check_dispatch(self, call: ast.Call, line: int) -> None:
        name = _call_name(call)
        if name is None or name in _BLOCKING_NAME_BLOCKLIST:
            return
        if name not in BLOCKING_BASE \
                and name not in self.summary.blockers:
            return
        for h in self.held:
            if h.cls in HOT_CLASSES:
                self._violate(
                    "blocking-call-under-lock", line,
                    f"blocking call `{name}(...)` while holding "
                    f"{h.cls} lock `{h.src}` (line {h.line}); every "
                    f"waiter on that lock stalls behind the block — "
                    f"move the call outside the guard or hand it to "
                    f"the io rung")
                break


def _release_srcs(stmts) -> set:
    out = set()
    for s in stmts:
        for sub in ast.walk(s):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "release":
                try:
                    out.add(ast.unparse(sub.func.value))
                except Exception:   # pragma: no cover
                    pass
    return out


def _check_unguarded(walker: _DataflowWalker) -> None:
    """Structural pass: every classifiable `.acquire()` needs a
    try/finally in the same function that releases the same lock
    expression, either enclosing the acquire or following it."""
    fn = walker.fn
    guards = []
    for t in ast.walk(fn):
        if isinstance(t, ast.Try) and t.finalbody:
            rel = _release_srcs(t.finalbody)
            if rel:
                guards.append((t, rel))
    for sub in ast.walk(fn):
        if not (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "acquire"):
            continue
        target = sub.func.value
        cls = walker._classify_env(target)
        if cls is None:
            continue
        try:
            src = ast.unparse(target)
        except Exception:   # pragma: no cover
            continue
        guarded = False
        for t, rel in guards:
            if src not in rel:
                continue
            inside = t.body and t.body[0].lineno <= sub.lineno \
                <= (t.body[-1].end_lineno or sub.lineno)
            follows = t.lineno >= sub.lineno
            if inside or follows:
                guarded = True
                break
        if not guarded:
            walker._violate(
                "unguarded-acquire", sub.lineno,
                f"bare `.acquire()` on {cls} lock `{src}` with no "
                f"try/finally releasing it; an exception here leaves "
                f"the lock held forever — use `with {src}:` or the "
                f"acquire/try/finally/release idiom")


def check_dataflow(ctx: FileContext, summary) -> List[Violation]:
    out: List[Violation] = []

    def visit(node: ast.AST, class_name: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                w = _DataflowWalker(ctx, summary, class_name, child)
                w.walk()
                _check_unguarded(w)
                out.extend(w.out)
                visit(child, class_name)
            else:
                visit(child, class_name)

    visit(ctx.tree, "")
    return out
