"""Lock-graph rules: lock-order, unsorted-locks, device-under-lock.

A lexical held-set simulation over each function body: `with` items,
`ExitStack.enter_context(...)` and bare `.acquire()` calls push onto
the held set, classified into the canonical order classes below; the
rules fire on the acquisition events.

Canonical order (must only ever grow rightward while locks are held):

  repl.maintain(0) -> repl.rebalance(1) -> repl.leases(2) ->
  repl.membership(3) -> repl.peers(4) -> repl.quorum(5) ->
  repl.writergroup(6) -> qos(8) -> global(10) -> shard(20) ->
  io(25) -> oplog(30) -> device(40) -> leaf(50)

(`qos` is the adaptive-admission controller's rung, deliberately
OUTER to the scheduler's global lock: the control loop takes qos then
global to read queue fills, while the hot admission path under global
reads the published deadline table lock-free — code under global must
never take the qos lock.)

(`repl.rebalance` is the elastic-mesh planning rung: the rebalancer
plans migrations under it and may then take lease state, but lease
code must never call back into the planner — outer to repl.leases.)

(`repl.writergroup` is the hot-doc write-splitting table's rung,
deliberately INNER to the lease lock: the lease table's floor-raise
hook fences group registrations while the lease lock is held, and the
group table never calls back into lease state while its own lock is
held — taking them the other way around deadlocks against the hook.)

(`io` is the DocStore flush-pass serializer: it is deliberately OUTER
to the oplog guard — encode runs under the store lock inside an
io-serialized pass so a stalled flusher can never overwrite a newer
snapshot — and is never held together with scheduler locks.)

Lock expressions are classified by name pattern (e.g. `_shard_locks[s]`
-> shard) with the enclosing class name disambiguating bare
`self.lock` / `self._lock` (MergeScheduler's is the global lock,
DocStore's is the oplog guard, LeaseManager's the lease lock).
Unknown lock expressions are ignored — the linter enforces the
documented order over the NAMED locks, it does not guess.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..lint import FileContext, Violation

# canonical order levels; a lock may only be acquired while every held
# lock has a strictly SMALLER level (same level: see rank/sorted rules)
ORDER_LEVELS = {
    "repl.maintain": 0,
    "repl.rebalance": 1,
    "repl.leases": 2,
    "repl.membership": 3,
    "repl.peers": 4,
    "repl.quorum": 5,
    "repl.writergroup": 6,
    "qos": 8,
    "global": 10,
    "shard": 20,
    "io": 25,
    "oplog": 30,
    "device": 40,
    "leaf": 50,
}

# direct device-dispatch surface: jax sync points + the repo's own
# dispatch wrappers. Pass 1 (lint.build_summary) widens this one hop:
# any function whose body calls one of these is itself a dispatcher.
DISPATCH_BASE = {
    "block_until_ready", "device_put",
    "fused_replay", "mesh_fused_replay", "warmup_fused_cache",
    "sync_doc",
}

# names that never mean "this call reaches a device" even though some
# function somewhere shares the name (kept tight: only add here with a
# comment saying which collision it resolves)
_DISPATCH_NAME_BLOCKLIST = {
    "get", "put", "read", "write", "append",
}

_SORTED_WRAPPERS = {"sorted"}
_ITER_WRAPPERS = {"enumerate", "reversed", "list", "tuple"}


def _classify(expr: ast.AST, class_name: str) -> Optional[str]:
    """Map a lock expression to its order class (None = unknown)."""
    try:
        src = ast.unparse(expr)
    except Exception:   # pragma: no cover - malformed tree
        return None
    if "_shard_locks" in src:
        return "shard"
    if "_device_locks" in src or "device_lock" in src \
            or src in ("dlock", "dl"):
        return "device"
    if "_sync_lock" in src or "oplog_lock" in src or src == "olock" \
            or src.endswith("store.lock") or src == "store.lock":
        return "oplog"
    # adaptive admission: the controller's rung sits between the
    # replication plane and the scheduler global lock (step() takes
    # qos -> global to read queue fills; the hot path never takes it)
    if "_qos_lock" in src:
        return "qos"
    if "_maintain_lock" in src:
        return "repl.maintain"
    # elastic mesh: the rebalancer's planning guard and the placement
    # override table both sit between maintain and the lease lock —
    # migration planning reads lease state, never the reverse
    if "_rebalance_lock" in src:
        return "repl.rebalance"
    if src.endswith("leases.lock"):
        return "repl.leases"
    # hot-doc write splitting: the group table's lock is INNER to the
    # lease lock (the floor-raise hook fences registrations under it)
    if src.endswith("writergroups.lock"):
        return "repl.writergroup"
    if "io_lock" in src:
        return "io"
    # residency tier: the hydrator's warm-map guard, the tier's table
    # lock, and the per-doc file locks ("_doc_lock" also covers the
    # `self._doc_lock(doc_id)` accessor form) all live on the io rung —
    # deliberately OUTER to the oplog guard, like io_lock above
    if "_hydrate_lock" in src or "_tier_lock" in src \
            or "_doc_lock" in src:
        return "io"
    # follower-read tier: the FollowerIndex evidence guard (`_read_lock`)
    # and the CheckoutCache guard (`_cache_lock`) are io-rung for the
    # same reason — the cache's single-flight leader materializes
    # checkouts (oplog rung) strictly OUTSIDE the cache guard, so io
    # stays outer to oplog and never the reverse
    if "_read_lock" in src or "_cache_lock" in src:
        return "io"
    # wire tier: the WireChannel snapshot-frame cache guard is io-rung
    # for the same reason as the checkout cache — frame builds (which
    # take the oplog guard) run strictly OUTSIDE the cache lock, so a
    # racing pair builds twice rather than ever nesting io inside oplog
    if "_frame_cache_lock" in src:
        return "io"
    # device-transform planning: the xform jit-cache guard is a
    # DEVICE-class lock (the batched transform dispatch runs in the
    # planning phase, under shard locks but outside the oplog guard and
    # the per-device replay locks) — must classify BEFORE the generic
    # "_jit_lock" leaf rule below
    if "_xform_jit_lock" in src:
        return "device"
    # window-arena staging: the donated-buffer recycle table guard is
    # a DEVICE-class lock (acquire/adopt bracket the mesh dispatch but
    # run under the scheduler's per-class replay, outside the oplog
    # guard; the dispatch itself never runs while it is held) — must
    # classify BEFORE the generic "_jit_lock" leaf rule below
    if "_arena_lock" in src:
        return "device"
    # shape steering: the warm-class table guard is a pure leaf —
    # note_warm/snap are called strictly OUTSIDE the jit-cache leaf
    # locks and never dispatch or call back out while held
    if "_steer_lock" in src:
        return "leaf"
    if "_first_touch_lock" in src or "_jit_lock" in src:
        return "leaf"
    # live-telemetry tier: the TimeSeries ring guard (`_ts_lock`, also
    # the exemplar store) and the top-K sketch guard (`_sketch_lock`)
    # are leaf rungs — record_*/note() double-writes happen while the
    # caller already holds serve/read/replicate locks, and the obs
    # structures never call back out while held
    if "_ts_lock" in src or "_sketch_lock" in src:
        return "leaf"
    # incident engine: the AnomalyDetector state guard and the
    # IncidentStore ring guard are leaf rungs — poll() gathers all its
    # TimeSeries/recorder reads BEFORE taking the lock and opens
    # bundles AFTER releasing it, so nothing ever nests under them
    if "_incident_lock" in src:
        return "leaf"
    if src in ("self.lock", "self._lock", "lock"):
        if "Scheduler" in class_name:
            return "global"
        if "Store" in class_name:
            return "oplog"
        if "Lease" in class_name or "Ownership" in class_name:
            return "repl.leases"
        if "Peer" in class_name:
            return "repl.peers"
        if "Quorum" in class_name:
            return "repl.quorum"
        if "WriterGroup" in class_name:
            return "repl.writergroup"
        if "Membership" in class_name:
            return "repl.membership"
        return None
    if src == "self.banks" or src.endswith("_idle_cv"):
        return None
    return None


def _call_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _is_sorted_expr(expr: ast.AST, sorted_names: Set[str]) -> bool:
    """Is `expr` lexically a sorted iteration source? Accepts
    `sorted(...)`, a Name previously bound to one, and the thin
    wrappers enumerate/reversed/list/tuple around either."""
    if isinstance(expr, ast.Call):
        name = _call_name(expr)
        if name in _SORTED_WRAPPERS:
            return True
        if name in _ITER_WRAPPERS and expr.args:
            return _is_sorted_expr(expr.args[0], sorted_names)
        return False
    if isinstance(expr, ast.Name):
        return expr.id in sorted_names
    return False


def _collect_sorted_names(fn: ast.AST) -> Set[str]:
    """Names lexically bound to sorted iteration sources in `fn`:
    `x = sorted(...)`, `x = list(sorted(...))`, and one comprehension
    hop `x = [e for t in S ...]` with S sorted. (No statement-level
    flow analysis — code that wants an acquisition loop to pass the
    sorted check binds its source visibly or suppresses with a
    justification.)"""
    names: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            ok = _is_sorted_expr(value, names)
            if not ok and isinstance(value, (ast.ListComp,
                                             ast.GeneratorExp)):
                gens = value.generators
                ok = bool(gens) and _is_sorted_expr(gens[0].iter, names)
            if ok:
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id not in names:
                        names.add(t.id)
                        changed = True
    return names


class _Held:
    __slots__ = ("cls", "level", "src", "line", "stack_tag")

    def __init__(self, cls: str, src: str, line: int,
                 stack_tag: Optional[str] = None) -> None:
        self.cls = cls
        self.level = ORDER_LEVELS[cls]
        self.src = src
        self.line = line
        self.stack_tag = stack_tag   # ExitStack var owning this entry


class _FnWalker:
    """Held-set simulation for one function body."""

    def __init__(self, ctx: FileContext, summary, class_name: str,
                 fn: ast.AST) -> None:
        self.ctx = ctx
        self.summary = summary
        self.class_name = class_name
        self.fn = fn
        self.sorted_names = _collect_sorted_names(fn)
        self.held: List[_Held] = []
        self.loops: List[ast.For] = []
        self.out: List[Violation] = []
        self.env: dict = {}
        self._build_env()

    # ---- local alias environment -----------------------------------------

    def _classify_env(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name) and expr.id in self.env:
            return self.env[expr.id]
        return _classify(expr, self.class_name)

    def _build_env(self) -> None:
        """Fixpoint over local bindings so aliases classify: `lk =
        self._device_locks[s]`, `dlocks.append(lk)`, `for lk in
        dlocks:`, walrus bindings, and comprehensions whose element is
        a classified name. A container of device locks carries the
        `device` class — iterating it re-binds the loop var to it."""
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.fn):
                cls: Optional[str] = None
                targets: List[str] = []
                if isinstance(node, ast.Assign):
                    value = node.value
                    cls = self._classify_env(value)
                    if cls is None and isinstance(
                            value, (ast.ListComp, ast.GeneratorExp)):
                        cls = self._classify_env(value.elt)
                    targets = [t.id for t in node.targets
                               if isinstance(t, ast.Name)]
                elif isinstance(node, ast.NamedExpr):
                    cls = self._classify_env(node.value)
                    if isinstance(node.target, ast.Name):
                        targets = [node.target.id]
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("append", "add") \
                        and node.args \
                        and isinstance(node.func.value, ast.Name):
                    cls = self._classify_env(node.args[0])
                    targets = [node.func.value.id]
                elif isinstance(node, ast.For) \
                        and isinstance(node.target, ast.Name):
                    cls = self._classify_env(node.iter)
                    targets = [node.target.id]
                if cls is None:
                    continue
                for t in targets:
                    if self.env.get(t) != cls:
                        self.env[t] = cls
                        changed = True

    # ---- events ----------------------------------------------------------

    def _violate(self, rule: str, line: int, msg: str) -> None:
        self.out.append(Violation(rule=rule, path=self.ctx.rel,
                                  line=line, message=msg))

    def _acquire(self, expr: ast.AST, line: int,
                 stack_tag: Optional[str] = None,
                 in_loop: bool = False) -> Optional[_Held]:
        cls = self._classify_env(expr)
        if cls is None:
            return None
        try:
            src = ast.unparse(expr)
        except Exception:   # pragma: no cover
            src = "<lock>"
        level = ORDER_LEVELS[cls]
        for h in self.held:
            if h.level > level:
                self._violate(
                    "lock-order", line,
                    f"acquires {cls} lock `{src}` while holding "
                    f"{h.cls} lock `{h.src}` (line {h.line}); "
                    f"canonical order is "
                    f"{' -> '.join(k for k, _ in sorted(ORDER_LEVELS.items(), key=lambda kv: kv[1]))}")
            elif h.cls == cls and h.src == src and not in_loop:
                # same expression re-entered outside a loop: either a
                # reentrant lock (fine at runtime) or a copy-paste bug;
                # the witness checks the runtime side, stay quiet here
                pass
        if in_loop and cls in ("shard", "device") \
                and stack_tag is not None:
            loop = self.loops[-1]
            if not _is_sorted_expr(loop.iter, self.sorted_names):
                try:
                    it = ast.unparse(loop.iter)
                except Exception:   # pragma: no cover
                    it = "<iter>"
                self._violate(
                    "unsorted-locks", line,
                    f"acquires multiple {cls} locks (`{src}`) in a "
                    f"loop over `{it}` whose sort order is not "
                    f"lexically evident; iterate a `sorted(...)` "
                    f"source (or bind it via one comprehension hop) "
                    f"so every path agrees on acquisition order")
        h = _Held(cls, src, line, stack_tag=stack_tag)
        self.held.append(h)
        return h

    def _release_tag(self, tag: str) -> None:
        self.held = [h for h in self.held if h.stack_tag != tag]

    def _check_dispatch(self, call: ast.Call, line: int) -> None:
        name = _call_name(call)
        if name is None or name in _DISPATCH_NAME_BLOCKLIST:
            return
        if name not in DISPATCH_BASE \
                and name not in self.summary.dispatchers:
            return
        for h in self.held:
            if h.cls in ("global", "oplog"):
                self._violate(
                    "device-under-lock", line,
                    f"device dispatch `{name}(...)` while holding "
                    f"{h.cls} lock `{h.src}` (line {h.line}); device "
                    f"work may only run under shard/device locks so "
                    f"submits and oplog readers never stall behind a "
                    f"device call")
                break

    # ---- expression scan (calls inside one statement) --------------------

    def _scan_expr(self, node: ast.AST, in_loop: bool) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = _call_name(sub)
            if name == "enter_context" and sub.args:
                tag = None
                fn = sub.func
                if isinstance(fn, ast.Attribute) \
                        and isinstance(fn.value, ast.Name):
                    tag = fn.value.id
                self._acquire(sub.args[0], sub.lineno,
                              stack_tag=tag or "<stack>",
                              in_loop=in_loop)
            elif name == "acquire" and isinstance(sub.func,
                                                  ast.Attribute):
                self._acquire(sub.func.value, sub.lineno,
                              stack_tag="<acquired>", in_loop=in_loop)
            elif name == "release" and isinstance(sub.func,
                                                  ast.Attribute):
                cls = self._classify_env(sub.func.value)
                if cls is not None:
                    for i in range(len(self.held) - 1, -1, -1):
                        if self.held[i].cls == cls and \
                                self.held[i].stack_tag == "<acquired>":
                            del self.held[i]
                            break
            else:
                self._check_dispatch(sub, sub.lineno)

    # ---- statement walk --------------------------------------------------

    def walk(self) -> List[Violation]:
        body = getattr(self.fn, "body", [])
        self._walk_body(body)
        return self.out

    def _walk_body(self, stmts) -> None:
        for st in stmts:
            self._walk_stmt(st)

    def _walk_stmt(self, st: ast.stmt) -> None:
        in_loop = bool(self.loops)
        if isinstance(st, (ast.With, ast.AsyncWith)):
            acquired: List[_Held] = []
            stack_vars: List[str] = []
            for item in st.items:
                ce = item.context_expr
                if isinstance(ce, ast.Call) \
                        and _call_name(ce) == "ExitStack":
                    if isinstance(item.optional_vars, ast.Name):
                        stack_vars.append(item.optional_vars.id)
                    continue
                self._scan_expr(ce, in_loop)
                h = self._acquire(ce, st.lineno, in_loop=in_loop)
                if h is not None:
                    acquired.append(h)
            self._walk_body(st.body)
            for h in acquired:
                if h in self.held:
                    self.held.remove(h)
            for tag in stack_vars:
                self._release_tag(tag)
        elif isinstance(st, ast.For):
            self._scan_expr(st.iter, in_loop)
            self.loops.append(st)
            self._walk_body(st.body)
            self.loops.pop()
            self._walk_body(st.orelse)
        elif isinstance(st, ast.While):
            self._scan_expr(st.test, in_loop)
            self._walk_body(st.body)
            self._walk_body(st.orelse)
        elif isinstance(st, ast.If):
            self._scan_expr(st.test, in_loop)
            self._walk_body(st.body)
            self._walk_body(st.orelse)
        elif isinstance(st, ast.Try):
            self._walk_body(st.body)
            for h in st.handlers:
                self._walk_body(h.body)
            self._walk_body(st.orelse)
            self._walk_body(st.finalbody)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pass    # nested defs are walked as their own functions
        elif isinstance(st, ast.ClassDef):
            pass
        else:
            self._scan_expr(st, in_loop)


def check_locks(ctx: FileContext, summary) -> List[Violation]:
    out: List[Violation] = []
    stack: List[Tuple[str, ast.AST]] = [("", ctx.tree)]
    # walk every function with its enclosing class name for `self.lock`
    # disambiguation (nested defs get their own empty held set — a
    # worker closure does not inherit its parent's lexical locks, which
    # is exactly the conservative direction)
    def visit(node: ast.AST, class_name: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                out.extend(_FnWalker(ctx, summary, class_name,
                                     child).walk())
                visit(child, class_name)
            else:
                visit(child, class_name)
    visit(ctx.tree, "")
    return out
