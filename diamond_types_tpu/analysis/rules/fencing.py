"""Fencing rule: doc-state mutation on write paths must be fenced.

Two scopes, matching the two write paths the replication design
documents (serve/README.md "Cross-host replication"):

  scheduler scope   in a class that defines `_fence` (i.e. it
                    participates in lease fencing), any method that
                    reaches a doc-state mutator (sync_doc/sync_docs/
                    adopt_window, directly or one hop through a method
                    whose own body mutates) must either contain a
                    fencing token itself or call only through methods
                    that fence internally (`_flush_items` calls
                    `self._fence` before touching docs, so calling it
                    is fine).

  handler scope     HTTP handler `do_*`/`_do_*` methods (classes with
                    "Handler" in the name) that decode or apply remote
                    ops must check the claimed lease epoch: reference
                    `X-DT-Lease-Epoch` or `check_write_fence`. The
                    pull-side client (`SyncClient`) is out of scope —
                    it applies ops it asked for.

An unfenced mutation is how a deposed leader keeps writing after its
lease moved: the lint makes "every mutation path re-checks the fence"
a build-time property instead of a soak-time hope.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..lint import FileContext, Violation

# doc-state mutators (method names on DocBank / scheduler internals)
MUTATOR_BASE = {"sync_doc", "sync_docs", "adopt_window"}

# any of these appearing in a method body counts as "this path checks
# the fence": the scheduler's lease check, the server's epoch header,
# the replica node's fence predicate, and the lease-table reads used
# to implement them
FENCE_TOKENS = {
    "_fence", "check_write_fence", "admit", "owns", "epoch_of",
    "active_epoch", "X-DT-Lease-Epoch",
}

# handler-side raw apply surface: decoding remote payloads into doc
# state or applying CRDT ops directly
_HANDLER_MUTATORS = {
    "decode_into", "_crdt_apply_op", "add_insert_at", "add_delete_at",
}


def _method_calls(fn: ast.AST) -> Set[str]:
    calls: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Name):
                calls.add(f.id)
            elif isinstance(f, ast.Attribute):
                calls.add(f.attr)
    return calls


def _method_tokens(fn: ast.AST) -> Set[str]:
    tokens: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Attribute):
            tokens.add(sub.attr)
        elif isinstance(sub, ast.Name):
            tokens.add(sub.id)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            tokens.add(sub.value)
    return tokens


def _first_mutating_call(fn: ast.AST, mutating: Set[str]):
    """(lineno, name) of the first call into `mutating`, else None."""
    best = None
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name in mutating:
            if best is None or sub.lineno < best[0]:
                best = (sub.lineno, name)
    return best


def check_fencing(ctx: FileContext, summary) -> List[Violation]:
    out: List[Violation] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        method_names = {m.name for m in methods}
        defines_fence = "_fence" in method_names

        if defines_fence:
            # mutating surface from THIS method's point of view: the
            # raw mutators plus any method (here or cross-file) whose
            # body mutates — minus methods that fence internally
            # (calling a self-fencing method is a fenced mutation)
            mutating = (MUTATOR_BASE | set(summary.mutators)) \
                - set(summary.self_fenced)
            for m in methods:
                if m.name == "_fence":
                    continue
                hit = _first_mutating_call(m, mutating)
                if hit is None:
                    continue
                if _method_tokens(m) & FENCE_TOKENS:
                    continue
                line, name = hit
                out.append(Violation(
                    rule="unfenced-mutation", path=ctx.rel, line=line,
                    message=(
                        f"{cls.name}.{m.name} reaches doc-state "
                        f"mutator `{name}` with no fencing check; a "
                        f"deposed leader can keep mutating after its "
                        f"lease moved — call `self._fence(...)` / "
                        f"`admit` first, or route through a method "
                        f"that fences internally")))

        if "Handler" in cls.name:
            for m in methods:
                if not (m.name.startswith("do_")
                        or m.name.startswith("_do_")):
                    continue
                hit = _first_mutating_call(m, _HANDLER_MUTATORS)
                if hit is None:
                    continue
                tokens = _method_tokens(m)
                if "X-DT-Lease-Epoch" in tokens \
                        or "check_write_fence" in tokens:
                    continue
                line, name = hit
                out.append(Violation(
                    rule="unfenced-mutation", path=ctx.rel, line=line,
                    message=(
                        f"{cls.name}.{m.name} applies remote ops "
                        f"(`{name}`) without validating the claimed "
                        f"lease epoch; check the X-DT-Lease-Epoch "
                        f"header via node.check_write_fence and "
                        f"answer 409 when fenced")))
    return out
