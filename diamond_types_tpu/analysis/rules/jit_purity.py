"""Jit-purity rules: impurity inside traced bodies; thin cache keys.

jit-impurity (warn) — resolves the first argument of `jax.jit(...)` /
`jit(...)` / `shard_map(...)`:

  * a Lambda: scanned directly;
  * a Name bound to a local or module-level `def`: the def is scanned;
  * a module-local factory call (`jax.jit(make_replay_body(mi))`):
    the factory's returned inner `def` is scanned — the repo's
    standard pattern for shape-specialised kernels.

Inside the resolved body, host impurity is flagged: `time.*`,
`random.*` / `np.random`, `open(`, `print(`, `os.environ`,
`datetime.now`, and `global`/`nonlocal` statements. Traced bodies run
an unpredictable number of times (trace + compile + replay), so host
effects there are at best misleading and at worst nondeterminism that
only shows up on retrace.

jit-cache-key (warn) — subscript/.get() lookups on names ending
`_jit_cache` whose key tuple (resolved through one local
`key = (...)` assignment) has fewer than 3 elements. The kernels are
shape-specialised on (batch, n_ops, max_insert[, cap][, mesh]); a
2-tuple key means two different shapes collide on one compiled fn.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..lint import FileContext, Violation

_JIT_NAMES = {"jit", "shard_map"}

# (dotted-prefix, message) checked against unparsed call/attribute text
_IMPURE_CALLS = {
    "time.": "host clock read",
    "random.": "host RNG",
    "np.random": "host RNG (numpy)",
    "numpy.random": "host RNG (numpy)",
    "datetime.now": "host clock read",
    "os.environ": "host environment read",
}
_IMPURE_BARE = {"open": "host io", "print": "host io/stdout"}


def _module_defs(tree: ast.Module) -> Dict[str, ast.AST]:
    """name -> def for module-level and one-level-nested functions."""
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    return defs


def _returned_inner_def(factory: ast.AST) -> Optional[ast.AST]:
    """For a factory function, the inner def it returns (the
    make_replay_body -> run pattern)."""
    inner: Dict[str, ast.AST] = {}
    for node in factory.body if hasattr(factory, "body") else []:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner[node.name] = node
    for node in ast.walk(factory):
        if isinstance(node, ast.Return) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in inner:
            return inner[node.value.id]
        if isinstance(node, ast.Return) \
                and isinstance(node.value, ast.Lambda):
            return node.value
    return None


def _resolve_body(arg: ast.AST, defs: Dict[str, ast.AST]) -> Optional[ast.AST]:
    if isinstance(arg, ast.Lambda):
        return arg
    if isinstance(arg, ast.Name):
        return defs.get(arg.id)
    if isinstance(arg, ast.Call):
        f = arg.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        factory = defs.get(name) if name else None
        if factory is not None:
            return _returned_inner_def(factory)
    return None


def _scan_body(ctx: FileContext, body: ast.AST, where: str,
               out: List[Violation]) -> None:
    for node in ast.walk(body):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            out.append(Violation(
                rule="jit-impurity", path=ctx.rel, line=node.lineno,
                message=(f"{where}: `{'global' if isinstance(node, ast.Global) else 'nonlocal'}` "
                         f"statement inside a traced body — traced "
                         f"code must be pure (it reruns on retrace)")))
            continue
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in _IMPURE_BARE:
            out.append(Violation(
                rule="jit-impurity", path=ctx.rel, line=node.lineno,
                message=(f"{where}: {_IMPURE_BARE[f.id]} "
                         f"(`{f.id}(...)`) inside a traced body")))
            continue
        try:
            src = ast.unparse(f)
        except Exception:   # pragma: no cover
            continue
        for prefix, why in _IMPURE_CALLS.items():
            if src.startswith(prefix) or src == prefix.rstrip("."):
                out.append(Violation(
                    rule="jit-impurity", path=ctx.rel,
                    line=node.lineno,
                    message=(f"{where}: {why} (`{src}(...)`) inside "
                             f"a traced body — hoist it to the host "
                             f"side and pass the value in")))
                break


def _scope_walk(scope: ast.AST):
    """Walk `scope` WITHOUT descending into nested function defs —
    each def is its own key-binding scope (a `key = (a, b)` in one
    helper must not reinterpret another helper's 7-tuple key)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_cache_keys(ctx: FileContext, out: List[Violation]) -> None:
    # local `key = (...)` bindings, resolved per scope, one hop
    scopes = [ctx.tree] + [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in scopes:
        key_sizes: Dict[str, int] = {}
        for node in _scope_walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Tuple):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        key_sizes[t.id] = len(node.value.elts)

        def key_width(expr: ast.AST) -> Optional[int]:
            if isinstance(expr, ast.Tuple):
                return len(expr.elts)
            if isinstance(expr, ast.Name):
                return key_sizes.get(expr.id)
            return None

        for node in _scope_walk(fn):
            cache_name = None
            key_expr = None
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id.endswith("_jit_cache"):
                cache_name = node.value.id
                key_expr = node.slice
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id.endswith("_jit_cache") \
                    and node.args:
                cache_name = node.func.value.id
                key_expr = node.args[0]
            if cache_name is None:
                continue
            width = key_width(key_expr)
            if width is not None and width < 3:
                out.append(Violation(
                    rule="jit-cache-key", path=ctx.rel,
                    line=node.lineno,
                    message=(
                        f"`{cache_name}` keyed by a {width}-tuple; "
                        f"shape-specialised kernels need every shape "
                        f"dim in the cache key (batch, n_ops, "
                        f"max_insert at minimum) or two shapes "
                        f"collide on one compiled fn")))


def check_jit_purity(ctx: FileContext, summary) -> List[Violation]:
    out: List[Violation] = []
    defs = _module_defs(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name not in _JIT_NAMES or not node.args:
            continue
        body = _resolve_body(node.args[0], defs)
        if body is None:
            continue
        where = f"{name}() body at line {node.lineno}"
        _scan_body(ctx, body, where, out)
    _check_cache_keys(ctx, out)
    return out
