"""Metrics-schema drift rule (error).

The repo's metrics contract is "declared key surfaces, fixed at
module scope": ReplicationMetrics._GROUPS, serve's _SHARD_KEYS /
HYDRATION_KEYS, read's READ_KEYS, storage's TIER_KEYS. The prom
renderer zero-fills families from those same tuples, and the PR 10
live-telemetry double-write derives its TimeSeries names from them
(`repl.{group}.{key}`, `read.{key}`, `serve.{key}`). A producer that
bumps a key missing from its declared tuple either raises at runtime
(ReadMetrics) or silently mints a counter no renderer ever exports
(dict-backed producers) — both are schema drift.

This rule cross-references, at lint time, every literal-keyed
recording call against the REAL declared tuples (imported, not
copied, so the rule can never drift from the schema itself):

  .bump("group", "key")        both in ReplicationMetrics._GROUPS
  .bump("group", key_var)      group-forwarding wrapper: group exists
  .bump(shard_var, "key")      ServeMetrics style: key in _SHARD_KEYS
  ._bump("key") / .bump("key") key in SOME declared single-key surface
  .record_hydration("key")     key in HYDRATION_KEYS
  .observe_latency("name")     name in the replication histogram set
  .bump_wire("chan", "key")    chan in wire.frames.WIRE_CHANNELS and
                               key in WIRE_KEYS (the flat `wire` group
                               key is derived as f"{chan}_{key}", so
                               the generic literal check can't see it)
  .account("chan", sent_bytes=...)  WireChannel accounting entrypoint:
                               chan in WIRE_CHANNELS (only calls that
                               pass a wire accounting keyword are
                               matched — `.account` alone is too
                               generic a method name)
  .bump_class("cls", "key")    cls in qos.classes.QOS_CLASSES and key
                               in qos.metrics.QOS_CLASS_KEYS (the
                               dt_qos_*{class} prom families zero-fill
                               from those same tuples)
  .bump_ctl("key")             key in qos.metrics.QOS_CTL_KEYS
  .open_incident("kind", ...)  kind in obs.incident.INCIDENT_KINDS
                               (also `_open_locked` — the detector's
                               internal entrypoint; the dt_incident_*
                               prom families zero-fill from that same
                               tuple, so an undeclared kind would mint
                               a bundle no renderer ever counts)

plus the exemplar join: a module defining `_EXEMPLAR_FAMILIES` (the
prom histogram -> TimeSeries mapping) must only name families some
producer actually writes — the full family string, or its last-dot
suffix, must appear as a literal in an inc/observe/observe_latency
call somewhere in the linted tree (summary.metric_literals).

The single-key check is a union across surfaces: a key valid for tier
but bumped on the read path would pass. That imprecision is accepted
— the drift failure this rule exists for is "key renamed/added on one
side only", which the union does catch.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..lint import FileContext, Violation
from ...obs.incident import INCIDENT_KINDS
from ...qos.classes import QOS_CLASSES
from ...qos.metrics import QOS_CLASS_KEYS, QOS_CTL_KEYS
from ...read.metrics import READ_KEYS
from ...replicate.metrics import _GROUPS, _LATENCY_NAMES
from ...serve.metrics import HYDRATION_KEYS, _SHARD_KEYS
from ...storage.tier import TIER_KEYS
from ...wire.frames import WIRE_CHANNELS, WIRE_KEYS

# keywords that mark an `.account(...)` call as wire accounting (the
# bare method name is too generic to match on its own)
_WIRE_ACCOUNT_KWARGS = {"sent_bytes", "json_bytes", "framed", "snapshot"}

_GROUP_KEYS = {k for keys in _GROUPS.values() for k in keys}
# every declared single-key surface a bare `.bump("key")` may target
_SINGLE_KEYS = (set(READ_KEYS) | set(HYDRATION_KEYS)
                | set(_SHARD_KEYS) | set(TIER_KEYS) | _GROUP_KEYS)

_RECORDERS = {"bump", "_bump", "_bump_group"}


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def check_metrics_schema(ctx: FileContext, summary) -> List[Violation]:
    out: List[Violation] = []

    def violate(line: int, msg: str) -> None:
        out.append(Violation(rule="metrics-schema-drift", path=ctx.rel,
                             line=line, message=msg))

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            name = node.func.attr
            args = node.args
            if name in _RECORDERS and args:
                a0 = _const_str(args[0])
                a1 = _const_str(args[1]) if len(args) > 1 else None
                if a0 is not None and a1 is not None:
                    if a0 in _GROUPS:
                        if a1 not in _GROUPS[a0]:
                            violate(node.lineno,
                                    f"bump key {a1!r} is not declared "
                                    f"in ReplicationMetrics._GROUPS"
                                    f"[{a0!r}] — prom zero-fill and "
                                    f"the repl.{a0}.* time-series "
                                    f"table will never export it")
                    elif a1 in _GROUP_KEYS:
                        violate(node.lineno,
                                f"bump group {a0!r} is not declared "
                                f"in ReplicationMetrics._GROUPS (key "
                                f"{a1!r} belongs to a declared group)")
                elif a0 is not None:
                    # single literal: a direct key, or a
                    # group-forwarding wrapper's bound group
                    if a0 not in _GROUPS and a0 not in _SINGLE_KEYS:
                        violate(node.lineno,
                                f"bump key {a0!r} is not declared on "
                                f"any metrics surface (_GROUPS, "
                                f"_SHARD_KEYS, HYDRATION_KEYS, "
                                f"READ_KEYS, TIER_KEYS)")
                elif a1 is not None:
                    # ServeMetrics style: bump(shard, "key")
                    if a1 not in _SINGLE_KEYS:
                        violate(node.lineno,
                                f"bump key {a1!r} is not declared on "
                                f"any metrics surface")
            elif name == "bump_wire" and args:
                a0 = _const_str(args[0])
                a1 = _const_str(args[1]) if len(args) > 1 else None
                if a0 is not None and a0 not in WIRE_CHANNELS:
                    violate(node.lineno,
                            f"wire channel {a0!r} is not in "
                            f"wire.frames.WIRE_CHANNELS "
                            f"{WIRE_CHANNELS} — the dt_wire_* prom "
                            f"families will never export it")
                if a1 is not None and a1 not in WIRE_KEYS:
                    violate(node.lineno,
                            f"wire key {a1!r} is not in "
                            f"wire.frames.WIRE_KEYS {WIRE_KEYS}")
            elif name == "account" and args and any(
                    kw.arg in _WIRE_ACCOUNT_KWARGS
                    for kw in node.keywords):
                a0 = _const_str(args[0])
                if a0 is not None and a0 not in WIRE_CHANNELS:
                    violate(node.lineno,
                            f"wire channel {a0!r} is not in "
                            f"wire.frames.WIRE_CHANNELS "
                            f"{WIRE_CHANNELS}")
            elif name == "bump_class" and args:
                a0 = _const_str(args[0])
                a1 = _const_str(args[1]) if len(args) > 1 else None
                if a0 is not None and a0 not in QOS_CLASSES:
                    violate(node.lineno,
                            f"qos class {a0!r} is not in "
                            f"qos.classes.QOS_CLASSES {QOS_CLASSES} — "
                            f"the dt_qos_* prom families zero-fill "
                            f"only the declared taxonomy")
                if a1 is not None and a1 not in QOS_CLASS_KEYS:
                    violate(node.lineno,
                            f"qos counter {a1!r} is not in "
                            f"qos.metrics.QOS_CLASS_KEYS "
                            f"{QOS_CLASS_KEYS}")
            elif name == "bump_ctl" and args:
                a0 = _const_str(args[0])
                if a0 is not None and a0 not in QOS_CTL_KEYS:
                    violate(node.lineno,
                            f"qos controller decision {a0!r} is not "
                            f"in qos.metrics.QOS_CTL_KEYS "
                            f"{QOS_CTL_KEYS}")
            elif name in ("open_incident", "_open_locked") and args:
                a0 = _const_str(args[0])
                if a0 is not None and a0 not in INCIDENT_KINDS:
                    violate(node.lineno,
                            f"incident kind {a0!r} is not in "
                            f"obs.incident.INCIDENT_KINDS "
                            f"{INCIDENT_KINDS} — the dt_incident_* "
                            f"prom families zero-fill only the "
                            f"declared kinds (open_incident would "
                            f"also raise at runtime)")
            elif name == "record_hydration" and args:
                a0 = _const_str(args[0])
                if a0 is not None and a0 not in HYDRATION_KEYS:
                    violate(node.lineno,
                            f"hydration event {a0!r} is not in "
                            f"serve.metrics.HYDRATION_KEYS — the "
                            f"residency-tier prom block will never "
                            f"carry it")
            elif name == "observe_latency" and args:
                a0 = _const_str(args[0])
                if a0 is not None and a0 not in _LATENCY_NAMES:
                    violate(node.lineno,
                            f"latency family {a0!r} is not in the "
                            f"replication histogram set "
                            f"{_LATENCY_NAMES}")
        elif isinstance(node, ast.Assign):
            # the prom exemplar join: families must have a producer
            names = {t.id for t in node.targets
                     if isinstance(t, ast.Name)}
            if "_EXEMPLAR_FAMILIES" not in names \
                    or not isinstance(node.value, ast.Dict):
                continue
            for v in node.value.values:
                fam = _const_str(v)
                if fam is None:
                    continue
                suffix = fam.rsplit(".", 1)[-1]
                lits = summary.metric_literals
                if fam not in lits and suffix not in lits:
                    violate(v.lineno,
                            f"exemplar family {fam!r} has no "
                            f"producer: neither the family nor its "
                            f"suffix appears in any inc/observe/"
                            f"observe_latency call in the linted "
                            f"tree")
    return out
