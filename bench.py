#!/usr/bin/env python3
"""Benchmark driver — prints ONE compact JSON line with the primary metric.

Primary metric (BASELINE.json): ops/sec merged on git-makefile.dt
(high-fanout concurrent DAG), with text-equality parity (two independent
checkouts must agree byte-for-byte; friendsforever.dt must match the
reference's flattened trace).

vs_baseline: ratio against the MEASURED local baseline (BASELINE.md
"Measured locally"): the C++ host engine's round-2 git-makefile merge
throughput on this machine, frozen at LOCAL_BASELINE_OPS_PER_SEC. The
reference's own criterion harness can't be re-run here (no Rust
toolchain in this image, zero egress to install one); the author's
published 12 ms automerge-paper replay figure is reported only as
context in extra.vs_published_replay_figure.

Output discipline (round-3 driver contract — BENCH_r02.json was
parsed:null because the summary line outgrew the driver's tail window):
  * The FINAL stdout line is a compact JSON summary: scalars and SHORT
    error strings only, hard-capped in size (`_compact_extra`).
  * The full verbose report (stats, counters, error tails, sweep data)
    goes to stderr AND to bench_report_full.json — never the final line.
  * Device benches run FIRST (a tunnel that wedges mid-run must not
    erase the flagship evidence), behind a cheap liveness probe, with
    one retry + backoff on wedge/timeout signatures; after two
    consecutive total failures the remaining device benches are skipped
    with short error strings instead of burning their timeouts.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Round-2 measured local baseline: C++ host engine, git-makefile.dt merge,
# this machine (see BASELINE.md "Measured locally" for the full table).
LOCAL_BASELINE_OPS_PER_SEC = 27_171_331
# The reference author's published replay figure (unspecified hardware),
# kept as context only: crates/bench/src/main.rs:56-58.
PUBLISHED_REPLAY_OPS_PER_SEC = 259_778 / 0.012

BENCH_DATA = "/root/reference/benchmark_data"

# Device liveness window (seconds): the snippet prelude's watchdog allows
# this long for backend init + one forced-transfer op before failing fast.
LIVENESS_S = 60
RETRY_BACKOFF_S = 15
# Final-line budget (driver tail window safety margin).
MAX_SUMMARY_CHARS = 3500


def bench_merge(name: str, repeats: int = 3, warm: bool = True):
    from diamond_types_tpu.encoding.decode import load_oplog
    with open(os.path.join(BENCH_DATA, name), "rb") as f:
        data = f.read()
    ol = load_oplog(data)
    n_ops = len(ol)
    if warm:
        # one unmeasured checkout: the first call pays the native
        # context's one-time bulk load (graph/agent/op columns), which is
        # not merge work (round-3 friendsforever "merge outlier" was
        # exactly this sync billed to a single-repeat measurement)
        ol.checkout_tip()
    best = float("inf")
    snap = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        b = ol.checkout_tip()
        dt = time.perf_counter() - t0
        best = min(best, dt)
        if snap is None:
            snap = b.snapshot()
        else:
            assert snap == b.snapshot(), "non-deterministic merge!"
    return n_ops, best, snap, ol


def _run_device_bench(code: str, timeout: int):
    """Run a device bench snippet in a subprocess.

    Returns {"ok": True, "value": ..., ...extra keys printed as KEY=val} or
    {"ok": False, "why": ..., "tail": ...} — the why/tail always say what
    actually happened (init hang vs timeout vs crash), per VERDICT r1
    weakness #2: device benches must never vanish silently.
    """
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout)
        stdout, stderr, rc = r.stdout, r.stderr, r.returncode
    except subprocess.TimeoutExpired as e:
        stdout = (e.stdout or b"").decode("utf-8", "replace") \
            if isinstance(e.stdout, bytes) else (e.stdout or "")
        # Long benches (the batch sweep) print cumulative RESULT/JSONDATA
        # lines per stage: a timeout banks whatever stages completed
        # instead of discarding a 15-minute run.
        partial = _parse_bench_stdout(stdout)
        if partial.get("ok"):
            partial["partial_timeout"] = f"timed out after {timeout}s; " \
                "result covers completed stages only"
            return partial
        phase = "after device init" if "PLATFORM" in stdout \
            else "during jax/device init"
        return {"ok": False, "why": f"timeout after {timeout}s {phase}",
                "tail": stdout.strip().splitlines()[-1][:200]
                if stdout.strip() else ""}
    except OSError as e:
        return {"ok": False, "why": f"spawn failed: {e}"}

    if "DEVICE_UNRESPONSIVE" in stdout:
        return {"ok": False,
                "why": f"device unresponsive (liveness probe timed out "
                       f"after {LIVENESS_S}s; tunnel/backend wedged)",
                "tail": stderr.strip().splitlines()[-1][:200]
                if stderr.strip() else "",
                "platform": next((ln.split(None, 1)[1] for ln in
                                  stdout.splitlines()
                                  if ln.startswith("PLATFORM ")), "?")}
    out = _parse_bench_stdout(stdout)
    if out.get("ok"):
        if rc != 0:
            # cumulative-progress snippets can crash after printing valid
            # stage results: keep the data, but carry the crash so the
            # caller/bank can distinguish this from a completed run
            out["partial_crash"] = f"exit {rc}: " + (
                stderr.strip().splitlines()[-1][:160]
                if stderr.strip() else "no stderr")
        return out
    tail = stderr.strip().splitlines()[-1][:200] if stderr.strip() else ""
    return {"ok": False, "why": f"exit {rc}", "tail": tail, **out}


def _parse_bench_stdout(stdout: str) -> dict:
    """Parse a bench snippet's stdout protocol. Repeated RESULT/JSONDATA
    lines overwrite (snippets print cumulative progress so partial runs
    are parseable)."""
    out = {}
    for line in stdout.splitlines():
        if line.startswith("RESULT "):
            out["ok"] = True
            out["value"] = float(line.split()[1])
        elif line.startswith("PLATFORM "):
            out["platform"] = line.split(None, 1)[1]
        elif line.startswith("JSONDATA "):
            # structured per-bench payload (e.g. the batch sweep curve)
            try:
                out.update(json.loads(line[len("JSONDATA "):]))
            except ValueError:
                pass
        else:
            # any other "KEY value" line becomes an extra field
            parts = line.split()
            if len(parts) == 2 and parts[0].isupper():
                try:
                    out[parts[0].lower()] = float(parts[1])
                except ValueError:
                    pass
    return out


def _is_wedge(r: dict) -> bool:
    """Failure signatures a retry can plausibly cure (tunnel/backend hangs)
    vs real bugs (parity asserts, crashes) where a retry just wastes time."""
    why = r.get("why", "")
    return "unresponsive" in why or "timeout" in why


def _run_device_bench_retry(code: str, timeout: int):
    r = _run_device_bench(code, timeout)
    if not r.get("ok") and _is_wedge(r):
        time.sleep(RETRY_BACKOFF_S)
        r2 = _run_device_bench(code, timeout)
        r2.setdefault("retried", True)
        return r2
    return r


# Shared snippet prelude: the environment's site hook force-initializes the
# TPU backend inside jax.devices() regardless of JAX_PLATFORMS; honoring an
# explicit env request via the config API (before backend init) keeps the
# snippets smoke-testable on CPU while defaulting to the chip.
_PRELUDE = """
import sys, os, threading, time, json
sys.path.insert(0, {repo!r})
import numpy as np

# A wedged device/tunnel otherwise burns the full subprocess timeout. A
# watchdog THREAD (not SIGALRM: a C-blocked init call never returns to
# the interpreter, so a Python signal handler would not run) gives init +
# one trivial forced-transfer op {liveness}s, then fails fast precisely.
_live = threading.Event()

def _watchdog():
    if not _live.wait({liveness}):
        print("DEVICE_UNRESPONSIVE liveness probe did not complete",
              flush=True)
        os._exit(3)

threading.Thread(target=_watchdog, daemon=True).start()
import jax
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
try:
    # Persistent compile cache shared by every bench subprocess: device
    # bench retries across watcher windows skip their multi-minute XLA
    # compiles (the 2026-07-31 sweep lost chunks 64+ to compile time
    # alone). Harmless if the backend can't serialize executables.
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join({repo!r}, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except Exception:
    pass
import jax.numpy as jnp
print("PLATFORM", jax.devices()[0].platform, flush=True)
np.asarray(jnp.arange(4) + 1)   # liveness: forces a real device round-trip
_live.set()
print("DEVICE_LIVE 1", flush=True)

def bench_call(fn, fetch, reps=5):
    # Time fn() end to end, forcing completion by TRANSFERRING a small
    # output (np.asarray). On the tunneled TPU platform here,
    # block_until_ready() returns before the computation has actually
    # drained -- timing with it under-reports by orders of magnitude (the
    # round-1/2 device numbers had exactly that artifact). A host transfer
    # is the only sync primitive we can trust, so every rep pays one tiny
    # fetch + tunnel round-trip; reported numbers INCLUDE that latency.
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(fetch(fn()))
        ts.append(time.perf_counter() - t0)
    return min(ts)
"""

_PROBE_SNIPPET = _PRELUDE + """
# Liveness verdict FIRST — the RTT measurement below is advisory and
# must never turn a live-but-slow tunnel into "probe failed" (which
# would skip every device bench).
print("RESULT 1", flush=True)

# Tunnel RTT floor: time a trivial 1-element op end to end (dispatch +
# tunnel round trip + transfer; effectively zero kernel time). This is
# the intercept of every device bench's latency — reported so device
# numbers decompose into "tunnel floor" vs "kernel time" (VERDICT r3
# next-step #1: prove which one binds). Guarded by its own deadline: if
# the tunnel stalls mid-measurement, exit cleanly with the liveness
# verdict already on stdout.
_rtt_done = threading.Event()

def _rtt_guard():
    if not _rtt_done.wait(20):
        os._exit(0)

threading.Thread(target=_rtt_guard, daemon=True).start()
try:
    x = jnp.arange(4)
    f = jax.jit(lambda v: v + 1)
    np.asarray(f(x))   # compile
    rtt = bench_call(lambda: f(x), lambda r: r, reps=3)
    print("RTT_MS", rtt * 1e3, flush=True)
except Exception:
    pass
_rtt_done.set()
"""


def device_probe(timeout: int = LIVENESS_S + 30):
    """Cheap tunnel/backend liveness gate run before any device bench.
    Also measures the tunnel's RTT floor (returned as rtt_ms)."""
    code = _PROBE_SNIPPET.format(
        repo=os.path.dirname(os.path.abspath(__file__)), liveness=LIVENESS_S)
    return _run_device_bench_retry(code, timeout)


_TPU_BENCH_SNIPPET = _PRELUDE + """
from functools import partial
from __graft_entry__ import _example_batch
from diamond_types_tpu.tpu.batch import replay_batch
batch, n_ops, cap = {batch}, {n_ops}, {cap}
pos, dlen, ilen, chars = _example_batch(batch, n_ops, 4)
args = tuple(jnp.asarray(x) for x in (pos, dlen, ilen, chars))
fn = jax.jit(partial(replay_batch, cap=cap))
np.asarray(fn(*args)[1])  # warmup/compile
dt = bench_call(lambda: fn(*args), lambda r: r[1])
print("RESULT", batch * n_ops / dt)
"""


def bench_tpu_batch(batch: int = 1024, n_ops: int = 256, cap: int = 1024,
                    timeout: int = 240):
    """Batched multi-doc replay on the real chip (BASELINE config 4 shape)."""
    code = _TPU_BENCH_SNIPPET.format(
        repo=os.path.dirname(os.path.abspath(__file__)),
        batch=batch, n_ops=n_ops, cap=cap, liveness=LIVENESS_S)
    return _run_device_bench_retry(code, timeout)


_MERGE_KERNEL_SNIPPET = _PRELUDE + """
os.environ["DT_TPU_PALLAS"] = {pallas!r}
if {pallas!r}:
    # a Pallas bench must fail loudly rather than silently report the
    # XLA fallback's numbers as kernel numbers
    os.environ["DT_TPU_PALLAS_STRICT"] = "1"
    os.environ.setdefault("DT_PALLAS_SMEM_RUNS", "32768")
from diamond_types_tpu.encoding.decode import load_oplog
from diamond_types_tpu.tpu.merge_kernel import (prepare_doc, pad_docs,
                                                _jitted_kernel, _pow2)
ol = load_oplog(open({data!r}, 'rb').read())
t0 = time.perf_counter()
doc = prepare_doc(ol)   # host origin extraction (once; device is the bench)
prep_ms = (time.perf_counter() - t0) * 1e3
chunk = {chunk}
parent, side, kp, ka, ks, vis, off, chars = pad_docs([doc] * chunk)
cap = _pow2(doc.total_len)
fn = _jitted_kernel(cap)
args = tuple(jnp.asarray(x)
             for x in (parent, side, kp, ka, ks, vis, off, chars))
texts, totals = fn(*args)
# parity check for EVERY replica in the chunk (also the warmup/compile;
# full-text transfer, untimed) — a batching/padding bug in any row fails
expected = ol.checkout_tip().snapshot()
texts_np, totals_np = np.asarray(texts), np.asarray(totals)
for i in range(chunk):
    got = texts_np[i][:int(totals_np[i])].astype(np.int32)\\
        .tobytes().decode('utf-32-le')
    assert got == expected, f'device merge diverged from host (replica {{i}})'
dt = bench_call(lambda: fn(*args), lambda r: r[1])
print("CHUNK", chunk)
print("HOST_PREP_MS", round(prep_ms, 2))
print("PER_CALL_MS", round(dt * 1e3, 2))
print("RESULT", chunk * len(ol) / dt)
"""


def bench_device_merge(corpus: str, chunk: int, timeout: int = 420,
                       pallas: bool = False):
    """Batched device merge-kernel checkout (Fugue-tree linearization):
    the device resolves concurrent order + assembles text for `chunk`
    replica docs of `corpus` per kernel call; parity-checked against the
    host engine inside the subprocess (every replica row). Timing forces
    completion via a host transfer (see bench_call) and so includes one
    tunnel round-trip. git-makefile.dt is the primary-metric corpus
    (high-fanout DAG — the case that stresses linearization). With
    pallas=True the materialize stage runs as the hand-written Pallas
    kernel (pallas_kernels.materialize_pallas)."""
    code = _MERGE_KERNEL_SNIPPET.format(
        repo=os.path.dirname(os.path.abspath(__file__)),
        data=os.path.join(BENCH_DATA, corpus), chunk=chunk,
        liveness=LIVENESS_S, pallas="1" if pallas else "")
    return _run_device_bench_retry(code, timeout)


_TRANSFORM_SNIPPET = _PRELUDE + """
import numpy as _np
from diamond_types_tpu.text.oplog import OpLog
from diamond_types_tpu.tpu.flush_fuse import FusedDocSession, fused_replay
from diamond_types_tpu.tpu.xform import (TailExtract, extract_tail,
                                         resolve_positions)

docs, branches, edits = {docs}, {branches}, {edits}
# Tail text sampled from the flagship corpus checkout when the
# benchmark_data tree exists; deterministic synthetic words otherwise.
# Transform cost is shape-driven (tail rows x branch fanout), not
# content-driven, so the synthetic numbers stay comparable.
words = None
try:
    from diamond_types_tpu.encoding.decode import load_oplog
    _txt = load_oplog(open({data!r}, 'rb').read())\\
        .checkout_tip().snapshot()
    words = [_txt[i:i + 7].replace("\\x00", " ") or "mk"
             for i in range(0, 7 * 8192, 7)]
    print("CORPUS 1")
except Exception:
    print("CORPUS 0")

def _word(k):
    return words[k % len(words)] if words else "w%05d " % (k % 99991)

sessions, oplogs = [], []
for di in range(docs):
    ol = OpLog()
    ol.doc_id = "doc-%d" % di
    ags = [ol.get_or_create_agent_id("a%d" % b) for b in range(branches)]
    ol.add_insert_at(ags[0], [], 0, "seed ")
    sess = FusedDocSession(ol, cap=8192, max_ins=16)
    base = list(ol.version)
    # `branches` concurrent linear runs forked at the session frontier:
    # every run is concurrent with every other run -- the conflict-zone
    # shape the device transform exists for.
    for b in range(branches):
        head, pos = base, 0
        for j in range(edits):
            w = _word((di * branches + b) * edits + j)
            lv = ol.add_insert_at(ags[b], head, pos, w)
            head, pos = [lv], pos + len(w)
    sessions.append(sess)
    oplogs.append(ol)
tail_lvs = sum(len(ol) for ol in oplogs) \\
    - sum(s.synced_to for s in sessions)

# Host control: the tracker-walk plan (plan_tail is a pure read, so the
# same tails can be planned repeatedly).
reps = 3
host_ts = []
for _ in range(reps):
    t0 = time.perf_counter()
    host_plans = [s.plan_tail() for s in sessions]
    host_ts.append(time.perf_counter() - t0)
host_dt = min(host_ts)

exts = [extract_tail(s) for s in sessions]
n_dev = sum(isinstance(e, TailExtract) for e in exts)
print("DEVICE_DOCS", n_dev)
assert n_dev == docs, "extract_tail fell back on %d docs" % (docs - n_dev)
plans = resolve_positions(exts)   # warmup/compile
assert all(p is not None for p in plans), "device transform fell back"
# Device timing is end to end: host origin extraction + the jitted
# order/visibility/position kernel (apples to apples with plan_tail).
dt = bench_call(lambda: resolve_positions([extract_tail(s)
                                           for s in sessions]),
                lambda ps: ps[0].pos, reps=reps)

# Parity: replay the device-planned tails through the fused kernel and
# compare every doc against the host oracle checkout.
oks, _ = fused_replay(sessions, plans)
assert all(oks), "poison fence tripped during parity replay"
for s, ol in zip(sessions, oplogs):
    assert s.text() == ol.checkout_tip().snapshot(), \\
        "device transform diverged (%s)" % ol.doc_id
print("PARITY_CHECKED 1")
print("HOST_PLAN_MS", round(host_dt * 1e3, 3))
print("DEVICE_PLAN_MS", round(dt * 1e3, 3))
print("TRANSFORM_SPEEDUP", round(host_dt / max(dt, 1e-9), 3))
print("RESULT", tail_lvs / dt)
"""


def bench_device_transform(corpus: str = "git-makefile.dt",
                           docs: int = 8, branches: int = 8,
                           edits: int = 24, timeout: int = 300):
    """Device-resident tail transform (tpu/xform.py): `docs` sessions
    each carrying a `branches`-way concurrent tail, merge positions
    resolved on device (Fugue linearization + split-run visibility)
    vs. the host tracker walk on identical tails. Parity-gated by
    replaying the device plans through the fused kernel and comparing
    every doc to the host checkout. Falls back to synthetic tail text
    when the corpus tree is absent (shape, not content, drives the
    transform's cost)."""
    code = _TRANSFORM_SNIPPET.format(
        repo=os.path.dirname(os.path.abspath(__file__)),
        data=os.path.join(BENCH_DATA, corpus),
        docs=docs, branches=branches, edits=edits, liveness=LIVENESS_S)
    return _run_device_bench_retry(code, timeout)


_ZONE_MERGE_SNIPPET = _PRELUDE + """
import numpy as _np
from diamond_types_tpu.encoding.decode import load_oplog
from diamond_types_tpu.listmerge.zone_np import prepare_zone
from diamond_types_tpu.tpu.zone_kernel import (pack_zone_tape,
                                               execute_zone_batch_jax,
                                               execute_zone_batch_sliced_jax,
                                               slice_tape_xs, BIG32)
ol = load_oplog(open({data!r}, 'rb').read())
t0 = time.perf_counter()
prep = prepare_zone(ol)        # host: plan compile + entry composition —
tape = pack_zone_tape(prep)    # NO merge engine anywhere (VERDICT r2 #2)
prep_ms = (time.perf_counter() - t0) * 1e3
chunk = {chunk}
# The tunneled v5e runtime kills ANY single program past a ~60 s
# device-time bound (TPU worker "kernel fault"; root-caused 2026-07-31:
# friendsforever batch 8 as one 7,649-step program dies, the same steps
# as eight 1,024-step dispatches survive). On tpu the scan therefore
# runs as sliced dispatches whose length shrinks with batch x W
# (auto_slice_steps), carry device-resident between them.
# DT_ZONE_SLICE overrides: a positive value sets the slice length on
# any backend, 0 forces the whole-tape scan even on tpu.
from diamond_types_tpu.tpu.zone_kernel import auto_slice_steps
_sl_env = os.environ.get('DT_ZONE_SLICE')
slice_steps = (auto_slice_steps(tape, chunk)
               if jax.default_backend() == 'tpu' else 0) \\
    if _sl_env is None else max(0, int(_sl_env))
# Both paths time execution with the tape already device-resident (the
# deployment shape: a doc's tape uploads once, merges repeat); per-call
# still includes one tunnel round-trip via bench_call's fetch.
if slice_steps:
    S, xs_slices = slice_tape_xs(tape, slice_steps)   # upload once
    n_sl = len(xs_slices)
    print("SLICE_STEPS", S)
    print("N_SLICES", n_sl)
    run = lambda: execute_zone_batch_sliced_jax(
        tape, prep.agent_k, prep.seq_k, chunk, xs_slices=xs_slices)
    # Calibrate before committing to the full scan: compile + one
    # timed slice-prefix pass, then extrapolate the full per-call
    # time. A corpus whose zone scan cannot fit the bench budget on
    # this chip (git-makefile: ~500 dispatches at W ~500k) reports
    # the MEASURED steady-state rate and the extrapolated bound
    # instead of burning the timeout (parity unchecked — the full
    # scan never ran; the CPU-backend CI parity covers the kernel).
    _r = execute_zone_batch_sliced_jax(      # compile (1 dispatch)
        tape, prep.agent_k, prep.seq_k, chunk, xs_slices=xs_slices[:1])
    _np.asarray(_r[0][:, :4])
    K = min(4, n_sl)
    t0 = time.perf_counter()
    _r = execute_zone_batch_sliced_jax(
        tape, prep.agent_k, prep.seq_k, chunk, xs_slices=xs_slices[:K])
    _np.asarray(_r[0][:, :4])
    t_k = time.perf_counter() - t0
    est_call_s = t_k / K * n_sl
    print("EST_PER_CALL_S", round(est_call_s, 1))
    # 4 full-call equivalents: warmup + 2 reps, plus the calibration
    # pass already spent (1+K dispatches ~= one call when n_sl is
    # small) — a corpus just under a 3x threshold would blow the
    # subprocess timeout and lose the measurement entirely
    if est_call_s * 4 > {zone_budget}:
        print("BOUNDED 1")
        print("PARITY_CHECKED 0")
        print("STEP_REPLICAS_PER_S",
              round(chunk * S * K / t_k))
        print("CHUNK", chunk)
        print("HOST_PREP_MS", round(prep_ms, 2))
        print("TAPE_STEPS", tape.total_steps)
        print("PER_CALL_MS", round(est_call_s * 1e3, 2))
        # honest extrapolation from the measured steady-state rate —
        # the BOUNDED/PARITY_CHECKED keys mark it as a bound, not a
        # completed, parity-checked merge
        print("RESULT", chunk * len(ol) / est_call_s)
        raise SystemExit(0)
else:
    from diamond_types_tpu.tpu.zone_kernel import _pad_tape_xs
    xs_res = {{k: jnp.asarray(v) for k, v in _pad_tape_xs(tape).items()}}
    run = lambda: execute_zone_batch_jax(
        tape, prep.agent_k, prep.seq_k, chunk, xs=xs_res)
# warmup/compile + parity for EVERY replica (full transfer, untimed)
rank, ever = run()
rank, ever = _np.asarray(rank), _np.asarray(ever)
expected = ol.checkout_tip().snapshot()
for i in range(chunk):
    order = _np.argsort(rank[i], kind='stable')
    order = order[:int((rank[i] < int(BIG32)).sum())]
    vis = ever[i][order] == 0
    got = prep.pool[order[vis]].astype(_np.int32).tobytes()\\
        .decode('utf-32-le')
    assert got == expected, 'zone kernel diverged (replica %d)' % i
print("PARITY_CHECKED 1")
dt = bench_call(run, lambda r: r[0][:, :4],
                reps=2 if slice_steps else 5)
print("CHUNK", chunk)
print("HOST_PREP_MS", round(prep_ms, 2))
print("TAPE_STEPS", tape.total_steps)
print("PER_CALL_MS", round(dt * 1e3, 2))
print("RESULT", chunk * len(ol) / dt)
"""


def bench_device_zone(corpus: str, chunk: int, timeout: int = 600):
    """Self-sufficient device merge: origin extraction runs ON device
    (zone kernel — one lax.scan over the plan tape); the host only
    compiles the plan and composes entries. This is the path VERDICT r2
    missing #1 asked for: no M1/native transform anywhere. Parity-checked
    per replica inside the subprocess; timing forces completion via a
    small host transfer (includes one tunnel round-trip)."""
    code = _ZONE_MERGE_SNIPPET.format(
        repo=os.path.dirname(os.path.abspath(__file__)),
        data=os.path.join(BENCH_DATA, corpus), chunk=chunk,
        liveness=LIVENESS_S, zone_budget=max(60, timeout - 180))
    return _run_device_bench_retry(code, timeout)


_SESSION_SNIPPET = _PRELUDE + """
import numpy as _np
from diamond_types_tpu.encoding.decode import load_oplog
from diamond_types_tpu.tpu.zone_session import DeviceZoneSession
ol = load_oplog(open({data!r}, 'rb').read())
agents = list(range(len(ol.cg.agent_assignment.agent_names)))
t0 = time.perf_counter()
sess = DeviceZoneSession(ol)
sess.touch()
build_ms = (time.perf_counter() - t0) * 1e3
# realtime continuation: the corpus's agents keep typing from their own
# heads (merge-per-edit; reference hot path src/list/merge.rs:63-96)
heads = {{a: [sess._agent_last_lv(a)] for a in agents[:2]}}
lens = {{a: len(ol.checkout(heads[a]).snapshot()) for a in agents[:2]}}
import random as _rnd
rng = _rnd.Random(7)
def one_edit(i):
    # length tracked incrementally: the TIMED region must contain only
    # session work, not per-edit host checkouts
    a = agents[i % 2]
    pos = rng.randrange(max(lens[a], 1))
    heads[a] = [ol.add_insert_at(a, heads[a], pos, 'q')]
    lens[a] += 1
# warmup (compile the micro-tape sizes)
one_edit(0); sess.sync(); sess.touch()
one_edit(1); sess.sync(); sess.touch()
# timed: per-merge latency, single edit per sync
ts = []
for i in range(8):
    one_edit(i)
    t0 = time.perf_counter()
    sess.sync(); sess.touch()
    ts.append(time.perf_counter() - t0)
per_merge_ms = min(ts) * 1e3
# batched edits per sync (amortizes the tunnel round trip): one UNTIMED
# batch first so the 32-edit tape size is compiled before the clock runs
for i in range(32):
    one_edit(i)
sess.sync(); sess.touch()
t0 = time.perf_counter()
for i in range(32):
    one_edit(i)
sess.sync(); sess.touch()
batch32_ms = (time.perf_counter() - t0) * 1e3
assert sess.text() == ol.checkout_tip().snapshot(), \\
    'session diverged from host engine'
print("BUILD_MS", round(build_ms, 2))
print("RESYNCS", sess.resyncs)
print("BATCH32_MS", round(batch32_ms, 2))
print("RESULT", round(per_merge_ms, 3))
"""


def bench_device_session(corpus: str = "friendsforever.dt",
                         timeout: int = 600):
    """Device-resident incremental session (VERDICT r2 #4): the document
    state lives on the device across merges; each sync ships only the
    composed micro-tape of the new ops. Reports per-merge latency
    (includes one tunnel round trip — the touch() transfer) and the
    32-edit batched variant; parity-checked against the host engine."""
    code = _SESSION_SNIPPET.format(
        repo=os.path.dirname(os.path.abspath(__file__)),
        data=os.path.join(BENCH_DATA, corpus), liveness=LIVENESS_S)
    return _run_device_bench_retry(code, timeout)


_MERGE_SWEEP_SNIPPET = _PRELUDE + """
from diamond_types_tpu.encoding.decode import load_oplog
from diamond_types_tpu.tpu.merge_kernel import (prepare_doc, pad_docs,
                                                _jitted_kernel, _pow2)
ol = load_oplog(open({data!r}, 'rb').read())
doc = prepare_doc(ol)
cap = _pow2(doc.total_len)
expected = ol.checkout_tip().snapshot()
n_ops = len(ol)
# Upload ONE doc's padded arrays, tile to each chunk size ON DEVICE (a
# real many-doc deployment holds per-doc arrays device-resident — task:
# measure whether the kernel amortizes over batch, not PCIe/tunnel
# upload). jnp.tile is a materialized broadcast: every batch row is
# really computed by the vmapped kernel (no cross-row CSE in XLA).
parent, side, kp, ka, ks, vis, off, chars = pad_docs([doc])
base = tuple(jnp.asarray(x[0])
             for x in (parent, side, kp, ka, ks, vis, off, chars))
curve = {{}}
best = None
t_sweep0 = time.perf_counter()
last_chunk_wall = 0.0
last_chunk = 0          # last SUCCESSFUL chunk (predictor anchor)
last_dt = 0.0           # its measured per-call seconds
for chunk in {chunks}:
    # Window-budget guard: on the tunneled runtime the server-side AOT
    # compile of a big-chunk program alone can exceed the whole bench
    # budget (chunk 256 at cap 2^20 blew two 1500 s windows; the jax
    # persistent cache does not apply to the remote-compile path), and
    # a timeout strands the bench as a forever-retried partial.
    # Measured walls (2026-07-31, banked curve wall_s): chunk 8 = 6.3 s,
    # chunk 64 = 78.2 s — compiles are cheap; per-call RUN time grows
    # ~2x the chunk ratio (HBM-resident past ~8 docs: 1.09 s -> 17.8 s
    # for 8x docs, predictor 17.5 s). Two guards, reasons banked in the
    # curve:
    #  * kill bound: the tunneled runtime kills any single program past
    #    ~60 s of device time, so a chunk whose PREDICTED per-call
    #    exceeds 55 s can never complete here (chunk 256 ~= 142 s burned
    #    three 1500 s windows exactly this way);
    #  * window budget: remaining budget must cover ~6 predicted calls
    #    (warmup + validation fetch + 3 reps is ~5 call-scale
    #    operations, plus compile margin). The 60 s reserve covers the
    #    subprocess startup that predates t_sweep0's clock. The wall
    #    fallback also fires when NO chunk has succeeded yet (an
    #    errored chunk still updates last_chunk_wall) so a first-chunk
    #    failure cannot leave the larger chunks unguarded.
    _remaining = {sweep_budget} - 60 - (time.perf_counter() - t_sweep0)
    _pred_call_s = (last_dt * 2.0 * (chunk / last_chunk)
                    if last_chunk else 0.0)
    if last_chunk and _pred_call_s > 55:
        curve[str(chunk)] = {{"skipped": "kill bound: predicted "
                             "%.0f s/call exceeds the runtime's ~60 s "
                             "per-program limit" % _pred_call_s}}
        print("JSONDATA", json.dumps({{"sweep": curve}}), flush=True)
        continue
    # 120 s floor: early chunks finish in single-digit seconds, so the
    # two scaled terms can both be tiny right when the NEXT chunk's
    # remote AOT compile is about to cost minutes — a near-exhausted
    # window would pass the scaled guard and blow the whole budget on
    # one doomed compile.
    if (last_chunk or last_chunk_wall) and \\
            _remaining < max(6 * _pred_call_s, 2.2 * last_chunk_wall, 120):
        curve[str(chunk)] = {{"skipped": "window budget: larger-chunk "
                             "compile+run exceeds the remaining bench "
                             "budget on this runtime"}}
        print("JSONDATA", json.dumps({{"sweep": curve}}), flush=True)
        continue
    t_chunk0 = time.perf_counter()
    try:
        args = tuple(jnp.tile(x[None], (chunk,) + (1,) * x.ndim)
                     for x in base)
        fn = _jitted_kernel(cap)
        texts, totals = fn(*args)
        # Validate every replica at small chunks; at large chunks the
        # vmapped kernel computes identical rows, and fetching the full
        # [chunk, cap] text batch over the tunnel (0.5 GB at 1024) costs
        # more than the bench itself — sample rows and fetch ONLY those.
        rows = list(range(chunk)) if chunk <= 8 else \
            sorted({{0, 1, chunk // 2, chunk - 1}})
        sel = jnp.asarray(rows)
        texts_np = np.asarray(texts[sel])
        totals_np = np.asarray(totals[sel])
        for k, i in enumerate(rows):
            got = texts_np[k][:int(totals_np[k])].astype(np.int32)\\
                .tobytes().decode('utf-32-le')
            assert got == expected, \\
                'device merge diverged from host (replica %d)' % i
        dt = bench_call(lambda: fn(*args), lambda r: r[1], reps=3)
        ops_s = chunk * n_ops / dt
        curve[str(chunk)] = {{"per_call_ms": round(dt * 1e3, 2),
                              "ops_per_sec": round(ops_s),
                              "validated_rows": len(rows)}}
        if best is None or ops_s > best[1]:
            best = (chunk, ops_s, dt)
        last_chunk, last_dt = chunk, dt
    except Exception as e:
        curve[str(chunk)] = {{"error": str(e)[:120]}}
    last_chunk_wall = time.perf_counter() - t_chunk0
    # wall includes this chunk's remote compile — recorded for guard
    # calibration across runtimes
    curve.setdefault(str(chunk), {{}})["wall_s"] = round(last_chunk_wall, 1)
    # cumulative progress: a timeout on a later chunk must not discard
    # the completed points (bench.py parses the LAST of each line kind;
    # flush so a timeout-kill can't drop a buffered error-only curve)
    print("JSONDATA", json.dumps({{"sweep": curve}}), flush=True)
    if best is not None:
        print("BEST_CHUNK", best[0])
        print("PER_CALL_MS", round(best[2] * 1e3, 2))
        print("RESULT", best[1], flush=True)
if best is None:
    raise SystemExit("no sweep point succeeded: " + json.dumps(curve))
"""


def bench_device_merge_sweep(corpus: str = "node_nodecc.dt",
                             chunks=(8, 64, 256, 1024), timeout: int = 1500):
    """Batch-amortization sweep (BASELINE config 4 at its written scale):
    device merge of `corpus` replicas at several batch sizes, reporting
    the ops/sec curve. Answers empirically whether batching amortizes the
    per-call latency (round-2 claimed it doesn't past ~8, unmeasured)."""
    env_chunks = os.environ.get("DT_BENCH_SWEEP_CHUNKS")
    if env_chunks:
        chunks = tuple(int(c) for c in env_chunks.split(","))
    code = _MERGE_SWEEP_SNIPPET.format(
        repo=os.path.dirname(os.path.abspath(__file__)),
        data=os.path.join(BENCH_DATA, corpus), chunks=tuple(chunks),
        liveness=LIVENESS_S, sweep_budget=timeout)
    return _run_device_bench_retry(code, timeout)


_FANIN_SNIPPET = _PRELUDE + """
from diamond_types_tpu.causalgraph.graph import Graph
from diamond_types_tpu.tpu import graph_kernels as gk
n_rep, run_len = {n_rep}, 8
g = Graph()
for i in range(n_rep):
    g.push([], i * run_len, (i + 1) * run_len)
tip = n_rep * run_len
g.push([(i + 1) * run_len - 1 for i in range(n_rep)], tip, tip + 4)
packed = gk.pack_graph(g)
n = packed["n"]
reach0 = jnp.asarray(np.where(np.arange(n) == n - 1, tip + 3,
                              -1).astype(np.int32))
fn = jax.jit(lambda r0: gk.reach_fixed_point(packed, r0))
reach = np.asarray(fn(reach0))  # warmup/compile + correctness fetch
assert (reach[:n_rep] == (np.arange(n_rep) + 1) * run_len - 1).all()
dt = bench_call(lambda: fn(reach0), lambda r: r)
print("RESULT", dt * 1e3)
"""


def bench_fanin_10k(n_rep: int = 10_000, timeout: int = 240):
    """BASELINE config 5: 10k-replica fan-in causal-graph propagation
    (CSR scatter-max fixed point) on the chip; reports wall-clock ms per
    full propagation. The sharded (8-device) variant of the same kernel
    is validated by tests/test_tpu_kernels.py::test_sharded_10k_replica_
    fanin and the driver's multichip dryrun."""
    code = _FANIN_SNIPPET.format(
        repo=os.path.dirname(os.path.abspath(__file__)), n_rep=n_rep,
        liveness=LIVENESS_S)
    return _run_device_bench_retry(code, timeout)


def bench_linear_replay(trace: str = "automerge-paper.json.gz",
                        full: bool = True):
    """BASELINE config 1: linear single-branch trace replay.

    apply = per-op append path through the NATIVE local-ingest session
    (the editor-facing hot path, VERDICT r4 #3; reference:
    local/apply_direct over the native push path, src/list/oplog.rs:
    203-296); apply_python = the same per-op calls through the pure-
    Python path (the oracle — byte-parity-gated against the native
    session); apply_grouped = bulk columnar ingest (reference:
    local/apply_grouped_rle — the reference also pre-groups outside the
    timed apply). With full=False only the grouped ingest + checkout are
    reported (the secondary traces)."""
    from diamond_types_tpu.text.trace import (load_trace, replay_into_oplog,
                                              replay_into_oplog_grouped,
                                              replay_into_oplog_native)
    data = load_trace(os.path.join(BENCH_DATA, trace))
    data.patch_columns()  # built at parse time, outside the timed apply
    t_grouped, ol = min(
        (_timed(lambda: replay_into_oplog_grouped(data)) for _ in range(3)),
        key=lambda p: p[0])
    # warm + best-of-3, same methodology as bench_merge (r3 fix) and the
    # reference's criterion b.iter loops (every iteration after the first
    # is warm): the first checkout pays the native context's one-time
    # bulk column load, which is not replay work
    b = ol.checkout_tip()
    t_checkout = min(_timed(ol.checkout_tip)[0] for _ in range(3))
    n = data.num_ops()
    out = {
        "apply_grouped_ops_per_sec": round(n / t_grouped),
        "checkout_ops_per_sec": round(n / t_checkout),
        "parity": b.snapshot() == data.end_content,
    }
    if full:
        from diamond_types_tpu.native.ingest import native_ingest_available
        t0 = time.perf_counter()
        ol2 = replay_into_oplog(data)
        out["apply_python_ops_per_sec"] = \
            round(n / (time.perf_counter() - t0))
        out["parity"] = out["parity"] and \
            ol2.checkout_tip().snapshot() == data.end_content
        if native_ingest_available():
            t_native, ol3 = min(
                (_timed(lambda: replay_into_oplog_native(data))
                 for _ in range(3)), key=lambda p: p[0])
            out["apply_ops_per_sec"] = round(n / t_native)
            # the native session must be BYTE-identical to the Python
            # per-op path, not merely convergent
            from diamond_types_tpu.encoding.encode import encode_oplog
            out["parity"] = out["parity"] and \
                ol3.checkout_tip().snapshot() == data.end_content and \
                encode_oplog(ol3) == encode_oplog(ol2)
        else:
            # never report the PySession fallback under the native key —
            # that would record a false native-path number
            out["apply_ops_per_sec_error"] = \
                "native ingest extension unavailable"
    return out


def bench_codec(name: str):
    """Binary load + save timings for a shipped corpus (reference:
    crates/bench/src/main.rs complex/decode + complex/encode)."""
    from diamond_types_tpu.encoding.decode import load_oplog
    from diamond_types_tpu.encoding.encode import ENCODE_FULL, encode_oplog
    with open(os.path.join(BENCH_DATA, name), "rb") as f:
        data = f.read()
    t_dec, ol = min((_timed(lambda: load_oplog(data)) for _ in range(3)),
                    key=lambda p: p[0])
    t_enc = min(_timed(lambda: encode_oplog(ol, ENCODE_FULL))[0]
                for _ in range(3))
    n = len(ol)
    return {"decode_ops_per_sec": round(n / t_dec),
            "encode_ops_per_sec": round(n / t_enc)}


def bench_serve_sched(shards: int = 4, docs: int = 8, txns: int = 10,
                      engine: str = "device", timeout: int = 300,
                      fused: bool = True, steady_rounds: int = 8,
                      mesh_window: bool = False,
                      telemetry: bool = True,
                      journey: bool = True,
                      mode: str = "trace",
                      flush_docs: int = None,
                      max_sessions: int = None,
                      device_plan: bool = False,
                      pallas: bool = False,
                      steer: bool = True,
                      device_stage: bool = True):
    """Sharded multi-document merge scheduler (serve/): replays the
    synthetic trace across `docs` docs on `shards` CPU-simulated shards
    through the router + shape-bucketed admission queue + per-shard
    session banks, byte-parity-gated per doc against the single-engine
    host checkout. Runs as a subprocess: the CLI pins JAX_PLATFORMS=cpu
    itself, so a wedged accelerator tunnel can never stall the host
    phase, and the jit caches it warms die with the child.

    `fused` toggles the vmapped bucket flush (--no-fused = the serial
    per-doc zone-session path); `steady_rounds` lockstep rounds against
    resident sessions are where fused occupancy is actually measured —
    the continuous feed races the flush workers (see serve/driver.py).
    `mesh_window` routes flushes through the mesh flush-window
    coordinator: one shard_map dispatch per window instead of one
    device call per shard (the report's device_calls_per_window is the
    A/B signal). `device_plan` resolves concurrent merge positions on
    device (tpu/xform.py) instead of the host tracker walk; `pallas`
    adds the Pallas step-kernel rung at the top of the flush ladder.
    The transform A/B needs `mode="concurrent"` (a linear trace has no
    conflict zone — the device rung falls back per design) and
    `max_sessions >= docs` (residency thrash rebuilds sessions
    caught-up, leaving the transform nothing to plan)."""
    cmd = [sys.executable, "-m", "diamond_types_tpu.tools.cli",
           "serve-bench", "--shards", str(shards), "--docs", str(docs),
           "--txns", str(txns), "--engine", engine,
           "--fused" if fused else "--no-fused",
           "--steady-rounds", str(steady_rounds), "--json",
           "--mode", mode]
    if flush_docs is not None:
        cmd += ["--flush-docs", str(flush_docs)]
    if max_sessions is not None:
        cmd += ["--max-sessions", str(max_sessions)]
    if device_plan:
        cmd.append("--device-plan")
    if pallas:
        cmd.append("--pallas")
    if mesh_window:
        cmd.append("--mesh-window")
    if not steer:
        cmd.append("--no-steer")
    if not device_stage:
        cmd.append("--no-device-stage")
    if fused:
        cmd.append("--warmup")
    if not telemetry:
        cmd.append("--no-telemetry")
    if not journey:
        cmd.append("--no-journey")
    p = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=timeout,
                       cwd=os.path.dirname(os.path.abspath(__file__)))
    if p.returncode != 0:
        raise RuntimeError(f"serve-bench rc={p.returncode}: "
                           f"{(p.stderr or p.stdout)[-200:]}")
    return json.loads(p.stdout.strip().splitlines()[-1])


def _timed(fn):
    t0 = time.perf_counter()
    out = fn()
    return time.perf_counter() - t0, out


def _short_err(r: dict) -> str:
    """Collapse a failure dict to one short string for the summary line;
    the full dict (tails etc.) lives in the stderr/file report."""
    s = r.get("why", "unknown failure")
    return s[:120]


def _flush_partial(full: dict, out: dict) -> None:
    """Persist per-bench progress (VERDICT r4 #2: a re-wedge between
    benches must not erase an earlier catch). Atomic rename so a reader
    never sees a torn file; silent no-op without DT_DEVICE_PARTIAL_PATH."""
    path = os.environ.get("DT_DEVICE_PARTIAL_PATH")
    if not path:
        return
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"summary": out, "full": full,
                       "flushed_at": time.time()}, f, indent=1, default=str)
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        # best-effort: a serialization quirk (default=str covers values,
        # not dict keys) must never abort the device phase it documents
        pass


# Every device bench _run_device_phase runs, in its summary-key naming
# (error keys are exactly f"{name}_error"). device_watcher.py imports
# this to classify banked keys — keep it in sync with the guarded()
# calls below. Ordering: longest prefix first (pallas before its base)
# so prefix classification is unambiguous.
DEVICE_BENCHES = (
    "tpu_merge_git_makefile_pallas",
    "tpu_merge_git_makefile",
    "tpu_merge_friendsforever",
    "tpu_merge_node_nodecc_sweep",
    "tpu_zone_git_makefile",
    "tpu_zone_friendsforever",
    "tpu_session_friendsforever",
    "tpu_transform_git_makefile",
    "tpu_batched_replay",
    "fanin_10k",
)


DEVICE_LOCK = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".device_lock")


def _pid_alive(pid: int) -> bool:
    """Signal-0 liveness probe (shared with device_watcher.py's
    single-instance guard)."""
    try:
        os.kill(pid, 0)
        return True
    except PermissionError:
        return True           # exists, owned by another user
    except (OSError, ValueError):
        return False


def _pid_is(pid: int, needle: bytes) -> bool:
    """True if `pid` is alive AND its cmdline contains `needle` — the
    shared pid-reuse guard (a dead pid recycled by an unrelated process
    must not read as a live holder). Unreadable /proc (another uid) is
    conservatively treated as a match. Used by bench_is_active and
    device_watcher.py's single-instance guard."""
    if not pid or not _pid_alive(pid):
        return False
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return needle in f.read()
    except OSError:
        return True


def _acquire_device_lock(timeout_s: int = 10800) -> None:
    """Mutual exclusion between concurrent device phases (bench.py main
    vs device_watcher.py): two processes driving the tunneled chip at
    once would bill each other's contention as kernel time. Blocks while
    a LIVE holder exists, up to timeout_s — after that we proceed anyway
    (the round-end bench run must never be starved by a hung watcher);
    a dead holder's lock is stolen immediately. The default exceeds the
    worst-case phase duration: per-bench subprocess timeouts sum to
    ~84 min (the 1500 s sweep included), and a phase where several
    non-consecutive benches earn a wedge retry can roughly double that
    before the 2-strike breaker trips — stealing from a phase that is
    merely slow would cause the exact contamination the lock prevents,
    so the deadline errs long (a genuinely hung holder is a DEAD pid
    and is stolen immediately anyway; the deadline only matters for a
    live-but-stuck holder, which per-bench subprocess timeouts make
    near-impossible)."""
    deadline = time.time() + timeout_s
    while True:
        try:
            fd = os.open(DEVICE_LOCK, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, str(os.getpid()).encode())
            os.close(fd)
            return
        except FileExistsError:
            try:
                holder = int(open(DEVICE_LOCK).read().strip() or "0")
            except (OSError, ValueError):
                holder = 0
            alive = False
            if holder and holder != os.getpid():
                alive = _pid_alive(holder)
            elif holder == 0:
                # empty/garbled file: may be a holder between its
                # O_EXCL create and pid write — only treat as dead
                # once the file is old enough that that window is over
                try:
                    alive = (time.time()
                             - os.path.getmtime(DEVICE_LOCK)) < 60
                except OSError:
                    alive = False      # vanished: retry the create
            if not alive or time.time() > deadline:
                # steal via rename-aside: only ONE of several waiters
                # can win the rename of a given lock inode, so a
                # concurrent stealer can't blind-remove the winner's
                # freshly re-created lock
                steal = f"{DEVICE_LOCK}.steal.{os.getpid()}"
                try:
                    os.rename(DEVICE_LOCK, steal)
                    # re-validate post-rename: if the renamed file no
                    # longer holds the pid we judged dead, we raced a
                    # faster stealer's re-created LIVE lock — restore it
                    try:
                        now = int(open(steal).read().strip() or "0")
                    except (OSError, ValueError):
                        now = holder
                    if now != holder and now and _pid_alive(now):
                        os.rename(steal, DEVICE_LOCK)
                    else:
                        os.remove(steal)
                except OSError:
                    pass          # another waiter won; re-evaluate
                continue
            time.sleep(10)


def _release_device_lock() -> None:
    try:
        # release only our own lock: after a deadline steal the old
        # holder's release must not delete the stealer's lock
        if int(open(DEVICE_LOCK).read().strip() or "0") == os.getpid():
            os.remove(DEVICE_LOCK)
    except (OSError, ValueError):
        pass


def _run_device_phase(full: dict, probe: dict = None,
                      skip: frozenset = frozenset()) -> dict:
    """All device benches, probe-gated, wedge-bounded. Returns a dict of
    summary-line entries (scalars + short error strings). A caller that
    just probed (device_watcher.py) passes its result in to skip the
    second probe round-trip; `skip` names benches already banked this
    round, so a short recovery window is spent on the missing ones (the
    skip entries come back as short `_error` strings, which the
    watcher's bank merge ignores in favor of the banked ok data)."""
    t0 = time.time()
    _acquire_device_lock()
    try:
        if probe is not None and time.time() - t0 > 120:
            probe = None   # stale after a long lock wait: re-probe
        out = _run_device_phase_locked(full, probe, skip)
    finally:
        _release_device_lock()
    return _substitute_banked(out, full)


def _substitute_banked(out: dict, full: dict) -> dict:
    """Round-end durability for banked catches (VERDICT r4 #2): a bench
    that errors NOW but has complete ok data banked by device_watcher.py
    from an earlier live window reports the banked numbers instead of
    the error — a late tunnel wedge must not erase on-chip evidence from
    the round's official record. Substituted benches are listed under
    `device_bank_used` with the bank's capture time."""
    bank_path = os.environ.get("DT_DEVICE_BANK") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "DEVICE_BANK.json")
    try:
        with open(bank_path) as f:
            bank_doc = json.load(f)
        bank = bank_doc.get("summary", {})
    except (OSError, ValueError):
        return out
    # Staleness gate: DEVICE_BANK.json is committed, so a bench run in a
    # LATER round (or on a copied checkout) would otherwise resurrect a
    # previous round's numbers as its own. Rounds last ~12 h; catches
    # older than 18 h are history, not this round's evidence.
    banked_at = max((r.get("at", 0) for r in bank_doc.get("runs", [])),
                    default=0)
    if not banked_at or time.time() - banked_at > 18 * 3600:
        return out
    at_iso = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(banked_at))
    try:
        import device_watcher as dw
    except ImportError:
        return out
    banked_per, _glob = dw._group(bank)
    used = {}
    for b in DEVICE_BENCHES:
        cur = {k: v for k, v in out.items() if dw._bench_of(k) == b}
        # preference: complete ok > partial ok (marker kept) > error
        banked = banked_per[b]
        take = (dw._bench_full_ok(banked) and not dw._bench_full_ok(cur)) \
            or (dw._bench_ok(banked) and not dw._bench_ok(cur))
        if take:
            for k in cur:
                del out[k]
            out.update(banked)
            used[b] = f"banked {at_iso}"
    if used:
        out["device_bank_used"] = {"at": at_iso, "benches": sorted(used)}
        full["device_bank_used"] = used
    return out


def _run_device_phase_locked(full: dict, probe: dict,
                             skip: frozenset = frozenset()) -> dict:
    out = {}
    if probe is None:
        probe = device_probe()
    full["device_probe"] = probe
    _flush_partial(full, out)
    if not probe.get("ok"):
        attempts = "twice" if probe.get("retried") else "once (no retry: " \
            "failure signature is not a wedge)"
        msg = f"device probe failed {attempts}: " + _short_err(probe)
        for k in DEVICE_BENCHES:
            out[f"{k}_error"] = msg
        _flush_partial(full, out)
        return out
    out["device_platform"] = probe.get("platform", "?")
    if probe.get("rtt_ms") is not None:
        # every device bench's per-call latency includes this floor
        out["tunnel_rtt_ms"] = round(float(probe["rtt_ms"]), 2)

    consecutive_wedges = 0

    def guarded(name, fn):
        nonlocal consecutive_wedges
        # entry flush picks up the PREVIOUS bench's summary entries (the
        # caller adds them to `out` after guarded returns); the phase-end
        # flush covers the last bench
        _flush_partial(full, out)
        if name in skip:
            full[name] = {"ok": False,
                          "why": "skipped: already banked this round"}
            return full[name]
        if consecutive_wedges >= 2:
            full[name] = {"ok": False, "why": "skipped: tunnel wedged "
                          "(2 consecutive device benches failed)"}
            return full[name]
        r = fn()
        full[name] = r
        # Partial-ok results (cumulative-progress bench timed out or
        # crashed mid-run) keep their data but must neither reset the
        # wedge breaker (a mid-run timeout IS wedge evidence) nor bank
        # as a completed run — the `_partial` summary key keeps the
        # bench on the watcher's retry list for every bench kind.
        partial = r.get("partial_timeout") or r.get("partial_crash")
        if r.get("ok") and partial:
            out[f"{name}_partial"] = str(partial)[:120]
        if not r.get("ok") and _is_wedge(r):
            consecutive_wedges += 1
        elif r.get("ok") and r.get("partial_timeout"):
            consecutive_wedges += 1     # device stopped answering mid-run
        elif r.get("ok") and not partial:
            consecutive_wedges = 0
        # ok+partial_crash: leave the count unchanged — the worker
        # crash-restarts (observed 2026-07-31) and may serve the next
        # bench, but it is not evidence the tunnel is healthy either
        return r

    # Flagship first: the primary-metric corpus on the merge kernel.
    r = guarded("tpu_merge_git_makefile",
                lambda: bench_device_merge("git-makefile.dt", 8))
    if r.get("ok"):
        out["tpu_merge_git_makefile_ops_per_sec"] = round(r["value"])
        for src, dst in (("per_call_ms", "tpu_merge_git_makefile_per_call_ms"),
                         ("host_prep_ms", "tpu_merge_git_makefile_prep_ms")):
            if r.get(src) is not None:
                out[dst] = r[src]
        out["tpu_merge_git_makefile_docs_per_call"] = int(r.get("chunk", 8))
    else:
        out["tpu_merge_git_makefile_error"] = _short_err(r)

    # Batch-amortization sweep (BASELINE config 4 at its written scale).
    r = guarded("tpu_merge_node_nodecc_sweep",
                lambda: bench_device_merge_sweep())
    if r.get("ok"):
        out["tpu_merge_node_nodecc_best_ops_per_sec"] = round(r["value"])
        out["tpu_merge_node_nodecc_best_chunk"] = int(r.get("best_chunk", 0))
        sweep = r.get("sweep", {})
        out["tpu_merge_batch_sweep"] = {
            k: v.get("ops_per_sec",
                     v.get("error", v.get("skipped", "?")))
            for k, v in sweep.items()}
    else:
        out["tpu_merge_node_nodecc_sweep_error"] = _short_err(r)

    r = guarded("tpu_merge_friendsforever",
                lambda: bench_device_merge("friendsforever.dt", 8))
    if r.get("ok"):
        out["tpu_merge_friendsforever_ops_per_sec"] = round(r["value"])
        out["tpu_merge_friendsforever_per_call_ms"] = r.get("per_call_ms")
    else:
        out["tpu_merge_friendsforever_error"] = _short_err(r)

    r = guarded("tpu_session_friendsforever",
                lambda: bench_device_session())
    if r.get("ok"):
        out["tpu_session_per_merge_ms"] = round(r["value"], 3)
        if r.get("batch32_ms") is not None:
            out["tpu_session_batch32_ms"] = r.get("batch32_ms")
        if r.get("build_ms") is not None:
            out["tpu_session_build_ms"] = r.get("build_ms")
    else:
        out["tpu_session_friendsforever_error"] = _short_err(r)

    # Device-resident tail transform vs. the host tracker walk on the
    # same concurrent tails (the serve ladder's planning stage; corpus
    # text when present, synthetic tails otherwise — see the snippet).
    r = guarded("tpu_transform_git_makefile",
                lambda: bench_device_transform())
    if r.get("ok"):
        out["tpu_transform_git_makefile_ops_per_sec"] = round(r["value"])
        if r.get("transform_speedup") is not None:
            out["tpu_transform_speedup"] = r["transform_speedup"]
        if r.get("device_plan_ms") is not None:
            out["tpu_transform_device_plan_ms"] = r["device_plan_ms"]
        if r.get("host_plan_ms") is not None:
            out["tpu_transform_host_plan_ms"] = r["host_plan_ms"]
    else:
        out["tpu_transform_git_makefile_error"] = _short_err(r)

    r = guarded("tpu_batched_replay", bench_tpu_batch)
    if r.get("ok"):
        out["tpu_batched_replay_ops_per_sec"] = round(r["value"])
    else:
        out["tpu_batched_replay_error"] = _short_err(r)

    r = guarded("fanin_10k", bench_fanin_10k)
    if r.get("ok"):
        out["fanin_10k_propagation_ms"] = round(r["value"], 3)
    else:
        out["fanin_10k_error"] = _short_err(r)

    # Crash-risk benches LAST (observed 2026-07-31: the zone kernel and
    # the pallas merge each took down the TPU worker — "kernel fault" —
    # and the wedged tunnel then starved every bench scheduled after
    # them for the rest of the live window). Running them after the safe
    # set means a crash can only cost benches that already ran.
    #
    # Self-sufficient device merge (origin extraction on device): the
    # round-3 flagship. git-makefile is the primary corpus; friendsforever
    # exercises the deep-entry shape.
    for corpus, chunk in (("git-makefile.dt", 8), ("friendsforever.dt", 8)):
        kb = "tpu_zone_" + corpus.split(".")[0].replace("-", "_")
        r = guarded(kb, lambda c=corpus, k=chunk: bench_device_zone(c, k))
        if r.get("ok"):
            # A BOUNDED result is a calibration, not a completed merge:
            # the full scan would blow the bench budget on this chip, so
            # the snippet reports the measured steady-state rate and the
            # extrapolated per-call bound under distinct keys (parity
            # unchecked on device; CPU-backend CI covers the kernel).
            if r.get("bounded"):
                out[f"{kb}_bounded_ops_per_sec"] = round(r["value"])
                out[f"{kb}_bound_per_call_s"] = round(
                    float(r.get("est_per_call_s", 0)), 1)
                if r.get("step_replicas_per_s") is not None:
                    out[f"{kb}_step_replicas_per_s"] = round(
                        r["step_replicas_per_s"])
            else:
                out[f"{kb}_ops_per_sec"] = round(r["value"])
            if r.get("per_call_ms") is not None and not r.get("bounded"):
                out[f"{kb}_per_call_ms"] = r.get("per_call_ms")
            if r.get("host_prep_ms") is not None:
                out[f"{kb}_prep_ms"] = r.get("host_prep_ms")
        else:
            out[f"{kb}_error"] = _short_err(r)

    # Pallas materialize stage on the flagship corpus (SURVEY §7 step 6).
    r = guarded("tpu_merge_git_makefile_pallas",
                lambda: bench_device_merge("git-makefile.dt", 8,
                                           pallas=True))
    if r.get("ok"):
        out["tpu_merge_git_makefile_pallas_ops_per_sec"] = round(r["value"])
        if r.get("per_call_ms") is not None:
            out["tpu_merge_git_makefile_pallas_per_call_ms"] = \
                r.get("per_call_ms")
    else:
        out["tpu_merge_git_makefile_pallas_error"] = _short_err(r)
    _flush_partial(full, out)
    return out


def _compact_extra(extra: dict) -> dict:
    """Enforce the summary-line size budget: strings clipped, and if the
    line is still too long, low-priority keys are dropped (they remain in
    the full report)."""
    def clip(v):
        if isinstance(v, str):
            return v[:120]
        if isinstance(v, dict):
            return {k: clip(x) for k, x in v.items()}
        if isinstance(v, float):
            return round(v, 4)
        return v

    extra = {k: clip(v) for k, v in extra.items()}
    # Drop order: verbose/secondary keys first, device evidence LAST.
    drop_order = [k for k in extra if k.endswith("_codec")] + \
        [k for k in extra if k.endswith("_linear") and k != "automerge_linear"]
    while len(json.dumps(extra)) > MAX_SUMMARY_CHARS and drop_order:
        extra.pop(drop_order.pop(0), None)
    return extra


BENCH_ACTIVE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".bench_active")


def bench_is_active() -> bool:
    """True while a bench.py main() run is in flight (live holder pid in
    .bench_active). Background campaigns (device_watcher.py, tools/soak.py)
    poll this and pause so they cannot contaminate official timings —
    ambient CPU load swings host numbers ±20% on this machine and a
    wedged-tunnel probe subprocess burns a core for ~90 s."""
    try:
        pid = int(open(BENCH_ACTIVE).read().strip() or "0")
    except (OSError, ValueError):
        return False
    # _pid_is guards against a SIGKILLed run's stale pidfile + pid reuse
    return _pid_is(pid, b"bench")


def main() -> None:
    # Never stomp a LIVE holder's pidfile: if two bench runs overlap and
    # the second overwrote the marker then finished first, its cleanup
    # would drop the guard while the first run is still benching (the
    # campaigns would resume and contaminate it). The overlapping run is
    # itself contamination either way; leaving the existing guard up is
    # the conservative choice for both runs.
    owned = not bench_is_active()
    if owned:
        with open(BENCH_ACTIVE, "w") as f:
            f.write(str(os.getpid()))
    try:
        _main()
    finally:
        if owned:
            try:
                # remove only our own marker (a stale-dead holder's file
                # we replaced above must not be dropped by *their* exit)
                if int(open(BENCH_ACTIVE).read().strip() or "0") \
                        == os.getpid():
                    os.remove(BENCH_ACTIVE)
            except (OSError, ValueError):
                pass


def _main() -> None:
    from diamond_types_tpu.native.core import (native_counters,
                                               reset_native_counters)
    from diamond_types_tpu.utils.stats import oplog_stats

    full = {}   # verbose report -> stderr + bench_report_full.json
    extra = {}

    # ---- device phase FIRST (driver contract: a late wedge must not
    # erase device evidence; two rounds of records have zero device data).
    extra.update(_run_device_phase(full))

    # ---- host phase ----
    reset_native_counters()
    # best-of-5: ambient machine load swings single runs by ~15%; the
    # 1/5/15-min load averages are recorded ALONGSIDE the number so a
    # future regression is distinguishable from a loaded-machine run
    # (VERDICT r3 methodology fix).
    extra["loadavg_before"] = [round(x, 2) for x in os.getloadavg()]

    def _cpu_spin_ms():
        # noisy-neighbor/thermal slowdowns on this shared host do NOT
        # show in loadavg (observed: the same binary 18% slower at load
        # 0.0), and /proc/cpuinfo MHz is a nominal constant on VM
        # guests. Time a fixed spin instead: steal time and frequency
        # drops both inflate it (best of 3 filters scheduler blips).
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            x = 0
            for i in range(1_000_000):
                x += i
            best = min(best, time.perf_counter() - t0)
        return round(best * 1e3, 2)

    extra["cpu_spin_ms_before"] = _cpu_spin_ms()
    n_ops, best, _snap, gm_ol = bench_merge("git-makefile.dt", repeats=5)
    ops_per_sec = n_ops / best
    host_ops = {"git-makefile.dt": ops_per_sec}
    extra["loadavg_after_primary"] = [round(x, 2) for x in os.getloadavg()]
    extra["cpu_spin_ms_after_primary"] = _cpu_spin_ms()

    # Structured observability for the primary corpus: per-structure RLE
    # size/compaction breakdown + merge-kernel event counters (reference:
    # print_stats, src/list/oplog.rs:353-405; counters per SURVEY §5) —
    # full report only, never the summary line.
    try:
        from diamond_types_tpu.listmerge import policy as _policy
        full["engine_policy_rates"] = _policy.GLOBAL.snapshot()
        full["stats"] = oplog_stats(gm_ol, include_encoded_sizes=True)
        c = native_counters()
        if c is not None:
            full["native_merge_counters"] = c
    except Exception as e:  # pragma: no cover
        full["stats_error"] = str(e)[:200]

    try:
        # best-of-9 as of r5 (was best-of-3 in r1-r4): at 2.4 ms/run the
        # extra repeats are free and the small corpus is the most
        # variance-sensitive merge row; recorded in BASELINE.md r5 notes
        ff_ops, ff_t, ff_snap, _ = bench_merge("friendsforever.dt",
                                               repeats=9)
        import gzip
        import json as _json
        with gzip.open(os.path.join(BENCH_DATA,
                                    "friendsforever_flat.json.gz"),
                       "rt") as f:
            parity = ff_snap == _json.load(f)["endContent"]
        extra["friendsforever_ops_per_sec"] = round(ff_ops / ff_t)
        extra["friendsforever_parity"] = parity
        host_ops["friendsforever.dt"] = ff_ops / ff_t
    except Exception as e:  # pragma: no cover
        extra["friendsforever_error"] = str(e)[:120]

    try:
        nn_ops, nn_t, _, _ = bench_merge("node_nodecc.dt", repeats=2)
        extra["node_nodecc_ops_per_sec"] = round(nn_ops / nn_t)
        host_ops["node_nodecc.dt"] = nn_ops / nn_t
    except Exception as e:  # pragma: no cover
        extra["node_nodecc_error"] = str(e)[:120]

    try:
        extra["automerge_linear"] = bench_linear_replay()
    except Exception as e:  # pragma: no cover
        extra["automerge_error"] = str(e)[:120]

    # The reference's other linear traces (local/apply_* groups run all 5:
    # crates/bench/src/main.rs:17) — grouped ingest + checkout per trace.
    for trace in ("rustcode", "sveltecomponent", "seph-blog1"):
        key = trace.replace("-", "_")
        try:
            extra[f"{key}_linear"] = \
                bench_linear_replay(trace + ".json.gz", full=False)
        except Exception as e:  # pragma: no cover
            extra[f"{key}_error"] = str(e)[:120]

    # complex/decode + complex/encode (crates/bench/src/main.rs:112-144).
    for corpus in ("git-makefile.dt", "node_nodecc.dt", "friendsforever.dt"):
        key = corpus.split(".")[0].replace("-", "_")
        try:
            extra[f"{key}_codec"] = bench_codec(corpus)
        except Exception as e:  # pragma: no cover
            extra[f"{key}_codec_error"] = str(e)[:120]

    # Sharded multi-doc serve scheduler (serve/ tier): device-engine
    # sessions on CPU-simulated shards, parity-gated per doc. Summary
    # keeps the capacity-planning signals; the full report keeps the
    # whole metrics snapshot (per-shard rows, flush histogram).
    try:
        sv = bench_serve_sched()
        full["serve_sched"] = sv
        m = sv["metrics"]
        dp = sv.get("devprof") or {}
        flush_p99 = (m.get("latencies", {}).get("flush", {})
                     .get("p99"))
        extra["serve_sched"] = {
            "ops_per_sec": sv["ops_per_sec"],
            "parity": sv["parity_ok"],
            "batch_occupancy": m["batch_occupancy"],
            "queue_bound_violations": m["queue_bound_violations"],
            "host_fallback_ratio": m["host_fallback_ratio"],
            # obs/devprof: where flush wall time actually goes
            "flush_p99_s": flush_p99,
            "device_fraction": dp.get("device_fraction"),
            "jit_cache": dp.get("jit_cache"),
            "transfer_bytes": dp.get("transfer_bytes"),
            # fused bucket flush: docs folded per vmapped device call
            "fused_device_calls": sv.get("fused_device_calls"),
            "fused_occupancy": sv.get("fused_occupancy"),
            # flush-window dispatch accounting (per-shard control:
            # one handoff per due bucket; the mesh A/B below targets 1)
            "device_calls_per_window":
                sv.get("device_calls_per_window"),
        }
        # serial (per-doc zone-session) comparison on the same trace:
        # the fused-vs-serial speedup is THE number ROADMAP item (c)
        # exists to move
        try:
            sv2 = bench_serve_sched(fused=False)
            full["serve_sched_serial"] = sv2
            p99s = (sv2["metrics"].get("latencies", {})
                    .get("flush", {}).get("p99"))
            extra["serve_sched"]["serial_flush_p99_s"] = p99s
            extra["serve_sched"]["serial_ops_per_sec"] = \
                sv2["ops_per_sec"]
            if sv2.get("feed_wall_s"):
                extra["serve_sched"]["fused_speedup"] = round(
                    sv2["feed_wall_s"] / max(sv["feed_wall_s"], 1e-9),
                    3)
        except Exception as e:  # pragma: no cover
            extra["serve_sched"]["serial_error"] = str(e)[:120]
        # mesh flush-window comparison on the same trace: every due
        # shard's bucket in ONE shard_map dispatch per window vs. the
        # per-shard control above — window_speedup and the
        # device_calls_per_window collapse are the ROADMAP item 1
        # (true multi-chip serving) numbers
        try:
            svm = bench_serve_sched(mesh_window=True)
            full["serve_sched_mesh"] = svm
            extra["serve_sched"]["mesh_ops_per_sec"] = \
                svm["ops_per_sec"]
            extra["serve_sched"]["mesh_device_calls_per_window"] = \
                svm.get("device_calls_per_window")
            extra["serve_sched"]["mesh_parity"] = svm["parity_ok"]
            extra["serve_sched"]["mesh_occupancy"] = \
                svm["metrics"]["window"]["mesh_occupancy"]
            if svm.get("feed_wall_s"):
                extra["serve_sched"]["window_speedup"] = round(
                    sv["feed_wall_s"] / max(svm["feed_wall_s"], 1e-9),
                    3)
        except Exception as e:  # pragma: no cover
            extra["serve_sched"]["mesh_error"] = str(e)[:120]
        # live-telemetry overhead A/B on the same trace: windowed
        # TimeSeries + SLO engine + exemplars + hot-doc attribution
        # disabled. The live tier's contract is <=3% of serve-bench
        # throughput — `telemetry_overhead_ok` is the guard
        try:
            svt = bench_serve_sched(telemetry=False)
            full["serve_sched_no_telemetry"] = svt
            base = svt["ops_per_sec"]
            overhead = round(1.0 - sv["ops_per_sec"] / max(base, 1),
                             4)
            extra["serve_sched"]["no_telemetry_ops_per_sec"] = base
            extra["serve_sched"]["telemetry_overhead"] = overhead
            extra["serve_sched"]["telemetry_overhead_ok"] = \
                overhead <= 0.03
            extra["serve_sched"]["slo_ok"] = sv.get("slo_ok")
        except Exception as e:  # pragma: no cover
            extra["serve_sched"]["telemetry_error"] = str(e)[:120]
        # journey-stamp overhead A/B on the same trace: the edit-to-
        # visibility tracker disabled (single-branch no-op stamps).
        # Same <=3% throughput contract as the live-telemetry tier —
        # `journey_overhead_ok` is the guard
        try:
            svj = bench_serve_sched(journey=False)
            full["serve_sched_no_journey"] = svj
            jbase = svj["ops_per_sec"]
            joverhead = round(
                1.0 - sv["ops_per_sec"] / max(jbase, 1), 4)
            extra["serve_sched"]["no_journey_ops_per_sec"] = jbase
            extra["serve_sched"]["journey_overhead"] = joverhead
            extra["serve_sched"]["journey_overhead_ok"] = \
                joverhead <= 0.03
        except Exception as e:  # pragma: no cover
            extra["serve_sched"]["journey_error"] = str(e)[:120]
        # device-plan transform A/B on a CONCURRENT trace: host tracker
        # walk (control) vs. the device transform rung + Pallas replay
        # on the same schedule. A concurrent mode + resident sessions
        # (max_sessions >= docs, steady rounds) are required for the
        # rung to engage at all — a linear trace has no conflict zone
        # and evicted sessions rebuild caught-up (empty tails).
        try:
            xkw = dict(mode="concurrent", shards=2, docs=6, txns=6,
                       flush_docs=3, max_sessions=8, steady_rounds=8)
            svc = bench_serve_sched(**xkw)          # host-plan control
            svx = bench_serve_sched(device_plan=True, pallas=True,
                                    **xkw)
            full["serve_sched_xform_host"] = svc
            full["serve_sched_xform"] = svx
            tr = svx.get("transform") or {}
            extra["serve_sched_xform"] = {
                "parity": svx["parity_ok"],
                "ops_per_sec": svx["ops_per_sec"],
                "host_plan_ops_per_sec": svc["ops_per_sec"],
                "device_docs": tr.get("device_docs"),
                "host_docs": tr.get("host_docs"),
                "fallbacks": tr.get("fallbacks"),
                "device_ratio": tr.get("device_ratio"),
                "pallas_jit": (svx.get("devprof") or {})
                    .get("jit_cache", {}).get("pallas"),
            }
            if svc.get("feed_wall_s") and svx.get("feed_wall_s"):
                extra["serve_sched_xform"]["transform_speedup"] = round(
                    svc["feed_wall_s"] / max(svx["feed_wall_s"], 1e-9),
                    3)
        except Exception as e:  # pragma: no cover
            extra["serve_sched_xform_error"] = str(e)[:120]
        # Shape-steering + device-resident staging A/B on a FLASH-
        # CROWD trace (migrating hot doc => churning window shapes,
        # the worst case for jit-cache hit rate). Three arms on the
        # same mesh-window tape: steered+staged (the PR 20 path),
        # steering off (every novel shape compiles), device staging
        # off (legacy host-numpy staging, full stage bytes). The
        # no-steer arm's scorecard is the control for the
        # `scorecard-diff --gate` verdict — byte parity is asserted
        # per-arm by serve-bench itself (parity_ok).
        try:
            from diamond_types_tpu.obs.scorecard import diff_scorecards
            skw = dict(mode="flash", mesh_window=True, fused=True,
                       txns=24, steady_rounds=16, timeout=600)
            svs = bench_serve_sched(steer=True, device_stage=True,
                                    **skw)
            svn = bench_serve_sched(steer=False, device_stage=True,
                                    **skw)
            svh = bench_serve_sched(steer=True, device_stage=False,
                                    **skw)
            full["serve_sched_steer"] = svs
            full["serve_sched_no_steer"] = svn
            full["serve_sched_host_stage"] = svh
            diff = diff_scorecards(svn["scorecard"], svs["scorecard"])
            full["steer_ab_diff"] = diff
            extra["serve_sched_steer"] = {
                "gate_ok": diff["ok"],
                "regressions": diff["regressions"],
                "parity": svs["parity_ok"],
                "no_steer_parity": svn["parity_ok"],
                "host_stage_parity": svh["parity_ok"],
                "steady_jit_hit_rate": svs.get("steady_jit_hit_rate"),
                "no_steer_steady_jit_hit_rate":
                    svn.get("steady_jit_hit_rate"),
                "steer_compiles":
                    (svs.get("steer") or {}).get("compiles"),
                "no_steer_compiles":
                    (svn.get("steer") or {}).get("compiles"),
                "staged_bytes_per_window":
                    svs.get("staged_bytes_per_window"),
                "host_staged_bytes_per_window":
                    svh.get("staged_bytes_per_window"),
                "ops_per_sec": svs["ops_per_sec"],
                "no_steer_ops_per_sec": svn["ops_per_sec"],
            }
            hb = svh.get("staged_bytes_per_window")
            db = svs.get("staged_bytes_per_window")
            if isinstance(hb, (int, float)) and hb > 0 \
                    and isinstance(db, (int, float)):
                extra["serve_sched_steer"]["staged_bytes_reduction"] = \
                    round(1.0 - db / hb, 4)
        except Exception as e:  # pragma: no cover
            extra["serve_sched_steer_error"] = str(e)[:120]
    except Exception as e:  # pragma: no cover
        extra["serve_sched_error"] = str(e)[:120]

    # Wire-frame codec micro-bench (wire/ tier): a churn op tape
    # through each frame codec vs its JSON twin — the summary keeps
    # the transport ratios the mesh scenario runs bank on (same row
    # `cli wire-bench` prints)
    try:
        from diamond_types_tpu.tools.cli import wire_bench
        wb = wire_bench()
        full["wire"] = wb
        extra["wire"] = {
            "ops_encode_per_sec": wb["ops"]["encode_per_sec"],
            "ops_decode_per_sec": wb["ops"]["decode_per_sec"],
            "ops_ratio": wb["ops"]["ratio"],
            "summary_ratio": wb["summary"]["ratio"],
            "patch_ratio": wb["patch"]["ratio"],
            "docs_ratio": wb["docs"]["ratio"],
        }
    except Exception as e:  # pragma: no cover
        extra["wire_error"] = str(e)[:120]

    # Follower-read A/B (read/ tier): two-server mesh, Zipf readers at
    # each doc's non-owner replica — bounded-staleness local serving
    # vs the owner-only-proxy control, with client-side staleness +
    # read-your-writes verification (speedup is THE ROADMAP item 5
    # follower-read number)
    try:
        from diamond_types_tpu.read.bench import run_read_bench
        rb = run_read_bench(docs=3, readers=4, reads_per_reader=60,
                            seed=7)
        full["serve_read"] = rb
        extra["serve_read"] = {
            "control_reads_per_sec": rb["control"]["reads_per_s"],
            "follower_reads_per_sec": rb["follower"]["reads_per_s"],
            "speedup": rb["speedup"],
            "violations": rb["violations"],
            "follower_local": rb["follower"]["local"],
            "control_proxied": rb["control"]["proxied"],
            "max_observed_staleness_s":
                rb["follower"]["max_observed_staleness_s"],
            "ok": rb["ok"],
        }
    except Exception as e:  # pragma: no cover
        extra["serve_read_error"] = str(e)[:120]

    # Scenario harness (workload/ tier): the tier-1 smoke scenario —
    # mixed-tenant Poisson/Zipf traffic + session churn + bulk + bank
    # lanes against two replicated servers with the SLO engine live.
    # The full scorecard goes in the full report; the summary keeps
    # the one-diff regression signals (scorecard-diff gates on these)
    try:
        from diamond_types_tpu.workload import get_scenario, run_scenario
        card = run_scenario(get_scenario("smoke"))
        full["scenario_smoke"] = card
        extra["scenario_smoke"] = {
            "ok": card["ok"],
            "ops_per_sec": card["throughput"]["ops_per_s"],
            "flush_p99_s": card["latency_p99_s"]["flush"],
            "read_p99_s": card["latency_p99_s"]["read"],
            "visibility_p99_s": card["latency_p99_s"]["visibility"],
            "burn_minutes": card["burn_minutes_total"],
            "bytes_per_op": card["bytes_per_op"],
            "converged": card["convergence"]["converged"],
            "spills_to_snapshot":
                card["hydration"].get("spills_to_snapshot"),
            "spill_bytes": card["hydration"].get("spill_bytes"),
        }
    except Exception as e:  # pragma: no cover
        extra["scenario_smoke_error"] = str(e)[:120]

    # Adaptive-admission A/B (qos/ tier): the same smoke scenario with
    # the closed-loop QoS controller attached, diffed in-process
    # against the static-admission card above at equal parity (same
    # seed, same tape). The summary keeps the one-diff gate verdict
    # plus the controller's decision mix — shed counts on a healthy
    # run must be zero.
    try:
        from diamond_types_tpu.obs.scorecard import diff_scorecards
        from diamond_types_tpu.workload import (get_scenario,
                                                run_scenario)
        control = full.get("scenario_smoke") \
            or run_scenario(get_scenario("smoke"))
        adaptive = run_scenario(get_scenario("smoke"), qos=True)
        diff = diff_scorecards(control, adaptive)
        full["qos_ab"] = {"control": control, "adaptive": adaptive,
                          "diff": diff}
        qblock = adaptive.get("qos") or {}
        extra["qos_ab"] = {
            "gate_ok": diff["ok"],
            "regressions": diff["regressions"],
            "ops_per_sec": adaptive["throughput"]["ops_per_s"],
            "control_ops_per_sec": control["throughput"]["ops_per_s"],
            "flush_p99_s": adaptive["latency_p99_s"]["flush"],
            "control_flush_p99_s": control["latency_p99_s"]["flush"],
            "admitted": {c: row.get("admitted", 0) for c, row in
                         (qblock.get("classes") or {}).items()},
            "sheds": qblock.get("sheds_observed"),
            "controller": qblock.get("controller"),
        }
    except Exception as e:  # pragma: no cover
        extra["qos_ab_error"] = str(e)[:120]

    # Incident-engine A/B (obs/incident.py): the same smoke scenario
    # with the anomaly detector disabled (`--no-incidents`). The
    # detector's contract is <=3% of scenario throughput. The smoke
    # tape is short enough that single-run ops/s jitters +-10% on a
    # loaded box — far above the signal — so each arm takes the best
    # of 3 runs, and the deterministic per-poll cost (one poll() over
    # the run's warmed series, as a fraction of the tick budget) is
    # the primary `incidents_overhead_ok` guard; a healthy smoke tape
    # must still open zero bundles on the armed arm.
    try:
        from diamond_types_tpu.workload import (get_scenario,
                                                run_scenario)
        runs_armed = [full.get("scenario_smoke")
                      or run_scenario(get_scenario("smoke"))]
        runs_armed += [run_scenario(get_scenario("smoke"))
                       for _ in range(2)]
        runs_dark = [run_scenario(get_scenario("smoke"), incidents=False)
                     for _ in range(3)]
        armed = max(runs_armed,
                    key=lambda r: r["throughput"]["ops_per_s"])
        base = max(r["throughput"]["ops_per_s"] for r in runs_dark)
        overhead = round(
            1.0 - armed["throughput"]["ops_per_s"] / max(base, 1e-9), 4)
        # deterministic arm: time poll() itself against the smoke tick
        import time as _time
        from diamond_types_tpu.obs import Observability as _Obs
        from diamond_types_tpu.obs.incident import (AnomalyDetector
                                                    as _Det)
        _obs = _Obs()
        for _i in range(40):            # runner-scale warmed series
            for _j in range(600):
                _obs.ts.observe("inc.bench.%d" % _i, 0.01)
        _det = _Det(_obs.ts, recorder=_obs.recorder)
        _det.poll()
        _t0 = _time.perf_counter()
        for _ in range(50):
            _det.poll()
        _poll_s = (_time.perf_counter() - _t0) / 50
        _tick_s = get_scenario("smoke").tick_s
        poll_frac = round(_poll_s / _tick_s, 4)
        extra["incidents_ab"] = {
            "ops_per_sec": armed["throughput"]["ops_per_s"],
            "no_incidents_ops_per_sec": base,
            "incidents_overhead": overhead,
            "poll_cost_s": round(_poll_s, 6),
            "poll_tick_fraction": poll_frac,
            "incidents_overhead_ok": poll_frac <= 0.03,
            "bundles_opened": (armed.get("incidents") or {}).get("count"),
        }
    except Exception as e:  # pragma: no cover
        extra["incidents_ab_error"] = str(e)[:120]

    # Soak-resume smoke (workload/ long-run mode): checkpoint the
    # smoke tape every virtual second, kill it at tick 3, resume from
    # the checkpoint dir, and require the resumed run to converge with
    # its incidents block intact — the `cli scenario run --resume`
    # contract exercised end to end.
    try:
        import shutil as _sh
        from diamond_types_tpu.workload import (get_scenario,
                                                run_scenario)
        part = run_scenario(get_scenario("smoke"),
                            checkpoint_every_s=1.0, stop_after_ticks=3)
        resumed = run_scenario(None, resume_dir=part["resume_dir"])
        _sh.rmtree(part["resume_dir"], ignore_errors=True)
        extra["soak_resume"] = {
            "aborted_at_tick": part.get("tick"),
            "ok": resumed["ok"],
            "converged": resumed["convergence"]["converged"],
            "resumed": resumed.get("extra", {}).get("resumed"),
            "incidents": (resumed.get("incidents") or {}).get("count"),
        }
    except Exception as e:  # pragma: no cover
        extra["soak_resume_error"] = str(e)[:120]

    # Peak-memory probe (reference: examples/posstats.rs behind the
    # memusage feature / trace-alloc counting allocator). Python-side
    # allocations only; the C++ tier's tables are outside tracemalloc.
    try:
        from diamond_types_tpu.utils.stats import peak_memory_probe
        _, peak = peak_memory_probe(lambda: gm_ol.checkout_tip())
        extra["merge_peak_py_bytes"] = int(peak)
        from diamond_types_tpu.encoding.decode import load_oplog as _lo
        with open(os.path.join(BENCH_DATA, "git-makefile.dt"), "rb") as f:
            _data = f.read()
        _, peak = peak_memory_probe(lambda: _lo(_data))
        extra["decode_peak_py_bytes"] = int(peak)
    except Exception as e:  # pragma: no cover
        extra["memusage_error"] = str(e)[:120]

    # Device-vs-host ratios (device phase ran before host numbers existed).
    for key, corpus in (("tpu_merge_git_makefile", "git-makefile.dt"),
                        ("tpu_merge_friendsforever", "friendsforever.dt")):
        v = extra.get(f"{key}_ops_per_sec")
        if v and corpus in host_ops:
            extra[f"{key}_vs_host"] = round(v / host_ops[corpus], 2)
    v = extra.get("tpu_merge_node_nodecc_best_ops_per_sec")
    if v and "node_nodecc.dt" in host_ops:
        extra["tpu_merge_node_nodecc_best_vs_host"] = round(
            v / host_ops["node_nodecc.dt"], 2)

    extra["tpu_timing_note"] = (
        "device timings force completion via host transfer (tunneled "
        "platform's block_until_ready does not synchronize)")
    extra["vs_published_replay_figure"] = round(
        ops_per_sec / PUBLISHED_REPLAY_OPS_PER_SEC, 4)

    # The UNCOMPACTED extra goes into the full report first — compaction
    # must never lose data, only move it off the summary line.
    full["extra_full"] = dict(extra)
    summary = {
        "metric": "git-makefile.dt merge throughput",
        "value": round(ops_per_sec),
        "unit": "ops/sec",
        "vs_baseline": round(ops_per_sec / LOCAL_BASELINE_OPS_PER_SEC, 4),
        "extra": _compact_extra(extra),
    }

    # Full verbose report: stderr + file, NEVER the final stdout line.
    full["summary"] = summary
    report = json.dumps(full, indent=1, default=str)
    print(report, file=sys.stderr)
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_report_full.json"), "w") as f:
            f.write(report)
    except OSError:
        pass

    line = json.dumps(summary)
    if len(line) > MAX_SUMMARY_CHARS + 1500:  # belt and braces
        summary["extra"] = {"truncated": "see bench_report_full.json",
                            **{k: v for k, v in summary["extra"].items()
                               if isinstance(v, (int, float))}}
        line = json.dumps(summary)
    print(line)


if __name__ == "__main__":
    main()
