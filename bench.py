#!/usr/bin/env python3
"""Benchmark driver — prints ONE JSON line with the primary metric.

Primary metric (BASELINE.json): ops/sec merged on git-makefile.dt
(high-fanout concurrent DAG), with text-equality parity (two independent
checkouts must agree byte-for-byte; friendsforever.dt must match the
reference's flattened trace).

vs_baseline: ratio against the only absolute throughput number stored in the
reference repo — 12 ms for a full 259,778-op replay of automerge-paper
(reference: crates/bench/src/main.rs:56-58) ≈ 21.6M ops/s on the author's
machine. The reference's criterion harness can't be re-run here (no Rust
toolchain in this image), so this is the documented stand-in baseline until a
measured one exists.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_OPS_PER_SEC = 259_778 / 0.012  # reference replay figure (see above)

BENCH_DATA = "/root/reference/benchmark_data"


def bench_merge(name: str, repeats: int = 3):
    from diamond_types_tpu.encoding.decode import load_oplog
    with open(os.path.join(BENCH_DATA, name), "rb") as f:
        data = f.read()
    ol = load_oplog(data)
    n_ops = len(ol)
    best = float("inf")
    snap = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        b = ol.checkout_tip()
        dt = time.perf_counter() - t0
        best = min(best, dt)
        if snap is None:
            snap = b.snapshot()
        else:
            assert snap == b.snapshot(), "non-deterministic merge!"
    return n_ops, best, snap


_TPU_BENCH_SNIPPET = """
import sys, time
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from functools import partial
from __graft_entry__ import _example_batch
from diamond_types_tpu.tpu.batch import replay_batch
batch, n_ops, cap = {batch}, {n_ops}, {cap}
pos, dlen, ilen, chars = _example_batch(batch, n_ops, 4)
args = tuple(jnp.asarray(x) for x in (pos, dlen, ilen, chars))
fn = jax.jit(partial(replay_batch, cap=cap))
docs, lens = fn(*args)
docs.block_until_ready()
t0 = time.perf_counter()
docs, lens = fn(*args)
docs.block_until_ready()
print("RESULT", batch * n_ops / (time.perf_counter() - t0))
"""


def bench_tpu_batch(batch: int = 1024, n_ops: int = 256, cap: int = 1024,
                    timeout: int = 240):
    """Batched multi-doc replay on the real chip (BASELINE config 4 shape).

    Runs in a subprocess with a hard timeout: if the accelerator tunnel is
    unavailable, the primary (host) metric must still be reported.
    """
    import subprocess
    code = _TPU_BENCH_SNIPPET.format(
        repo=os.path.dirname(os.path.abspath(__file__)),
        batch=batch, n_ops=n_ops, cap=cap)
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout)
        for line in r.stdout.splitlines():
            if line.startswith("RESULT "):
                return float(line.split()[1])
    except (subprocess.TimeoutExpired, OSError):
        pass
    return None


_MERGE_KERNEL_SNIPPET = """
import sys, time
sys.path.insert(0, {repo!r})
import numpy as np
from diamond_types_tpu.encoding.decode import load_oplog
from diamond_types_tpu.tpu.merge_kernel import prepare_doc, pad_docs, _jitted_kernel, _pow2
ol = load_oplog(open({data!r}, 'rb').read())
doc = prepare_doc(ol)   # host origin extraction (once; device is the bench)
docs = [doc] * {batch}
import jax, jax.numpy as jnp
parent, side, ka, ks, vis, off, chars = pad_docs(docs)
cap = _pow2(doc.total_len)
fn = _jitted_kernel(cap)
args = tuple(jnp.asarray(x) for x in (parent, side, ka, ks, vis, off, chars))
texts, totals = fn(*args)
texts.block_until_ready()
t0 = time.perf_counter()
texts, totals = fn(*args)
texts.block_until_ready()
dt = time.perf_counter() - t0
expected = ol.checkout_tip().snapshot()
got = np.asarray(texts[0][:int(totals[0])]).astype(np.int32).tobytes().decode('utf-32-le')
assert got == expected, 'device merge diverged from host engine'
print("RESULT", {batch} * len(ol) / dt)
"""


def bench_device_merge(batch: int = 256, timeout: int = 240):
    """Batched device MERGE-kernel checkout (Fugue-tree linearization of
    friendsforever's 2-agent concurrent history, x batch replicas): the
    device resolves concurrent order + assembles text; parity-checked
    against the host engine inside the subprocess."""
    import subprocess
    code = _MERGE_KERNEL_SNIPPET.format(
        repo=os.path.dirname(os.path.abspath(__file__)),
        data=os.path.join(BENCH_DATA, "friendsforever.dt"),
        batch=batch)
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout)
        for line in r.stdout.splitlines():
            if line.startswith("RESULT "):
                return float(line.split()[1])
        if r.returncode != 0:
            # a real failure (e.g. the in-subprocess parity assert), NOT
            # missing hardware — surface it instead of swallowing it
            return ("error", r.stderr.strip().splitlines()[-1][:200]
                    if r.stderr.strip() else f"exit {r.returncode}")
    except (subprocess.TimeoutExpired, OSError):
        pass
    return None


def bench_linear_replay():
    """BASELINE config 1: automerge-paper linear single-branch replay."""
    from diamond_types_tpu.text.trace import load_trace, replay_into_oplog
    data = load_trace(os.path.join(BENCH_DATA, "automerge-paper.json.gz"))
    t0 = time.perf_counter()
    ol = replay_into_oplog(data)
    t_apply = time.perf_counter() - t0
    t0 = time.perf_counter()
    b = ol.checkout_tip()
    t_checkout = time.perf_counter() - t0
    n = data.num_ops()
    return {
        "apply_ops_per_sec": round(n / t_apply),
        "checkout_ops_per_sec": round(n / t_checkout),
        "parity": b.snapshot() == data.end_content,
    }


def main() -> None:
    n_ops, best, _snap = bench_merge("git-makefile.dt")
    ops_per_sec = n_ops / best

    extra = {}
    try:
        ff_ops, ff_t, ff_snap = bench_merge("friendsforever.dt", repeats=1)
        import gzip
        import json as _json
        with gzip.open(os.path.join(BENCH_DATA, "friendsforever_flat.json.gz"),
                       "rt") as f:
            parity = ff_snap == _json.load(f)["endContent"]
        extra["friendsforever_ops_per_sec"] = round(ff_ops / ff_t)
        extra["friendsforever_parity"] = parity
    except Exception as e:  # pragma: no cover
        extra["friendsforever_error"] = str(e)[:100]

    try:
        nn_ops, nn_t, _ = bench_merge("node_nodecc.dt", repeats=2)
        extra["node_nodecc_ops_per_sec"] = round(nn_ops / nn_t)
    except Exception as e:  # pragma: no cover
        extra["node_nodecc_error"] = str(e)[:100]

    try:
        extra["automerge_linear"] = bench_linear_replay()
    except Exception as e:  # pragma: no cover
        extra["automerge_error"] = str(e)[:100]

    tpu = bench_tpu_batch()
    if tpu is not None:
        extra["tpu_batched_replay_ops_per_sec"] = round(tpu)

    dm = bench_device_merge()
    if isinstance(dm, tuple):
        extra["tpu_batched_merge_error"] = dm[1]
    elif dm is not None:
        extra["tpu_batched_merge_ops_per_sec"] = round(dm)

    print(json.dumps({
        "metric": "git-makefile.dt merge throughput",
        "value": round(ops_per_sec),
        "unit": "ops/sec",
        "vs_baseline": round(ops_per_sec / BASELINE_OPS_PER_SEC, 4),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
