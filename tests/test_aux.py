"""Auxiliary subsystems: shelf CRDT, stats/counters, stochastic summary,
invariant checkers (SURVEY.md §5)."""

import random

from diamond_types_tpu.causalgraph.stochastic_summary import (
    estimate_common_frontier, sample_versions)
from diamond_types_tpu.db import shelf
from diamond_types_tpu.utils.stats import oplog_stats, peak_memory_probe
from tests.test_encode import build_random_oplog
import pytest

from tests.test_fuzz import random_edit


def test_shelf_merge_commutative():
    a = shelf.new_shelf({})
    a = shelf.set_key(a, "x", 1)
    a = shelf.set_key(a, "y", "hello")
    b = shelf.new_shelf({})
    b = shelf.set_key(b, "x", 2)
    b = shelf.set_key(b, "x", 3)  # higher version for x

    m1 = shelf.merge(a, b)
    m2 = shelf.merge(b, a)
    assert shelf.get(m1) == shelf.get(m2)
    assert shelf.get(m1)["x"] == 3      # b wrote x twice -> higher version
    assert shelf.get(m1)["y"] == "hello"


def test_oplog_stats_and_memprobe():
    ol = build_random_oplog(2, steps=30)
    s = oplog_stats(ol, include_encoded_sizes=True)
    assert s["num_ops"] == len(ol)
    assert s["op_runs"] >= 1
    assert s["ops_per_run"] >= 1
    assert s["op_runs_bytes"] == s["op_runs"] * 48
    assert s["op_uncompacted_bytes"] >= s["op_runs_bytes"]
    assert s["graph_runs_bytes"] > 0 and s["agent_runs_bytes"] > 0
    assert 0 < s["encoded_patch_from_tip_bytes"] < s["encoded_full_bytes"]

    (_, peak) = peak_memory_probe(ol.checkout_tip)
    assert peak > 0


def test_merge_counters_wired():
    """SURVEY §5 / VERDICT r1 weak #7: the structured counters must count
    real merge work in BOTH engines — they were decorative in round 1."""
    import os
    from diamond_types_tpu.native.core import (native_available,
                                               native_counters,
                                               reset_native_counters)
    from diamond_types_tpu.utils.stats import GLOBAL_COUNTERS

    ol = build_random_oplog(5, steps=40)

    # python engine
    GLOBAL_COUNTERS.counts.clear()
    os.environ["DT_TPU_NO_NATIVE"] = "1"
    try:
        ol.checkout_tip()
    finally:
        del os.environ["DT_TPU_NO_NATIVE"]
    snap = GLOBAL_COUNTERS.snapshot()["counts"]
    assert snap.get("apply_ins_runs", 0) > 0
    assert snap.get("integrate_calls", 0) > 0

    # native engine
    if native_available():
        reset_native_counters()
        ol2 = build_random_oplog(5, steps=40)
        ol2.checkout_tip()
        c = native_counters()
        assert c["integrate_calls"] > 0
        assert c["apply_ins_runs"] > 0
        assert c["walk_steps"] > 0


def test_stochastic_summary_converges():
    rng = random.Random(0)
    a = build_random_oplog(11, steps=30)
    from diamond_types_tpu.encoding.decode import load_oplog
    from diamond_types_tpu.encoding.encode import ENCODE_FULL, encode_oplog
    b = load_oplog(encode_oplog(a, ENCODE_FULL))
    shared = a.version
    # a advances
    v, c = a.version, a.checkout_tip().snapshot()
    for _ in range(10):
        v, c = random_edit(rng, a, 0, v, c)

    est = estimate_common_frontier(a.cg, b.cg, rounds=4, k=32)
    # Estimate must be a true lower bound of the common version...
    assert a.cg.graph.frontier_contains_frontier(shared, est)
    # ...and with the frontier included in samples it finds it exactly.
    assert est == shared


def test_sample_includes_frontier():
    ol = build_random_oplog(4, steps=10)
    s = sample_versions(ol.cg, k=4)
    remote_frontier = ol.cg.local_to_remote_frontier(ol.version)
    for rv in remote_frontier:
        assert tuple(rv) in [tuple(x) for x in s]


def test_invariant_checkers_on_random_oplogs():
    from diamond_types_tpu.utils.checkers import check_oplog
    for seed in range(6):
        ol = build_random_oplog(seed, steps=30)
        check_oplog(ol, deep=True)


def test_invariant_checkers_on_corpora():
    import os
    from diamond_types_tpu.encoding.decode import load_oplog
    from diamond_types_tpu.utils.checkers import check_oplog
    p = "/root/reference/benchmark_data/friendsforever.dt"
    if not os.path.exists(p):
        return
    with open(p, "rb") as f:
        ol = load_oplog(f.read())
    check_oplog(ol, deep=False)


def test_wchar_conversions():
    from diamond_types_tpu.core.unicount import (chars_to_wchars, count_utf16,
                                                 wchars_to_chars)
    s = "a\U0001F600b\U0001F3F4c"  # astral chars take 2 UTF-16 units
    assert count_utf16(s) == 7
    assert chars_to_wchars(s, 0) == 0
    assert chars_to_wchars(s, 2) == 3
    assert chars_to_wchars(s, 5) == 7
    assert wchars_to_chars(s, 3) == 2
    assert wchars_to_chars(s, 7) == 5
    import pytest
    with pytest.raises(ValueError):
        wchars_to_chars(s, 2)  # inside the surrogate pair


def test_branch_wchar_edits():
    from diamond_types_tpu import OpLog
    from diamond_types_tpu.text.branch import Branch

    ol = OpLog()
    a = ol.get_or_create_agent_id("a")
    b = Branch()
    b.insert(ol, a, 0, "x\U0001F600y")
    b.insert_at_wchar(ol, a, 3, "!")   # after the emoji (2 units) + x
    assert b.snapshot() == "x\U0001F600!y"
    b.delete_at_wchar(ol, a, 1, 3)     # delete the emoji
    assert b.snapshot() == "x!y"
    assert ol.checkout_tip().snapshot() == b.snapshot()


# ---- conflict detection (reference: has_conflicts_when_merging,
# src/list/merge.rs:51; merge_conflict_checks, listmerge/mod.rs:50-51) ----

def _conflict_fixture():
    from diamond_types_tpu import OpLog
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    b = ol.get_or_create_agent_id("bob")
    base = [ol.add_insert_at(a, [], 0, "hello world")]
    return ol, a, b, base


def test_conflicts_non_colliding():
    """Concurrent edits at DIFFERENT positions: mergeable without any
    insert-order ambiguity -> no conflicts."""
    ol, a, b, base = _conflict_fixture()
    ol.add_insert_at(a, base, 0, "A")       # front
    ol.add_insert_at(b, base, 11, "B")      # back
    assert ol.count_conflicts_when_merging([]) == 0
    assert not ol.has_conflicts_when_merging([])
    br = ol.checkout_tip()
    assert br.last_merge_collisions in (0, None)
    assert br.snapshot() == "Ahello worldB"


def test_conflicts_colliding():
    """Concurrent inserts at the SAME gap: the YjsMod tie-break fires."""
    ol, a, b, base = _conflict_fixture()
    ol.add_insert_at(a, base, 5, "A")
    ol.add_insert_at(b, base, 5, "B")
    assert ol.has_conflicts_when_merging([])
    assert ol.count_conflicts_when_merging([]) >= 1
    br = ol.checkout_tip()
    assert br.last_merge_collisions >= 1
    assert br.snapshot() == "helloAB world"   # alice < bob by name


def test_conflicts_engine_agreement():
    """Native and Python engines must agree on the collision verdict."""
    import os
    import random
    from diamond_types_tpu import OpLog
    from diamond_types_tpu.native import native_available
    if not native_available():
        import pytest
        pytest.skip("native library unavailable")
    rng = random.Random(31337)
    from test_zone import random_edit
    for trial in range(10):
        ol = OpLog()
        agents = [ol.get_or_create_agent_id(n) for n in ("a", "b")]
        branches = [([], "")]
        for _ in range(25):
            bi = rng.randrange(len(branches))
            version, content = branches[bi]
            version, content = random_edit(
                rng, ol, agents[rng.randrange(2)], version, content)
            if rng.random() < 0.3 and len(branches) < 3:
                branches.append((version, content))
            else:
                branches[bi] = (version, content)
        native_n = ol.count_conflicts_when_merging([])
        os.environ["DT_TPU_NO_NATIVE"] = "1"
        try:
            py_n = ol.count_conflicts_when_merging([])
        finally:
            del os.environ["DT_TPU_NO_NATIVE"]
        # The VERDICT (has/has-not conflicts) must agree across engines;
        # the COUNT is engine-specific (RLE run granularity differs
        # between the C++ B-tree and the Python treap, so the number of
        # integrate scan encounters differs — the reference itself only
        # keeps a boolean flag).
        assert (native_n > 0) == (py_n > 0), (trial, native_n, py_n)


def test_conflicts_incremental_frontier():
    """From a frontier that already contains one side, only the other
    side's inserts can collide."""
    ol, a, b, base = _conflict_fixture()
    va = [ol.add_insert_at(a, base, 5, "A")]
    ol.add_insert_at(b, base, 5, "B")
    assert ol.has_conflicts_when_merging([])        # from scratch: collide
    assert ol.has_conflicts_when_merging(va)        # folding B into A's doc
    assert not ol.has_conflicts_when_merging(list(ol.version))  # no-op


@pytest.mark.parametrize("seed", range(6))
def test_astral_wchar_fuzz_roundtrip(seed):
    """Unicode-heavy fuzz across the wchar (UTF-16) interop endpoints:
    concurrent astral-char edits must survive encode -> decode -> merge,
    and every wchar position must round-trip (reference: the
    wchar_conversion feature, branch.rs insert_at_wchar; fuzz alphabet
    src/list_fuzzer_tools.rs:18-24)."""
    import random
    from diamond_types_tpu.core.unicount import (chars_to_wchars,
                                                 count_utf16,
                                                 wchars_to_chars)
    from diamond_types_tpu.encoding.decode import decode_into, load_oplog
    from diamond_types_tpu.encoding.encode import ENCODE_FULL, encode_oplog
    from tests.test_fuzz import ALPHABET
    from diamond_types_tpu import ListCRDT
    rng = random.Random(1000 + seed)
    c = ListCRDT()
    a = c.get_or_create_agent_id("astral")
    # seed text dense with astral chars (each = 2 wchar units)
    seed_text = "".join(rng.choice(ALPHABET) for _ in range(40))
    c.insert(a, 0, seed_text)
    # wchar-addressed edits: only at positions that don't split pairs
    for _ in range(30):
        snap = c.branch.snapshot()
        wpos = chars_to_wchars(snap, rng.randint(0, len(snap)))
        if rng.random() < 0.6 or not snap:
            c.branch.insert_at_wchar(c.oplog, a, wpos,
                                     rng.choice(ALPHABET))
        else:
            cpos = wchars_to_chars(snap, wpos)
            if cpos < len(snap):
                wend = chars_to_wchars(snap, cpos + 1)
                c.branch.delete_at_wchar(c.oplog, a, wpos, wend)
    # encode -> fresh replica -> concurrent branch edits -> cross merge
    blob = encode_oplog(c.oplog, ENCODE_FULL)
    d = ListCRDT()
    decode_into(d.oplog, blob)
    d.branch = d.oplog.checkout_tip()
    b = d.get_or_create_agent_id("bob")
    snap = d.branch.snapshot()
    d.branch.insert_at_wchar(d.oplog, b, chars_to_wchars(snap, len(snap) // 2),
                             "\U00010190X\U0001019a")
    c.insert(a, 0, "\U00010194")
    # merge both ways; snapshots must agree and wchar maps must invert
    blob_c = encode_oplog(c.oplog, ENCODE_FULL)
    blob_d = encode_oplog(d.oplog, ENCODE_FULL)
    decode_into(c.oplog, blob_d)
    decode_into(d.oplog, blob_c)
    sc = c.oplog.checkout_tip().snapshot()
    sd = d.oplog.checkout_tip().snapshot()
    assert sc == sd
    for cpos in range(len(sc) + 1):
        w = chars_to_wchars(sc, cpos)
        assert wchars_to_chars(sc, w) == cpos
    assert count_utf16(sc) == len(sc) + sum(1 for ch in sc if ord(ch) > 0xFFFF)
