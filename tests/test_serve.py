"""Tests for the sharded multi-document merge scheduler (serve/).

Fast CPU-only tier-1 tests: the device-engine cases run on simulated
shards (conftest pins JAX_PLATFORMS=cpu with an 8-device virtual mesh)
and share session shapes across docs so the whole fleet reuses one jit
cache entry per micro-tape length — the e2e parity test stays seconds,
not minutes.
"""

import json
import threading
import urllib.request

import pytest

from diamond_types_tpu.serve import (AdmissionQueue, Backpressure,
                                     MergeScheduler, SessionBank,
                                     ServeMetrics, ShardRouter,
                                     shape_bucket)
from diamond_types_tpu.text.oplog import OpLog

pytestmark = pytest.mark.serve


def _mk_oplog(doc_id: str, text: str = "hello") -> OpLog:
    ol = OpLog()
    ol.doc_id = doc_id
    agent = ol.get_or_create_agent_id("a")
    if text:
        ol.add_insert_at(agent, [], 0, text)
    return ol


# ---- router ---------------------------------------------------------------

def test_router_deterministic_across_instances():
    ids = [f"doc{i}" for i in range(64)]
    r1, r2 = ShardRouter(8), ShardRouter(8)
    assert [r1.shard_of(d) for d in ids] == [r2.shard_of(d) for d in ids]
    # assignment is pure in doc_id: repeated queries never move a doc
    assert [r1.shard_of(d) for d in ids] == [r1.shard_of(d) for d in ids]


def test_router_rough_balance():
    r = ShardRouter(4)
    for i in range(400):
        r.assign(f"doc{i:04d}")
    counts = r.counts()
    assert sum(counts) == 400
    # rendezvous hashing: every shard takes a meaningful share
    assert min(counts) > 400 / 4 * 0.5
    assert max(counts) < 400 / 4 * 1.6


def test_router_rebalance_moves_only_required_subset():
    r = ShardRouter(4)
    ids = [f"doc{i:04d}" for i in range(200)]
    before = {d: r.assign(d) for d in ids}
    moved = r.rebalance(3)
    # rendezvous property: exactly the docs whose TOP shard was removed
    # move; everyone else keeps their assignment
    for d in ids:
        if d in moved:
            old, new = moved[d]
            assert old == before[d] and old == 3 and new != 3
        else:
            assert r.shard_of(d) == before[d]
    assert 0 < len(moved) < len(ids)
    # growing back re-adopts the original assignment (pure hash)
    r2 = ShardRouter(4)
    assert all(r2.shard_of(d) == before[d] for d in ids)


def test_router_rebalance_pinned_assignments():
    """Pinned blake2b rendezvous placements: these exact values must
    hold in every process and across PRs — replication's doc-ownership
    (replicate/ownership.py) derives host placement from the same
    scoring, so silent drift here would strand leases cluster-wide."""
    docs = [f"doc-{i}" for i in range(12)]
    pinned = {
        8: [5, 7, 1, 5, 6, 3, 6, 0, 7, 7, 6, 5],
        5: [2, 3, 1, 2, 3, 3, 0, 0, 4, 0, 2, 4],
        3: [2, 0, 1, 2, 0, 0, 0, 0, 0, 0, 2, 0],
    }
    for n, want in pinned.items():
        assert [ShardRouter(n).shard_of(d) for d in docs] == want
    # minimal rendezvous delta on shrink: exactly the docs whose top
    # shard was removed (8-shard placement >= 5) move, nobody else
    r = ShardRouter(8)
    for d in docs:
        r.assign(d)
    moved = r.rebalance(5)
    assert sorted(moved) == sorted(d for d, s in zip(docs, pinned[8])
                                   if s >= 5)
    for d, (old, new) in moved.items():
        assert old == pinned[8][docs.index(d)]
        assert new == pinned[5][docs.index(d)]
    for d in docs:
        assert r.assignments[d] == pinned[5][docs.index(d)]
    # growing back is a clean inverse: the same set returns home
    moved_back = r.rebalance(8)
    assert sorted(moved_back) == sorted(moved)
    assert [r.assignments[d] for d in docs] == pinned[8]


# ---- admission queue ------------------------------------------------------

def test_shape_bucket_pow2():
    assert [shape_bucket(n) for n in (0, 1, 2, 3, 4, 5, 9, 64)] == \
        [1, 1, 2, 4, 4, 8, 16, 64]


def test_flush_trigger_size():
    q = AdmissionQueue(1, flush_docs=3, flush_deadline_s=10.0)
    t = 100.0
    q.submit(0, "a", 2, t)
    q.submit(0, "b", 2, t)
    assert q.due(t) == []          # 2 of 3 docs, deadline far away
    q.submit(0, "c", 2, t)
    assert q.due(t) == [(0, 2, "size")]
    items = q.take(0, 2)
    assert [i.doc_id for i in items] == ["a", "b", "c"]   # FIFO
    assert q.due(t) == [] and q.depth(0) == 0


def test_flush_trigger_deadline():
    q = AdmissionQueue(1, flush_docs=8, flush_deadline_s=0.05)
    t = 100.0
    q.submit(0, "a", 1, t)
    assert q.due(t + 0.04) == []
    assert q.due(t + 0.06) == [(0, 1, "deadline")]


def test_coalescing_keeps_deadline_and_depth():
    q = AdmissionQueue(1, max_pending=4, flush_docs=8,
                       flush_deadline_s=0.05)
    t = 100.0
    b = q.submit(0, "a", 1, t)
    assert b == 1
    # re-submit coalesces: depth unchanged, ops accumulate, the entry
    # migrates to the larger shape bucket, the ORIGINAL clock survives
    b = q.submit(0, "a", 3, t + 0.03)
    assert b == 4 and q.depth(0) == 1
    assert q.due(t + 0.06) == [(0, 4, "deadline")]
    (item,) = q.take(0, 4)
    assert item.n_ops == 4 and item.enqueued_at == t


def test_backpressure_bounds_depth():
    q = AdmissionQueue(1, max_pending=3, flush_docs=100,
                       flush_deadline_s=0.05)
    t = 100.0
    for d in ("a", "b", "c"):
        q.submit(0, d, 1, t)
    with pytest.raises(Backpressure) as ei:
        q.submit(0, "d", 1, t)
    assert ei.value.retry_after > 0
    assert q.depth(0) == 3          # rejected submit added nothing
    q.submit(0, "a", 1, t)          # coalescing is NOT new depth
    assert q.depth(0) == 3


def test_scheduler_reject_surfaces_retry_after_and_bound_holds():
    ols = {f"d{i}": _mk_oplog(f"d{i}") for i in range(12)}
    sched = MergeScheduler(1, resolve=ols.__getitem__, engine="host",
                           max_pending=4, flush_docs=100,
                           flush_deadline_s=60.0)
    results = [sched.submit(d) for d in ols]
    accepted = [r for r in results if r["accepted"]]
    rejected = [r for r in results if not r["accepted"]]
    assert len(accepted) == 4 and len(rejected) == 8
    assert all(r["retry_after"] > 0 for r in rejected)
    snap = sched.metrics_json()
    assert snap["totals"]["rejects"] == 8
    assert snap["queue_bound_violations"] == 0
    assert snap["max_depth_seen"] <= 4
    # after a drain the rejected docs resubmit fine
    sched.drain()
    assert all(sched.submit(d)["accepted"] for d in list(ols)[:4])


# ---- session bank ---------------------------------------------------------

def test_bank_lru_eviction_accounting():
    m = ServeMetrics(1, flush_docs=4, max_pending=16)
    bank = SessionBank(0, max_sessions=2, engine="host", metrics=m)
    ols = {d: _mk_oplog(d) for d in ("a", "b", "c")}
    for d in ("a", "b"):
        bank.sync_doc(d, ols[d])
    assert set(bank.sessions) == {"a", "b"}
    bank.sync_doc("a", ols["a"])            # refresh a's LRU slot
    bank.sync_doc("c", ols["c"])            # evicts b, the LRU victim
    assert set(bank.sessions) == {"a", "c"}
    assert m.shard[0]["evictions"] == 1 and m.shard[0]["builds"] == 3
    # the evicted doc rebuilds on its next merge
    bank.sync_doc("b", ols["b"])
    assert m.shard[0]["builds"] == 4 and m.shard[0]["evictions"] == 2
    # text still correct for everything, resident or not
    for d, ol in ols.items():
        assert bank.text(d, ol) == ol.checkout_tip().snapshot()


def test_bank_slot_budget_eviction_device():
    # device-engine bank with a slot budget sized for ~1 tiny session:
    # the second build must evict the first (capacity, not count)
    m = ServeMetrics(1, flush_docs=4, max_pending=16)
    bank = SessionBank(0, max_sessions=8, engine="device", metrics=m)
    ols = {d: _mk_oplog(d) for d in ("a", "b")}
    bank.sync_doc("a", ols["a"])
    fp = bank.footprint_slots()
    assert fp > 0                    # footprint accounting is live
    bank.max_slots = int(fp * 1.5)   # room for one, not two
    bank.sync_doc("b", ols["b"])
    assert set(bank.sessions) == {"b"}
    assert m.shard[0]["evictions"] == 1
    assert bank.text("a", ols["a"]) == "hello"


def test_bank_host_fallback_on_device_failure(monkeypatch):
    m = ServeMetrics(1, flush_docs=4, max_pending=16)
    bank = SessionBank(0, engine="device", metrics=m)
    ol = _mk_oplog("a")

    class Boom:
        def sync(self):
            raise RuntimeError("worker crashed")

        def footprint_slots(self):
            return 0

    monkeypatch.setattr(bank, "_build", lambda doc_id, oplog: Boom())
    r = bank.sync_doc("a", ol)
    assert r["engine"] == "host" and "error" in r
    assert m.shard[0]["host_fallbacks"] == 1
    assert bank.sessions == {}       # broken session evicted
    assert bank.text("a", ol) == "hello"


# ---- scheduler (host engine) ----------------------------------------------

def test_scheduler_host_end_to_end_with_rebalance():
    ols = {f"d{i}": _mk_oplog(f"d{i}", "") for i in range(10)}
    agents = {d: ol.get_or_create_agent_id("w") for d, ol in ols.items()}
    sched = MergeScheduler(4, resolve=ols.__getitem__, engine="host",
                           flush_docs=3, flush_deadline_s=0.01)
    for step in range(3):
        for i, (d, ol) in enumerate(ols.items()):
            ol.add_insert_at(agents[d], list(ol.version), 0,
                             f"{d}:{step} ")
            assert sched.submit(d)["accepted"]
        sched.pump(force=True)
    moved = sched.rebalance(3)
    assert all(old == 3 for (old, _new) in moved.values())
    for d, ol in ols.items():
        assert sched.text(d) == ol.checkout_tip().snapshot()
    snap = sched.metrics_json()
    assert snap["totals"]["flushes"] > 0
    assert snap["queue_bound_violations"] == 0
    assert sum(snap["router_counts"]) == len(ols)
    assert all(s != 3 for s in
               (sched.router.shard_of(d) for d in ols))


def test_scheduler_read_flushes_pending():
    ol = _mk_oplog("d0", "")
    agent = ol.get_or_create_agent_id("w")
    sched = MergeScheduler(2, resolve=lambda d: ol, engine="host",
                           flush_docs=100, flush_deadline_s=60.0)
    ol.add_insert_at(agent, list(ol.version), 0, "xyz")
    assert sched.submit("d0")["accepted"]
    # no pump ran — the read itself must flush the doc's bucket
    assert sched.text("d0") == "xyz"
    snap = sched.metrics_json()
    assert snap["flush_reasons"].get("read", 0) == 1
    assert snap["totals"]["flushed_docs"] == 1


# ---- e2e parity on simulated shards (the acceptance gate) -----------------

def test_serve_bench_device_parity_4_shards():
    from diamond_types_tpu.serve.driver import run_serve_bench
    report = run_serve_bench(shards=4, docs=8, txns=8, engine="device",
                             mode="trace", flush_docs=4,
                             flush_deadline_s=0.02)
    assert report["parity_ok"], report["parity_mismatches"]
    m = report["metrics"]
    assert m["batch_occupancy"] > 0
    assert m["queue_bound_violations"] == 0
    assert m["totals"]["flushes"] > 0
    # work really spread across the shard fleet
    active = [s for s in m["per_shard"] if s["syncs"] > 0]
    assert len(active) >= 2
    # the device engine actually served the merges (CPU-simulated chip)
    assert m["host_fallback_ratio"] < 0.5


def test_serve_bench_concurrent_mode_host():
    from diamond_types_tpu.serve.driver import run_serve_bench
    report = run_serve_bench(shards=4, docs=6, txns=10, engine="host",
                             mode="concurrent", place_on_devices=False)
    assert report["parity_ok"], report["parity_mismatches"]
    assert report["total_ops"] > 0
    assert report["metrics"]["queue_bound_violations"] == 0


# ---- server + cli integration ---------------------------------------------

def test_docstore_scheduler_integration(tmp_path):
    from diamond_types_tpu.tools.server import serve
    httpd = serve(port=0, data_dir=str(tmp_path), serve_shards=2)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{port}"

        def post(path, obj):
            req = urllib.request.Request(base + path,
                                         data=json.dumps(obj).encode())
            return json.loads(urllib.request.urlopen(req).read())

        v = post("/doc/d1/edit", {"agent": "a1", "version": [], "ops":
                                  [{"kind": "ins", "pos": 0,
                                    "text": "hello"}]})
        post("/doc/d1/edit", {"agent": "a1", "version": v["version"],
                              "ops": [{"kind": "ins", "pos": 5,
                                       "text": " world"}]})
        sched = httpd.store.scheduler
        assert sched is not None
        sched.drain()
        assert sched.text("d1") == "hello world"
        m = json.loads(urllib.request.urlopen(base + "/metrics").read())
        assert m["serve"]["totals"]["submits"] == 2
        assert m["serve"]["queue_bound_violations"] == 0
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=5)


def test_cli_serve_bench_dry_run(capsys):
    from diamond_types_tpu.tools import cli
    assert cli.main(["serve-bench", "--dry-run", "--json"]) == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["parity_ok"]
    assert report["config"]["engine"] == "host"
