"""Encoded-size parity against the reference's published numbers
(reference: BINARY.md:42-46 — automerge-perf trace: DT full snapshot 281KB,
DT patch encoding 23KB)."""

import os

import pytest

from diamond_types_tpu.encoding.decode import load_oplog
from diamond_types_tpu.encoding.encode import (ENCODE_FULL, EncodeOptions,
                                               encode_oplog)
from diamond_types_tpu.text.trace import load_trace, replay_into_oplog
from tests.conftest import reference_path


@pytest.fixture(scope="module")
def automerge_oplog():
    p = reference_path("benchmark_data", "automerge-paper.json.gz")
    if not os.path.exists(p):
        pytest.skip("corpus missing")
    return replay_into_oplog(load_trace(p)), load_trace(p)


def test_full_snapshot_beats_reference_size(automerge_oplog):
    ol, data = automerge_oplog
    full = encode_oplog(ol, ENCODE_FULL)
    assert len(full) < 281 * 1024  # reference's published full-snapshot size
    assert load_oplog(full).checkout_tip().snapshot() == data.end_content


def test_patch_encoding_beats_reference_size(automerge_oplog):
    ol, _data = automerge_oplog
    patch = encode_oplog(ol, EncodeOptions(store_inserted_content=False,
                                           store_start_branch_content=False))
    assert len(patch) < 23 * 1024  # reference's published patch size
