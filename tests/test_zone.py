"""Zone engine (compose + per-entry YjsMod resolution) — the merge path
with NO native/M1 transform anywhere: host does plan compilation + entry
composition only; origin resolution happens against state rows exactly the
way tpu/zone_kernel.py runs it on device. Differential-tested here against
the tracker engines (reference test strategy: cross-engine differential
testing, SURVEY.md §4.6).
"""

import os
import random

import pytest

from conftest import reference_path

from diamond_types_tpu import OpLog
from diamond_types_tpu.listmerge.zone_np import zone_checkout_np

BENCH_DATA = reference_path("benchmark_data")
# Unicode-heavy, reference-style (src/list_fuzzer_tools.rs:18-24): BMP +
# astral chars through the zone composer/kernel paths too.
ALPHABET = ("abcdefghijklmnop_ XYZ123*&^%$#@!~`:;'\"|"
            "©¥½ΎΔδϠ←↯↻⇈"
            "\U00010190\U00010194\U00010198\U0001019a")


def random_edit(rng, oplog, agent, version, content):
    doc_len = len(content)
    insert_weight = 0.65 if doc_len < 100 else 0.45
    if doc_len == 0 or rng.random() < insert_weight:
        pos = rng.randint(0, doc_len)
        n = rng.randint(1, 4)
        s = "".join(rng.choice(ALPHABET) for _ in range(n))
        lv = oplog.add_insert_at(agent, version, pos, s)
        content = content[:pos] + s + content[pos:]
    else:
        start = rng.randint(0, doc_len - 1)
        n = min(rng.randint(1, 5), doc_len - start)
        lv = oplog.add_delete_at(agent, version, start, start + n,
                                 content[start:start + n])
        content = content[:start] + content[start + n:]
    return [lv], content


@pytest.mark.parametrize(
    "corpus", ["friendsforever.dt", "git-makefile.dt", "node_nodecc.dt"])
def test_zone_corpus_parity(corpus):
    """Byte parity with the tracker engine on every shipped corpus —
    including git-makefile's same-agent-on-concurrent-branches DAG."""
    from diamond_types_tpu.encoding.decode import load_oplog
    with open(os.path.join(BENCH_DATA, corpus), "rb") as f:
        ol = load_oplog(f.read())
    txt, frontier = zone_checkout_np(ol)
    b = ol.checkout_tip()
    assert txt == b.snapshot()
    assert sorted(frontier) == sorted(b.version)


@pytest.mark.parametrize("seed", range(40))
def test_zone_concurrent_branches(seed):
    """Random concurrent branches in one oplog; zone checkout must equal
    the tracker checkout."""
    rng = random.Random(7000 + seed)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n)
              for n in ("alice", "bob", "carol")]
    branches = [([], "")]
    for _ in range(60):
        bi = rng.randrange(len(branches))
        version, content = branches[bi]
        agent = agents[rng.randrange(len(agents))]
        version, content = random_edit(rng, ol, agent, version, content)
        if rng.random() < 0.25 and len(branches) < 5:
            branches.append((version, content))
        else:
            branches[bi] = (version, content)
    txt, _ = zone_checkout_np(ol)
    assert txt == ol.checkout_tip().snapshot()


@pytest.mark.parametrize("seed", range(20))
def test_zone_same_agent_concurrent(seed):
    """The git-import pattern: ONE agent committing on parallel branches
    (sequence numbers out of causal order). This is the regression class
    behind round-3's first zone-engine bug."""
    rng = random.Random(9100 + seed)
    ol = OpLog()
    agent = ol.get_or_create_agent_id("git-author")
    other = ol.get_or_create_agent_id("other")
    branches = [([], "")]
    for _ in range(50):
        bi = rng.randrange(len(branches))
        version, content = branches[bi]
        a = agent if rng.random() < 0.7 else other
        version, content = random_edit(rng, ol, a, version, content)
        if rng.random() < 0.3 and len(branches) < 6:
            branches.append((version, content))
        else:
            branches[bi] = (version, content)
    txt, _ = zone_checkout_np(ol)
    assert txt == ol.checkout_tip().snapshot()


@pytest.mark.parametrize("seed", range(20))
def test_zone_incremental_merge(seed):
    """zone_checkout_np(from, merge) must equal the tracker's Branch.merge
    result from the same frontier (the incremental hot path,
    reference: src/list/merge.rs:63-96)."""
    rng = random.Random(9900 + seed)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("a", "b")]
    branches = [([], "")]
    versions_seen = []
    for _ in range(50):
        bi = rng.randrange(len(branches))
        version, content = branches[bi]
        agent = agents[rng.randrange(2)]
        version, content = random_edit(rng, ol, agent, version, content)
        versions_seen.append(list(version))
        if rng.random() < 0.25 and len(branches) < 4:
            branches.append((version, content))
        else:
            branches[bi] = (version, content)
    frm = versions_seen[rng.randrange(len(versions_seen))]
    # oracle: checkout at `frm`, then merge to tip via the tracker engine
    b = ol.checkout(frm)
    b.merge_tip(ol)
    txt, frontier = zone_checkout_np(ol, frm)
    assert txt == b.snapshot()
    assert sorted(frontier) == sorted(b.version)


def test_zone_empty_and_linear():
    ol = OpLog()
    assert zone_checkout_np(ol)[0] == ""
    a = ol.get_or_create_agent_id("x")
    v = [ol.add_insert_at(a, [], 0, "hello ")]
    v = [ol.add_insert_at(a, v, 6, "world")]
    v = [ol.add_delete_at(a, v, 0, 1, "h")]
    txt, fr = zone_checkout_np(ol)
    assert txt == "ello world"
    assert sorted(fr) == sorted(ol.version)


@pytest.mark.parametrize("corpus", ["friendsforever.dt", "git-makefile.dt"])
def test_native_composer_matches_python(corpus):
    """The C++ composer (native/dt_core.cpp Composer) must produce
    column-identical output to the pure-Python EntryComposer it
    accelerates — the Python path stays live as the DT_TPU_NO_NATIVE /
    unsupported-input fallback, so divergence would split the engines."""
    import numpy as np
    from diamond_types_tpu.encoding.decode import load_oplog
    from diamond_types_tpu.listmerge import compose as C
    from diamond_types_tpu.listmerge.plan2 import compile_plan2
    from diamond_types_tpu.native import native_available
    if not native_available() or os.environ.get("DT_TPU_NO_NATIVE"):
        pytest.skip("native library unavailable")
    with open(os.path.join(BENCH_DATA, corpus), "rb") as f:
        ol = load_oplog(f.read())
    plan = compile_plan2(ol.cg.graph, [], list(ol.version))
    spans = [en.span for en in plan.entries]
    nat = C._native_composed(ol, spans)
    assert nat is not None
    py = [C.compose_entry(ol, s) for s in spans]
    assert len(nat) == len(py)
    for i, (a, b) in enumerate(zip(py, nat)):
        assert list(a.q_cursor) == list(b.q_cursor), f"entry {i}"
        assert [tuple(x) for x in a.del_base] == \
            [tuple(x) for x in b.del_base], f"entry {i}"
        assert [tuple(x) for x in a.del_own] == \
            [tuple(x) for x in b.del_own], f"entry {i}"
        for fld in ("ch_lv", "ch_block", "ch_head", "ch_kind", "ch_anchor",
                    "ch_q", "ch_headlv", "ch_orrown", "blk_root_q",
                    "blk_root_lv", "blk_start", "blk_len"):
            assert np.array_equal(np.asarray(getattr(a, fld)),
                                  np.asarray(getattr(b, fld))), \
                f"entry {i} field {fld}"
    # the linear-prefix composer too: native vs Python piece streams
    if plan.ff_spans:
        from diamond_types_tpu.native import native_ctx_or_none
        ctx = native_ctx_or_none(ol)
        res = ctx.compose_linear(sorted(plan.ff_spans))
        assert res is not None
        os.environ["DT_TPU_NO_NATIVE"] = "1"
        try:
            expected = C.assemble_prefix(ol, plan.ff_spans)
        finally:
            del os.environ["DT_TPU_NO_NATIVE"]
        lvs, lens = res
        got = "".join(ol.ops.content_slice(int(lv), int(ln))
                      for lv, ln in zip(lvs, lens))
        assert got == expected


def test_engine_policy_boundary_differential():
    """Engine selection is measured policy (VERDICT r3 #8): Branch.merge
    auto-selects the zone engine exactly when its recorded throughput
    beats the tracker's — and a selection flip can never change merged
    text (the tracker stays the oracle on both sides of the boundary)."""
    from diamond_types_tpu.listmerge import policy
    from diamond_types_tpu.native import native_available
    from diamond_types_tpu.text.branch import Branch
    if not native_available() or os.environ.get("DT_TPU_NO_NATIVE"):
        pytest.skip("policy arbitrates native engines; oracle-only env")

    rng = random.Random(31)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("pa", "pb")]
    branches = [([], "")]
    for _ in range(50):
        bi = rng.randrange(len(branches))
        v, c = branches[bi]
        v, c = random_edit(rng, ol, agents[rng.randrange(2)], v, c)
        if rng.random() < 0.3 and len(branches) < 4:
            branches.append((v, c))
        else:
            branches[bi] = (v, c)

    # conftest's autouse _fresh_engine_policy fixture isolates GLOBAL;
    # each scenario below swaps in a fresh policy of its own
    policy.GLOBAL = policy.EnginePolicy()
    if True:
        # measured-tracker-wins side of the boundary
        policy.GLOBAL.record(policy.TRACKER, 10_000, 0.001)
        policy.GLOBAL.record(policy.ZONE, 10_000, 1.0)
        b1 = Branch()
        b1.merge(ol, ol.version)
        assert b1.last_merge_engine == policy.TRACKER
        oracle = b1.snapshot()

        # measured-zone-wins side: same merge, flipped selection
        policy.GLOBAL = policy.EnginePolicy()
        policy.GLOBAL.record(policy.TRACKER, 10_000, 1.0)
        policy.GLOBAL.record(policy.ZONE, 10_000, 0.001)
        b2 = Branch()
        b2.merge(ol, ol.version)
        assert b2.last_merge_engine == policy.ZONE
        assert b2.snapshot() == oracle, \
            "policy flip changed merged text"
        # the zone run fed the measurement loop
        assert policy.GLOBAL.rate(policy.ZONE) is not None

        # no measurements at all -> tracker (the default oracle)
        policy.GLOBAL = policy.EnginePolicy()
        b3 = Branch()
        b3.merge(ol, ol.version)
        assert b3.last_merge_engine == policy.TRACKER
        assert b3.snapshot() == oracle


def test_engine_policy_probe_bounded():
    """The loser-refresh probe must skip merges above PROBE_MAX_OPS: a
    probe could otherwise turn one huge merge into a multi-second stall
    on the slower engine."""
    from diamond_types_tpu.listmerge import policy
    p = policy.EnginePolicy()
    p.record(policy.TRACKER, 100_000, 0.001)
    p.record(policy.ZONE, 100, 1.0)
    big = [p.choose(n_ops_hint=10**6) for _ in range(64)]
    assert big.count(policy.ZONE) == 0          # never probed on big merges
    forks = [p.choose(n_ops_hint=-1) for _ in range(64)]
    assert forks.count(policy.ZONE) == 0        # fork proxy counts as big
    # a probe skipped on big merges stays DUE: the very next small merge
    # fires it (big-merge-dominated workloads still refresh the loser)
    assert p.choose(n_ops_hint=10) == policy.ZONE
    small = [p.choose(n_ops_hint=10) for _ in range(64)]
    assert small.count(policy.ZONE) > 0         # probes keep happening


def test_engine_policy_demotion_cooldown_reprobe(monkeypatch):
    """A failure-demotion (forget) must not disable the zone engine for
    the process lifetime (ADVICE r4): after DEMOTION_COOLDOWN_S one
    probe-eligible merge re-tries it, a success clears the demotion, and
    a renewed failure just waits out the next window. Clock is faked so
    the test is deterministic under CI load."""
    from diamond_types_tpu.listmerge import policy
    now = [1000.0]
    monkeypatch.setattr(policy.time, "monotonic", lambda: now[0])
    p = policy.EnginePolicy()   # real DEMOTION_COOLDOWN_S (60 s)
    p.record(policy.TRACKER, 10_000, 0.01)
    p.record(policy.ZONE, 100_000, 0.01)
    assert p.choose(100) == policy.ZONE
    p.forget(policy.ZONE)
    assert p.choose(100) == policy.TRACKER       # inside the cooldown
    now[0] += p.DEMOTION_COOLDOWN_S + 1
    assert p.choose(10**7) == policy.TRACKER     # big merge: never a probe
    assert p.choose(100) == policy.ZONE          # cooldown re-probe fires
    assert p.choose(100) == policy.TRACKER       # window re-armed
    p.record(policy.ZONE, 100_000, 0.01)         # the probe succeeded
    assert p.choose(100) == policy.ZONE          # back in rotation
    p.forget(policy.ZONE)
    now[0] += p.DEMOTION_COOLDOWN_S + 1
    # hint-less embedder calls are probe-eligible too: they must not be
    # the one path where a demoted engine can never recover
    assert p.choose() == policy.ZONE
    # second consecutive failure: nothing until the NEXT window
    p.forget(policy.ZONE)
    assert p.choose(100) == policy.TRACKER
