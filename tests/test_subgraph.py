"""Subgraph projection vs a brute-force ancestor-closure oracle
(reference capability: src/causalgraph/graph/subgraph.rs; random-graph test
style from graph/random_graphs.rs)."""

import random

import pytest

from diamond_types_tpu.causalgraph.graph import Graph
from diamond_types_tpu.causalgraph.subgraph import (project_onto_subgraph,
                                                    subgraph)


def random_graph(rng, n_runs=12, max_run=4):
    g = Graph()
    lv = 0
    heads = []
    for _ in range(n_runs):
        n = rng.randint(1, max_run)
        if not heads or rng.random() < 0.3:
            parents = []
        else:
            k = min(len(heads), 1 + (rng.random() < 0.35))
            parents = sorted(rng.sample(heads, k))
        g.push(parents, lv, lv + n)
        for p in parents:
            if p in heads:
                heads.remove(p)
        heads.append(lv + n - 1)
        lv += n
    return g, lv


def ancestors(g, frontier):
    """Brute-force transitive closure."""
    out = set()
    stack = list(frontier)
    while stack:
        v = stack.pop()
        if v in out:
            continue
        out.add(v)
        stack.extend(g.parents_at(v))
    return out


def brute_projection(g, filter_spans, frontier):
    anc = ancestors(g, frontier)
    in_filter = set()
    for (a, b) in filter_spans:
        in_filter.update(range(a, b))
    cand = anc & in_filter
    # dominators: v in cand with no other w in cand strictly descending from v
    result = []
    for v in cand:
        if not any(w != v and g.frontier_contains_version([w], v)
                   for w in cand):
            result.append(v)
    return sorted(result)


@pytest.mark.parametrize("seed", range(40))
def test_projection_matches_bruteforce(seed):
    rng = random.Random(seed)
    g, n = random_graph(rng)
    # Random filter: a few disjoint spans.
    spans = []
    pos = 0
    while pos < n:
        a = pos + rng.randint(0, 3)
        b = a + rng.randint(1, 4)
        if a >= n:
            break
        spans.append((a, min(b, n)))
        pos = b + rng.randint(0, 2)
    frontier = g.find_dominators(
        sorted(rng.sample(range(n), rng.randint(1, min(3, n)))))
    got = project_onto_subgraph(g, spans, frontier)
    assert got == brute_projection(g, spans, frontier), (spans, frontier)


@pytest.mark.parametrize("seed", range(25))
def test_subgraph_parents_consistent(seed):
    rng = random.Random(1000 + seed)
    g, n = random_graph(rng)
    spans = [(a, min(a + rng.randint(1, 5), n))
             for a in sorted(rng.sample(range(n), min(3, n)))]
    # de-overlap
    clean = []
    for (a, b) in spans:
        if clean and a < clean[-1][1]:
            a = clean[-1][1]
        if a < b:
            clean.append((a, b))
    frontier = g.find_dominators(list(range(n)))  # tip of everything
    sub, proj = subgraph(g, clean, frontier)

    # Every subgraph entry's LVs must come from the filter.
    in_filter = set()
    for (a, b) in clean:
        in_filter.update(range(a, b))
    covered = set()
    for i in range(len(sub)):
        covered.update(range(sub.starts[i], sub.ends[i]))
        # Parents must be filtered LVs and real ancestors.
        for p in sub.parents[i]:
            assert p in in_filter
            assert g.frontier_contains_version([sub.starts[i]], p)
    assert covered == in_filter  # everything is in the frontier's history

    # The projected frontier must dominate the whole subgraph.
    for v in covered:
        assert sub.frontier_contains_version(proj, v)
