"""Cross-engine differential tests: native (C++) vs Python merge engine
(mirrors the reference's listmerge vs listmerge2 differential testing,
SURVEY.md §4.6)."""

import os
import random

import pytest

from diamond_types_tpu.native import native_available
from tests.test_encode import build_random_oplog


@pytest.mark.skipif(not native_available(), reason="native core not built")
@pytest.mark.parametrize("seed", range(20))
def test_native_matches_python_engine(seed):
    ol = build_random_oplog(seed, steps=50)
    os.environ["DT_TPU_NO_NATIVE"] = "1"
    try:
        py = ol.checkout_tip()
    finally:
        del os.environ["DT_TPU_NO_NATIVE"]
    nat = ol.checkout_tip()
    assert py.snapshot() == nat.snapshot()
    assert py.version == nat.version


@pytest.mark.skipif(not native_available(), reason="native core not built")
@pytest.mark.parametrize("seed", range(8))
def test_native_incremental_merge_matches(seed):
    rng = random.Random(seed)
    ol = build_random_oplog(seed, steps=30)
    # Merge from a random mid version rather than root.
    mid = sorted(rng.sample(range(len(ol)), 2))
    mid = ol.cg.graph.find_dominators(mid)
    os.environ["DT_TPU_NO_NATIVE"] = "1"
    try:
        b1 = ol.checkout(mid)
        b1.merge(ol, ol.version)
    finally:
        del os.environ["DT_TPU_NO_NATIVE"]
    b2 = ol.checkout(mid)
    b2.merge(ol, ol.version)
    assert b1.snapshot() == b2.snapshot()
    assert b1.version == b2.version
