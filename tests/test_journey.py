"""Convergence-tracing tests (obs/journey.py + obs/assemble.py + the
serving/replication wiring): stage-stamp semantics (first-wins begin,
the advert-after-apply guard, FIFO eviction), clock-skew-robust
cross-host assembly with an exact critical-path decomposition, the
disabled-journey zero-allocation pin, the visibility_p99 SLO driven
ok -> burning -> ok on seeded lags, the /debug/trace endpoints + the
dt-trace CLI, prom zero-fill for the dt_journey_* / dt_convergence_*
families, and the two-server acceptance run assembling one proxied
edit's trace across both hosts. Tier-1 safe: in-process servers on
ephemeral ports, no TPU.
"""

import json
import threading
import time
import tracemalloc
import types
import urllib.request

import pytest

from diamond_types_tpu.obs import Observability
from diamond_types_tpu.obs.assemble import (aggregate, assemble_trace,
                                            estimate_offset,
                                            render_human)
from diamond_types_tpu.obs.journey import (CONVERGENCE_PREFIX,
                                           PEER_STAGES, STAGES,
                                           VISIBILITY_SERIES,
                                           OpJourney)
from diamond_types_tpu.obs.prom import render_metrics
from diamond_types_tpu.obs.slo import Objective, SloEngine
from diamond_types_tpu.obs.timeseries import TimeSeries

pytestmark = pytest.mark.journey


class _Clock:
    """Injectable monotonic clock (mirrors test_telemetry.py)."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# ---- stage stamping -------------------------------------------------------

def test_journey_stage_stamps_waterfall_and_convergence_lag():
    clk = _Clock(100.0)
    ts = TimeSeries(window_s=10.0, n_windows=8, clock=clk)
    j = OpJourney(ts=ts, clock=clk)
    key = j.begin("alice", 7, doc="d1", trace="t-abc")
    assert key == "t-abc"
    # first begin wins: a re-announce (the scheduler's begin with no
    # identity) must not reset t_admitted or double-count `admitted`
    clk.t = 100.5
    assert j.begin(None, None, doc="d1", trace="t-abc") == "t-abc"
    assert j.snapshot()["stages"]["admitted"] == 1
    for stage in ("queued", "planned", "adopted", "wal_durable"):
        clk.t += 0.1
        j.stamp(key, stage)
    # first stamp wins per stage
    j.stamp(key, "queued", t=999.0)
    entry = j.journey(key)
    assert entry["agent"] == "alice" and entry["seq"] == 7
    assert entry["stages"]["admitted"] == 100.0
    assert entry["stages"]["queued"] == pytest.approx(100.6)
    # peer-side facts arrive via the doc index (AE knows doc, not trace)
    clk.t = 101.2
    j.stamp_doc("d1", "ae_shipped", peer="p1")
    # the advert guard: an advert BEFORE the peer applied proves
    # nothing about this edit's visibility — the stamp is skipped
    j.stamp_doc("d1", "advert_usable", peer="p1", t=101.25)
    assert "advert_usable" not in j.journey(key)["peers"]["p1"]
    j.stamp_doc("d1", "applied_at_peer", peer="p1", t=101.3)
    j.stamp_doc("d1", "advert_usable", peer="p1", t=101.5)
    peers = j.journey(key)["peers"]["p1"]
    assert set(peers) == set(PEER_STAGES)
    # convergence lag = advert_usable - admitted, double-written into
    # the per-peer family and the SLO aggregate
    lag = j.lag_summary()["p1"]
    assert lag["n"] == 1
    assert lag["mean_s"] == pytest.approx(1.5)
    assert ts.count_over(VISIBILITY_SERIES, 0.0, 300.0)[1] == 1
    assert ts.count_over(f"{CONVERGENCE_PREFIX}.p1", 0.0, 300.0)[1] == 1
    # the waterfall orders rows by offset from admitted
    rows = j.waterfall(key)
    assert rows[0] == ("admitted", 0.0, None)
    offs = [r[1] for r in rows]
    assert offs == sorted(offs)
    assert ("advert_usable", 1.5, "p1") in rows
    snap = j.snapshot()
    assert snap["stages"]["advert_usable"] == 1
    assert snap["stages"]["device_replayed"] == 0
    json.dumps(snap)


def test_journey_fifo_eviction_and_doc_index_cleanup():
    j = OpJourney(capacity=4, clock=_Clock())
    for i in range(6):
        j.begin(f"a{i}", i, doc=f"d{i}")
    assert j.snapshot()["tracked"] == 4
    assert j.snapshot()["dropped"] == 2
    # evicted journeys leave no doc-index residue: stamping their doc
    # is a no-op, stamping a live doc still lands
    j.stamp_doc("d0", "wal_durable")
    j.stamp_doc("d5", "wal_durable")
    assert j.journey("a5:5")["stages"].get("wal_durable") is not None
    assert j.snapshot()["stages"]["wal_durable"] == 1


def test_disabled_journey_single_branch_zero_alloc():
    """The disabled journey is ONE branch per call: tracemalloc must
    attribute zero allocations to journey.py across 200 stamp cycles
    (same contract as the disabled tracer/TimeSeries)."""
    import diamond_types_tpu.obs.journey as j_mod
    j = OpJourney(enabled=False)
    j.begin("a", 1, "d")
    j.stamp("a:1", "queued")
    j.stamp_doc("d", "wal_durable")
    files = {j_mod.__file__}

    def _cycle():
        for _ in range(200):
            j.begin("a", 1, "d")
            j.stamp("a:1", "queued")
            j.stamp_doc("d", "wal_durable", "p")

    _cycle()
    grew = []
    tracemalloc.start()
    for _attempt in range(3):
        before = tracemalloc.take_snapshot()
        _cycle()
        after = tracemalloc.take_snapshot()
        grew = [st for st in after.compare_to(before, "lineno")
                if st.size_diff > 0
                and st.traceback[0].filename in files
                and st.traceback[0].lineno > 0]
        if not grew:
            break
    tracemalloc.stop()
    assert not grew, [str(g) for g in grew]
    assert j.stamped == 0 and j.snapshot()["tracked"] == 0


# ---- skew-robust assembly -------------------------------------------------

def test_skewed_two_host_assembly_monotonic_and_exact_critical_path(
        monkeypatch):
    """Two hosts on clocks 5s apart (faults.py skew bookkeeping) plus
    a deliberately asymmetric RTT on one fetch: after alignment the
    monotonic repair must keep every child at or after its parent, and
    the critical path's owned segments must telescope to exactly the
    root's wall time."""
    import diamond_types_tpu.replicate.faults as faults_mod
    truth = _Clock(0.0)
    monkeypatch.setattr(faults_mod, "time",
                        types.SimpleNamespace(monotonic=truth))
    fi = faults_mod.FaultInjector()
    fi.set_clock_skew("a", 3.0)
    fi.set_clock_skew("b", -2.0)

    def at(host, true_t):
        truth.t = true_t
        return fi.now(host)

    tid = "t-skew"
    spans_a = [
        {"trace": tid, "span": "s-root", "parent": None,
         "name": "http.doc_edit", "t0": at("a", 10.0), "dur_s": 0.100},
        {"trace": tid, "span": "s-proxy", "parent": "s-root",
         "name": "repl.proxy", "t0": at("a", 10.010), "dur_s": 0.080},
    ]
    spans_b = [
        {"trace": tid, "span": "s-rhttp", "parent": "s-proxy",
         "name": "http.doc_edit", "t0": at("b", 10.020), "dur_s": 0.060},
        {"trace": tid, "span": "s-admit", "parent": "s-rhttp",
         "name": "serve.admit", "t0": at("b", 10.025), "dur_s": 0.010},
    ]
    # host a fetched with a symmetric zero-RTT probe: exact offset
    fetch_a = {"host": "a", "spans": spans_a,
               "t_send": 20.0, "t_recv": 20.0, "now": at("a", 20.0)}
    # host b's probe is asymmetric: the server sampled `now` at
    # t_recv, not the midpoint, so the estimate is off by RTT/2 =
    # 25ms — enough to order the remote hop before its proxy parent
    fetch_b = {"host": "b", "spans": spans_b,
               "t_send": 20.0, "t_recv": 20.05, "now": at("b", 20.05)}
    assert estimate_offset(0.0, 2.0, 11.0) == pytest.approx(10.0)
    rep = assemble_trace(tid, [fetch_a, fetch_b])
    assert rep["hosts"] == ["a", "b"]
    assert rep["spans"] == 4 and rep["orphans"] == 0
    assert rep["root"] == {"name": "http.doc_edit", "host": "a"}
    # monotonic repair: no waterfall row precedes the root, and every
    # child starts at or after its parent
    by_span = {r["span"]: r for r in rep["waterfall"]}
    for r in rep["waterfall"]:
        assert r["t0_rel_s"] >= 0.0
        if r["parent"] is not None:
            assert r["t0_rel_s"] >= by_span[r["parent"]]["t0_rel_s"]
    # residual skew DID violate causality pre-repair: the remote hop
    # got clamped up to its proxy parent's start
    assert by_span["s-rhttp"]["t0_rel_s"] == \
        by_span["s-proxy"]["t0_rel_s"]
    # exact telescoping decomposition along the 4-deep chain
    cp = rep["critical_path"]
    assert [s["name"] for s in cp] == \
        ["http.doc_edit", "repl.proxy", "http.doc_edit", "serve.admit"]
    assert [s["host"] for s in cp] == ["a", "a", "b", "b"]
    assert [s["owned_s"] for s in cp] == \
        pytest.approx([0.020, 0.020, 0.050, 0.010])
    assert rep["critical_path_s"] == pytest.approx(rep["wall_s"],
                                                   abs=1e-6)
    t0s = [s["t0_rel_s"] for s in cp]
    assert t0s == sorted(t0s)
    # aggregation attributes ownership across (name, host)
    agg = aggregate([rep, rep])
    assert agg["traces"] == 2
    assert agg["total_owned_s"] == pytest.approx(2 * rep["wall_s"])
    assert agg["owners"][0]["name"] == "http.doc_edit"
    assert sum(r["share"] for r in agg["owners"]) == pytest.approx(1.0)
    text = render_human(rep, agg)
    assert "== critical path" in text and "@b owns" in text


def test_assemble_missing_host_degrades_to_orphans():
    tid = "t-x"
    fetches = [{"host": "a", "offset_s": 0.0, "spans": [
        {"trace": tid, "span": "r", "parent": None, "name": "root",
         "t0": 1.0, "dur_s": 0.5},
        {"trace": tid, "span": "k", "parent": "missing",
         "name": "stray", "t0": 1.2, "dur_s": 0.1},
    ]}]
    rep = assemble_trace(tid, fetches)
    # the span whose parent lives on an unreachable host becomes a
    # secondary root, reported as an orphan — never dropped silently
    assert rep["orphans"] == 1 and rep["spans"] == 2
    assert rep["critical_path_s"] == pytest.approx(rep["wall_s"])
    empty = assemble_trace("nope", fetches)
    assert empty["root"] is None and empty["spans"] == 0
    assert "no spans found" in render_human(empty)


def test_assemble_survives_span_id_collision_cycle():
    """Span-id collisions across hosts (or a malicious peer) can form
    parent CYCLES in the merged set — the tree walk must truncate the
    cycle, not hang the CLI."""
    tid = "t-cyc"
    fetches = [
        {"host": "a", "offset_s": 0.0, "spans": [
            {"trace": tid, "span": "r", "parent": None, "name": "root",
             "t0": 1.0, "dur_s": 0.5},
            {"trace": tid, "span": "x", "parent": "r", "name": "kid",
             "t0": 1.1, "dur_s": 0.3},
            {"trace": tid, "span": "y", "parent": "x", "name": "gk",
             "t0": 1.2, "dur_s": 0.2},
        ]},
        # the colliding host reuses id "x", parented on "y": x -> y ->
        # x is a cycle once both hosts' records are merged
        {"host": "b", "offset_s": 0.0, "spans": [
            {"trace": tid, "span": "x", "parent": "y", "name": "dup",
             "t0": 1.25, "dur_s": 0.1},
        ]},
    ]
    rep = assemble_trace(tid, fetches)
    assert rep["root"]["name"] == "root" and rep["spans"] == 4
    assert rep["critical_path"][0]["name"] == "root"
    assert len(rep["critical_path"]) <= 4


# ---- visibility SLO -------------------------------------------------------

def test_visibility_slo_ok_burning_ok_with_lag_verdict_column():
    """Seeded replication delay drives visibility_p99 ok -> burning ->
    ok, and the soak-verdict convergence-lag column reflects the seeded
    lags (the column replicate/soak.py + rebalance_soak.py embed)."""
    clk = _Clock()
    ts = TimeSeries(window_s=10.0, n_windows=60, clock=clk)
    j = OpJourney(capacity=1024, ts=ts, clock=clk)
    eng = SloEngine(ts, objectives=[
        Objective("visibility_p99", VISIBILITY_SERIES, threshold_s=0.1,
                  target=0.99, fast_window_s=60.0,
                  slow_window_s=300.0)])

    def converge(n, lag_s, tag):
        for i in range(n):
            key = j.begin(f"{tag}{i}", i, doc=f"{tag}d{i}", t=0.0)
            j.stamp(key, "applied_at_peer", peer="peer-1", t=0.0)
            j.stamp(key, "advert_usable", peer="peer-1", t=lag_s)

    def state():
        return eng.evaluate()[0]["state"]

    converge(100, 0.005, "g")          # healthy replication
    assert state() == "ok"
    converge(60, 2.0, "b")             # seeded replication delay
    assert state() == "burning"
    v = eng.verdict()
    assert v["slo_ok"] is False and v["burning"] == ["visibility_p99"]
    # the verdict's convergence-lag column carries the seeded delay
    col = j.lag_summary()
    assert col["peer-1"]["n"] == 160
    assert col["peer-1"]["max_s"] == pytest.approx(2.0)
    assert col["peer-1"]["mean_s"] > 0.5
    clk.t = 400.0                      # the bad windows age out
    converge(100, 0.005, "h")
    assert state() == "ok"
    assert eng.verdict()["slo_ok"] is True
    assert eng.snapshot()["objectives"][0]["transitions"] >= 2


# ---- prom zero-fill -------------------------------------------------------

def test_prom_journey_and_convergence_zero_fill():
    """A fresh server with zero traffic still exposes every
    dt_journey_stage_total stage row and the peer="all" convergence
    rollup, so dashboards never see series flicker into existence."""
    obs = Observability(sample_rate=0.0)
    text = render_metrics({"obs": obs.snapshot()})
    for stage in STAGES:
        assert f'dt_journey_stage_total{{stage="{stage}"}} 0' in text, \
            stage
    assert "dt_journey_enabled 1" in text
    assert "dt_journey_tracked 0" in text
    assert "dt_journey_stamps_total 0" in text
    assert "dt_journey_dropped_total 0" in text
    assert 'dt_convergence_lag_count{peer="all"} 0' in text
    assert 'dt_convergence_lag_seconds_sum{peer="all"} 0' in text
    assert 'dt_convergence_lag_seconds_max{peer="all"} 0' in text
    # journey=False drops the tier to a disabled stub, still scraped
    off = Observability(sample_rate=0.0, journey=False)
    assert not off.journey.enabled
    assert "dt_journey_enabled 0" in \
        render_metrics({"obs": off.snapshot()})


# ---- server endpoints + CLI ----------------------------------------------

def _serve_one(**obs_opts):
    from diamond_types_tpu.tools.server import serve
    opts = {"sample_rate": 0.0}
    opts.update(obs_opts)
    httpd = serve(port=0, serve_shards=2, obs_opts=opts)
    addr = f"127.0.0.1:{httpd.server_address[1]}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, addr


def _get_json(addr, path):
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=5) as r:
        return json.loads(r.read())


def _post(addr, path, obj):
    req = urllib.request.Request(f"http://{addr}{path}",
                                 data=json.dumps(obj).encode("utf8"))
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, json.loads(r.read())


def _edit(addr, doc, text="hello"):
    return _post(addr, f"/doc/{doc}/edit",
                 {"agent": "journey", "version": [],
                  "ops": [{"kind": "ins", "pos": 0, "text": text}]})


def _wait_trace(obs_list, root_name="http.doc_edit", deadline_s=3.0):
    """HTTP spans end in the handlers' `finally` after the response is
    on the wire — poll until the root span lands in a ring."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for obs in obs_list:
            for s in obs.tracer.spans():
                if s["name"] == root_name and s["parent"] is None:
                    return s["trace"]
        time.sleep(0.01)
    raise AssertionError("no sampled root span landed")


def test_debug_trace_endpoints_and_dt_trace_cli(capsys):
    httpd, addr = _serve_one(sample_rate=1.0)
    try:
        status, _out = _edit(addr, "jdoc")
        assert status == 200
        httpd.store.scheduler.drain()
        obs = httpd.store.obs
        tid = _wait_trace([obs])
        # journey stamps landed along the single-host pipeline
        stages = obs.journey.snapshot()["stages"]
        for stage in ("admitted", "queued", "planned", "adopted"):
            assert stages[stage] >= 1, (stage, stages)
        # /debug/traces: the index lists the trace, newest first
        idx = _get_json(addr, "/debug/traces")
        assert idx["host"] == "local" and idx["now"] > 0
        row = next(r for r in idx["traces"] if r["trace"] == tid)
        assert row["root"] == "http.doc_edit" and row["spans"] >= 3
        # /debug/trace/<id>: this host's spans + its monotonic now
        one = _get_json(addr, f"/debug/trace/{tid}")
        assert one["trace"] == tid and one["host"] == "local"
        assert all(s["trace"] == tid for s in one["spans"])
        assert {s["name"] for s in one["spans"]} >= \
            {"http.doc_edit", "serve.admit"}
        # an unknown id is an empty fetch, not an error
        assert _get_json(addr, "/debug/trace/zzzz")["spans"] == []
        from diamond_types_tpu.tools import cli
        # listing mode
        assert cli.main(["dt-trace", addr]) == 0
        out = capsys.readouterr().out
        assert tid in out and "recent traces" in out
        # assembly mode: single host, JSON
        assert cli.main(["dt-trace", addr, tid, "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)["traces"][0]
        assert rep["trace"] == tid and rep["root"] is not None
        assert rep["critical_path_s"] == pytest.approx(rep["wall_s"],
                                                       abs=1e-5)
        # a bogus id exits nonzero (no root assembled)
        assert cli.main(["dt-trace", addr, "zzzz"]) == 1
        capsys.readouterr()
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_obs_watch_convergence_and_devprof_panels(capsys):
    from diamond_types_tpu.obs.devprof import PROFILER, note_jit_lookup
    httpd, addr = _serve_one(sample_rate=1.0)
    try:
        obs = httpd.store.obs
        key = obs.journey.begin("w", 1, doc="wdoc")
        obs.journey.stamp(key, "applied_at_peer", peer="peer-9")
        obs.journey.stamp(key, "advert_usable", peer="peer-9")
        # the PR-13 jit families surface in the device panel
        PROFILER.enabled = True
        note_jit_lookup("xform", True)
        note_jit_lookup("pallas", False)
        from diamond_types_tpu.tools import cli
        rc = cli.main(["obs-watch", addr, "--rounds", "1",
                       "--interval", "0"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "== convergence (tracked=1" in out
        assert "lag peer-9" in out
        assert "advert_usable=1" in out
        assert "== device (jit cache) ==" in out
        assert "xform" in out and "pallas" in out
        assert "visibility_p99" in out
    finally:
        PROFILER.enabled = False
        httpd.shutdown()
        httpd.server_close()


# ---- two-server acceptance ------------------------------------------------

def _serve_pair():
    from diamond_types_tpu.replicate import attach_replication
    from diamond_types_tpu.tools.server import serve
    httpds, addrs = [], []
    for _ in range(2):
        # follower_reads attaches read/follower.py's FollowerIndex —
        # the advert_usable stamp rides its note_advert
        httpd = serve(port=0, serve_shards=2, follower_reads=True,
                      obs_opts={"sample_rate": 1.0})
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    nodes = []
    for i, httpd in enumerate(httpds):
        nodes.append(attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            lease_ttl_s=5.0, backoff_base_s=0.01, backoff_cap_s=0.05))
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
    return httpds, nodes, addrs


def test_cross_host_trace_assembly_and_full_journey_acceptance(capsys):
    """Acceptance: one edit proxied across a two-server mesh yields a
    journey stamped at every owner-path stage, a cross-host trace
    whose assembly spans both hosts, and a critical path that sums to
    the trace's wall time."""
    httpds, nodes, addrs = _serve_pair()
    try:
        # a doc owned by server 1, posted to server 0 -> proxied
        doc = next(d for d in (f"jdoc-{i}" for i in range(64))
                   if nodes[0].desired_owner(d) == addrs[1])
        status, out = _edit(addrs[0], doc)
        assert status == 200 and out.get("version")
        httpds[1].store.scheduler.drain()
        tid = _wait_trace([h.store.obs for h in httpds])
        journey = httpds[1].store.obs.journey
        # AE round 1 pushes the patch (ae_shipped + applied_at_peer);
        # a later round's piggybacked frontier advert, now dominating,
        # lands advert_usable — poll rounds until the journey closes
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            nodes[1].antientropy.run_round()
            entry = journey.journey(tid)
            if entry and "advert_usable" in \
                    (entry["peers"].get(addrs[0]) or {}):
                break
            time.sleep(0.05)
        entry = journey.journey(tid)
        assert entry is not None, journey.snapshot()
        assert entry["doc"] == doc and entry["agent"] == "journey"
        # every owner-path stage (no data_dir -> no wal_durable; host
        # engine -> no device_replayed) plus all three peer stages
        for stage in ("admitted", "queued", "planned", "adopted"):
            assert stage in entry["stages"], (stage, entry)
        peer_slots = entry["peers"][addrs[0]]
        assert set(peer_slots) == set(PEER_STAGES)
        # stamps are causally ordered along the waterfall
        rows = journey.waterfall(tid)
        assert rows[0][0] == "admitted"
        assert [r[1] for r in rows] == sorted(r[1] for r in rows)
        # the convergence-lag column names the follower
        col = journey.lag_summary()
        assert col[addrs[0]]["n"] >= 1
        assert col[addrs[0]]["max_s"] > 0.0
        # and the live series feeds the visibility_p99 objective
        slo = {o["name"]: o
               for o in httpds[1].store.obs.slo.evaluate()}
        assert slo["visibility_p99"]["fast"]["total"] >= 1
        # cross-host assembly via the CLI: both hosts, exact critical
        # path, ownership spanning the proxy hop
        from diamond_types_tpu.tools import cli
        rc = cli.main(["dt-trace", addrs[0], tid,
                       "--peers", addrs[1], "--json"])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out)["traces"][0]
        assert sorted(rep["hosts"]) == sorted(addrs)
        assert rep["root"]["name"] == "http.doc_edit"
        assert rep["root"]["host"] == addrs[0]
        names = {r["name"] for r in rep["waterfall"]}
        assert {"http.doc_edit", "repl.proxy", "serve.admit"} <= names
        hosts_on_path = {s["host"] for s in rep["critical_path"]}
        assert addrs[0] in hosts_on_path
        assert rep["critical_path_s"] == pytest.approx(rep["wall_s"],
                                                       abs=1e-5)
        assert rep["wall_s"] > 0.0
        # human rendering round-trips the same assembly
        rc = cli.main(["dt-trace", addrs[0], tid, "--peers", addrs[1]])
        out = capsys.readouterr().out
        assert rc == 0
        assert f"== trace {tid}" in out and "2 hosts" in out
        assert "== critical path" in out
    finally:
        for h in httpds:
            h.shutdown()
            h.server_close()
